"""Compilation economics: one shared executable cache + compile-ahead.

Reference parity: the reference engine's "native" layer is
compile-once-run-many bytecode generation — PageFunctionCompiler memoizes
compiled projections/filters in a guava cache keyed by the row expression
(sql/gen/PageFunctionCompiler.java:105), and compiled classes are reused
across queries for the life of the JVM.  Our XLA analogue compiles a
whole fragment per (plan shape, chunk mult, mesh), which at SF100 runs
into MINUTES per program (BENCH_r05: q64 938s cold vs 226s warm), so the
compile bill must be paid once per MACHINE, not once per process — and
never serially in front of a waiting query when it can overlap.

Three layers, all fronted by this module:

1. the JAX persistent compilation cache (disk, keyed by HLO hash): wired
   from `PRESTO_TPU_COMPILE_CACHE` (legacy alias `PRESTO_TPU_XLA_CACHE`)
   or the `compile_cache_dir` session property.  A cold process with a
   warmed cache dir loads executables instead of compiling them.
2. a process-wide executable memo keyed by engine-level fingerprints
   (plan serde bytes x chunk mult x mesh shape x dtype layout, see
   `fingerprint`/`plan_fingerprint`): the per-session `_jit` /
   `_chunked_cache` / `_compiled_cache` dicts are views over this —
   a second session (or a second runner) with an identical fragment
   reuses the executable without retracing.  Entries are built
   SINGLE-FLIGHT: a compile-ahead thread and the query thread asking for
   the same key compile it once, everyone else waits.
3. a bounded compile-ahead worker pool: chunked plans AOT-compile
   fragments 2..N while fragment 1 executes; miss-prone fragments
   pre-compile their next bound-growth mult so "bound miss -> grow +
   re-jit" re-runs against a ready executable; cluster workers warm
   their scan inputs at task-accept time instead of first-page time.
   `PRESTO_TPU_COMPILE_AHEAD=off` (or session property
   `compile_ahead=False`) kills all of it; compile-ahead never changes
   results, only WHEN the same executables get built.

Telemetry: every build routes through `build_jit`, so QueryStats gains
exact `compiles` / `compile_ms` / `compile_cache_hits` /
`compile_ahead_hits` per query (bench.py emits them as
`compile_economics`).  Persistent-cache disk hits are observed through
jax.monitoring's `/jax/compilation_cache/cache_hits` event.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

import jax

from presto_tpu.observe import trace as TR

DEFAULT_CACHE_DIR = "/tmp/presto_tpu_xla_cache"

#: QueryStats counter names this module maintains (observe/stats.py
#: declares the same fields; bench.py emits them as compile_economics)
COUNTERS = ("compiles", "compile_ms", "compile_cache_hits",
            "compile_ahead_hits")


class CompileStats:
    """Counter bag with the QueryStats compile-economics fields; used as
    the process-wide aggregate and for worker-side task accounting."""

    def __init__(self):
        self.compiles = 0
        self.compile_ms = 0.0
        self.compile_cache_hits = 0
        self.compile_ahead_hits = 0

    def snapshot(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in COUNTERS}


#: process totals (tools/roofline.py and tests read these)
GLOBAL = CompileStats()

_tls = threading.local()
_note_lock = threading.Lock()


def _sinks():
    sinks = [GLOBAL]
    extra = getattr(_tls, "sink", None)
    if extra is not None:
        sinks.append(extra)
    return sinks


def _note(field: str, amount=1) -> None:
    with _note_lock:
        for s in _sinks():
            setattr(s, field, getattr(s, field, 0) + amount)


@contextmanager
def recording(stats):
    """Route this thread's compile accounting into `stats` (a QueryStats
    or CompileStats).  Nests: inner recordings shadow outer ones, the
    GLOBAL aggregate always collects."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = stats
    try:
        yield stats
    finally:
        _tls.sink = prev


# ---------------------------------------------------------------------------
# persistent-cache wiring
# ---------------------------------------------------------------------------

_conf_lock = threading.Lock()
_configured_dir: Optional[str] = "UNSET"
_listener_installed = False


def resolve_cache_dir(session=None) -> Optional[str]:
    """Cache dir precedence: `compile_cache_dir` session property >
    PRESTO_TPU_COMPILE_CACHE > PRESTO_TPU_XLA_CACHE (legacy) > default.
    '0' / 'off' / '' disables (returns None)."""
    d = None
    if session is not None:
        d = session.properties.get("compile_cache_dir") or None
    if d is None:
        d = os.environ.get("PRESTO_TPU_COMPILE_CACHE") \
            or os.environ.get("PRESTO_TPU_XLA_CACHE") \
            or DEFAULT_CACHE_DIR
    d = str(d)
    return None if d in ("0", "off", "") else d


def _on_event(event, **kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _note("compile_cache_hits")


def configure(session=None) -> None:
    """Idempotently point JAX's persistent compilation cache at the
    resolved dir and install the disk-hit listener.  Safe to call per
    query: only reconfigures when the resolved dir changes."""
    global _configured_dir, _listener_installed
    d = resolve_cache_dir(session)
    with _conf_lock:
        if not _listener_installed:
            try:
                jax.monitoring.register_event_listener(_on_event)
                _listener_installed = True
            except Exception:
                _listener_installed = True  # older jax: no disk-hit counts
        if d == _configured_dir:
            return
        _configured_dir = d
        if d is None:
            return
        jax.config.update("jax_compilation_cache_dir", d)
        # cache every compile that takes noticeable time (default 1s
        # would skip the many small per-fragment programs whose compiles
        # still add up across the 22-query suite); tests set the env to
        # 0 so CPU-sized compiles persist too
        min_s = float(os.environ.get("PRESTO_TPU_COMPILE_CACHE_MIN_S",
                                     "0.2"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_s)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            pass  # knob absent on older jax


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

_token_counter = itertools.count(1)


def catalog_token(catalog) -> str:
    """Process-unique identity token for a catalog instance.  id() is
    NOT usable in cache keys (a freed catalog's id can be recycled by a
    new one, aliasing stale executables onto fresh data); a token
    attribute assigned once per object cannot alias."""
    tok = getattr(catalog, "_compile_cache_token", None)
    if tok is None:
        tok = f"cat{next(_token_counter)}"
        try:
            catalog._compile_cache_token = tok
        except Exception:
            return f"id{id(catalog)}"  # slotted object: best effort
    return tok


def fingerprint(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


def plan_fingerprint(obj) -> Optional[str]:
    """Stable fingerprint of a plan (sub)tree via the cluster-wire serde
    (plan/serde.py) — the same bytes two sessions produce for identical
    plans.  None when the plan carries something unserializable; callers
    then skip the shared memo (the build is still counted)."""
    from presto_tpu.plan import serde

    try:
        return hashlib.sha256(serde.dumps(obj)).hexdigest()
    except Exception:
        return None


def avals_fingerprint(tree) -> str:
    """Shape/dtype fingerprint of a pytree of arrays (the dtype-layout
    component of executable keys: identical plans over different column
    layouts must not share executables)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(
        (getattr(x, "shape", ()), str(getattr(x, "dtype", type(x).__name__)))
        for x in leaves)
    return fingerprint(str(treedef), shapes)


def fused_key(fragment_bytes: bytes, ndev: int, session,
              scalar_results, ext_inputs) -> Optional[str]:
    """Executable-memo key for a fused super-fragment (fragment fusion,
    plan/distribute.fuse_fragments): one executable per (fused pipeline
    fingerprint, mesh shape, catalog identity+version, property map),
    reused forever — the cluster analog of the chunked/compiled memo
    keys, compounding with the persistent disk cache.

    Host values baked into the trace must ride the key: coordinator-
    evaluated scalar-subquery results, and the dictionary VALUES of any
    string-typed external exchange input (partition_hash bakes a
    host-computed per-code hash LUT).  Oversized string externals
    return None — the build still runs, uncached.

    The MESH SHAPE rides the key too: the same fused fragment traced at
    the same ndev compiles a DIFFERENT program on a multi-process
    global mesh (per-process shard feeds, DCN collectives), so the
    process topology (count, index) is a key component alongside ndev —
    a single-host executable must never serve a gang member."""
    from presto_tpu.parallel import mesh as _MH

    h = hashlib.sha256(fragment_bytes)
    h.update(f"procs={_MH.process_count()}/{_MH.process_index()}"
             .encode())
    for _pid, val in sorted(scalar_results.items()):
        h.update(repr(val).encode())
        h.update(b"\x00")
    nvals = 0
    for eid in sorted(ext_inputs):
        for sym in sorted(ext_inputs[eid]["cols"]):
            data, _valid = ext_inputs[eid]["cols"][sym]
            import numpy as _np

            arr = _np.asarray(data)
            if arr.dtype == object or arr.dtype.kind in ("U", "S"):
                uniq = _np.unique(arr.astype(str))
                nvals += len(uniq)
                if nvals > 100_000:
                    return None  # hashing the dictionary costs too much
                for v in uniq.tolist():
                    h.update(str(v).encode("utf-8", "replace"))
                    h.update(b"\x01")
    return fingerprint("fused", h.hexdigest(), ndev,
                       session_fingerprint(session))


def session_fingerprint(session) -> tuple:
    """The session-dependent key components every executable bakes in at
    trace time: catalog identity+version and the full property map."""
    return (catalog_token(session.catalog),
            getattr(session.catalog, "version", 0),
            tuple(sorted((k, repr(v))
                         for k, v in session.properties.items())))


# ---------------------------------------------------------------------------
# counted jit builds (AOT when example args are available)
# ---------------------------------------------------------------------------


def _shape_struct(x):
    if getattr(x, "weak_type", False) or not hasattr(x, "dtype") \
            or not hasattr(x, "shape"):
        return x
    sharding = getattr(x, "sharding", None)  # mesh-sharded chunk args
    try:
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    except TypeError:
        return jax.ShapeDtypeStruct(x.shape, x.dtype)


class Executable:
    """A counted jax.jit product.  With example args it AOT-compiles
    immediately (lower+compile timed as compile_ms — execution excluded);
    calls dispatch to the AOT executable while argument avals match and
    fall back to the live jit wrapper (which retraces, counted) when
    they stop matching — e.g. an exchange-buffer capacity that changed
    between runs."""

    __slots__ = ("_jitted", "_compiled", "_fellback")

    def __init__(self, fn, jit_kwargs):
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._compiled = None
        self._fellback = False

    def aot_compile(self, example_args) -> None:
        t0 = TR.clock_ns()
        # lower against shape structs, not the concrete arrays: AOT must
        # not pin (or later donate) multi-GB example buffers.  Leaves
        # that aren't plain strong-typed arrays stay concrete — a
        # weak-typed scalar lowered strong would mismatch at call time.
        # The span puts the compile on the query's trace timeline —
        # compile-ahead builds appear on their own pool-thread lane.
        with TR.maybe_span("xla_compile", kind="compile"):
            shapes = jax.tree_util.tree_map(_shape_struct, example_args)
            self._compiled = self._jitted.lower(*shapes).compile()
        _note("compiles")
        _note("compile_ms", (TR.clock_ns() - t0) / 1e6)

    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)

    def __call__(self, *args):
        c = self._compiled
        if c is not None:
            try:
                return c(*args)
            except (TypeError, ValueError):
                # aval/sharding mismatch vs the AOT signature (e.g. an
                # exchange-buffer capacity that changed between runs, or
                # arrays that moved devices): retrace live
                self._compiled = None
        if not self._fellback and self._compiled is None \
                and c is not None:
            self._fellback = True
            _note("compiles")  # the retrace below compiles fresh
        return self._jitted(*args)


def build_jit(fn: Callable, *, example=None, **jit_kwargs) -> Executable:
    """THE routed constructor for engine-level jax.jit programs (the
    test_lint AST rule forbids raw jax.jit outside this module and the
    two executors).  `example`: concrete args to AOT-compile against —
    exact compile timing, and the executable is ready before first use.
    Without example the first call traces+compiles inside jit (counted
    as one compile; its wall time is indistinguishable from execution,
    so compile_ms only grows by AOT builds)."""
    ex = Executable(fn, jit_kwargs)
    if example is not None:
        try:
            ex.aot_compile(example)
        except ValueError as e:
            # mixed-device example (e.g. a mesh-sharded exchange buffer
            # next to host-created arrays): AOT pins explicit shardings
            # where the live jit would reshard implicitly — compile at
            # first call instead.  Anything else is a real trace error.
            if "incompatible devices" not in str(e):
                raise
            _note("compiles")
    else:
        _note("compiles")
    return ex


def static_jit(fn=None, **jit_kwargs):
    """Plain jax.jit passthrough for KERNEL helpers that are invoked
    inside other traced programs (e.g. the Pallas block-gather): nested
    jits inline into the enclosing trace, so counting them would
    double-book the enclosing program's compile."""
    if fn is None:
        return lambda f: jax.jit(f, **jit_kwargs)
    return jax.jit(fn, **jit_kwargs)


# ---------------------------------------------------------------------------
# the process-wide executable memo (single-flight)
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("value", "built_ahead", "ahead_credited")

    def __init__(self, value, built_ahead: bool):
        self.value = value
        self.built_ahead = built_ahead
        self.ahead_credited = False


_memo: Dict[str, _Entry] = {}
_inflight: Dict[str, threading.Event] = {}
_memo_lock = threading.Lock()

#: fragment fingerprints that ever overflowed their compact bound in
#: this process: their next-growth executables are worth pre-compiling
_miss_prone: set = set()


def mark_miss_prone(fp: Optional[str]) -> None:
    if fp:
        with _memo_lock:
            _miss_prone.add(fp)


def is_miss_prone(fp: Optional[str]) -> bool:
    with _memo_lock:
        return fp in _miss_prone


def get_or_build(key: Optional[str], build: Callable[[], Any], *,
                 ahead: bool = False):
    """Memoized single-flight build.  `key` None => uncacheable, build
    directly.  Hits count as compile_cache_hits (or compile_ahead_hits
    the FIRST time a foreground caller collects a background build).
    A failed build caches nothing; concurrent waiters retry it
    themselves so the exception propagates to every caller."""
    if key is None:
        return build()
    while True:
        with _memo_lock:
            e = _memo.get(key)
            if e is not None:
                if not ahead:
                    if e.built_ahead and not e.ahead_credited:
                        e.ahead_credited = True
                        _note("compile_ahead_hits")
                    else:
                        _note("compile_cache_hits")
                return e.value
            ev = _inflight.get(key)
            if ev is None:
                ev = _inflight[key] = threading.Event()
                builder = True
            else:
                builder = False
        if builder:
            try:
                value = build()
                with _memo_lock:
                    _memo[key] = _Entry(value, ahead)
                return value
            finally:
                with _memo_lock:
                    _inflight.pop(key, None)
                ev.set()
        else:
            ev.wait()
            # loop: either the entry exists now, or the build failed and
            # this thread takes its turn


def clear() -> None:
    """Drop every memoized executable (test harness memory bounding —
    the tier-1 suite clears jax caches between modules; pinning
    executables here would defeat that)."""
    with _memo_lock:
        _memo.clear()
        _miss_prone.clear()


def stats() -> Dict[str, Any]:
    with _memo_lock:
        n = len(_memo)
    return dict(GLOBAL.snapshot(), memo_entries=n)


# ---------------------------------------------------------------------------
# compile-ahead pool
# ---------------------------------------------------------------------------

_pool = None
_pool_lock = threading.Lock()


def _cores() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def ahead_enabled(session=None) -> bool:
    """Compile-ahead policy.  Kill switches: env
    PRESTO_TPU_COMPILE_AHEAD=off|0 (process-wide) or session property
    compile_ahead=False; env =on|1|force forces it on.  With neither
    forced, it is ON wherever a background compile can actually overlap
    the query thread (>1 usable core) and OFF on single-core hosts,
    where a "background" compile only steals cycles from the query it
    is supposed to hide behind (TPU hosts have dozens of cores; the
    1-core CI tier is the exception this guards)."""
    env = os.environ.get("PRESTO_TPU_COMPILE_AHEAD", "").lower()
    if env in ("off", "0", "false"):
        return False
    if session is not None and not bool(
            session.properties.get("compile_ahead", True)):
        return False
    if env in ("on", "1", "true", "force"):
        return True
    return _cores() > 1


def _get_pool():
    global _pool
    with _pool_lock:
        if _pool is None:
            from concurrent.futures import ThreadPoolExecutor

            n = int(os.environ.get("PRESTO_TPU_COMPILE_AHEAD_WORKERS",
                                   "2"))
            _pool = ThreadPoolExecutor(
                max_workers=max(n, 1),
                thread_name_prefix="presto-tpu-compile-ahead")
        return _pool


def current_sink():
    """The stats object this thread's compile accounting flows into
    (pass it to `submit` so background builds bill the initiating
    query), or None outside any recording."""
    return getattr(_tls, "sink", None)


def submit(job: Callable[[], Any], stats_sink=None) -> bool:
    """Queue a compile-ahead job on the bounded pool.  Jobs build
    through `get_or_build(..., ahead=True)`, so the single-flight memo
    makes them race-free against the query thread: whichever side
    starts first compiles, the other waits or hits.  Job failures are
    swallowed — the foreground will rebuild and surface the error
    properly."""

    # the submitting thread's trace context rides along, so background
    # builds appear on the query's trace under the pool thread's lane
    tracer = TR.current()

    def wrapped():
        try:
            with recording(stats_sink if stats_sink is not None
                           else CompileStats()), TR.activate(tracer):
                job()
        except BaseException:
            pass  # foreground retries and reports

    try:
        _get_pool().submit(wrapped)
    except RuntimeError:  # interpreter shutdown
        return False
    return True
