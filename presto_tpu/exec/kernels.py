"""Relational kernels over columnar device arrays.

Reference parity: the operator layer (presto-main/.../operator/, §2.4 of
SURVEY.md) re-expressed as whole-column array programs:

- HashAggregationOperator + GroupByHash (operator/MultiChannelGroupByHash.java)
  -> exact key packing + sort + segmented reductions.  TPUs have no
  scatter-friendly hash tables; sort-based grouping is contention-free and
  maps onto the sorting network + segmented-scan idioms XLA compiles well.
- HashBuilderOperator/LookupJoinOperator (PagesIndex + JoinProbe)
  -> sort build side + vectorized searchsorted probe; FK joins (unique
  build keys) are a pure gather; one-to-many expands via repeat with a
  computed total (the PositionLinks analog).
- OrderByOperator/TopNOperator -> multi-key lexicographic argsort / sort+cut.
- Masks replace selection: filters AND into `sel` (no compaction inside a
  fragment), the static-shape answer to data-dependent page sizes.

Eager-mode kernels pull capacities to host (dynamic result sizing); the
jitted fragment path reuses the same functions with static capacities.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.exec import gather as G
from presto_tpu.exec.colval import translate_codes

I64_MIN = np.iinfo(np.int64).min
I64_MAX = np.iinfo(np.int64).max
I32_MAX = np.iinfo(np.int32).max


def key_sentinel(key) -> int:
    """Masked-row sentinel for a packed key array: the dtype's max
    (narrow int32 keys avoid the TPU's emulated 64-bit integer ops —
    the hardware has no native int64, so every i64 compare/sort/gather
    runs as u32-pair fusions, measured ~8s of TPC-H Q18's runtime)."""
    return I32_MAX if key.dtype == jnp.int32 else I64_MAX


# ---------------------------------------------------------------------------
# key packing: N key columns -> one int64 (exact, using runtime ranges)
# ---------------------------------------------------------------------------


def pack_keys(cols: List[Column], sel, extra_cols: Optional[List[Column]] = None):
    """Pack key columns into a single integer key per row — int32 when
    the packed widths fit 30 bits (native on TPU), else int64.  Masked-out
    rows get the dtype's max as sentinel (sorts last, never matches; see
    key_sentinel). NULL in any key column gets its own code (SQL GROUP BY
    treats NULLs as one group).

    Returns (key: i32[n]|i64[n], layout) where layout allows packing another
    column set with the same strides (for join build/probe sides pass
    `extra_cols` so both sides share ranges).
    """
    def _minmax(col):
        d = _orderable_int(col)
        if d.shape[0] == 0:  # zero-capacity side (empty split/partition)
            return jnp.asarray(I64_MAX), jnp.asarray(I64_MIN)
        return (jnp.min(jnp.where(_valid_arr(col), d, I64_MAX)),
                jnp.max(jnp.where(_valid_arr(col), d, I64_MIN)))

    parts = []
    for i, c in enumerate(cols):
        lo, hi = _minmax(c)
        if extra_cols is not None:
            elo, ehi = _minmax(extra_cols[i])
            lo = jnp.minimum(lo, elo)
            hi = jnp.maximum(hi, ehi)
        lo_h = int(lo)
        hi_h = int(hi)
        if hi_h < lo_h:  # all null / empty
            lo_h, hi_h = 0, 0
        parts.append((lo_h, hi_h - lo_h + 2))  # +1 for range, +1 for null code

    total_bits = sum(int(np.ceil(np.log2(max(card, 2)))) for _, card in parts)
    if total_bits > 62:
        return _hash_keys(cols, sel), None

    key = _apply_layout(cols, (layout := _assign_strides(parts)))
    key = jnp.where(sel, key, key_sentinel(key))
    return key, layout


def _assign_strides(parts) -> list:
    """(lo, card) per column -> (lo, stride, width) with the FIRST column
    most significant: ascending packed-key order == lexicographic order
    of the columns as listed.  This is what makes grouped output sorted
    on its group keys (the ordering-properties framework's producer
    side) at zero cost — stride assignment order is free."""
    widths = [int(np.ceil(np.log2(max(card, 2)))) for _, card in parts]
    layout = []
    stride = 1
    for (lo_h, _card), width in zip(reversed(parts), reversed(widths)):
        layout.append((lo_h, stride, width))
        stride <<= width
    layout.reverse()
    return layout


def _apply_layout(cols: List[Column], layout) -> jnp.ndarray:
    total_bits = sum(w for _, _, w in layout)
    kt = jnp.int32 if total_bits <= 30 else jnp.int64  # native i32 wins
    key = None
    for c, (lo, stride, width) in zip(cols, layout):
        d = _orderable_int(c)
        code = jnp.where(_valid_arr(c), d - lo + 1, 0)  # 0 = null code
        contrib = code.astype(kt) * kt(stride)
        key = contrib if key is None else key + contrib
    return key


def pack_with_layout(cols: List[Column], sel, layout) -> jnp.ndarray:
    if layout is None:
        return _hash_keys(cols, sel)
    key = _apply_layout(cols, layout)
    return jnp.where(sel, key, key_sentinel(key))


_POW2 = None  # lazily-built exact power-of-two table (host constants)


def _f64_orderable_arith(d: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving, injective f64 -> i64 WITHOUT any 64-bit bitcast
    (the axon TPU compile path cannot rewrite f64 bitcasts).  Decomposes
    |x| = m * 2^e arithmetically: e from log2 with comparison fixups, m
    recovered by an EXACT power-of-two table multiply, so mant = m*2^52
    is the exact 53-bit significand.  Layout: subnormal magnitudes map to
    [1, 2^52), normals to [(e+1023)*2^52 + mant52] <= 2047*2^52 < 2^63;
    negatives mirror; +-0 both map to 0 (SQL-correct: they compare
    equal); +-inf and NaN get sentinels with NaN largest (Presto sort
    order).  Replaces the classic sign-flip bit trick, which is kept
    out because jax.lax.bitcast_convert_type(f64) does not compile
    on this TPU stack."""
    global _POW2
    if _POW2 is None:
        # host-side numpy so the table is a fresh constant per trace
        # (a traced global would leak tracers)
        _POW2 = np.asarray([2.0 ** i for i in range(-1099, 1024)],
                           dtype=np.float64)
    pow2 = jnp.asarray(_POW2)

    min_normal = 2.2250738585072014e-308
    ax = jnp.abs(d)
    e = jnp.floor(jnp.log2(jnp.maximum(ax, min_normal))).astype(jnp.int64)
    e = jnp.clip(e, -1022, 1023)
    # ax * 2^-e in two half-exponent steps: a single 2^-1023 constant is
    # subnormal and DAZ-flushed to zero (which would collapse the whole
    # top binade); both halves and both intermediates stay normal
    e1 = e // 2
    e2 = e - e1
    m = (ax * pow2[1099 - e1]) * pow2[1099 - e2]  # exact
    # log2 rounding can be off by one near power-of-two boundaries;
    # two fixup rounds restore m in [1, 2) exactly
    for _ in range(2):
        too_big = m >= 2.0
        e = jnp.where(too_big, e + 1, e)
        m = jnp.where(too_big, m * 0.5, m)
        too_small = m < 1.0
        e = jnp.where(too_small & (e > -1022), e - 1, e)
        m = jnp.where(too_small & (e >= -1022), m * 2.0, m)
    mant = (m * (2.0 ** 52)).astype(jnp.int64) - (1 << 52)
    # max key = 2047*2^52 - 1, safely below the +-inf/NaN sentinels and
    # the masked-row sentinel I64_MAX
    key_norm = (e + 1023) * (1 << 52) + mant
    # subnormals: XLA runs with FTZ/DAZ, so every arithmetic op in the
    # engine already sees them as zero — key 0 keeps grouping/joins
    # consistent with that arithmetic
    key_mag = jnp.where(ax < min_normal, 0, key_norm)
    key = jnp.where(d < 0, -key_mag, key_mag)
    key = jnp.where(jnp.isinf(d),
                    jnp.where(d > 0, jnp.int64(I64_MAX - 16),
                              jnp.int64(-(I64_MAX - 16))), key)
    return jnp.where(jnp.isnan(d), jnp.int64(I64_MAX - 8), key)


def _f64_orderable_pair(d: jnp.ndarray) -> jnp.ndarray:
    """TPU orderable key for f64: lexicographic (hi, lo) float32 pair
    packed into i64 via 32-bit bitcasts (the only bitcasts this TPU
    stack compiles).  Monotone for ALL doubles; injective down to
    48-bit significands — finer-grained values merge, which matches the
    hardware reality that this TPU's f64 is itself emulated (its
    floor/convert ops already round near bit 49, see
    _f64_orderable_arith for the exact CPU path)."""
    hi = jnp.clip(d.astype(jnp.float32), -3.4e38, 3.4e38)
    lo = (d - hi.astype(jnp.float64)).astype(jnp.float32)
    # finite values beyond f32 range merge near the top of the finite
    # band but stay strictly below +-inf
    lo = jnp.where(jnp.isfinite(d), jnp.clip(lo, -3.4e38, 3.4e38), lo)

    def o32(f):
        b = jax.lax.bitcast_convert_type(f, jnp.int32)
        return jnp.where(b < 0, (~b) + jnp.int32(-(1 << 31)), b)

    key = (o32(hi).astype(jnp.int64) * (1 << 32)
           + o32(lo).astype(jnp.int64) + (1 << 31))
    key = jnp.where(d == 0, 0, key)  # +-0 compare equal in SQL
    return jnp.where(jnp.isnan(d), jnp.int64(I64_MAX - 8), key)


def _orderable_int(c: Column) -> jnp.ndarray:
    d = c.data
    if d.dtype == jnp.bool_:
        return d.astype(jnp.int64)
    if jnp.issubdtype(d.dtype, jnp.floating):
        if jax.default_backend() == "tpu":
            return _f64_orderable_pair(d.astype(jnp.float64))
        return _f64_orderable_arith(d.astype(jnp.float64))
    return d.astype(jnp.int64)


def _valid_arr(c: Column) -> jnp.ndarray:
    if c.valid is None:
        return jnp.ones(c.data.shape, dtype=bool)
    return c.valid


def _hash_keys(cols: List[Column], sel) -> jnp.ndarray:
    """64-bit mix fallback when exact packing exceeds 62 bits.
    Collision probability for n rows ~ n^2/2^64 (documented engine limit;
    an exact verification pass can be layered later)."""
    h = jnp.zeros(cols[0].data.shape, dtype=jnp.uint64)
    for c in cols:
        d = _orderable_int(c).astype(jnp.uint64)
        d = jnp.where(_valid_arr(c), d, jnp.uint64(0x9E3779B97F4A7C15))
        h = h ^ (d + jnp.uint64(0x9E3779B97F4A7C15) + (h << jnp.uint64(6)) + (h >> jnp.uint64(2)))
        z = h
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = z ^ (z >> jnp.uint64(31))
    key = (h >> jnp.uint64(1)).astype(jnp.int64)  # keep positive, below I64_MAX
    return jnp.where(sel, key, I64_MAX)


# ---------------------------------------------------------------------------
# group-by
# ---------------------------------------------------------------------------


def static_layout(cols: List[Column], stats_list) -> Optional[list]:
    """Compile-time pack layout from metadata: dictionary sizes for string
    codes, connector ColStats ranges for numerics.  Returns None when any
    column's range is unknown (callers fall back to 64-bit hashing, which
    needs no range and no host sync)."""
    parts = []
    for c, st in zip(cols, stats_list):
        if c.dictionary is not None:
            lo, hi = 0, max(len(c.dictionary) - 1, 0)
        elif c.data.dtype == jnp.bool_:
            lo, hi = 0, 1
        elif st is not None and st.min is not None and st.max is not None \
                and not jnp.issubdtype(c.data.dtype, jnp.floating):
            lo, hi = int(st.min), int(st.max)
        else:
            return None
        parts.append((lo, hi - lo + 2))
    total_bits = sum(int(np.ceil(np.log2(max(card, 2)))) for _, card in parts)
    if total_bits > 62:
        return None
    return _assign_strides(parts)


def layout_range_guard(cols: List[Column], sel, layout) -> jnp.ndarray:
    """True if any live value falls outside its static layout range —
    out-of-range values would bleed bits into adjacent packed fields and
    silently corrupt keys, so the compiled path re-runs dynamically."""
    bad = jnp.zeros((), bool)
    for c, (lo, _stride, width) in zip(cols, layout):
        d = _orderable_int(c)
        live = sel & _valid_arr(c)
        hi = lo + (1 << width) - 2  # code 0 reserved for NULL
        bad = bad | jnp.any(live & ((d < lo) | (d > hi)))
    return bad


def nonzero_i32(mask: jnp.ndarray, size: int, fill: int) -> jnp.ndarray:
    """jnp.nonzero(mask, size=, fill_value=)[0] in int32 throughout.
    Under jax x64 the stock nonzero computes its prefix sums in int64,
    which the TPU emulates as u32-pair fusions (~500ms per 6M rows,
    measured); an i32 cumsum + one i32 co-sort is ~3x cheaper."""
    n = mask.shape[0]
    fill = min(max(int(fill), 0), max(n - 1, 0))  # stock nonzero clips
    total = jnp.sum(mask.astype(jnp.int32)) if n else jnp.int32(0)
    if 0 < size <= (1 << 16) and n > 4 * size:
        # small k: top_k over a positional score (~10ms at 6M rows vs
        # ~170ms for the sort — same idiom as executor._compact_batch)
        pos = jnp.arange(n, dtype=jnp.int32)
        score = jnp.where(mask, n - pos, 0)
        top = jax.lax.top_k(score, size)[0]
        out = jnp.clip(n - top, 0, n - 1)
    else:
        ones = mask.astype(jnp.int32)
        cum = jnp.cumsum(ones)
        slot = jnp.where(mask, cum - ones, jnp.int32(n))  # excl. prefix
        _, sidx = jax.lax.sort((slot, jnp.arange(n, dtype=jnp.int32)),
                               num_keys=1)
        out = sidx[:size] if n >= size else jnp.concatenate(
            [sidx, jnp.full((size - n,), fill, jnp.int32)])
    return jnp.where(jnp.arange(size, dtype=jnp.int32) < total, out,
                     jnp.int32(fill))


def unpermute(order: jnp.ndarray, *payloads):
    """Carry payloads back to original row order: payload[i] moves to
    position order[i].  One co-sort keyed on the permutation replaces
    `payload[argsort(order)]` — on TPU an extra full-size GATHER costs
    ~43ms per 6M rows (measured, Q1 xplane) while sort payload operands
    ride along nearly free (8 payloads sort at 1-payload cost)."""
    out = jax.lax.sort((order,) + payloads, num_keys=1)[1:]
    return out[0] if len(out) == 1 else out


def sort_pair(key: jnp.ndarray):
    """(sorted key, permutation) — THE routed entry point for key sorts,
    so the executor's sort-permutation memo can cache and replay the
    permutation for every later grouping/join on the same key."""
    n = key.shape[0]
    return jax.lax.sort((key, jnp.arange(n, dtype=jnp.int32)), num_keys=1)


def monotone_guard(key: jnp.ndarray) -> jnp.ndarray:
    """True if `key` is NOT nondecreasing end to end (the traced
    ordering-claim verifier for presorted JOIN builds, where sentinels
    must already sit in a suffix — same pattern as layout_range_guard:
    a tripped guard sends the compiled program to the dynamic path)."""
    if key.shape[0] < 2:
        return jnp.zeros((), bool)
    return jnp.any(key[1:] < key[:-1])


def _live_runs(key: jnp.ndarray):
    """Run-boundary scan over a key whose LIVE subsequence is claimed
    nondecreasing (masked rows carry key_sentinel and may be anywhere).
    Returns (live, newgrp, guard): newgrp marks each live row starting a
    new key run; guard is True when the claim is violated.  The
    previous-live-key at row i is the running max of live keys before i
    — exact under the claim, and any violation (a live key below that
    max) trips the guard, so a wrong claim can never mis-group."""
    n = key.shape[0]
    live = key != key_sentinel(key)
    if n == 0:
        z = jnp.zeros((0,), bool)
        return z, z, jnp.zeros((), bool)
    # packed keys are nonnegative (codes >= 0 per field), so -1 is a
    # safe "no previous live row" floor
    floor = jnp.where(live, key, jnp.full((), -1, key.dtype))
    prev = jnp.concatenate([jnp.full((1,), -1, key.dtype),
                            jax.lax.cummax(floor)[:-1]])
    guard = jnp.any(live & (key < prev))
    newgrp = live & (key != prev)
    return live, newgrp, guard


def group_ids_presorted(key: jnp.ndarray, sel):
    """Sort-free grouping for a key already nondecreasing over its live
    rows (scan order from an ordering-declaring connector, or a
    prior grouped output): ONE run-boundary scan replaces the grouping
    sort AND the unpermute co-sort.  Returns (gid, newgrp, n_groups_t,
    guard) with gid semantics identical to group_ids — groups numbered
    in ascending key order; representatives are the first row of each
    run, recoverable as nonzero_i32(newgrp, ...) once the caller has
    host-synced n_groups_t (together with the guard, in ONE fetch).
    guard True => the ordering claim lied and the results are garbage;
    callers MUST fall back to group_ids."""
    live, newgrp, guard = _live_runs(key)
    n = key.shape[0]
    n_groups_t = jnp.sum(newgrp.astype(jnp.int32))
    gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1 if n else \
        jnp.zeros((0,), jnp.int32)
    gid = jnp.where(live, gid, n_groups_t)
    return gid, newgrp, n_groups_t, guard


def group_ids_presorted_static(key: jnp.ndarray, cap: int):
    """Static-capacity twin of group_ids_presorted: returns (gid,
    rep_rows[cap], exists[cap], overflow, guard) matching the
    group_ids_static contract, with guard riding the executor's existing
    static-guard channel (trip => whole-query dynamic fallback)."""
    live, newgrp, guard = _live_runs(key)
    n = key.shape[0]
    n_groups = jnp.sum(newgrp.astype(jnp.int32))
    if n == 0:
        gid = jnp.zeros((0,), jnp.int32)
        rep_rows = jnp.zeros((cap,), jnp.int32)
    else:
        gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
        gid = jnp.where(live & (gid < cap), gid, cap)
        rep_rows = nonzero_i32(newgrp, cap, 0)
    exists = jnp.arange(cap) < n_groups
    return gid, rep_rows, exists, n_groups > cap, guard


def group_ids_static(key: jnp.ndarray, cap: int, sorted_pair=None):
    """Static-shape grouping: same sort-based scheme as group_ids but with
    a fixed group capacity.  Returns (gid, rep_rows[cap], exists[cap],
    overflow) — overflow True means cap was too small (caller re-runs in
    dynamic mode; the guard is checked once per query, not per op).
    `sorted_pair` replays a memoized (skey, order) for this exact key."""
    n = key.shape[0]
    skey, order = sorted_pair if sorted_pair is not None else sort_pair(key)
    newgrp = jnp.concatenate([jnp.ones((1,), bool), skey[1:] != skey[:-1]])
    live_sorted = skey != key_sentinel(key)
    newgrp = newgrp & live_sorted
    n_groups = jnp.sum(newgrp)
    gid_sorted = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    gid_sorted = jnp.where(live_sorted & (gid_sorted < cap), gid_sorted, cap)
    gid = unpermute(order, gid_sorted)
    rep_pos = nonzero_i32(newgrp, cap, 0)
    if n == 0:  # empty input (e.g. zero-row exchange buffer)
        rep_rows = jnp.zeros((cap,), jnp.int32)
    else:
        rep_rows = order[rep_pos]
    exists = jnp.arange(cap) < n_groups
    return gid, rep_rows, exists, n_groups > cap


def group_ids(key: jnp.ndarray, sel,
              sorted_pair=None) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Sort-based grouping. Returns (gid[n] in [0, n_groups) for live rows,
    representative row index per group [n_groups], n_groups).
    Masked rows get gid = n_groups (callers drop them via segment bounds).
    `sorted_pair` replays a memoized (skey, order) for this exact key."""
    n = key.shape[0]
    skey, order = sorted_pair if sorted_pair is not None \
        else sort_pair(key)  # masked rows sort last
    newgrp = jnp.concatenate([jnp.ones((1,), bool), skey[1:] != skey[:-1]])
    live_sorted = skey != key_sentinel(key)
    newgrp = newgrp & live_sorted
    gid_sorted = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    n_groups = int(jnp.sum(newgrp))
    gid_sorted = jnp.where(live_sorted, gid_sorted, n_groups)
    gid = unpermute(order, gid_sorted)
    # representative row per group = first sorted occurrence
    rep_sorted_pos = nonzero_i32(newgrp, max(n_groups, 1), 0)
    rep_rows = order[rep_sorted_pos][:n_groups] if n_groups else jnp.zeros((0,), order.dtype)
    return gid, rep_rows, n_groups


_MATMUL_GROUPS = 4096  # few-group segment sums go through the MXU instead
# (einsum against a fused one-hot costs ~7ms at 6M rows x 1024 groups,
# measured, vs ~48ms per column for the TPU scatter-add lowering)


def segment_sum(x, gid, n_groups):
    if n_groups == 1:
        # global aggregate: a plain reduction — segment scatter-add into
        # one bucket serializes on TPU (hundreds of memory passes)
        return jnp.sum(x)[None]
    if n_groups <= _MATMUL_GROUPS and x.ndim == 1 \
            and x.shape[0] >= 4 * n_groups:
        # few groups, many rows: one-hot matmul rides the MXU; the TPU
        # scatter-add lowering serializes per-bucket otherwise
        oh = jax.nn.one_hot(gid, n_groups, dtype=jnp.float32)
        if jnp.issubdtype(x.dtype, jnp.floating):
            acc = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
            return jnp.einsum("r,rg->g", x.astype(acc),
                              oh.astype(acc)).astype(x.dtype)
        # exact int64 via three 22-bit limbs (each limb sum stays inside
        # the f64 integer range for any realistic row count); modular
        # reconstruction matches two's-complement int64 addition
        xi = x.astype(jnp.int64)
        ohf = oh.astype(jnp.float64)
        out = jnp.zeros((n_groups,), dtype=jnp.int64)
        for shift in (0, 22, 44):
            limb = ((xi >> shift) & 0x3FFFFF).astype(jnp.float64)
            s = jnp.einsum("r,rg->g", limb, ohf)
            out = out + (s.astype(jnp.int64) << shift)
        return out.astype(x.dtype if x.dtype != jnp.bool_ else jnp.int64)
    return jax.ops.segment_sum(x, gid, num_segments=n_groups + 1)[:n_groups]


def _reduce_identity(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    if dtype == jnp.bool_:
        return True if for_min else False
    info = jnp.iinfo(dtype)
    return info.max if for_min else info.min


def segment_min(x, gid, n_groups):
    if n_groups == 1:
        if x.shape[0] == 0:  # empty split/partition: the identity, like
            return jnp.full((1,), _reduce_identity(x.dtype, True), x.dtype)
        return jnp.min(x)[None]
    return jax.ops.segment_min(x, gid, num_segments=n_groups + 1)[:n_groups]


def segment_max(x, gid, n_groups):
    if n_groups == 1:
        if x.shape[0] == 0:
            return jnp.full((1,), _reduce_identity(x.dtype, False), x.dtype)
        return jnp.max(x)[None]
    return jax.ops.segment_max(x, gid, num_segments=n_groups + 1)[:n_groups]


def segment_any(mask, gid, n: int):
    """True where ANY row of the segment has `mask` set — the join
    layer's "any passing match per probe row" reduction.  Exact
    num_segments with no dead slot: gid here is a probe-row index,
    always in range (unlike the grouping kernels' sentinel slot)."""
    return jax.ops.segment_max(mask.astype(jnp.int32), gid,
                               num_segments=n) > 0


# ---------------------------------------------------------------------------
# join probe
# ---------------------------------------------------------------------------


def hll_hash64(col: Column) -> jnp.ndarray:
    """Process-independent 64-bit value hash for approx_distinct: string
    (dictionary) columns hash their VALUES via xxh64 host-side per
    dictionary entry (cached on the Dictionary), so shards/workers with
    different code assignments agree; numeric columns splitmix their
    orderable ints.  Single-device and distributed paths share this, so
    their HLL registers — and estimates — match exactly while both use
    m=1024 registers (hll_registers_and_estimate shrinks m above ~8k
    groups to bound the register matrix; past that point the two paths
    are independent — both valid — approximations)."""
    d = jnp.asarray(col.data)
    dic = col.dictionary
    if dic is not None and not hasattr(dic.values, "prefix"):
        hv = getattr(dic, "_value_hashes", None)
        if hv is None:
            from presto_tpu import native

            hv = np.asarray(
                [native.xxh64(str(v).encode("utf-8", "surrogatepass"))
                 for v in dic.values.tolist()], dtype=np.uint64)
            try:
                dic._value_hashes = hv
            except AttributeError:
                pass
        safe = jnp.clip(d, 0, max(len(dic) - 1, 0))
        return jnp.asarray(hv)[safe]
    # numeric / FormatDictionary (code<->value bijection): splitmix the value
    x = _orderable_int(col).astype(jnp.uint64)
    z = x + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def hll_registers_and_estimate(h: jnp.ndarray, valid: jnp.ndarray,
                               gid: jnp.ndarray, n_groups: int,
                               m: int = 1024) -> jnp.ndarray:
    """Vectorized HyperLogLog per group — the TPU-native
    approx_distinct (reference: ApproximateCountDistinctAggregation over
    airlift HLL sketches).  Instead of per-row sketch objects, all
    n_groups*m registers live in one array updated by a single
    segment_max; the bias-corrected estimate with small-range linear
    counting follows the standard HLL formula.  m=1024 registers gives
    ~3.25% standard error (1.04/sqrt(m)); for very large group counts m
    shrinks so the register matrix stays bounded (~64MB) instead of
    scaling to gigabytes with a static capacity hint."""
    max_registers = 1 << 23
    while m > 64 and n_groups * m > max_registers:
        m //= 2
    log2m = int(np.log2(m))
    reg = (h & jnp.uint64(m - 1)).astype(jnp.int64)
    w = ((h >> jnp.uint64(log2m)) & jnp.uint64(0xFFFFFFFF)).astype(jnp.float64)
    # rho = position of the leftmost 1-bit of the 32-bit w (1-based from
    # the top); w == 0 -> 33.  float64 log2 is exact for ints < 2^53.
    rho = jnp.where(w > 0, 32.0 - jnp.floor(jnp.log2(jnp.maximum(w, 1.0))),
                    33.0)
    seg = gid * m + reg
    seg = jnp.where(valid, seg, n_groups * m)  # dead rows -> overflow slot
    M = jax.ops.segment_max(
        jnp.where(valid, rho, 0.0), seg, num_segments=n_groups * m + 1,
    )[:-1].reshape(n_groups, m)
    M = jnp.maximum(M, 0.0)  # empty registers: segment_max identity is -inf
    return hll_estimate(M)


def hll_m_for_error(e: float) -> int:
    """Register count for a requested standard error e: the power of two
    with 1.04/sqrt(m) <= e, clamped to [64, 65536] (reference:
    HyperLogLog's indexBitLength from maxStandardError)."""
    m = 64
    while m < 65536 and 1.04 / np.sqrt(m) > e:
        m *= 2
    return m


def hll_estimate(M: jnp.ndarray) -> jnp.ndarray:
    """Bias-corrected HLL estimate with small-range linear counting from
    an (n_groups, m) register matrix (any integer/float register dtype)."""
    m = M.shape[1]
    Mf = M.astype(jnp.float64)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    E = alpha * m * m / jnp.sum(2.0 ** (-Mf), axis=1)
    zeros = jnp.sum(Mf == 0.0, axis=1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1).astype(jnp.float64))
    est = jnp.where((E <= 2.5 * m) & (zeros > 0), linear, E)
    return jnp.round(est).astype(jnp.int64)


def hll_partial(h: jnp.ndarray, valid: jnp.ndarray, gid: jnp.ndarray,
                n_groups: int, m: int = 1024) -> jnp.ndarray:
    """Per-group HLL register ROWS as the mergeable partial state: one
    (n_groups, m) uint8 matrix built by a single segment_max.  Unlike
    hll_registers_and_estimate this never shrinks m — the state's shape
    is part of its TYPE (types.hll_state(m)) and must agree across
    chunks/shards so partials fold with elementwise max."""
    log2m = int(np.log2(m))
    reg = (h & jnp.uint64(m - 1)).astype(jnp.int64)
    w = ((h >> jnp.uint64(log2m)) & jnp.uint64(0xFFFFFFFF)).astype(jnp.float64)
    rho = jnp.where(w > 0, 32.0 - jnp.floor(jnp.log2(jnp.maximum(w, 1.0))),
                    33.0)
    seg = gid * m + reg
    seg = jnp.where(valid, seg, n_groups * m)  # dead rows -> overflow slot
    M = jax.ops.segment_max(
        jnp.where(valid, rho, 0.0), seg, num_segments=n_groups * m + 1,
    )[:-1].reshape(n_groups, m)
    return jnp.maximum(M, 0.0).astype(jnp.uint8)


def hll_merge(regs: jnp.ndarray, valid, gid: jnp.ndarray,
              n_groups: int) -> jnp.ndarray:
    """Fold partial register rows per group — HLL union IS elementwise
    max, so a 2-D segment_max over the row axis merges any number of
    partial sketches exactly (order- and partition-independent)."""
    g = gid if valid is None else jnp.where(valid, gid, n_groups)
    M = jax.ops.segment_max(regs.astype(jnp.int32), g,
                            num_segments=n_groups + 1)[:n_groups]
    return jnp.maximum(M, 0).astype(jnp.uint8)


def hll_merge_estimate(regs: jnp.ndarray, valid, gid: jnp.ndarray,
                       n_groups: int) -> jnp.ndarray:
    """Final aggregate over partial HLL states: merge rows per group,
    then estimate.  Estimates are bit-identical to the single-pass
    kernel at equal m because max is associative over the same rho set."""
    return hll_estimate(hll_merge(regs, valid, gid, n_groups))


def kll_partial(x: jnp.ndarray, valid: jnp.ndarray, gid: jnp.ndarray,
                n_groups: int, K: int) -> jnp.ndarray:
    """Fixed-shape per-group quantile summary (KLL-style single
    compactor level): K evenly-spaced order statistics + their integer
    weights, concatenated into a (n_groups, 2K) float64 state row.  One
    global (group, value) lexsort builds every group's summary; weight
    w_j = floor((j+1)*cnt/K) - floor(j*cnt/K) telescopes to exactly cnt,
    so merged rank queries stay within ~1/K of truth per merge level."""
    n = x.shape[0]
    if n == 0:
        return jnp.zeros((n_groups, 2 * K), jnp.float64)
    xf = jnp.where(valid, x.astype(jnp.float64), jnp.inf)
    g = jnp.where(valid, gid, n_groups)       # invalid rows: dead group
    order = jnp.lexsort((xf, g))
    cnt = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                              num_segments=n_groups + 1)[:n_groups]
    starts = jnp.cumsum(cnt) - cnt
    cf = cnt.astype(jnp.float64)[:, None]
    j = jnp.arange(K, dtype=jnp.float64)[None, :]
    # j-th summary value = the floor((j+0.5)*cnt/K)-th smallest of the
    # group (midpoint rule keeps both tails represented)
    r = jnp.floor((j + 0.5) * cf / K).astype(jnp.int64)
    r = jnp.clip(r, 0, jnp.maximum(cnt - 1, 0)[:, None])
    pos = jnp.clip(starts[:, None] + r, 0, n - 1)
    vals = xf[order][pos]
    wts = jnp.floor((j + 1.0) * cf / K) - jnp.floor(j * cf / K)
    vals = jnp.where(wts > 0, vals, 0.0)  # empty groups gather junk
    return jnp.concatenate([vals, wts], axis=1)


def kll_percentile(state: jnp.ndarray, valid, gid: jnp.ndarray,
                   n_groups: int, p: float, K: int) -> tuple:
    """Final aggregate over partial KLL states: flatten every state
    row's (value, weight) pairs, lexsort by (group, value), and read the
    first value whose within-group cumulative weight reaches the target
    rank floor(p*(N-1))+1.  Zero-weight entries can never win: their
    cumulative weight equals the previous positive entry's, which sits
    earlier in sort order.  Returns (values, nonempty)."""
    n = state.shape[0]
    if n == 0:
        return (jnp.zeros((n_groups,), jnp.float64),
                jnp.zeros((n_groups,), jnp.bool_))
    vals, wts = state[:, :K], state[:, K:]
    ok = jnp.ones((n,), jnp.bool_) if valid is None else valid
    g_flat = jnp.repeat(jnp.where(ok, gid, n_groups), K)
    v_flat = vals.reshape(-1)
    w_flat = jnp.where(ok[:, None], wts, 0.0).reshape(-1)
    order = jnp.lexsort((v_flat, g_flat))
    vs, ws, gs = v_flat[order], w_flat[order], g_flat[order]
    totw = jax.ops.segment_sum(ws, gs, num_segments=n_groups + 1)[:n_groups]
    offs = jnp.cumsum(totw) - totw            # weight of earlier groups
    cumw = jnp.cumsum(ws)                     # global prefix (dead group last)
    g_safe = jnp.minimum(gs, n_groups - 1)
    t = jnp.clip(jnp.floor(p * jnp.maximum(totw - 1, 0)) + 1.0, 1.0,
                 jnp.maximum(totw, 1.0))
    cand = (cumw - offs[g_safe] >= t[g_safe]) & (gs < n_groups)
    idx = jnp.where(cand, jnp.arange(vs.shape[0]), vs.shape[0])
    first = jax.ops.segment_min(idx, gs, num_segments=n_groups + 1)[:n_groups]
    out = vs[jnp.clip(first, 0, vs.shape[0] - 1)]
    return out, totw > 0


def sketch_sample_mask(h: jnp.ndarray) -> jnp.ndarray:
    """Deterministic 1-in-8 value sample for COUNT/SUM ... WITH ERROR:
    keep rows whose value hash lands in one of 8 residue classes.  The
    kept fraction is exactly 1/8 of DISTINCT hash space, so the x8
    scale-up is an exact power-of-two multiply and every execution mode
    (single, chunked, sharded) samples the SAME rows — estimates are
    bit-identical regardless of partitioning."""
    return (h & jnp.uint64(7)) == jnp.uint64(0)


def group_percentile(x: jnp.ndarray, valid: jnp.ndarray, gid: jnp.ndarray,
                     n_groups: int, p) -> tuple:
    """Per-group percentile by global sort — the TPU replacement for
    per-group quantile-digest accumulators (reference: approx_percentile
    over QuantileDigest): sort all rows by (group, value) once, then
    gather each group's p-th position.  Returns (values, nonempty)."""
    cnt = jax.ops.segment_sum(valid.astype(jnp.int64), gid,
                              num_segments=n_groups)
    xf = x.astype(jnp.float64)
    xf = jnp.where(valid, xf, jnp.inf)        # invalid rows sort last
    g = jnp.where(valid, gid, n_groups)       # ...and into a dead group
    order = jnp.lexsort((xf, g))
    starts = jnp.cumsum(cnt) - cnt
    k = jnp.clip(jnp.floor(p * jnp.maximum(cnt - 1, 0).astype(jnp.float64))
                 .astype(jnp.int64), 0, jnp.maximum(cnt - 1, 0))
    pos = jnp.clip(starts + k, 0, x.shape[0] - 1)
    vals = x[order[pos]]
    return vals, cnt > 0


def build_probe(build_key: jnp.ndarray, probe_key: jnp.ndarray,
                build_order=None):
    """Sort build side; position every probe key among the build keys.
    Returns (order, lb, ub): build_key[order] sorted; matches for probe row
    i are order[lb[i]:ub[i]].

    One composite lax.sort of (key, side-flag) + prefix scans replaces two
    searchsorted(method='sort') calls: each of those hides a full-size
    permutation SCATTER, which serializes on TPU (~600ms per 7M rows,
    measured) — the scan+gather formulation costs three sorts and no
    scatter, ~3x faster end-to-end on the join-heavy TPC-H queries.

    `build_order` elides the build argsort (1 of the 3 sorts): a
    memoized permutation of this exact key, or an identity arange when
    the build side is already fully nondecreasing (sentinels in a
    suffix — callers verify via monotone_guard; equal-key order within
    a run is free, matches are consumed as a set)."""
    nb = build_key.shape[0]
    npr = probe_key.shape[0]
    order = build_order if build_order is not None \
        else sort_pair(build_key)[1]
    n = nb + npr
    allk = jnp.concatenate([build_key, probe_key])
    flag = jnp.concatenate([jnp.zeros((nb,), jnp.int32),
                            jnp.ones((npr,), jnp.int32)])
    sk, sf, sidx = jax.lax.sort(
        (allk, flag, jnp.arange(n, dtype=jnp.int32)), num_keys=2)
    is_build = (sf == 0).astype(jnp.int32)
    before = jnp.cumsum(is_build) - is_build  # builds strictly before pos
    # first position of each equal-key run via a running maximum
    pos = jnp.arange(n, dtype=jnp.int32)
    newrun = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    run_start = jax.lax.cummax(jnp.where(newrun, pos, jnp.int32(-1)))
    # builds sort before probes within a run, so at a probe's position:
    #   lb = builds before its run (key <  probe key)
    #   ub = builds before itself  (key <= probe key)
    lb_at = before[jnp.clip(run_start, 0, n - 1)]
    # co-sort keyed on the permutation carries lb/ub home without the
    # two full-size inverse-perm gathers (see unpermute)
    lb_all, ub_all = unpermute(sidx, lb_at, before)
    lb = lb_all[nb:]
    ub = ub_all[nb:]
    # sentinel keys (masked build rows) must not match masked probe rows
    live = probe_key != key_sentinel(probe_key)
    lb = jnp.where(live, lb, 0)
    ub = jnp.where(live, ub, 0)
    return order, lb, ub


def sort_order_plan(idx: jnp.ndarray, *aligned):
    """Pre-permute a gather's request-aligned operands into ASCENDING
    index order — the sort-order materialization primitive (reference
    role: PagesIndex.getSortedPages).  Returns (sorted_idx,
    [aligned...]) permuted by ONE lax.sort; callers then gather with
    presorted=True and simply leave the batch in sorted order, skipping
    the inverse permutation entirely.  Only valid when every downstream
    consumer is order-insensitive (aggregation, semi-join membership) —
    the executor's order-insensitivity walk decides that."""
    ii = jnp.asarray(idx).astype(jnp.int32)
    ops = [ii]
    bools = []
    for a in aligned:
        a = jnp.asarray(a)
        bools.append(a.dtype == jnp.bool_)
        ops.append(a.astype(jnp.int32) if a.dtype == jnp.bool_ else a)
    out = jax.lax.sort(tuple(ops), num_keys=1)
    rest = [o.astype(jnp.bool_) if b else o
            for o, b in zip(out[1:], bools)]
    return out[0], rest


def batch_word_width(batch: Batch) -> int:
    """u32 words one gathered row of this batch costs (the take_rows
    pack width): sizes the sort-order-materialization side choice."""
    w = 0
    for c in batch.columns.values():
        w += 2 if c.data.dtype.itemsize == 8 else 1
        if c.valid is not None:
            w += 1
    return w


def take_rows(arrays: List[jnp.ndarray], idx: jnp.ndarray,
              presorted: bool = False) -> List[jnp.ndarray]:
    """Gather idx rows from every array, packing columns into one u32
    matrix so ONE gather moves them all.  TPU gathers pay a fixed
    per-index cost (~45ms per 6M f32 rows, measured) that amortizes
    across the row width: gathering a (6M,8) matrix costs ~1/7th of 8
    separate column gathers.  All 4-byte types bitcast to u32; bools
    widen; i64 splits into two u32 words; f64 stays separate (the TPU
    X64 rewriter cannot lower f64 bitcasts).

    Large gathers route through the gather-aware tier (exec/gather.py):
    indices are sorted, rows are staged through VMEM-windowed
    sequential reads (Pallas block-gather), and results ride ONE
    co-sort back to request order.  `presorted=True` asserts idx is
    already nondecreasing (ascending expansions, sort_order_plan
    output): the staging then skips both the sort and the way home."""
    if arrays and arrays[0].shape[0] == 0 and idx.shape[0] > 0:
        # gathering from an EMPTY source (e.g. a zero-row exchange
        # buffer): every index is dead and the caller masks the result —
        # type-correct zeros avoid an out-of-range XLA gather
        return [jnp.zeros((idx.shape[0],) + a.shape[1:], a.dtype)
                for a in arrays]
    words: List[jnp.ndarray] = []    # u32 columns going into the pack
    spec: List = [None] * len(arrays)  # how to rebuild each output
    out: List = [None] * len(arrays)
    for i, a in enumerate(arrays):
        dt = a.dtype
        if a.ndim > 1:
            # matrix-shaped rows (sketch register states): whole-row
            # gather — the u32 pack is strictly rank-1 per word
            spec[i] = ("direct", None)
        elif dt == jnp.bool_:
            spec[i] = ("bool", len(words))
            words.append(a.astype(jnp.uint32))
        elif jnp.issubdtype(dt, jnp.floating) and dt.itemsize == 8:
            spec[i] = ("direct", None)
        elif dt.itemsize == 8:
            spec[i] = ("i64", len(words))
            m = jnp.asarray(0xFFFFFFFF, dt)  # dtype-matched (u64 vs i64)
            words.append((a & m).astype(jnp.uint32))
            words.append(((a >> 32) & m).astype(jnp.uint32))
        elif dt.itemsize == 4:
            spec[i] = ("cast", len(words))
            words.append(jax.lax.bitcast_convert_type(a, jnp.uint32))
        else:
            spec[i] = ("widen", len(words))
            words.append(jax.lax.bitcast_convert_type(
                a.astype(jnp.int32), jnp.uint32))
    n_src = arrays[0].shape[0] if arrays else 0
    route = G.gather_route(n_src, idx.shape[0], len(words), presorted)
    if route == "staged" and all(w.ndim == 1 for w in words) \
            and all(a.ndim == 1 for a in arrays):
        # 2-D words (Int128 limb columns) keep the flat path — the u32
        # matrix pack is rank-1-per-word on both routes
        return _take_rows_staged(arrays, idx, words, spec, presorted)
    # pack from TWO words up: the gather's per-index cost amortizes
    # across row width (measured: two separate 8M 1-col gathers 140ms
    # vs one (8M,2) packed gather 35-50ms on chip), so a single i64
    # column (= 2 u32 words) already wins
    if len(words) >= 2 and idx.shape[0] >= 65536:
        packed = jnp.stack(words, axis=1)[idx]
        col = lambda k: packed[:, k]
    else:
        taken = [w[idx] for w in words]
        col = lambda k: taken[k]
    return _rebuild_taken(arrays, idx, spec, col, out)


def _take_rows_staged(arrays, idx, words, spec, presorted):
    """Sorted-index staging: ascending gather through exec/gather's
    VMEM-windowed kernel, then (for request-order callers) ONE co-sort
    keyed on the saved positions carries every word — and the f64
    side columns — home together.  Payload operands ride a lax.sort
    nearly free; the inverse-permutation GATHER this replaces paid the
    full ~45ns/index random cost a second time."""
    out: List = [None] * len(arrays)
    ii = jnp.asarray(idx).astype(jnp.int32)
    if presorted:
        sidx, spos = ii, None
    else:
        n = ii.shape[0]
        sidx, spos = jax.lax.sort(
            (ii, jnp.arange(n, dtype=jnp.int32)), num_keys=1)
    mat = jnp.stack(words, axis=1)
    rows = G.staged_gather(mat, sidx)
    cols = [rows[:, k] for k in range(len(words))]
    directs = {i: arrays[i][sidx] for i, a in enumerate(arrays)
               if spec[i][0] == "direct"}
    if spos is not None:
        home = unpermute(spos, *(cols + list(directs.values())))
        cols = list(home[:len(cols)])
        directs = dict(zip(directs, home[len(cols):]))
    col = lambda k: cols[k]
    for i, a in enumerate(arrays):
        if spec[i][0] == "direct":
            out[i] = directs[i]
    return _rebuild_taken(arrays, idx, spec, col, out, skip_direct=True)


def _rebuild_taken(arrays, idx, spec, col, out, skip_direct=False):
    for i, a in enumerate(arrays):
        kind, k = spec[i]
        dt = a.dtype
        if kind == "direct":
            if not skip_direct:
                out[i] = a[idx]
        elif kind == "bool":
            out[i] = col(k) != 0
        elif kind == "i64":
            lo = col(k).astype(jnp.int64)
            hi = jax.lax.bitcast_convert_type(col(k + 1),
                                              jnp.int32).astype(jnp.int64)
            out[i] = ((hi << 32) | lo).astype(dt)
        elif kind == "cast":
            out[i] = jax.lax.bitcast_convert_type(col(k), dt)
        else:  # widen
            out[i] = jax.lax.bitcast_convert_type(
                col(k), jnp.int32).astype(dt)
    return out


def take_columns(columns: Dict[str, Column], idx: jnp.ndarray,
                 extra: Optional[List[jnp.ndarray]] = None,
                 presorted: bool = False):
    """Gather idx rows of (data, valid) for every column in one packed
    take_rows pass.  Returns ({name: (data, valid)}, [extra results]).
    `extra` arrays ride the same pack."""
    arrays = list(extra or [])
    n_extra = len(arrays)
    for c in columns.values():
        arrays.append(c.data)
        if c.valid is not None:
            arrays.append(c.valid)
    taken = take_rows(arrays, idx, presorted=presorted)
    out = {}
    i = n_extra
    for name, c in columns.items():
        data = taken[i]
        i += 1
        valid = None
        if c.valid is not None:
            valid = taken[i]
            i += 1
        out[name] = (data, valid)
    return out, taken[:n_extra]


def _take_batch(batch: Batch, safe: jnp.ndarray, presorted: bool = False):
    """Gather rows of all of a batch's arrays (data+valid+sel) at safe
    (pre-clipped) indices with dtype-packed gathers."""
    raw, (sel,) = take_columns(batch.columns, safe, extra=[batch.sel],
                               presorted=presorted)
    cols = {name: (data, valid, batch.columns[name].type,
                   batch.columns[name].dictionary)
            for name, (data, valid) in raw.items()}
    return cols, sel


def gather_batch(batch: Batch, idx: jnp.ndarray, idx_valid=None,
                 presorted: bool = False) -> Batch:
    """Gather rows of all columns at idx (clipped); optionally mask.
    presorted=True asserts idx is nondecreasing (ascending expansions,
    sort_order_plan output) so large gathers stage sequentially without
    paying the way back to request order — idx_valid, if given, must
    already be in the same (sorted) order."""
    n = batch.capacity
    safe = jnp.clip(idx, 0, max(n - 1, 0))
    raw, sel = _take_batch(batch, safe, presorted=presorted)
    cols = {}
    for name, (data, valid, typ, dic) in raw.items():
        if idx_valid is not None:
            valid = idx_valid if valid is None else (valid & idx_valid)
        cols[name] = Column(data, valid, typ, dic)
    if idx_valid is not None:
        sel = sel & idx_valid
    return Batch(cols, sel)


def pack_fetch(batch: Batch, guard) -> Tuple[jnp.ndarray, dict]:
    """Flatten a result batch (+ guard scalar) into ONE uint32 buffer so
    the host pulls a single array: on tunneled TPU backends every array
    in a fetched pytree adds ~4ms and the first costs a ~70ms round trip
    (measured), so a 12-column result fetched column-wise pays ~2x the
    packed fetch.  Returns (buffer, meta); unpack_fetch inverts on host.
    Must be called under trace (jit) — meta is static."""
    n = batch.capacity
    parts = [jnp.asarray(batch.sel).astype(jnp.uint32)]
    side = []  # f64 columns ride as separate pytree leaves (one RPC still)
    cols_meta = []
    for name, c in batch.columns.items():
        d = c.data
        if jnp.issubdtype(d.dtype, jnp.floating) and d.dtype.itemsize == 8:
            # the TPU X64 rewriter cannot lower any f64 bitcast, so f64
            # can't enter the u32 buffer; a separate leaf costs ~4ms on
            # the tunnel vs ~70ms for a separate fetch
            side.append(d)
            w, words = None, 0
        elif d.dtype == jnp.bool_:
            w, words = d.astype(jnp.uint32), 1
        elif d.dtype.itemsize == 8:
            # i64 -> 2x32 via shifts/masks (64->32 bitcast unsupported)
            lo = (d & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = ((d >> 32) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
            w = jnp.stack([lo, hi], axis=1).reshape(-1)
            words = 2
        elif d.dtype.itemsize == 4:
            w, words = jax.lax.bitcast_convert_type(d, jnp.uint32), 1
        else:  # narrow ints: widen (host casts back)
            w = jax.lax.bitcast_convert_type(d.astype(jnp.int32), jnp.uint32)
            words = 1
        if w is not None:
            parts.append(w)
        if c.valid is not None:
            parts.append(c.valid.astype(jnp.uint32))
        cols_meta.append((name, str(d.dtype), words, c.valid is not None,
                          c.type, c.dictionary))
    parts.append(jnp.asarray(guard).astype(jnp.uint32).reshape(1))
    meta = {"n": n, "cols": cols_meta}
    return (jnp.concatenate(parts), side), meta


def unpack_fetch(fetched, meta: dict):
    """Host-side inverse of pack_fetch: returns ({name: (data, valid)},
    sel, guard) as numpy arrays."""
    buf, side = fetched
    n = meta["n"]
    buf = np.asarray(buf)
    side = [np.asarray(a) for a in side]
    si = 0
    sel = buf[:n] != 0
    off = n
    datas = {}
    for name, dtype_s, words, has_valid, _typ, _dic in meta["cols"]:
        dt = np.dtype(dtype_s)
        if words == 0:  # f64 side leaf
            data = side[si]
            si += 1
        else:
            raw = buf[off:off + n * words]
            off += n * words
            if dt == np.bool_:
                data = raw != 0
            elif words == 2:
                lo = raw.reshape(n, 2)[:, 0].astype(np.uint64)
                hi = raw.reshape(n, 2)[:, 1].astype(np.uint64)
                data = (lo | (hi << np.uint64(32))).view(np.int64) \
                    if dt == np.int64 else \
                    (lo | (hi << np.uint64(32))).astype(dt)
            elif dt.itemsize == 4:
                data = raw.view(dt)
            else:
                data = raw.view(np.int32).astype(dt)
        valid = None
        if has_valid:
            valid = buf[off:off + n] != 0
            off += n
        datas[name] = (data, valid)
    guard = bool(buf[off]) if off < len(buf) else False
    return datas, sel, guard


def compact(batch: Batch) -> Batch:
    """Drop masked rows (host-sync on the live count). Used at fragment
    boundaries (exchange points), not inside fragments."""
    n_live = int(jnp.sum(batch.sel))
    idx = nonzero_i32(batch.sel, max(n_live, 1), 0)
    if n_live == 0:
        idx = idx[:0]
    raw, _ = _take_batch(batch, idx)
    cols = {name: Column(data, valid, typ, dic)
            for name, (data, valid, typ, dic) in raw.items()}
    return Batch(cols, jnp.ones((n_live,), bool))


def concat_batches(batches: List[Batch]) -> Batch:
    """Concatenate same-schema batches (dictionary columns are merged)."""
    names = list(batches[0].columns)
    cols: Dict[str, Column] = {}
    for name in names:
        parts = [b.columns[name] for b in batches]
        dicts = [p.dictionary for p in parts]
        with_dict = [d for d in dicts if d is not None]
        if with_dict and (len({id(d) for d in with_dict}) > 1
                          or len(with_dict) < len(parts)):
            # branches without a dictionary are typed-NULL columns
            # (e.g. grouping-set padding): their codes are dead, any
            # in-range value serves
            all_vals = [v for d in with_dict for v in d.values.tolist()]
            if all(isinstance(v, str) for v in all_vals):
                # strings keep the np-sorted invariant (code order ==
                # lexicographic order, which comparisons rely on)
                merged = Dictionary(np.unique(np.concatenate(
                    [d.values for d in with_dict])))
                luts = {id(d): translate_codes(d, merged)
                        for d in with_dict}
            else:
                # tuple dictionaries (ARRAY columns, possibly holding
                # NULL elements): python-map merge, repr-keyed order
                # (array code order is not semantically compared)
                uniq = sorted(set(all_vals), key=repr)
                cmap = {v: i for i, v in enumerate(uniq)}
                u = np.empty(len(uniq), dtype=object)
                u[:] = uniq
                merged = Dictionary(u)
                luts = {id(d): np.asarray(
                    [cmap[v] for v in d.values.tolist()], dtype=np.int32)
                    for d in with_dict}
            datas = []
            for p in parts:
                if p.dictionary is None:
                    datas.append(jnp.zeros_like(jnp.asarray(p.data),
                                                dtype=jnp.int32))
                    continue
                lut = jnp.asarray(luts[id(p.dictionary)])
                datas.append(lut[jnp.clip(p.data, 0, len(p.dictionary) - 1)])
            data = jnp.concatenate(datas)
            dictionary = merged
        else:
            data = jnp.concatenate([p.data for p in parts])
            dictionary = dicts[0]
        if any(p.valid is not None for p in parts):
            valid = jnp.concatenate([
                p.valid if p.valid is not None else jnp.ones(p.data.shape, bool)
                for p in parts])
        else:
            valid = None
        cols[name] = Column(data, valid, parts[0].type, dictionary)
    sel = jnp.concatenate([b.sel for b in batches])
    return Batch(cols, sel)


# ---------------------------------------------------------------------------
# runtime filters (dynamic filtering)
#
# Build-side key summaries probed on the probe side BEFORE the join ever
# sees the rows (reference: DynamicFilterService + LocalDynamicFiltersCollector
# feeding TupleDomains into probe-side page sources).  Two membership
# structures, routed by build capacity:
#   exact  — the sorted build keys themselves + a searchsorted probe
#            (no false positives; masked rows ride as trailing sentinels)
#   bloom  — a blocked bloom bitset over splitmix64-mixed keys (bits set
#            within one 64-bit block per key; false positives possible,
#            false negatives never — the correctness contract)
# Everything is pure jnp so a filter built inside a compiled fragment
# stays inside the trace.  Host (numpy) twins serve the cluster side
# channel and chunk/zone-map pruning; this module is the ONLY home for
# the membership mixing (tests/test_lint.py enforces).
# ---------------------------------------------------------------------------


RF_EXACT_MAX = 1 << 17   # build capacities up to this probe exactly
RF_BLOOM_K = 3           # bits set/tested per key
RF_BLOOM_BITS_PER_KEY = 16  # target bitset density (m/n); FPR ~ 0.5%
RF_WIRE_MAX = 1 << 16    # largest exact key set shipped over the wire


def rf_bloom_bits(n_keys: int) -> int:
    """Bloom bitset size for n keys: ~RF_BLOOM_BITS_PER_KEY bits per
    key, power-of-two (block index = h % nblocks needs no division by a
    traced value), floor 1024.  FPR ~ (1 - e^(-k*n/m))^k ~ 0.5% at
    k=3, m/n=16 — tests/test_dynamic_filters.py pins the measured rate."""
    n = max(int(n_keys), 1)
    return 1 << max(int(np.ceil(np.log2(n * RF_BLOOM_BITS_PER_KEY))), 10)


def _rf_mix64(v: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer over int64 key values (the same mixing
    family as _hash_keys / hll_hash64), uint64 out."""
    z = v.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def _rf_bloom_positions(h: jnp.ndarray, nbits: int):
    """RF_BLOOM_K bit positions per hash, all inside ONE 64-bit block
    (blocked bloom: the probe's k gathers hit one cache line)."""
    nblocks = max(nbits // 64, 1)
    block = (h % jnp.uint64(nblocks)).astype(jnp.int64) * 64
    return [block + ((h >> jnp.uint64(8 + 6 * j)) & jnp.uint64(63))
            .astype(jnp.int64) for j in range(RF_BLOOM_K)]


def rf_build(col: Column, live, structure: str = "auto") -> dict:
    """Build-side runtime-filter summary over the live rows of an
    integer-orderable key column.  Returns an all-jnp dict (trace-safe):
    {"kind": "exact", "keys": sorted i64 with dead rows as I64_MAX
    sentinels} or {"kind": "bloom", "bits": bool[nbits]}."""
    d = _orderable_int(col)
    live = live & _valid_arr(col)
    n = int(d.shape[0])
    kind = structure
    if kind == "auto":
        kind = "exact" if n <= RF_EXACT_MAX else "bloom"
    if kind == "exact":
        return {"kind": "exact",
                "keys": sort_values(jnp.where(live, d, I64_MAX))}
    nbits = rf_bloom_bits(n)
    h = _rf_mix64(d)
    # dead rows scatter into the overflow slot nbits (sliced off)
    idx = jnp.concatenate([jnp.where(live, p, nbits)
                           for p in _rf_bloom_positions(h, nbits)])
    bits = jnp.zeros((nbits + 1,), bool).at[idx].set(True)
    return {"kind": "bloom", "bits": bits[:nbits]}


def rf_probe(summary: dict, col: Column) -> jnp.ndarray:
    """Probe-side membership mask: True = the row MAY have a build match
    (exact/domain: iff; bloom: false positives possible, false negatives
    never).  NULL probe rows map False — an equi-join NULL never
    matches, so pruning them is always sound for INNER/SEMI consumers."""
    d = _orderable_int(col)
    valid = _valid_arr(col)
    kind = summary["kind"]
    if kind == "domain":
        return valid & (d >= summary["lo"]) & (d <= summary["hi"])
    if kind == "exact":
        keys = summary["keys"]
        nb = keys.shape[0]
        if nb == 0:
            return jnp.zeros(d.shape, bool)  # empty build: nothing matches
        pos = jnp.clip(jnp.searchsorted(keys, d), 0, nb - 1)
        # a probe value equal to the dead-row sentinel could only
        # "match" a masked build slot — keep it (false positive, safe)
        return valid & (keys[pos] == d)
    bits = summary["bits"]
    h = _rf_mix64(d)
    m = valid
    for p in _rf_bloom_positions(h, int(bits.shape[0])):
        m = m & bits[p]
    return m


def rf_domain(col: Column, live):
    """(lo, hi) traced min/max of the live key values — the runtime
    TupleDomain half of the filter.  Empty live set -> (I64_MAX,
    I64_MIN), which callers map to an impossible Domain."""
    d = _orderable_int(col)
    live = live & _valid_arr(col)
    if d.shape[0] == 0:
        return jnp.asarray(I64_MAX), jnp.asarray(I64_MIN)
    return (jnp.min(jnp.where(live, d, I64_MAX)),
            jnp.max(jnp.where(live, d, I64_MIN)))


def rf_summary_host(values: np.ndarray, max_exact: int = RF_WIRE_MAX) -> dict:
    """Host-side summary from live build key VALUES (integers): the wire
    form shipped over the cluster side channel and compared against
    shard zone maps / chunk grids.  {"lo", "hi", "vals": sorted-unique
    list, or None when the set is too large to ship exactly}."""
    v = np.asarray(values).astype(np.int64, copy=False)
    if v.size == 0:
        return {"lo": None, "hi": None, "vals": []}  # impossible domain
    uniq = np.unique(v)
    return {"lo": int(uniq[0]), "hi": int(uniq[-1]),
            "vals": [int(x) for x in uniq] if uniq.size <= max_exact
            else None}


def rf_union_host(parts: list) -> Optional[dict]:
    """Union partial host summaries (one per repartition bucket of the
    build side) into one complete summary — every build row lands in
    exactly one bucket, so the union over all buckets IS the build key
    set.  Any part without an exact value list degrades the union to a
    min/max domain; returns None for no parts."""
    if not parts:
        return None
    los = [p["lo"] for p in parts if p.get("lo") is not None]
    his = [p["hi"] for p in parts if p.get("hi") is not None]
    if not los:
        return {"lo": None, "hi": None, "vals": []}
    lo, hi = min(los), max(his)
    if any(p.get("vals") is None for p in parts):
        return {"lo": lo, "hi": hi, "vals": None}
    vals = sorted({v for p in parts for v in p["vals"]})
    if len(vals) > RF_WIRE_MAX:
        return {"lo": lo, "hi": hi, "vals": None}
    return {"lo": lo, "hi": hi, "vals": vals}


def rf_host_to_device(summary: dict) -> Optional[dict]:
    """Lift a wire/host summary into a probe-able device summary."""
    vals = summary.get("vals")
    if vals is not None:
        return {"kind": "exact",
                "keys": jnp.asarray(np.asarray(vals, dtype=np.int64))}
    if summary.get("lo") is None:
        return {"kind": "exact", "keys": jnp.zeros((0,), jnp.int64)}
    return {"kind": "domain", "lo": jnp.int64(summary["lo"]),
            "hi": jnp.int64(summary["hi"])}


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------


def sort_perm(batch: Batch, keys: List[Tuple[Column, bool, Optional[bool]]]):
    """Lexicographic permutation; masked rows last.
    keys: (column, ascending, nulls_first). Default null order matches the
    reference (NULLS LAST for ASC, NULLS FIRST for DESC —
    presto-parser SortItem.NullOrdering defaults)."""
    n = batch.capacity
    # ONE multi-operand lexicographic lax.sort: masked-rows-last is the
    # primary key, then the sort keys in priority order, then a position
    # tiebreak for stability.  Extra sort-key operands are nearly free on
    # TPU, while the per-key argsort+gather chain this replaces paid a
    # full-size gather per key (~43ms per 6M rows each, measured).
    operands = [(~jnp.asarray(batch.sel)).astype(jnp.int32)]
    for col, asc, nulls_first in keys:
        valid = col.valid if col.valid is not None else \
            jnp.ones(col.data.shape[0], bool)  # 1-D even for limb pairs
        nf = (not asc) if nulls_first is None else nulls_first
        # a dedicated null-flag operand per key instead of in-band
        # sentinels: sentinel values can collide with real data at the
        # dtype extremes (int32 MIN under DESC negation), and extra
        # lexicographic operands are nearly free on TPU
        if col.valid is not None:
            operands.append(jnp.where(valid, jnp.int32(0 if not nf else 1),
                                      jnp.int32(1 if not nf else 0)))
        if getattr(col.data, "ndim", 1) == 2:
            # long decimal (Int128 limbs): two lexicographic operands
            # (reference: Int128ArrayBlock comparison is hi-then-lo)
            from presto_tpu.exec import dec128 as D128

            for d in D128.sort_operands(jnp.asarray(col.data)):
                if not asc:
                    # bitwise NOT is an exact order-reversing bijection
                    # on int64 (negation maps both I64_MIN and
                    # I64_MIN+1 to I64_MAX: low-limb ties would
                    # misorder DESC)
                    d = ~d
                operands.append(jnp.where(valid, d, 0))
            continue
        d = _sort_operand_native(col)
        if not asc:
            d = ~d  # order-reversing bijection; negation wraps the min
        operands.append(jnp.where(valid, d, jnp.zeros((), d.dtype)))
    operands.append(jnp.arange(n, dtype=jnp.int32))
    out = jax.lax.sort(tuple(operands), num_keys=len(operands))
    return out[-1]


def _sort_operand_native(col: Column) -> jnp.ndarray:
    """Orderable integer in the NARROWEST dtype that preserves order:
    int32 stays int32 and float32 maps onto int32 with ONE bitcast —
    i64 sort operands run u32-pair emulated on TPU (~1.5x), so keeping
    Q3-class sort keys (f32 revenue, i32 dates) in i32 roughly halves
    the multi-operand sort cost."""
    d = col.data
    if d.dtype == jnp.bool_:
        return d.astype(jnp.int32)
    if d.dtype == jnp.float32 and jax.default_backend() == "tpu":
        b = jax.lax.bitcast_convert_type(d, jnp.int32)
        key = jnp.where(b < 0, (~b) + jnp.int32(-(1 << 31)), b)
        key = jnp.where(d == 0, 0, key)  # +-0 compare equal in SQL
        # NaN sorts largest (Presto order) REGARDLESS of its sign bit —
        # a negative-bit NaN (0xFFC.., preserved verbatim from file
        # data) would otherwise land below -inf
        return jnp.where(jnp.isnan(d), jnp.int32((1 << 31) - 8), key)
    if jnp.issubdtype(d.dtype, jnp.floating):
        return _orderable_int(col)
    if d.dtype in (jnp.int32, jnp.int16, jnp.int8):
        return d.astype(jnp.int32)
    return d.astype(jnp.int64)


def argsort_stable(key: jnp.ndarray) -> jnp.ndarray:
    """Stable argsort (equal keys keep input order) — routed entry point
    for the exchange layer's destination-bucket ordering."""
    return jnp.argsort(key, stable=True)


def lexsort_pair(minor: jnp.ndarray, major: jnp.ndarray) -> jnp.ndarray:
    """Permutation sorting by (major, then minor) — routed entry point
    (jnp.lexsort order convention: last key is primary)."""
    return jnp.lexsort((minor, major))


def sort_values(x: jnp.ndarray) -> jnp.ndarray:
    """Ascending value sort — routed entry point for splitter sampling
    in the range exchange."""
    return jnp.sort(x)


def spill_partition_ids(cols: List[Column], sel, nparts: int,
                        level: int = 0) -> np.ndarray:
    """Partition id per row for spill-tiered execution (exec/spill_exec.py)
    — the same splitmix64 mixing family as rf_* and write_bucket_ids, so a
    bucket-aligned dynamic filter, an engine-written layout, and a spill
    partition agree on which keys co-locate.  `level` salts the mix for
    recursive re-partitioning: rows of one level-N partition share a
    residue of the level-N mix, so an unsalted re-partition could never
    split them — a remix with a different salt decorrelates the levels.
    Host numpy out (the spill fan-out masks host-side); dead rows get an
    arbitrary id (they are dropped by the per-partition sel mask)."""
    key = _hash_keys(cols, sel)
    z = key.astype(jnp.uint64)
    if level:
        z = _rf_mix64(z + jnp.uint64(level))
    p = (z % jnp.uint64(max(int(nparts), 1))).astype(jnp.int32)
    return np.asarray(jax.device_get(p))


# ---------------------------------------------------------------------------
# write-path layout kernels (exec/writer.py): bucket assignment shares
# the splitmix64 mixing with the runtime-filter family above, so a
# bucket-aligned dynamic filter and an engine-written bucket layout
# agree on which keys co-locate; the sort permutation rides the same
# routed sort entry points the executor's accounting sees.
# ---------------------------------------------------------------------------


def write_bucket_ids(values, bucket_count: int) -> np.ndarray:
    """Hash-bucket assignment for a write's bucket column(s): splitmix64
    over each int64 key column, XOR-combined, modulo bucket_count
    (reference: HiveBucketing.getHiveBucket feeding HivePageSink's
    per-bucket writers).  Host numpy in, host numpy out — the writer
    partitions host pages; the mix itself runs through the device kernel
    so there is exactly ONE splitmix implementation, shared with the
    runtime-filter membership family above."""
    cols = values if isinstance(values, (list, tuple)) else [values]
    h = None
    for v in cols:
        m = _rf_mix64(jnp.asarray(
            np.ascontiguousarray(v, dtype=np.int64)))
        h = m if h is None else h ^ m
    b = (h % jnp.uint64(max(int(bucket_count), 1))).astype(jnp.int32)
    return np.asarray(jax.device_get(b))


def write_sort_perm(keys: List[np.ndarray],
                    ascending: Optional[List[bool]] = None) -> np.ndarray:
    """Lexicographic sort permutation for a write page: keys in priority
    order (keys[0] primary), each already an orderable host int/float
    array (string columns enter as sorted-dictionary codes, so code
    order == value order).  Successive stable sorts from minor to major
    key — the classic lexsort construction — with every device sort
    routed through argsort_stable."""
    n = len(keys[0]) if keys else 0
    perm = np.arange(n, dtype=np.int64)
    asc = ascending if ascending is not None else [True] * len(keys)
    for key, up in reversed(list(zip(keys, asc))):
        k = np.ascontiguousarray(np.asarray(key)[perm])
        if not up:
            if k.dtype.kind in ("i", "u"):
                k = ~k  # exact order-reversing bijection on ints
            else:
                k = -k
        o = np.asarray(jax.device_get(argsort_stable(jnp.asarray(k))))
        perm = perm[o]
    return perm


# ---------------------------------------------------------------------------
# Pallas TPU kernels (hot ops the XLA autovectorizer doesn't fuse:
# the multi-aggregate segmented reduction).  CPU test meshes run the
# same kernels under the Pallas interpreter.
# ---------------------------------------------------------------------------


def _pallas_interpret() -> bool:
    return jax.default_backend() != "tpu"


def fused_group_sums(vals: jnp.ndarray, gid: jnp.ndarray,
                     n_groups: int) -> jnp.ndarray:
    """ONE pass computing k segmented sums that share group ids.

    The reference engine pays one hash-table probe per aggregate per row
    (InMemoryHashAggregationBuilder); plain XLA pays one scatter-add
    pass per aggregate column.  This Pallas kernel streams each row
    block through VMEM once, expands gid to a one-hot (VPU compare
    against a lane iota), and accumulates ALL k aggregate columns into a
    VMEM-resident (k, G) table across the sequential TPU grid — the
    aggregation becomes bandwidth-bound on a single read of the data.

    vals: [k, n] float64 (dead rows must already be zeroed)
    gid:  [n] int32 in [0, n_groups)
    returns [k, n_groups] sums (float64).

    Mosaic has no 64-bit types, so the TPU path computes PER-BLOCK f32
    partial sums on the MXU (one [k,B]x[B,G] matmul per block, no
    cross-block carry in f32) and XLA reduces the per-block partials in
    f64 outside the kernel — block-local rounding only, never a long
    f32 accumulation chain.  The CPU interpreter path keeps f64 inside
    the kernel.
    """
    from jax.experimental import pallas as pl

    k, n = vals.shape
    G = max(int(np.ceil(n_groups / 128)) * 128, 128)
    BLOCK = 8192
    npad = int(np.ceil(n / BLOCK)) * BLOCK
    if npad != n:
        vals = jnp.pad(vals, ((0, 0), (0, npad - n)))
        gid = jnp.pad(gid, (0, npad - n))  # padded rows carry zeros: harmless
    steps = npad // BLOCK
    gid2 = gid.reshape(1, -1)

    if _pallas_interpret():
        def kernel(vals_ref, gid_ref, out_ref):
            @pl.when(pl.program_id(0) == 0)
            def _init():
                out_ref[:, :] = jnp.zeros_like(out_ref)

            g = gid_ref[0, :]  # [BLOCK]
            onehot = (g[:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK, G), 1)).astype(vals_ref.dtype)
            out_ref[:, :] += jax.lax.dot_general(
                vals_ref[:, :], onehot, (((1,), (0,)), ((), ())),
                preferred_element_type=vals_ref.dtype)

        out = pl.pallas_call(
            kernel,
            grid=(steps,),
            in_specs=[
                pl.BlockSpec((k, BLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((k, G), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((k, G), vals.dtype),
            interpret=True,
        )(vals, gid2)
        return out[:, :n_groups]

    def kernel32(vals_ref, gid_ref, out_ref):
        g = gid_ref[0, :]
        onehot = (g[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (BLOCK, G), 1)).astype(jnp.float32)
        out_ref[0, :, :] = jax.lax.dot_general(
            vals_ref[:, :], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    vals32 = vals.astype(jnp.float32)
    # the engine runs with x64 on; Mosaic only takes 32-bit types, so the
    # kernel traces in an x64-off scope (operands are f32/i32 already)
    with jax.enable_x64(False):
        partials = pl.pallas_call(
            kernel32,
            grid=(steps,),
            in_specs=[
                pl.BlockSpec((k, BLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((1, k, G), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((steps, k, G), jnp.float32),
        )(vals32, gid2)
    return partials.astype(jnp.float64).sum(axis=0)[:, :n_groups]
