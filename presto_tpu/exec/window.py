"""Window function execution.

Reference parity: operator/WindowOperator.java + the 21 window function
implementations in operator/window/ (RowNumberFunction, RankFunction,
NthValueFunction, LagFunction, ...; framing in WindowPartition.java).
The reference sorts each partition with PagesIndex and walks frames row
by row; here the whole batch is sorted once by (partition, order) keys
and every function is computed as a vectorized prefix/segment scan over
the sorted column — the TPU-friendly formulation (no per-row loop).

Framing: ROWS/RANGE with UNBOUNDED/CURRENT/k-offset bounds.  Sum-like
aggregates use prefix-sum differences over per-row [frame_start,
frame_end] index vectors; min/max use segmented Hillis-Steele scans
(supported when a running scan can answer the frame, which covers the
default frame, whole-partition frames, and suffix frames).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec import kernels as K
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


class WindowError(Exception):
    pass


def execute_window(ex, node: P.Window) -> Batch:
    from presto_tpu.exec.executor import StaticFallback

    if ex.static:
        raise StaticFallback("window functions run in dynamic mode")
    b = ex.exec_node(node.source)
    b = K.compact(b)
    # sort by (partition keys ASC, order keys as specified); stable
    keys = [(b.columns[s], True, None) for s in node.partition_by]
    keys += [(b.columns[s], asc, nf) for s, asc, nf in node.order_by]
    if keys:
        perm = K.sort_perm(b, keys)
        b = K.gather_batch(b, perm)
    n = b.capacity
    cols = dict(b.columns)
    if n == 0:
        for sym, call in node.functions.items():
            dt = np.dtype(object) if call.type.is_string else call.type.numpy_dtype()
            cols[sym] = Column(np.zeros(0, dt), None, call.type, None)
        return Batch(cols, b.sel)

    part_cols = [b.columns[s] for s in node.partition_by]
    order_cols = [b.columns[s] for s, _, _ in node.order_by]
    ctx = _FrameContext(n, part_cols, order_cols, node.order_by and True or False,
                        node.frame)
    for sym, call in node.functions.items():
        cols[sym] = _compute(ctx, b, call)
    return Batch(cols, np.ones(n, dtype=bool))


def _col_host(c: Column) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    d = np.asarray(c.data)
    v = None if c.valid is None else np.asarray(c.valid)
    return d, v


def _adjacent_change(cols: List[Column], n: int) -> np.ndarray:
    """new[i] = row i differs from row i-1 on any column (nulls equal)."""
    new = np.zeros(n, dtype=bool)
    new[0] = True
    for c in cols:
        d, v = _col_host(c)
        diff = d[1:] != d[:-1]
        if v is not None:
            both_null = ~v[1:] & ~v[:-1]
            diff = np.where(both_null, False, diff | (v[1:] != v[:-1]))
        new[1:] |= diff
    return new


class _FrameContext:
    """Per-window-spec row geometry: partition/peer boundaries and frame
    index vectors (reference: WindowPartition frame computation)."""

    def __init__(self, n, part_cols, order_cols, has_order, frame):
        self.n = n
        ar = np.arange(n)
        self.ar = ar
        self.part_new = (_adjacent_change(part_cols, n) if part_cols
                         else _first_only(n))
        # no ORDER BY: every partition row is a peer of every other
        self.peer_new = self.part_new | (
            _adjacent_change(order_cols, n) if order_cols else False)
        self.part_id = np.cumsum(self.part_new) - 1
        self.part_start = np.maximum.accumulate(np.where(self.part_new, ar, 0))
        sizes = np.bincount(self.part_id)
        self.part_size = sizes[self.part_id]
        self.part_end = self.part_start + self.part_size - 1
        self.peer_start = np.maximum.accumulate(np.where(self.peer_new, ar, 0))
        nxt = np.append(self.peer_new[1:], True)
        self.peer_end = np.minimum.accumulate(
            np.where(nxt, ar, n)[::-1])[::-1]
        self.rn = ar - self.part_start + 1
        self.has_order = has_order
        self.frame = frame

    def frame_bounds(self):
        """Per-row [fs, fe] row-index bounds (inclusive); empty if fs>fe."""
        if self.frame is None:
            if self.has_order:
                ftype, start, end = "RANGE", "UNBOUNDED PRECEDING", "CURRENT ROW"
            else:
                ftype, start, end = ("ROWS", "UNBOUNDED PRECEDING",
                                     "UNBOUNDED FOLLOWING")
        else:
            ftype, start, end = self.frame
        fs = self._bound(ftype, start, is_start=True)
        fe = self._bound(ftype, end, is_start=False)
        fs = np.maximum(fs, self.part_start)
        fe = np.minimum(fe, self.part_end)
        return fs, fe

    def _bound(self, ftype, spec, is_start):
        ar = self.ar
        if spec == "UNBOUNDED PRECEDING":
            return self.part_start
        if spec == "UNBOUNDED FOLLOWING":
            return self.part_end
        if spec == "CURRENT ROW":
            if ftype == "ROWS":
                return ar
            return self.peer_start if is_start else self.peer_end
        k_str, direction = spec.split()
        k = int(k_str)
        if ftype != "ROWS":
            raise WindowError("RANGE with offset frame bounds not supported")
        return ar - k if direction == "PRECEDING" else ar + k


def _first_only(n):
    a = np.zeros(n, dtype=bool)
    a[0] = True
    return a


# ---------------------------------------------------------------------------
# function dispatch
# ---------------------------------------------------------------------------

def _compute(ctx: _FrameContext, b: Batch, call: ir.AggCall) -> Column:
    fn = call.fn
    if fn == "row_number":
        return _int_col(ctx.rn, call.type)
    if fn == "rank":
        return _int_col(ctx.peer_start - ctx.part_start + 1, call.type)
    if fn == "dense_rank":
        dr = np.cumsum(ctx.peer_new)
        return _int_col(dr - dr[ctx.part_start] + 1, call.type)
    if fn == "percent_rank":
        rank = ctx.peer_start - ctx.part_start + 1
        denom = np.maximum(ctx.part_size - 1, 1)
        out = np.where(ctx.part_size > 1, (rank - 1) / denom, 0.0)
        return Column(out.astype(np.float64), None, call.type, None)
    if fn == "cume_dist":
        out = (ctx.peer_end - ctx.part_start + 1) / ctx.part_size
        return Column(out.astype(np.float64), None, call.type, None)
    if fn == "ntile":
        k = _lit_int(call.args[0], "ntile bucket count")
        if k < 1:
            raise WindowError("ntile bucket count must be positive")
        return _int_col(_ntile(ctx, k), call.type)
    if fn in ("lag", "lead"):
        return _lag_lead(ctx, b, call)
    if fn in ("first_value", "last_value", "nth_value"):
        return _value_fn(ctx, b, call)
    return _frame_aggregate(ctx, b, call)


def _int_col(a, t):
    return Column(a.astype(np.int64), None, t, None)


def _lit_int(e: ir.RowExpr, what: str) -> int:
    if isinstance(e, ir.Lit):
        return int(e.value)
    raise WindowError(f"{what} must be a literal")


def _ntile(ctx, k):
    rn0 = ctx.rn - 1
    size = ctx.part_size // k
    rem = ctx.part_size % k
    thresh = rem * (size + 1)
    big = np.where(size > 0, rn0 // np.maximum(size + 1, 1), rn0)
    small = rem + np.where(size > 0, (rn0 - thresh) // np.maximum(size, 1), 0)
    return np.where(rn0 < thresh, big, small) + 1


def _arg_column(b: Batch, e: ir.RowExpr) -> Column:
    if isinstance(e, ir.Ref):
        return b.columns[e.name]
    if isinstance(e, ir.Lit):
        n = b.capacity
        if e.type.is_string:
            d = np.full(n, e.value, dtype=object)
        else:
            d = np.full(n, e.value if e.value is not None else 0,
                        dtype=e.type.numpy_dtype())
        v = None if e.value is not None else np.zeros(n, dtype=bool)
        return Column(d, v, e.type, None)
    raise WindowError("window argument must be a column or literal")


def _gather_col(c: Column, idx: np.ndarray, in_frame: np.ndarray) -> Column:
    d, v = _col_host(c)
    safe = np.clip(idx, 0, len(d) - 1)
    out = d[safe]
    valid = in_frame.copy()
    if v is not None:
        valid &= v[safe]
    if c.type.is_string and c.dictionary is None:
        out = np.where(valid, out, "")
    else:
        out = np.where(valid, out, np.zeros_like(out))
    return Column(out, valid if not valid.all() else None, c.type, c.dictionary)


def _lag_lead(ctx, b, call):
    off = _lit_int(call.args[1], "offset") if len(call.args) > 1 else 1
    src = _arg_column(b, call.args[0])
    if call.fn == "lag":
        idx = ctx.ar - off
        in_part = idx >= ctx.part_start
    else:
        idx = ctx.ar + off
        in_part = idx <= ctx.part_end
    out = _gather_col(src, idx, in_part)
    if len(call.args) > 2:  # default value fills out-of-partition slots
        dflt = _arg_column(b, call.args[2])
        dd, dv = _col_host(dflt)
        d, v = _col_host(out)
        use_d = ~in_part
        d = np.where(use_d, dd, d)
        valid = np.where(use_d,
                         dv if dv is not None else np.ones(ctx.n, bool),
                         v if v is not None else np.ones(ctx.n, bool))
        same_dict = (out.dictionary is dflt.dictionary)
        if out.type.is_string and not same_dict:
            raise WindowError("lag/lead string default requires matching encoding")
        out = Column(d, None if valid.all() else valid, out.type, out.dictionary)
    return out


def _value_fn(ctx, b, call):
    src = _arg_column(b, call.args[0])
    fs, fe = ctx.frame_bounds()
    nonempty = fs <= fe
    if call.fn == "first_value":
        idx = fs
    elif call.fn == "last_value":
        idx = fe
    else:
        k = _lit_int(call.args[1], "nth_value offset")
        if k < 1:
            raise WindowError("nth_value offset must be positive")
        idx = fs + k - 1
        nonempty = nonempty & (idx <= fe)
    return _gather_col(src, idx, nonempty)


# ---------------------------------------------------------------------------
# aggregates over frames
# ---------------------------------------------------------------------------

def _prefix_at(csum: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Sum of x[0..idx] using inclusive prefix csum; idx may be -1."""
    return np.where(idx >= 0, csum[np.clip(idx, 0, len(csum) - 1)], 0)


def _frame_aggregate(ctx, b, call):
    fn = call.fn
    fs, fe = ctx.frame_bounds()
    nonempty = fs <= fe
    if fn == "count" and not call.args:
        cnt = np.where(nonempty, fe - fs + 1, 0)
        return _int_col(cnt, call.type)

    src = _arg_column(b, call.args[0]) if call.args else None
    d, v = _col_host(src)
    notnull = v if v is not None else np.ones(ctx.n, dtype=bool)
    cs = np.cumsum(notnull.astype(np.int64))
    cnt = _prefix_at(cs, fe) - _prefix_at(cs, fs - 1)
    cnt = np.where(nonempty, cnt, 0)
    if fn == "count":
        return _int_col(cnt, call.type)

    if fn in ("sum", "avg", "stddev", "stddev_samp", "stddev_pop",
              "variance", "var_samp", "var_pop"):
        if src.type.is_string:
            raise WindowError(f"{fn} over strings")
        x = np.where(notnull, d, 0).astype(np.float64)
        s = np.cumsum(x)
        tot = _prefix_at(s, fe) - _prefix_at(s, fs - 1)
        valid = nonempty & (cnt > 0)
        if fn == "sum":
            if call.type.is_integer or call.type.name == "DECIMAL":
                si = np.cumsum(np.where(notnull, d, 0).astype(np.int64))
                tot = _prefix_at(si, fe) - _prefix_at(si, fs - 1)
            return Column(tot, None if valid.all() else valid, call.type, None)
        mean = tot / np.maximum(cnt, 1)
        if fn == "avg":
            return Column(mean, None if valid.all() else valid, call.type, None)
        s2 = np.cumsum(x * x)
        tot2 = _prefix_at(s2, fe) - _prefix_at(s2, fs - 1)
        m2 = tot2 - tot * tot / np.maximum(cnt, 1)
        if fn in ("stddev", "stddev_samp", "variance", "var_samp"):
            denom = np.maximum(cnt - 1, 1)
            valid = valid & (cnt > 1)
        else:
            denom = np.maximum(cnt, 1)
        var = np.maximum(m2 / denom, 0.0)
        out = np.sqrt(var) if fn.startswith("stddev") else var
        return Column(out, None if valid.all() else valid, call.type, None)

    if fn in ("min", "max"):
        return _minmax(ctx, src, d, notnull, fs, fe, nonempty & (cnt > 0), call)
    raise WindowError(f"window aggregate {fn} not supported")


def _segmented_scan(vals, seg_new, op, identity):
    """Hillis-Steele segmented inclusive scan — log2(n) vectorized passes."""
    n = len(vals)
    res = vals.copy()
    flag = seg_new.copy()
    shift = 1
    while shift < n:
        prev = np.concatenate([np.full(shift, identity, dtype=res.dtype),
                               res[:-shift]])
        prev_flag = np.concatenate([np.ones(shift, dtype=bool), flag[:-shift]])
        res = np.where(flag, res, op(res, prev))
        flag = flag | prev_flag
        shift <<= 1
    return res


def _minmax(ctx, src, d, notnull, fs, fe, valid, call):
    op = np.minimum if call.fn == "min" else np.maximum
    if src.type.is_string and src.dictionary is None:
        # order on raw strings: factorize to ranks, min/max over ranks
        uniq, codes = np.unique(d.astype(str), return_inverse=True)
        work = codes.astype(np.int64)
        decode = lambda r: uniq[np.clip(r, 0, len(uniq) - 1)]
        ident = np.iinfo(np.int64).max if call.fn == "min" else np.iinfo(np.int64).min
    elif src.dictionary is not None:
        # dictionary codes are sorted-unique in encode_strings -> order-preserving
        work = np.asarray(d, dtype=np.int64)
        decode = lambda r: r  # keep codes; dictionary travels with the column
        ident = np.iinfo(np.int64).max if call.fn == "min" else np.iinfo(np.int64).min
    else:
        work = d.astype(np.float64) if d.dtype.kind == "f" else d.astype(np.int64)
        if d.dtype.kind == "f":
            ident = np.inf if call.fn == "min" else -np.inf
        else:
            ident = np.iinfo(np.int64).max if call.fn == "min" else np.iinfo(np.int64).min
        decode = lambda r: r
    work = np.where(notnull, work, ident)

    ar = ctx.ar
    run_fwd = _segmented_scan(work, ctx.part_new, op, ident)
    run_bwd = _segmented_scan(work[::-1], np.append(ctx.part_new[1:], True)[::-1],
                              op, ident)[::-1]
    # answerable cases: fs == part_start (prefix scan at fe), or
    # fe == part_end (suffix scan at fs), or single-row frames
    if np.array_equal(fs, ctx.part_start):
        raw = run_fwd[np.clip(fe, 0, ctx.n - 1)]
    elif np.array_equal(fe, ctx.part_end):
        raw = run_bwd[np.clip(fs, 0, ctx.n - 1)]
    elif np.array_equal(fs, fe):
        raw = work[np.clip(fs, 0, ctx.n - 1)]
    else:
        raw = _minmax_sliding(work, fs, fe, op, ident)
    # validity = frame contains a non-null value (passed in as `valid`);
    # a sentinel comparison would misreport legitimate extreme values
    out = decode(raw)
    if src.type.is_string and src.dictionary is None:
        out = np.where(valid, out, "")
        out = out.astype(object)
    else:
        out = np.where(valid, out, np.zeros_like(out))
    return Column(out, None if valid.all() else valid, call.type,
                  src.dictionary if src.dictionary is not None else None)


def _minmax_sliding(work, fs, fe, op, ident):
    """Bounded ROWS frames: sparse-table (doubling) range min/max —
    O(n log n) precompute, O(1) per row."""
    n = len(work)
    width = fe - fs + 1
    max_w = int(np.max(np.maximum(width, 1)))
    levels = [work]
    span = 1
    while span < max_w:
        cur = levels[-1]
        nxt = op(cur, np.concatenate([cur[span:], np.full(span, ident, cur.dtype)]))
        levels.append(nxt)
        span <<= 1
    k = np.maximum(width, 1)
    lev = np.floor(np.log2(k)).astype(np.int64)
    span_arr = (1 << lev)
    out = np.full(n, ident, dtype=work.dtype)
    for li, table in enumerate(levels):
        m = lev == li
        if not m.any():
            continue
        a = table[np.clip(fs[m], 0, n - 1)]
        second = np.clip(fe[m] - span_arr[m] + 1, 0, n - 1)
        out[m] = op(a, table[second])
    return out
