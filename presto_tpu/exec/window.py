"""Window function execution — fully on-device (jnp), jit-compatible.

Reference parity: operator/WindowOperator.java + the 21 window function
implementations in operator/window/ (RowNumberFunction, RankFunction,
NthValueFunction, LagFunction, ...; framing in WindowPartition.java).
The reference sorts each partition with PagesIndex and walks frames row
by row; here the whole batch is sorted once by (partition, order) keys
and every function is computed as a vectorized prefix/segment scan over
the sorted columns — the TPU-friendly formulation (no per-row loop,
no host round trips), so windowed queries compile into the same XLA
program as the rest of the fragment and distribute by hash-partitioning
on the partition keys (sql/planner/optimizations/AddExchanges.java
inserts the same partitioned exchange for WindowNode).

Framing: ROWS/RANGE with UNBOUNDED/CURRENT/k-offset bounds.  Frame
SHAPE is decided at plan time (the spec is static), so the
prefix-vs-suffix-vs-sliding strategy never branches on data.  Sum-like
aggregates use prefix-sum differences over per-row [frame_start,
frame_end] index vectors; min/max use segmented Hillis-Steele scans or
a sparse-table (doubling) range query for bounded ROWS frames.

Masked (sel=False) rows sort last and form their own partition runs via
a leading liveness sort/partition key, so static mode needs no
compaction: dead rows produce garbage outputs that stay masked.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec import kernels as K
from presto_tpu.plan import ir
from presto_tpu.plan import nodes as P


class WindowError(Exception):
    pass


def execute_window(ex, node: P.Window) -> Batch:
    b = ex.exec_node(node.source)
    if not ex.static:
        b = K.compact(b)
    n = b.capacity
    live_col = Column(jnp.asarray(b.sel), None, T.BOOLEAN)
    # sort by (liveness, partition keys ASC, order keys as specified);
    # sort_perm already puts masked rows last, and the liveness flag as a
    # partition key fences them into their own (garbage, masked) runs
    keys = [(b.columns[s], True, None) for s in node.partition_by]
    keys += [(b.columns[s], asc, nf) for s, asc, nf in node.order_by]
    if keys or (ex.static and n):
        # static mode must sort even for OVER (): interleaved masked
        # rows would otherwise split the single partition into
        # per-liveness runs (sort_perm orders masked rows last)
        perm = K.sort_perm(b, keys)
        b = K.gather_batch(b, perm)
        live_col = Column(jnp.asarray(b.sel), None, T.BOOLEAN)
    cols = dict(b.columns)
    if n == 0:
        for sym, call in node.functions.items():
            dt = (np.dtype(np.int32) if call.type.is_string
                  else call.type.numpy_dtype())
            cols[sym] = Column(jnp.zeros(0, dt), None, call.type, None)
        return Batch(cols, b.sel)

    part_cols = [live_col] + [b.columns[s] for s in node.partition_by]
    order_cols = [b.columns[s] for s, _, _ in node.order_by]
    ctx = _FrameContext(n, part_cols, order_cols, bool(node.order_by),
                        node.frame)
    for sym, call in node.functions.items():
        cols[sym] = _compute(ctx, b, call)
    return Batch(cols, b.sel)


def _adjacent_change(cols: List[Column], n: int) -> jnp.ndarray:
    """new[i] = row i differs from row i-1 on any column (nulls equal)."""
    new = jnp.zeros(n, dtype=bool).at[0].set(True)
    for c in cols:
        d = jnp.asarray(c.data)
        diff = d[1:] != d[:-1]
        v = c.valid
        if v is not None:
            both_null = ~v[1:] & ~v[:-1]
            diff = jnp.where(both_null, False, diff | (v[1:] != v[:-1]))
        new = new.at[1:].set(new[1:] | diff)
    return new


class _FrameContext:
    """Per-window-spec row geometry: partition/peer boundaries and frame
    index vectors (reference: WindowPartition frame computation)."""

    def __init__(self, n, part_cols, order_cols, has_order, frame):
        self.n = n
        ar = jnp.arange(n)
        self.ar = ar
        self.part_new = _adjacent_change(part_cols, n)
        # no ORDER BY: every partition row is a peer of every other
        if order_cols:
            self.peer_new = self.part_new | _adjacent_change(order_cols, n)
        else:
            self.peer_new = self.part_new
        self.part_id = jnp.cumsum(self.part_new.astype(jnp.int32)) - 1
        self.part_start = jax.lax.cummax(
            jnp.where(self.part_new, ar, 0))
        nxt_part = jnp.concatenate(
            [self.part_new[1:], jnp.ones(1, bool)])
        self.part_end = jnp.flip(jax.lax.cummin(
            jnp.flip(jnp.where(nxt_part, ar, n))))
        self.part_size = self.part_end - self.part_start + 1
        self.peer_start = jax.lax.cummax(
            jnp.where(self.peer_new, ar, 0))
        nxt = jnp.concatenate([self.peer_new[1:], jnp.ones(1, bool)])
        self.peer_end = jnp.flip(jax.lax.cummin(
            jnp.flip(jnp.where(nxt, ar, n))))
        self.rn = ar - self.part_start + 1
        self.has_order = has_order
        self.frame = frame

    def frame_bounds(self):
        """(fs, fe, shape) — per-row inclusive bounds plus the STATIC
        frame shape tag: 'prefix' (fs==part_start), 'suffix'
        (fe==part_end), 'whole', 'single', 'sliding:<maxw>'."""
        if self.frame is None:
            if self.has_order:
                ftype, start, end = ("RANGE", "UNBOUNDED PRECEDING",
                                     "CURRENT ROW")
            else:
                ftype, start, end = ("ROWS", "UNBOUNDED PRECEDING",
                                     "UNBOUNDED FOLLOWING")
        else:
            ftype, start, end = self.frame
        fs, s_off = self._bound(ftype, start, is_start=True)
        fe, e_off = self._bound(ftype, end, is_start=False)
        fs = jnp.maximum(fs, self.part_start)
        fe = jnp.minimum(fe, self.part_end)
        if start == "UNBOUNDED PRECEDING" and end == "UNBOUNDED FOLLOWING":
            shape = "whole"
        elif start == "UNBOUNDED PRECEDING":
            shape = "prefix"
        elif end == "UNBOUNDED FOLLOWING":
            shape = "suffix"
        elif ftype == "ROWS" and start == end == "CURRENT ROW":
            shape = "single"
        elif ftype == "RANGE" and start == end == "CURRENT ROW":
            shape = "peer"  # the whole peer group (width is data-dependent)
        else:
            maxw = (s_off or 0) + (e_off or 0) + 1
            shape = f"sliding:{maxw}"
        return fs, fe, shape

    def _bound(self, ftype, spec, is_start):
        """Returns (index vector, static offset magnitude or None)."""
        ar = self.ar
        if spec == "UNBOUNDED PRECEDING":
            return self.part_start, None
        if spec == "UNBOUNDED FOLLOWING":
            return self.part_end, None
        if spec == "CURRENT ROW":
            if ftype == "ROWS":
                return ar, 0
            return (self.peer_start, None) if is_start \
                else (self.peer_end, None)
        k_str, direction = spec.split()
        k = int(k_str)
        if ftype != "ROWS":
            raise WindowError("RANGE with offset frame bounds not supported")
        return (ar - k if direction == "PRECEDING" else ar + k), k


# ---------------------------------------------------------------------------
# function dispatch
# ---------------------------------------------------------------------------

def _compute(ctx: _FrameContext, b: Batch, call: ir.AggCall) -> Column:
    fn = call.fn
    if fn == "row_number":
        return _int_col(ctx.rn, call.type)
    if fn == "rank":
        return _int_col(ctx.peer_start - ctx.part_start + 1, call.type)
    if fn == "dense_rank":
        dr = jnp.cumsum(ctx.peer_new.astype(jnp.int64))
        return _int_col(dr - dr[ctx.part_start] + 1, call.type)
    if fn == "percent_rank":
        rank = ctx.peer_start - ctx.part_start + 1
        denom = jnp.maximum(ctx.part_size - 1, 1)
        out = jnp.where(ctx.part_size > 1, (rank - 1) / denom, 0.0)
        return Column(out.astype(jnp.float64), None, call.type, None)
    if fn == "cume_dist":
        out = (ctx.peer_end - ctx.part_start + 1) / ctx.part_size
        return Column(out.astype(jnp.float64), None, call.type, None)
    if fn == "ntile":
        k = _lit_int(call.args[0], "ntile bucket count")
        if k < 1:
            raise WindowError("ntile bucket count must be positive")
        return _int_col(_ntile(ctx, k), call.type)
    if fn in ("lag", "lead"):
        return _lag_lead(ctx, b, call)
    if fn in ("first_value", "last_value", "nth_value"):
        return _value_fn(ctx, b, call)
    return _frame_aggregate(ctx, b, call)


def _int_col(a, t):
    return Column(a.astype(jnp.int64), None, t, None)


def _lit_int(e: ir.RowExpr, what: str) -> int:
    if isinstance(e, ir.Lit):
        return int(e.value)
    raise WindowError(f"{what} must be a literal")


def _ntile(ctx, k):
    rn0 = ctx.rn - 1
    size = ctx.part_size // k
    rem = ctx.part_size % k
    thresh = rem * (size + 1)
    big = jnp.where(size > 0, rn0 // jnp.maximum(size + 1, 1), rn0)
    small = rem + jnp.where(size > 0,
                            (rn0 - thresh) // jnp.maximum(size, 1), 0)
    return jnp.where(rn0 < thresh, big, small) + 1


def _arg_column(b: Batch, e: ir.RowExpr) -> Column:
    if isinstance(e, ir.Ref):
        return b.columns[e.name]
    if isinstance(e, ir.Lit):
        n = b.capacity
        if e.type.is_string:
            raise WindowError("string literal window argument")
        d = jnp.full(n, e.value if e.value is not None else 0,
                     dtype=e.type.numpy_dtype())
        v = None if e.value is not None else jnp.zeros(n, dtype=bool)
        return Column(d, v, e.type, None)
    raise WindowError("window argument must be a column or literal")


def _gather_col(c: Column, idx, in_frame) -> Column:
    d = jnp.asarray(c.data)
    safe = jnp.clip(idx, 0, d.shape[0] - 1)
    out = d[safe]
    valid = in_frame
    if c.valid is not None:
        valid = valid & c.valid[safe]
    if c.type.is_string and c.dictionary is None:
        raise WindowError("non-dictionary string window values")
    out = jnp.where(valid, out, jnp.zeros((), out.dtype))
    return Column(out, valid, c.type, c.dictionary)


def _nn_machinery(ctx, src):
    """(inclusive nn-count, exclusive nn-count) over the window-sorted
    rows — the vectorized basis for IGNORE NULLS: the m-th non-null's
    index is searchsorted(cnt, m) (reference: the value functions'
    nullTreatment in operator/window/)."""
    valid = src.valid if src.valid is not None \
        else jnp.ones(ctx.n, dtype=bool)
    cnt = jnp.cumsum(valid.astype(jnp.int32))
    return cnt, cnt - valid.astype(jnp.int32), valid


def _lag_lead(ctx, b, call):
    off = _lit_int(call.args[1], "offset") if len(call.args) > 1 else 1
    src = _arg_column(b, call.args[0])
    if getattr(call, "ignore_nulls", False):
        cnt, cnt0, _valid = _nn_machinery(ctx, src)
        if call.fn == "lag":
            # the off-th non-null strictly before this row
            m = cnt0 - off + 1
            in_part = m >= cnt0[ctx.part_start] + 1
        else:
            # the off-th non-null strictly after this row
            m = cnt + off
            in_part = m <= cnt[ctx.part_end]
        m = jnp.maximum(m, 1)
        idx = jnp.searchsorted(cnt, m).astype(jnp.int32)
    elif call.fn == "lag":
        idx = ctx.ar - off
        in_part = idx >= ctx.part_start
    else:
        idx = ctx.ar + off
        in_part = idx <= ctx.part_end
    out = _gather_col(src, idx, in_part)
    if len(call.args) > 2:  # default value fills out-of-partition slots
        dflt = _arg_column(b, call.args[2])
        same_dict = out.dictionary is dflt.dictionary
        if out.type.is_string and not same_dict:
            raise WindowError(
                "lag/lead string default requires matching encoding")
        use_d = ~in_part
        d = jnp.where(use_d, dflt.data, out.data)
        ones = jnp.ones(ctx.n, bool)
        valid = jnp.where(
            use_d,
            dflt.valid if dflt.valid is not None else ones,
            out.valid if out.valid is not None else ones)
        out = Column(d, valid, out.type, out.dictionary)
    return out


def _value_fn(ctx, b, call):
    src = _arg_column(b, call.args[0])
    fs, fe, _shape = ctx.frame_bounds()
    nonempty = fs <= fe
    if getattr(call, "ignore_nulls", False):
        cnt, cnt0, _valid = _nn_machinery(ctx, src)
        if call.fn == "first_value":
            m = cnt0[fs] + 1  # first non-null at/after frame start
        elif call.fn == "last_value":
            m = cnt[fe]  # last non-null at/before frame end
        else:
            k = _lit_int(call.args[1], "nth_value offset")
            if k < 1:
                raise WindowError("nth_value offset must be positive")
            m = cnt0[fs] + k
        nonempty = nonempty & (m >= cnt0[fs] + 1) & (m <= cnt[fe])
        idx = jnp.searchsorted(cnt, jnp.maximum(m, 1)).astype(jnp.int32)
        return _gather_col(src, idx, nonempty)
    if call.fn == "first_value":
        idx = fs
    elif call.fn == "last_value":
        idx = fe
    else:
        k = _lit_int(call.args[1], "nth_value offset")
        if k < 1:
            raise WindowError("nth_value offset must be positive")
        idx = fs + k - 1
        nonempty = nonempty & (idx <= fe)
    return _gather_col(src, idx, nonempty)


# ---------------------------------------------------------------------------
# aggregates over frames
# ---------------------------------------------------------------------------

def _prefix_at(csum, idx):
    """Sum of x[0..idx] using inclusive prefix csum; idx may be -1."""
    return jnp.where(idx >= 0,
                     csum[jnp.clip(idx, 0, csum.shape[0] - 1)], 0)


def _frame_aggregate(ctx, b, call):
    fn = call.fn
    fs, fe, shape = ctx.frame_bounds()
    nonempty = fs <= fe
    if fn == "count" and not call.args:
        cnt = jnp.where(nonempty, fe - fs + 1, 0)
        return _int_col(cnt, call.type)

    src = _arg_column(b, call.args[0]) if call.args else None
    d = jnp.asarray(src.data)
    notnull = src.valid if src.valid is not None \
        else jnp.ones(ctx.n, dtype=bool)
    cs = jnp.cumsum(notnull.astype(jnp.int64))
    cnt = _prefix_at(cs, fe) - _prefix_at(cs, fs - 1)
    cnt = jnp.where(nonempty, cnt, 0)
    if fn == "count":
        return _int_col(cnt, call.type)

    if fn in ("sum", "avg", "stddev", "stddev_samp", "stddev_pop",
              "variance", "var_samp", "var_pop"):
        if src.type.is_string:
            raise WindowError(f"{fn} over strings")
        acc = jnp.float32 if d.dtype == jnp.float32 else jnp.float64
        x = jnp.where(notnull, d, jnp.zeros((), d.dtype)).astype(acc)
        s = jnp.cumsum(x)
        tot = _prefix_at(s, fe) - _prefix_at(s, fs - 1)
        valid = nonempty & (cnt > 0)
        if fn == "sum":
            if call.type.is_integer or call.type.name == "DECIMAL":
                si = jnp.cumsum(jnp.where(
                    notnull, d, jnp.zeros((), d.dtype)).astype(jnp.int64))
                tot = _prefix_at(si, fe) - _prefix_at(si, fs - 1)
            return Column(tot, valid, call.type, None)
        mean = tot / jnp.maximum(cnt, 1)
        if fn == "avg":
            return Column(mean.astype(jnp.float64), valid, call.type, None)
        s2 = jnp.cumsum(x * x)
        tot2 = _prefix_at(s2, fe) - _prefix_at(s2, fs - 1)
        m2 = tot2 - tot * tot / jnp.maximum(cnt, 1)
        if fn in ("stddev", "stddev_samp", "variance", "var_samp"):
            denom = jnp.maximum(cnt - 1, 1)
            valid = valid & (cnt > 1)
        else:
            denom = jnp.maximum(cnt, 1)
        var = jnp.maximum(m2 / denom, 0.0)
        out = jnp.sqrt(var) if fn.startswith("stddev") else var
        return Column(out.astype(jnp.float64), valid, call.type, None)

    if fn in ("min", "max"):
        return _minmax(ctx, src, d, notnull, fs, fe, shape,
                       nonempty & (cnt > 0), call)
    raise WindowError(f"window aggregate {fn} not supported")


def _segmented_scan(vals, seg_new, op, identity):
    """Hillis-Steele segmented inclusive scan — log2(n) vectorized passes."""
    n = vals.shape[0]
    res = vals
    flag = seg_new
    shift = 1
    while shift < n:
        prev = jnp.concatenate([
            jnp.full(shift, identity, dtype=res.dtype), res[:-shift]])
        prev_flag = jnp.concatenate([
            jnp.ones(shift, dtype=bool), flag[:-shift]])
        res = jnp.where(flag, res, op(res, prev))
        flag = flag | prev_flag
        shift <<= 1
    return res


def _minmax(ctx, src, d, notnull, fs, fe, shape, valid, call):
    op = jnp.minimum if call.fn == "min" else jnp.maximum
    if src.type.is_string and src.dictionary is None:
        raise WindowError("min/max over non-dictionary strings")
    if src.dictionary is not None:
        # dictionary codes are sorted-unique -> order-preserving
        work = d.astype(jnp.int64)
        ident = (np.iinfo(np.int64).max if call.fn == "min"
                 else np.iinfo(np.int64).min)
    elif jnp.issubdtype(d.dtype, jnp.floating):
        work = d.astype(jnp.float64)
        ident = np.inf if call.fn == "min" else -np.inf
    else:
        work = d.astype(jnp.int64)
        ident = (np.iinfo(np.int64).max if call.fn == "min"
                 else np.iinfo(np.int64).min)
    work = jnp.where(notnull, work, ident)

    n = ctx.n
    # the frame SHAPE is static (from the spec), so strategy selection
    # never branches on data
    if shape == "prefix" or shape == "whole":
        run_fwd = _segmented_scan(work, ctx.part_new, op, ident)
        raw = run_fwd[jnp.clip(fe, 0, n - 1)]
    elif shape == "peer":
        # frame == the peer group: forward scan over PEER segments,
        # evaluated at each row's peer_end (== fe)
        run_fwd = _segmented_scan(work, ctx.peer_new, op, ident)
        raw = run_fwd[jnp.clip(fe, 0, n - 1)]
    elif shape == "suffix":
        nxt = jnp.concatenate([ctx.part_new[1:], jnp.ones(1, bool)])
        run_bwd = jnp.flip(_segmented_scan(
            jnp.flip(work), jnp.flip(nxt), op, ident))
        raw = run_bwd[jnp.clip(fs, 0, n - 1)]
    elif shape == "single":
        raw = work[jnp.clip(fs, 0, n - 1)]
    else:  # sliding:<maxw>
        maxw = int(shape.split(":")[1])
        raw = _minmax_sliding(work, fs, fe, op, ident, maxw)
    out = jnp.where(valid, raw, jnp.zeros((), raw.dtype))
    if src.dictionary is not None:
        out = out.astype(d.dtype)
    return Column(out, valid, call.type,
                  src.dictionary if src.dictionary is not None else None)


def _minmax_sliding(work, fs, fe, op, ident, max_w):
    """Bounded ROWS frames: sparse-table (doubling) range min/max —
    O(n log n) precompute, O(1) per row.  max_w is static (from the
    frame spec's offsets)."""
    n = work.shape[0]
    width = fe - fs + 1
    levels = [work]
    span = 1
    while span < max(max_w, 1):
        cur = levels[-1]
        nxt = op(cur, jnp.concatenate(
            [cur[span:], jnp.full(span, ident, cur.dtype)]))
        levels.append(nxt)
        span <<= 1
    k = jnp.maximum(width, 1)
    lev = jnp.floor(jnp.log2(k.astype(jnp.float64))).astype(jnp.int64)
    span_arr = 1 << lev
    out = jnp.full(n, ident, dtype=work.dtype)
    for li, table in enumerate(levels):
        m = lev == li
        a = table[jnp.clip(fs, 0, n - 1)]
        second = jnp.clip(fe - span_arr + 1, 0, n - 1)
        cand = op(a, table[second])
        out = jnp.where(m, cand, out)
    return out
