"""Catalog & connector interfaces.

Reference parity: presto-spi/.../spi/connector/Connector.java:27
(getMetadata / getSplitManager / getPageSourceProvider) and
metadata/MetadataManager.  Trimmed to the TPU engine's needs: a connector
exposes table schemas and serves host-columnar data per split; ingestion to
device batches happens in the scan operator.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors import tpch as tpch_gen


class ConnectorTable:
    """Metadata + data access for one table."""

    def __init__(self, name: str, schema: Dict[str, T.Type]):
        self.name = name
        self.schema = dict(schema)

    def row_count(self) -> int:
        raise NotImplementedError

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        raise NotImplementedError

    def read(self, columns: Optional[List[str]] = None,
             split: Optional[Tuple[int, int]] = None) -> Dict[str, np.ndarray]:
        """Host columnar data for the given columns (projection pushdown)."""
        raise NotImplementedError

    # ---- statistics SPI (reference: ConnectorMetadata.getTableStatistics
    # feeding cost/StatsCalculator; here also the source of STATIC shapes
    # for the compiled execution mode — see plan/stats.py) ----
    def column_stats(self, column: str):
        return None

    def unique_keys(self) -> List[tuple]:
        return []

    def key_layout(self, column: str):
        """Invertible layout of a unique integer key column, or None.
        Returns (base, block_keys, block_rows): row i holds key
        base + (i // block_rows) * block_keys + (i % block_rows).
        Dense surrogate keys are (min, 1, 1); dbgen's sparse orderkey
        (8 keys per 32-key block) is (1, 32, 8).  Index joins use this
        to turn the probe into one gather (P10), with an in-trace
        layout verification guarding staleness."""
        return None

    def max_rows_per_key(self) -> Dict[tuple, int]:
        return {}

    def ordering(self) -> List[Tuple[str, bool]]:
        """Declared physical row ordering: [(column, ascending), ...] —
        rows are emitted lexicographically nondecreasing on this column
        prefix (reference: ConnectorMetadata table layout
        LocalProperties).  A CLAIM consumed behind runtime monotonicity
        guards (plan/properties.py), so a wrong declaration costs the
        elided sort back, never correctness.  Empty = unordered."""
        return []

    # ---- write-layout SPI (exec/writer.py): the physical properties a
    # write DECLARED (bucketed_by/bucket_count/sorted_by/partitioned_by)
    # and — when the written file sequence verified as globally ordered
    # — the ordering() claim derived from them.  SHOW CREATE TABLE and
    # DESCRIBE surface these so a round-trip reproduces the layout. ----
    def write_properties(self) -> Optional[dict]:
        return None

    # ---- bucketing SPI (reference: Connector.getNodePartitioningProvider,
    # presto-spi/.../spi/connector/Connector.java:74 + BucketNodeMap;
    # here the metadata that lets grouped/chunked execution stream this
    # table bucket-by-bucket, exec/chunked.py) ----
    def bucketing(self):
        """ChunkFamily this table belongs to, or None if it cannot
        stream chunk-wise."""
        return None

    def _invalidate(self) -> None:
        """Drop cached device columns + bump the catalog version after a
        write (compiled-plan caches key on catalog version)."""
        _drop_device_cache(self)
        cat = getattr(self, "_catalog", None)
        if cat is not None:
            cat.version += 1


class MemoryTable(ConnectorTable):
    """In-memory table (reference: presto-memory connector)."""

    def __init__(self, name, schema, data: Dict[str, np.ndarray]):
        super().__init__(name, schema)
        # np.asarray would silently STRIP a null mask
        self.data = {k: (v if isinstance(v, np.ma.MaskedArray)
                         else np.asarray(v)) for k, v in data.items()}
        self._rows = len(next(iter(self.data.values()))) if self.data else 0

    def column_stats(self, column: str):
        from presto_tpu.plan.stats import ColStats

        a = self.data.get(column)
        if a is None or len(a) == 0:
            return ColStats(ndv=0)
        if a.dtype == object:  # strings: ndv only
            return ColStats(ndv=len(set(a.tolist())))
        return ColStats(min=float(np.min(a)), max=float(np.max(a)),
                        ndv=int(len(np.unique(a))))

    def row_count(self) -> int:
        return self._rows

    def splits(self, n_splits):
        edges = np.linspace(0, self._rows, n_splits + 1).astype(int)
        return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:]) if a < b]

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        a, b = split if split is not None else (0, self._rows)
        return {c: self.data[c][a:b] for c in cols}

    # ---- write SPI (reference: ConnectorPageSinkProvider; the memory
    # connector's MemoryPagesStore.add).  The memory connector has no
    # staged sink; engine writes adapt through connectors.AppendPageSink
    # and the writer records layout properties post-commit. ----
    def record_write_properties(self, props, ordered: bool = False) -> None:
        self._write_props = props
        self._layout_ordered = bool(ordered)

    def write_properties(self):
        return getattr(self, "_write_props", None)

    def ordering(self):
        if getattr(self, "_layout_ordered", False) and self._write_props:
            return [(c, bool(a))
                    for c, a in self._write_props.get("sorted_by", [])]
        return []

    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        def keep_mask(v):
            return v if isinstance(v, np.ma.MaskedArray) else np.asarray(v)

        if self._rows == 0:
            self.data = {c: keep_mask(arrays[c]) for c in self.schema}
        else:
            def cat(old, new):
                # masked concat ONLY for columns that carry a mask —
                # null-free columns must stay plain ndarrays
                if isinstance(old, np.ma.MaskedArray) \
                        or isinstance(new, np.ma.MaskedArray):
                    return np.ma.concatenate([old, new])
                return np.concatenate([old, new])

            self.data = {c: cat(self.data[c], keep_mask(arrays[c]))
                         for c in self.schema}
        self._rows += n
        self._invalidate()
        return n

    def delete_where(self, keep_mask: np.ndarray) -> int:
        deleted = int((~keep_mask).sum())
        self.data = {c: v[keep_mask] for c, v in self.data.items()}
        self._rows -= deleted
        # deletes break the append-only MV delta contract even when the
        # row count later recovers (connectors/delta.py watermark)
        self._mv_delete_epoch = getattr(self, "_mv_delete_epoch", 0) + 1
        self._invalidate()
        return deleted

class TpchTable(ConnectorTable):
    """TPC-H generator table (reference: presto-tpch), with a host disk
    cache so repeated test/bench runs skip regeneration."""

    def __init__(self, name: str, sf: float, cache_dir: Optional[str] = None):
        super().__init__(name, tpch_gen.SCHEMAS[name])
        self.sf = sf
        self.cache_dir = cache_dir

    def row_count(self) -> int:
        return tpch_gen.row_count(self.name, self.sf)

    def bucketing(self):
        from presto_tpu.connectors.tpch_device import chunk_family

        return chunk_family(self.name, self.sf)

    def column_stats(self, column: str):
        from presto_tpu.plan.stats import ColStats

        return tpch_gen.column_stats(self.name, column, self.sf, ColStats)

    def unique_keys(self):
        return tpch_gen.UNIQUE_KEYS.get(self.name, [])

    def key_layout(self, column: str):
        if self.name == "orders" and column == "o_orderkey":
            return (1, 32, 8)  # dbgen sparse orderkey: 8 per 32 block
        return None

    def max_rows_per_key(self):
        return tpch_gen.MAX_ROWS_PER_KEY.get(self.name, {})

    def ordering(self):
        # generator emits every table in primary-key order (validated
        # against generated data in tests/test_ordering_properties.py);
        # split/chunk scans preserve it — ranges are contiguous,
        # ascending, and concatenated in index order
        return tpch_gen.ORDERINGS.get(self.name, [])

    def splits(self, n_splits):
        return tpch_gen.split_ranges(self.name, self.sf, n_splits)

    def pushdown_like(self, column: str, pattern: str):
        """Connector LIKE pushdown: returns a BOOLEAN virtual column
        name evaluable at scan (generator word draws), or None."""
        return tpch_gen.like_pushdown_virtual(self.name, column, pattern)

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        virtual = [c for c in cols if "$contains$" in c]
        cols = [c for c in cols if "$contains$" not in c]
        data = self._full_table()
        if split is not None:
            a, b = split
            if self.name == "lineitem":
                lo, hi = tpch_gen.lineitem_offsets(a, b)
                out = {c: data[c][lo:hi] for c in cols}
            else:
                out = {c: data[c][a:b] for c in cols}
        else:
            out = {c: data[c] for c in cols}
        for v in virtual:
            word = v.rsplit("$", 1)[1]
            a, b = split if split is not None else (0, self.row_count())
            out[v] = tpch_gen.part_name_contains(a, b - a, word)
        return out

    def device_columns(self, columns, f32=False):
        """Generate columns directly on device (no host round trip) when
        the device generator covers them; returns None otherwise and the
        caller falls back to read().  See connectors/tpch_device.py."""
        from presto_tpu.connectors import tpch_device as D

        if not all(D.is_device_generable(self.name, c) for c in columns):
            return None
        from presto_tpu.exec import compile_cache as CC

        key = (tuple(sorted(columns)), f32)
        cache = getattr(self, "_device_gen_jit", None)
        if cache is None:
            cache = self._device_gen_jit = {}
        fn = cache.get(key)
        if fn is None:
            cols = list(key[0])

            def gen():
                return D.generate_device(self.name, self.sf, cols, f32=f32)

            # zero-arg AOT: the generator compile is part of a query's
            # cold cost and belongs in its compile-economics counters
            fn = cache[key] = CC.build_jit(gen, example=())
        return fn()

    def _full_table(self):
        # per-table lock: streaming cluster tasks run concurrently and
        # must not generate/unpickle the same table more than once
        lock = self.__dict__.setdefault("_mat_lock", threading.Lock())
        with lock:
            return self._full_table_locked()

    def _full_table_locked(self):
        if not hasattr(self, "_data"):
            path = None
            if self.cache_dir:
                os.makedirs(self.cache_dir, exist_ok=True)
                path = os.path.join(self.cache_dir, f"tpch_{self.name}_sf{self.sf}.pkl")
            if path and os.path.exists(path):
                with open(path, "rb") as f:
                    self._data = pickle.load(f)
            else:
                self._data = tpch_gen.generate(self.name, self.sf)
                if path:
                    with open(path, "wb") as f:
                        pickle.dump(self._data, f, protocol=4)
        return self._data


#: every live catalog, for bulk cache release (the test suite frees
#: device-column caches between modules to bound one-process memory)
import weakref

_live_catalogs: "weakref.WeakSet[Catalog]" = weakref.WeakSet()


def _drop_device_cache(table) -> None:
    """The ONE device-column-cache drop (used by writes via
    ConnectorTable._invalidate and by release_device_caches); instance
    attrs only — some tables expose _device_cols as a property.  The
    distributed data plane keeps per-mesh-size sharded copies
    (_dist_cols_<ndev>, parallel/dist_executor.sharded_scan) that must
    drop with the rest or post-write reads serve stale shards."""
    for attr in list(getattr(table, "__dict__", {})):
        if attr in ("_device_cols", "_device_cols_f32") \
                or attr.startswith("_dist_cols_"):
            delattr(table, attr)


def release_device_caches() -> None:
    """Drop cached device columns on every live catalog's tables (they
    re-upload lazily).  Host memory otherwise accumulates one copy per
    (catalog, sf) across a long test session."""
    for cat in list(_live_catalogs):
        for t in cat.tables.values():
            _drop_device_cache(t)


class Catalog:
    """Named schemas of tables (reference: MetadataManager + StaticCatalogStore).
    `version` bumps on registration so compiled-plan caches invalidate;
    in-place mutation of a registered MemoryTable's arrays is unsupported —
    re-register instead."""

    def __init__(self):
        self.tables: Dict[str, ConnectorTable] = {}
        #: materialized-view registry: flat name -> exec.matview.MvDefinition
        self.matviews: Dict[str, object] = {}
        self.version = 0
        _live_catalogs.add(self)
        # per-instance copy: a connector attaching a new qualifier (e.g.
        # sqlite) must not change name resolution in OTHER catalogs
        self.known_qualifiers = set(self.KNOWN_QUALIFIERS)
        # prefixes CLAIMED by a connector: a qualified miss under them is
        # an error, never a fallback to a same-named internal table
        self.claimed_prefixes: set = set()

    def register(self, table: ConnectorTable) -> None:
        self.tables[table.name.lower()] = table
        table._catalog = self  # mutation hooks bump version (write path)
        self.version += 1

    def drop(self, name: str, if_exists: bool = False) -> bool:
        n = name.lower()
        if n not in self.tables and "." in n:
            # qualified name over a flat registration: resolve the same
            # way get() does, or DROP memory.default.t would delete the
            # table's data and then fail to unregister it
            flat = self._flat_name(n)
            if flat is not None and flat in self.tables:
                n = flat
        t = self.tables.pop(n, None)
        if t is None:
            if if_exists:
                return False
            raise KeyError(f"Table '{name}' does not exist")
        self.version += 1
        return True

    def register_memory(self, name: str, schema: Dict[str, T.Type],
                        data: Dict[str, np.ndarray]) -> None:
        self.register(MemoryTable(name, schema, data))

    def register_parquet(self, name: str, path: str,
                         ordering=None) -> None:
        """A .parquet file (or directory of them) as a table
        (reference: hive external tables over parquet files).
        `ordering`: optional [(column, ascending), ...] physical sort
        declaration (hive SORTED BY analog) — exploited by ordering-
        aware execution behind runtime guards."""
        from presto_tpu.connectors.parquet import ParquetTable

        self.register(ParquetTable(name, path, ordering=ordering))

    def register_orc(self, name: str, path: str) -> None:
        """A .orc file (or directory of them) as a table (reference:
        hive external tables over ORC, presto-orc readers)."""
        from presto_tpu.connectors.orc import OrcTable

        self.register(OrcTable(name, path))

    def register_csv(self, name: str, path: str, schema=None) -> None:
        """A header-rowed CSV file as a table; types infer from the
        data when no schema is given (presto-record-decoder role)."""
        from presto_tpu.connectors.textfile import CsvTable

        self.register(CsvTable(name, path, schema))

    def register_jsonl(self, name: str, path: str, schema=None) -> None:
        """A JSON-lines file as a table (JsonRowDecoder role); nested
        values surface as JSON text."""
        from presto_tpu.connectors.textfile import JsonlTable

        self.register(JsonlTable(name, path, schema))

    #: catalog/schema qualifiers accepted for flat registrations; a bogus
    #: prefix must NOT silently resolve to the bare table
    KNOWN_QUALIFIERS = {"tpch", "tpcds", "memory", "localfile", "blackhole",
                        "parquet", "orc",
                        "presto_tpu", "default", "system"}

    def _flat_name(self, name: str) -> Optional[str]:
        parts = name.lower().split(".")
        if len(parts) < 2:
            return None
        if parts[0] in self.claimed_prefixes:
            return None  # connector-owned namespace: exact matches only
        import re as _re

        if all(p in self.known_qualifiers
               or _re.fullmatch(r"sf\d+(_\d+)?", p) for p in parts[:-1]):
            return parts[-1]
        return None

    def get(self, name: str) -> ConnectorTable:
        t = self.tables.get(name.lower())
        if t is None and "." in name:
            # catalog.schema.table written against a flat registration
            flat = self._flat_name(name)
            t = self.tables.get(flat) if flat else None
        if t is None:
            raise KeyError(f"Table '{name}' does not exist")
        return t

    def __contains__(self, name: str) -> bool:
        n = name.lower()
        if n in self.tables:
            return True
        flat = self._flat_name(n)
        return flat is not None and flat in self.tables


def tpch_catalog(sf: float = 0.01, cache_dir: Optional[str] = None) -> Catalog:
    cat = Catalog()
    for name in tpch_gen.SCHEMAS:
        cat.register(TpchTable(name, sf, cache_dir))
    return cat


class TpcdsTable(ConnectorTable):
    """TPC-DS generator table (reference: presto-tpcds), same disk-cache
    scheme as TpchTable."""

    def __init__(self, name: str, sf: float, cache_dir: Optional[str] = None):
        from presto_tpu.connectors import tpcds as tpcds_gen

        super().__init__(name, tpcds_gen.SCHEMAS[name])
        self._gen = tpcds_gen
        self.sf = sf
        self.cache_dir = cache_dir

    def row_count(self) -> int:
        return self._gen.row_count(self.name, self.sf)

    def bucketing(self):
        from presto_tpu.connectors.tpcds_device import chunk_family

        return chunk_family(self.name, self.sf)

    def column_stats(self, column: str):
        from presto_tpu.plan.stats import ColStats

        return self._gen.column_stats(self.name, column, self.sf, ColStats)

    def unique_keys(self):
        return self._gen.UNIQUE_KEYS.get(self.name, [])

    def max_rows_per_key(self):
        return self._gen.MAX_ROWS_PER_KEY.get(self.name, {})

    def ordering(self):
        return self._gen.ORDERINGS.get(self.name, [])

    def splits(self, n_splits):
        return self._gen.split_ranges(self.name, self.sf, n_splits)

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        data = self._full_table()
        if split is not None:
            a, b = split
            return {c: data[c][a:b] for c in cols}
        return {c: data[c] for c in cols}

    def _full_table(self):
        lock = self.__dict__.setdefault("_mat_lock", threading.Lock())
        with lock:
            return self._full_table_locked()

    def _full_table_locked(self):
        if not hasattr(self, "_data"):
            path = None
            if self.cache_dir:
                os.makedirs(self.cache_dir, exist_ok=True)
                path = os.path.join(self.cache_dir,
                                    # v2: money values moved to explicit
                                    # rint/reciprocal rounding (tpcds._round)
                                    f"tpcds_{self.name}_sf{self.sf}_v2.pkl")
            if path and os.path.exists(path):
                with open(path, "rb") as f:
                    self._data = pickle.load(f)
            else:
                self._data = self._gen.generate(self.name, self.sf)
                if path:
                    with open(path, "wb") as f:
                        pickle.dump(self._data, f, protocol=4)
        return self._data


def tpcds_catalog(sf: float = 0.01, cache_dir: Optional[str] = None) -> Catalog:
    from presto_tpu.connectors import tpcds as tpcds_gen

    cat = Catalog()
    for name in tpcds_gen.SCHEMAS:
        cat.register(TpcdsTable(name, sf, cache_dir))
    return cat
