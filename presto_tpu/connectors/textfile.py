"""Text-format tables: CSV and JSON-lines files on local disk.

Reference parity: presto-record-decoder (the JSON/CSV row decoders
Kafka/Redis/local-file sources share) + the hive connector's text
formats.  Decoding happens once at first scan into typed numpy columns
(nulls as masked arrays); from there the engine's columnar path takes
over — there is no per-row decode at query time, which is the
TPU-friendly restating of the reference's streaming decoders.
"""

from __future__ import annotations

import csv
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import ConnectorTable


def _coerce(values: List[object], t: T.Type,
            empty_is_null: bool = True) -> np.ndarray:
    """Python values (None = null) -> typed column (masked when any
    null).  empty_is_null is the CSV convention; JSON keeps "" a real
    VARCHAR value."""
    mask = np.asarray([v is None or (empty_is_null and v == "")
                       for v in values], bool)
    if t.is_string:
        arr = np.empty(len(values), object)
        arr[:] = ["" if m else str(v) for v, m in zip(values, mask)]
    elif t.name == "BOOLEAN":
        arr = np.asarray([False if m else str(v).lower()
                          in ("true", "1", "t") for v, m in
                          zip(values, mask)])
    elif t.name == "DATE":
        import datetime as _dt

        arr = np.asarray([0 if m else
                          (_dt.date.fromisoformat(str(v))
                           - _dt.date(1970, 1, 1)).days
                          for v, m in zip(values, mask)], np.int32)
    elif t.is_integer:
        arr = np.asarray([0 if m else int(float(v))
                          for v, m in zip(values, mask)],
                         t.numpy_dtype())
    else:
        arr = np.asarray([0.0 if m else float(v)
                          for v, m in zip(values, mask)],
                         t.numpy_dtype())
    if mask.any():
        return np.ma.masked_array(arr, mask)
    return arr


def _infer_type(samples: List[object]) -> T.Type:
    """BIGINT < DOUBLE < BOOLEAN < VARCHAR by what every sample parses
    as (the record-decoder's schema-less default)."""
    seen = [s for s in samples if s is not None and s != ""]
    if not seen:
        return T.VARCHAR
    if all(isinstance(s, bool) for s in seen):
        return T.BOOLEAN

    def ok(fn):
        try:
            for s in seen:
                fn(s)
            return True
        except (TypeError, ValueError):
            return False

    if all(not isinstance(s, float) for s in seen) and ok(int):
        return T.BIGINT
    if ok(float):
        return T.DOUBLE
    if all(str(s).lower() in ("true", "false") for s in seen):
        return T.BOOLEAN
    return T.VARCHAR


class _DecodedTextTable(ConnectorTable):
    """Shared base: subclasses decode file -> {col: python values}."""

    EMPTY_IS_NULL = True  # CSV convention; JSONL overrides

    def __init__(self, name: str, path: str,
                 schema: Optional[Dict[str, T.Type]] = None):
        self.path = path
        raw = self._decode(path)
        inferred = schema is None
        if inferred:
            schema = {c: _infer_type(vals[:200])
                      for c, vals in raw.items()}
        self._data = {}
        for c, t in schema.items():
            try:
                self._data[c] = _coerce(raw[c], t, self.EMPTY_IS_NULL)
            except (TypeError, ValueError) as e:
                if not inferred:
                    raise ValueError(
                        f"column {c!r} does not parse as {t}: {e}"
                    ) from e
                # inference sampled a numeric-looking prefix; a later
                # value disagreed — fall back to VARCHAR
                schema[c] = T.VARCHAR
                self._data[c] = _coerce(raw[c], T.VARCHAR,
                                        self.EMPTY_IS_NULL)
        self._rows = len(next(iter(self._data.values()))) if self._data \
            else 0
        super().__init__(name, schema)

    def row_count(self) -> int:
        return self._rows

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        edges = np.linspace(0, self._rows, n_splits + 1).astype(int)
        return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:])
                if a < b]

    def read(self, columns=None, split=None) -> Dict[str, np.ndarray]:
        cols = columns if columns is not None else list(self.schema)
        a, b = split if split is not None else (0, self._rows)
        return {c: self._data[c][a:b] for c in cols}


class CsvTable(_DecodedTextTable):
    """CSV with a header row (reference: CsvRowDecoder + hive text)."""

    def _decode(self, path: str) -> Dict[str, List[object]]:
        with open(path, newline="", encoding="utf-8") as f:
            rd = csv.reader(f)
            header = next(rd, [])
            cols: Dict[str, List[object]] = {h: [] for h in header}
            for row in rd:
                for h, v in zip(header, row):
                    cols[h].append(v if v != "" else None)
                for h in header[len(row):]:  # ragged short rows
                    cols[h].append(None)
        return cols


class JsonlTable(_DecodedTextTable):
    """JSON-lines: one object per line, columns = union of keys
    (reference: JsonRowDecoder)."""

    EMPTY_IS_NULL = False  # "" is a real JSON string value

    def _decode(self, path: str) -> Dict[str, List[object]]:
        rows = []
        keys: List[str] = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                for k in obj:
                    if k not in keys:
                        keys.append(k)
                rows.append(obj)
        return {k: [self._scalar(r.get(k)) for r in rows] for k in keys}

    @staticmethod
    def _scalar(v):
        if isinstance(v, (dict, list)):
            return json.dumps(v)  # nested values surface as JSON text
        return v
