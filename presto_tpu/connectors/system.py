"""System connector: engine runtime state as SQL tables.

Reference parity: presto-main connector/system/ (SystemConnector with
system.runtime.queries / system.runtime.nodes), the information_schema
connector (connector/informationSchema/), and the presto-jmx module's
"metrics queryable in SQL" role.  Tables are virtual: each read() pulls a
fresh snapshot from the live Session, so they are always current and cost
nothing when unused (no device residency — system tables are tiny and
host-only by design; uploading them to HBM would waste transfers).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import ConnectorTable


class SystemTable(ConnectorTable):
    """Virtual table backed by a provider callback returning host columns."""

    def __init__(self, name: str, schema: Dict[str, T.Type], provider):
        super().__init__(name, schema)
        self._provider = provider

    def row_count(self) -> int:
        cols = self._provider()
        return len(next(iter(cols.values()))) if cols else 0

    def splits(self, n_splits: int):
        return [(0, self.row_count())]

    def read(self, columns: Optional[List[str]] = None, split=None):
        cols = self._provider()
        want = columns if columns is not None else list(self.schema)
        out = {}
        for c in want:
            a = cols[c]
            out[c] = (np.asarray(a, dtype=object)
                      if self.schema[c].is_string
                      else np.asarray(a, dtype=self.schema[c].numpy_dtype()))
        return out

    def _invalidate(self):  # never cache device columns for live state
        pass

    @property
    def _device_cols(self):
        return None

    @_device_cols.setter
    def _device_cols(self, v):
        pass  # discard: each scan re-ingests the fresh snapshot

    @property
    def _device_cols_f32(self):
        return None

    @_device_cols_f32.setter
    def _device_cols_f32(self, v):
        pass


def _queries_provider(session):
    def provide():
        hist = session.history_snapshot()
        return {
            "query_id": [q.query_id for q in hist],
            "state": [q.state for q in hist],
            "query": [q.sql for q in hist],
            "execution_mode": [q.execution_mode or "" for q in hist],
            "output_rows": [int(q.output_rows) for q in hist],
            "error": [q.error or "" for q in hist],
            "created": [int(q.create_time * 1e6) for q in hist],
            "ended": [int(q.end_time * 1e6) for q in hist],
            "total_ms": [q.total_ns / 1e6 for q in hist],
            "peak_memory_bytes": [int(q.peak_memory_bytes) for q in hist],
            "spilled_bytes": [int(q.spilled_bytes) for q in hist],
        }

    return provide


_QUERIES_SCHEMA = {
    "query_id": T.VARCHAR, "state": T.VARCHAR, "query": T.VARCHAR,
    "execution_mode": T.VARCHAR, "output_rows": T.BIGINT,
    "error": T.VARCHAR, "created": T.TIMESTAMP, "ended": T.TIMESTAMP,
    "total_ms": T.DOUBLE, "peak_memory_bytes": T.BIGINT,
    "spilled_bytes": T.BIGINT,
}


def _nodes_provider(session):
    start = time.time()

    def provide():
        import jax

        try:
            devs = jax.devices()
        except Exception:
            devs = []
        node_ids, versions, coord, state, uptime = [], [], [], [], []
        for d in devs:
            node_ids.append(f"{d.platform}:{d.id}")
            versions.append(jax.__version__)
            coord.append(d.id == 0)
            state.append("active")
            uptime.append(time.time() - start)
        return {"node_id": node_ids, "node_version": versions,
                "coordinator": coord, "state": state,
                "uptime_seconds": uptime}

    return provide


_NODES_SCHEMA = {
    "node_id": T.VARCHAR, "node_version": T.VARCHAR,
    "coordinator": T.BOOLEAN, "state": T.VARCHAR,
    "uptime_seconds": T.DOUBLE,
}


def _tables_provider(session):
    def provide():
        names = sorted(n for n in session.catalog.tables
                       if "." not in n or n.startswith(("system.",
                                                        "information_schema.")))
        return {
            "table_catalog": ["presto_tpu"] * len(names),
            "table_schema": [n.rsplit(".", 1)[0] if "." in n else "default"
                             for n in names],
            "table_name": [n.rsplit(".", 1)[-1] for n in names],
        }

    return provide


_TABLES_SCHEMA = {
    "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
    "table_name": T.VARCHAR,
}


def _columns_provider(session):
    def provide():
        cat, sch, tab, col, pos, typ = [], [], [], [], [], []
        for n in sorted(session.catalog.tables):
            t = session.catalog.tables[n]
            if isinstance(t, SystemTable) and not n.startswith(
                    ("system.", "information_schema.")):
                continue
            for i, (c, ct) in enumerate(t.schema.items()):
                cat.append("presto_tpu")
                sch.append(n.rsplit(".", 1)[0] if "." in n else "default")
                tab.append(n.rsplit(".", 1)[-1])
                col.append(c)
                pos.append(i + 1)
                typ.append(str(ct))
        return {"table_catalog": cat, "table_schema": sch,
                "table_name": tab, "column_name": col,
                "ordinal_position": pos, "data_type": typ}

    return provide


_COLUMNS_SCHEMA = {
    "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
    "table_name": T.VARCHAR, "column_name": T.VARCHAR,
    "ordinal_position": T.BIGINT, "data_type": T.VARCHAR,
}


def _properties_provider(session):
    def provide():
        names = sorted(session.properties)
        return {
            "name": names,
            "value": [str(session.properties[n]) for n in names],
            "explicit": [n in session._explicit_props for n in names],
        }

    return provide


_PROPERTIES_SCHEMA = {
    "name": T.VARCHAR, "value": T.VARCHAR, "explicit": T.BOOLEAN,
}


def register_system_tables(session) -> None:
    """Install the system/information_schema tables into the session's
    catalog (reference: SystemConnector registration in
    connector/ConnectorManager + the static information_schema catalog)."""
    cat = session.catalog
    for name, schema, provider in [
        ("system.runtime.queries", _QUERIES_SCHEMA,
         _queries_provider(session)),
        ("system.runtime.nodes", _NODES_SCHEMA, _nodes_provider(session)),
        ("system.session.properties", _PROPERTIES_SCHEMA,
         _properties_provider(session)),
        ("information_schema.tables", _TABLES_SCHEMA,
         _tables_provider(session)),
        ("information_schema.columns", _COLUMNS_SCHEMA,
         _columns_provider(session)),
    ]:
        cat.tables[name] = SystemTable(name, schema, provider)
