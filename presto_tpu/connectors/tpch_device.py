"""Device-side TPC-H generation: the TPU generates its own scan batches.

Reference parity: presto-tpch generates rows on the fly inside the scan
operator (TpchRecordSet) instead of reading storage.  TPU-native
adaptation: the generator is a counter-based hash (splitmix64 over
(table, column, row) counters, connectors/tpch.py), which is pure
integer math — so any row range of any column can be produced ON DEVICE
by the same XLA program that consumes it.  At SF100 the host generator
produces ~0.1M rows/s on one core; the device version produces the
needed columns at memory-bandwidth speed, which is what makes the
BASELINE SF10/SF100 configs runnable at all.

Exactness: every formula mirrors connectors/tpch.py bit-for-bit (same
splitmix64 counters, same f64 scaling), validated column-for-column
against the host generator in tests/test_tpch_device.py.

String columns come back as dictionary codes computed on device:
- enum picks (flags, segments, priorities, modes...) map through a tiny
  host-precomputed LUT onto the sorted-unique dictionary the engine
  expects (code order == lexicographic order);
- numbered names (Customer#000000001, Supplier#..., Clerk#...) use a
  FormatDictionary — a *functional* dictionary that renders values from
  codes at materialization time (the LazyBlock idea,
  presto-spi/.../spi/block/LazyBlock.java: decode only what the result
  actually touches);
- free-text columns (comments, p_name, addresses, phones) are NOT
  device-generable; reads of those fall back to the host generator.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Column, Dictionary
from presto_tpu.connectors import tpch as H


# ---------------------------------------------------------------------------
# functional dictionary for numbered-name columns
# ---------------------------------------------------------------------------


class _FormatValues:
    """Vectorized `prefix#%0*d` renderer with ndarray-style indexing."""

    def __init__(self, prefix: str, width: int, n: int):
        self.prefix = prefix
        self.width = width
        self.n = n

    def __getitem__(self, codes):
        codes = np.asarray(codes)
        return np.char.add(
            self.prefix,
            np.char.zfill(codes.astype(np.int64).astype(str), self.width)
        ).astype(object)


class FormatDictionary(Dictionary):
    """Dictionary whose values are a formula, not an array: code k
    renders as `{prefix}{k:0{width}d}`.  Zero-filled numbering keeps
    code order == lexicographic order, the invariant dictionary
    comparisons rely on.  Codes are the entity keys themselves, so no
    giant value array ever materializes (15M customer names at SF100
    stay a single int column until the final rows are formatted)."""

    def __init__(self, prefix: str, width: int, n: int):
        # deliberately skip Dictionary.__init__'s np.asarray
        self.values = _FormatValues(prefix, width, n)
        self._id = next(type(self)._ids)
        self._n = n

    _ids = itertools.count(1 << 40)

    def __len__(self) -> int:
        return self._n

    def __repr__(self) -> str:
        return f"FormatDictionary({self.values.prefix!r}, n={self._n})"


# ---------------------------------------------------------------------------
# splitmix64 core on device (u64 emulated as u32 pairs by XLA)
# ---------------------------------------------------------------------------


def _mix(x: jnp.ndarray) -> jnp.ndarray:
    z = x + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def _raw(table: str, col: str, row0: int, n: int, draw: int = 0,
         k: int = 1) -> jnp.ndarray:
    """f64 uniforms in [0,1) for rows [row0, row0+n), draw index `draw`
    of `k` — matches H._raw(...)[:, draw] bit-for-bit."""
    rows = jnp.asarray(row0, jnp.uint64) + jnp.arange(n, dtype=jnp.uint64)
    ctr = (rows * jnp.uint64(k) + jnp.uint64(draw)
           + jnp.uint64(int(H._colkey(table, col)))
           * jnp.uint64(0x632BE59BD9B4E019))
    u = _mix(ctr)
    return (u >> jnp.uint64(11)).astype(jnp.float64) * (2.0 ** -53)


def _u(table, col, row0, n, lo, hi, dtype=jnp.int64):
    return (lo + jnp.floor(_raw(table, col, row0, n)
                           * (hi - lo + 1))).astype(dtype)


def _uf(table, col, row0, n, lo, hi):
    return lo + _raw(table, col, row0, n) * (hi - lo)


def _money(table, col, row0, n, lo_cents, hi_cents):
    return _u(table, col, row0, n, lo_cents, hi_cents) / 100.0


def _lines_per_order(oi: jnp.ndarray) -> jnp.ndarray:
    h = ((oi.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15))
         ^ jnp.uint64(0xBF58476D1CE4E5B9))
    return ((h >> jnp.uint64(33)) % jnp.uint64(7)
            + jnp.uint64(1)).astype(jnp.int64)


def _retailprice(pk: jnp.ndarray) -> jnp.ndarray:
    cents = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
    return cents / 100.0


def _orderkey(oi: jnp.ndarray) -> jnp.ndarray:
    return (oi // 8) * 32 + oi % 8 + 1


def _ps_suppkey(pk, slot, sf):
    s = max(int(10_000 * sf), 1)
    return (pk + slot * (s // H.SUPP_PER_PART + (pk - 1) // s)) % s + 1


def _order_dates(row0, n):
    return _u("orders", "orderdate", row0, n,
              H.START_DATE, H.END_DATE - 151, jnp.int32)


def _order_custkey(row0, n, sf):
    ncust = max(int(150_000 * sf), 3)
    ck = _u("orders", "custkey", row0, n, 1, ncust)
    ck = ck - (ck % 3 == 0)
    return jnp.maximum(ck, 1)


# ---------------------------------------------------------------------------
# enum dictionaries: device code -> sorted-unique dictionary code LUTs
# ---------------------------------------------------------------------------


def _enum(choices: List[str]):
    """(Dictionary over sorted uniques, LUT: pick index -> dict code)."""
    values = np.unique(np.asarray(choices, dtype=object))
    lut = np.array([int(np.searchsorted(values, c)) for c in choices],
                   dtype=np.int32)
    return Dictionary(values), lut  # numpy: jit-safe host constant


def _enum2(c1: List[str], c2: List[str], sep=" "):
    combos = [a + sep + b for a in c1 for b in c2]
    values = np.unique(np.asarray(combos, dtype=object))
    lut = np.array([int(np.searchsorted(values, c)) for c in combos],
                   dtype=np.int32).reshape(len(c1), len(c2))
    return Dictionary(values), lut


def _enum3(c1, c2, c3):
    combos = [a + " " + b + " " + c for a in c1 for b in c2 for c in c3]
    values = np.unique(np.asarray(combos, dtype=object))
    lut = np.array([int(np.searchsorted(values, c)) for c in combos],
                   dtype=np.int32).reshape(len(c1), len(c2), len(c3))
    return Dictionary(values), lut


# built once per process (tiny)
_ENUMS: Dict[str, tuple] = {}


def _enums():
    if not _ENUMS:
        _ENUMS["returnflag"] = _enum(["A", "N", "R"])  # identity (sorted)
        _ENUMS["ra"] = _enum(["R", "A"])
        _ENUMS["linestatus"] = _enum(["F", "O"])
        _ENUMS["orderstatus"] = _enum(["F", "O", "P"])
        _ENUMS["segment"] = _enum(H.SEGMENTS)
        _ENUMS["priority"] = _enum(H.PRIORITIES)
        _ENUMS["instruct"] = _enum(H.INSTRUCTIONS)
        _ENUMS["mode"] = _enum(H.MODES)
        _ENUMS["container"] = _enum2(H.CONTAINER_S1, H.CONTAINER_S2)
        _ENUMS["type"] = _enum3(H.TYPE_S1, H.TYPE_S2, H.TYPE_S3)
        _ENUMS["mfgr"] = _enum([f"Manufacturer#{m}" for m in range(1, 6)])
        bvals = np.unique(np.asarray(
            [f"Brand#{m}{x}" for m in range(1, 6) for x in range(1, 6)],
            dtype=object))
        blut = np.array([[int(np.searchsorted(
            bvals, f"Brand#{m}{x}")) for x in range(1, 6)]
            for m in range(1, 6)], dtype=np.int32)
        _ENUMS["brand"] = (Dictionary(bvals), blut)
    return _ENUMS


# ---------------------------------------------------------------------------
# per-table device column generators
# generators return (data, dictionary) — dictionary None for plain types
# ---------------------------------------------------------------------------


def _gen_customer(sf, row0, n, cols):
    E = _enums()
    out = {}
    if "c_custkey" in cols:
        out["c_custkey"] = (row0 + 1 + jnp.arange(n, dtype=jnp.int64), None)
    if "c_nationkey" in cols:
        out["c_nationkey"] = (_u("customer", "nation", row0, n, 0, 24), None)
    if "c_acctbal" in cols:
        out["c_acctbal"] = (_money("customer", "acctbal", row0, n,
                                   -99999, 999999), None)
    if "c_mktsegment" in cols:
        d, lut = E["segment"]
        idx = _u("customer", "segment", row0, n, 0,
                 len(H.SEGMENTS) - 1, jnp.int32)
        out["c_mktsegment"] = (jnp.asarray(lut)[idx], d)
    if "c_name" in cols:
        ck = row0 + 1 + jnp.arange(n, dtype=jnp.int64)
        ncust = H.row_count("customer", sf)
        out["c_name"] = (ck.astype(jnp.int32),
                         FormatDictionary("Customer#", 9, ncust + 1))
    return out


def _gen_orders(sf, row0, n, cols):
    E = _enums()
    out = {}
    oi = jnp.arange(n, dtype=jnp.int64) + row0
    if "o_orderkey" in cols:
        out["o_orderkey"] = (_orderkey(oi), None)
    if "o_custkey" in cols:
        out["o_custkey"] = (_order_custkey(row0, n, sf), None)
    if "o_orderstatus" in cols:
        d, lut = E["orderstatus"]
        odate = _order_dates(row0, n)
        # F < O < P sorted: F=0, O=1, P=2
        code = jnp.where(odate + 121 < H.CURRENT_DATE, 0,
                         jnp.where(odate > H.CURRENT_DATE, 1, 2))
        out["o_orderstatus"] = (jnp.asarray(lut)[code], d)
    if "o_totalprice" in cols:
        out["o_totalprice"] = (_money("orders", "totalprice", row0, n,
                                      85000, 55000000), None)
    if "o_orderdate" in cols:
        out["o_orderdate"] = (_order_dates(row0, n), None)
    if "o_orderpriority" in cols:
        d, lut = E["priority"]
        idx = _u("orders", "priority", row0, n, 0,
                 len(H.PRIORITIES) - 1, jnp.int32)
        out["o_orderpriority"] = (jnp.asarray(lut)[idx], d)
    if "o_clerk" in cols:
        nclerk = max(int(1000 * sf), 1)
        ck = _u("orders", "clerk", row0, n, 1, nclerk, jnp.int32)
        out["o_clerk"] = (ck, FormatDictionary("Clerk#", 9, nclerk + 1))
    if "o_shippriority" in cols:
        out["o_shippriority"] = (jnp.zeros(n, jnp.int32), None)
    return out


def _gen_lineitem(sf, order_row0, order_row1, cols,
                  n_orders=None, line_row0=None, pad=None):
    """row0/row1 index ORDERS rows, like the host generator.  Chunked
    callers pass static sizes (n_orders orders padded, pad lineitem
    rows) with possibly-traced starts (order_row0, line_row0); rows past
    the real chunk extent are garbage the caller masks via sel."""
    t = "lineitem"
    E = _enums()
    if n_orders is None:
        n_orders = order_row1 - order_row0
    oi = jnp.arange(n_orders, dtype=jnp.int64) + order_row0
    counts = _lines_per_order(oi)
    if pad is None:
        lo, hi = H.lineitem_offsets(order_row0, order_row1)
        n = hi - lo
        row0 = lo
    else:
        n = pad
        row0 = line_row0
    out = {}
    need_odate = any(c in cols for c in
                     ("l_shipdate", "l_commitdate", "l_receiptdate",
                      "l_returnflag", "l_linestatus"))
    if "l_orderkey" in cols:
        out["l_orderkey"] = (jnp.repeat(_orderkey(oi), counts,
                                        total_repeat_length=n), None)
    odate = None
    if need_odate:
        odate = jnp.repeat(_order_dates(order_row0, len(oi)), counts,
                           total_repeat_length=n).astype(jnp.int64)
    pk = None
    if "l_partkey" in cols or "l_suppkey" in cols \
            or "l_extendedprice" in cols:
        npart = max(int(200_000 * sf), H.SUPP_PER_PART)
        pk = _u(t, "partkey", row0, n, 1, npart)
    if "l_partkey" in cols:
        out["l_partkey"] = (pk, None)
    if "l_suppkey" in cols:
        slot = _u(t, "suppslot", row0, n, 0, H.SUPP_PER_PART - 1)
        out["l_suppkey"] = (_ps_suppkey(pk, slot, sf), None)
    if "l_linenumber" in cols:
        starts = jnp.cumsum(counts) - counts
        out["l_linenumber"] = ((jnp.arange(n, dtype=jnp.int64)
                                - jnp.repeat(starts, counts,
                                             total_repeat_length=n) + 1)
                               .astype(jnp.int32), None)
    qty = None
    if "l_quantity" in cols or "l_extendedprice" in cols:
        qty = _u(t, "quantity", row0, n, 1, 50).astype(jnp.float64)
    if "l_quantity" in cols:
        out["l_quantity"] = (qty, None)
    if "l_extendedprice" in cols:
        out["l_extendedprice"] = (_retailprice(pk) * qty, None)
    if "l_discount" in cols:
        out["l_discount"] = (_u(t, "discount", row0, n, 0, 10) / 100.0, None)
    if "l_tax" in cols:
        out["l_tax"] = (_u(t, "tax", row0, n, 0, 8) / 100.0, None)
    shipdate = None
    if any(c in cols for c in ("l_shipdate", "l_receiptdate",
                               "l_returnflag", "l_linestatus")):
        shipdate = (odate + _u(t, "shipdelta", row0, n, 1, 121,
                               jnp.int32)).astype(jnp.int32)
    if "l_shipdate" in cols:
        out["l_shipdate"] = (shipdate, None)
    if "l_commitdate" in cols:
        out["l_commitdate"] = ((odate + _u(t, "commitdelta", row0, n, 30, 90,
                                           jnp.int32)).astype(jnp.int32),
                               None)
    receiptdate = None
    if "l_receiptdate" in cols or "l_returnflag" in cols:
        receiptdate = shipdate + _u(t, "receiptdelta", row0, n, 1, 30,
                                    jnp.int32)
    if "l_receiptdate" in cols:
        out["l_receiptdate"] = (receiptdate, None)
    if "l_returnflag" in cols:
        d, _ = E["returnflag"]  # sorted A,N,R
        ra = _u(t, "returnflag", row0, n, 0, 1, jnp.int32)  # 0=R 1=A
        code = jnp.where(receiptdate <= H.CURRENT_DATE,
                         jnp.where(ra == 0, 2, 0), 1)
        out["l_returnflag"] = (code.astype(jnp.int32), d)
    if "l_linestatus" in cols:
        d, _ = E["linestatus"]  # F=0 O=1
        out["l_linestatus"] = (
            (shipdate > H.CURRENT_DATE).astype(jnp.int32), d)
    if "l_shipinstruct" in cols:
        d, lut = E["instruct"]
        idx = _u(t, "instruct", row0, n, 0,
                 len(H.INSTRUCTIONS) - 1, jnp.int32)
        out["l_shipinstruct"] = (jnp.asarray(lut)[idx], d)
    if "l_shipmode" in cols:
        d, lut = E["mode"]
        idx = _u(t, "mode", row0, n, 0, len(H.MODES) - 1, jnp.int32)
        out["l_shipmode"] = (jnp.asarray(lut)[idx], d)
    return out


def _gen_part(sf, row0, n, cols):
    t = "part"
    E = _enums()
    out = {}
    pk = row0 + 1 + jnp.arange(n, dtype=jnp.int64)
    if "p_partkey" in cols:
        out["p_partkey"] = (pk, None)
    bm = bn = None
    if "p_mfgr" in cols or "p_brand" in cols:
        bm = _u(t, "brand_m", row0, n, 1, 5, jnp.int32)
        bn = _u(t, "brand_n", row0, n, 1, 5, jnp.int32)
    if "p_mfgr" in cols:
        d, lut = E["mfgr"]
        out["p_mfgr"] = (jnp.asarray(lut)[bm - 1], d)
    if "p_brand" in cols:
        d, lut = E["brand"]
        out["p_brand"] = (jnp.asarray(lut)[bm - 1, bn - 1], d)
    if "p_type" in cols:
        d, lut = E["type"]
        i1 = _u(t, "type1", row0, n, 0, len(H.TYPE_S1) - 1, jnp.int32)
        i2 = _u(t, "type2", row0, n, 0, len(H.TYPE_S2) - 1, jnp.int32)
        i3 = _u(t, "type3", row0, n, 0, len(H.TYPE_S3) - 1, jnp.int32)
        out["p_type"] = (jnp.asarray(lut)[i1, i2, i3], d)
    if "p_size" in cols:
        out["p_size"] = (_u(t, "size", row0, n, 1, 50, jnp.int32), None)
    if "p_container" in cols:
        d, lut = E["container"]
        i1 = _u(t, "cont1", row0, n, 0, len(H.CONTAINER_S1) - 1, jnp.int32)
        i2 = _u(t, "cont2", row0, n, 0, len(H.CONTAINER_S2) - 1, jnp.int32)
        out["p_container"] = (jnp.asarray(lut)[i1, i2], d)
    if "p_retailprice" in cols:
        out["p_retailprice"] = (_retailprice(pk), None)
    for c in cols:
        if c.startswith("p_name$contains$"):
            word = c.rsplit("$", 1)[1]
            target = H.COLORS.index(word)
            hit = jnp.zeros(n, bool)
            for j in range(5):
                idx = jnp.floor(_raw(t, "name", row0, n, draw=j, k=5)
                                * len(H.COLORS)).astype(jnp.int32)
                hit = hit | (idx == target)
            out[c] = (hit, None)
    return out


def _gen_supplier(sf, row0, n, cols):
    out = {}
    sk = row0 + 1 + jnp.arange(n, dtype=jnp.int64)
    if "s_suppkey" in cols:
        out["s_suppkey"] = (sk, None)
    if "s_nationkey" in cols:
        out["s_nationkey"] = (_u("supplier", "nation", row0, n, 0, 24), None)
    if "s_acctbal" in cols:
        out["s_acctbal"] = (_money("supplier", "acctbal", row0, n,
                                   -99999, 999999), None)
    if "s_name" in cols:
        nsupp = H.row_count("supplier", sf)
        out["s_name"] = (sk.astype(jnp.int32),
                         FormatDictionary("Supplier#", 9, nsupp + 1))
    return out


def _gen_partsupp(sf, row0, n, cols):
    t = "partsupp"
    out = {}
    r = jnp.arange(n, dtype=jnp.int64) + row0
    pk = r // H.SUPP_PER_PART + 1
    if "ps_partkey" in cols:
        out["ps_partkey"] = (pk, None)
    if "ps_suppkey" in cols:
        out["ps_suppkey"] = (_ps_suppkey(pk, r % H.SUPP_PER_PART, sf), None)
    if "ps_availqty" in cols:
        out["ps_availqty"] = (_u(t, "availqty", row0, n, 1, 9999,
                                 jnp.int32), None)
    if "ps_supplycost" in cols:
        out["ps_supplycost"] = (_money(t, "supplycost", row0, n,
                                       100, 100000), None)
    return out


_DEVICE_GENERATORS = {
    "customer": _gen_customer,
    "orders": _gen_orders,
    "lineitem": _gen_lineitem,
    "part": _gen_part,
    "supplier": _gen_supplier,
    "partsupp": _gen_partsupp,
}

# columns each table can produce on device
DEVICE_COLUMNS = {
    "customer": {"c_custkey", "c_nationkey", "c_acctbal", "c_mktsegment",
                 "c_name"},
    "orders": {"o_orderkey", "o_custkey", "o_orderstatus", "o_totalprice",
               "o_orderdate", "o_orderpriority", "o_clerk",
               "o_shippriority"},
    "lineitem": {"l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
                 "l_quantity", "l_extendedprice", "l_discount", "l_tax",
                 "l_returnflag", "l_linestatus", "l_shipdate",
                 "l_commitdate", "l_receiptdate", "l_shipinstruct",
                 "l_shipmode"},
    "part": {"p_partkey", "p_mfgr", "p_brand", "p_type", "p_size",
             "p_container", "p_retailprice"},  # + p_name$contains$<w>
             # virtual predicate columns (is_device_generable)
    "supplier": {"s_suppkey", "s_nationkey", "s_acctbal", "s_name"},
    "partsupp": {"ps_partkey", "ps_suppkey", "ps_availqty",
                 "ps_supplycost"},
}


def generate_device(table: str, sf: float, cols: List[str],
                    row0: int = 0, row1: Optional[int] = None,
                    f32: bool = False, pad: Optional[int] = None,
                    n_orders: Optional[int] = None,
                    line_row0=None) -> Dict[str, Column]:
    """Generate `cols` of `table` rows [row0,row1) on the default device
    (orders-row ranges for lineitem, like the host generator).  DOUBLE
    columns come back f32 when f32=True (saves HBM + emulated-f64 math
    for the float32_compute session mode).

    Chunked mode (pad is not None): shapes are STATIC (pad rows; for
    lineitem additionally n_orders padded orders) while the starts
    (row0, line_row0) may be traced scalars — one compiled program
    serves every chunk.  Rows past the real chunk extent are garbage
    the caller must mask via the batch sel."""
    schema = H.SCHEMAS[table]
    if pad is not None:
        if table == "lineitem":
            raw = _gen_lineitem(sf, row0, None, set(cols),
                                n_orders=n_orders, line_row0=line_row0,
                                pad=pad)
        else:
            raw = _DEVICE_GENERATORS[table](sf, row0, pad, set(cols))
        out = {}
        for c in cols:
            if c not in raw:
                raise KeyError(
                    f"column {c} of {table} is not device-generable")
            data, dic = raw[c]
            typ = schema.get(c, T.BOOLEAN)  # virtual predicate columns
            if f32 and typ.name == "DOUBLE":
                data = data.astype(jnp.float32)
            out[c] = Column(data, None, typ, dic)
        return out
    gen = _DEVICE_GENERATORS[table]
    if table == "lineitem":
        total = int(H._TABLE_ROWS["orders"] * sf)
    else:
        total = H.row_count(table, sf)
    row1 = total if row1 is None else min(row1, total)
    if table == "lineitem":
        raw = gen(sf, row0, row1, set(cols))
    else:
        raw = gen(sf, row0, row1 - row0, set(cols))
    out = {}
    for c in cols:
        if c not in raw:
            raise KeyError(f"column {c} of {table} is not device-generable")
        data, dic = raw[c]
        typ = schema.get(c, T.BOOLEAN)  # virtual predicate columns
        if f32 and typ.name == "DOUBLE":
            data = data.astype(jnp.float32)
        out[c] = Column(data, None, typ, dic)
    return out


def is_device_generable(table: str, col: str) -> bool:
    if col in DEVICE_COLUMNS.get(table, set()):
        return True
    return table == "part" and col.startswith("p_name$contains$")


# ---------------------------------------------------------------------------
# connector bucketing SPI (chunk family): how lineitem/orders stream
# chunk-wise through grouped execution.  Reference: connector bucketing
# (ConnectorNodePartitioningProvider, Connector.java:74, BucketNodeMap)
# + grouped execution (StageExecutionDescriptor.java:24-27,
# Lifespan.java:26-38).  TPU-native adaptation: a bucket is an
# order-row RANGE (range-bucketing colocates orderkey equi-joins the
# same way hash-bucketing does), and the "page source" for a bucket is
# device-side generation inside the consuming XLA program.
# ---------------------------------------------------------------------------


DEFAULT_CHUNK_ORDERS = 2_000_000


class TpchChunkGrid:
    """One chunk plan: order-row edges + lineitem offsets, static pad
    capacities, and the in-trace scan builder."""

    def __init__(self, sf: float, order_edges, line_offsets):
        self.sf = sf
        self.order_edges = order_edges
        self.line_offsets = line_offsets
        self.nchunks = len(order_edges) - 1
        self.cap_orders = max(b - a for a, b in zip(order_edges[:-1],
                                                    order_edges[1:]))
        self.cap_lines = max(b - a for a, b in zip(line_offsets[:-1],
                                                   line_offsets[1:]))

    def capacity(self, table: str) -> int:
        return self.cap_lines if table == "lineitem" else self.cap_orders

    def exchange_bound(self) -> int:
        """Default per-chunk compact bound for exchange outputs (chunk
        outputs are reductions of the chunk — aggregates on the bucket
        key, selective filters)."""
        return self.cap_orders

    def bucket_ndv(self) -> int:
        """Distinct bucket (orderkey) values in any one chunk — lets the
        chunked runner bound a per-chunk GROUP BY bucket_key output at
        order grain instead of lineitem grain."""
        return self.cap_orders

    def chunk_column_domain(self, table: str, col: str, i: int):
        """Zone map of `col` over chunk i, or None when unknowable —
        the dynamic-filtering chunk-pruning hook (exec/chunked.py):
        chunks whose range misses a runtime filter's domain are skipped
        before their program is ever dispatched.  Only the bucket
        column has a closed form: chunk i covers order rows
        [edges[i], edges[i+1]), and the sparse dbgen orderkey layout
        (8 keys per 32-key block) is monotone in the row index."""
        if table not in ("lineitem", "orders") or \
                col not in ("l_orderkey", "o_orderkey"):
            return None
        o0 = self.order_edges[i]
        o1 = self.order_edges[i + 1]
        if o1 <= o0:
            return None
        key = lambda oi: (oi // 8) * 32 + oi % 8 + 1  # noqa: E731
        return int(key(o0)), int(key(o1 - 1))

    def chunk_args(self, i: int):
        """Traced scalars for chunk i — a fixed pytree so ONE jitted
        program serves every chunk."""
        o0 = self.order_edges[i]
        o1 = self.order_edges[i + 1]
        return (jnp.asarray(o0, jnp.int64),
                jnp.asarray(self.line_offsets[i], jnp.int64),
                jnp.asarray(o1 - o0, jnp.int32),
                jnp.asarray(self.line_offsets[i + 1]
                            - self.line_offsets[i], jnp.int32))

    def build_scan(self, table: str, cols: List[str], args, f32: bool):
        """(raw {col: Column}, sel) for one chunk of `table`, inside the
        traced program."""
        o0, line0, n_ord, n_line = args
        if table == "lineitem":
            raw = generate_device(
                "lineitem", self.sf, cols, row0=o0, f32=f32,
                pad=self.cap_lines, n_orders=self.cap_orders,
                line_row0=line0)
            sel = jnp.arange(self.cap_lines) < n_line
        elif table == "orders":
            raw = generate_device("orders", self.sf, cols, row0=o0,
                                  f32=f32, pad=self.cap_orders)
            sel = jnp.arange(self.cap_orders) < n_ord
        else:
            raise KeyError(f"{table} is not in the tpch chunk family")
        return raw, sel


class TpchChunkFamily:
    """lineitem+orders co-bucketed on orderkey (reference:
    TpchNodePartitioningProvider buckets both on orderkey so the Q18
    join is colocated, presto-tpch/.../TpchNodePartitioningProvider)."""

    name = "tpch-orders"
    BUCKET_COLUMNS = {"lineitem": "l_orderkey", "orders": "o_orderkey"}

    def __init__(self, sf: float):
        self.sf = sf

    def tables(self):
        return set(self.BUCKET_COLUMNS)

    def bucket_column(self, table: str) -> str:
        return self.BUCKET_COLUMNS[table]

    def device_columns(self, table: str):
        return DEVICE_COLUMNS.get(table, set())

    def make_grid(self, session) -> TpchChunkGrid:
        chunk_orders = int(session.properties.get(
            "chunk_orders", DEFAULT_CHUNK_ORDERS))
        edges, line_offsets = H.chunk_grid(self.sf, chunk_orders)
        return TpchChunkGrid(self.sf, edges, line_offsets)


def chunk_family(table: str, sf: float):
    """Bucketing metadata for `table`, or None (the connector SPI hook
    TpchTable.bucketing delegates to)."""
    if table in TpchChunkFamily.BUCKET_COLUMNS:
        return TpchChunkFamily(sf)
    return None
