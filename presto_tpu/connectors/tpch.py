"""TPC-H data generator connector.

Reference parity: presto-tpch (TpchConnectorFactory.java:32, TpchRecordSet) —
the deterministic generated-data connector used as the universal test
fixture (SURVEY.md §4.5).  Like the reference's airlift-tpch generator it is
deterministic per (table, scale factor, row range); unlike it, generation is
fully vectorized numpy and *counter-based* (Philox streams keyed per
(table, column)), so any split [row0, row1) of any table can be produced
independently — the property the reference gets from per-part generator
seeking, and the one our split-parallel scans need.

Faithful to dbgen in schema, key relationships (FK validity incl. the
partsupp (partkey, supplier-slot) formula), value vocabularies, and date
logic; NOT bit-identical to dbgen output (correctness testing is
differential against sqlite on the same generated data, reference analog:
H2QueryRunner).

Money columns are DOUBLE, matching the reference connector's default
(presto-tpch TpchMetadata: useDecimal=false).
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T

EPOCH = np.datetime64("1970-01-01", "D")


def _days(date_str: str) -> int:
    return int((np.datetime64(date_str, "D") - EPOCH) / np.timedelta64(1, "D"))


START_DATE = _days("1992-01-01")  # 8035
END_DATE = _days("1998-12-01")
CURRENT_DATE = _days("1995-06-17")  # dbgen's "now"

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
# Comment vocabulary includes the words the spec's LIKE-predicates hunt for
# (Q13 '%special%requests%', Q16 '%Customer%Complaints%').
COMMENT_WORDS = (
    "blithely bold brave busy careful carefully quick quickly regular special "
    "express final furious ironic pending silent slow sly unusual even "
    "requests deposits accounts packages foxes pinto beans theodolites "
    "instructions dependencies excuses realms courts braids frays dugouts "
    "Customer Complaints sleep wake cajole nag haggle doze run dazzle boost "
    "breach affix detect doubt sublate about above according across after "
    "against along among around at before behind beside between beyond"
).split()

SUPP_PER_PART = 4

_TABLE_ROWS = {  # rows at SF1 (scaled linearly except nation/region)
    "nation": 25,
    "region": 5,
    "part": 200_000,
    "supplier": 10_000,
    "partsupp": 800_000,
    "customer": 150_000,
    "orders": 1_500_000,
    # lineitem row count is data-dependent (1..7 lines per order, avg 4)
}

SCHEMAS = {
    "region": {"r_regionkey": T.BIGINT, "r_name": T.VARCHAR, "r_comment": T.VARCHAR},
    "nation": {"n_nationkey": T.BIGINT, "n_name": T.VARCHAR,
               "n_regionkey": T.BIGINT, "n_comment": T.VARCHAR},
    "part": {"p_partkey": T.BIGINT, "p_name": T.VARCHAR, "p_mfgr": T.VARCHAR,
             "p_brand": T.VARCHAR, "p_type": T.VARCHAR, "p_size": T.INTEGER,
             "p_container": T.VARCHAR, "p_retailprice": T.DOUBLE,
             "p_comment": T.VARCHAR},
    "supplier": {"s_suppkey": T.BIGINT, "s_name": T.VARCHAR, "s_address": T.VARCHAR,
                 "s_nationkey": T.BIGINT, "s_phone": T.VARCHAR,
                 "s_acctbal": T.DOUBLE, "s_comment": T.VARCHAR},
    "partsupp": {"ps_partkey": T.BIGINT, "ps_suppkey": T.BIGINT,
                 "ps_availqty": T.INTEGER, "ps_supplycost": T.DOUBLE,
                 "ps_comment": T.VARCHAR},
    "customer": {"c_custkey": T.BIGINT, "c_name": T.VARCHAR, "c_address": T.VARCHAR,
                 "c_nationkey": T.BIGINT, "c_phone": T.VARCHAR,
                 "c_acctbal": T.DOUBLE, "c_mktsegment": T.VARCHAR,
                 "c_comment": T.VARCHAR},
    "orders": {"o_orderkey": T.BIGINT, "o_custkey": T.BIGINT,
               "o_orderstatus": T.VARCHAR, "o_totalprice": T.DOUBLE,
               "o_orderdate": T.DATE, "o_orderpriority": T.VARCHAR,
               "o_clerk": T.VARCHAR, "o_shippriority": T.INTEGER,
               "o_comment": T.VARCHAR},
    "lineitem": {"l_orderkey": T.BIGINT, "l_partkey": T.BIGINT,
                 "l_suppkey": T.BIGINT, "l_linenumber": T.INTEGER,
                 "l_quantity": T.DOUBLE, "l_extendedprice": T.DOUBLE,
                 "l_discount": T.DOUBLE, "l_tax": T.DOUBLE,
                 "l_returnflag": T.VARCHAR, "l_linestatus": T.VARCHAR,
                 "l_shipdate": T.DATE, "l_commitdate": T.DATE,
                 "l_receiptdate": T.DATE, "l_shipinstruct": T.VARCHAR,
                 "l_shipmode": T.VARCHAR, "l_comment": T.VARCHAR},
}

_TABLE_IDS = {t: i for i, t in enumerate(SCHEMAS)}


_LINEITEM_COUNT_CACHE: dict = {}


def row_count(table: str, sf: float) -> int:
    if table in ("nation", "region"):
        return _TABLE_ROWS[table]
    if table == "lineitem":
        # exact: sum of per-order line counts, computable without
        # generation.  Cached — the CBO derives stats many times per plan
        # and this sum walks 1.5M*sf hashes (seconds at SF100).
        n_orders = int(_TABLE_ROWS["orders"] * sf)
        n = _LINEITEM_COUNT_CACHE.get(n_orders)
        if n is None:
            n = int(np.sum(_lines_per_order(
                np.arange(n_orders, dtype=np.int64))))
            _LINEITEM_COUNT_CACHE[n_orders] = n
        return n
    return int(_TABLE_ROWS[table] * sf)


SEED = 20260729


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer — the counter-based RNG core.
    Each (table, column, row, draw) maps to one u64, so any row range of
    any column is reproducible independently (split independence)."""
    with np.errstate(over="ignore"):
        z = x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _colkey(table: str, column: str) -> np.uint64:
    h = SEED
    for ch in (table + "/" + column).encode():
        h = ((h ^ ch) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return np.uint64(h)


def _raw(table, col, row0, n, k=1):
    """(n, k) uniform doubles in [0,1) for rows [row0, row0+n)."""
    with np.errstate(over="ignore"):
        rows = np.arange(row0, row0 + n, dtype=np.uint64)[:, None]
        draws = np.arange(k, dtype=np.uint64)[None, :]
        ctr = rows * np.uint64(k) + draws + _colkey(table, col) * np.uint64(0x632BE59BD9B4E019)
        u = _splitmix64(ctr)
    return (u >> np.uint64(11)).astype(np.float64) * (2.0**-53)


def _u(table, col, row0, n, lo, hi, dtype=np.int64):
    """Uniform integers in [lo, hi] — exactly one counter draw per row."""
    return (lo + np.floor(_raw(table, col, row0, n)[:, 0] * (hi - lo + 1))).astype(dtype)


def _uf(table, col, row0, n, lo, hi):
    return lo + _raw(table, col, row0, n)[:, 0] * (hi - lo)


def _money(table, col, row0, n, lo_cents, hi_cents):
    return _u(table, col, row0, n, lo_cents, hi_cents) / 100.0


def _pick(table, col, row0, n, choices):
    idx = _u(table, col, row0, n, 0, len(choices) - 1, np.int32)
    return np.asarray(choices, dtype=object)[idx]


def _words(table, col, row0, n, vocab, k):
    """k-word space-joined phrases, vectorized (object arrays)."""
    idx = np.floor(_raw(table, col, row0, n, k) * len(vocab)).astype(np.int64)
    v = np.asarray(vocab, dtype=object)
    out = v[idx[:, 0]]
    for j in range(1, k):
        out = out + " "
        out = out + v[idx[:, j]]
    return out


def _numbered(prefix: str, keys: np.ndarray, width: int = 9) -> np.ndarray:
    return np.char.add(prefix, np.char.zfill(keys.astype(str), width)).astype(object)


def _phone(table, col, row0, n, nationkeys):
    raw = _raw(table, col, row0, n, 3)
    a = (100 + np.floor(raw[:, 0] * 900)).astype(np.int64)
    b = (100 + np.floor(raw[:, 1] * 900)).astype(np.int64)
    c = (1000 + np.floor(raw[:, 2] * 9000)).astype(np.int64)
    cc = (nationkeys + 10).astype(str)
    return (
        np.char.add(np.char.add(np.char.add(np.char.add(np.char.add(
            np.char.add(cc, "-"), a.astype(str)), "-"), b.astype(str)), "-"),
            c.astype(str))
    ).astype(object)


def _lines_per_order(order_idx: np.ndarray) -> np.ndarray:
    """1..7 lines per order, as a pure hash of the order index so that
    lineitem offsets are computable arithmetically (split independence)."""
    with np.errstate(over="ignore"):
        h = (order_idx.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) ^ np.uint64(
            0xBF58476D1CE4E5B9
        )
        return ((h >> np.uint64(33)) % np.uint64(7) + np.uint64(1)).astype(np.int64)


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    # dbgen formula: 90000 + ((partkey/10) % 20001) + 100*(partkey % 1000), in cents
    cents = 90000 + (partkey // 10) % 20001 + 100 * (partkey % 1000)
    return cents / 100.0


# ---------------------------------------------------------------------------
# per-table generators: generate(table, sf, row0, row1) -> dict[col, np.ndarray]
# ---------------------------------------------------------------------------


def _gen_region(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64)
    return {
        "r_regionkey": k,
        "r_name": np.asarray(REGIONS, dtype=object)[row0:row1],
        "r_comment": _words("region", "comment", row0, row1 - row0, COMMENT_WORDS, 6),
    }


def _gen_nation(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64)
    names = np.asarray([n for n, _ in NATIONS], dtype=object)[row0:row1]
    regions = np.asarray([r for _, r in NATIONS], dtype=np.int64)[row0:row1]
    return {
        "n_nationkey": k,
        "n_name": names,
        "n_regionkey": regions,
        "n_comment": _words("nation", "comment", row0, row1 - row0, COMMENT_WORDS, 8),
    }


def _gen_part(sf, row0, row1):
    n = row1 - row0
    pk = np.arange(row0 + 1, row1 + 1, dtype=np.int64)
    t = "part"
    brand_m = _u(t, "brand_m", row0, n, 1, 5)
    brand_n = _u(t, "brand_n", row0, n, 1, 5)
    mfgr = np.char.add("Manufacturer#", brand_m.astype(str)).astype(object)
    brand = np.char.add("Brand#", (brand_m * 10 + brand_n).astype(str)).astype(object)
    typ = (
        _pick(t, "type1", row0, n, TYPE_S1) + " "
        + _pick(t, "type2", row0, n, TYPE_S2) + " "
        + _pick(t, "type3", row0, n, TYPE_S3)
    )
    container = _pick(t, "cont1", row0, n, CONTAINER_S1) + " " + _pick(
        t, "cont2", row0, n, CONTAINER_S2)
    return {
        "p_partkey": pk,
        "p_name": _words(t, "name", row0, n, COLORS, 5),
        "p_mfgr": mfgr,
        "p_brand": brand,
        "p_type": typ,
        "p_size": _u(t, "size", row0, n, 1, 50, np.int32),
        "p_container": container,
        "p_retailprice": _retailprice(pk),
        "p_comment": _words(t, "comment", row0, n, COMMENT_WORDS, 5),
    }


def _gen_supplier(sf, row0, row1):
    n = row1 - row0
    sk = np.arange(row0 + 1, row1 + 1, dtype=np.int64)
    t = "supplier"
    nat = _u(t, "nation", row0, n, 0, 24)
    # dbgen: 5 suppliers per SF1 get "Customer...Complaints" comments (Q16)
    comment = _words(t, "comment", row0, n, COMMENT_WORDS, 7)
    bad = (sk % 1987) == 0
    comment = np.where(bad, "slow Customer even Complaints sleep", comment)
    return {
        "s_suppkey": sk,
        "s_name": _numbered("Supplier#", sk),
        "s_address": _words(t, "address", row0, n, COMMENT_WORDS, 3),
        "s_nationkey": nat,
        "s_phone": _phone(t, "phone", row0, n, nat),
        "s_acctbal": _money(t, "acctbal", row0, n, -99999, 999999),
        "s_comment": comment,
    }


def _gen_partsupp(sf, row0, row1):
    """Row r = (partkey = r // 4 + 1, supplier slot j = r % 4).
    Supplier formula mirrors dbgen so lineitem FK pairs stay valid:
      suppkey = (partkey + j*(S/4 + (partkey-1)//S)) % S + 1, S = 10000*sf."""
    n = row1 - row0
    r = np.arange(row0, row1, dtype=np.int64)
    pk = r // SUPP_PER_PART + 1
    j = r % SUPP_PER_PART
    t = "partsupp"
    return {
        "ps_partkey": pk,
        "ps_suppkey": _ps_suppkey(pk, j, sf),
        "ps_availqty": _u(t, "availqty", row0, n, 1, 9999, np.int32),
        "ps_supplycost": _money(t, "supplycost", row0, n, 100, 100000),
        "ps_comment": _words(t, "comment", row0, n, COMMENT_WORDS, 10),
    }


def _ps_suppkey(partkey: np.ndarray, slot: np.ndarray, sf: float) -> np.ndarray:
    s = max(int(10_000 * sf), 1)
    return (partkey + slot * (s // SUPP_PER_PART + (partkey - 1) // s)) % s + 1


def _gen_customer(sf, row0, row1):
    n = row1 - row0
    ck = np.arange(row0 + 1, row1 + 1, dtype=np.int64)
    t = "customer"
    nat = _u(t, "nation", row0, n, 0, 24)
    return {
        "c_custkey": ck,
        "c_name": _numbered("Customer#", ck),
        "c_address": _words(t, "address", row0, n, COMMENT_WORDS, 3),
        "c_nationkey": nat,
        "c_phone": _phone(t, "phone", row0, n, nat),
        "c_acctbal": _money(t, "acctbal", row0, n, -99999, 999999),
        "c_mktsegment": _pick(t, "segment", row0, n, SEGMENTS),
        "c_comment": _words(t, "comment", row0, n, COMMENT_WORDS, 8),
    }


def _order_dates(row0: int, n: int) -> np.ndarray:
    return _u("orders", "orderdate", row0, n, START_DATE, END_DATE - 151, np.int32)


def _order_custkey(row0: int, n: int, sf: float) -> np.ndarray:
    # dbgen: only 2/3 of customers have orders (custkey % 3 != 0 -> shift)
    ncust = max(int(150_000 * sf), 3)
    ck = _u("orders", "custkey", row0, n, 1, ncust)
    ck = ck - (ck % 3 == 0)  # avoid multiples of 3 => 1/3 of customers orderless
    return np.maximum(ck, 1)


def _gen_orders(sf, row0, row1):
    n = row1 - row0
    t = "orders"
    oi = np.arange(row0, row1, dtype=np.int64)
    ok = _orderkey(oi)
    odate = _order_dates(row0, n)
    # status: F if all lines shipped before current date, O if none, else P.
    # Approximate dbgen by deriving from orderdate the way ship dates do.
    status = np.where(
        odate + 121 < CURRENT_DATE, "F", np.where(odate > CURRENT_DATE, "O", "P")
    ).astype(object)
    return {
        "o_orderkey": ok,
        "o_custkey": _order_custkey(row0, n, sf),
        "o_orderstatus": status,
        "o_totalprice": _money(t, "totalprice", row0, n, 85000, 55000000),
        "o_orderdate": odate,
        "o_orderpriority": _pick(t, "priority", row0, n, PRIORITIES),
        "o_clerk": _numbered("Clerk#", _u(t, "clerk", row0, n, 1, max(int(1000 * sf), 1))),
        "o_shippriority": np.zeros(n, dtype=np.int32),
        "o_comment": _words(t, "comment", row0, n, COMMENT_WORDS, 10),
    }


def _orderkey(order_idx: np.ndarray) -> np.ndarray:
    """Sparse orderkeys like dbgen (8 per 32-key block)."""
    return (order_idx // 8) * 32 + order_idx % 8 + 1


def lineitem_offsets(order_row0: int, order_row1: int) -> tuple[int, int]:
    """Global lineitem row range produced by an order row range."""
    idx = np.arange(0, order_row1, dtype=np.int64)
    counts = _lines_per_order(idx)
    total_before = int(np.sum(counts[:order_row0]))
    total = int(np.sum(counts))
    return total_before, total


def _gen_lineitem_for_orders(sf, order_row0, order_row1):
    t = "lineitem"
    oi = np.arange(order_row0, order_row1, dtype=np.int64)
    counts = _lines_per_order(oi)
    n = int(np.sum(counts))
    row0, _ = lineitem_offsets(order_row0, order_row1)

    ok = np.repeat(_orderkey(oi), counts)
    odate = np.repeat(_order_dates(order_row0, len(oi)), counts).astype(np.int64)
    linenumber = (np.arange(n, dtype=np.int64)
                  - np.repeat(np.cumsum(counts) - counts, counts) + 1)

    npart = max(int(200_000 * sf), SUPP_PER_PART)
    pk = _u(t, "partkey", row0, n, 1, npart)
    slot = _u(t, "suppslot", row0, n, 0, SUPP_PER_PART - 1)
    sk = _ps_suppkey(pk, slot, sf)

    qty = _u(t, "quantity", row0, n, 1, 50).astype(np.float64)
    price = _retailprice(pk) * qty
    ship_delta = _u(t, "shipdelta", row0, n, 1, 121, np.int32)
    commit_delta = _u(t, "commitdelta", row0, n, 30, 90, np.int32)
    receipt_delta = _u(t, "receiptdelta", row0, n, 1, 30, np.int32)
    shipdate = (odate + ship_delta).astype(np.int32)
    receiptdate = shipdate + receipt_delta
    returnflag = np.where(
        receiptdate <= CURRENT_DATE,
        _pick(t, "returnflag", row0, n, ["R", "A"]),
        "N",
    ).astype(object)
    linestatus = np.where(shipdate > CURRENT_DATE, "O", "F").astype(object)
    return {
        "l_orderkey": ok,
        "l_partkey": pk,
        "l_suppkey": sk,
        "l_linenumber": linenumber.astype(np.int32),
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": _u(t, "discount", row0, n, 0, 10) / 100.0,
        "l_tax": _u(t, "tax", row0, n, 0, 8) / 100.0,
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_shipdate": shipdate,
        "l_commitdate": (odate + commit_delta).astype(np.int32),
        "l_receiptdate": receiptdate,
        "l_shipinstruct": _pick(t, "instruct", row0, n, INSTRUCTIONS),
        "l_shipmode": _pick(t, "mode", row0, n, MODES),
        "l_comment": _words(t, "comment", row0, n, COMMENT_WORDS, 4),
    }


_GENERATORS = {
    "region": _gen_region,
    "nation": _gen_nation,
    "part": _gen_part,
    "supplier": _gen_supplier,
    "partsupp": _gen_partsupp,
    "customer": _gen_customer,
    "orders": _gen_orders,
}


def generate(table: str, sf: float = 1.0, row0: int = 0, row1: int | None = None):
    """Generate host columnar data for `table` rows [row0, row1).

    For lineitem, row0/row1 index ORDERS rows (the split unit, mirroring the
    reference where lineitem splits follow order-part boundaries); the
    returned arrays hold all lineitems of those orders.
    """
    if table == "lineitem":
        n_orders = int(_TABLE_ROWS["orders"] * sf)
        row1 = n_orders if row1 is None else min(row1, n_orders)
        return _gen_lineitem_for_orders(sf, row0, row1)
    total = row_count(table, sf)
    row1 = total if row1 is None else min(row1, total)
    return _GENERATORS[table](sf, row0, row1)


def like_pushdown_virtual(table: str, column: str, pattern: str):
    """Virtual-column name for a connector-evaluable LIKE predicate, or
    None.  `p_name LIKE '%word%'` is decidable from the generator's word
    DRAWS without materializing any string (reference analog: TupleDomain
    predicate pushdown into the connector, PickTableLayout): p_name is 5
    vocabulary words joined by spaces, so a single-word substring match
    (where no other vocabulary word contains it) holds iff some draw
    picked that word."""
    if table != "part" or column != "p_name":
        return None
    if len(pattern) < 3 or not (pattern.startswith("%")
                                and pattern.endswith("%")):
        return None
    word = pattern[1:-1]
    if "%" in word or "_" in word or " " in word:
        return None
    containing = [c for c in COLORS if word in c]
    if containing != [word]:
        return None  # ambiguous: substring of another vocabulary word
    return f"p_name$contains${word}"


def part_name_contains(row0: int, n: int, word: str) -> np.ndarray:
    """Host evaluation of the p_name LIKE '%word%' virtual column."""
    idx = np.floor(_raw("part", "name", row0, n, 5) * len(COLORS)).astype(
        np.int64)
    return (idx == COLORS.index(word)).any(axis=1)


def chunk_grid(sf: float, chunk_orders: int):
    """Order-row chunk grid + lineitem offsets for chunked execution:
    returns (order_edges[n+1], line_offsets[n+1]).  Buckets are
    order-row ranges, so every orderkey's lineitems live in exactly one
    chunk (the connector-bucketing property grouped execution needs)."""
    n_orders = int(_TABLE_ROWS["orders"] * sf)
    edges = list(range(0, n_orders, chunk_orders)) + [n_orders]
    if edges[-2] == edges[-1]:
        edges.pop()
    line_offsets = [0]
    for a, b in zip(edges[:-1], edges[1:]):
        counts = _lines_per_order(np.arange(a, b, dtype=np.int64))
        line_offsets.append(line_offsets[-1] + int(np.sum(counts)))
    return edges, line_offsets


def split_ranges(table: str, sf: float, n_splits: int) -> list[tuple[int, int]]:
    """Even row-range splits (order-ranges for lineitem)."""
    total = int(_TABLE_ROWS["orders"] * sf) if table == "lineitem" else row_count(table, sf)
    edges = np.linspace(0, total, n_splits + 1).astype(int)
    return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:]) if a < b]


# ---------------------------------------------------------------------------
# statistics (arithmetic, no scanning) — reference: presto-tpch
# TpchMetadata.getTableStatistics backed by precomputed stats files;
# here derivable from the generator formulas directly.
# ---------------------------------------------------------------------------

UNIQUE_KEYS = {
    "region": [("r_regionkey",)],
    "nation": [("n_nationkey",)],
    "part": [("p_partkey",)],
    "supplier": [("s_suppkey",)],
    "partsupp": [("ps_partkey", "ps_suppkey")],
    "customer": [("c_custkey",)],
    "orders": [("o_orderkey",)],
    "lineitem": [("l_orderkey", "l_linenumber")],
}

# physical row ordering the generator emits (ordering-properties SPI,
# plan/properties.py): every table comes out in primary-key order —
# dbgen writes entity files in key order and the counter-based
# generator indexes rows the same way.  (partsupp's ps_suppkey is a
# slot formula, NOT sorted within a part, so only ps_partkey is
# declared.)  Consumed behind runtime monotonicity guards.
ORDERINGS = {
    "region": [("r_regionkey", True)],
    "nation": [("n_nationkey", True)],
    "part": [("p_partkey", True)],
    "supplier": [("s_suppkey", True)],
    "partsupp": [("ps_partkey", True)],
    "customer": [("c_custkey", True)],
    "orders": [("o_orderkey", True)],
    "lineitem": [("l_orderkey", True), ("l_linenumber", True)],
}

# max rows sharing one value of the key set (join fanout upper bounds)
MAX_ROWS_PER_KEY = {
    "lineitem": {("l_orderkey",): 7, ("l_orderkey", "l_linenumber"): 1},
    "partsupp": {("ps_partkey",): SUPP_PER_PART,
                 ("ps_partkey", "ps_suppkey"): 1},
}


def column_stats(table: str, column: str, sf: float, ColStats):
    n = row_count(table, sf)
    nparts = max(int(200_000 * sf), 1)
    n_ps = max(int(800_000 * sf), 1)
    max_ps_partkey = (n_ps - 1) // SUPP_PER_PART + 1
    nsupp = max(int(10_000 * sf), 1)
    ncust = max(int(150_000 * sf), 1)
    norders = max(int(1_500_000 * sf), 1)
    max_orderkey = ((norders - 1) // 8) * 32 + (norders - 1) % 8 + 1
    R = {
        "r_regionkey": (0, 4, 5), "n_nationkey": (0, 24, 25),
        "n_regionkey": (0, 4, 5),
        "p_partkey": (1, nparts, nparts),
        "p_size": (1, 50, 50),
        "p_retailprice": (900.0, 2099.0, None),
        "s_suppkey": (1, nsupp, nsupp),
        "s_nationkey": (0, 24, 25),
        "s_acctbal": (-999.99, 9999.99, None),
        "ps_partkey": (1, max(nparts, max_ps_partkey), nparts),
        "ps_suppkey": (1, nsupp, nsupp),
        "ps_availqty": (1, 9999, 9999),
        "ps_supplycost": (1.0, 1000.0, None),
        "c_custkey": (1, ncust, ncust),
        "c_nationkey": (0, 24, 25),
        "c_acctbal": (-999.99, 9999.99, None),
        "o_orderkey": (1, max_orderkey, norders),
        "o_custkey": (1, ncust, ncust),
        "o_totalprice": (850.0, 550000.0, None),
        "o_orderdate": (START_DATE, END_DATE - 151, END_DATE - START_DATE),
        "o_shippriority": (0, 0, 1),
        "l_orderkey": (1, max_orderkey, norders),
        "l_partkey": (1, nparts, nparts),
        "l_suppkey": (1, nsupp, nsupp),
        "l_linenumber": (1, 7, 7),
        "l_quantity": (1.0, 50.0, 50),
        "l_extendedprice": (900.0, 104950.0, None),
        "l_discount": (0.0, 0.10, 11),
        "l_tax": (0.0, 0.08, 9),
        "l_shipdate": (START_DATE + 1, END_DATE - 151 + 121, None),
        "l_commitdate": (START_DATE + 30, END_DATE - 151 + 90, None),
        "l_receiptdate": (START_DATE + 2, END_DATE - 151 + 121 + 30, None),
    }
    NDV_ONLY = {
        "r_name": 5, "n_name": 25, "p_mfgr": 5, "p_brand": 25,
        "p_type": 150, "p_container": 40, "p_name": None,
        "c_mktsegment": 5, "o_orderpriority": 5, "o_orderstatus": 3,
        "l_returnflag": 3, "l_linestatus": 2, "l_shipinstruct": 4,
        "l_shipmode": 7,
    }
    if column in R:
        lo, hi, ndv = R[column]
        schema_t = SCHEMAS[table][column]
        if schema_t.name == "DOUBLE":
            return ColStats(min=lo, max=hi, ndv=ndv)
        return ColStats(min=float(lo), max=float(hi), ndv=ndv)
    if column in NDV_ONLY:
        return ColStats(ndv=NDV_ONLY[column])
    return ColStats(ndv=min(n, 4_000_000))  # names/comments/phones etc.
