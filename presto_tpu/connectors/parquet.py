"""Parquet connector: tables over .parquet files on local disk.

Reference parity: presto-hive's ParquetPageSourceFactory +
presto-parquet readers (the Raptor-style "directory of files is a
table" model the localfile connector already uses).  The decoder/encoder
live in storage/parquet.py — in-engine, no external parquet library;
splits map to row groups so the scan path parallelizes like the
reference's Parquet stripes.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import ConnectorTable
from presto_tpu.storage.parquet import ParquetFile, write_parquet


class ParquetTable(ConnectorTable):
    """A .parquet file, or a directory of them with one schema."""

    supports_null_append = True  # null channel in the format

    def __init__(self, name: str, path: str,
                 schema: Optional[Dict[str, T.Type]] = None,
                 ordering=None):
        self.path = path
        # declared physical sort order (hive SORTED BY analog): the
        # files are claimed written lexicographically nondecreasing on
        # these (column, ascending) pairs — consumed behind runtime
        # monotonicity guards, so a false declaration costs the elided
        # sort back, never correctness
        self._ordering = [(c, bool(a)) for c, a in (ordering or [])]
        if schema is None:
            files = self._files()
            if not files:
                raise FileNotFoundError(f"no parquet files under {path}")
            f0 = ParquetFile(files[0])
            schema = {c.name: c.sql_type() for c in f0.columns}
        else:
            # a FRESH table (CTAS) must not silently absorb another
            # table-lifetime's part files sitting in the directory
            if self._files():
                raise ValueError(
                    f"target directory {path} already contains parquet "
                    "files; register it read-only or choose a new path")
            os.makedirs(path, exist_ok=True)
        super().__init__(name, schema)

    def ordering(self):
        return list(self._ordering)

    # -- layout --------------------------------------------------------
    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        if not os.path.isdir(self.path):
            return []
        return sorted(
            os.path.join(self.path, p) for p in os.listdir(self.path)
            if p.endswith(".parquet"))

    def _readers(self) -> List[ParquetFile]:
        paths = tuple(self._files())
        cached = getattr(self, "_reader_cache", None)
        if cached is None or cached[0] != paths:
            self._reader_cache = (paths, [ParquetFile(p) for p in paths])
        return self._reader_cache[1]

    def _invalidate(self):
        self._reader_cache = None
        super()._invalidate()  # device-column cache + catalog version

    # -- metadata ------------------------------------------------------
    def row_count(self) -> int:
        return sum(f.num_rows for f in self._readers())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        # row-group boundaries are the natural split grain (reference:
        # ParquetPageSourceFactory planning one split per row group)
        edges = [0]
        for f in self._readers():
            for rg in f.row_groups:
                edges.append(edges[-1] + rg[3])  # RowGroup.num_rows
        if len(edges) <= 1:
            return []
        targets = np.linspace(0, edges[-1], n_splits + 1)
        # snap to row-group boundaries, keeping splits non-empty
        snapped = sorted({min(edges, key=lambda e: abs(e - t))
                          for t in targets})
        if snapped[0] != 0:
            snapped.insert(0, 0)
        if snapped[-1] != edges[-1]:
            snapped.append(edges[-1])
        return [(a, b) for a, b in zip(snapped[:-1], snapped[1:]) if a < b]

    supports_domain_pushdown = True

    # -- read path -----------------------------------------------------
    def read(self, columns=None, split=None,
             domains=None) -> Dict[str, np.ndarray]:
        """`domains` ({column: storage.shard.Domain}) prunes whole row
        groups via footer statistics before any page decodes — the
        selective-read path (reference: OrcSelectiveRecordReader /
        TupleDomainParquetPredicate).  Pruning is advisory: surviving
        groups still carry non-matching rows for the Filter above."""
        cols = columns if columns is not None else list(self.schema)
        a, b = split if split is not None else (0, self.row_count())
        parts: Dict[str, list] = {c: [] for c in cols}
        counters = {"groups_total": 0, "groups_read": 0,
                    "bytes_total": 0, "bytes_read": 0}
        base = 0
        for f in self._readers():
            bycol = {c.name: c for c in f.columns}
            for gi, rg in enumerate(f.row_groups):
                n = rg[3]
                lo, hi = max(base, a), min(base + n, b)
                if lo < hi:
                    counters["groups_total"] += 1
                    counters["bytes_total"] += f.rg_byte_size(gi)
                    if not self._rg_matches(f, gi, bycol, domains):
                        base += n
                        continue
                    counters["groups_read"] += 1
                    counters["bytes_read"] += f.rg_byte_size(gi)
                    s0, s1 = lo - base, hi - base
                    for c in cols:
                        vals, valid, _t = f.read_column(gi, bycol[c])
                        seg = vals[s0:s1]
                        if valid is not None:
                            seg = np.ma.masked_array(
                                seg, mask=~valid[s0:s1])
                        parts[c].append(seg)
                base += n
        self.last_scan_counters = counters
        out = {}
        for c in cols:
            ps = parts[c]
            if not ps:
                t = self.schema[c]
                out[c] = np.empty(0, object if t.is_string
                                  else t.numpy_dtype())
            elif any(isinstance(p, np.ma.MaskedArray) for p in ps):
                out[c] = np.ma.concatenate(ps)
            else:
                out[c] = np.concatenate(ps)
        return out

    @staticmethod
    def _rg_matches(f: ParquetFile, gi: int, bycol, domains) -> bool:
        if not domains:
            return True
        for col, dom in domains.items():
            pc = bycol.get(col)
            if pc is None:
                continue
            st = f.rg_stats(gi, pc)
            if st is None:
                continue  # no stats -> cannot prune
            if not dom.overlaps(st[0], st[1]):
                return False
        return True

    # -- write path (reference: the hive connector's parquet sink) ----
    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        if os.path.isfile(self.path):
            raise ValueError(
                "single-file parquet table is read-only; register a "
                "directory to INSERT")
        os.makedirs(self.path, exist_ok=True)
        idx = len(self._files())
        out = os.path.join(self.path, f"part_{idx:06d}.parquet")
        write_parquet(out, {c: arrays[c] for c in self.schema},
                      self.schema,
                      row_group_rows=getattr(self, "row_group_rows", 0))
        self._invalidate()
        return n

    def drop_data(self) -> None:
        if os.path.isdir(self.path):
            for p in self._files():
                os.remove(p)
