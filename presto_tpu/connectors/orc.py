"""ORC connector: tables over .orc files on local disk (read path).

Reference parity: presto-hive's OrcPageSourceFactory over presto-orc/
readers; the decoder lives in storage/orc.py — in-engine, no external
ORC library.  Splits map to stripes, the reference's parallelism grain.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.catalog import ConnectorTable
from presto_tpu.connectors import StagedFileSink, files_ordered
from presto_tpu.storage.orc import OrcFile

_STR_NROWS = 5
MANIFEST_NAME = "_manifest.json"


class OrcTable(ConnectorTable):
    """A .orc file, or a directory of them with one schema.

    Engine-written directories carry a `_manifest.json` sidecar (the
    same snapshot/commit layer as the parquet and localfile
    connectors): authoritative file list + recorded write layout +
    verified ordering claim; externally-registered paths keep the
    legacy directory glob."""

    supports_null_append = True  # null channel in the format
    sink_file_prefix = "part"
    sink_file_ext = ".orc"

    def __init__(self, name: str, path: str, schema=None):
        self.path = path
        self._manifest: Optional[dict] = None
        if schema is None:
            mp = os.path.join(path, MANIFEST_NAME) \
                if os.path.isdir(path) else None
            if mp and os.path.exists(mp):
                with open(mp) as f:
                    self._manifest = json.load(f)
            files = self._files()
            if not files:
                raise FileNotFoundError(f"no orc files under {path}")
            f0 = OrcFile(files[0])
            schema = {c.name: c.sql_type() for c in f0.columns}
        else:
            if self._legacy_files():  # no silent stale-part absorb
                raise ValueError(
                    f"target directory {path} already contains orc "
                    "files; register it read-only or choose a new path")
            os.makedirs(path, exist_ok=True)
            self._manifest = {"files": [], "retired": [], "file_meta": {},
                              "write_props": None, "layout_ordered": False,
                              "generation": 0}
            self._write_manifest()
        super().__init__(name, schema)

    # -- manifest (snapshot layer; see connectors/localfile.py) --------
    def _write_manifest(self) -> None:
        mp = os.path.join(self.path, MANIFEST_NAME)
        tmp = mp + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._manifest, f)
        os.replace(tmp, mp)  # atomic publish

    def snapshot_state(self) -> Optional[dict]:
        if self._manifest is None:
            return None
        state = json.loads(json.dumps(self._manifest))
        state["__schema"] = {c: str(t) for c, t in self.schema.items()}
        return state

    def restore_state(self, state: dict) -> None:
        from presto_tpu import types as T

        state = dict(state)
        schema = state.pop("__schema", None)
        self._manifest = state
        if schema:
            self.schema = {c: T.parse_type(t) for c, t in schema.items()}
        self._write_manifest()
        self._invalidate()

    def write_properties(self) -> Optional[dict]:
        return None if self._manifest is None \
            else self._manifest.get("write_props")

    def record_write_properties(self, props: Optional[dict],
                                ordered: bool = False) -> None:
        self._adopt_manifest()
        self._manifest["write_props"] = props
        self._manifest["layout_ordered"] = bool(ordered)
        self._write_manifest()

    def ordering(self) -> List[Tuple[str, bool]]:
        m = self._manifest
        if m is None or not m.get("write_props") \
                or not m.get("layout_ordered"):
            return []
        return [(c, bool(a))
                for c, a in m["write_props"].get("sorted_by", [])]

    def _adopt_manifest(self) -> None:
        if self._manifest is None:
            self._manifest = {
                "files": [os.path.basename(p)
                          for p in self._legacy_files()],
                "retired": [], "file_meta": {}, "write_props": None,
                "layout_ordered": False, "generation": 0}

    def _commit_write(self, new_files, file_meta, write_props, replace,
                      schema=None, gc: bool = True) -> None:
        m = self._manifest
        shards = ([] if replace else list(m.get("files", []))) + new_files
        meta = {} if replace else dict(m.get("file_meta", {}))
        meta.update(file_meta)
        prev_retired = list(m.get("retired", []))
        retired = list(m.get("files", [])) if replace else []
        if not gc:
            retired = prev_retired + retired
        else:
            for p in prev_retired:
                try:
                    os.remove(os.path.join(self.path, p))
                except OSError:
                    pass
        wp = write_props if write_props is not None \
            else (None if replace else m.get("write_props"))
        sorted_by = (wp or {}).get("sorted_by") or []
        ordered = bool(sorted_by) and all(a for _c, a in sorted_by) \
            and files_ordered([(meta.get(s) or {}).get("ranges")
                               for s in shards])
        if schema is not None:
            self.schema = dict(schema)
        m["files"] = shards
        m["retired"] = retired
        m["file_meta"] = {s: meta[s] for s in shards if s in meta}
        m["write_props"] = wp
        m["layout_ordered"] = bool(ordered)
        m["generation"] = int(m.get("generation", 0)) + 1
        self._write_manifest()
        self._invalidate()

    def _legacy_files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        if not os.path.isdir(self.path):
            return []
        return sorted(
            os.path.join(self.path, p) for p in os.listdir(self.path)
            if p.endswith(".orc"))

    def _files(self) -> List[str]:
        if self._manifest is not None:
            return [os.path.join(self.path, p)
                    for p in self._manifest.get("files", [])]
        return self._legacy_files()

    def _readers(self) -> List[OrcFile]:
        paths = tuple(self._files())
        cached = getattr(self, "_orc_cache", None)
        if cached is None or cached[0] != paths:
            self._orc_cache = (paths, [OrcFile(p) for p in paths])
        return self._orc_cache[1]

    def _invalidate(self):
        self._orc_cache = None
        super()._invalidate()

    # -- write path (reference: presto-orc OrcWriter behind the hive
    # sink) --------------------------------------------------------
    def _sink_write_file(self, path: str, arrays, schema) -> None:
        from presto_tpu.storage.orc import write_orc

        write_orc(path, arrays, schema,
                  stripe_rows=getattr(self, "stripe_rows", 0))

    def page_sink(self, write_props=None, replace: bool = False,
                  schema=None, defer_gc: bool = False) -> StagedFileSink:
        if os.path.isfile(self.path):
            raise ValueError(
                "single-file orc table is read-only; register a "
                "directory to INSERT")
        os.makedirs(self.path, exist_ok=True)
        self._adopt_manifest()
        return StagedFileSink(self, write_props, replace=replace,
                              schema=schema, defer_gc=bool(defer_gc))

    def append(self, arrays) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        sink = self.page_sink()
        try:
            sink.append_page(dict(arrays))
            sink.finish()
        except BaseException:
            sink.abort()
            raise
        return n

    def drop_data(self) -> None:
        if os.path.isdir(self.path):
            for p in os.listdir(self.path):
                if p.endswith(".orc") or p.endswith(".stg") \
                        or p == MANIFEST_NAME:
                    try:
                        os.remove(os.path.join(self.path, p))
                    except OSError:
                        pass
            self._manifest = {"files": [], "retired": [], "file_meta": {},
                              "write_props": None,
                              "layout_ordered": False, "generation": 0}
            self._invalidate()

    def row_count(self) -> int:
        return sum(f.num_rows for f in self._readers())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        # stripe boundaries are the split grain (reference: one split
        # per stripe in the hive connector)
        edges = [0]
        for f in self._readers():
            for st in f.stripes:
                edges.append(edges[-1] + st[_STR_NROWS][0])
        if len(edges) <= 1:
            return []
        targets = np.linspace(0, edges[-1], n_splits + 1)
        snapped = sorted({min(edges, key=lambda e: abs(e - t))
                          for t in targets})
        if snapped[0] != 0:
            snapped.insert(0, 0)
        if snapped[-1] != edges[-1]:
            snapped.append(edges[-1])
        return [(a, b) for a, b in zip(snapped[:-1], snapped[1:]) if a < b]

    supports_domain_pushdown = True

    def read(self, columns=None, split=None,
             domains=None) -> Dict[str, np.ndarray]:
        """`domains` prunes whole stripes via the Metadata-section
        ColumnStatistics before any stream decodes (reference:
        OrcSelectiveRecordReader / OrcPredicate stripe pruning)."""
        cols = columns if columns is not None else list(self.schema)
        a, b = split if split is not None else (0, self.row_count())
        parts: Dict[str, list] = {c: [] for c in cols}
        counters = {"groups_total": 0, "groups_read": 0,
                    "bytes_total": 0, "bytes_read": 0}
        base = 0
        for f in self._readers():
            bycol = {c.name: c for c in f.columns}
            for si, st in enumerate(f.stripes):
                n = st[_STR_NROWS][0]
                nbytes = st.get(3, [0])[0]  # dataLength
                lo, hi = max(base, a), min(base + n, b)
                if lo < hi:
                    counters["groups_total"] += 1
                    counters["bytes_total"] += nbytes
                    if not self._stripe_matches(f, si, bycol, domains):
                        base += n
                        continue
                    counters["groups_read"] += 1
                    counters["bytes_read"] += nbytes
                    s0, s1 = lo - base, hi - base
                    for c in cols:
                        vals, valid, _t = f.read_column(si, bycol[c])
                        seg = vals[s0:s1]
                        if valid is not None:
                            seg = np.ma.masked_array(
                                seg, mask=~valid[s0:s1])
                        parts[c].append(seg)
                base += n
        self.last_scan_counters = counters
        out = {}
        for c in cols:
            ps = parts[c]
            if not ps:
                t = self.schema[c]
                out[c] = np.empty(0, object if t.is_string
                                  else t.numpy_dtype())
            elif any(isinstance(p, np.ma.MaskedArray) for p in ps):
                out[c] = np.ma.concatenate(ps)
            else:
                out[c] = np.concatenate(ps)
        return out

    @staticmethod
    def _stripe_matches(f: OrcFile, si: int, bycol, domains) -> bool:
        if not domains:
            return True
        for col, dom in domains.items():
            oc = bycol.get(col)
            if oc is None:
                continue
            st = f.stripe_col_stats(si, oc)
            if st is None:
                continue  # no stats -> cannot prune
            if not dom.overlaps(st[0], st[1]):
                return False
        return True
