"""ORC connector: tables over .orc files on local disk (read path).

Reference parity: presto-hive's OrcPageSourceFactory over presto-orc/
readers; the decoder lives in storage/orc.py — in-engine, no external
ORC library.  Splits map to stripes, the reference's parallelism grain.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

from presto_tpu.catalog import ConnectorTable
from presto_tpu.storage.orc import OrcFile

_STR_NROWS = 5


class OrcTable(ConnectorTable):
    """A .orc file, or a directory of them with one schema."""

    supports_null_append = True  # null channel in the format

    def __init__(self, name: str, path: str, schema=None):
        self.path = path
        files = self._files()
        if schema is None:
            if not files:
                raise FileNotFoundError(f"no orc files under {path}")
            f0 = OrcFile(files[0])
            schema = {c.name: c.sql_type() for c in f0.columns}
        else:
            if files:  # see ParquetTable: no silent stale-part absorb
                raise ValueError(
                    f"target directory {path} already contains orc "
                    "files; register it read-only or choose a new path")
            os.makedirs(path, exist_ok=True)
        super().__init__(name, schema)

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        if not os.path.isdir(self.path):
            return []
        return sorted(
            os.path.join(self.path, p) for p in os.listdir(self.path)
            if p.endswith(".orc"))

    def _readers(self) -> List[OrcFile]:
        paths = tuple(self._files())
        cached = getattr(self, "_orc_cache", None)
        if cached is None or cached[0] != paths:
            self._orc_cache = (paths, [OrcFile(p) for p in paths])
        return self._orc_cache[1]

    # -- write path (reference: presto-orc OrcWriter behind the hive
    # sink) --------------------------------------------------------
    def append(self, arrays) -> int:
        from presto_tpu.storage.orc import write_orc

        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        if os.path.isfile(self.path):
            raise ValueError(
                "single-file orc table is read-only; register a "
                "directory to INSERT")
        os.makedirs(self.path, exist_ok=True)
        idx = len(self._files())
        write_orc(os.path.join(self.path, f"part_{idx:06d}.orc"),
                  {c: arrays[c] for c in self.schema}, self.schema,
                  stripe_rows=getattr(self, "stripe_rows", 0))
        self._orc_cache = None
        self._invalidate()
        return n

    def drop_data(self) -> None:
        if os.path.isdir(self.path):
            for p in self._files():
                os.remove(p)

    def row_count(self) -> int:
        return sum(f.num_rows for f in self._readers())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        # stripe boundaries are the split grain (reference: one split
        # per stripe in the hive connector)
        edges = [0]
        for f in self._readers():
            for st in f.stripes:
                edges.append(edges[-1] + st[_STR_NROWS][0])
        if len(edges) <= 1:
            return []
        targets = np.linspace(0, edges[-1], n_splits + 1)
        snapped = sorted({min(edges, key=lambda e: abs(e - t))
                          for t in targets})
        if snapped[0] != 0:
            snapped.insert(0, 0)
        if snapped[-1] != edges[-1]:
            snapped.append(edges[-1])
        return [(a, b) for a, b in zip(snapped[:-1], snapped[1:]) if a < b]

    supports_domain_pushdown = True

    def read(self, columns=None, split=None,
             domains=None) -> Dict[str, np.ndarray]:
        """`domains` prunes whole stripes via the Metadata-section
        ColumnStatistics before any stream decodes (reference:
        OrcSelectiveRecordReader / OrcPredicate stripe pruning)."""
        cols = columns if columns is not None else list(self.schema)
        a, b = split if split is not None else (0, self.row_count())
        parts: Dict[str, list] = {c: [] for c in cols}
        counters = {"groups_total": 0, "groups_read": 0,
                    "bytes_total": 0, "bytes_read": 0}
        base = 0
        for f in self._readers():
            bycol = {c.name: c for c in f.columns}
            for si, st in enumerate(f.stripes):
                n = st[_STR_NROWS][0]
                nbytes = st.get(3, [0])[0]  # dataLength
                lo, hi = max(base, a), min(base + n, b)
                if lo < hi:
                    counters["groups_total"] += 1
                    counters["bytes_total"] += nbytes
                    if not self._stripe_matches(f, si, bycol, domains):
                        base += n
                        continue
                    counters["groups_read"] += 1
                    counters["bytes_read"] += nbytes
                    s0, s1 = lo - base, hi - base
                    for c in cols:
                        vals, valid, _t = f.read_column(si, bycol[c])
                        seg = vals[s0:s1]
                        if valid is not None:
                            seg = np.ma.masked_array(
                                seg, mask=~valid[s0:s1])
                        parts[c].append(seg)
                base += n
        self.last_scan_counters = counters
        out = {}
        for c in cols:
            ps = parts[c]
            if not ps:
                t = self.schema[c]
                out[c] = np.empty(0, object if t.is_string
                                  else t.numpy_dtype())
            elif any(isinstance(p, np.ma.MaskedArray) for p in ps):
                out[c] = np.ma.concatenate(ps)
            else:
                out[c] = np.concatenate(ps)
        return out

    @staticmethod
    def _stripe_matches(f: OrcFile, si: int, bycol, domains) -> bool:
        if not domains:
            return True
        for col, dom in domains.items():
            oc = bycol.get(col)
            if oc is None:
                continue
            st = f.stripe_col_stats(si, oc)
            if st is None:
                continue  # no stats -> cannot prune
            if not dom.overlaps(st[0], st[1]):
                return False
        return True
