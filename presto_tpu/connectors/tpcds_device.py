"""Device-side TPC-DS fact-table generation + chunk families.

Reference parity: presto-tpcds generates rows inside the scan operator
(TpcdsRecordSet wrapping dsdgen); grouped execution streams bucketed
fact tables one bucket at a time (Lifespan.java:26-38,
StageExecutionDescriptor.java:24-27); connector bucketing colocates the
sales<->returns joins (ConnectorNodePartitioningProvider,
Connector.java:74).  TPU-native adaptation: the host generator
(connectors/tpcds.py) is a counter-based splitmix64 hash, pure integer
math — so any row range of any fact column is producible ON DEVICE by
the same XLA program that consumes it.  That is what makes TPC-DS
SF100 (store_sales ~288M rows) runnable on one chip: the scan never
exists anywhere, each chunk is generated, filtered and reduced inside
one compiled program.

The four big fact tables (store_sales, store_returns, catalog_sales,
catalog_returns) are fully numeric — every column is device-generable
(dates/customers/items are _sk ints) — so unlike TPC-H no dictionary
machinery is needed.

Chunk families (bucketing metadata the chunked runner consumes):
- store:   store_sales + store_returns co-bucketed on ticket_number.
  A chunk is a sales-row range aligned to ticket boundaries
  (ticket = row // 3 + 1); the returns rows for those sales are exactly
  j in [ceil(a/10), ceil(b/10)) because return j's parent sale is row
  j*10 — both stream with pure arithmetic offsets.
- catalog: catalog_sales + catalog_returns co-bucketed on order_number
  (order = row // 4 + 1), same construction.

Exactness: every formula mirrors connectors/tpcds.py bit-for-bit (same
splitmix64 counters, same f64 scaling/rounding), validated
column-for-column in tests/test_tpcds_device.py.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp

from presto_tpu.batch import Column
from presto_tpu.connectors import tpcds as DS
from presto_tpu.connectors.tpch_device import _mix


# ---------------------------------------------------------------------------
# counter-based draws on device (bit-identical to tpcds.py's _raw_at)
# ---------------------------------------------------------------------------


def _key(table: str, col: str) -> int:
    """Host-precomputed (colkey * 0x632BE59BD9B4E019) mod 2^64 — numpy
    wraps the product; the device adds the wrapped constant."""
    return (int(DS._colkey("tpcds/" + table, col))
            * 0x632BE59BD9B4E019) % (1 << 64)


def _raw_at(table, col, rows, draw: int = 0, k: int = 1) -> jnp.ndarray:
    ctr = (rows.astype(jnp.uint64) * jnp.uint64(k) + jnp.uint64(draw)
           + jnp.uint64(_key(table, col)))
    u = _mix(ctr)
    return (u >> jnp.uint64(11)).astype(jnp.float64) * (2.0 ** -53)


def _u_at(table, col, rows, lo, hi, dtype=jnp.int64):
    return (lo + jnp.floor(_raw_at(table, col, rows)
                           * (hi - lo + 1))).astype(dtype)


def _money_at(table, col, rows, lo_cents, hi_cents):
    # * 0.01 (not / 100): must match the host generator's explicit
    # reciprocal-multiply, see tpcds._round
    return _u_at(table, col, rows, lo_cents, hi_cents) * 0.01


def _rint(x: jnp.ndarray) -> jnp.ndarray:
    """Exact round-half-to-even (np.rint semantics) built from floor.
    NOT lax.round: this environment's XLA CPU lowering of
    round_nearest_even is off-by-one near .5 boundaries for f64
    (lax.round(7582.499773998605) == 7581.0, lax.round(.49999999999999994)
    == -1.0), which would desync device generation from the host
    generator by whole cents."""
    f = jnp.floor(x)
    diff = x - f
    up = (diff > 0.5) | ((diff == 0.5) & (jnp.floor(f / 2) * 2 != f))
    r = f + up
    # beyond 2^52 every f64 is integral (and diff math loses meaning)
    return jnp.where(jnp.abs(x) >= 2.0 ** 52, x, r)


def _round2(x):
    """tpcds._round(x, 2) bit-for-bit: scale, rint, reciprocal-multiply
    (XLA's div-by-constant rewrite makes /100.0 a different operation
    under jit than on the host)."""
    return _rint(x * 100.0) * 0.01


# ---------------------------------------------------------------------------
# store channel
# ---------------------------------------------------------------------------


def _store_sales_cols(sf, rows, cols) -> Dict[str, jnp.ndarray]:
    """store_sales columns for explicit (possibly traced) row indices —
    mirrors tpcds._store_sales_cols formula-for-formula, computing only
    what `cols` needs."""
    t = "store_sales"
    need = set(cols)
    out = {}
    ticket = rows.astype(jnp.int64) // DS.ITEMS_PER_TICKET + 1
    if "ss_ticket_number" in need:
        out["ss_ticket_number"] = ticket
    # per-ticket attributes: drawn from the ticket counter, not the row
    if "ss_customer_sk" in need:
        out["ss_customer_sk"] = _u_at(t, "cust", ticket, 1,
                                      DS.row_count("customer", sf))
    if "ss_hdemo_sk" in need:
        out["ss_hdemo_sk"] = _u_at(
            t, "hdemo", ticket, 1,
            DS._FIXED_ROWS["household_demographics"])
    if "ss_addr_sk" in need:
        out["ss_addr_sk"] = _u_at(t, "addr", ticket, 1,
                                  DS.row_count("customer_address", sf))
    if "ss_store_sk" in need:
        out["ss_store_sk"] = _u_at(t, "store", ticket, 1,
                                   DS.row_count("store", sf))
    if "ss_sold_date_sk" in need:
        out["ss_sold_date_sk"] = _u_at(t, "date", ticket,
                                       DS.SALES_DATE_LO, DS.SALES_DATE_HI)
    # per-row attributes
    if "ss_sold_time_sk" in need:
        out["ss_sold_time_sk"] = _u_at(t, "time", rows, 28800, 75600)
    if "ss_item_sk" in need:
        out["ss_item_sk"] = _u_at(t, "item", rows, 1,
                                  DS.row_count("item", sf))
    if "ss_cdemo_sk" in need:
        out["ss_cdemo_sk"] = _u_at(
            t, "cdemo", rows, 1,
            DS.row_count("customer_demographics", sf))
    if "ss_promo_sk" in need:
        out["ss_promo_sk"] = _u_at(t, "promo", rows, 1,
                                   DS.row_count("promotion", sf))
    money = need & {"ss_quantity", "ss_wholesale_cost", "ss_list_price",
                    "ss_sales_price", "ss_ext_discount_amt",
                    "ss_ext_sales_price", "ss_ext_wholesale_cost",
                    "ss_ext_list_price", "ss_ext_tax", "ss_coupon_amt",
                    "ss_net_paid", "ss_net_paid_inc_tax", "ss_net_profit"}
    if money:
        qty = _u_at(t, "qty", rows, 1, 100, jnp.int32)
        wholesale = _money_at(t, "wholesale", rows, 100, 10_000)
        markup = _raw_at(t, "markup", rows) * 1.0
        discount = _raw_at(t, "discount", rows)
        list_price = _round2(wholesale * (1.0 + markup))
        sales_price = _round2(list_price * (1.0 - discount))
        qf = qty.astype(jnp.float64)
        ext_list = _round2(list_price * qf)
        ext_sales = _round2(sales_price * qf)
        ext_wholesale = _round2(wholesale * qf)
        coupon = _round2(ext_sales * (_raw_at(t, "coupon", rows) < 0.2)
                         * _raw_at(t, "coupamt", rows) * 0.5)
        net_paid = _round2(ext_sales - coupon)
        tax = _round2(net_paid * 0.08)
        vals = {
            "ss_quantity": qty,
            "ss_wholesale_cost": wholesale,
            "ss_list_price": list_price,
            "ss_sales_price": sales_price,
            "ss_ext_discount_amt": _round2(ext_list - ext_sales),
            "ss_ext_sales_price": ext_sales,
            "ss_ext_wholesale_cost": ext_wholesale,
            "ss_ext_list_price": ext_list,
            "ss_ext_tax": tax,
            "ss_coupon_amt": coupon,
            "ss_net_paid": net_paid,
            "ss_net_paid_inc_tax": _round2(net_paid + tax),
            "ss_net_profit": _round2(net_paid - ext_wholesale),
        }
        out.update({c: vals[c] for c in money})
    return out


def _store_returns_cols(sf, j, cols) -> Dict[str, jnp.ndarray]:
    """store_returns columns for return indices `j` — reads the parent
    sale's draws at row j*RETURN_EVERY like tpcds._gen_store_returns."""
    t = "store_returns"
    need = set(cols)
    parent = j.astype(jnp.int64) * DS.RETURN_EVERY
    parent_need = set()
    if need & {"sr_returned_date_sk"}:
        parent_need.add("ss_sold_date_sk")
    if "sr_item_sk" in need:
        parent_need.add("ss_item_sk")
    if "sr_customer_sk" in need:
        parent_need.add("ss_customer_sk")
    if "sr_cdemo_sk" in need:
        parent_need.add("ss_cdemo_sk")
    if "sr_hdemo_sk" in need:
        parent_need.add("ss_hdemo_sk")
    if "sr_addr_sk" in need:
        parent_need.add("ss_addr_sk")
    if "sr_store_sk" in need:
        parent_need.add("ss_store_sk")
    if "sr_ticket_number" in need:
        parent_need.add("ss_ticket_number")
    amount_cols = need & {"sr_return_quantity", "sr_return_amt",
                          "sr_return_tax", "sr_return_amt_inc_tax",
                          "sr_fee", "sr_return_ship_cost",
                          "sr_refunded_cash", "sr_reversed_charge",
                          "sr_store_credit", "sr_net_loss"}
    if amount_cols:
        parent_need |= {"ss_sales_price", "ss_quantity"}
    ss = _store_sales_cols(sf, parent, parent_need)
    out = {}
    if "sr_returned_date_sk" in need:
        out["sr_returned_date_sk"] = (ss["ss_sold_date_sk"]
                                      + _u_at(t, "lag", j, 1, 60))
    if "sr_return_time_sk" in need:
        out["sr_return_time_sk"] = _u_at(t, "time", j, 28800, 75600)
    for sr, sscol in (("sr_item_sk", "ss_item_sk"),
                      ("sr_customer_sk", "ss_customer_sk"),
                      ("sr_cdemo_sk", "ss_cdemo_sk"),
                      ("sr_hdemo_sk", "ss_hdemo_sk"),
                      ("sr_addr_sk", "ss_addr_sk"),
                      ("sr_store_sk", "ss_store_sk"),
                      ("sr_ticket_number", "ss_ticket_number")):
        if sr in need:
            out[sr] = ss[sscol]
    if "sr_reason_sk" in need:
        out["sr_reason_sk"] = _u_at(t, "reason", j, 1,
                                    DS._FIXED_ROWS["reason"])
    if amount_cols:
        ret_qty = jnp.minimum(_u_at(t, "qty", j, 1, 100, jnp.int32),
                              ss["ss_quantity"])
        amt = _round2(ss["ss_sales_price"] * ret_qty)
        tax = _round2(amt * 0.08)
        fee = _money_at(t, "fee", j, 50, 10_000)
        ship = _money_at(t, "ship", j, 0, 10_000)
        frac = _raw_at(t, "cashfrac", j)
        cash = _round2(amt * frac)
        charge = _round2((amt - cash) * _raw_at(t, "chargefrac", j))
        credit = _round2(amt - cash - charge)
        vals = {
            "sr_return_quantity": ret_qty,
            "sr_return_amt": amt,
            "sr_return_tax": tax,
            "sr_return_amt_inc_tax": _round2(amt + tax),
            "sr_fee": fee,
            "sr_return_ship_cost": ship,
            "sr_refunded_cash": cash,
            "sr_reversed_charge": charge,
            "sr_store_credit": credit,
            "sr_net_loss": _round2(fee + ship + tax),
        }
        out.update({c: vals[c] for c in amount_cols})
    return out


# ---------------------------------------------------------------------------
# catalog channel
# ---------------------------------------------------------------------------


def _sales_money_cols(t, rows, need) -> Dict[str, jnp.ndarray]:
    """Device mirror of tpcds._sales_money_cols (channel-shared pricing
    math), computing only the suffixes `need` asks for."""
    qty = _u_at(t, "qty", rows, 1, 100, jnp.int32)
    wholesale = _money_at(t, "wholesale", rows, 100, 10_000)
    markup = _raw_at(t, "markup", rows)
    discount = _raw_at(t, "discount", rows)
    list_price = _round2(wholesale * (1.0 + markup))
    sales_price = _round2(list_price * (1.0 - discount))
    qf = qty.astype(jnp.float64)
    ext_list = _round2(list_price * qf)
    ext_sales = _round2(sales_price * qf)
    ext_wholesale = _round2(wholesale * qf)
    coupon = _round2(ext_sales * (_raw_at(t, "coupon", rows) < 0.2)
                     * _raw_at(t, "coupamt", rows) * 0.5)
    ship_cost = _money_at(t, "shipc", rows, 0, 5_000) * qf
    net_paid = _round2(ext_sales - coupon)
    tax = _round2(net_paid * 0.08)
    vals = {
        "quantity": qty, "wholesale_cost": wholesale,
        "list_price": list_price, "sales_price": sales_price,
        "ext_discount_amt": _round2(ext_list - ext_sales),
        "ext_sales_price": ext_sales, "ext_wholesale_cost": ext_wholesale,
        "ext_list_price": ext_list, "ext_tax": tax, "coupon_amt": coupon,
        "ext_ship_cost": _round2(ship_cost), "net_paid": net_paid,
        "net_paid_inc_tax": _round2(net_paid + tax),
        "net_paid_inc_ship": _round2(net_paid + ship_cost),
        "net_paid_inc_ship_tax": _round2(net_paid + ship_cost + tax),
        "net_profit": _round2(net_paid - ext_wholesale),
    }
    return {k: v for k, v in vals.items() if k in need}


_CS_MONEY = {"quantity", "wholesale_cost", "list_price", "sales_price",
             "ext_discount_amt", "ext_sales_price", "ext_wholesale_cost",
             "ext_list_price", "ext_tax", "coupon_amt", "ext_ship_cost",
             "net_paid", "net_paid_inc_tax", "net_paid_inc_ship",
             "net_paid_inc_ship_tax", "net_profit"}


def _catalog_sales_cols(sf, rows, cols) -> Dict[str, jnp.ndarray]:
    t = "catalog_sales"
    need = set(cols)
    out = {}
    order = rows.astype(jnp.int64) // DS.ITEMS_PER_ORDER + 1
    if "cs_order_number" in need:
        out["cs_order_number"] = order
    n_cust = DS.row_count("customer", sf)
    n_cd = DS.row_count("customer_demographics", sf)
    n_hd = DS._FIXED_ROWS["household_demographics"]
    n_addr = DS.row_count("customer_address", sf)
    if "cs_bill_customer_sk" in need:
        out["cs_bill_customer_sk"] = _u_at(t, "bcust", order, 1, n_cust)
    if "cs_ship_customer_sk" in need:
        out["cs_ship_customer_sk"] = _u_at(t, "scust", order, 1, n_cust)
    sold = None
    if need & {"cs_sold_date_sk", "cs_ship_date_sk"}:
        sold = _u_at(t, "date", order, DS.SALES_DATE_LO, DS.SALES_DATE_HI)
    if "cs_sold_date_sk" in need:
        out["cs_sold_date_sk"] = sold
    if "cs_ship_date_sk" in need:
        out["cs_ship_date_sk"] = sold + _u_at(t, "shiplag", rows, 2, 90)
    if "cs_sold_time_sk" in need:
        out["cs_sold_time_sk"] = _u_at(t, "time", rows, 28800, 75600)
    if "cs_bill_cdemo_sk" in need:
        out["cs_bill_cdemo_sk"] = _u_at(t, "bcdemo", rows, 1, n_cd)
    if "cs_bill_hdemo_sk" in need:
        out["cs_bill_hdemo_sk"] = _u_at(t, "bhdemo", order, 1, n_hd)
    if "cs_bill_addr_sk" in need:
        out["cs_bill_addr_sk"] = _u_at(t, "baddr", order, 1, n_addr)
    if "cs_ship_cdemo_sk" in need:
        out["cs_ship_cdemo_sk"] = _u_at(t, "scdemo", rows, 1, n_cd)
    if "cs_ship_hdemo_sk" in need:
        out["cs_ship_hdemo_sk"] = _u_at(t, "shdemo", order, 1, n_hd)
    if "cs_ship_addr_sk" in need:
        out["cs_ship_addr_sk"] = _u_at(t, "saddr", order, 1, n_addr)
    if "cs_call_center_sk" in need:
        out["cs_call_center_sk"] = _u_at(t, "cc", rows, 1, 6)
    if "cs_catalog_page_sk" in need:
        out["cs_catalog_page_sk"] = _u_at(t, "cp", rows, 1, 11_718)
    if "cs_ship_mode_sk" in need:
        out["cs_ship_mode_sk"] = _u_at(t, "sm", rows, 1,
                                       DS._FIXED_ROWS["ship_mode"])
    if "cs_warehouse_sk" in need:
        out["cs_warehouse_sk"] = _u_at(t, "wh", rows, 1,
                                       DS.row_count("warehouse", sf))
    if "cs_item_sk" in need:
        out["cs_item_sk"] = _u_at(t, "item", rows, 1,
                                  DS.row_count("item", sf))
    if "cs_promo_sk" in need:
        out["cs_promo_sk"] = _u_at(t, "promo", rows, 1,
                                   DS.row_count("promotion", sf))
    money_need = {c[len("cs_"):] for c in need} & _CS_MONEY
    if money_need:
        m = _sales_money_cols(t, rows, money_need)
        out.update({"cs_" + k: v for k, v in m.items()})
    return out


def _catalog_returns_cols(sf, j, cols) -> Dict[str, jnp.ndarray]:
    t = "catalog_returns"
    need = set(cols)
    parent = j.astype(jnp.int64) * DS.RETURN_EVERY
    amount_cols = need & {"cr_return_quantity", "cr_return_amount",
                          "cr_return_tax", "cr_return_amt_inc_tax",
                          "cr_fee", "cr_return_ship_cost",
                          "cr_refunded_cash", "cr_reversed_charge",
                          "cr_store_credit", "cr_net_loss"}
    pairs = (("cr_item_sk", "cs_item_sk"),
             ("cr_refunded_customer_sk", "cs_bill_customer_sk"),
             ("cr_refunded_cdemo_sk", "cs_bill_cdemo_sk"),
             ("cr_refunded_hdemo_sk", "cs_bill_hdemo_sk"),
             ("cr_refunded_addr_sk", "cs_bill_addr_sk"),
             ("cr_returning_customer_sk", "cs_ship_customer_sk"),
             ("cr_returning_cdemo_sk", "cs_ship_cdemo_sk"),
             ("cr_returning_hdemo_sk", "cs_ship_hdemo_sk"),
             ("cr_returning_addr_sk", "cs_ship_addr_sk"),
             ("cr_call_center_sk", "cs_call_center_sk"),
             ("cr_catalog_page_sk", "cs_catalog_page_sk"),
             ("cr_ship_mode_sk", "cs_ship_mode_sk"),
             ("cr_warehouse_sk", "cs_warehouse_sk"),
             ("cr_order_number", "cs_order_number"))
    parent_need = {cs for cr, cs in pairs if cr in need}
    if "cr_returned_date_sk" in need:
        parent_need.add("cs_sold_date_sk")
    if amount_cols:
        parent_need |= {"cs_sales_price", "cs_quantity"}
    cs = _catalog_sales_cols(sf, parent, parent_need)
    out = {}
    if "cr_returned_date_sk" in need:
        out["cr_returned_date_sk"] = (cs["cs_sold_date_sk"]
                                      + _u_at(t, "lag", j, 1, 60))
    if "cr_returned_time_sk" in need:
        out["cr_returned_time_sk"] = _u_at(t, "time", j, 28800, 75600)
    for cr, cscol in pairs:
        if cr in need:
            out[cr] = cs[cscol]
    if "cr_reason_sk" in need:
        out["cr_reason_sk"] = _u_at(t, "reason", j, 1,
                                    DS._FIXED_ROWS["reason"])
    if amount_cols:
        ret_qty = jnp.minimum(_u_at(t, "qty", j, 1, 100, jnp.int32),
                              cs["cs_quantity"])
        amt = _round2(cs["cs_sales_price"] * ret_qty)
        tax = _round2(amt * 0.08)
        fee = _money_at(t, "fee", j, 50, 10_000)
        ship = _money_at(t, "ship", j, 0, 10_000)
        frac = _raw_at(t, "cashfrac", j)
        cash = _round2(amt * frac)
        charge = _round2((amt - cash) * _raw_at(t, "chargefrac", j))
        credit = _round2(amt - cash - charge)
        vals = {
            "cr_return_quantity": ret_qty,
            "cr_return_amount": amt,
            "cr_return_tax": tax,
            "cr_return_amt_inc_tax": _round2(amt + tax),
            "cr_fee": fee,
            "cr_return_ship_cost": ship,
            "cr_refunded_cash": cash,
            "cr_reversed_charge": charge,
            "cr_store_credit": credit,
            "cr_net_loss": _round2(fee + ship + tax),
        }
        out.update({c: vals[c] for c in amount_cols})
    return out


_GENERATORS = {
    "store_sales": _store_sales_cols,
    "store_returns": _store_returns_cols,
    "catalog_sales": _catalog_sales_cols,
    "catalog_returns": _catalog_returns_cols,
}

# every column of the four fact tables is numeric -> device-generable
DEVICE_COLUMNS = {t: set(DS.SCHEMAS[t]) for t in _GENERATORS}


def generate_device(table: str, sf: float, cols: List[str], row0,
                    pad: int, f32: bool = False) -> Dict[str, Column]:
    """Generate `cols` of `table` rows [row0, row0+pad) on device.
    Shapes are STATIC (pad rows) while row0 may be a traced scalar —
    one compiled program serves every chunk.  Rows past the real chunk
    extent are garbage the caller must mask via the batch sel."""
    rows = jnp.asarray(row0, jnp.int64) + jnp.arange(pad, dtype=jnp.int64)
    raw = _GENERATORS[table](sf, rows, set(cols))
    schema = DS.SCHEMAS[table]
    out = {}
    for c in cols:
        if c not in raw:
            raise KeyError(f"column {c} of {table} is not device-generable")
        data = raw[c]
        typ = schema[c]
        if f32 and typ.name == "DOUBLE":
            data = data.astype(jnp.float32)
        out[c] = Column(data, None, typ, None)
    return out


# ---------------------------------------------------------------------------
# chunk families (bucketing SPI, consumed by exec/chunked.py)
# ---------------------------------------------------------------------------


DEFAULT_CHUNK_FACT_ROWS = 12_000_000


class _SalesChunkGrid:
    """Chunk grid over a sales-row axis: the sales table streams in
    row ranges aligned to its per-unit stride (ticket/order), the
    returns table streams the exact matching parent ranges."""

    def __init__(self, sf, sales, returns, unit, edges, ret_edges):
        self.sf = sf
        self.sales = sales
        self.returns = returns
        self.unit = unit
        self.edges = edges
        self.ret_edges = ret_edges
        self.nchunks = len(edges) - 1
        self.cap_sales = max(b - a for a, b in zip(edges[:-1], edges[1:]))
        self.cap_returns = max(
            b - a for a, b in zip(ret_edges[:-1], ret_edges[1:]))

    def capacity(self, table: str) -> int:
        return self.cap_sales if table == self.sales else self.cap_returns

    def exchange_bound(self) -> int:
        # per-chunk exchange outputs are reductions of the chunk
        # (aggregates on the bucket key, selective filters, sales x
        # returns matches <= the chunk's return count x small fanout)
        return self.cap_sales // 2

    def bucket_ndv(self) -> int:
        # edges land on unit (ticket/order) boundaries, so a chunk
        # holds at most cap_sales/unit distinct bucket values
        return max(self.cap_sales // max(self.unit, 1), 1)

    def chunk_args(self, i: int):
        return (jnp.asarray(self.edges[i], jnp.int64),
                jnp.asarray(self.edges[i + 1] - self.edges[i], jnp.int32),
                jnp.asarray(self.ret_edges[i], jnp.int64),
                jnp.asarray(self.ret_edges[i + 1] - self.ret_edges[i],
                            jnp.int32))

    def build_scan(self, table: str, cols: List[str], args, f32: bool):
        s0, n_s, r0, n_r = args
        if table == self.sales:
            raw = generate_device(table, self.sf, cols, s0,
                                  self.cap_sales, f32)
            sel = jnp.arange(self.cap_sales) < n_s
        elif table == self.returns:
            raw = generate_device(table, self.sf, cols, r0,
                                  self.cap_returns, f32)
            sel = jnp.arange(self.cap_returns) < n_r
        else:
            raise KeyError(f"{table} is not in the {self.sales} family")
        return raw, sel


class _SalesChunkFamily:
    def __init__(self, name, sales, returns, bucket_cols, unit, sf):
        self.name = name
        self.sales = sales
        self.returns = returns
        self._bucket = bucket_cols  # table -> bucket column
        self.unit = unit
        self.sf = sf

    def tables(self):
        return {self.sales, self.returns}

    def bucket_column(self, table: str) -> str:
        return self._bucket[table]

    def device_columns(self, table: str):
        return DEVICE_COLUMNS[table]

    def make_grid(self, session) -> _SalesChunkGrid:
        chunk_rows = int(session.properties.get(
            "chunk_fact_rows", DEFAULT_CHUNK_FACT_ROWS))
        # interior edges on unit boundaries so every ticket/order's rows
        # land in exactly one chunk (the bucketing colocation property)
        chunk_rows = max(self.unit, chunk_rows - chunk_rows % self.unit)
        total = DS.row_count(self.sales, self.sf)
        total_ret = DS.row_count(self.returns, self.sf)
        edges = list(range(0, total, chunk_rows)) + [total]
        if len(edges) >= 2 and edges[-2] == edges[-1]:
            edges.pop()
        # return j's parent sale is row j*RETURN_EVERY: parents in
        # [a, b) <=> j in [ceil(a/E), ceil(b/E)) — an exact partition
        E = DS.RETURN_EVERY
        ret_edges = [min(-(-a // E), total_ret) for a in edges]
        ret_edges[-1] = total_ret
        return _SalesChunkGrid(self.sf, self.sales, self.returns,
                               self.unit, edges, ret_edges)


def chunk_family(table: str, sf: float):
    """Bucketing metadata for `table`, or None (the connector SPI hook
    TpcdsTable.bucketing delegates to)."""
    if table in ("store_sales", "store_returns"):
        return _SalesChunkFamily(
            "tpcds-store", "store_sales", "store_returns",
            {"store_sales": "ss_ticket_number",
             "store_returns": "sr_ticket_number"},
            DS.ITEMS_PER_TICKET, sf)
    if table in ("catalog_sales", "catalog_returns"):
        return _SalesChunkFamily(
            "tpcds-catalog", "catalog_sales", "catalog_returns",
            {"catalog_sales": "cs_order_number",
             "catalog_returns": "cr_order_number"},
            DS.ITEMS_PER_ORDER, sf)
    return None
