"""Hive-shaped connector: partitioned warehouse tables behind a remote
metastore.

Reference parity: presto-hive — HiveMetadata (schema from the
metastore), HivePartitionManager.getPartitions (partition pruning from
the TupleDomain BEFORE any file IO), HiveSplitManager (one split unit
per partition's files), HivePageSourceProvider dispatching per storage
format, and HiveMetadata.finishInsert + SemiTransactionalHiveMetastore
(INSERT writes files into partition directories and registers new
partitions).  The metastore lives behind HTTP (server/metastore.py) the
way the reference's lives behind thrift — every metadata operation is a
real network round trip.

TPU-first restating: a partition prunes to a boolean decision on the
host (no device work at all), surviving partitions decode columnar and
concatenate into the engine's device batch, and partition-key columns
materialize as constant arrays — the scan feeds the same fixed-shape
Batch every other connector produces.
"""

from __future__ import annotations

import csv
import datetime as _dt
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import Catalog, ConnectorTable
from presto_tpu.server.metastore import (MetastoreClient, MetastoreError,
                                         parse_partition_path,
                                         partition_path)

_EPOCH = _dt.date(1970, 1, 1)

#: types usable as partition keys (reference: HiveUtil.checkPartitionKey
#: supports the primitive types; same trim here)
_PARTITION_TYPES = ("VARCHAR", "BIGINT", "INTEGER", "SMALLINT", "TINYINT",
                    "DOUBLE", "BOOLEAN", "DATE")


def _render_partition_value(v, t: T.Type) -> Optional[str]:
    """Engine value -> directory-name string (None stays None = NULL)."""
    if v is None:
        return None
    if t.name == "DATE":
        return (_EPOCH + _dt.timedelta(days=int(v))).isoformat()
    if t.name == "BOOLEAN":
        return "true" if v else "false"
    if t.is_string:
        return str(v)
    if t.is_integer:
        return str(int(v))
    return repr(float(v))


def _parse_partition_value(s: Optional[str], t: T.Type):
    """Directory-name string -> engine literal-space value (DATE = days,
    matching plan/domains.py literal space for pruning comparisons)."""
    if s is None:
        return None
    if t.name == "DATE":
        return (_dt.date.fromisoformat(s) - _EPOCH).days
    if t.name == "BOOLEAN":
        return s == "true"
    if t.is_string:
        return s
    if t.is_integer:
        return int(s)
    return float(s)


class HivePartition:
    """One resolved partition (reference: HivePartition + Partition)."""

    __slots__ = ("name", "values", "location", "num_rows")

    def __init__(self, name: str, values: list, location: str,
                 num_rows: Optional[int]):
        self.name = name
        self.values = values  # literal-space, aligned w/ partition cols
        self.location = location
        self.num_rows = num_rows


class HiveContext:
    """One attached metastore (client + warehouse root for new tables)."""

    def __init__(self, client: MetastoreClient, warehouse: str):
        self.client = client
        self.warehouse = warehouse


class HiveTable(ConnectorTable):
    """One warehouse table.  Schema = data columns then partition
    columns (the hive layout: partition keys are directory names, not
    file contents)."""

    supports_domain_pushdown = True

    def __init__(self, name: str, ctx: HiveContext, db: str, table: str):
        self.ctx = ctx
        self.db = db
        self.table = table
        doc = ctx.client.get_table(db, table)
        self.format = doc["format"]
        self.location = doc["location"]
        self.data_schema = {c: T.parse_type(t) for c, t in doc["columns"]}
        self.partition_schema = {c: T.parse_type(t)
                                 for c, t in doc["partition_columns"]}
        for c, t in self.partition_schema.items():
            if t.name not in _PARTITION_TYPES:
                raise ValueError(f"partition column '{c}' has "
                                 f"unsupported type {t}")
        super().__init__(name, {**self.data_schema, **self.partition_schema})
        self._part_cache: Optional[Tuple[int, List[HivePartition]]] = None
        self._reader_cache: Dict[str, ConnectorTable] = {}

    # nulls survive INSERT when the file format carries a null channel
    @property
    def supports_null_append(self) -> bool:
        return self.format in ("parquet", "orc")

    # ---- partition metadata (every call = metastore round trip or a
    # sequence-validated cache hit, HivePartitionManager's shape) ------
    def _partitions(self) -> List[HivePartition]:
        seq = self.ctx.client.sequence()
        if self._part_cache is not None and self._part_cache[0] == seq:
            return self._part_cache[1]
        raw, seq2 = self.ctx.client.partitions(self.db, self.table)
        ptypes = list(self.partition_schema.values())
        parts = []
        for p in raw:
            vals = [_parse_partition_value(v, t)
                    for v, t in zip(p["values"], ptypes)]
            nr = p.get("parameters", {}).get("numRows")
            loc = p["location"]
            if not os.path.isabs(loc):
                loc = os.path.join(self.location, loc)
            parts.append(HivePartition(p["name"], vals, loc,
                                       int(nr) if nr is not None else None))
        if not self.partition_schema:
            # unpartitioned: the table location is the single "partition"
            parts = [HivePartition("", [], self.location, None)]
        self._part_cache = (seq2 if seq2 >= 0 else seq, parts)
        return parts

    def _invalidate(self):
        self._part_cache = None
        self._reader_cache = {}
        super()._invalidate()

    # ---- per-partition file access -----------------------------------
    def _reader(self, location: str) -> Optional[ConnectorTable]:
        """Format reader over one partition directory (reference:
        HivePageSourceProvider dispatch on the partition's storage
        format).  None when the partition has no data files yet."""
        r = self._reader_cache.get(location)
        if r is not None:
            return r
        if self.format == "parquet":
            from presto_tpu.connectors.parquet import ParquetTable

            if not any(p.endswith(".parquet")
                       for p in _listdir(location)):
                return None
            r = ParquetTable(self.table, location)
        elif self.format == "orc":
            from presto_tpu.connectors.orc import OrcTable

            if not any(p.endswith(".orc") for p in _listdir(location)):
                return None
            r = OrcTable(self.table, location)
        else:  # csv
            files = [p for p in _listdir(location) if p.endswith(".csv")]
            if not files:
                return None
            r = _CsvPartition(self.table,
                              [os.path.join(location, p) for p in files],
                              self.data_schema)
        self._reader_cache[location] = r
        return r

    def _partition_rows(self, part: HivePartition) -> int:
        if part.num_rows is not None:
            return part.num_rows
        r = self._reader(part.location)
        return 0 if r is None else r.row_count()

    # ---- metadata SPI ------------------------------------------------
    def row_count(self) -> int:
        return sum(self._partition_rows(p) for p in self._partitions())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        """Partition boundaries are the split grain (reference:
        HiveSplitManager produces splits per partition's files)."""
        edges = [0]
        for p in self._partitions():
            n = self._partition_rows(p)
            if n:
                edges.append(edges[-1] + n)
        if len(edges) <= 1:
            return []
        if len(edges) - 1 > n_splits:
            keep = np.linspace(0, len(edges) - 1, n_splits + 1).astype(int)
            edges = [edges[i] for i in sorted(set(keep.tolist()))]
        return [(a, b) for a, b in zip(edges[:-1], edges[1:]) if a < b]

    def column_stats(self, column: str):
        from presto_tpu.plan.stats import ColStats

        if column in self.partition_schema:
            vals = [p.values[list(self.partition_schema).index(column)]
                    for p in self._partitions()]
            vals = [v for v in vals if v is not None]
            if not vals:
                return ColStats(ndv=0)
            if self.partition_schema[column].is_string:
                return ColStats(ndv=len(set(vals)))
            return ColStats(min=float(min(vals)), max=float(max(vals)),
                            ndv=len(set(vals)))
        return None

    # ---- read path ---------------------------------------------------
    def read(self, columns=None, split=None,
             domains=None) -> Dict[str, np.ndarray]:
        """Partition pruning happens FIRST, on metadata alone (the
        reference's HivePartitionManager.getPartitions over the
        TupleDomain); only surviving partitions open files, where the
        format reader applies the remaining data-column domains at
        stripe/row-group granularity."""
        cols = columns if columns is not None else list(self.schema)
        pcols = list(self.partition_schema)
        data_cols = [c for c in cols if c not in self.partition_schema]
        data_domains = {c: d for c, d in (domains or {}).items()
                        if c not in self.partition_schema} or None
        counters = {"partitions_total": 0, "partitions_read": 0,
                    "groups_total": 0, "groups_read": 0,
                    "bytes_total": 0, "bytes_read": 0}
        a, b = split if split is not None else (0, None)
        parts_out: Dict[str, list] = {c: [] for c in cols}
        base = 0
        for part in self._partitions():
            n = self._partition_rows(part)
            if n == 0:
                continue
            counters["partitions_total"] += 1
            hi = base + n
            lo_r, hi_r = max(base, a), (hi if b is None else min(hi, b))
            base = hi
            if lo_r >= hi_r:
                continue
            if not self._partition_matches(part, domains, pcols):
                continue
            counters["partitions_read"] += 1
            r = self._reader(part.location)
            if r is None:
                continue
            sub = (lo_r - (hi - n), hi_r - (hi - n))
            if data_cols:
                if getattr(r, "supports_domain_pushdown", False):
                    data = r.read(data_cols, split=sub,
                                  domains=data_domains)
                    for k in ("groups_total", "groups_read",
                              "bytes_total", "bytes_read"):
                        counters[k] += r.last_scan_counters.get(k, 0)
                else:
                    data = r.read(data_cols, split=sub)
                got = len(next(iter(data.values())))
            else:
                data = {}
                got = sub[1] - sub[0]
            for c in data_cols:
                parts_out[c].append(self._coerce_decl(data[c],
                                                      self.data_schema[c]))
            for c in cols:
                if c in self.partition_schema:
                    v = part.values[pcols.index(c)]
                    parts_out[c].append(_constant_column(
                        v, self.partition_schema[c], got))
        self.last_scan_counters = counters
        out = {}
        for c in cols:
            ps = parts_out[c]
            if not ps:
                t = self.schema[c]
                out[c] = np.empty(0, object if t.is_string
                                  else t.numpy_dtype())
            elif any(isinstance(p, np.ma.MaskedArray) for p in ps):
                out[c] = np.ma.concatenate(ps)
            else:
                out[c] = np.concatenate(ps)
        return out

    @staticmethod
    def _coerce_decl(a: np.ndarray, t: T.Type) -> np.ndarray:
        """File dtype -> declared dtype (a CSV partition infers BIGINT
        where the table declares INTEGER, etc.)."""
        if t.is_string or a.dtype == object:
            return a
        want = t.numpy_dtype()
        if a.dtype == want:
            return a
        if isinstance(a, np.ma.MaskedArray):
            return np.ma.masked_array(a.data.astype(want), a.mask)
        return a.astype(want)

    def _partition_matches(self, part: HivePartition, domains,
                           pcols: List[str]) -> bool:
        if not domains:
            return True
        for c, dom in domains.items():
            if c not in self.partition_schema:
                continue
            v = part.values[pcols.index(c)]
            if v is None:
                # a NULL partition key matches no range/point domain
                # (comparisons with NULL are never TRUE)
                return False
            if dom.values is not None:
                if v not in dom.values:
                    return False
            else:
                if dom.lo is not None and v < dom.lo:
                    return False
                if dom.hi is not None and v > dom.hi:
                    return False
        return True

    # ---- write path (reference: HiveMetadata.finishInsert +
    # HiveWriterFactory one writer per partition) ----------------------
    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        pcols = list(self.partition_schema)
        ptypes = list(self.partition_schema.values())
        # pre-insert row counts, BEFORE any file lands: a reader built
        # after the write would see the new file and double-count (and a
        # sync'd partition without numRows must count its files, not 0)
        prev_rows = {p.name: self._partition_rows(p)
                     for p in self._partitions()}
        new_parts = []
        for key, sel in _group_by_partition(arrays, pcols, n).items():
            strs = [_render_partition_value(v, t)
                    for v, t in zip(key, ptypes)]
            rel = partition_path(pcols, strs) if pcols else ""
            pdir = os.path.join(self.location, rel) if rel \
                else self.location
            os.makedirs(pdir, exist_ok=True)
            rows = {c: arrays[c][sel] for c in self.data_schema}
            self._write_file(pdir, rows)
            new_parts.append({"values": strs, "location": rel,
                              "parameters": {"numRows":
                                             prev_rows.get(rel, 0)
                                             + len(sel)}})
        if pcols:
            self.ctx.client.add_partitions(self.db, self.table, new_parts)
        else:
            self.ctx.client.update_parameters(
                self.db, self.table,
                {"numRows": prev_rows.get("", 0) + n})
        self._invalidate()
        return n

    def _write_file(self, pdir: str, rows: Dict[str, np.ndarray]) -> None:
        # unique writer id, not len(listdir): concurrent writers sharing
        # the metastore must never clobber each other's part files
        # (reference: HiveWriterFactory's per-writer UUID file names)
        import uuid

        stem = f"part_{uuid.uuid4().hex[:16]}"
        if self.format == "parquet":
            from presto_tpu.storage.parquet import write_parquet

            write_parquet(os.path.join(pdir, stem + ".parquet"),
                          rows, self.data_schema)
        elif self.format == "orc":
            from presto_tpu.storage.orc import write_orc

            write_orc(os.path.join(pdir, stem + ".orc"),
                      rows, self.data_schema)
        else:
            _write_csv(os.path.join(pdir, stem + ".csv"),
                       rows, self.data_schema)

    def drop_data(self) -> None:
        """DROP TABLE: metastore entry first, THEN data files — if the
        metastore is unreachable the data survives intact (the
        reference's HiveMetadata.dropTable commits metadata before the
        recursive delete)."""
        import shutil

        try:
            self.ctx.client.drop_table(self.db, self.table)
        except MetastoreError as e:
            if e.status != 404:  # already gone is fine
                raise
        if os.path.isdir(self.location):
            shutil.rmtree(self.location, ignore_errors=True)

    # ---- partition repair (reference: the hive procedure
    # system.sync_partition_metadata / MSCK REPAIR) --------------------
    def sync_partition_metadata(self) -> List[str]:
        """Register partition directories found on disk but missing
        from the metastore.  Returns the added partition names."""
        pcols = list(self.partition_schema)
        if not pcols:
            return []
        known = {p.name for p in self._partitions()}
        found = []

        def walk(d: str, depth: int, rel: str):
            if depth == len(pcols):
                if rel not in known and _listdir(
                        os.path.join(self.location, rel)):
                    found.append(rel)
                return
            for e in _listdir(d):
                if e.startswith(f"{pcols[depth]}="):
                    walk(os.path.join(d, e), depth + 1,
                         f"{rel}/{e}" if rel else e)

        walk(self.location, 0, "")
        if found:
            self.ctx.client.add_partitions(
                self.db, self.table,
                [{"values": parse_partition_path(rel), "location": rel,
                  "parameters": {}} for rel in found])
            self._invalidate()
        return sorted(found)


def _group_by_partition(arrays: Dict[str, np.ndarray], pcols: List[str],
                        n: int) -> Dict[tuple, np.ndarray]:
    """{partition-value tuple: row indices}, vectorized — factorize each
    partition column (code 0 = NULL), pair codes into one key, one
    np.unique over the combined key.  A per-row Python loop here would
    dominate large partitioned INSERT/CTAS."""
    if not pcols:
        return {(): np.arange(n)}
    codes, uniques = [], []
    for c in pcols:
        a = arrays[c]
        mask = np.ma.getmaskarray(a) if isinstance(a, np.ma.MaskedArray) \
            else np.zeros(n, bool)
        data = np.ma.getdata(a)
        if mask.any():
            # masked slots may hold unorderable fill (None in object
            # arrays); give them a sortable placeholder — code 0 wins
            data = data.copy()
            data[mask] = "" if data.dtype == object else data.dtype.type(0)
        u, inv = np.unique(data, return_inverse=True)
        inv = inv.astype(np.int64) + 1
        inv[mask] = 0
        codes.append(inv)
        uniques.append(u)
    combined = codes[0]
    for code, u in zip(codes[1:], uniques[1:]):
        combined = combined * (len(u) + 1) + code
    _, first, inv = np.unique(combined, return_index=True,
                              return_inverse=True)
    order = np.argsort(inv, kind="stable")
    bounds = np.searchsorted(inv[order], np.arange(len(first) + 1))
    out: Dict[tuple, np.ndarray] = {}
    for g, i0 in enumerate(first):
        key = []
        for code, u in zip(codes, uniques):
            if code[i0] == 0:
                key.append(None)
            else:
                v = u[code[i0] - 1]
                key.append(v.item() if isinstance(v, np.generic) else v)
        out[tuple(key)] = order[bounds[g]:bounds[g + 1]]
    return out


def _listdir(d: str) -> List[str]:
    try:
        return sorted(os.listdir(d))
    except FileNotFoundError:
        return []


def _constant_column(v, t: T.Type, n: int) -> np.ndarray:
    """A partition-key value as an n-row column."""
    if v is None:
        base = np.zeros(n, object if t.is_string else t.numpy_dtype())
        return np.ma.masked_array(base, mask=np.ones(n, bool))
    if t.is_string:
        a = np.empty(n, object)
        a[:] = str(v)
        return a
    return np.full(n, v, t.numpy_dtype())


# ---------------------------------------------------------------------
# CSV partition files (hive text format)
# ---------------------------------------------------------------------

class _CsvPartition(ConnectorTable):
    """Headerless CSV files in one partition directory, decoded against
    the table schema (hive's text SerDe is schema-on-read; headers live
    in the metastore, not the file)."""

    def __init__(self, name: str, files: List[str],
                 schema: Dict[str, T.Type]):
        super().__init__(name, schema)
        self.files = files
        self._cache: Optional[Dict[str, np.ndarray]] = None

    def _data(self) -> Dict[str, np.ndarray]:
        if self._cache is None:
            from presto_tpu.connectors.textfile import _coerce

            cols: Dict[str, list] = {c: [] for c in self.schema}
            names = list(self.schema)
            for path in self.files:
                with open(path, newline="", encoding="utf-8") as f:
                    for row in csv.reader(f):
                        for c, v in zip(names, row):
                            cols[c].append(v if v != "" else None)
                        for c in names[len(row):]:
                            cols[c].append(None)
            self._cache = {c: _coerce(cols[c], t)
                           for c, t in self.schema.items()}
        return self._cache

    def row_count(self) -> int:
        return len(next(iter(self._data().values()))) if self.schema else 0

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        data = self._data()
        a, b = split if split is not None else (0, self.row_count())
        return {c: data[c][a:b] for c in cols}


def _write_csv(path: str, rows: Dict[str, np.ndarray],
               schema: Dict[str, T.Type]) -> None:
    n = len(next(iter(rows.values()))) if rows else 0
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        cols = list(schema)
        for i in range(n):
            rec = []
            for c in cols:
                a = rows[c]
                if isinstance(a, np.ma.MaskedArray) and \
                        np.ma.getmaskarray(a)[i]:
                    rec.append("")
                    continue
                v = a[i]
                t = schema[c]
                if t.name == "DATE":
                    v = (_EPOCH + _dt.timedelta(days=int(v))).isoformat()
                elif isinstance(v, np.generic):
                    v = v.item()
                rec.append(v)
            w.writerow(rec)


# ---------------------------------------------------------------------
# catalog attachment + DDL entry points
# ---------------------------------------------------------------------

def attach_hive(catalog: Catalog, metastore_uri: str,
                catalog_name: str = "hive",
                warehouse: Optional[str] = None,
                secret: Optional[str] = None) -> List[str]:
    """Discover and register every table the metastore knows
    (reference: HiveMetadata.listTables driving the catalog).  Tables
    register qualified `<catalog>.<db>.<table>`; CREATE TABLE under the
    claimed prefix routes to this connector."""
    client = MetastoreClient(metastore_uri, secret=secret)
    ctx = HiveContext(client, warehouse or "")
    registered = []
    for db in client.databases():
        for tbl in client.tables(db):
            qualified = f"{catalog_name}.{db}.{tbl}"
            t = HiveTable(qualified, ctx, db, tbl)
            catalog.tables[qualified] = t
            t._catalog = catalog
            registered.append(qualified)
    catalog.version += 1
    catalog.known_qualifiers.add(catalog_name)
    catalog.claimed_prefixes.add(catalog_name)
    if not hasattr(catalog, "hive_contexts"):
        catalog.hive_contexts = {}
    catalog.hive_contexts[catalog_name] = ctx
    return registered


def create_hive_table(catalog: Catalog, name: str,
                      schema: Dict[str, T.Type],
                      properties: dict) -> HiveTable:
    """CREATE TABLE <prefix>.<db>.<t> (...) WITH (format='parquet',
    partitioned_by='dt,region') — reference: HiveMetadata.createTable
    (partition columns must be declared and are moved to the end, the
    hive rule; partitioned_by is comma-separated)."""
    parts = name.lower().split(".")
    ctxs = getattr(catalog, "hive_contexts", {})
    if not parts or parts[0] not in ctxs:
        raise ValueError(f"no hive catalog attached for '{name}'")
    ctx = ctxs[parts[0]]
    if len(parts) == 3:
        db, tbl = parts[1], parts[2]
    elif len(parts) == 2:
        db, tbl = "default", parts[1]
    else:
        raise ValueError(f"hive table name must be "
                         f"<catalog>.<db>.<table>: '{name}'")
    fmt = str(properties.get("format", "parquet")).lower()
    pby = properties.get("partitioned_by", "")
    pcols = [c.strip().lower() for c in str(pby).split(",") if c.strip()]
    unknown = [c for c in pcols if c not in schema]
    if unknown:
        raise ValueError(f"partitioned_by columns not declared: {unknown}")
    data_cols = [(c, str(t)) for c, t in schema.items() if c not in pcols]
    part_cols = [(c, str(schema[c])) for c in pcols]
    if not data_cols:
        raise ValueError("hive table needs at least one data column")
    location = properties.get("location") or properties.get("path")
    if not location:
        if not ctx.warehouse:
            raise ValueError("hive catalog has no warehouse root; pass "
                             "WITH (location = '...')")
        location = os.path.join(ctx.warehouse, db, tbl)
    os.makedirs(location, exist_ok=True)
    if db not in ctx.client.databases():
        ctx.client.create_database(db)
    ctx.client.create_table(db, tbl, {
        "columns": data_cols, "partition_columns": part_cols,
        "format": fmt, "location": os.path.abspath(location),
        "parameters": {}})
    qualified = f"{parts[0]}.{db}.{tbl}"
    t = HiveTable(qualified, ctx, db, tbl)
    catalog.tables[qualified] = t
    t._catalog = catalog
    catalog.version += 1
    return t


def is_hive_name(catalog: Catalog, name: str) -> bool:
    parts = name.lower().split(".")
    return bool(parts) and parts[0] in getattr(catalog, "hive_contexts", {})
