"""TPC-DS data generator connector.

Reference parity: presto-tpcds (TpcdsConnectorFactory, TpcdsRecordSet —
the reference wraps the Teradata dsdgen library).  Like the TPC-H
connector (connectors/tpch.py) this is a deterministic *counter-based*
vectorized generator: every (table, column, row) maps to one splitmix64
draw, so any row range of any table is independently generable (the
split-parallel scan property).  Faithful to the TPC-DS schema (column
names/types per the spec) and key relationships (valid FK ranges;
returns reference their parent sale's item/ticket/customer/prices); NOT
bit-identical to dsdgen — correctness testing is differential against
sqlite over identical generated data.

Covered tables: ALL 24 of the TPC-DS schema — every dimension
(date_dim, time_dim, item, customer, customer_address,
customer_demographics, household_demographics, income_band, promotion,
reason, ship_mode, store, warehouse, web_site, web_page, call_center,
catalog_page) and every fact channel (store_sales/store_returns,
catalog_sales/catalog_returns, web_sales/web_returns, inventory),
enough for the full 99-query differential corpus.

Row counts at SF1 follow the spec (store_sales 2,880,404; catalog_sales
1,441,548; returns ~10% of sales).  Fixed-size dimensions
(date_dim, household_demographics, income_band) do not scale;
customer_demographics (spec-fixed 1,920,800) is scaled below SF1 to keep
test fixtures small — FK validity is preserved at every scale.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.tpch import _colkey, _splitmix64

# ---------------------------------------------------------------------------
# counter-based draw helpers (distinct key-space from TPC-H via "tpcds/")
# ---------------------------------------------------------------------------


def _round(x, decimals=2):
    """np.round with explicit scale / rint / reciprocal-multiply.
    XLA rewrites division by a constant into multiplication by its
    reciprocal under jit; the device fact generator (tpcds_device.py)
    therefore multiplies by 0.01, and the host must do the SAME or the
    two diverge by 1 ULP per money value (np.round divides)."""
    s = 10.0 ** decimals
    return np.rint(x * s) * (1.0 / s)


def _raw_at(table, col, rows, k=1):
    """(len(rows), k) uniform doubles in [0,1) for explicit row indices —
    the strided-access generalization the returns tables need to read
    their parent sale's draws."""
    with np.errstate(over="ignore"):
        r = np.asarray(rows, dtype=np.uint64)[:, None]
        draws = np.arange(k, dtype=np.uint64)[None, :]
        ctr = (r * np.uint64(k) + draws
               + _colkey("tpcds/" + table, col) * np.uint64(0x632BE59BD9B4E019))
        u = _splitmix64(ctr)
    return (u >> np.uint64(11)).astype(np.float64) * (2.0**-53)


def _raw(table, col, row0, n, k=1):
    return _raw_at(table, col, np.arange(row0, row0 + n, dtype=np.uint64), k)


def _u_at(table, col, rows, lo, hi, dtype=np.int64):
    return (lo + np.floor(_raw_at(table, col, rows)[:, 0] * (hi - lo + 1))).astype(dtype)


def _u(table, col, row0, n, lo, hi, dtype=np.int64):
    return _u_at(table, col, np.arange(row0, row0 + n, dtype=np.uint64), lo, hi, dtype)


def _money_at(table, col, rows, lo_cents, hi_cents):
    return _u_at(table, col, rows, lo_cents, hi_cents) * 0.01


def _money(table, col, row0, n, lo_cents, hi_cents):
    return _u(table, col, row0, n, lo_cents, hi_cents) * 0.01


def _pick_at(table, col, rows, choices):
    idx = _u_at(table, col, rows, 0, len(choices) - 1, np.int32)
    return np.asarray(choices, dtype=object)[idx]


def _pick(table, col, row0, n, choices):
    return _pick_at(table, col, np.arange(row0, row0 + n, dtype=np.uint64), choices)


def _numbered(prefix: str, keys: np.ndarray, width: int = 16) -> np.ndarray:
    return np.char.add(prefix, np.char.zfill(keys.astype(str), width)).astype(object)


# ---------------------------------------------------------------------------
# vocabularies (spec-flavored)
# ---------------------------------------------------------------------------

COLORS = ("almond antique aquamarine azure beige bisque black blanched blue "
          "blush brown burlywood burnished chartreuse chiffon chocolate coral "
          "cornflower cornsilk cream cyan dark deep dim dodger drab firebrick "
          "floral forest frosted gainsboro ghost goldenrod green grey honeydew "
          "hot indian ivory khaki lace lavender lawn lemon light lime linen "
          "magenta maroon medium metallic midnight mint misty moccasin navajo "
          "navy olive orange orchid pale papaya peach peru pink plum powder "
          "puff purple red rose rosy royal saddle salmon sandy seashell sienna "
          "sky slate smoke snow spring steel tan thistle tomato turquoise "
          "violet wheat white yellow").split()
CATEGORIES = ["Women", "Men", "Children", "Shoes", "Music", "Jewelry",
              "Home", "Sports", "Books", "Electronics"]
CLASSES = ["accessories", "classical", "pants", "shirts", "dresses",
           "earings", "bedding", "fishing", "mystery", "portable",
           "athletic", "maternity", "country", "swimwear", "romance"]
BRAND_SYL = ["amalg", "edu pack", "exporti", "importo", "scholar",
             "brand", "corp", "maxi", "univ", "nameless"]
UNITS = ["Unknown", "Each", "Dozen", "Case", "Pallet", "Gross", "Box",
         "Pound", "Ounce", "Ton", "Tbl", "Oz", "Lb", "Dram", "Carton",
         "Cup", "Gram", "Bunch", "Tsp", "N/A", "Bundle"]
CONTAINERS = ["Unknown"]
SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"]
FIRST_NAMES = ("James John Robert Michael William David Richard Charles "
               "Joseph Thomas Mary Patricia Linda Barbara Elizabeth Jennifer "
               "Maria Susan Margaret Dorothy Lisa Nancy Karen Betty Helen "
               "Sandra Donna Carol Ruth Sharon").split()
LAST_NAMES = ("Smith Johnson Williams Jones Brown Davis Miller Wilson Moore "
              "Taylor Anderson Thomas Jackson White Harris Martin Thompson "
              "Garcia Martinez Robinson Clark Rodriguez Lewis Lee Walker "
              "Hall Allen Young Hernandez King").split()
COUNTRIES = ["UNITED STATES"]
STATES = ("AL AK AZ AR CA CO CT DE FL GA HI ID IL IN IA KS KY LA ME MD MA MI "
          "MN MS MO MT NE NV NH NJ NM NY NC ND OH OK OR PA RI SC SD TN TX UT "
          "VT VA WA WV WI WY").split()
CITIES = ("Midway Fairview Oakland Salem Franklin Greenville Bridgeport "
          "Springdale Oak_Grove Centerville Riverside Clinton Georgetown "
          "Marion Five_Points Liberty Greenwood Oakdale Glendale Union "
          "Pleasant_Hill Lebanon Summit Ashland Lakeview").split()
STREET_NAMES = ("Main Oak Park First Second Third Fourth Fifth Sixth Seventh "
                "Eighth Ninth Tenth Elm Maple Cedar Pine Spruce Walnut Lake "
                "Hill River Ridge View Sunset Washington Jefferson Lincoln "
                "Jackson Williams Smith Davis College Church Center Mill "
                "Railroad Dogwood Birch Hickory Laurel Willow Broadway Green "
                "Forest Meadow Highland Valley Spring North South East West "
                "Locust Chestnut Poplar Sycamore Johnson Franklin Madison "
                "Adams 1st 2nd 3rd 4th 5th 6th 7th 8th 9th 10th 11th 12th "
                "13th 14th 15th Wilson Lee College_Park").split()
STREET_TYPES = ["Street", "Ave", "Blvd", "Boulevard", "Circle", "Cir", "Court",
                "Ct", "Drive", "Dr", "Lane", "Ln", "Parkway", "Pkwy", "Road",
                "RD", "ST", "Way", "Wy"]
GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
                 "Unknown"]
REASONS = ["Package was damaged", "Stopped working", "Did not fit",
           "Not the product that was ordred", "Parts missing",
           "Does not work with a product that I have",
           "Gift exchange", "Did not like the color",
           "Did not like the model", "Did not like the make",
           "Found a better price in a store", "Found a better extension",
           "Not working any more", "unauthoized purchase",
           "duplicate purchase", "no service location",
           "wrong size", "lost my job", "it is a boring product",
           "found a better price elsewhere", "reason 21", "reason 22",
           "reason 23", "reason 24", "reason 25", "reason 26", "reason 27",
           "reason 28", "reason 29", "reason 30", "reason 31", "reason 32",
           "reason 33", "reason 34", "reason 35"]
SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
SHIP_CODES = ["AIR", "SURFACE", "SEA"]
CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
            "ZOUROS", "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN",
            "BOXBUNDLES", "GERMA", "HARMSTORF", "PRIVATECARRIER", "DIAMOND",
            "RUPEKSA", "GREAT EASTERN"]
PROMO_PURPOSE = ["Unknown"]

EPOCH = np.datetime64("1970-01-01", "D")
DATE_DIM_START = np.datetime64("1900-01-01", "D")
DATE_DIM_ROWS = 73049  # 1900-01-01 .. 2099-12-31 per spec
JULIAN_OF_START = 2415021  # d_date_sk of 1900-01-01 (Julian day number)
# sales span: 1998-01-01 .. 2002-12-31 (spec's active range)
SALES_DATE_LO = JULIAN_OF_START + int(
    (np.datetime64("1998-01-01") - DATE_DIM_START) / np.timedelta64(1, "D"))
SALES_DATE_HI = JULIAN_OF_START + int(
    (np.datetime64("2002-12-31") - DATE_DIM_START) / np.timedelta64(1, "D"))

ITEMS_PER_TICKET = 3      # store_sales rows sharing one ticket/customer
ITEMS_PER_ORDER = 4       # catalog_sales rows sharing one order/customer
RETURN_EVERY = 10         # every 10th sale row is returned

_SF1_ROWS = {
    "store_sales": 2_880_404,
    "catalog_sales": 1_441_548,
    "web_sales": 719_384,
    "customer": 100_000,
    "customer_address": 50_000,
    "item": 18_000,
    "store": 12,
    "promotion": 300,
    "warehouse": 5,
    "web_site": 30,
    "web_page": 60,
    "call_center": 6,
    "catalog_page": 11_718,
}
_FIXED_ROWS = {
    "date_dim": DATE_DIM_ROWS,
    "household_demographics": 7_200,
    "income_band": 20,
    "reason": 35,
    "ship_mode": 20,
    "time_dim": 86_400,
}
CD_CROSS = 1_920_800  # spec-fixed cross product of the 7 cd attributes


def row_count(table: str, sf: float) -> int:
    if table in _FIXED_ROWS:
        return _FIXED_ROWS[table]
    if table == "customer_demographics":
        return CD_CROSS if sf >= 1 else max(7_200, int(CD_CROSS * sf))
    if table == "store_returns":
        return row_count("store_sales", sf) // RETURN_EVERY
    if table == "catalog_returns":
        return row_count("catalog_sales", sf) // RETURN_EVERY
    if table == "web_returns":
        return row_count("web_sales", sf) // RETURN_EVERY
    if table == "inventory":
        # weekly snapshots; items capped sub-linearly like the spec
        # (inventory is ~400M at SF100, not items*weeks*warehouses linear)
        return INV_WEEKS * _inv_items(sf) * row_count("warehouse", sf)
    base = _SF1_ROWS[table]
    if table in ("store", "warehouse", "promotion", "web_site", "web_page",
                 "call_center"):
        return max(base, int(base * max(sf, 1) ** 0.5))
    if table == "catalog_page":
        return base  # spec: page count grows sub-linearly; fixed here
    return max(1, int(base * sf))


SCHEMAS = {
    "date_dim": {
        "d_date_sk": T.BIGINT, "d_date_id": T.VARCHAR, "d_date": T.DATE,
        "d_month_seq": T.INTEGER, "d_week_seq": T.INTEGER,
        "d_quarter_seq": T.INTEGER, "d_year": T.INTEGER, "d_dow": T.INTEGER,
        "d_moy": T.INTEGER, "d_dom": T.INTEGER, "d_qoy": T.INTEGER,
        "d_fy_year": T.INTEGER, "d_fy_quarter_seq": T.INTEGER,
        "d_fy_week_seq": T.INTEGER, "d_day_name": T.VARCHAR,
        "d_quarter_name": T.VARCHAR, "d_holiday": T.VARCHAR,
        "d_weekend": T.VARCHAR, "d_following_holiday": T.VARCHAR,
        "d_first_dom": T.INTEGER, "d_last_dom": T.INTEGER,
        "d_same_day_ly": T.INTEGER, "d_same_day_lq": T.INTEGER,
        "d_current_day": T.VARCHAR, "d_current_week": T.VARCHAR,
        "d_current_month": T.VARCHAR, "d_current_quarter": T.VARCHAR,
        "d_current_year": T.VARCHAR,
    },
    "item": {
        "i_item_sk": T.BIGINT, "i_item_id": T.VARCHAR,
        "i_rec_start_date": T.DATE, "i_rec_end_date": T.DATE,
        "i_item_desc": T.VARCHAR, "i_current_price": T.DOUBLE,
        "i_wholesale_cost": T.DOUBLE, "i_brand_id": T.INTEGER,
        "i_brand": T.VARCHAR, "i_class_id": T.INTEGER, "i_class": T.VARCHAR,
        "i_category_id": T.INTEGER, "i_category": T.VARCHAR,
        "i_manufact_id": T.INTEGER, "i_manufact": T.VARCHAR,
        "i_size": T.VARCHAR, "i_formulation": T.VARCHAR, "i_color": T.VARCHAR,
        "i_units": T.VARCHAR, "i_container": T.VARCHAR,
        "i_manager_id": T.INTEGER, "i_product_name": T.VARCHAR,
    },
    "customer": {
        "c_customer_sk": T.BIGINT, "c_customer_id": T.VARCHAR,
        "c_current_cdemo_sk": T.BIGINT, "c_current_hdemo_sk": T.BIGINT,
        "c_current_addr_sk": T.BIGINT, "c_first_shipto_date_sk": T.BIGINT,
        "c_first_sales_date_sk": T.BIGINT, "c_salutation": T.VARCHAR,
        "c_first_name": T.VARCHAR, "c_last_name": T.VARCHAR,
        "c_preferred_cust_flag": T.VARCHAR, "c_birth_day": T.INTEGER,
        "c_birth_month": T.INTEGER, "c_birth_year": T.INTEGER,
        "c_birth_country": T.VARCHAR, "c_login": T.VARCHAR,
        "c_email_address": T.VARCHAR, "c_last_review_date_sk": T.BIGINT,
    },
    "customer_address": {
        "ca_address_sk": T.BIGINT, "ca_address_id": T.VARCHAR,
        "ca_street_number": T.VARCHAR, "ca_street_name": T.VARCHAR,
        "ca_street_type": T.VARCHAR, "ca_suite_number": T.VARCHAR,
        "ca_city": T.VARCHAR, "ca_county": T.VARCHAR, "ca_state": T.VARCHAR,
        "ca_zip": T.VARCHAR, "ca_country": T.VARCHAR,
        "ca_gmt_offset": T.DOUBLE, "ca_location_type": T.VARCHAR,
    },
    "customer_demographics": {
        "cd_demo_sk": T.BIGINT, "cd_gender": T.VARCHAR,
        "cd_marital_status": T.VARCHAR, "cd_education_status": T.VARCHAR,
        "cd_purchase_estimate": T.INTEGER, "cd_credit_rating": T.VARCHAR,
        "cd_dep_count": T.INTEGER, "cd_dep_employed_count": T.INTEGER,
        "cd_dep_college_count": T.INTEGER,
    },
    "household_demographics": {
        "hd_demo_sk": T.BIGINT, "hd_income_band_sk": T.BIGINT,
        "hd_buy_potential": T.VARCHAR, "hd_dep_count": T.INTEGER,
        "hd_vehicle_count": T.INTEGER,
    },
    "income_band": {
        "ib_income_band_sk": T.BIGINT, "ib_lower_bound": T.INTEGER,
        "ib_upper_bound": T.INTEGER,
    },
    "promotion": {
        "p_promo_sk": T.BIGINT, "p_promo_id": T.VARCHAR,
        "p_start_date_sk": T.BIGINT, "p_end_date_sk": T.BIGINT,
        "p_item_sk": T.BIGINT, "p_cost": T.DOUBLE,
        "p_response_target": T.INTEGER, "p_promo_name": T.VARCHAR,
        "p_channel_dmail": T.VARCHAR, "p_channel_email": T.VARCHAR,
        "p_channel_catalog": T.VARCHAR, "p_channel_tv": T.VARCHAR,
        "p_channel_radio": T.VARCHAR, "p_channel_press": T.VARCHAR,
        "p_channel_event": T.VARCHAR, "p_channel_demo": T.VARCHAR,
        "p_channel_details": T.VARCHAR, "p_purpose": T.VARCHAR,
        "p_discount_active": T.VARCHAR,
    },
    "store": {
        "s_store_sk": T.BIGINT, "s_store_id": T.VARCHAR,
        "s_rec_start_date": T.DATE, "s_rec_end_date": T.DATE,
        "s_closed_date_sk": T.BIGINT, "s_store_name": T.VARCHAR,
        "s_number_employees": T.INTEGER, "s_floor_space": T.INTEGER,
        "s_hours": T.VARCHAR, "s_manager": T.VARCHAR, "s_market_id": T.INTEGER,
        "s_geography_class": T.VARCHAR, "s_market_desc": T.VARCHAR,
        "s_market_manager": T.VARCHAR, "s_division_id": T.INTEGER,
        "s_division_name": T.VARCHAR, "s_company_id": T.INTEGER,
        "s_company_name": T.VARCHAR, "s_street_number": T.VARCHAR,
        "s_street_name": T.VARCHAR, "s_street_type": T.VARCHAR,
        "s_suite_number": T.VARCHAR, "s_city": T.VARCHAR, "s_county": T.VARCHAR,
        "s_state": T.VARCHAR, "s_zip": T.VARCHAR, "s_country": T.VARCHAR,
        "s_gmt_offset": T.DOUBLE, "s_tax_precentage": T.DOUBLE,
    },
    "reason": {
        "r_reason_sk": T.BIGINT, "r_reason_id": T.VARCHAR,
        "r_reason_desc": T.VARCHAR,
    },
    "ship_mode": {
        "sm_ship_mode_sk": T.BIGINT, "sm_ship_mode_id": T.VARCHAR,
        "sm_type": T.VARCHAR, "sm_code": T.VARCHAR, "sm_carrier": T.VARCHAR,
        "sm_contract": T.VARCHAR,
    },
    "warehouse": {
        "w_warehouse_sk": T.BIGINT, "w_warehouse_id": T.VARCHAR,
        "w_warehouse_name": T.VARCHAR, "w_warehouse_sq_ft": T.INTEGER,
        "w_street_number": T.VARCHAR, "w_street_name": T.VARCHAR,
        "w_street_type": T.VARCHAR, "w_suite_number": T.VARCHAR,
        "w_city": T.VARCHAR, "w_county": T.VARCHAR, "w_state": T.VARCHAR,
        "w_zip": T.VARCHAR, "w_country": T.VARCHAR, "w_gmt_offset": T.DOUBLE,
    },
    "store_sales": {
        "ss_sold_date_sk": T.BIGINT, "ss_sold_time_sk": T.BIGINT,
        "ss_item_sk": T.BIGINT, "ss_customer_sk": T.BIGINT,
        "ss_cdemo_sk": T.BIGINT, "ss_hdemo_sk": T.BIGINT,
        "ss_addr_sk": T.BIGINT, "ss_store_sk": T.BIGINT,
        "ss_promo_sk": T.BIGINT, "ss_ticket_number": T.BIGINT,
        "ss_quantity": T.INTEGER, "ss_wholesale_cost": T.DOUBLE,
        "ss_list_price": T.DOUBLE, "ss_sales_price": T.DOUBLE,
        "ss_ext_discount_amt": T.DOUBLE, "ss_ext_sales_price": T.DOUBLE,
        "ss_ext_wholesale_cost": T.DOUBLE, "ss_ext_list_price": T.DOUBLE,
        "ss_ext_tax": T.DOUBLE, "ss_coupon_amt": T.DOUBLE,
        "ss_net_paid": T.DOUBLE, "ss_net_paid_inc_tax": T.DOUBLE,
        "ss_net_profit": T.DOUBLE,
    },
    "store_returns": {
        "sr_returned_date_sk": T.BIGINT, "sr_return_time_sk": T.BIGINT,
        "sr_item_sk": T.BIGINT, "sr_customer_sk": T.BIGINT,
        "sr_cdemo_sk": T.BIGINT, "sr_hdemo_sk": T.BIGINT,
        "sr_addr_sk": T.BIGINT, "sr_store_sk": T.BIGINT,
        "sr_reason_sk": T.BIGINT, "sr_ticket_number": T.BIGINT,
        "sr_return_quantity": T.INTEGER, "sr_return_amt": T.DOUBLE,
        "sr_return_tax": T.DOUBLE, "sr_return_amt_inc_tax": T.DOUBLE,
        "sr_fee": T.DOUBLE, "sr_return_ship_cost": T.DOUBLE,
        "sr_refunded_cash": T.DOUBLE, "sr_reversed_charge": T.DOUBLE,
        "sr_store_credit": T.DOUBLE, "sr_net_loss": T.DOUBLE,
    },
    "catalog_sales": {
        "cs_sold_date_sk": T.BIGINT, "cs_sold_time_sk": T.BIGINT,
        "cs_ship_date_sk": T.BIGINT, "cs_bill_customer_sk": T.BIGINT,
        "cs_bill_cdemo_sk": T.BIGINT, "cs_bill_hdemo_sk": T.BIGINT,
        "cs_bill_addr_sk": T.BIGINT, "cs_ship_customer_sk": T.BIGINT,
        "cs_ship_cdemo_sk": T.BIGINT, "cs_ship_hdemo_sk": T.BIGINT,
        "cs_ship_addr_sk": T.BIGINT, "cs_call_center_sk": T.BIGINT,
        "cs_catalog_page_sk": T.BIGINT, "cs_ship_mode_sk": T.BIGINT,
        "cs_warehouse_sk": T.BIGINT, "cs_item_sk": T.BIGINT,
        "cs_promo_sk": T.BIGINT, "cs_order_number": T.BIGINT,
        "cs_quantity": T.INTEGER, "cs_wholesale_cost": T.DOUBLE,
        "cs_list_price": T.DOUBLE, "cs_sales_price": T.DOUBLE,
        "cs_ext_discount_amt": T.DOUBLE, "cs_ext_sales_price": T.DOUBLE,
        "cs_ext_wholesale_cost": T.DOUBLE, "cs_ext_list_price": T.DOUBLE,
        "cs_ext_tax": T.DOUBLE, "cs_coupon_amt": T.DOUBLE,
        "cs_ext_ship_cost": T.DOUBLE, "cs_net_paid": T.DOUBLE,
        "cs_net_paid_inc_tax": T.DOUBLE, "cs_net_paid_inc_ship": T.DOUBLE,
        "cs_net_paid_inc_ship_tax": T.DOUBLE, "cs_net_profit": T.DOUBLE,
    },
    "catalog_returns": {
        "cr_returned_date_sk": T.BIGINT, "cr_returned_time_sk": T.BIGINT,
        "cr_item_sk": T.BIGINT, "cr_refunded_customer_sk": T.BIGINT,
        "cr_refunded_cdemo_sk": T.BIGINT, "cr_refunded_hdemo_sk": T.BIGINT,
        "cr_refunded_addr_sk": T.BIGINT, "cr_returning_customer_sk": T.BIGINT,
        "cr_returning_cdemo_sk": T.BIGINT, "cr_returning_hdemo_sk": T.BIGINT,
        "cr_returning_addr_sk": T.BIGINT, "cr_call_center_sk": T.BIGINT,
        "cr_catalog_page_sk": T.BIGINT, "cr_ship_mode_sk": T.BIGINT,
        "cr_warehouse_sk": T.BIGINT, "cr_reason_sk": T.BIGINT,
        "cr_order_number": T.BIGINT, "cr_return_quantity": T.INTEGER,
        "cr_return_amount": T.DOUBLE, "cr_return_tax": T.DOUBLE,
        "cr_return_amt_inc_tax": T.DOUBLE, "cr_fee": T.DOUBLE,
        "cr_return_ship_cost": T.DOUBLE, "cr_refunded_cash": T.DOUBLE,
        "cr_reversed_charge": T.DOUBLE, "cr_store_credit": T.DOUBLE,
        "cr_net_loss": T.DOUBLE,
    },
    "web_sales": {
        "ws_sold_date_sk": T.BIGINT, "ws_sold_time_sk": T.BIGINT,
        "ws_ship_date_sk": T.BIGINT, "ws_item_sk": T.BIGINT,
        "ws_bill_customer_sk": T.BIGINT, "ws_bill_cdemo_sk": T.BIGINT,
        "ws_bill_hdemo_sk": T.BIGINT, "ws_bill_addr_sk": T.BIGINT,
        "ws_ship_customer_sk": T.BIGINT, "ws_ship_cdemo_sk": T.BIGINT,
        "ws_ship_hdemo_sk": T.BIGINT, "ws_ship_addr_sk": T.BIGINT,
        "ws_web_page_sk": T.BIGINT, "ws_web_site_sk": T.BIGINT,
        "ws_ship_mode_sk": T.BIGINT, "ws_warehouse_sk": T.BIGINT,
        "ws_promo_sk": T.BIGINT, "ws_order_number": T.BIGINT,
        "ws_quantity": T.INTEGER, "ws_wholesale_cost": T.DOUBLE,
        "ws_list_price": T.DOUBLE, "ws_sales_price": T.DOUBLE,
        "ws_ext_discount_amt": T.DOUBLE, "ws_ext_sales_price": T.DOUBLE,
        "ws_ext_wholesale_cost": T.DOUBLE, "ws_ext_list_price": T.DOUBLE,
        "ws_ext_tax": T.DOUBLE, "ws_coupon_amt": T.DOUBLE,
        "ws_ext_ship_cost": T.DOUBLE, "ws_net_paid": T.DOUBLE,
        "ws_net_paid_inc_tax": T.DOUBLE, "ws_net_paid_inc_ship": T.DOUBLE,
        "ws_net_paid_inc_ship_tax": T.DOUBLE, "ws_net_profit": T.DOUBLE,
    },
    "web_returns": {
        "wr_returned_date_sk": T.BIGINT, "wr_returned_time_sk": T.BIGINT,
        "wr_item_sk": T.BIGINT, "wr_refunded_customer_sk": T.BIGINT,
        "wr_refunded_cdemo_sk": T.BIGINT, "wr_refunded_hdemo_sk": T.BIGINT,
        "wr_refunded_addr_sk": T.BIGINT, "wr_returning_customer_sk": T.BIGINT,
        "wr_returning_cdemo_sk": T.BIGINT, "wr_returning_hdemo_sk": T.BIGINT,
        "wr_returning_addr_sk": T.BIGINT, "wr_web_page_sk": T.BIGINT,
        "wr_reason_sk": T.BIGINT, "wr_order_number": T.BIGINT,
        "wr_return_quantity": T.INTEGER, "wr_return_amt": T.DOUBLE,
        "wr_return_tax": T.DOUBLE, "wr_return_amt_inc_tax": T.DOUBLE,
        "wr_fee": T.DOUBLE, "wr_return_ship_cost": T.DOUBLE,
        "wr_refunded_cash": T.DOUBLE, "wr_reversed_charge": T.DOUBLE,
        "wr_account_credit": T.DOUBLE, "wr_net_loss": T.DOUBLE,
    },
    "web_site": {
        "web_site_sk": T.BIGINT, "web_site_id": T.VARCHAR,
        "web_name": T.VARCHAR, "web_manager": T.VARCHAR,
        "web_market_manager": T.VARCHAR, "web_company_id": T.INTEGER,
        "web_company_name": T.VARCHAR, "web_street_name": T.VARCHAR,
        "web_street_type": T.VARCHAR, "web_city": T.VARCHAR,
        "web_county": T.VARCHAR, "web_state": T.VARCHAR,
        "web_zip": T.VARCHAR, "web_country": T.VARCHAR,
        "web_gmt_offset": T.DOUBLE, "web_tax_percentage": T.DOUBLE,
    },
    "web_page": {
        "wp_web_page_sk": T.BIGINT, "wp_web_page_id": T.VARCHAR,
        "wp_creation_date_sk": T.BIGINT, "wp_access_date_sk": T.BIGINT,
        "wp_autogen_flag": T.VARCHAR, "wp_url": T.VARCHAR,
        "wp_type": T.VARCHAR, "wp_char_count": T.INTEGER,
        "wp_link_count": T.INTEGER, "wp_image_count": T.INTEGER,
        "wp_max_ad_count": T.INTEGER,
    },
    "call_center": {
        "cc_call_center_sk": T.BIGINT, "cc_call_center_id": T.VARCHAR,
        "cc_name": T.VARCHAR, "cc_class": T.VARCHAR,
        "cc_employees": T.INTEGER, "cc_sq_ft": T.INTEGER,
        "cc_hours": T.VARCHAR, "cc_manager": T.VARCHAR,
        "cc_mkt_id": T.INTEGER, "cc_mkt_class": T.VARCHAR,
        "cc_market_manager": T.VARCHAR, "cc_county": T.VARCHAR,
        "cc_state": T.VARCHAR, "cc_country": T.VARCHAR,
        "cc_gmt_offset": T.DOUBLE, "cc_tax_percentage": T.DOUBLE,
    },
    "catalog_page": {
        "cp_catalog_page_sk": T.BIGINT, "cp_catalog_page_id": T.VARCHAR,
        "cp_start_date_sk": T.BIGINT, "cp_end_date_sk": T.BIGINT,
        "cp_department": T.VARCHAR, "cp_catalog_number": T.INTEGER,
        "cp_catalog_page_number": T.INTEGER, "cp_description": T.VARCHAR,
        "cp_type": T.VARCHAR,
    },
    "time_dim": {
        "t_time_sk": T.BIGINT, "t_time_id": T.VARCHAR, "t_time": T.INTEGER,
        "t_hour": T.INTEGER, "t_minute": T.INTEGER, "t_second": T.INTEGER,
        "t_am_pm": T.VARCHAR, "t_shift": T.VARCHAR,
        "t_sub_shift": T.VARCHAR, "t_meal_time": T.VARCHAR,
    },
    "inventory": {
        "inv_date_sk": T.BIGINT, "inv_item_sk": T.BIGINT,
        "inv_warehouse_sk": T.BIGINT, "inv_quantity_on_hand": T.INTEGER,
    },
}


# ---------------------------------------------------------------------------
# dimension generators
# ---------------------------------------------------------------------------


def _gen_date_dim(sf, row0, row1):
    i = np.arange(row0, row1, dtype=np.int64)
    dates = DATE_DIM_START + i.astype("timedelta64[D]")
    days = ((dates - EPOCH) / np.timedelta64(1, "D")).astype(np.int32)
    y = dates.astype("datetime64[Y]")
    m = dates.astype("datetime64[M]")
    year = y.astype(int) + 1970
    moy = (m - y).astype(int) + 1
    dom = (dates - m).astype(int) + 1
    qoy = (moy - 1) // 3 + 1
    # 1900-01-01 was a Monday; spec d_dow: 0 = Sunday
    dow = (i + 1) % 7
    month_seq = (year - 1900) * 12 + moy - 1
    week_seq = (i + 1) // 7 + 1
    quarter_seq = (year - 1900) * 4 + qoy - 1
    first_dom = (JULIAN_OF_START + i - (dom - 1)).astype(np.int64)
    last_dom = first_dom + (((m + 1).astype("datetime64[D]") - m.astype("datetime64[D]"))
                            / np.timedelta64(1, "D")).astype(np.int64) - 1
    day_names = np.asarray(["Sunday", "Monday", "Tuesday", "Wednesday",
                            "Thursday", "Friday", "Saturday"], dtype=object)
    return {
        "d_date_sk": JULIAN_OF_START + i,
        "d_date_id": _numbered("AAAAAAAA", JULIAN_OF_START + i, 8),
        "d_date": days,
        "d_month_seq": month_seq.astype(np.int32),
        "d_week_seq": week_seq.astype(np.int32),
        "d_quarter_seq": quarter_seq.astype(np.int32),
        "d_year": year.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_moy": moy.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_qoy": qoy.astype(np.int32),
        "d_fy_year": year.astype(np.int32),
        "d_fy_quarter_seq": quarter_seq.astype(np.int32),
        "d_fy_week_seq": week_seq.astype(np.int32),
        "d_day_name": day_names[dow],
        "d_quarter_name": np.char.add(np.char.add(year.astype(str), "Q"),
                                      qoy.astype(str)).astype(object),
        "d_holiday": np.where((moy == 12) & (dom == 25), "Y", "N").astype(object),
        "d_weekend": np.where((dow == 0) | (dow == 6), "Y", "N").astype(object),
        "d_following_holiday": np.where((moy == 12) & (dom == 26), "Y", "N").astype(object),
        "d_first_dom": first_dom.astype(np.int32),
        "d_last_dom": last_dom.astype(np.int32),
        "d_same_day_ly": (JULIAN_OF_START + i - 365).astype(np.int32),
        "d_same_day_lq": (JULIAN_OF_START + i - 91).astype(np.int32),
        "d_current_day": np.full(len(i), "N", dtype=object),
        "d_current_week": np.full(len(i), "N", dtype=object),
        "d_current_month": np.full(len(i), "N", dtype=object),
        "d_current_quarter": np.full(len(i), "N", dtype=object),
        "d_current_year": np.full(len(i), "N", dtype=object),
    }


def _gen_item(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    cat_id = _u("item", "cat", row0, n, 1, len(CATEGORIES))
    class_id = _u("item", "class", row0, n, 1, len(CLASSES))
    manufact_id = _u("item", "manu", row0, n, 1, 1000)
    brand_id = cat_id * 1_000_000 + class_id * 1000 + manufact_id % 1000
    brand = np.char.add(
        np.char.add(_pick("item", "brand1", row0, n, BRAND_SYL).astype(str), " #"),
        (brand_id % 10000).astype(str)).astype(object)
    price = _money("item", "price", row0, n, 9, 99_999)
    start = np.datetime64("1997-10-27", "D") - EPOCH
    return {
        "i_item_sk": k,
        "i_item_id": _numbered("AAAAAAAA", k, 8),
        "i_rec_start_date": np.full(n, int(start / np.timedelta64(1, "D")),
                                    np.int32),
        "i_rec_end_date": np.full(n, int(start / np.timedelta64(1, "D")) + 3650,
                                  np.int32),
        "i_item_desc": _pick("item", "desc", row0, n, COLORS),
        "i_current_price": price,
        "i_wholesale_cost": _round(price * 0.6, 2),
        "i_brand_id": brand_id.astype(np.int32),
        "i_brand": brand,
        "i_class_id": class_id.astype(np.int32),
        "i_class": np.asarray(CLASSES, object)[class_id - 1],
        "i_category_id": cat_id.astype(np.int32),
        "i_category": np.asarray(CATEGORIES, object)[cat_id - 1],
        "i_manufact_id": manufact_id.astype(np.int32),
        "i_manufact": _numbered("manufact#", manufact_id, 4),
        "i_size": _pick("item", "size", row0, n,
                        ["small", "medium", "large", "extra large", "petite",
                         "economy", "N/A"]),
        "i_formulation": _numbered("formulation", k % 100000, 6),
        "i_color": _pick("item", "color", row0, n, COLORS),
        "i_units": _pick("item", "units", row0, n, UNITS),
        "i_container": np.full(n, "Unknown", dtype=object),
        "i_manager_id": _u("item", "mgr", row0, n, 1, 100, np.int32),
        "i_product_name": _numbered("product", k, 9),
    }


def _gen_customer(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    n_cd = row_count("customer_demographics", sf)
    n_hd = _FIXED_ROWS["household_demographics"]
    n_addr = row_count("customer_address", sf)
    first_sales = _u("customer", "fsales", row0, n,
                     SALES_DATE_LO - 3650, SALES_DATE_LO)
    return {
        "c_customer_sk": k,
        "c_customer_id": _numbered("AAAAAAAA", k, 8),
        "c_current_cdemo_sk": _u("customer", "cdemo", row0, n, 1, n_cd),
        "c_current_hdemo_sk": _u("customer", "hdemo", row0, n, 1, n_hd),
        "c_current_addr_sk": _u("customer", "addr", row0, n, 1, n_addr),
        "c_first_shipto_date_sk": first_sales + 30,
        "c_first_sales_date_sk": first_sales,
        "c_salutation": _pick("customer", "salut", row0, n, SALUTATIONS),
        "c_first_name": _pick("customer", "fname", row0, n, FIRST_NAMES),
        "c_last_name": _pick("customer", "lname", row0, n, LAST_NAMES),
        "c_preferred_cust_flag": _pick("customer", "pref", row0, n, ["Y", "N"]),
        "c_birth_day": _u("customer", "bday", row0, n, 1, 28, np.int32),
        "c_birth_month": _u("customer", "bmon", row0, n, 1, 12, np.int32),
        "c_birth_year": _u("customer", "byear", row0, n, 1924, 1992, np.int32),
        "c_birth_country": np.full(n, "UNITED STATES", dtype=object),
        "c_login": np.full(n, "", dtype=object),
        "c_email_address": np.char.add(
            _numbered("Customer", k, 9).astype(str),
            "@example.com").astype(object),
        "c_last_review_date_sk": _u("customer", "review", row0, n,
                                    SALES_DATE_LO, SALES_DATE_HI),
    }


def _gen_customer_address(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    return {
        "ca_address_sk": k,
        "ca_address_id": _numbered("AAAAAAAA", k, 8),
        "ca_street_number": _u("ca", "stno", row0, n, 1, 999).astype(str).astype(object),
        "ca_street_name": _pick("ca", "stname", row0, n, STREET_NAMES),
        "ca_street_type": _pick("ca", "sttype", row0, n, STREET_TYPES),
        "ca_suite_number": _numbered("Suite ", _u("ca", "suite", row0, n, 0, 99), 2),
        "ca_city": _pick("ca", "city", row0, n, CITIES),
        "ca_county": _pick("ca", "county", row0, n,
                           ["Williamson County", "Walker County", "Ziebach County",
                            "Fairfield County", "Bronx County", "Franklin Parish",
                            "Barrow County", "Daviess County", "Luce County",
                            "Richland County", "San Miguel County", "Dauphin County",
                            "Mobile County", "Maverick County", "Huron County"]),
        "ca_state": _pick("ca", "state", row0, n, STATES),
        "ca_zip": np.char.zfill(_u("ca", "zip", row0, n, 601, 99950).astype(str),
                                5).astype(object),
        "ca_country": np.full(n, "United States", dtype=object),
        "ca_gmt_offset": _u("ca", "gmt", row0, n, -10, -5).astype(np.float64),
        "ca_location_type": _pick("ca", "loctype", row0, n,
                                  ["apartment", "condo", "single family"]),
    }


def _gen_customer_demographics(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    # mixed-radix decode of (sk-1) over the spec's attribute cross product
    x = k - 1
    gender = x % 2; x = x // 2
    marital = x % 5; x = x // 5
    edu = x % 7; x = x // 7
    purchase = x % 20; x = x // 20
    credit = x % 4; x = x // 4
    dep = x % 7; x = x // 7
    dep_emp = x % 7; x = x // 7
    return {
        "cd_demo_sk": k,
        "cd_gender": np.asarray(GENDERS, object)[gender],
        "cd_marital_status": np.asarray(MARITAL, object)[marital],
        "cd_education_status": np.asarray(EDUCATION, object)[edu],
        "cd_purchase_estimate": ((purchase + 1) * 500).astype(np.int32),
        "cd_credit_rating": np.asarray(CREDIT, object)[credit],
        "cd_dep_count": dep.astype(np.int32),
        "cd_dep_employed_count": dep_emp.astype(np.int32),
        "cd_dep_college_count": (x % 7).astype(np.int32),
    }


def _gen_household_demographics(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    x = k - 1
    ib = x % 20; x = x // 20
    buy = x % 6; x = x // 6
    dep = x % 10; x = x // 10
    veh = x % 6
    return {
        "hd_demo_sk": k,
        "hd_income_band_sk": ib + 1,
        "hd_buy_potential": np.asarray(BUY_POTENTIAL, object)[buy],
        "hd_dep_count": dep.astype(np.int32),
        "hd_vehicle_count": veh.astype(np.int32),
    }


def _gen_income_band(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    lower = (k - 1) * 10000
    return {
        "ib_income_band_sk": k,
        "ib_lower_bound": (lower + (k > 1)).astype(np.int32),
        "ib_upper_bound": (k * 10000).astype(np.int32),
    }


def _gen_promotion(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    n_item = row_count("item", sf)
    start = _u("promotion", "start", row0, n, SALES_DATE_LO, SALES_DATE_HI - 60)
    yn = lambda col: _pick("promotion", col, row0, n, ["N", "N", "N", "Y"])
    return {
        "p_promo_sk": k,
        "p_promo_id": _numbered("AAAAAAAA", k, 8),
        "p_start_date_sk": start,
        "p_end_date_sk": start + _u("promotion", "len", row0, n, 10, 60),
        "p_item_sk": _u("promotion", "item", row0, n, 1, n_item),
        "p_cost": _round(1000.0 * _u("promotion", "cost", row0, n, 1, 1000), 2),
        "p_response_target": np.ones(n, np.int32),
        "p_promo_name": _pick("promotion", "name", row0, n,
                              ["anti", "bar", "ese", "ought", "able", "pri",
                               "pres", "ation", "eing", "callly"]),
        "p_channel_dmail": yn("dmail"),
        "p_channel_email": np.full(n, "N", dtype=object),
        "p_channel_catalog": np.full(n, "N", dtype=object),
        "p_channel_tv": yn("tv"),
        "p_channel_radio": np.full(n, "N", dtype=object),
        "p_channel_press": np.full(n, "N", dtype=object),
        "p_channel_event": yn("event"),
        "p_channel_demo": np.full(n, "N", dtype=object),
        "p_channel_details": _numbered("promo details ", k, 6),
        "p_purpose": np.full(n, "Unknown", dtype=object),
        "p_discount_active": np.full(n, "N", dtype=object),
    }


def _gen_store(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    start = np.datetime64("1997-03-13", "D") - EPOCH
    return {
        "s_store_sk": k,
        "s_store_id": _numbered("AAAAAAAA", (k + 1) // 2, 8),  # SCD pairs share id
        "s_rec_start_date": np.full(n, int(start / np.timedelta64(1, "D")), np.int32),
        "s_rec_end_date": np.full(n, int(start / np.timedelta64(1, "D")) + 3650,
                                  np.int32),
        "s_closed_date_sk": np.zeros(n, np.int64),
        "s_store_name": _pick("store", "name", row0, n,
                              ["ought", "able", "pri", "ese", "anti", "cally",
                               "ation", "eing", "bar"]),
        "s_number_employees": _u("store", "emp", row0, n, 200, 300, np.int32),
        "s_floor_space": _u("store", "floor", row0, n, 5_000_000, 10_000_000,
                            np.int32),
        "s_hours": _pick("store", "hours", row0, n, ["8AM-8AM", "8AM-4PM", "8AM-12AM"]),
        "s_manager": _pick("store", "mgr", row0, n, FIRST_NAMES),
        "s_market_id": _u("store", "mktid", row0, n, 1, 10, np.int32),
        "s_geography_class": np.full(n, "Unknown", dtype=object),
        "s_market_desc": _numbered("market number ", k % 10 + 1, 2),
        "s_market_manager": _pick("store", "mktmgr", row0, n, FIRST_NAMES),
        "s_division_id": np.ones(n, np.int32),
        "s_division_name": np.full(n, "Unknown", dtype=object),
        "s_company_id": np.ones(n, np.int32),
        "s_company_name": np.full(n, "Unknown", dtype=object),
        "s_street_number": _u("store", "stno", row0, n, 1, 999).astype(str).astype(object),
        "s_street_name": _pick("store", "stname", row0, n, STREET_NAMES),
        "s_street_type": _pick("store", "sttype", row0, n, STREET_TYPES),
        "s_suite_number": _numbered("Suite ", _u("store", "suite", row0, n, 0, 99), 2),
        "s_city": _pick("store", "city", row0, n, CITIES[:6]),
        "s_county": _pick("store", "county", row0, n, ["Williamson County"]),
        "s_state": _pick("store", "state", row0, n, STATES[:9]),
        "s_zip": np.char.zfill(_u("store", "zip", row0, n, 601, 99950).astype(str),
                               5).astype(object),
        "s_country": np.full(n, "United States", dtype=object),
        "s_gmt_offset": _u("store", "gmt", row0, n, -10, -5).astype(np.float64),
        "s_tax_precentage": _round(_u("store", "tax", row0, n, 0, 11) / 100.0, 2),
    }


def _gen_reason(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    return {
        "r_reason_sk": k,
        "r_reason_id": _numbered("AAAAAAAA", k, 8),
        "r_reason_desc": np.asarray(REASONS, object)[(k - 1) % len(REASONS)],
    }


def _gen_ship_mode(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    return {
        "sm_ship_mode_sk": k,
        "sm_ship_mode_id": _numbered("AAAAAAAA", k, 8),
        "sm_type": np.asarray(SHIP_TYPES, object)[(k - 1) % len(SHIP_TYPES)],
        "sm_code": np.asarray(SHIP_CODES, object)[(k - 1) % len(SHIP_CODES)],
        "sm_carrier": np.asarray(CARRIERS, object)[(k - 1) % len(CARRIERS)],
        "sm_contract": _numbered("contract", k, 6),
    }


def _gen_warehouse(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    return {
        "w_warehouse_sk": k,
        "w_warehouse_id": _numbered("AAAAAAAA", k, 8),
        "w_warehouse_name": _pick("warehouse", "name", row0, n,
                                  ["Conventional childr", "Important issues liv",
                                   "Doors canno", "Bad cards must make.",
                                   "Rooms cook "]),
        "w_warehouse_sq_ft": _u("warehouse", "sqft", row0, n, 50_000, 1_000_000,
                                np.int32),
        "w_street_number": _u("warehouse", "stno", row0, n, 1, 999)
            .astype(str).astype(object),
        "w_street_name": _pick("warehouse", "stname", row0, n, STREET_NAMES),
        "w_street_type": _pick("warehouse", "sttype", row0, n, STREET_TYPES),
        "w_suite_number": _numbered("Suite ", _u("warehouse", "suite", row0, n, 0, 99), 2),
        "w_city": _pick("warehouse", "city", row0, n, CITIES[:6]),
        "w_county": _pick("warehouse", "county", row0, n, ["Williamson County"]),
        "w_state": _pick("warehouse", "state", row0, n, STATES[:9]),
        "w_zip": np.char.zfill(_u("warehouse", "zip", row0, n, 601, 99950)
                               .astype(str), 5).astype(object),
        "w_country": np.full(n, "United States", dtype=object),
        "w_gmt_offset": _u("warehouse", "gmt", row0, n, -10, -5).astype(np.float64),
    }


# ---------------------------------------------------------------------------
# fact generators — store & catalog channels
# ---------------------------------------------------------------------------


def _store_sales_cols(sf, rows):
    """store_sales columns for explicit row indices (shared by the sales
    generator and the returns generator reading parent rows)."""
    t = "store_sales"
    n_item = row_count("item", sf)
    n_cust = row_count("customer", sf)
    n_cd = row_count("customer_demographics", sf)
    n_hd = _FIXED_ROWS["household_demographics"]
    n_addr = row_count("customer_address", sf)
    n_store = row_count("store", sf)
    n_promo = row_count("promotion", sf)
    ticket = np.asarray(rows, np.int64) // ITEMS_PER_TICKET + 1
    # per-ticket attributes: drawn from the ticket counter, not the row
    cust = _u_at(t, "cust", ticket, 1, n_cust)
    hdemo = _u_at(t, "hdemo", ticket, 1, n_hd)
    addr = _u_at(t, "addr", ticket, 1, n_addr)
    store = _u_at(t, "store", ticket, 1, n_store)
    sold_date = _u_at(t, "date", ticket, SALES_DATE_LO, SALES_DATE_HI)
    # per-row attributes
    item = _u_at(t, "item", rows, 1, n_item)
    cdemo = _u_at(t, "cdemo", rows, 1, n_cd)
    promo = _u_at(t, "promo", rows, 1, n_promo)
    qty = _u_at(t, "qty", rows, 1, 100, np.int32)
    wholesale = _money_at(t, "wholesale", rows, 100, 10_000)
    markup = _raw_at(t, "markup", rows)[:, 0] * 1.0  # 0..100% markup
    discount = _raw_at(t, "discount", rows)[:, 0]    # 0..100% discount
    list_price = _round(wholesale * (1.0 + markup), 2)
    sales_price = _round(list_price * (1.0 - discount), 2)
    qf = qty.astype(np.float64)
    ext_list = _round(list_price * qf, 2)
    ext_sales = _round(sales_price * qf, 2)
    ext_wholesale = _round(wholesale * qf, 2)
    ext_discount = _round(ext_list - ext_sales, 2)
    coupon = _round(ext_sales * (_raw_at(t, "coupon", rows)[:, 0] < 0.2)
                      * _raw_at(t, "coupamt", rows)[:, 0] * 0.5, 2)
    net_paid = _round(ext_sales - coupon, 2)
    tax = _round(net_paid * 0.08, 2)
    return {
        "ss_sold_date_sk": sold_date,
        "ss_sold_time_sk": _u_at(t, "time", rows, 28800, 75600),
        "ss_item_sk": item,
        "ss_customer_sk": cust,
        "ss_cdemo_sk": cdemo,
        "ss_hdemo_sk": hdemo,
        "ss_addr_sk": addr,
        "ss_store_sk": store,
        "ss_promo_sk": promo,
        "ss_ticket_number": ticket,
        "ss_quantity": qty,
        "ss_wholesale_cost": wholesale,
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_discount_amt": ext_discount,
        "ss_ext_sales_price": ext_sales,
        "ss_ext_wholesale_cost": ext_wholesale,
        "ss_ext_list_price": ext_list,
        "ss_ext_tax": tax,
        "ss_coupon_amt": coupon,
        "ss_net_paid": net_paid,
        "ss_net_paid_inc_tax": _round(net_paid + tax, 2),
        "ss_net_profit": _round(net_paid - ext_wholesale, 2),
    }


def _gen_store_sales(sf, row0, row1):
    return _store_sales_cols(sf, np.arange(row0, row1, dtype=np.int64))


def _gen_store_returns(sf, row0, row1):
    t = "store_returns"
    j = np.arange(row0, row1, dtype=np.int64)
    parent = j * RETURN_EVERY
    ss = _store_sales_cols(sf, parent)
    ret_qty = np.minimum(
        _u_at(t, "qty", j, 1, 100, np.int32), ss["ss_quantity"])
    amt = _round(ss["ss_sales_price"] * ret_qty, 2)
    tax = _round(amt * 0.08, 2)
    fee = _money_at(t, "fee", j, 50, 10_000)
    ship = _money_at(t, "ship", j, 0, 10_000)
    frac = _raw_at(t, "cashfrac", j)[:, 0]
    cash = _round(amt * frac, 2)
    charge = _round((amt - cash) * _raw_at(t, "chargefrac", j)[:, 0], 2)
    credit = _round(amt - cash - charge, 2)
    return {
        "sr_returned_date_sk": ss["ss_sold_date_sk"]
            + _u_at(t, "lag", j, 1, 60),
        "sr_return_time_sk": _u_at(t, "time", j, 28800, 75600),
        "sr_item_sk": ss["ss_item_sk"],
        "sr_customer_sk": ss["ss_customer_sk"],
        "sr_cdemo_sk": ss["ss_cdemo_sk"],
        "sr_hdemo_sk": ss["ss_hdemo_sk"],
        "sr_addr_sk": ss["ss_addr_sk"],
        "sr_store_sk": ss["ss_store_sk"],
        "sr_reason_sk": _u_at(t, "reason", j, 1, _FIXED_ROWS["reason"]),
        "sr_ticket_number": ss["ss_ticket_number"],
        "sr_return_quantity": ret_qty,
        "sr_return_amt": amt,
        "sr_return_tax": tax,
        "sr_return_amt_inc_tax": _round(amt + tax, 2),
        "sr_fee": fee,
        "sr_return_ship_cost": ship,
        "sr_refunded_cash": cash,
        "sr_reversed_charge": charge,
        "sr_store_credit": credit,
        "sr_net_loss": _round(fee + ship + tax, 2),
    }


def _catalog_sales_cols(sf, rows):
    t = "catalog_sales"
    n_item = row_count("item", sf)
    n_cust = row_count("customer", sf)
    n_cd = row_count("customer_demographics", sf)
    n_hd = _FIXED_ROWS["household_demographics"]
    n_addr = row_count("customer_address", sf)
    n_promo = row_count("promotion", sf)
    n_wh = row_count("warehouse", sf)
    order = np.asarray(rows, np.int64) // ITEMS_PER_ORDER + 1
    bill_cust = _u_at(t, "bcust", order, 1, n_cust)
    ship_cust = _u_at(t, "scust", order, 1, n_cust)
    sold_date = _u_at(t, "date", order, SALES_DATE_LO, SALES_DATE_HI)
    item = _u_at(t, "item", rows, 1, n_item)
    m = _sales_money_cols(t, sf, rows)
    out = {
        "cs_sold_date_sk": sold_date,
        "cs_sold_time_sk": _u_at(t, "time", rows, 28800, 75600),
        "cs_ship_date_sk": sold_date + _u_at(t, "shiplag", rows, 2, 90),
        "cs_bill_customer_sk": bill_cust,
        "cs_bill_cdemo_sk": _u_at(t, "bcdemo", rows, 1, n_cd),
        "cs_bill_hdemo_sk": _u_at(t, "bhdemo", order, 1, n_hd),
        "cs_bill_addr_sk": _u_at(t, "baddr", order, 1, n_addr),
        "cs_ship_customer_sk": ship_cust,
        "cs_ship_cdemo_sk": _u_at(t, "scdemo", rows, 1, n_cd),
        "cs_ship_hdemo_sk": _u_at(t, "shdemo", order, 1, n_hd),
        "cs_ship_addr_sk": _u_at(t, "saddr", order, 1, n_addr),
        "cs_call_center_sk": _u_at(t, "cc", rows, 1, 6),
        "cs_catalog_page_sk": _u_at(t, "cp", rows, 1, 11_718),
        "cs_ship_mode_sk": _u_at(t, "sm", rows, 1, _FIXED_ROWS["ship_mode"]),
        "cs_warehouse_sk": _u_at(t, "wh", rows, 1, n_wh),
        "cs_item_sk": item,
        "cs_promo_sk": _u_at(t, "promo", rows, 1, n_promo),
        "cs_order_number": order,
    }
    for k, v in m.items():
        out["cs_" + k] = v
    return out


def _gen_catalog_sales(sf, row0, row1):
    return _catalog_sales_cols(sf, np.arange(row0, row1, dtype=np.int64))


def _gen_catalog_returns(sf, row0, row1):
    t = "catalog_returns"
    j = np.arange(row0, row1, dtype=np.int64)
    parent = j * RETURN_EVERY
    cs = _catalog_sales_cols(sf, parent)
    r = _returns_money_cols(t, j, cs["cs_sales_price"], cs["cs_quantity"])
    return {
        "cr_returned_date_sk": cs["cs_sold_date_sk"] + _u_at(t, "lag", j, 1, 60),
        "cr_returned_time_sk": _u_at(t, "time", j, 28800, 75600),
        "cr_item_sk": cs["cs_item_sk"],
        "cr_refunded_customer_sk": cs["cs_bill_customer_sk"],
        "cr_refunded_cdemo_sk": cs["cs_bill_cdemo_sk"],
        "cr_refunded_hdemo_sk": cs["cs_bill_hdemo_sk"],
        "cr_refunded_addr_sk": cs["cs_bill_addr_sk"],
        "cr_returning_customer_sk": cs["cs_ship_customer_sk"],
        "cr_returning_cdemo_sk": cs["cs_ship_cdemo_sk"],
        "cr_returning_hdemo_sk": cs["cs_ship_hdemo_sk"],
        "cr_returning_addr_sk": cs["cs_ship_addr_sk"],
        "cr_call_center_sk": cs["cs_call_center_sk"],
        "cr_catalog_page_sk": cs["cs_catalog_page_sk"],
        "cr_ship_mode_sk": cs["cs_ship_mode_sk"],
        "cr_warehouse_sk": cs["cs_warehouse_sk"],
        "cr_reason_sk": _u_at(t, "reason", j, 1, _FIXED_ROWS["reason"]),
        "cr_order_number": cs["cs_order_number"],
        "cr_return_quantity": r["return_quantity"],
        "cr_return_amount": r["return_amt"],
        "cr_return_tax": r["return_tax"],
        "cr_return_amt_inc_tax": r["return_amt_inc_tax"],
        "cr_fee": r["fee"],
        "cr_return_ship_cost": r["return_ship_cost"],
        "cr_refunded_cash": r["refunded_cash"],
        "cr_reversed_charge": r["reversed_charge"],
        "cr_store_credit": r["credit"],
        "cr_net_loss": r["net_loss"],
    }


# ---------------------------------------------------------------------------
# web channel + inventory + small dims (reference: presto-tpcds covers the
# full 24-table schema; these complete the web_sales/web_returns channel,
# weekly inventory snapshots, and the remaining dimensions)
# ---------------------------------------------------------------------------


def _sales_money_cols(t, sf, rows):
    """Channel-shared pricing math (quantity, wholesale/list/sales price,
    ext_* amounts, coupon, shipping, tax, net paid/profit) keyed by the
    channel's table name so draws stay independent per channel."""
    qty = _u_at(t, "qty", rows, 1, 100, np.int32)
    wholesale = _money_at(t, "wholesale", rows, 100, 10_000)
    markup = _raw_at(t, "markup", rows)[:, 0]
    discount = _raw_at(t, "discount", rows)[:, 0]
    list_price = _round(wholesale * (1.0 + markup), 2)
    sales_price = _round(list_price * (1.0 - discount), 2)
    qf = qty.astype(np.float64)
    ext_list = _round(list_price * qf, 2)
    ext_sales = _round(sales_price * qf, 2)
    ext_wholesale = _round(wholesale * qf, 2)
    coupon = _round(ext_sales * (_raw_at(t, "coupon", rows)[:, 0] < 0.2)
                      * _raw_at(t, "coupamt", rows)[:, 0] * 0.5, 2)
    ship_cost = _money_at(t, "shipc", rows, 0, 5_000) * qf
    net_paid = _round(ext_sales - coupon, 2)
    tax = _round(net_paid * 0.08, 2)
    return {
        "quantity": qty, "wholesale_cost": wholesale,
        "list_price": list_price, "sales_price": sales_price,
        "ext_discount_amt": _round(ext_list - ext_sales, 2),
        "ext_sales_price": ext_sales, "ext_wholesale_cost": ext_wholesale,
        "ext_list_price": ext_list, "ext_tax": tax, "coupon_amt": coupon,
        "ext_ship_cost": _round(ship_cost, 2), "net_paid": net_paid,
        "net_paid_inc_tax": _round(net_paid + tax, 2),
        "net_paid_inc_ship": _round(net_paid + ship_cost, 2),
        "net_paid_inc_ship_tax": _round(net_paid + ship_cost + tax, 2),
        "net_profit": _round(net_paid - ext_wholesale, 2),
    }


def _returns_money_cols(t, rows_j, sales_price, sale_qty):
    """Channel-shared returns math (returned quantity, amounts, fee,
    shipping, cash/charge/credit split)."""
    ret_qty = np.minimum(_u_at(t, "qty", rows_j, 1, 100, np.int32), sale_qty)
    amt = _round(sales_price * ret_qty, 2)
    tax = _round(amt * 0.08, 2)
    fee = _money_at(t, "fee", rows_j, 50, 10_000)
    ship = _money_at(t, "ship", rows_j, 0, 10_000)
    frac = _raw_at(t, "cashfrac", rows_j)[:, 0]
    cash = _round(amt * frac, 2)
    charge = _round((amt - cash) * _raw_at(t, "chargefrac", rows_j)[:, 0], 2)
    credit = _round(amt - cash - charge, 2)
    return {
        "return_quantity": ret_qty, "return_amt": amt, "return_tax": tax,
        "return_amt_inc_tax": _round(amt + tax, 2), "fee": fee,
        "return_ship_cost": ship, "refunded_cash": cash,
        "reversed_charge": charge, "credit": credit,
        "net_loss": _round(fee + ship + tax, 2),
    }


def _web_sales_cols(sf, rows):
    t = "web_sales"
    n_item = row_count("item", sf)
    n_cust = row_count("customer", sf)
    n_cd = row_count("customer_demographics", sf)
    n_hd = _FIXED_ROWS["household_demographics"]
    n_addr = row_count("customer_address", sf)
    n_promo = row_count("promotion", sf)
    n_wh = row_count("warehouse", sf)
    order = np.asarray(rows, np.int64) // ITEMS_PER_ORDER + 1
    bill_cust = _u_at(t, "bcust", order, 1, n_cust)
    ship_cust = _u_at(t, "scust", order, 1, n_cust)
    sold_date = _u_at(t, "date", order, SALES_DATE_LO, SALES_DATE_HI)
    item = _u_at(t, "item", rows, 1, n_item)
    m = _sales_money_cols(t, sf, rows)
    out = {
        "ws_sold_date_sk": sold_date,
        "ws_sold_time_sk": _u_at(t, "time", rows, 28800, 75600),
        "ws_ship_date_sk": sold_date + _u_at(t, "shiplag", rows, 2, 90),
        "ws_item_sk": item,
        "ws_bill_customer_sk": bill_cust,
        "ws_bill_cdemo_sk": _u_at(t, "bcdemo", rows, 1, n_cd),
        "ws_bill_hdemo_sk": _u_at(t, "bhdemo", order, 1, n_hd),
        "ws_bill_addr_sk": _u_at(t, "baddr", order, 1, n_addr),
        "ws_ship_customer_sk": ship_cust,
        "ws_ship_cdemo_sk": _u_at(t, "scdemo", rows, 1, n_cd),
        "ws_ship_hdemo_sk": _u_at(t, "shdemo", order, 1, n_hd),
        "ws_ship_addr_sk": _u_at(t, "saddr", order, 1, n_addr),
        "ws_web_page_sk": _u_at(t, "wp", rows, 1, row_count("web_page", sf)),
        "ws_web_site_sk": _u_at(t, "wsite", order, 1,
                                row_count("web_site", sf)),
        "ws_ship_mode_sk": _u_at(t, "sm", rows, 1, _FIXED_ROWS["ship_mode"]),
        "ws_warehouse_sk": _u_at(t, "wh", rows, 1, n_wh),
        "ws_promo_sk": _u_at(t, "promo", rows, 1, n_promo),
        "ws_order_number": order,
    }
    for k, v in m.items():
        out["ws_" + k] = v
    return out


def _gen_web_sales(sf, row0, row1):
    return _web_sales_cols(sf, np.arange(row0, row1, dtype=np.int64))


def _gen_web_returns(sf, row0, row1):
    t = "web_returns"
    j = np.arange(row0, row1, dtype=np.int64)
    parent = j * RETURN_EVERY
    ws = _web_sales_cols(sf, parent)
    r = _returns_money_cols(t, j, ws["ws_sales_price"], ws["ws_quantity"])
    return {
        "wr_returned_date_sk": ws["ws_sold_date_sk"] + _u_at(t, "lag", j, 1, 60),
        "wr_returned_time_sk": _u_at(t, "time", j, 28800, 75600),
        "wr_item_sk": ws["ws_item_sk"],
        "wr_refunded_customer_sk": ws["ws_bill_customer_sk"],
        "wr_refunded_cdemo_sk": ws["ws_bill_cdemo_sk"],
        "wr_refunded_hdemo_sk": ws["ws_bill_hdemo_sk"],
        "wr_refunded_addr_sk": ws["ws_bill_addr_sk"],
        "wr_returning_customer_sk": ws["ws_ship_customer_sk"],
        "wr_returning_cdemo_sk": ws["ws_ship_cdemo_sk"],
        "wr_returning_hdemo_sk": ws["ws_ship_hdemo_sk"],
        "wr_returning_addr_sk": ws["ws_ship_addr_sk"],
        "wr_web_page_sk": ws["ws_web_page_sk"],
        "wr_reason_sk": _u_at(t, "reason", j, 1, _FIXED_ROWS["reason"]),
        "wr_order_number": ws["ws_order_number"],
        "wr_return_quantity": r["return_quantity"],
        "wr_return_amt": r["return_amt"],
        "wr_return_tax": r["return_tax"],
        "wr_return_amt_inc_tax": r["return_amt_inc_tax"],
        "wr_fee": r["fee"],
        "wr_return_ship_cost": r["return_ship_cost"],
        "wr_refunded_cash": r["refunded_cash"],
        "wr_reversed_charge": r["reversed_charge"],
        "wr_account_credit": r["credit"],
        "wr_net_loss": r["net_loss"],
    }


def _gen_web_site(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    t = "web_site"
    return {
        "web_site_sk": k,
        "web_site_id": _numbered("AAAAAAAA", k, 8),
        "web_name": np.char.add("site_", ((k - 1) // 6).astype(str)
                                ).astype(object),
        "web_manager": _pick(t, "mgr", row0, n, FIRST_NAMES[:20]),
        "web_market_manager": _pick(t, "mmgr", row0, n, FIRST_NAMES[20:40]),
        "web_company_id": _u(t, "coid", row0, n, 1, 6, np.int32),
        "web_company_name": _pick(t, "coname", row0, n,
                                  ["pri", "able", "ought", "bar", "cally",
                                   "ation"]),
        "web_street_name": _pick(t, "stname", row0, n, STREET_NAMES),
        "web_street_type": _pick(t, "sttype", row0, n, STREET_TYPES),
        "web_city": _pick(t, "city", row0, n, CITIES[:6]),
        "web_county": _pick(t, "county", row0, n, ["Williamson County"]),
        "web_state": _pick(t, "state", row0, n, STATES[:9]),
        "web_zip": np.char.zfill(_u(t, "zip", row0, n, 601, 99950)
                                 .astype(str), 5).astype(object),
        "web_country": np.full(n, "United States", dtype=object),
        "web_gmt_offset": _u(t, "gmt", row0, n, -10, -5).astype(np.float64),
        "web_tax_percentage": _u(t, "taxp", row0, n, 0, 12) / 100.0,
    }


def _gen_web_page(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    t = "web_page"
    return {
        "wp_web_page_sk": k,
        "wp_web_page_id": _numbered("AAAAAAAA", k, 8),
        "wp_creation_date_sk": _u(t, "cdate", row0, n,
                                  SALES_DATE_LO - 1000, SALES_DATE_LO),
        "wp_access_date_sk": _u(t, "adate", row0, n,
                                SALES_DATE_LO, SALES_DATE_HI),
        "wp_autogen_flag": _pick(t, "auto", row0, n, ["Y", "N"]),
        "wp_url": np.full(n, "http://www.foo.com", dtype=object),
        "wp_type": _pick(t, "type", row0, n,
                         ["welcome", "protected", "dynamic", "feedback",
                          "general", "ad", "order"]),
        "wp_char_count": _u(t, "chars", row0, n, 100, 8000, np.int32),
        "wp_link_count": _u(t, "links", row0, n, 2, 25, np.int32),
        "wp_image_count": _u(t, "imgs", row0, n, 1, 7, np.int32),
        "wp_max_ad_count": _u(t, "ads", row0, n, 0, 4, np.int32),
    }


def _gen_call_center(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    t = "call_center"
    return {
        "cc_call_center_sk": k,
        "cc_call_center_id": _numbered("AAAAAAAA", k, 8),
        "cc_name": np.char.add("call center ", k.astype(str)).astype(object),
        "cc_class": _pick(t, "class", row0, n, ["small", "medium", "large"]),
        "cc_employees": _u(t, "emp", row0, n, 10, 7000, np.int32),
        "cc_sq_ft": _u(t, "sqft", row0, n, 5000, 50000, np.int32),
        "cc_hours": _pick(t, "hours", row0, n,
                          ["8AM-4PM", "8AM-12AM", "8AM-8AM"]),
        "cc_manager": _pick(t, "mgr", row0, n, FIRST_NAMES[:20]),
        "cc_mkt_id": _u(t, "mkt", row0, n, 1, 6, np.int32),
        "cc_mkt_class": _pick(t, "mktclass", row0, n,
                              ["A bit narrow forms matter animals. Consist",
                               "Largely blank forms m", "Sales expect "]),
        "cc_market_manager": _pick(t, "mmgr", row0, n, FIRST_NAMES[20:40]),
        "cc_county": _pick(t, "county", row0, n, ["Williamson County"]),
        "cc_state": _pick(t, "state", row0, n, STATES[:9]),
        "cc_country": np.full(n, "United States", dtype=object),
        "cc_gmt_offset": _u(t, "gmt", row0, n, -10, -5).astype(np.float64),
        "cc_tax_percentage": _u(t, "taxp", row0, n, 0, 12) / 100.0,
    }


def _gen_catalog_page(sf, row0, row1):
    k = np.arange(row0, row1, dtype=np.int64) + 1
    n = len(k)
    t = "catalog_page"
    return {
        "cp_catalog_page_sk": k,
        "cp_catalog_page_id": _numbered("AAAAAAAA", k, 8),
        "cp_start_date_sk": _u(t, "sdate", row0, n,
                               SALES_DATE_LO - 30, SALES_DATE_LO + 330),
        "cp_end_date_sk": _u(t, "edate", row0, n,
                             SALES_DATE_LO + 360, SALES_DATE_HI),
        "cp_department": np.full(n, "DEPARTMENT", dtype=object),
        "cp_catalog_number": ((k - 1) // 108 + 1).astype(np.int32),
        "cp_catalog_page_number": ((k - 1) % 108 + 1).astype(np.int32),
        "cp_description": _pick(t, "desc", row0, n,
                                ["Early important ways", "Flat, united",
                                 "Young, valid", "Also southern cars"]),
        "cp_type": _pick(t, "type", row0, n,
                         ["bi-annual", "quarterly", "monthly"]),
    }


def _gen_time_dim(sf, row0, row1):
    sec = np.arange(row0, row1, dtype=np.int64)
    h = sec // 3600
    mi = (sec // 60) % 60
    s = sec % 60
    shift = np.where(h < 8, "third", np.where(h < 16, "first", "second"))
    sub = np.where(h % 8 < 3, "morning",
                   np.where(h % 8 < 6, "afternoon", "evening"))
    meal = np.where((h >= 6) & (h <= 8), "breakfast",
                    np.where((h >= 11) & (h <= 13), "lunch",
                             np.where((h >= 17) & (h <= 19), "dinner", "")))
    return {
        "t_time_sk": sec,
        "t_time_id": _numbered("AAAAAAAA", sec + 1, 8),
        "t_time": sec.astype(np.int32),
        "t_hour": h.astype(np.int32),
        "t_minute": mi.astype(np.int32),
        "t_second": s.astype(np.int32),
        "t_am_pm": np.where(h < 12, "AM", "PM").astype(object),
        "t_shift": shift.astype(object),
        "t_sub_shift": sub.astype(object),
        "t_meal_time": meal.astype(object),
    }


INV_WEEKS = 261  # weekly snapshots over the 5-year sales window


def _inv_items(sf: float) -> int:
    """Items covered by inventory snapshots: capped at 45k (official
    inventory grows sub-linearly: 11.7M/133M/399M at SF1/10/100)."""
    return min(row_count("item", sf), 45_000)


def _gen_inventory(sf, row0, row1):
    """Row r = (week w, item i, warehouse h) in row-major (w, i, h) order;
    inv date = first sales date + 7*w."""
    n_item = _inv_items(sf)
    n_wh = row_count("warehouse", sf)
    r = np.arange(row0, row1, dtype=np.int64)
    per_week = n_item * n_wh
    w = r // per_week
    i = (r % per_week) // n_wh
    h = r % n_wh
    return {
        "inv_date_sk": SALES_DATE_LO + 7 * w,
        "inv_item_sk": i + 1,
        "inv_warehouse_sk": h + 1,
        "inv_quantity_on_hand": _u_at("inventory", "qty", r, 0, 1000,
                                      np.int32),
    }


_GENERATORS = {
    "date_dim": _gen_date_dim,
    "item": _gen_item,
    "customer": _gen_customer,
    "customer_address": _gen_customer_address,
    "customer_demographics": _gen_customer_demographics,
    "household_demographics": _gen_household_demographics,
    "income_band": _gen_income_band,
    "promotion": _gen_promotion,
    "store": _gen_store,
    "reason": _gen_reason,
    "ship_mode": _gen_ship_mode,
    "warehouse": _gen_warehouse,
    "store_sales": _gen_store_sales,
    "store_returns": _gen_store_returns,
    "catalog_sales": _gen_catalog_sales,
    "catalog_returns": _gen_catalog_returns,
    "web_sales": _gen_web_sales,
    "web_returns": _gen_web_returns,
    "web_site": _gen_web_site,
    "web_page": _gen_web_page,
    "call_center": _gen_call_center,
    "catalog_page": _gen_catalog_page,
    "time_dim": _gen_time_dim,
    "inventory": _gen_inventory,
}


# ---------------------------------------------------------------------------
# statistics (arithmetic, no scanning) — reference: presto-tpcds
# TpcdsMetadata.getTableStatistics; derivable from the generator
# formulas.  Feeds the CBO (plan/stats.py) AND the static-shape bounds
# of compiled/chunked execution (join fanout, agg capacities).
# ---------------------------------------------------------------------------

PRIMARY_KEYS = {
    "date_dim": "d_date_sk", "item": "i_item_sk",
    "customer": "c_customer_sk", "customer_address": "ca_address_sk",
    "customer_demographics": "cd_demo_sk",
    "household_demographics": "hd_demo_sk",
    "income_band": "ib_income_band_sk", "promotion": "p_promo_sk",
    "store": "s_store_sk", "reason": "r_reason_sk",
    "ship_mode": "sm_ship_mode_sk", "warehouse": "w_warehouse_sk",
    "web_site": "web_site_sk", "web_page": "wp_web_page_sk",
    "call_center": "cc_call_center_sk",
    "catalog_page": "cp_catalog_page_sk", "time_dim": "t_time_sk",
}

# returns are unique on the ticket/order alone: parent sales rows are
# every RETURN_EVERY-th row and RETURN_EVERY (10) exceeds the rows per
# ticket (3) / order (4), so no two returns share a parent unit
UNIQUE_KEYS = {
    **{t: [(k,)] for t, k in PRIMARY_KEYS.items()},
    "store_returns": [("sr_ticket_number",),
                      ("sr_item_sk", "sr_ticket_number")],
    "catalog_returns": [("cr_order_number",),
                        ("cr_item_sk", "cr_order_number")],
    "web_returns": [("wr_order_number",),
                    ("wr_item_sk", "wr_order_number")],
    "inventory": [("inv_date_sk", "inv_item_sk", "inv_warehouse_sk")],
}

# physical row ordering the generator emits (ordering-properties SPI,
# plan/properties.py): dimensions in surrogate-key order; sales in
# ticket/order-number order (unit = row // items-per-unit + 1); returns
# inherit their parent sale's unit, sampled every RETURN_EVERY rows in
# row order.  Validated against generated data in
# tests/test_ordering_properties.py; consumed behind monotonicity
# guards.
ORDERINGS = {
    **{t: [(k, True)] for t, k in PRIMARY_KEYS.items()},
    "store_sales": [("ss_ticket_number", True)],
    "store_returns": [("sr_ticket_number", True)],
    "catalog_sales": [("cs_order_number", True)],
    "catalog_returns": [("cr_order_number", True)],
    "web_sales": [("ws_order_number", True)],
    "web_returns": [("wr_order_number", True)],
    "inventory": [("inv_date_sk", True), ("inv_item_sk", True)],
}

# max rows sharing one value of the key set (join fanout upper bounds)
MAX_ROWS_PER_KEY = {
    "store_sales": {("ss_ticket_number",): ITEMS_PER_TICKET,
                    ("ss_item_sk", "ss_ticket_number"): ITEMS_PER_TICKET},
    "catalog_sales": {("cs_order_number",): ITEMS_PER_ORDER,
                      ("cs_item_sk", "cs_order_number"): ITEMS_PER_ORDER},
    "web_sales": {("ws_order_number",): ITEMS_PER_ORDER,
                  ("ws_item_sk", "ws_order_number"): ITEMS_PER_ORDER},
}


def _fk_targets(sf: float):
    """FK column suffix -> (lo, hi) of the referenced key range."""
    return {
        "_date_sk": (JULIAN_OF_START, JULIAN_OF_START + DATE_DIM_ROWS - 1),
        "_time_sk": (0, 86_399),
        "_item_sk": (1, row_count("item", sf)),
        "_customer_sk": (1, row_count("customer", sf)),
        "_cdemo_sk": (1, row_count("customer_demographics", sf)),
        "_hdemo_sk": (1, _FIXED_ROWS["household_demographics"]),
        "_addr_sk": (1, row_count("customer_address", sf)),
        "_store_sk": (1, row_count("store", sf)),
        "_promo_sk": (1, row_count("promotion", sf)),
        "_warehouse_sk": (1, row_count("warehouse", sf)),
        "_call_center_sk": (1, 6),
        "_catalog_page_sk": (1, 11_718),
        "_ship_mode_sk": (1, _FIXED_ROWS["ship_mode"]),
        "_reason_sk": (1, _FIXED_ROWS["reason"]),
        "_income_band_sk": (1, _FIXED_ROWS["income_band"]),
        "_web_page_sk": (1, row_count("web_page", sf)),
        "_web_site_sk": (1, row_count("web_site", sf)),
    }


def column_stats(table: str, column: str, sf: float, ColStats):
    """(min, max, ndv) per column from the generator formulas — exact
    bounds, approximate ndv."""
    rows = row_count(table, sf)
    if column == "d_date_sk":
        return ColStats(min=float(JULIAN_OF_START),
                        max=float(JULIAN_OF_START + rows - 1), ndv=rows)
    if column == "t_time_sk":
        return ColStats(min=0.0, max=float(rows - 1), ndv=rows)
    if column == PRIMARY_KEYS.get(table):  # k = row + 1
        return ColStats(min=1.0, max=float(rows), ndv=rows)
    # fact-table unit numbers
    if column in ("ss_ticket_number",):
        n = row_count("store_sales", sf) // ITEMS_PER_TICKET + 1
        return ColStats(min=1.0, max=float(n), ndv=n)
    if column in ("cs_order_number", "cr_order_number"):
        n = row_count("catalog_sales", sf) // ITEMS_PER_ORDER + 1
        return ColStats(min=1.0, max=float(n), ndv=n)
    if column in ("ws_order_number", "wr_order_number"):
        n = row_count("web_sales", sf) // ITEMS_PER_ORDER + 1
        return ColStats(min=1.0, max=float(n), ndv=n)
    if column == "sr_ticket_number":
        n = row_count("store_sales", sf) // ITEMS_PER_TICKET + 1
        return ColStats(min=1.0, max=float(n), ndv=min(rows, n))
    # sold/returned/ship dates on fact tables: the 5-year sales window
    if column.endswith("sold_date_sk") or column.endswith(
            "returned_date_sk") or column.endswith("ship_date_sk"):
        # ship/returned lag up to 90/60 days past the sold window: the
        # +150 widening must cover ndv too (group capacities sized from
        # ndv must never undershoot)
        return ColStats(min=float(SALES_DATE_LO),
                        max=float(SALES_DATE_HI + 150),
                        ndv=SALES_DATE_HI + 150 - SALES_DATE_LO + 1)
    if column.endswith("sold_time_sk") or column.endswith(
            "return_time_sk") or column.endswith("returned_time_sk"):
        return ColStats(min=28800.0, max=75600.0, ndv=46801)
    # FK columns by suffix
    for suffix, (lo, hi) in _fk_targets(sf).items():
        if column.endswith(suffix):
            return ColStats(min=float(lo), max=float(hi),
                            ndv=min(rows, hi - lo + 1))
    # date_dim derived columns queries filter on constantly
    D = {
        "d_year": (1900, 2099, 200), "d_moy": (1, 12, 12),
        "d_dom": (1, 31, 31), "d_qoy": (1, 4, 4), "d_dow": (0, 6, 7),
        "d_month_seq": (0, 2399, 2400), "d_week_seq": (1, 10436, 10436),
        "d_quarter_seq": (0, 799, 800),
        "d_date": (-25567, 47481, DATE_DIM_ROWS),
        "i_manager_id": (1, 100, 100), "i_manufact_id": (1, 1000, 1000),
        "i_brand_id": (1_001_000,
                       len(CATEGORIES) * 1_000_000 + len(CLASSES) * 1000
                       + 999, len(CATEGORIES) * len(CLASSES) * 1000),
        "i_class_id": (1, len(CLASSES), len(CLASSES)),
        "i_category_id": (1, len(CATEGORIES), len(CATEGORIES)),
        "i_current_price": (0.09, 999.99, 99_991),
        "cd_purchase_estimate": (500, 10000, 20),
        "cd_dep_count": (0, 6, 7), "cd_dep_employed_count": (0, 6, 7),
        "cd_dep_college_count": (0, 6, 7),
        "hd_dep_count": (0, 9, 10), "hd_vehicle_count": (0, 5, 6),
        "ib_lower_bound": (0, 190001, 20),
        "ib_upper_bound": (10000, 200000, 20),
        "c_birth_day": (1, 28, 28), "c_birth_month": (1, 12, 12),
        "c_birth_year": (1924, 1992, 69),
        "ca_gmt_offset": (-10, -5, 6),
        "inv_quantity_on_hand": (0, 1000, 1001),
    }
    if column in D:
        lo, hi, ndv = D[column]
        return ColStats(min=float(lo), max=float(hi), ndv=ndv)
    # quantities / money on fact tables: exact generator ranges.
    # ext_* amounts are unit price x quantity (<=100), so their bounds
    # and ndvs are ~100x the unit-price rules — match the ext_ prefix
    # FIRST or group capacities sized from ndv undershoot by 100x
    if column.endswith("_quantity"):
        return ColStats(min=0.0 if "return" in column else 1.0,
                        max=100.0, ndv=101)
    if column == "i_wholesale_cost":  # price * 0.6, price <= 999.99
        return ColStats(min=0.05, max=600.0, ndv=60_000)
    if "_ext_" in column or column.endswith("_paid") \
            or "_paid_inc" in column or column.endswith("_profit") \
            or column.endswith("_coupon_amt"):
        # worst case list_price(200) x qty(100), plus ship (<=5000/unit
        # x qty via ext_ship_cost) and tax on the _inc_ variants; profit
        # can go negative.  Bounds here must never undershoot (they feed
        # range selectivity AND static range-narrowing)
        lo = -20_000.0 if "profit" in column or "discount" in column \
            else 0.0
        return ColStats(min=lo, max=27_000.0, ndv=2_000_000)
    if column.endswith("wholesale_cost"):
        return ColStats(min=1.0, max=100.0, ndv=9901)
    if column.endswith("list_price") and table != "item":
        return ColStats(min=1.0, max=200.0, ndv=19901)
    if column.endswith("sales_price") and table != "item":
        return ColStats(min=0.0, max=200.0, ndv=20001)
    typ = SCHEMAS[table].get(column)
    if typ is not None and typ.name == "VARCHAR":
        # string ndvs: enum picks are tiny, ids/names scale with rows
        return ColStats(ndv=min(rows, 100_000))
    return ColStats()


def generate(table: str, sf: float = 1.0, row0: int = 0,
             row1: int | None = None):
    n = row_count(table, sf)
    if row1 is None:
        row1 = n
    row1 = min(row1, n)
    return _GENERATORS[table](sf, row0, row1)


def split_ranges(table: str, sf: float, n_splits: int):
    n = row_count(table, sf)
    edges = np.linspace(0, n, n_splits + 1).astype(int)
    return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:]) if a < b]
