"""SQLite connector: query tables living in an external SQL system.

Reference parity: presto-base-jdbc (BaseJdbcClient) + the per-database
connectors built on it (presto-mysql/postgresql/...).  SQLite stands in
for the external JDBC-reachable database: schema discovery through the
catalog's metadata tables, split generation by rowid ranges, projection
pushdown into the remote SELECT, and column statistics pulled with
aggregate queries — the same shape BaseJdbcClient implements over JDBC
metadata + ResultSets.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import Catalog, ConnectorTable

import re as _re

# longest/most-specific first (the scan is substring-based, so SMALLINT
# must match before the generic integer rule)
_AFFINITY = [
    ("SMALLINT", T.INTEGER), ("TINYINT", T.INTEGER),
    ("DOUBLE", T.DOUBLE), ("FLOAT", T.DOUBLE), ("REAL", T.DOUBLE),
    ("NUMERIC", T.DOUBLE), ("DECIMAL", T.DOUBLE),
    ("VARCHAR", T.VARCHAR), ("CHAR", T.VARCHAR), ("TEXT", T.VARCHAR),
    ("CLOB", T.VARCHAR), ("BLOB", T.VARCHAR),
    ("BOOLEAN", T.BOOLEAN),
    ("DATETIME", T.VARCHAR), ("DATE", T.VARCHAR),
]

# SQLite integer affinity: any *INT* word — INT, INT8, INT(11), BIGINT,
# MEDIUMINT — but not POINT (the 'INT' must not follow a letter)
_INT_RE = _re.compile(r"(^|[^A-Z])(TINY|SMALL|MEDIUM|BIG)?INT(EGER)?\d*\b")


def _qident(name: str) -> str:
    """Quote an identifier for SQLite, escaping embedded double quotes —
    hostile table/column names in an attached file must not break out of
    the quoted context."""
    return '"' + name.replace('"', '""') + '"'


def _map_type(decl: str) -> T.Type:
    d = _re.sub(r"\(.*\)", "", (decl or "").upper()).strip()
    for key, t in _AFFINITY:
        if key in d:
            return t
    if _INT_RE.search(d):
        return T.BIGINT
    return T.VARCHAR  # SQLite's dynamic typing default


class SqliteTable(ConnectorTable):
    """One external table (reference: JdbcTableHandle + JdbcRecordSet)."""

    def __init__(self, conn_factory, name: str, schema: Dict[str, T.Type],
                 quoted: str):
        super().__init__(name, schema)
        self._connect = conn_factory
        self._quoted = quoted
        self._local = threading.local()

    def _conn(self) -> sqlite3.Connection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self._local.conn = self._connect()
        return c

    def row_count(self) -> int:
        (n,) = self._conn().execute(
            f"SELECT count(*) FROM {self._quoted}").fetchone()
        return int(n)

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        """Rowid ranges (reference: JdbcSplitManager; JDBC connectors
        usually produce one split, we do better when rowids are dense).
        WITHOUT ROWID tables fall back to one full-scan split."""
        try:
            row = self._conn().execute(
                f"SELECT min(rowid), max(rowid) FROM "
                f"{self._quoted}").fetchone()
        except sqlite3.OperationalError:
            return [(-1, -1)]  # sentinel: full scan (see read)
        if row is None or row[0] is None:
            return []
        lo, hi = int(row[0]), int(row[1]) + 1
        n_splits = max(1, min(n_splits, hi - lo))
        edges = np.linspace(lo, hi, n_splits + 1).astype(np.int64)
        return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:])
                if a < b]

    def read(self, columns: Optional[List[str]] = None,
             split: Optional[Tuple[int, int]] = None) -> Dict[str, np.ndarray]:
        cols = columns if columns is not None else list(self.schema)
        sel = ", ".join(_qident(c) for c in cols)  # projection pushdown
        sql = f"SELECT {sel} FROM {self._quoted}"
        args: tuple = ()
        if split is not None and split[0] >= 0:
            sql += " WHERE rowid >= ? AND rowid < ?"
            args = (split[0], split[1])
        rows = self._conn().execute(sql, args).fetchall()
        out: Dict[str, np.ndarray] = {}
        for i, c in enumerate(cols):
            t = self.schema[c]
            vals = [r[i] for r in rows]
            mask = np.asarray([v is None for v in vals], dtype=bool)
            if t.is_string:
                a = np.asarray(
                    ["" if v is None
                     else (v.decode("utf-8", errors="replace")
                           if isinstance(v, bytes) else str(v))
                     for v in vals], dtype=object)
            elif t.is_floating:
                a = np.asarray([0.0 if v is None else float(v)
                                for v in vals], dtype=np.float64)
            else:
                a = np.asarray([0 if v is None else int(v) for v in vals],
                               dtype=t.numpy_dtype())
            # NULLs ride a masked array (see batch.column_from_numpy)
            out[c] = np.ma.masked_array(a, mask=mask) if mask.any() else a
        return out

    def column_stats(self, column: str):
        from presto_tpu.plan.stats import ColStats

        t = self.schema[column]
        q = _qident(column)
        if t.is_string:
            (ndv,) = self._conn().execute(
                f"SELECT count(DISTINCT {q}) FROM {self._quoted}").fetchone()
            return ColStats(ndv=int(ndv))
        row = self._conn().execute(
            f"SELECT min({q}), max({q}), count(DISTINCT {q}) "
            f"FROM {self._quoted}").fetchone()
        if row[0] is None:
            return ColStats(ndv=0)
        return ColStats(min=float(row[0]), max=float(row[1]),
                        ndv=int(row[2]))


def attach_sqlite(catalog: Catalog, path: str,
                  catalog_name: str = "sqlite") -> List[str]:
    """Discover and register every table of a SQLite database file
    (reference: BaseJdbcClient.getTableNames + getColumns driving the
    connector's metadata).  Tables register as `<catalog_name>.<table>`
    and by bare name when unclaimed."""

    def connect():
        c = sqlite3.connect(path, check_same_thread=False)
        return c

    conn = connect()
    names = [r[0] for r in conn.execute(
        "SELECT name FROM sqlite_master WHERE type = 'table' "
        "AND name NOT LIKE 'sqlite_%' ORDER BY name")]
    registered = []
    for name in names:
        info = conn.execute(
            f"PRAGMA table_info({_qident(name)})").fetchall()
        # the engine's parser lowercases identifiers; SQLite resolves
        # quoted lowercase names case-insensitively, so read() still works
        schema = {r[1].lower(): _map_type(r[2]) for r in info}
        t = SqliteTable(connect, name.lower(), schema, _qident(name))
        qualified = f"{catalog_name}.{name.lower()}"
        catalog.tables[qualified] = t  # one table object, both names
        t._catalog = catalog
        if name.lower() not in catalog.tables:
            catalog.tables[name.lower()] = t
        registered.append(qualified)
    catalog.version += 1
    catalog.known_qualifiers.add(catalog_name)  # this catalog only
    # qualified misses under this prefix must error, not fall back to a
    # same-named internal table
    catalog.claimed_prefixes.add(catalog_name)
    return registered
