"""Local-file connector over the native shard format.

Reference parity: presto-local-file + the presto-raptor storage model
(ORC shards on local disk, metadata in a store); here a table is a
directory of .ptsh shard files written by the engine itself (CTAS /
INSERT target) and scanned with stripe-level zone-map pruning
(presto-orc's row-group pruning analog).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import ConnectorTable
from presto_tpu.storage.shard import Domain, ShardReader, write_shard


class LocalFileTable(ConnectorTable):
    """A directory of shard files + a schema.json sidecar."""

    # zone maps in the PTSH stripes serve the engine's TupleDomain
    # pushdown (plan/domains.py -> read(domains=...))
    supports_domain_pushdown = True

    def __init__(self, name: str, directory: str,
                 schema: Optional[Dict[str, T.Type]] = None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        meta_path = os.path.join(directory, "schema.json")
        if schema is None:
            with open(meta_path) as f:
                meta = json.load(f)
            schema = {c: T.parse_type(t) for c, t in meta["schema"].items()}
        else:
            with open(meta_path, "w") as f:
                json.dump({"schema": {c: str(t) for c, t in schema.items()}}, f)
        super().__init__(name, schema)

    # ---- read path ---------------------------------------------------
    def _shards(self) -> List[str]:
        return sorted(
            os.path.join(self.dir, p) for p in os.listdir(self.dir)
            if p.endswith(".ptsh"))

    def _readers(self) -> List[ShardReader]:
        paths = tuple(self._shards())
        cached = getattr(self, "_reader_cache", None)
        if cached is None or cached[0] != paths:
            self._reader_cache = (paths, [ShardReader(p) for p in paths])
        return self._reader_cache[1]

    def row_count(self) -> int:
        return sum(r.nrows for r in self._readers())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        n = self.row_count()
        edges = np.linspace(0, n, n_splits + 1).astype(int)
        return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:]) if a < b]

    def read(self, columns=None, split=None,
             domains: Optional[Dict[str, Domain]] = None) -> Dict[str, np.ndarray]:
        """Read columns, decoding only what is needed: a split maps to
        the overlapping stripes (stripe = the IO unit, as in the
        reference's ORC row groups), and zone-map domains prune stripes
        before any frame is decompressed."""
        cols = columns if columns is not None else list(self.schema)
        parts: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        base = 0  # global row offset of the current reader
        a, b = split if split is not None else (0, None)
        for r in self._readers():
            if b is not None and base >= b:
                break
            pruned = set(r.select_stripes(domains)) if domains else None
            take = []
            slices = []
            for si, (s0, s1) in enumerate(r.stripe_row_ranges()):
                g0, g1 = base + s0, base + s1  # stripe's global row range
                lo = max(g0, a)
                hi = g1 if b is None else min(g1, b)
                if lo >= hi:
                    continue
                if pruned is not None and si not in pruned:
                    continue
                take.append(si)
                slices.append((lo - g0, hi - g0))
            if take:
                data = r.read(cols, take)
                # offsets of each taken stripe within the concatenated read
                ranges = r.stripe_row_ranges()
                concat_off = 0
                for si, (s_lo, s_hi) in zip(take, slices):
                    n_stripe = ranges[si][1] - ranges[si][0]
                    for c in cols:
                        parts[c].append(
                            data[c][concat_off + s_lo:concat_off + s_hi])
                    concat_off += n_stripe
            base += r.nrows
        out = {}
        for c in cols:
            out[c] = (np.concatenate(parts[c]) if parts[c]
                      else np.empty(0, self.schema[c].numpy_dtype()
                                    if not self.schema[c].is_string else object))
        return out

    def pruned_stats(self, domains: Optional[Dict[str, Domain]]):
        """(kept_stripes, total_stripes) — observability for EXPLAIN/tests."""
        kept = total = 0
        for r in self._readers():
            total += r.n_stripes
            kept += len(r.select_stripes(domains))
        return kept, total

    # ---- write path (reference: ConnectorPageSinkProvider) -----------
    #: rows per writer page; appends above one page scale writers (P4)
    WRITER_PAGE_ROWS = 262_144
    #: writers scale up while backlog > this many pages per active
    #: writer (reference: ScaledWriterScheduler.java scales tasks while
    #: buffered bytes outpace the running writers)
    SCALE_UP_BACKLOG = 2
    MAX_WRITERS = 4

    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        pages = -(-n // self.WRITER_PAGE_ROWS)
        if pages <= 1:
            idx = len(self._shards())
            path = os.path.join(self.dir, f"shard_{idx:06d}.ptsh")
            write_shard(path, {c: arrays[c] for c in self.schema},
                        self.schema)
            self.last_writers_used = 1
            self._invalidate()
            return n
        self._scaled_append(arrays, n, pages)
        self._invalidate()
        return n

    def _scaled_append(self, arrays, n: int, pages: int) -> None:
        """P4 scaled-writer redistribution, local adaptation (reference:
        execution/scheduler/ScaledWriterScheduler.java — writer tasks
        start at one and scale up while the produced-page backlog
        outpaces the active writers).  Here the writers are shard-writer
        threads; each page becomes one shard file, so the readers'
        split/stripe machinery parallelizes the read back."""
        import queue
        import threading

        q: "queue.Queue" = queue.Queue()
        base = len(self._shards())
        for p in range(pages):
            lo = p * self.WRITER_PAGE_ROWS
            hi = min(n, lo + self.WRITER_PAGE_ROWS)
            q.put((base + p, lo, hi))
        errors: List[BaseException] = []

        def writer():
            while True:
                try:
                    idx, lo, hi = q.get_nowait()
                except queue.Empty:
                    return
                try:
                    path = os.path.join(self.dir,
                                        f"shard_{idx:06d}.ptsh")
                    write_shard(path, {c: arrays[c][lo:hi]
                                       for c in self.schema}, self.schema)
                except BaseException as e:  # surfaced to the caller
                    errors.append(e)
                finally:
                    q.task_done()

        threads = [threading.Thread(target=writer, daemon=True)]
        threads[0].start()
        # scale-up loop: add a writer while the backlog stays above
        # SCALE_UP_BACKLOG pages per active writer
        while not q.empty() and len(threads) < self.MAX_WRITERS:
            if q.qsize() > self.SCALE_UP_BACKLOG * len(threads):
                t = threading.Thread(target=writer, daemon=True)
                t.start()
                threads.append(t)
            else:
                break
        q.join()
        for t in threads:
            t.join(timeout=60.0)
        self.last_writers_used = len(threads)
        if errors:
            raise errors[0]

    def delete_where(self, keep_mask: np.ndarray) -> int:
        """Rewrite shards keeping only masked rows (reference: Raptor
        compaction-style delete; row-level deletes rewrite the shard)."""
        data = self.read()
        deleted = int((~keep_mask).sum())
        for p in self._shards():
            os.remove(p)
        kept = {c: v[keep_mask] for c, v in data.items()}
        if len(next(iter(kept.values()), [])) > 0:
            write_shard(os.path.join(self.dir, "shard_000000.ptsh"),
                        kept, self.schema)
        self._invalidate()
        return deleted

    def drop_data(self) -> None:
        """Remove managed storage on DROP TABLE (the table owns its
        directory; leaving shards behind would resurrect old data on a
        same-name re-create)."""
        for p in self._shards():
            os.remove(p)
        meta = os.path.join(self.dir, "schema.json")
        if os.path.exists(meta):
            os.remove(meta)
        self._invalidate()

    def _invalidate(self):
        if hasattr(self, "_reader_cache"):
            del self._reader_cache
        super()._invalidate()


class BlackholeTable(ConnectorTable):
    """Null source/sink (reference: presto-blackhole) — swallows writes,
    scans empty; perf testing the write path without storage cost."""

    def __init__(self, name: str, schema: Dict[str, T.Type]):
        super().__init__(name, schema)
        self.rows_written = 0

    def row_count(self) -> int:
        return 0

    def splits(self, n_splits):
        return []

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        return {c: np.empty(0, dtype=self.schema[c].numpy_dtype()
                            if not self.schema[c].is_string else object)
                for c in cols}

    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        self.rows_written += n
        return n
