"""Local-file connector over the native shard format.

Reference parity: presto-local-file + the presto-raptor storage model
(ORC shards on local disk, metadata in a store); here a table is a
directory of .ptsh shard files written by the engine itself (CTAS /
INSERT target) and scanned with stripe-level zone-map pruning
(presto-orc's row-group pruning analog).

Snapshot layer (PR: writable engine): `schema.json` doubles as the
table MANIFEST — the authoritative, atomically-replaced (tmp +
os.replace) list of live shard files plus the recorded write layout
(bucketed_by / sorted_by / partitioned_by, exec/writer.py).  Writes
stage invisible `.stg` files and publish by renaming + rewriting the
manifest in one generation bump; readers resolve their file list
through the manifest, so an in-flight reader keeps the previous
generation's files (retired files are garbage-collected one generation
later, or at DROP).  This is what makes CREATE OR REPLACE a
refresh-and-serve cut-over and localfile writes transactional
(transaction.py snapshots/restores the manifest)."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.catalog import ConnectorTable
from presto_tpu.connectors import PageSink, StagedFileSink, files_ordered
from presto_tpu.storage.shard import Domain, ShardReader, write_shard


class LocalFileTable(ConnectorTable):
    """A directory of shard files + a schema.json manifest sidecar."""

    # zone maps in the PTSH stripes serve the engine's TupleDomain
    # pushdown (plan/domains.py -> read(domains=...))
    supports_domain_pushdown = True

    def __init__(self, name: str, directory: str,
                 schema: Optional[Dict[str, T.Type]] = None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        meta_path = os.path.join(directory, "schema.json")
        if schema is None:
            with open(meta_path) as f:
                meta = json.load(f)
            schema = {c: T.parse_type(t) for c, t in meta["schema"].items()}
            self._manifest = meta
            if "shards" not in meta:
                # legacy directory (no manifest): adopt the files present
                self._manifest["shards"] = [
                    p for p in sorted(os.listdir(directory))
                    if p.endswith(".ptsh")]
        else:
            self._manifest = {
                "schema": {c: str(t) for c, t in schema.items()},
                "shards": [], "retired": [], "file_meta": {},
                "write_props": None, "layout_ordered": False,
                "generation": 0}
            self._write_manifest()
        super().__init__(name, schema)

    # ---- manifest (the snapshot layer) -------------------------------
    def _write_manifest(self) -> None:
        meta_path = os.path.join(self.dir, "schema.json")
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._manifest, f)
        os.replace(tmp, meta_path)  # atomic publish

    def snapshot_state(self) -> dict:
        """Transactional snapshot: the manifest IS the table state
        (files are immutable once published)."""
        return json.loads(json.dumps(self._manifest))

    def restore_state(self, state: dict) -> None:
        self._manifest = state
        self.schema = {c: T.parse_type(t)
                       for c, t in state.get("schema", {}).items()} \
            or self.schema  # a replace may have changed the schema
        self._write_manifest()
        self._invalidate()

    def write_properties(self) -> Optional[dict]:
        return self._manifest.get("write_props")

    def record_write_properties(self, props: Optional[dict],
                                ordered: bool = False) -> None:
        """Declare a layout on an (empty) table — CREATE TABLE ... WITH
        (sorted_by=...); later INSERTs apply and re-verify it."""
        self._manifest["write_props"] = props
        self._manifest["layout_ordered"] = bool(ordered)
        self._write_manifest()

    def ordering(self) -> List[Tuple[str, bool]]:
        """The recorded sort order, claimed ONLY when the committed file
        sequence verified as globally nondecreasing (layout_ordered) —
        consumed by ordering-aware execution behind the same runtime
        monotonicity guards as generator declarations."""
        wp = self._manifest.get("write_props")
        if not wp or not self._manifest.get("layout_ordered"):
            return []
        return [(c, bool(a)) for c, a in wp.get("sorted_by", [])]

    #: how many generations a retired file outlives its retirement —
    #: 1 (default) keeps it through the next commit for in-flight
    #: readers; MV backing tables raise it to 2 so a long-poll reader
    #: spanning TWO consecutive refreshes still resolves every file
    retire_depth = 1

    def _commit_write(self, new_files: List[str], file_meta: Dict[str, dict],
                      write_props: Optional[dict], replace: bool,
                      schema: Optional[Dict[str, T.Type]] = None,
                      gc: bool = False) -> None:
        """Atomic publish of a staged write: adopt the new files (after
        the old ones unless replacing), optionally garbage-collect files
        retired by PREVIOUS generations (kept at least `retire_depth`
        generations for in-flight readers; `gc` stays False while a
        transaction could still roll the manifest back), verify the
        ordering claim over the resulting file sequence, and rewrite the
        manifest in one os.replace."""
        m = self._manifest
        old_shards = [] if replace else list(m.get("shards", []))
        shards = old_shards + new_files
        meta = dict(m.get("file_meta", {}))
        if replace:
            meta = {}
        meta.update(file_meta)
        # generation-stamped retirement: entries are [retire_gen, name]
        # (legacy bare names adopt the previous generation's stamp)
        cur_gen = int(m.get("generation", 0))
        new_gen = cur_gen + 1
        prev_retired = [e if isinstance(e, list) else [cur_gen, e]
                        for e in m.get("retired", [])]
        retired = prev_retired + (
            [[new_gen, p] for p in m.get("shards", [])] if replace else [])
        if gc:
            depth = max(1, int(getattr(self, "retire_depth", 1)))
            keep = []
            for rg, p in retired:
                if int(rg) <= new_gen - depth:
                    try:
                        os.remove(os.path.join(self.dir, p))
                    except OSError:
                        pass
                else:
                    keep.append([rg, p])
            retired = keep
        wp = write_props if write_props is not None \
            else (None if replace else m.get("write_props"))
        sorted_by = (wp or {}).get("sorted_by") or []
        ordered = bool(sorted_by) and all(a for _c, a in sorted_by) \
            and files_ordered([(meta.get(s) or {}).get("ranges")
                               for s in shards])
        if schema is not None:
            self.schema = dict(schema)
            m["schema"] = {c: str(t) for c, t in schema.items()}
        m["shards"] = shards
        m["retired"] = retired
        m["file_meta"] = {s: meta[s] for s in shards if s in meta}
        m["write_props"] = wp
        m["layout_ordered"] = bool(ordered)
        m["generation"] = new_gen
        # MV watermark stamp: rides the SAME os.replace as the data
        # commit, so the snapshot and the source coverage it claims are
        # atomic (exec/matview.py sets the pending stamp pre-commit)
        stamp = getattr(self, "_mv_stamp", None)
        if stamp is not None:
            m["mv"] = stamp
            self._mv_stamp = None
        self._write_manifest()
        self._invalidate()

    # ---- MV watermarks (consumed by connectors/delta.py) -------------
    def set_mv_stamp(self, stamp: Optional[dict]) -> None:
        """Queue an MV watermark record to publish with the NEXT commit."""
        self._mv_stamp = stamp

    def mv_watermarks(self) -> Optional[dict]:
        return self._manifest.get("mv")

    # ---- read path ---------------------------------------------------
    def _shards(self) -> List[str]:
        return [os.path.join(self.dir, p)
                for p in self._manifest.get("shards", [])]

    def _readers(self) -> List[ShardReader]:
        paths = tuple(self._shards())
        cached = getattr(self, "_reader_cache", None)
        if cached is None or cached[0] != paths:
            self._reader_cache = (paths, [ShardReader(p) for p in paths])
        return self._reader_cache[1]

    def row_count(self) -> int:
        return sum(r.nrows for r in self._readers())

    def splits(self, n_splits: int) -> List[Tuple[int, int]]:
        n = self.row_count()
        edges = np.linspace(0, n, n_splits + 1).astype(int)
        return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:]) if a < b]

    def read(self, columns=None, split=None,
             domains: Optional[Dict[str, Domain]] = None) -> Dict[str, np.ndarray]:
        """Read columns, decoding only what is needed: a split maps to
        the overlapping stripes (stripe = the IO unit, as in the
        reference's ORC row groups), and zone-map domains prune stripes
        before any frame is decompressed."""
        cols = columns if columns is not None else list(self.schema)
        parts: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        base = 0  # global row offset of the current reader
        a, b = split if split is not None else (0, None)
        for r in self._readers():
            if b is not None and base >= b:
                break
            pruned = set(r.select_stripes(domains)) if domains else None
            take = []
            slices = []
            for si, (s0, s1) in enumerate(r.stripe_row_ranges()):
                g0, g1 = base + s0, base + s1  # stripe's global row range
                lo = max(g0, a)
                hi = g1 if b is None else min(g1, b)
                if lo >= hi:
                    continue
                if pruned is not None and si not in pruned:
                    continue
                take.append(si)
                slices.append((lo - g0, hi - g0))
            if take:
                data = r.read(cols, take)
                # offsets of each taken stripe within the concatenated read
                ranges = r.stripe_row_ranges()
                concat_off = 0
                for si, (s_lo, s_hi) in zip(take, slices):
                    n_stripe = ranges[si][1] - ranges[si][0]
                    for c in cols:
                        parts[c].append(
                            data[c][concat_off + s_lo:concat_off + s_hi])
                    concat_off += n_stripe
            base += r.nrows
        out = {}
        for c in cols:
            out[c] = (np.concatenate(parts[c]) if parts[c]
                      else np.empty(0, self.schema[c].numpy_dtype()
                                    if not self.schema[c].is_string else object))
        return out

    def pruned_stats(self, domains: Optional[Dict[str, Domain]]):
        """(kept_stripes, total_stripes) — observability for EXPLAIN/tests."""
        kept = total = 0
        for r in self._readers():
            total += r.n_stripes
            kept += len(r.select_stripes(domains))
        return kept, total

    # ---- write path (reference: ConnectorPageSinkProvider) -----------
    #: rows per writer page; appends above one page scale writers (P4)
    WRITER_PAGE_ROWS = 262_144
    #: writers scale up while backlog > this many pages per active
    #: writer (reference: ScaledWriterScheduler.java scales tasks while
    #: buffered bytes outpace the running writers)
    SCALE_UP_BACKLOG = 2
    MAX_WRITERS = 4

    sink_file_prefix = "shard"
    sink_file_ext = ".ptsh"

    def _sink_write_file(self, path: str, arrays, schema) -> None:
        write_shard(path, arrays, schema)

    def page_sink(self, write_props=None, replace: bool = False,
                  schema: Optional[Dict[str, T.Type]] = None,
                  defer_gc: bool = False) -> PageSink:
        return StagedFileSink(self, write_props, replace=replace,
                              schema=schema, defer_gc=bool(defer_gc))

    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        """Bulk append (legacy SPI, kept for the scaled-writer path):
        pages fan out over writer threads into ONE staged sink, then
        commit atomically.  Engine statements route through
        exec/writer.py instead; this surface serves direct API users and
        the P4 scaled-writer redistribution."""
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        sink = self.page_sink()
        pages = -(-n // self.WRITER_PAGE_ROWS)
        try:
            if pages <= 1:
                sink.append_page({c: arrays[c] for c in self.schema})
                self.last_writers_used = 1
            else:
                self._scaled_append(sink, arrays, n, pages)
            sink.finish()
        except BaseException:
            sink.abort()
            raise
        return n

    def _scaled_append(self, sink: "LocalFilePageSink", arrays,
                       n: int, pages: int) -> None:
        """P4 scaled-writer redistribution, local adaptation (reference:
        execution/scheduler/ScaledWriterScheduler.java — writer tasks
        start at one and scale up while the produced-page backlog
        outpaces the active writers).  Here the writers are shard-writer
        threads; each page becomes one staged shard file whose explicit
        seq preserves row order, so the readers' split/stripe machinery
        parallelizes the read back."""
        import queue
        import threading

        q: "queue.Queue" = queue.Queue()
        for p in range(pages):
            lo = p * self.WRITER_PAGE_ROWS
            hi = min(n, lo + self.WRITER_PAGE_ROWS)
            q.put((p, lo, hi))
        errors: List[BaseException] = []

        def writer():
            while True:
                try:
                    idx, lo, hi = q.get_nowait()
                except queue.Empty:
                    return
                try:
                    sink.append_page({c: arrays[c][lo:hi]
                                      for c in self.schema}, seq=idx)
                except BaseException as e:  # surfaced to the caller
                    errors.append(e)
                finally:
                    q.task_done()

        threads = [threading.Thread(target=writer, daemon=True)]
        threads[0].start()
        # scale-up loop: add a writer while the backlog stays above
        # SCALE_UP_BACKLOG pages per active writer
        while not q.empty() and len(threads) < self.MAX_WRITERS:
            if q.qsize() > self.SCALE_UP_BACKLOG * len(threads):
                t = threading.Thread(target=writer, daemon=True)
                t.start()
                threads.append(t)
            else:
                break
        q.join()
        for t in threads:
            t.join(timeout=60.0)
        self.last_writers_used = len(threads)
        if errors:
            raise errors[0]

    def delete_where(self, keep_mask: np.ndarray) -> int:
        """Rewrite shards keeping only masked rows (reference: Raptor
        compaction-style delete; row-level deletes rewrite the shard)."""
        data = self.read()
        deleted = int((~keep_mask).sum())
        kept = {c: v[keep_mask] for c, v in data.items()}
        new_files: List[str] = []
        if len(next(iter(kept.values()), [])) > 0:
            gen = int(self._manifest.get("generation", 0)) + 1
            fname = f"shard_g{gen:04d}_000000.ptsh"
            write_shard(os.path.join(self.dir, fname), kept, self.schema)
            new_files = [fname]
        # the rewrite RETIRES the old shards (GC'd by a later commit /
        # drop) so a transactional rollback can restore the pre-delete
        # manifest; the layout's ordering claim dies with the rewrite
        self._commit_write(new_files, {}, None, replace=True)
        return deleted

    def drop_data(self) -> None:
        """Remove managed storage on DROP TABLE (the table owns its
        directory; leaving shards behind would resurrect old data on a
        same-name re-create).  Removes live, retired, AND staged files."""
        for p in os.listdir(self.dir):
            if p.endswith(".ptsh") or p.endswith(".stg") \
                    or p == "schema.json":
                try:
                    os.remove(os.path.join(self.dir, p))
                except OSError:
                    pass
        self._manifest = {"schema": self._manifest.get("schema", {}),
                          "shards": [], "retired": [], "file_meta": {},
                          "write_props": None, "layout_ordered": False,
                          "generation": 0}
        self._invalidate()

    def _invalidate(self):
        if hasattr(self, "_reader_cache"):
            del self._reader_cache
        super()._invalidate()


class BlackholeTable(ConnectorTable):
    """Null source/sink (reference: presto-blackhole) — swallows writes,
    scans empty; perf testing the write path without storage cost."""

    def __init__(self, name: str, schema: Dict[str, T.Type]):
        super().__init__(name, schema)
        self.rows_written = 0

    def row_count(self) -> int:
        return 0

    def splits(self, n_splits):
        return []

    def read(self, columns=None, split=None):
        cols = columns if columns is not None else list(self.schema)
        return {c: np.empty(0, dtype=self.schema[c].numpy_dtype()
                            if not self.schema[c].is_string else object)
                for c in cols}

    def append(self, arrays: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(arrays.values()))) if arrays else 0
        self.rows_written += n
        return n
