"""Source-delta detection for incremental materialized views.

A materialized-view snapshot records, per source table, a WATERMARK
describing exactly what the snapshot covers (file set + manifest
generation for manifest-backed connectors, row count + delete epoch for
in-memory tables).  REFRESH diffs the current source state against the
recorded watermark and classifies the change:

  empty   -- nothing new; refresh is a no-op
  append  -- only new rows/files past the watermark; refresh runs the
             view query over JUST the delta row range and folds it in
  full    -- anything else (files vanished, rows deleted, table object
             replaced, schema drift): degrade LOUDLY to full recompute
             -- counted, never wrong

This module is the ONLY place outside exec/writer.py that reads raw
manifest generation fields (enforced by tests/test_lint.py); the plan
and server layers consume verdicts, not generations.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class DeltaVerdict:
    """Outcome of diffing a source table against a recorded watermark."""

    kind: str  # "empty" | "append" | "full"
    reason: str = ""
    #: global row range [a, b) holding exactly the appended rows
    row_range: Optional[Tuple[int, int]] = None
    #: appended file/split count vs the source's total (counters)
    delta_splits: int = 0
    total_splits: int = 0


def capture(table) -> dict:
    """Watermark for `table` as of NOW (stamped into the MV manifest at
    refresh commit, so coverage and data publish atomically)."""
    manifest = getattr(table, "_manifest", None)
    if manifest is not None and "shards" in manifest:
        return {
            "kind": "files",
            "generation": int(manifest.get("generation", 0)),
            "files": list(manifest.get("shards", [])),
            "row_count": int(table.row_count()),
        }
    return {
        "kind": "rows",
        "row_count": int(table.row_count()),
        "epoch": int(getattr(table, "_mv_delete_epoch", 0)),
        "obj": id(table),
    }


def diff(table, recorded: Optional[dict]) -> DeltaVerdict:
    """Classify what changed in `table` since `recorded` (a dict from
    capture()).  None / unrecognized watermarks force a full recompute."""
    if not recorded:
        return DeltaVerdict("full", reason="no recorded watermark")
    current = capture(table)
    if current["kind"] != recorded.get("kind"):
        return DeltaVerdict("full", reason="source storage kind changed")

    if current["kind"] == "files":
        old_files = list(recorded.get("files", []))
        new_files = list(current["files"])
        total = max(len(new_files), 1)
        if current["generation"] == recorded.get("generation") \
                and new_files == old_files:
            return DeltaVerdict("empty", row_range=(0, 0),
                                total_splits=total)
        # append-only iff every recorded file is still live, as a prefix
        # (appends add files at the END of the manifest's shard list)
        if new_files[:len(old_files)] != old_files:
            return DeltaVerdict(
                "full", reason="recorded files retired or reordered "
                "(replace/delete/compaction)", total_splits=total)
        a = int(recorded.get("row_count", 0))
        b = int(current["row_count"])
        if b < a:
            return DeltaVerdict("full", reason="source shrank",
                                total_splits=total)
        return DeltaVerdict(
            "append", row_range=(a, b),
            delta_splits=len(new_files) - len(old_files),
            total_splits=total)

    # rows watermark (memory tables, generator tables)
    if current["obj"] != recorded.get("obj"):
        return DeltaVerdict("full", reason="source table re-registered")
    if current["epoch"] != recorded.get("epoch", 0):
        return DeltaVerdict("full", reason="source saw deletes")
    a = int(recorded.get("row_count", 0))
    b = int(current["row_count"])
    if b < a:
        return DeltaVerdict("full", reason="source shrank")
    if b == a:
        return DeltaVerdict("empty", row_range=(a, a), total_splits=1)
    return DeltaVerdict("append", row_range=(a, b), delta_splits=1,
                        total_splits=2)
