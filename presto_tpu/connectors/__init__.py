"""Connector SPI surface shared by every connector package.

Reference parity: presto-spi/.../spi/connector/ConnectorPageSinkProvider
+ ConnectorPageSink (PAPER.md §L4): a write is `begin -> appendPage* ->
finish` against a sink the CONNECTOR provides, never an ad-hoc
materialize-then-bulk-append.  The engine-side orchestration (bucket
partitioning, within-bucket sorting, layout verification, TableWriter /
TableFinish plan nodes) lives in exec/writer.py; this module owns only
the sink contract the connectors implement:

- `append_page(arrays, bucket=..., partition=...)` streams ONE host
  page into staged storage (a file sink writes a staged file per page,
  invisible to readers until commit);
- `finish()` publishes every staged page ATOMICALLY (file sinks rename
  + rewrite a manifest in one os.replace; in-flight readers holding the
  previous manifest keep reading the previous snapshot's files);
- `abort()` deletes staged output, leaving the table byte-identical.

Connectors without a native sink (memory, blackhole, hive) are adapted
through AppendPageSink, which forwards pages to the legacy
`table.append` — no staging, but the same streaming surface, so the
writer has ONE code path in all execution modes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class WriteResult:
    """What a committed sink reports back to TableFinish (reference:
    ConnectorPageSink.finish()'s fragments, collapsed to counters +
    the published file names)."""

    rows: int = 0
    bytes: int = 0
    files: List[str] = field(default_factory=list)


@dataclass
class PageMeta:
    """Per-page bookkeeping a sink records for every append_page call:
    the ordering-claim verifier (exec/writer.py) and the manifest's
    per-file pruning metadata both read it back at finish."""

    seq: int
    rows: int
    bucket: Optional[int] = None
    partition: Optional[tuple] = None  # (col, value) pairs
    # per-sort-column (min, max) over the page, in sorted_by order —
    # boundary monotonicity across the final file sequence is what
    # upgrades a per-file sort into a table-level ordering() claim
    key_ranges: Optional[list] = None


class PageSink:
    """One write's sink: begin (construction) -> append_page* -> finish
    | abort.  Implementations must tolerate append_page from several
    writer threads (distributed writes allocate page sequence numbers
    through _next_seq's lock)."""

    #: sinks that carry a null channel (parquet/orc definition levels,
    #: masked-array forwarding) accept masked pages; raw-array sinks
    #: must keep rejecting NULLs loudly (see executor null handling)
    supports_null_append = False

    def __init__(self):
        self._seq_lock = threading.Lock()
        self._seq = 0
        self.pages: List[PageMeta] = []
        self.finished: Optional[WriteResult] = None

    def _next_seq(self) -> int:
        with self._seq_lock:
            s = self._seq
            self._seq += 1
            return s

    def _record(self, meta: PageMeta) -> None:
        with self._seq_lock:
            self.pages.append(meta)

    # -- contract ------------------------------------------------------
    def append_page(self, arrays: Dict[str, np.ndarray],
                    bucket: Optional[int] = None,
                    partition: Optional[tuple] = None,
                    key_ranges: Optional[list] = None) -> int:
        raise NotImplementedError

    def finish(self) -> WriteResult:
        raise NotImplementedError

    def abort(self) -> None:
        raise NotImplementedError


class AppendPageSink(PageSink):
    """Adapter over the legacy `table.append` SPI (memory / blackhole /
    hive): pages forward immediately, finish is a no-op commit.  Not
    snapshot-isolated — connectors wanting staged atomic publishes
    implement page_sink() natively (localfile/parquet/orc)."""

    def __init__(self, table):
        super().__init__()
        self.table = table
        self._rows = 0
        self._bytes = 0

    @property
    def supports_null_append(self):  # delegate to the table's declaration
        return bool(getattr(self.table, "supports_null_append", False))

    def append_page(self, arrays, bucket=None, partition=None,
                    key_ranges=None) -> int:
        seq = self._next_seq()
        n = self.table.append(dict(arrays))
        self._rows += n
        self._bytes += sum(int(getattr(a, "nbytes", 0))
                           for a in arrays.values())
        self._record(PageMeta(seq=seq, rows=n, bucket=bucket,
                              partition=partition, key_ranges=key_ranges))
        return n

    def finish(self) -> WriteResult:
        if self.finished is None:
            self.finished = WriteResult(rows=self._rows, bytes=self._bytes)
        return self.finished

    def abort(self) -> None:
        # pages were applied eagerly; transactional undo (pre-image /
        # manifest snapshot) is the transaction manager's job
        pass


def files_ordered(ranges_seq) -> bool:
    """Verifier shared by the writer and the file-sink commits: given
    each file's [first-row, last-row] sort-key tuples IN FILE ORDER,
    True iff the concatenated scan is globally nondecreasing — every
    file internally sorted (first <= last is implied by how the writer
    produces ranges) and every boundary lexicographically monotone.
    Any file without ranges makes the sequence unverifiable (False)."""
    prev_last = None
    for kr in ranges_seq:
        if not kr or len(kr) != 2:
            return False
        first, last = kr[0], kr[1]
        if prev_last is not None and tuple(first) < tuple(prev_last):
            return False
        prev_last = last
    return True


class StagedFileSink(PageSink):
    """Staged file sink shared by the file connectors (localfile PTSH
    shards, parquet parts, orc parts): every append_page writes one
    invisible `.stg` file; finish renames them (partition-major, then
    bucket, then append seq) and publishes through the table's manifest
    commit in one atomic step (reference: HivePageSink's staging
    directory + the metastore commit).

    The table provides three hooks:
      - `_sink_write_file(path, arrays, schema)` encodes one page;
      - `_commit_write(new_files, file_meta, write_props, replace,
        schema, gc)` publishes the manifest;
      - `sink_file_prefix` / `sink_file_ext` name the final files.
    """

    def __init__(self, table, write_props=None, replace: bool = False,
                 schema=None, defer_gc: bool = False):
        super().__init__()
        self.table = table
        self.write_props = write_props
        self.replace = replace
        self.schema_override = schema
        self.defer_gc = defer_gc
        import itertools as _it
        import os as _os

        cnt = getattr(type(self), "_stage_counter", None)
        if cnt is None:
            cnt = type(self)._stage_counter = _it.count()
        self.token = f"{_os.getpid():x}-{next(cnt):x}"
        self._staged: Dict[int, tuple] = {}  # seq -> (meta, staged path)
        self._bytes = 0

    @property
    def supports_null_append(self):
        return bool(getattr(self.table, "supports_null_append", False))

    def append_page(self, arrays, bucket=None, partition=None,
                    key_ranges=None, seq=None) -> int:
        import os as _os

        schema = self.schema_override or self.table.schema
        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            return 0
        if seq is None:
            s = self._next_seq()
        else:
            s = seq
            with self._seq_lock:  # explicit seqs must not collide with
                self._seq = max(self._seq, s + 1)  # allocated ones
        d = getattr(self.table, "dir", None) or self.table.path
        path = _os.path.join(d, f".stg-{self.token}-{s:06d}.stg")
        self.table._sink_write_file(path, {c: arrays[c] for c in schema},
                                    schema)
        nbytes = _os.path.getsize(path)
        meta = PageMeta(seq=s, rows=n, bucket=bucket, partition=partition,
                        key_ranges=key_ranges)
        with self._seq_lock:
            self._staged[s] = (meta, path)
            self._bytes += nbytes
        self._record(meta)
        return n

    def finish(self) -> WriteResult:
        import os as _os

        if self.finished is not None:
            return self.finished
        # publish order: partition-major, then bucket, then append seq —
        # range-bucketed pages land in global sort order, hash buckets
        # land bucket-contiguous (split scans stay bucket-aligned)
        entries = sorted(
            self._staged.values(),
            key=lambda e: (e[0].partition is not None,
                           e[0].partition if e[0].partition is not None
                           else (), e[0].bucket is not None,
                           e[0].bucket if e[0].bucket is not None else -1,
                           e[0].seq))
        gen = int(self.table._manifest.get("generation", 0)) + 1
        d = getattr(self.table, "dir", None) or self.table.path
        new_files: List[str] = []
        file_meta: Dict[str, dict] = {}
        rows = 0
        for i, (meta, staged) in enumerate(entries):
            fname = f"{self.table.sink_file_prefix}_g{gen:04d}_{i:06d}"
            if meta.bucket is not None:
                fname += f"_b{meta.bucket:04d}"
            fname += self.table.sink_file_ext
            _os.replace(staged, _os.path.join(d, fname))
            new_files.append(fname)
            fm = {"rows": meta.rows}
            if meta.key_ranges is not None:
                fm["ranges"] = meta.key_ranges
            if meta.bucket is not None:
                fm["bucket"] = meta.bucket
            if meta.partition is not None:
                fm["partition"] = [[c, v] for c, v in meta.partition]
            file_meta[fname] = fm
            rows += meta.rows
        wp = self.write_props
        wp_dict = wp.to_dict() if hasattr(wp, "to_dict") else wp
        self.table._commit_write(new_files, file_meta, wp_dict,
                                 replace=self.replace,
                                 schema=self.schema_override,
                                 gc=not bool(self.defer_gc))
        self.finished = WriteResult(rows=rows, bytes=self._bytes,
                                    files=new_files)
        return self.finished

    def abort(self) -> None:
        import os as _os

        for _meta, path in self._staged.values():
            try:
                _os.remove(path)
            except OSError:
                pass
        self._staged.clear()


def open_sink(table, write_props=None, defer_gc: bool = False) -> PageSink:
    """The engine's getPageSinkProvider dispatch: a connector exposing
    `page_sink` provides a staged sink; anything else with `append`
    adapts through AppendPageSink.  `defer_gc` (an open transaction
    could still roll the manifest back) keeps retired generations on
    disk through the commit."""
    fn = getattr(table, "page_sink", None)
    if fn is not None:
        return fn(write_props, defer_gc=defer_gc)
    if hasattr(table, "append"):
        return AppendPageSink(table)
    raise TypeError(f"table '{getattr(table, 'name', table)}' does not "
                    "support writes (no page_sink / append SPI)")
