"""Access control + session property managers.

Reference parity:
- security/AccessControlManager + the file-based access control in
  presto-plugin-toolkit: pluggable checks on table read/write/DDL,
  rule-matched by (user, table-name regex) with ordered first-match.
- presto-session-property-managers: rule-based session property
  overrides matched on (user, source) applied at query submit.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional


class AccessDeniedError(Exception):
    pass


class AccessControl:
    """Interface (reference: spi/security/SystemAccessControl).  The
    default allows everything (the reference's AllowAllAccessControl)."""

    def check_can_select(self, user: str, table: str) -> None:
        pass

    def check_can_insert(self, user: str, table: str) -> None:
        pass

    def check_can_delete(self, user: str, table: str) -> None:
        pass

    def check_can_create_table(self, user: str, table: str) -> None:
        pass

    def check_can_drop_table(self, user: str, table: str) -> None:
        pass

    def check_can_set_session_property(self, user: str, name: str) -> None:
        pass


ALLOW_ALL = AccessControl()


class FileBasedAccessControl(AccessControl):
    """Ordered first-match rules (reference: FileBasedSystemAccessControl
    rules.json):

    {"tables": [{"user": "etl.*", "table": "tmp_.*",
                 "privileges": ["SELECT", "INSERT", "DELETE", "OWNERSHIP"]},
                {"table": ".*", "privileges": ["SELECT"]}]}

    Absent a matching rule, access is denied (reference default)."""

    def __init__(self, config: dict):
        self.rules = []
        for r in config.get("tables", []):
            self.rules.append((
                re.compile(r.get("user", ".*")),
                re.compile(r.get("table", ".*")),
                frozenset(p.upper() for p in r.get("privileges", []))))

    def _privileges(self, user: str, table: str) -> frozenset:
        for user_re, table_re, privs in self.rules:
            if user_re.fullmatch(user or "") and table_re.fullmatch(table):
                return privs
        return frozenset()

    def _check(self, user, table, priv):
        if priv not in self._privileges(user, table):
            raise AccessDeniedError(
                f"Access Denied: user '{user}' cannot {priv} table '{table}'")

    def check_can_select(self, user, table):
        self._check(user, table, "SELECT")

    def check_can_insert(self, user, table):
        self._check(user, table, "INSERT")

    def check_can_delete(self, user, table):
        self._check(user, table, "DELETE")

    def check_can_create_table(self, user, table):
        self._check(user, table, "OWNERSHIP")

    def check_can_drop_table(self, user, table):
        self._check(user, table, "OWNERSHIP")


class SessionPropertyManager:
    """Rule-based property defaults applied at query submit (reference:
    AbstractSessionPropertyManager; config shape mirrors
    session-property-config.json):

    [{"user": "etl.*", "source": null,
      "sessionProperties": {"spill_enabled": true}}]
    """

    def __init__(self, rules: Optional[List[dict]] = None):
        self.rules = []
        for r in rules or []:
            self.rules.append((
                re.compile(r["user"]) if r.get("user") else None,
                re.compile(r["source"]) if r.get("source") else None,
                dict(r.get("sessionProperties", {}))))

    def overrides(self, user: str = "", source: str = "") -> Dict[str, object]:
        """ALL matching rules apply, later rules win (reference:
        SessionPropertyConfigurationManager semantics)."""
        out: Dict[str, object] = {}
        for user_re, source_re, props in self.rules:
            if user_re is not None and not user_re.fullmatch(user or ""):
                continue
            if source_re is not None and not source_re.fullmatch(source or ""):
                continue
            out.update(props)
        return out


class AuthenticationError(Exception):
    pass


class PasswordAuthenticator:
    """Base authenticator SPI (reference:
    presto-spi/.../security/PasswordAuthenticator + the
    presto-password-authenticators plugin module)."""

    def authenticate(self, user: str, password: str) -> str:
        """Returns the authenticated principal or raises."""
        raise NotImplementedError


class FilePasswordAuthenticator(PasswordAuthenticator):
    """htpasswd-style credential file (reference: the
    password-authenticators plugin's file-based authenticator).  Lines are
    `user:{scheme}hash`; supported schemes: {pbkdf2} (default for new
    hashes: pbkdf2_hmac-sha256, iterations$salt$hexdigest), {sha256}
    (legacy single-round salt$hexdigest — accepted but weak), and {plain}
    (tests only)."""

    PBKDF2_ITERATIONS = 120_000

    def __init__(self, path: str):
        self.creds = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                user, spec = line.split(":", 1)
                self.creds[user] = spec

    @classmethod
    def hash_password(cls, password: str, salt: str = "") -> str:
        import hashlib
        import secrets

        salt = salt or secrets.token_hex(16)  # per-user random salt
        d = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt.encode(),
            cls.PBKDF2_ITERATIONS).hex()
        return "{pbkdf2}" + f"{cls.PBKDF2_ITERATIONS}${salt}${d}"

    def authenticate(self, user: str, password: str) -> str:
        import hashlib
        import hmac as _hmac

        spec = self.creds.get(user)
        if spec is None:
            raise AuthenticationError(f"unknown user '{user}'")
        if spec.startswith("{plain}"):
            ok = _hmac.compare_digest(spec[len("{plain}"):], password)
        elif spec.startswith("{pbkdf2}"):
            try:
                iters, salt, digest = spec[len("{pbkdf2}"):].split("$", 2)
                d = hashlib.pbkdf2_hmac(
                    "sha256", password.encode(), salt.encode(),
                    int(iters)).hex()
            except (ValueError, OverflowError):
                raise AuthenticationError("malformed pbkdf2 credential")
            ok = _hmac.compare_digest(digest, d)
        elif spec.startswith("{sha256}"):
            salt, _, digest = spec[len("{sha256}"):].partition("$")
            d = hashlib.sha256((salt + "$" + password).encode()).hexdigest()
            ok = _hmac.compare_digest(digest, d)
        else:
            raise AuthenticationError("unsupported credential scheme")
        if not ok:
            raise AuthenticationError("invalid credentials")
        return user
