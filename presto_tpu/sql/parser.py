"""SQL lexer + recursive-descent/Pratt parser.

Reference parity: presto-parser's ANTLR4 grammar SqlBase.g4 (785 lines) +
SqlParser.java.  Hand-rolled (no parser generator in the image) covering
the query-language subset the engine executes: full TPC-H, joins of all
types, subqueries (scalar/IN/EXISTS), CTEs, set operations, window
functions, CASE/CAST/EXTRACT/INTERVAL, EXPLAIN [ANALYZE], SHOW,
CREATE TABLE AS, INSERT, SET SESSION.
"""

from __future__ import annotations

import re
from typing import List

from presto_tpu.sql import ast


class ParseError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"""
      (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
    | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
    | (?P<string>'(?:[^']|'')*')
    | (?P<qident>"(?:[^"]|"")*")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
    | (?P<op><>|!=|>=|<=|\|\||=>|->|[-+*/%(),.;=<>\[\]?])
    """,
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "AS", "AND", "OR", "NOT", "IN", "EXISTS", "BETWEEN", "LIKE", "ESCAPE",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "CAST", "TRY_CAST", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER",
    "CROSS", "ON", "USING", "UNION", "INTERSECT", "EXCEPT", "ALL", "DISTINCT",
    "WITH", "ASC", "DESC", "NULLS", "FIRST", "LAST", "DATE", "TIME",
    "TIMESTAMP", "INTERVAL", "EXTRACT", "SUBSTRING", "FOR", "VALUES",
    "EXPLAIN", "ANALYZE", "SHOW", "TABLES", "COLUMNS", "CREATE", "TABLE",
    "INSERT", "INTO", "SET", "SESSION", "OVER", "PARTITION", "ROWS", "RANGE",
    "UNBOUNDED", "PRECEDING", "FOLLOWING", "CURRENT", "ROW", "UNNEST",
    "ORDINALITY", "FILTER", "DROP", "DELETE", "IF", "START", "TRANSACTION",
    "COMMIT", "ROLLBACK", "READ", "ONLY", "WRITE", "PREPARE", "EXECUTE",
    "DEALLOCATE", "USING", "ROLLUP", "CUBE",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind  # 'number' | 'string' | 'ident' | 'kw' | 'op' | 'eof'
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


def tokenize(text: str) -> List[Token]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise ParseError(f"lex error at {pos}: {text[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "ident":
            up = val.upper()
            if up in KEYWORDS:
                out.append(Token("kw", up, m.start()))
            else:
                out.append(Token("ident", val.lower(), m.start()))
        elif kind == "qident":
            out.append(Token("ident", val[1:-1].replace('""', '"'), m.start()))
        elif kind == "string":
            out.append(Token("string", val[1:-1].replace("''", "'"), m.start()))
        else:
            out.append(Token(kind, val, m.start()))
    out.append(Token("eof", None, len(text)))
    return out


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0
        self._n_params = 0  # `?` placeholders seen, in textual order

    # ---- token helpers ----------------------------------------------
    def peek(self, ahead=0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def accept_op(self, *ops) -> bool:
        if self.at_op(*ops):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw) -> None:
        if not self.accept_kw(kw):
            self.err(f"expected {kw}")

    def expect_op(self, op) -> None:
        if not self.accept_op(op):
            self.err(f"expected '{op}'")

    def err(self, msg):
        t = self.peek()
        ctx = self.text[max(0, t.pos - 30): t.pos + 30]
        raise ParseError(f"{msg} at position {t.pos} near {ctx!r} (got {t!r})")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            self.i += 1
            return t.value
        # keywords usable as identifiers in non-reserved positions
        if t.kind == "kw" and t.value in ("DATE", "TIME", "TIMESTAMP", "VALUES",
                                          "FILTER", "ROW", "ANALYZE", "SESSION",
                                          "TABLES", "COLUMNS", "FIRST", "LAST",
                                          "ALL", "SET", "SHOW", "IF",
                                          # txn words are only consumed at
                                          # statement starts — non-reserved
                                          "START", "TRANSACTION", "COMMIT",
                                          "ROLLBACK", "READ", "ONLY", "WRITE"):
            self.i += 1
            return t.value.lower()
        self.err("expected identifier")

    def dotted_name(self) -> str:
        """catalog.schema.table target names in DDL/DML (reference:
        qualifiedName in SqlBase.g4 used by CREATE/DROP/INSERT/DELETE)."""
        name = self.ident()
        while self.accept_op("."):
            name += "." + self.ident()
        return name

    # ---- statements -------------------------------------------------
    def parse_statement(self) -> ast.Statement:
        stmt = self._statement()
        self.accept_op(";")
        if self.peek().kind != "eof":
            self.err("unexpected trailing input")
        return stmt

    def _statement(self) -> ast.Statement:
        if self.accept_kw("EXPLAIN"):
            analyze = False
            etype = "LOGICAL"
            if self.accept_op("("):  # (TYPE ..., FORMAT ...) options
                while True:
                    if self._accept_word("TYPE"):
                        etype = str(self.ident()).upper()
                        if etype not in ("LOGICAL", "DISTRIBUTED",
                                         "VALIDATE", "IO"):
                            self.err(f"unknown EXPLAIN type {etype}")
                    elif self._accept_word("FORMAT"):
                        self.ident()  # TEXT only; accepted and ignored
                    else:
                        self.err("expected TYPE or FORMAT")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            if self.accept_kw("ANALYZE"):
                analyze = True
            return ast.Explain(self._statement(), analyze=analyze,
                               type_=etype)
        if self.accept_kw("SHOW"):
            if self.accept_kw("TABLES"):
                return ast.ShowTables()
            if self.accept_kw("COLUMNS"):
                self.expect_kw("FROM")
                return ast.ShowColumns(self.dotted_name())
            if self.accept_kw("CREATE"):
                self.expect_kw("TABLE")
                return ast.ShowCreateTable(self.dotted_name())
            if self._accept_word("FUNCTIONS"):
                return ast.ShowFunctions()
            if self.accept_kw("SESSION"):
                return ast.ShowSession()
            if self._accept_word("CATALOGS"):
                return ast.ShowCatalogs()
            if self._accept_word("SCHEMAS"):
                return ast.ShowSchemas()
            if self._accept_word("STATS"):
                self.expect_kw("FOR")
                return ast.ShowStats(self.dotted_name())
            if self._accept_word("MATERIALIZED"):
                if not self._accept_word("VIEWS"):
                    self.err("expected VIEWS after SHOW MATERIALIZED")
                return ast.ShowMaterializedViews()
            self.err("expected TABLES, COLUMNS, CREATE TABLE, FUNCTIONS, "
                     "SESSION, CATALOGS, SCHEMAS, STATS or MATERIALIZED "
                     "VIEWS")
        if self._accept_word("DESCRIBE") or self.accept_kw("DESC"):
            # DESCRIBE INPUT/OUTPUT <prepared>; DESCRIBE t == SHOW
            # COLUMNS FROM t (reference: SqlBase.g4)
            if self._accept_word("INPUT"):
                return ast.DescribeInput(self.ident())
            if self._accept_word("OUTPUT"):
                return ast.DescribeOutput(self.ident())
            return ast.ShowColumns(self.dotted_name())
        if self.accept_kw("CREATE"):
            or_replace = False
            if self.accept_kw("OR"):
                # CREATE OR REPLACE TABLE ... AS: atomic refresh cut-over
                if not self._accept_word("REPLACE"):
                    self.err("expected REPLACE after CREATE OR")
                or_replace = True
            if self._accept_word("MATERIALIZED"):
                if not self._accept_word("VIEW"):
                    self.err("expected VIEW after CREATE MATERIALIZED")
                if_not_exists = False
                if self.accept_kw("IF"):
                    self.expect_kw("NOT")
                    self.expect_kw("EXISTS")
                    if_not_exists = True
                name = self.dotted_name()
                props = self._with_properties()
                self.expect_kw("AS")
                return ast.CreateMaterializedView(
                    name, self.parse_query(), properties=props,
                    if_not_exists=if_not_exists, or_replace=or_replace)
            self.expect_kw("TABLE")
            if_not_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                if_not_exists = True
            name = self.dotted_name()
            if self.accept_op("("):  # CREATE TABLE t (col type, ...)
                if or_replace:
                    self.err("CREATE OR REPLACE requires AS <query>")
                columns = []
                while True:
                    cname = self.ident()
                    columns.append((cname, self._type_name()))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                props = self._with_properties()
                return ast.CreateTable(name, columns, props, if_not_exists)
            props = self._with_properties()
            self.expect_kw("AS")
            stmt = ast.CreateTableAs(name, self.parse_query())
            stmt.properties = props  # connector choice rides WITH(...)
            stmt.if_not_exists = if_not_exists
            stmt.or_replace = or_replace
            return stmt
        if self.accept_kw("DROP"):
            if self._accept_word("MATERIALIZED"):
                if not self._accept_word("VIEW"):
                    self.err("expected VIEW after DROP MATERIALIZED")
                if_exists = False
                if self.accept_kw("IF"):
                    self.expect_kw("EXISTS")
                    if_exists = True
                return ast.DropMaterializedView(self.dotted_name(),
                                                if_exists)
            self.expect_kw("TABLE")
            if_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return ast.DropTable(self.dotted_name(), if_exists)
        if self.accept_kw("DELETE"):
            self.expect_kw("FROM")
            name = self.dotted_name()
            where = None
            if self.accept_kw("WHERE"):
                where = self.expr()
            return ast.Delete(name, where)
        if self.accept_kw("PREPARE"):
            name = self.ident()
            self.expect_kw("FROM")
            # the remaining raw text IS the statement (parameters are `?`
            # placeholders, substituted at EXECUTE — reference:
            # QueryPreparer.prepare)
            start = self.peek().pos
            self.i = len(self.toks) - 1  # consume everything
            return ast.Prepare(name, self.text[start:].rstrip(" ;"))
        if self.accept_kw("EXECUTE"):
            name = self.ident()
            params = []
            if self.accept_kw("USING"):
                params.append(self.expr())
                while self.accept_op(","):
                    params.append(self.expr())
            return ast.Execute(name, params)
        if self.accept_kw("DEALLOCATE"):
            self.accept_kw("PREPARE")
            return ast.Deallocate(self.ident())
        if self.accept_kw("START"):
            self.expect_kw("TRANSACTION")
            read_only = False
            if self.accept_kw("READ"):
                if self.accept_kw("ONLY"):
                    read_only = True
                else:
                    self.expect_kw("WRITE")
            return ast.TransactionStatement("START", read_only)
        if self.accept_kw("COMMIT"):
            return ast.TransactionStatement("COMMIT")
        if self.accept_kw("ROLLBACK"):
            return ast.TransactionStatement("ROLLBACK")
        if self.accept_kw("INSERT"):
            self.expect_kw("INTO")
            name = self.dotted_name()
            cols = None
            if self.accept_op("("):
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            return ast.InsertInto(name, cols, self.parse_query())
        if self.at_kw("SET") and self.peek(1).kind == "kw" and self.peek(1).value == "SESSION":
            self.next(), self.next()
            name = self.dotted_name()
            self.expect_op("=")
            v = self.next()
            value = v.value
            if v.kind == "number":
                value = float(v.value) if "." in v.value else int(v.value)
            elif v.kind == "kw" and v.value in ("TRUE", "FALSE"):
                value = v.value == "TRUE"
            return ast.SetSession(name, value)
        if self._accept_word("REFRESH"):
            if not self._accept_word("MATERIALIZED"):
                self.err("expected MATERIALIZED VIEW after REFRESH")
            if not self._accept_word("VIEW"):
                self.err("expected VIEW after REFRESH MATERIALIZED")
            return ast.RefreshMaterializedView(self.dotted_name())
        return ast.QueryStatement(self.parse_query())

    # ---- queries ----------------------------------------------------
    def parse_query(self) -> ast.Query:
        ctes = []
        if self.accept_kw("WITH"):
            while True:
                name = self.ident()
                col_aliases = None
                if self.accept_op("("):
                    col_aliases = [self.ident()]
                    while self.accept_op(","):
                        col_aliases.append(self.ident())
                    self.expect_op(")")
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, q, col_aliases))
                if not self.accept_op(","):
                    break
        body = self._set_op_body()
        order_by, limit = self._order_limit()
        return ast.Query(body, order_by, limit, ctes)

    def _set_op_body(self):
        left = self._query_term()
        while self.at_kw("UNION", "INTERSECT", "EXCEPT"):
            op = self.next().value
            all_ = self.accept_kw("ALL")
            self.accept_kw("DISTINCT")
            right = self._query_term()
            left = ast.SetOp(op, all_, left, right)
        return left

    def _query_term(self):
        if self.accept_op("("):
            body = self._set_op_body()
            self.expect_op(")")
            return body
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return ast.QuerySpec(
                [ast.SelectItem(ast.Star())], from_=ast.ValuesRelation(rows)
            )
        return self._query_spec()

    def _order_limit(self):
        order_by = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self._sort_item())
            while self.accept_op(","):
                order_by.append(self._sort_item())
        limit = None
        if self.accept_kw("LIMIT"):
            t = self.next()
            if t.kind == "number":
                limit = int(t.value)
            elif t.kind == "kw" and t.value == "ALL":
                limit = None
            else:
                self.err("expected LIMIT count")
        return order_by, limit

    def _sort_item(self) -> ast.SortItem:
        e = self.expr()
        asc = True
        if self.accept_kw("ASC"):
            asc = True
        elif self.accept_kw("DESC"):
            asc = False
        nulls_first = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return ast.SortItem(e, asc, nulls_first)

    def _query_spec(self) -> ast.QuerySpec:
        self.expect_kw("SELECT")
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        else:
            self.accept_kw("ALL")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_ = None
        if self.accept_kw("FROM"):
            from_ = self._relation()
            while self.accept_op(","):
                right = self._relation()
                from_ = ast.Join("CROSS", from_, right)
        where = self.expr() if self.accept_kw("WHERE") else None
        group_by = []
        grouping_sets = None
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            if (self.peek().kind == "ident"
                    and self.peek().value == "grouping"
                    and self.peek(1).kind == "ident"
                    and self.peek(1).value == "sets"):
                # contextual keywords: GROUPING/SETS stay usable as
                # identifiers (non-reserved in the reference grammar)
                self.next(), self.next()
                grouping_sets = self._grouping_sets()
            elif self.at_kw("ROLLUP", "CUBE"):
                kind = self.next().value
                exprs = self._paren_expr_list()
                if kind == "ROLLUP":
                    grouping_sets = [exprs[:k] for k in
                                     range(len(exprs), -1, -1)]
                else:  # CUBE: all subsets, preserving expr order
                    grouping_sets = []
                    for mask in range((1 << len(exprs)) - 1, -1, -1):
                        grouping_sets.append(
                            [e for i, e in enumerate(exprs)
                             if mask & (1 << i)])
                group_by = list(exprs)
            else:
                group_by.append(self.expr())
                while self.accept_op(","):
                    group_by.append(self.expr())
        having = self.expr() if self.accept_kw("HAVING") else None
        spec = ast.QuerySpec(items, distinct, from_, where, group_by, having)
        spec.grouping_sets = grouping_sets
        return spec

    def _quantified(self, op: str, left):
        """`expr op ANY|SOME|ALL (subquery)` rewritten to the engine's
        existing subquery forms (reference: QuantifiedComparisonExpression,
        lowered by TransformQuantifiedComparisonApplyToLateralJoin):
          = ANY  -> IN          <> ALL -> NOT IN
          everything else -> a three-valued CASE over min/max/count
          scalar aggregates of the subquery (TRUE/FALSE/NULL exactly per
          SQL:2016 8.9, so NOT(...)/IS NULL stay correct)
        """
        t = self.peek()
        if not (t.kind == "ident" and t.value in ("any", "some")
                or t.kind == "kw" and t.value == "ALL"):
            return None
        # commit only on the full `ANY (SELECT ...` shape — any/some are
        # non-reserved and must keep working as column names on the RHS
        if not (self.peek(1).kind == "op" and self.peek(1).value == "("
                and self.peek(2).kind == "kw"
                and self.peek(2).value in ("SELECT", "WITH")):
            return None
        quant = "ANY" if t.value in ("any", "some") else "ALL"
        self.next()
        self.expect_op("(")
        q = self.parse_query()
        self.expect_op(")")
        # NB: the scalar rewrites below embed the subquery more than once
        # (so it plans/executes per reference) — correctness-first v1; the
        # reference lowers to one lateral join instead.

        def scalar_agg(agg, star=False):
            return ast.ScalarSubquery(ast.Query(body=ast.QuerySpec(
                select=[ast.SelectItem(ast.FunctionCall(
                    agg, [] if star else [ast.Identifier(("q_", "v_"))]))],
                from_=ast.SubqueryRelation(q, "q_", ["v_"]))))

        # Three-valued CASE lowering (SQL:2016 8.9): the result must be
        # NULL — not FALSE — when no definite answer exists, so it stays
        # correct under NOT / IS NULL.  Branch order encodes the decision
        # table; a NULL WHEN condition falls through to the next branch.
        null_lit = ast.Literal(None)
        true_l, false_l = ast.Literal(True), ast.Literal(False)
        left_null = ast.IsNull(left)
        empty = ast.BinaryOp("=", scalar_agg("count", star=True),
                             ast.Literal(0))
        # count(*) <> count(v_): NULL values present among the rows
        has_nulls = ast.BinaryOp("<>", scalar_agg("count", star=True),
                                 scalar_agg("count"))
        minv = lambda: scalar_agg("min")
        maxv = lambda: scalar_agg("max")

        def some_differs():  # TRUE iff a non-NULL element <> left
            return ast.BinaryOp(
                "OR", ast.BinaryOp("<>", minv(), left),
                ast.BinaryOp("<>", maxv(), left))

        if quant == "ANY":
            if op == "=":
                return ast.InSubquery(left, q, False)
            if op == "<>":
                return ast.Case(None, [(empty, false_l),
                                       (left_null, null_lit),
                                       (some_differs(), true_l),
                                       (has_nulls, null_lit)], false_l)
            # loosest bound: <: max, >: min (over non-NULL elements)
            ext = maxv() if op in ("<", "<=") else minv()
            return ast.Case(None, [(empty, false_l),
                                   (left_null, null_lit),
                                   (ast.BinaryOp(op, left, ext), true_l),
                                   (has_nulls, null_lit)], false_l)
        # ALL
        if op == "<>":
            return ast.InSubquery(left, q, True)  # <> ALL == NOT IN
        if op == "=":
            return ast.Case(None, [(empty, true_l),
                                   (left_null, null_lit),
                                   (some_differs(), false_l),
                                   (has_nulls, null_lit)], true_l)
        # tightest bound: <: min, >: max (over non-NULL elements)
        ext = minv() if op in ("<", "<=") else maxv()
        failed = ast.UnaryOp("NOT", ast.BinaryOp(op, left, ext))
        return ast.Case(None, [(empty, true_l),
                               (left_null, null_lit),
                               (failed, false_l),
                               (has_nulls, null_lit)], true_l)

    def _grouping_sets(self):
        """((a, b), (a), ()) — each set is a parenthesized expr list."""
        self.expect_op("(")
        sets = []
        while True:
            if self.at_op("("):
                sets.append(self._paren_expr_list())
            else:
                sets.append([self.expr()])  # bare expr = singleton set
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return sets

    def _paren_expr_list(self):
        self.expect_op("(")
        out = []
        if not self.at_op(")"):
            out.append(self.expr())
            while self.accept_op(","):
                out.append(self.expr())
        self.expect_op(")")
        return out

    def _select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        # t.* form
        if (self.peek().kind == "ident" and self.peek(1).kind == "op"
                and self.peek(1).value == "." and self.peek(2).kind == "op"
                and self.peek(2).value == "*"):
            q = self.next().value
            self.next(), self.next()
            return ast.SelectItem(ast.Star(qualifier=q))
        e = self.expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.peek().kind == "ident" \
                and str(self.peek().value).upper() != "TABLESAMPLE":
            # TABLESAMPLE is a sample clause, never an implicit alias
            # (reference: SqlBase.g4 reserves it)
            alias = self.next().value
        return ast.SelectItem(e, alias)

    # ---- relations --------------------------------------------------
    def _relation(self) -> ast.Relation:
        rel = self._relation_primary()
        while True:
            if self.accept_kw("CROSS"):
                self.expect_kw("JOIN")
                right = self._relation_primary()
                rel = ast.Join("CROSS", rel, right)
                continue
            jt = None
            if self.at_kw("JOIN"):
                jt = "INNER"
            elif self.at_kw("INNER") and self.peek(1).value == "JOIN":
                self.next()
                jt = "INNER"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                jt = self.peek().value
                nxt = self.peek(1)
                if nxt.kind == "kw" and nxt.value in ("JOIN", "OUTER"):
                    self.next()
                    self.accept_kw("OUTER")
                else:
                    jt = None
            if jt is None:
                break
            self.expect_kw("JOIN")
            right = self._relation_primary()
            if self.accept_kw("ON"):
                rel = ast.Join(jt, rel, right, on=self.expr())
            elif self.accept_kw("USING"):
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                rel = ast.Join(jt, rel, right, using=cols)
            else:
                self.err("expected ON or USING")
        return rel

    def _relation_primary(self) -> ast.Relation:
        if self.accept_kw("UNNEST"):
            self.expect_op("(")
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            with_ord = False
            if self.accept_kw("WITH"):
                self.expect_kw("ORDINALITY")
                with_ord = True
            alias, col_aliases = self._alias()
            u = ast.Unnest(exprs, alias, with_ord)
            u.column_aliases = col_aliases
            return u
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            alias, col_aliases = self._alias()
            return ast.ValuesRelation(rows, alias, col_aliases)
        if self.accept_op("("):
            # subquery or parenthesized join
            if self.at_kw("SELECT", "WITH") or (self.at_op("(")):
                q = self.parse_query()
                self.expect_op(")")
                alias, col_aliases = self._alias()
                return ast.SubqueryRelation(q, alias, col_aliases)
            rel = self._relation()
            self.expect_op(")")
            alias, col_aliases = self._alias()
            if alias is not None and hasattr(rel, "alias"):
                rel.alias = alias
                if col_aliases and hasattr(rel, "column_aliases"):
                    rel.column_aliases = col_aliases
            return rel
        name = self.dotted_name()  # catalog.schema.table — full dotted name
        alias, col_aliases = self._alias()
        t = ast.Table(name, alias, col_aliases)
        if self._accept_word("TABLESAMPLE"):
            # reference: SqlBase.g4 sampledRelation — alias precedes the
            # sample clause; accept one after too when none came before
            method = str(self.ident()).upper()
            if method not in ("BERNOULLI", "SYSTEM"):
                self.err("expected BERNOULLI or SYSTEM")
            self.expect_op("(")
            tok = self.next()
            if tok.kind != "number":
                self.err("expected a sample percentage")
            self.expect_op(")")
            t.sample = (method, float(tok.value))
            if t.alias is None:
                t.alias, t.column_aliases = self._alias()
        return t

    def _accept_word(self, word: str) -> bool:
        """Match a non-reserved word (parsed as an identifier) without
        growing the KEYWORDS set — SHOW FUNCTIONS must not reserve
        'functions' as a column name."""
        tok = self.peek()
        if tok.kind == "ident" and str(tok.value).upper() == word:
            self.next()
            return True
        return False


    def _values_row(self):
        if self.accept_op("("):
            row = [self.expr()]
            while self.accept_op(","):
                row.append(self.expr())
            self.expect_op(")")
            return row
        return [self.expr()]

    def _alias(self):
        alias = None
        col_aliases = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.peek().kind == "ident" \
                and str(self.peek().value).upper() != "TABLESAMPLE":
            # TABLESAMPLE is a sample clause, never an implicit alias
            # (reference: SqlBase.g4 reserves it)
            alias = self.next().value
        if alias and self.at_op("(") and self._looks_like_column_aliases():
            self.next()
            col_aliases = [self.ident()]
            while self.accept_op(","):
                col_aliases.append(self.ident())
            self.expect_op(")")
        return alias, col_aliases

    def _looks_like_column_aliases(self) -> bool:
        # after alias: "(ident [, ident]* )" not followed by an operator
        j = self.i + 1
        if self.toks[j].kind != "ident":
            return False
        while self.toks[j].kind == "ident":
            j += 1
            if self.toks[j].kind == "op" and self.toks[j].value == ",":
                j += 1
                continue
            break
        return self.toks[j].kind == "op" and self.toks[j].value == ")"

    # ---- expressions (Pratt) ----------------------------------------
    def expr(self) -> ast.Expr:
        return self._or_expr()

    def _or_expr(self):
        left = self._and_expr()
        while self.accept_kw("OR"):
            left = ast.BinaryOp("OR", left, self._and_expr())
        return left

    def _and_expr(self):
        left = self._not_expr()
        while self.accept_kw("AND"):
            left = ast.BinaryOp("AND", left, self._not_expr())
        return left

    def _not_expr(self):
        if self.accept_kw("NOT"):
            return ast.UnaryOp("NOT", self._not_expr())
        return self._predicate()

    def _at_time_zone(self, left):
        # postfix `AT TIME ZONE 'zone'` (AT/ZONE are unreserved idents,
        # TIME lexes as a keyword) -> at_timezone(expr, zone)
        while (self.peek().kind == "ident"
               and str(self.peek().value).upper() == "AT"
               and self.peek(1).kind == "kw" and self.peek(1).value == "TIME"
               and self.peek(2).kind == "ident"
               and str(self.peek(2).value).upper() == "ZONE"):
            self.i += 3
            # the zone operand is a primary (string literal / column),
            # NOT an additive — `x AT TIME ZONE 'z' + INTERVAL ...`
            # must apply + to the converted value (reference grammar:
            # timeZoneSpecifier is a string or interval literal)
            zone = self._unary()
            left = ast.FunctionCall("at_timezone", [left, zone])
        return left

    def _predicate(self):
        left = self._additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                q = self._quantified(op, left)
                if q is not None:
                    left = q
                    continue
                right = self._additive()
                left = ast.BinaryOp(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("BETWEEN"):
                low = self._additive()
                self.expect_kw("AND")
                high = self._additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InList(left, items, negated)
                continue
            if self.accept_kw("LIKE"):
                pattern = self._additive()
                escape = None
                if self.accept_kw("ESCAPE"):
                    escape = self._additive()
                left = ast.Like(left, pattern, escape, negated)
                continue
            if negated:
                self.i = save
                break
            if self.accept_kw("IS"):
                neg = self.accept_kw("NOT")
                if self.accept_kw("DISTINCT"):
                    # IS [NOT] DISTINCT FROM: null-safe comparison
                    # (reference: SqlBase.g4 DISTINCT FROM predicate)
                    self.expect_kw("FROM")
                    rhs = self._additive()
                    call = ast.FunctionCall("is_distinct_from",
                                            [left, rhs])
                    left = ast.UnaryOp("NOT", call) if neg else call
                    continue
                self.expect_kw("NULL")
                left = ast.IsNull(left, neg)
                continue
            break
        return left

    def _additive(self):
        left = self._multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                left = ast.BinaryOp(op, left, self._multiplicative())
            elif self.at_op("||"):
                self.next()
                left = ast.BinaryOp("||", left, self._multiplicative())
            else:
                return left

    def _multiplicative(self):
        left = self._at_time_zone(self._unary())
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = ast.BinaryOp(op, left, self._at_time_zone(self._unary()))
        return left

    def _unary(self):
        if self.accept_op("-"):
            return ast.UnaryOp("-", self._unary())
        if self.accept_op("+"):
            return self._unary()
        e = self._primary()
        # postfix: subscript a[i] / m['k'], and .field on non-identifier
        # bases (identifier dot-chains are consumed by _primary itself)
        while True:
            if self.at_op("["):
                self.next()
                idx = self.expr()
                self.expect_op("]")
                e = ast.FunctionCall("subscript", [e, idx])
                continue
            if self.at_op(".") and self.peek(1).kind == "ident" \
                    and not isinstance(e, ast.Identifier):
                self.next()
                e = ast.FunctionCall("$dereference",
                                     [e, ast.Literal(self.ident())])
                continue
            break
        return e

    def _primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "op" and t.value == "?":
            # prepared-statement parameter (reference: SqlBase.g4
            # parameter); positions follow textual order, which is the
            # EXECUTE ... USING binding order
            self.next()
            p = ast.Parameter(self._n_params)
            self._n_params += 1
            return p
        if t.kind == "number":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                return ast.Literal(float(t.value))
            return ast.Literal(int(t.value))
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value)
        if self.accept_kw("TRUE"):
            return ast.Literal(True)
        if self.accept_kw("FALSE"):
            return ast.Literal(False)
        if self.accept_kw("NULL"):
            return ast.Literal(None)
        if self.at_kw("DATE") and self.peek(1).kind == "string":
            self.next()
            return ast.Literal(self.next().value, type_hint="date")
        if self.at_kw("TIMESTAMP") and self.peek(1).kind == "string":
            self.next()
            return ast.Literal(self.next().value, type_hint="timestamp")
        if self.at_kw("TIME") and self.peek(1).kind == "string":
            self.next()
            return ast.Literal(self.next().value, type_hint="time")
        if (self.peek().kind == "ident"
                and str(self.peek().value).upper() == "DECIMAL"
                and self.peek(1).kind == "string"):
            self.next()  # DECIMAL is not reserved, so it lexes as ident
            return ast.Literal(self.next().value, type_hint="decimal")
        if self.accept_kw("INTERVAL"):
            sign = -1 if self.accept_op("-") else 1
            v = self.next()
            if v.kind not in ("string", "number"):
                self.err("expected interval value")
            unit_tok = self.next()
            unit = (unit_tok.value or "").upper().rstrip("S") if unit_tok.kind in ("ident", "kw") else None
            if unit not in ("DAY", "MONTH", "YEAR", "HOUR", "MINUTE", "SECOND", "WEEK"):
                self.err(f"unsupported interval unit {unit}")
            return ast.IntervalLiteral(sign * int(str(v.value).strip("'")), unit)
        if self.accept_kw("CASE"):
            operand = None
            if not self.at_kw("WHEN"):
                operand = self.expr()
            whens = []
            while self.accept_kw("WHEN"):
                c = self.expr()
                self.expect_kw("THEN")
                whens.append((c, self.expr()))
            default = self.expr() if self.accept_kw("ELSE") else None
            self.expect_kw("END")
            return ast.Case(operand, whens, default)
        if self.at_kw("CAST", "TRY_CAST"):
            safe = self.next().value == "TRY_CAST"
            self.expect_op("(")
            v = self.expr()
            self.expect_kw("AS")
            type_name = self._type_name()
            self.expect_op(")")
            return ast.Cast(v, type_name, safe)
        if self.accept_kw("EXTRACT"):
            self.expect_op("(")
            fld = self.next().value
            self.expect_kw("FROM")
            v = self.expr()
            self.expect_op(")")
            return ast.Extract(str(fld).upper(), v)
        if self.accept_kw("SUBSTRING"):
            self.expect_op("(")
            v = self.expr()
            if self.accept_kw("FROM"):
                start = self.expr()
                length = self.expr() if self.accept_kw("FOR") else None
            else:
                self.expect_op(",")
                start = self.expr()
                length = self.expr() if self.accept_op(",") else None
            self.expect_op(")")
            args = [v, start] + ([length] if length is not None else [])
            return ast.FunctionCall("substring", args)
        if self.accept_kw("EXISTS"):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ast.Exists(q)
        if self.accept_op("("):
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" and t.value == "array" \
                and self.peek(1).kind == "op" and self.peek(1).value == "[":
            self.next(), self.next()
            elems = []
            if not self.at_op("]"):
                elems.append(self.expr())
                while self.accept_op(","):
                    elems.append(self.expr())
            self.expect_op("]")
            return ast.FunctionCall("array_constructor", elems)
        if t.kind == "ident" or (t.kind == "kw" and t.value in (
                "DATE", "TIME", "TIMESTAMP", "FILTER", "ROW", "FIRST", "LAST",
                "SET", "VALUES", "IF", "START", "READ", "ONLY", "WRITE",
                "COMMIT", "ROLLBACK", "TRANSACTION")):
            name = self.ident()
            if self.at_op("("):
                return self._function_call(name)
            if name in ("current_date", "current_timestamp", "current_time",
                        "localtime", "localtimestamp", "current_user") \
                    and not self.at_op("."):
                # SQL-spec niladic functions take no parentheses
                return ast.FunctionCall(name, [])
            parts = [name]
            while self.at_op(".") and self.peek(1).kind in ("ident", "kw"):
                self.next()
                parts.append(self.ident())
            return ast.Identifier(tuple(parts))
        self.err("expected expression")

    def _with_properties(self) -> dict:
        """WITH (k = v, ...) table properties (reference: SqlBase.g4
        `properties`; e.g. WITH (connector = 'localfile')).  ARRAY['a',
        'b'] values parse to python lists — the write-layout properties
        (bucketed_by/sorted_by/partitioned_by) use them, matching the
        hive connector's table-property shapes."""
        props: dict = {}
        if not (self.at_kw("WITH") and self.peek(1).kind == "op"
                and self.peek(1).value == "("):
            return props
        self.next()
        self.expect_op("(")
        while True:
            key = self.ident()
            self.expect_op("=")
            if (self.peek().kind in ("ident", "kw")
                    and str(self.peek().value).upper() == "ARRAY"
                    and self.peek(1).kind == "op"
                    and self.peek(1).value == "["):
                self.next()
                self.expect_op("[")
                items = []
                if not self.accept_op("]"):
                    while True:
                        items.append(self.next().value)
                        if not self.accept_op(","):
                            break
                    self.expect_op("]")
                props[key] = items
                if not self.accept_op(","):
                    break
                continue
            t = self.next()
            if t.kind == "number":
                props[key] = float(t.value) if "." in t.value else int(t.value)
            elif t.kind == "kw" and t.value in ("TRUE", "FALSE"):
                props[key] = t.value == "TRUE"
            else:
                props[key] = t.value
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return props

    def _type_name(self) -> str:
        name = self.next()
        if name.kind not in ("ident", "kw"):
            self.err("expected type name")
        tn = str(name.value)
        if tn.upper() == "DOUBLE" and self.peek().kind == "ident" and self.peek().value == "precision":
            self.next()
        if tn.upper() in ("TIMESTAMP", "TIME") and self.at_kw("WITH"):
            # TIMESTAMP/TIME WITH TIME ZONE (TIME is a kw, ZONE an ident)
            save = self.i
            self.next()
            if self.accept_kw("TIME") and self.peek().kind == "ident" \
                    and str(self.peek().value).upper() == "ZONE":
                self.next()
                tn += " WITH TIME ZONE"
            else:
                self.i = save
        if self.accept_op("("):
            # capture the balanced-paren argument list verbatim so nested
            # types (MAP(VARCHAR, ARRAY(BIGINT)), ROW(x BIGINT, ...)) pass
            # through to types.parse_type
            depth = 1
            parts = []
            while True:
                t = self.next()
                if t.kind == "eof":
                    self.err("unterminated type arguments")
                if t.kind == "op" and t.value == "(":
                    depth += 1
                elif t.kind == "op" and t.value == ")":
                    depth -= 1
                    if depth == 0:
                        break
                parts.append(str(t.value))
            tn += "(" + " ".join(parts) + ")"
        return tn

    def _function_call(self, name: str) -> ast.Expr:
        self.expect_op("(")
        distinct = False
        args: List[ast.Expr] = []
        if self.at_op("*"):
            self.next()
            args = []  # count(*)
        elif not self.at_op(")"):
            if self.accept_kw("DISTINCT"):
                distinct = True
            else:
                self.accept_kw("ALL")
            args.append(self._lambda_or_expr())
            while self.accept_op(","):
                args.append(self._lambda_or_expr())
        self.expect_op(")")
        if self.at_kw("WITH") and self.peek(1).kind == "ident" \
                and str(self.peek(1).value).upper() == "ERROR":
            # COUNT(x) WITH ERROR / SUM(x) WITH ERROR: the approximate
            # forms over a seeded 1-in-8 hash sample (value-hash-gated,
            # so the estimate is partition-independent).  Lookahead is
            # two tokens — a bare WITH after an aggregate otherwise
            # stays untouched (CTE WITH never appears here).
            self.next()
            self.next()
            if name.lower() not in ("count", "sum") or not args or distinct:
                self.err("WITH ERROR is only supported on "
                         "COUNT(x) and SUM(x)")
            name = "approx_" + name.lower()
        filt = None
        if self.at_kw("FILTER"):
            self.next()
            self.expect_op("(")
            self.expect_kw("WHERE")
            filt = self.expr()
            self.expect_op(")")
        nt = None
        if self._accept_word("IGNORE"):
            self.expect_kw("NULLS")
            nt = "IGNORE"
        elif self._accept_word("RESPECT"):
            self.expect_kw("NULLS")
            nt = "RESPECT"
        window = None
        if self.accept_kw("OVER"):
            window = self._window_spec()
        return ast.FunctionCall(name.lower(), args, distinct, filt, window,
                                nt)

    def _lambda_or_expr(self) -> ast.Expr:
        """Function argument: `x -> body`, `(x, y) -> body`, or an expression
        (reference: SqlBase.g4 `lambda` primaryExpression alternative)."""
        t = self.peek()
        if t.kind == "ident" and self.peek(1).kind == "op" \
                and self.peek(1).value == "->":
            name = self.next().value
            self.next()  # ->
            return ast.Lambda([name], self.expr())
        if t.kind == "op" and t.value == "(":
            # lookahead for  ( ident [, ident]* ) ->
            j = self.i + 1
            params: List[str] = []
            while True:
                tk = self.toks[j]
                if tk.kind != "ident":
                    params = []
                    break
                params.append(tk.value)
                j += 1
                tk = self.toks[j]
                if tk.kind == "op" and tk.value == ",":
                    j += 1
                    continue
                if tk.kind == "op" and tk.value == ")":
                    j += 1
                    break
                params = []
                break
            if params and self.toks[j].kind == "op" \
                    and self.toks[j].value == "->":
                self.i = j + 1
                return ast.Lambda(params, self.expr())
        return self.expr()

    def _window_spec(self) -> ast.WindowSpec:
        self.expect_op("(")
        partition_by: List[ast.Expr] = []
        order_by: List[ast.SortItem] = []
        frame = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self.expr())
            while self.accept_op(","):
                partition_by.append(self.expr())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self._sort_item())
            while self.accept_op(","):
                order_by.append(self._sort_item())
        if self.at_kw("ROWS", "RANGE"):
            ftype = self.next().value
            if self.accept_kw("BETWEEN"):
                start = self._frame_bound()
                self.expect_kw("AND")
                end = self._frame_bound()
            else:
                start = self._frame_bound()
                end = "CURRENT ROW"
            frame = (ftype, start, end)
        self.expect_op(")")
        return ast.WindowSpec(partition_by, order_by, frame)

    def _frame_bound(self) -> str:
        if self.accept_kw("UNBOUNDED"):
            if self.accept_kw("PRECEDING"):
                return "UNBOUNDED PRECEDING"
            self.expect_kw("FOLLOWING")
            return "UNBOUNDED FOLLOWING"
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return "CURRENT ROW"
        t = self.next()
        if t.kind != "number":
            self.err("expected frame bound")
        if self.accept_kw("PRECEDING"):
            return f"{t.value} PRECEDING"
        self.expect_kw("FOLLOWING")
        return f"{t.value} FOLLOWING"


def parse(text: str) -> ast.Statement:
    return Parser(text).parse_statement()


def parse_query(text: str) -> ast.Query:
    stmt = parse(text)
    if not isinstance(stmt, ast.QueryStatement):
        raise ParseError("expected a query")
    return stmt.query
