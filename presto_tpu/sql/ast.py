"""SQL abstract syntax tree.

Reference parity: presto-parser/src/main/java/com/facebook/presto/sql/tree/
(160 node classes).  Trimmed to the query language subset the engine
executes (full TPC-H + general analytic SQL); dataclasses instead of the
reference's visitor hierarchy — tree walks are plain pattern matches.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union


class Node:
    def children(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Node):
                yield v
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Node):
                        yield x


# ---- expressions ----------------------------------------------------------


@dataclass
class Expr(Node):
    pass


@dataclass
class Literal(Expr):
    value: object  # python int/float/str/bool/None
    type_hint: Optional[str] = None  # 'date' | 'timestamp' | 'decimal' | None


@dataclass
class Parameter(Expr):
    """A `?` placeholder in a prepared statement (reference:
    sql/tree/Parameter).  `type_` is bound by the serving tier at
    EXECUTE time (server/serving.py) from the parameter values'
    engine types, so the SAME template plans once per type signature
    and the plan/executable are value-free (ir.Param)."""

    position: int  # 0-based, textual order == EXECUTE ... USING order
    type_: object = None  # presto_tpu.types.Type once bound


@dataclass
class IntervalLiteral(Expr):
    value: int
    unit: str  # DAY | MONTH | YEAR


@dataclass
class Identifier(Expr):
    parts: Tuple[str, ...]  # possibly qualified: (table, column) or (column,)

    @property
    def name(self) -> str:
        return self.parts[-1]


@dataclass
class Star(Expr):
    qualifier: Optional[str] = None  # t.* or *


@dataclass
class BinaryOp(Expr):
    op: str  # + - * / % || = <> < <= > >= AND OR
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str  # - NOT
    operand: Expr


@dataclass
class Between(Expr):
    value: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    value: Expr
    items: List[Expr]
    negated: bool = False


@dataclass
class InSubquery(Expr):
    value: Expr
    query: "Query"
    negated: bool = False


@dataclass
class Exists(Expr):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(Expr):
    query: "Query"


@dataclass
class Like(Expr):
    value: Expr
    pattern: Expr
    escape: Optional[Expr] = None
    negated: bool = False


@dataclass
class IsNull(Expr):
    value: Expr
    negated: bool = False


@dataclass
class Case(Expr):
    operand: Optional[Expr]  # CASE x WHEN ... vs CASE WHEN ...
    whens: List[Tuple[Expr, Expr]]
    default: Optional[Expr]


@dataclass
class Cast(Expr):
    value: Expr
    type_name: str
    safe: bool = False  # TRY_CAST


@dataclass
class FunctionCall(Expr):
    name: str
    args: List[Expr]
    distinct: bool = False
    filter: Optional[Expr] = None
    window: Optional["WindowSpec"] = None
    # "IGNORE" | "RESPECT" | None (reference: nullTreatment)
    null_treatment: Optional[str] = None


@dataclass
class Lambda(Expr):
    """`x -> body` / `(x, y) -> body` — only valid as a function argument
    (reference: sql/tree/LambdaExpression.java)."""
    params: List[str]
    body: Expr


@dataclass
class Extract(Expr):
    fld: str  # YEAR MONTH DAY ...
    value: Expr


@dataclass
class WindowSpec(Node):
    partition_by: List[Expr] = field(default_factory=list)
    order_by: List["SortItem"] = field(default_factory=list)
    # frame support: (type, start, end) — ROWS/RANGE; None = default frame
    frame: Optional[Tuple[str, str, str]] = None


# ---- relations ------------------------------------------------------------


@dataclass
class Relation(Node):
    pass


@dataclass
class Table(Relation):
    name: str
    alias: Optional[str] = None
    column_aliases: Optional[List[str]] = None
    # TABLESAMPLE: ("BERNOULLI" | "SYSTEM", percentage) — reference:
    # SqlBase.g4 sampledRelation
    sample: Optional[tuple] = None


@dataclass
class SubqueryRelation(Relation):
    query: "Query"
    alias: Optional[str] = None
    column_aliases: Optional[List[str]] = None


@dataclass
class Join(Relation):
    join_type: str  # INNER LEFT RIGHT FULL CROSS
    left: Relation
    right: Relation
    on: Optional[Expr] = None
    using: Optional[List[str]] = None


@dataclass
class Unnest(Relation):
    exprs: List[Expr]
    alias: Optional[str] = None
    with_ordinality: bool = False


@dataclass
class ValuesRelation(Relation):
    rows: List[List[Expr]]
    alias: Optional[str] = None
    column_aliases: Optional[List[str]] = None


# ---- query structure ------------------------------------------------------


@dataclass
class SelectItem(Node):
    expr: Expr
    alias: Optional[str] = None


@dataclass
class SortItem(Node):
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = default (last for asc, first for desc)


@dataclass
class QuerySpec(Node):
    select: List[SelectItem]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    # GROUPING SETS/ROLLUP/CUBE: list of grouping-key subsets; the
    # planner expands to a UNION ALL of per-set aggregations
    # (reference: GroupIdNode + GroupIdOperator)
    grouping_sets: Optional[List[List[Expr]]] = None


@dataclass
class SetOp(Node):
    op: str  # UNION | INTERSECT | EXCEPT
    all: bool
    left: Union["QuerySpec", "SetOp"]
    right: Union["QuerySpec", "SetOp"]


@dataclass
class Query(Node):
    body: Union[QuerySpec, SetOp]
    order_by: List[SortItem] = field(default_factory=list)
    limit: Optional[int] = None
    ctes: List[Tuple[str, "Query", Optional[List[str]]]] = field(default_factory=list)


# ---- statements -----------------------------------------------------------


@dataclass
class Statement(Node):
    pass


@dataclass
class QueryStatement(Statement):
    query: Query


@dataclass
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    # EXPLAIN (TYPE LOGICAL | DISTRIBUTED | VALIDATE) — reference:
    # SqlBase.g4 explainOption / ExplainType
    type_: str = "LOGICAL"


@dataclass
class DescribeInput(Statement):
    name: str


@dataclass
class DescribeOutput(Statement):
    name: str


@dataclass
class ShowTables(Statement):
    pass


@dataclass
class ShowColumns(Statement):
    table: str


@dataclass
class ShowFunctions(Statement):
    pass


@dataclass
class ShowSession(Statement):
    pass


@dataclass
class ShowCatalogs(Statement):
    pass


@dataclass
class ShowSchemas(Statement):
    pass


@dataclass
class ShowStats(Statement):
    table: str


@dataclass
class CreateTableAs(Statement):
    """CREATE [OR REPLACE] TABLE t [WITH (...)] AS query.  OR REPLACE is
    the refresh-and-serve cut-over: the new snapshot stages invisibly
    and publishes atomically while concurrent readers keep the previous
    one (exec/writer.py, docs/WRITES.md)."""

    name: str
    query: Query
    properties: dict = field(default_factory=dict)
    if_not_exists: bool = False
    or_replace: bool = False


@dataclass
class ShowCreateTable(Statement):
    """SHOW CREATE TABLE t — renders DDL including the recorded
    physical-layout write properties (reference: ShowQueriesRewrite's
    SHOW CREATE handling)."""

    table: str


@dataclass
class InsertInto(Statement):
    table: str
    columns: Optional[List[str]]
    query: Query


@dataclass
class CreateTable(Statement):
    """CREATE TABLE t (col type, ...) [WITH (k = v, ...)] — reference:
    SqlBase.g4 createTable; WITH properties select the connector
    (connector = 'memory' | 'localfile' | 'blackhole')."""

    name: str
    columns: List[tuple]  # (name, type_text)
    properties: dict
    if_not_exists: bool = False


@dataclass
class DropTable(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateMaterializedView(Statement):
    """CREATE [OR REPLACE] MATERIALIZED VIEW v [WITH (...)] AS query.
    The backing table stores the rollup state (exact aggregate partials
    plus sketch register/summary columns) so REFRESH can fold a source
    delta in without rescanning history (exec/matview.py)."""

    name: str
    query: Query
    properties: dict = field(default_factory=dict)
    if_not_exists: bool = False
    or_replace: bool = False


@dataclass
class RefreshMaterializedView(Statement):
    name: str


@dataclass
class DropMaterializedView(Statement):
    name: str
    if_exists: bool = False


@dataclass
class ShowMaterializedViews(Statement):
    pass


@dataclass
class Delete(Statement):
    """DELETE FROM t [WHERE pred] — reference: SqlBase.g4 delete,
    executed as a keep-mask rewrite (MetadataDeleteOperator analog)."""

    table: str
    where: Optional[Expr]


@dataclass
class Prepare(Statement):
    """PREPARE name FROM statement (reference: SqlBase.g4 prepare;
    parameters are `?` placeholders substituted at EXECUTE)."""

    name: str
    statement_text: str


@dataclass
class Execute(Statement):
    name: str
    parameters: List[Expr]


@dataclass
class Deallocate(Statement):
    name: str


@dataclass
class TransactionStatement(Statement):
    """START TRANSACTION [READ ONLY] | COMMIT | ROLLBACK (reference:
    SqlBase.g4 startTransaction/commit/rollback)."""

    action: str  # START | COMMIT | ROLLBACK
    read_only: bool = False


@dataclass
class SetSession(Statement):
    name: str
    value: object
