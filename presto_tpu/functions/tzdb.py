"""Time-zone rules for the WITH TIME ZONE types.

Reference: presto-spi .../spi/type/TimeZoneKey.java (zone-index file) +
joda DateTimeZone transition lookups inside
presto-main/.../operator/scalar/DateTimeFunctions.java.

TPU-native design: instead of the reference's per-VALUE packed zone key
(millisUtc << 12 | zoneKey, unpacked on every operation), the zone lives
in the column TYPE (`types.timestamp_tz(zone)`) and the device lane is
pure UTC microseconds.  Comparisons, joins, sorts and GROUP BY then run
directly on the int64 lane with correct instant semantics — no unpack —
and a zone conversion is one `jnp.searchsorted` over the zone's
transition table (uploaded once per zone per process, ~100-300 entries).

The rules come from the host's IANA tzdata: TZif binary files are parsed
directly (RFC 8536) — same spirit as the in-engine thrift/protobuf
decoders in storage/.  Fixed-offset names (`+05:30`, `UTC`) need no
file.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Optional, Tuple

import numpy as np

_TZDIRS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo", "/etc/zoneinfo")

US = 1_000_000


class ZoneRules:
    """Sorted UTC transition instants + the offset in effect between
    them.  offs_us[i] applies to instants < trans_us[i] (i.e. the offset
    AFTER transition i-1); len(offs_us) == len(trans_us) + 1."""

    __slots__ = ("name", "trans_us", "offs_us", "_dev", "_dev_local")

    def __init__(self, name: str, trans_us: np.ndarray, offs_us: np.ndarray):
        self.name = name
        self.trans_us = trans_us
        self.offs_us = offs_us
        self._dev = None
        self._dev_local = None

    @property
    def fixed(self) -> bool:
        return len(self.trans_us) == 0

    # ---- host-side scalar conversions --------------------------------
    def offset_at_utc_scalar(self, utc_us: int) -> int:
        i = int(np.searchsorted(self.trans_us, utc_us, side="right"))
        return int(self.offs_us[i])

    def utc_to_local_scalar(self, utc_us: int) -> int:
        return utc_us + self.offset_at_utc_scalar(utc_us)

    def local_to_utc_scalar(self, local_us: int) -> int:
        tl, offs = self._local_transitions()
        i = int(np.searchsorted(tl, local_us, side="right"))
        return local_us - int(offs[i])

    def _local_transitions(self) -> Tuple[np.ndarray, np.ndarray]:
        """Transition instants on the PRE-transition wall clock.  An
        ambiguous local time (fall-back overlap) resolves to the EARLIER
        offset; a nonexistent one (spring-forward gap) to the offset
        AFTER the gap — both matching joda DateTimeZone.convertLocalToUTC
        non-strict (the reference's parse path) and java.time."""
        return self.trans_us + self.offs_us[:-1], self.offs_us

    # ---- device-side column conversions ------------------------------
    def _device_tables(self, local: bool):
        # HOST numpy arrays, embedded as constants into each traced
        # program by the jnp ops below.  Caching jax Arrays here would
        # leak tracers when the first lookup happens under jit tracing.
        cached = self._dev_local if local else self._dev
        if cached is None:
            if local:
                cached = self._local_transitions()
                self._dev_local = cached
            else:
                cached = (self.trans_us, self.offs_us)
                self._dev = cached
        return cached

    def utc_to_local(self, utc_us):
        """Columnar utc->wall-clock shift (device searchsorted)."""
        import jax.numpy as jnp

        if self.fixed:
            return utc_us + int(self.offs_us[0])
        trans, offs = self._device_tables(local=False)
        idx = jnp.searchsorted(trans, utc_us, side="right")
        return utc_us + offs[idx]

    def local_to_utc(self, local_us):
        import jax.numpy as jnp

        if self.fixed:
            return local_us - int(self.offs_us[0])
        trans, offs = self._device_tables(local=True)
        idx = jnp.searchsorted(trans, local_us, side="right")
        return local_us - offs[idx]


_CACHE: Dict[str, ZoneRules] = {}


def _parse_fixed(name: str) -> Optional[ZoneRules]:
    """`UTC`, `Z`, `+08:45`, `-05:00`, `+0530`, `UTC+5` style names."""
    up = name.strip()
    if up.upper() in ("UTC", "Z", "GMT", "UT"):
        return ZoneRules(name, np.empty(0, np.int64),
                         np.zeros(1, np.int64))
    s = up
    if s.upper().startswith(("UTC", "GMT")):
        s = s[3:]
    if not s or s[0] not in "+-":
        return None
    sign = -1 if s[0] == "-" else 1
    body = s[1:].replace(":", "")
    if not body.isdigit() or len(body) > 4:
        return None
    if len(body) <= 2:
        hh, mm = int(body), 0
    else:
        body = body.zfill(4)
        hh, mm = int(body[:2]), int(body[2:])
    if hh > 14 or mm > 59:
        return None
    off = sign * (hh * 3600 + mm * 60) * US
    return ZoneRules(name, np.empty(0, np.int64),
                     np.asarray([off], np.int64))


def _tzif_path(name: str) -> Optional[str]:
    if "/" in name and (".." in name or name.startswith("/")):
        return None  # no path escapes
    for d in _TZDIRS:
        p = os.path.join(d, name)
        if os.path.isfile(p):
            return p
    # the tzdata wheel (PEP 615 fallback) ships the same TZif files
    try:
        import importlib.resources as ir

        parts = name.split("/")
        trav = ir.files("tzdata").joinpath("zoneinfo", *parts)
        if trav.is_file():
            return str(trav)
    except (ImportError, ModuleNotFoundError, ValueError):
        pass
    return None


def _parse_tzif(name: str, raw: bytes) -> ZoneRules:
    """RFC 8536 TZif v1/2/3 -> transition arrays (64-bit block when
    present)."""

    def read_block(buf, pos, time_size):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack_from(">6I", buf, pos + 20)
        pos += 44
        fmt = ">%d%s" % (timecnt, "q" if time_size == 8 else "l")
        trans = struct.unpack_from(fmt, buf, pos)
        pos += timecnt * time_size
        idxs = struct.unpack_from(">%dB" % timecnt, buf, pos)
        pos += timecnt
        ttinfos = []
        for _ in range(typecnt):
            utoff, isdst, _desig = struct.unpack_from(">lBB", buf, pos)
            ttinfos.append((utoff, isdst))
            pos += 6
        pos += charcnt + leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return trans, idxs, ttinfos, pos

    if raw[:4] != b"TZif":
        raise ValueError(f"{name}: not a TZif file")
    version = raw[4:5]
    trans, idxs, ttinfos, pos = read_block(raw, 0, 4)
    if version in (b"2", b"3", b"4") and raw[pos:pos + 4] == b"TZif":
        trans, idxs, ttinfos, pos = read_block(raw, pos, 8)
    if not ttinfos:
        raise ValueError(f"{name}: no time types")
    # initial offset: first standard (non-dst) type, else the first type
    first_std = next((o for o, dst in ttinfos if not dst), ttinfos[0][0])
    offs = [first_std] + [ttinfos[i][0] for i in idxs]
    return ZoneRules(
        name,
        np.asarray(trans, np.int64) * US,
        np.asarray(offs, np.int64) * US)


def rules(name: str) -> ZoneRules:
    """Resolve a zone name to its rules; raises ValueError for unknown
    zones (reference: TimeZoneKey.getTimeZoneKey throws
    TimeZoneNotSupportedException)."""
    z = _CACHE.get(name)
    if z is not None:
        return z
    z = _parse_fixed(name)
    if z is None:
        path = _tzif_path(name)
        if path is None:
            raise ValueError(f"unknown time zone: {name!r}")
        with open(path, "rb") as f:
            z = _parse_tzif(name, f.read())
    _CACHE[name] = z
    return z


def is_valid_zone(name: str) -> bool:
    try:
        rules(name)
        return True
    except (ValueError, OSError):
        return False
