"""ML functions (the presto-ml module role).

Reference parity: presto-ml's learn_classifier / learn_regressor /
classify / regress / features over libsvm models.  TPU-native
adaptation: models train host-side inside the aggregate (like the
sketch aggregates) — logistic regression and ridge least-squares on
numpy instead of libsvm — and serialize to a VARBINARY blob; `classify`
and `regress` apply the model VECTORIZED on device over the feature
matrix (one jnp matmul per call, which is the TPU-shaped inference
path the reference's per-row libsvm calls cannot take).

`features(x1, x2, ...)` builds a device (n, k) float64 matrix carried
as a typed column (like geospatial's point columns).
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.exec.colval import ColVal, all_valid
from presto_tpu.functions.scalar import register

FEATURES = T.Type("FEATURES")  # device (n, k) f64 matrix
T._PHYSICAL.setdefault("FEATURES", np.int32)

MODEL = T.VARBINARY  # serialized model blob


def _feat_f64(a: ColVal):
    d = jnp.asarray(a.data).astype(jnp.float64)
    if a.type.is_decimal:  # decimal data is the UNSCALED integer
        d = d / (10 ** a.type.decimal_scale)
    return d


register("features")((
    lambda args: FEATURES if args and all(a.is_numeric for a in args)
    else None,
    lambda args: ColVal(
        jnp.stack(jnp.broadcast_arrays(*[_feat_f64(a) for a in args]),
                  axis=-1),
        all_valid(*args), FEATURES)))


# ---------------------------------------------------------------------------
# model blobs
# ---------------------------------------------------------------------------


def _pack_model(kind: str, weights: np.ndarray, bias,
                classes=None) -> bytes:
    return json.dumps({
        "kind": kind,
        "w": np.asarray(weights, np.float64).tolist(),
        "b": (np.asarray(bias, np.float64).tolist()
              if hasattr(bias, "__len__") else float(bias)),
        "classes": None if classes is None else list(classes),
    }).encode()


def _unpack_model(blob) -> dict:
    if isinstance(blob, str):
        blob = blob.encode()
    return json.loads(bytes(blob).decode())


def train_classifier(labels: np.ndarray, feats: np.ndarray,
                     iters: int = 300, lr: float = 0.5) -> bytes:
    """Multinomial logistic regression by full-batch gradient descent
    (the LibSvmClassifier role; classes = the distinct labels)."""
    classes, y = np.unique(labels, return_inverse=True)
    n, k = feats.shape
    c = len(classes)
    mu = feats.mean(axis=0)
    sd = feats.std(axis=0)
    sd[sd == 0] = 1.0
    x = (feats - mu) / sd
    w = np.zeros((k, c))
    b = np.zeros(c)
    onehot = np.eye(c)[y]
    for _ in range(iters):
        z = x @ w + b
        z -= z.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        g = (p - onehot) / n
        w -= lr * (x.T @ g + 1e-4 * w)
        b -= lr * g.sum(axis=0)
    # fold standardization into the weights: z = ((f-mu)/sd)w + b
    w_raw = w / sd[:, None]
    b_raw = b - mu @ w_raw
    return _pack_model("classifier", w_raw, b_raw,
                       [v.item() if hasattr(v, "item") else v
                        for v in classes])


def train_regressor(labels: np.ndarray, feats: np.ndarray) -> bytes:
    """Ridge least squares (the LibSvmRegressor role)."""
    n, k = feats.shape
    xb = np.hstack([feats, np.ones((n, 1))])
    ident = np.eye(k + 1) * 1e-8
    ident[-1, -1] = 0.0
    coef = np.linalg.solve(xb.T @ xb + ident, xb.T @ labels)
    return _pack_model("regressor", coef[:-1], coef[-1])


# ---------------------------------------------------------------------------
# inference scalars (vectorized on device)
# ---------------------------------------------------------------------------


def _model_of(v: ColVal):
    if getattr(v.data, "ndim", None) == 0 and v.dictionary is not None:
        return _unpack_model(v.dictionary.values[int(v.data)])
    if isinstance(v.data, (bytes, str)):
        return _unpack_model(v.data)
    if v.dictionary is not None and getattr(v.data, "ndim", 0) == 1:
        # model arrived as a per-row column (the canonical CROSS JOIN
        # form); one distinct model applies to the whole column
        if len(v.dictionary) == 1:
            return _unpack_model(v.dictionary.values[0])
        import numpy as _np

        codes = _np.unique(_np.asarray(v.data))
        if len(codes) == 1:
            return _unpack_model(v.dictionary.values[int(codes[0])])
        raise NotImplementedError(
            "classify/regress with multiple distinct models in one "
            "column")
    return None


def _emit_apply(kind):
    def emit(args):
        feats, model = args
        m = _model_of(model)
        if m is None or m.get("kind") != kind:
            raise ValueError(f"{kind} model expected")
        x = jnp.asarray(feats.data)
        if x.ndim == 1:
            x = x[None, :]
        w = jnp.asarray(np.asarray(m["w"], np.float64))
        b = jnp.asarray(np.asarray(m["b"], np.float64))
        z = x @ w + b  # ONE matmul for the whole column (MXU-shaped)
        if kind == "regressor":
            out = z if z.ndim == 1 else z.reshape(x.shape[0])
            return ColVal(out, all_valid(*args), T.DOUBLE)
        idx = jnp.argmax(z, axis=-1)
        classes = m["classes"]
        # type-stable: labels always come back as VARCHAR (the
        # reference's classify is varchar-typed too)
        from presto_tpu.exec.colval import normalize_dictionary

        vals = np.empty(len(classes), object)
        vals[:] = [str(c) for c in classes]
        return normalize_dictionary(
            vals, ColVal(idx.astype(jnp.int32), all_valid(*args),
                         T.VARCHAR))

    return emit


register("classify")((
    lambda args: T.VARCHAR if len(args) == 2 else None,
    _emit_apply("classifier")))
register("regress")((
    lambda args: T.DOUBLE if len(args) == 2 else None,
    _emit_apply("regressor")))
