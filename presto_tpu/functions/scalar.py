"""Scalar function registry: SQL-level signature resolution + JAX emission.

Reference parity: metadata/FunctionManager.java:82 (resolution) and the
397 @ScalarFunction implementations under presto-main/.../operator/scalar/.
Each entry resolves argument types to a return type (used by the analyzer)
and emits jnp ops over ColVals (used by the expression compiler — the role
bytecode generation plays in the reference, sql/gen/ExpressionCompiler).

Null semantics: default is strict null-propagation (result null if any
input null), matching the reference's RETURN_NULL_ON_NULL convention;
AND/OR/IS NULL/COALESCE/IF/CASE implement SQL three-valued logic
explicitly.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Dictionary
from presto_tpu.exec.colval import (
    ColVal,
    all_valid,
    normalize_dictionary,
    translate_codes,
)

# ---------------------------------------------------------------------------
# calendar math (jit-friendly; Howard Hinnant's civil-days algorithms)
# ---------------------------------------------------------------------------


def civil_from_days(days):
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def days_in_month(y, m):
    dim = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])[m - 1]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return jnp.where((m == 2) & leap, 29, dim)


def add_months(days, months):
    y, m, d = civil_from_days(jnp.asarray(days))
    mm = (y * 12 + (m - 1)) + months
    y2 = jnp.floor_divide(mm, 12)
    m2 = mm - y2 * 12 + 1
    d2 = jnp.minimum(d, days_in_month(y2, m2))
    return days_from_civil(y2, m2, d2).astype(jnp.int32)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _np_dtype(t: T.Type):
    return t.numpy_dtype()


def _cast_data(data, t: T.Type):
    return jnp.asarray(data).astype(_np_dtype(t)) if hasattr(data, "dtype") else data


def _host_string_pred(col: ColVal, fn) -> ColVal:
    """Evaluate a predicate over the dictionary on host, gather via codes."""
    lut = jnp.asarray(np.asarray([bool(fn(v)) for v in col.dictionary.values], dtype=bool))
    if len(col.dictionary) == 0:
        return ColVal(jnp.zeros_like(jnp.asarray(col.data), dtype=bool), col.valid, T.BOOLEAN)
    data = lut[jnp.clip(col.data, 0, len(col.dictionary) - 1)]
    return ColVal(data, col.valid, T.BOOLEAN)


def _host_string_transform(col: ColVal, fn, out_type=T.VARCHAR) -> ColVal:
    """Transform dictionary values on host, re-normalize (sorted unique)."""
    vals = np.asarray([fn(v) for v in col.dictionary.values], dtype=object)
    return normalize_dictionary(vals, ColVal(col.data, col.valid, out_type))


def _as_string_literal(v: ColVal) -> Optional[str]:
    if v.is_scalar and isinstance(v.data, str):
        return v.data
    return None


def _lit_to_dict_colval(v: ColVal) -> ColVal:
    """Turn a python-string literal into a 1-entry dictionary scalar."""
    d = Dictionary(np.asarray([v.data], dtype=object))
    return ColVal(jnp.asarray(0, dtype=jnp.int32), v.valid, T.VARCHAR, d)


def _string_compare(op: str, a: ColVal, b: ColVal) -> ColVal:
    """String comparison via dictionary LUTs; codes are order-isomorphic
    within one sorted dictionary."""
    valid = all_valid(a, b)
    sa, sb = _as_string_literal(a), _as_string_literal(b)
    if sa is not None and sb is not None:
        return ColVal(_PYOPS[op](sa, sb), valid, T.BOOLEAN)
    if sb is not None:  # column OP literal -> per-entry host eval
        r = _host_string_pred(a, lambda v: _PYOPS[op](v, sb))
        return ColVal(r.data, valid, T.BOOLEAN)
    if sa is not None:
        r = _host_string_pred(b, lambda v: _PYOPS[op](sa, v))
        return ColVal(r.data, valid, T.BOOLEAN)
    # column OP column
    if a.dictionary is b.dictionary:
        return ColVal(_PYOPS[op](a.data, b.data), valid, T.BOOLEAN)
    if op in ("eq", "ne"):
        lut = jnp.asarray(translate_codes(a.dictionary, b.dictionary))
        ta = lut[jnp.clip(a.data, 0, len(a.dictionary) - 1)]
        eq = (ta == b.data) & (ta >= 0)
        return ColVal(eq if op == "eq" else ~eq, valid, T.BOOLEAN)
    # order compare across dictionaries: re-encode both into merged dict
    merged = Dictionary(np.unique(np.concatenate([a.dictionary.values, b.dictionary.values])))
    la = jnp.asarray(translate_codes(a.dictionary, merged))
    lb = jnp.asarray(translate_codes(b.dictionary, merged))
    ca = la[jnp.clip(a.data, 0, len(a.dictionary) - 1)]
    cb = lb[jnp.clip(b.data, 0, len(b.dictionary) - 1)]
    return ColVal(_PYOPS[op](ca, cb), valid, T.BOOLEAN)


_PYOPS = {
    "eq": lambda x, y: x == y,
    "ne": lambda x, y: x != y,
    "lt": lambda x, y: x < y,
    "le": lambda x, y: x <= y,
    "gt": lambda x, y: x > y,
    "ge": lambda x, y: x >= y,
}


def like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "^" + "".join(out) + "$"


# ---------------------------------------------------------------------------
# function registry
# ---------------------------------------------------------------------------


class ScalarFn:
    def __init__(self, name: str, resolve: Callable, emit: Callable):
        self.name = name
        self.resolve = resolve  # (arg_types) -> Type | None
        self.emit = emit  # (args: List[ColVal]) -> ColVal


REGISTRY: Dict[str, ScalarFn] = {}


def register(name: str):
    def deco(cls_or_pair):
        resolve, emit = cls_or_pair
        REGISTRY[name] = ScalarFn(name, resolve, emit)
        return cls_or_pair

    return deco


def lookup(name: str) -> ScalarFn:
    fn = REGISTRY.get(name)
    if fn is None:
        raise KeyError(f"unknown function: {name}")
    return fn


# ---- arithmetic -----------------------------------------------------------


def _resolve_arith(name):
    def resolve(args):
        if len(args) != 2:
            return None
        a, b = args
        # date/timestamp +- interval
        if name in ("add", "sub") \
                and a.name in ("DATE", "TIMESTAMP", "TIMESTAMP_TZ", "TIME") \
                and b.name in ("INTERVAL_DAY_TIME", "INTERVAL_YEAR_MONTH"):
            if a.name == "TIME" and b.name == "INTERVAL_YEAR_MONTH":
                return None
            return a
        if name == "add" and a.name in ("INTERVAL_DAY_TIME",
                                        "INTERVAL_YEAR_MONTH") \
                and b.name in ("DATE", "TIMESTAMP", "TIMESTAMP_TZ"):
            return b
        if a.is_numeric and b.is_numeric:
            ct = T.common_super_type(a, b)
            if ct is not None and ct.is_decimal:
                if name == "div":
                    return T.DOUBLE  # decimal division promotes
                if name in ("add", "sub", "mul"):
                    return _decimal_result_type(name, a, b)
            return ct
        return None

    return resolve


def _emit_arith(name):
    def emit(args):
        a, b = args
        valid = all_valid(a, b)
        if a.type.name in ("INTERVAL_DAY_TIME", "INTERVAL_YEAR_MONTH") \
                and b.type.name in ("DATE", "TIMESTAMP", "TIMESTAMP_TZ"):
            a, b = b, a  # interval + temporal commutes (add only)
        if b.type.name == "INTERVAL_DAY_TIME" and a.type.name in (
                "DATE", "TIMESTAMP", "TIMESTAMP_TZ", "TIME"):
            delta = b.data if name == "add" else -b.data  # micros
            if a.type.name == "DATE":
                # whole result days (reference: joda plus + toDate)
                us = jnp.asarray(a.data).astype(jnp.int64) \
                    * 86_400_000_000 + delta
                return ColVal(jnp.floor_divide(us, 86_400_000_000)
                              .astype(jnp.int32), valid, T.DATE)
            if a.type.name == "TIME":
                r = jnp.mod(jnp.asarray(a.data) + delta, 86_400_000_000)
                return ColVal(r.astype(jnp.int64), valid, T.TIME)
            # TIMESTAMP wall / TIMESTAMP_TZ instant: plain micros add
            return ColVal((jnp.asarray(a.data) + delta)
                          .astype(jnp.int64), valid, a.type)
        if b.type.name == "INTERVAL_YEAR_MONTH" and a.type.name in (
                "DATE", "TIMESTAMP", "TIMESTAMP_TZ"):
            months = b.data if name == "add" else -b.data
            if a.type.name == "DATE":
                return ColVal(add_months(a.data, months), valid, T.DATE)
            from presto_tpu.functions import datetime_tz as _dtz

            src = a
            if a.type.name == "TIMESTAMP_TZ":  # civil math on wall clock
                src = _dtz._localize(a)
            us = jnp.asarray(src.data).astype(jnp.int64)
            days = jnp.floor_divide(us, 86_400_000_000)
            rem = us - days * 86_400_000_000
            out = add_months(days, months).astype(jnp.int64) \
                * 86_400_000_000 + rem
            r = ColVal(out, valid, T.TIMESTAMP)
            if a.type.name == "TIMESTAMP_TZ":
                r = _dtz._delocalize(r, a.type.tz or "UTC")
                return ColVal(r.data, valid, a.type)
            return r
        out_t = T.common_super_type(a.type, b.type)
        if out_t is not None and out_t.is_decimal:
            if name == "div":
                a = _decimal_to_double(a)
                b = _decimal_to_double(b)
                out_t = T.DOUBLE
            else:
                if name in ("add", "sub", "mul"):
                    out_t = _decimal_result_type(name, a.type, b.type)
                return _emit_decimal_arith(name, a, b, out_t, valid)
        x, y = a.data, b.data
        if name == "add":
            r = x + y
        elif name == "sub":
            r = x - y
        elif name == "mul":
            r = x * y
        elif name == "div":
            if out_t.is_integer:
                # SQL integer division truncates toward zero (C semantics),
                # unlike jnp floor_divide
                q = jnp.abs(x) // jnp.abs(y)
                r = jnp.where((x < 0) ^ (y < 0), -q, q)
            else:
                r = x / y
        elif name == "mod":
            if out_t.is_integer:
                r = jnp.sign(x) * (jnp.abs(x) % jnp.abs(y))
            else:
                r = jnp.abs(x) % jnp.abs(y) * jnp.sign(x)
        else:
            raise AssertionError(name)
        if out_t.is_decimal:
            out_t = T.DOUBLE if name == "div" else out_t
        return ColVal(r, valid, out_t)

    return emit


def _decimal_to_double(v: ColVal) -> ColVal:
    if not v.type.is_decimal:
        return v
    x = jnp.asarray(v.data).astype(jnp.float64) / (10 ** v.type.decimal_scale)
    return ColVal(x, v.valid, T.DOUBLE)


def _dec_shadow_checkable(*vals) -> bool:
    """Whether an int64-overflow shadow check is affordable here: data is
    concrete (not a tracer) and not resident on an accelerator — pulling
    a column off a TPU to guard an overflow would serialize the hot path."""
    for x in vals:
        if isinstance(x, jax.core.Tracer):
            return False
        if isinstance(x, jax.Array):
            try:
                if any(d.platform != "cpu" for d in x.devices()):
                    return False
            except Exception:
                return False
    return True


def _rescale_dec(data, frm_scale: int, to_scale: int, valid=None):
    """Rescale a scaled-int64 decimal; rounds half away from zero when
    reducing scale (Presto decimal rounding)."""
    if to_scale == frm_scale:
        return data
    if to_scale > frm_scale:
        factor = 10 ** (to_scale - frm_scale)
        if _dec_shadow_checkable(data, valid):
            T.check_decimal_overflow(
                np.asarray(data, dtype=np.float64) * factor,
                None if valid is None else np.asarray(valid),
                "rescaled value")
        return data * factor
    f = 10 ** (frm_scale - to_scale)
    q = jnp.abs(data) + f // 2
    return jnp.sign(data) * (q // f)


def _dec_scale(t: T.Type) -> int:
    return t.decimal_scale if t.is_decimal else 0


def _f64_to_u64_bits(x: jnp.ndarray) -> jnp.ndarray:
    """f64 in [0, 2^64) -> the int64 whose unsigned value is round(x)."""
    return jnp.where(x >= 2.0 ** 63, x - 2.0 ** 64, x).astype(jnp.int64)


def _check_dec38(r, what: str) -> None:
    """Raise on |value| >= 10^38 (the reference raises DECIMAL overflow,
    DecimalOperators) when the data is host-inspectable; traced values
    skip the check (long decimals run the dynamic executor, so data is
    concrete in practice)."""
    from presto_tpu.exec import dec128 as D128

    if isinstance(r, jax.core.Tracer):
        return
    bad = D128.exceeds_38_digits(r)
    if bool(jnp.any(bad)):
        raise ValueError(f"DECIMAL overflow: {what} exceeds 38 digits")


def _decimal_result_type(name: str, at: T.Type, bt: T.Type) -> T.Type:
    """Presto decimal result typing (DecimalOperators.{ADD,MULTIPLY}):
    integers coerce as decimal(18,0); precision growth past 18 switches
    to two-limb Int128 storage."""
    da = at if at.is_decimal else T.decimal(18, 0)
    db = bt if bt.is_decimal else T.decimal(18, 0)
    return T.decimal_add_type(da, db) if name in ("add", "sub") \
        else T.decimal_mul_type(da, db)


def _lift128(v: ColVal):
    """A decimal/integer operand as (n, 2) limbs (or (2,) for a scalar),
    at its own scale."""
    from presto_tpu.exec import dec128 as D128

    if v.type.is_decimal and v.type.is_long_decimal:
        if v.is_scalar and not hasattr(v.data, "shape"):
            return jnp.asarray(D128.from_host_int(int(v.data)))
        return jnp.asarray(v.data)
    if v.is_scalar and not hasattr(v.data, "shape"):
        return jnp.asarray(D128.from_host_int(int(v.data)))
    return D128.from_int64(jnp.asarray(v.data))


def _emit_decimal_arith_long(name, a, b, out_t, valid):
    """Two-limb Int128 path (reference:
    UnscaledDecimal128Arithmetic.{add,subtract,multiply})."""
    from presto_tpu.exec import dec128 as D128

    sa, sb = _dec_scale(a.type), _dec_scale(b.type)
    so = out_t.decimal_scale
    if a.is_scalar and b.is_scalar and not isinstance(
            a.data, jax.core.Tracer) and not isinstance(
            b.data, jax.core.Tracer):
        # literal folding: exact host integer arithmetic (covers python
        # ints AND concrete 0-d device scalars)
        x, y = int(a.data), int(b.data)
        if name == "add":
            r = x * 10 ** (so - sa) + y * 10 ** (so - sb)
        elif name == "sub":
            r = x * 10 ** (so - sa) - y * 10 ** (so - sb)
        elif name == "mul":
            r = x * y  # scales add to so
        else:
            raise NotImplementedError(f"long decimal {name}")
        return ColVal(r, valid, out_t)
    if name in ("add", "sub"):
        x = D128.scale_up(_lift128(a), so - sa)
        y = D128.scale_up(_lift128(b), so - sb)
        r = D128.add(x, y) if name == "add" else D128.sub(x, y)
        _check_dec38(r, "decimal " + name)
        return ColVal(r, valid, out_t)
    if name == "mul":
        # sa + sb == so by construction (decimal_mul_type)
        a_long = a.type.is_decimal and a.type.is_long_decimal
        b_long = b.type.is_decimal and b.type.is_long_decimal
        if not a_long and not b_long:
            x = jnp.asarray(a.data, jnp.int64) if not a.is_scalar \
                else jnp.int64(a.data)
            y = jnp.asarray(b.data, jnp.int64) if not b.is_scalar \
                else jnp.int64(b.data)
            return ColVal(D128.mul_int64(x, y), valid, out_t)
        # long x small-int scalar (e.g. sum * 2): exact via mul_small
        for big, small in ((a, b), (b, a)):
            bt_long = big.type.is_decimal and big.type.is_long_decimal
            if bt_long and small.is_scalar \
                    and not hasattr(small.data, "shape"):
                c = int(small.data)
                if abs(c) < (1 << 31):
                    r = D128.mul_small(_lift128(big), abs(c))
                    if c < 0:
                        r = D128.neg(r)
                    return ColVal(r, valid, out_t)
        raise NotImplementedError(
            "long-decimal x long-decimal multiply (128x128) is not "
            "supported; cast one side down or to DOUBLE")
    raise NotImplementedError(f"long decimal {name}")


def _emit_decimal_arith(name, a: ColVal, b: ColVal, out_t: T.Type, valid):
    if out_t.is_long_decimal or \
            (a.type.is_decimal and a.type.is_long_decimal) or \
            (b.type.is_decimal and b.type.is_long_decimal):
        return _emit_decimal_arith_long(name, a, b, out_t, valid)
    sa, sb = _dec_scale(a.type), _dec_scale(b.type)
    so = out_t.decimal_scale
    x = jnp.asarray(a.data).astype(jnp.int64) if not a.is_scalar else jnp.int64(a.data)
    y = jnp.asarray(b.data).astype(jnp.int64) if not b.is_scalar else jnp.int64(b.data)
    if name in ("add", "sub", "mod"):
        x = _rescale_dec(x, sa, so, a.valid)
        y = _rescale_dec(y, sb, so, b.valid)
        if name == "add":
            r = x + y
        elif name == "sub":
            r = x - y
        else:
            r = jnp.sign(x) * (jnp.abs(x) % jnp.abs(y))
        return ColVal(r, valid, out_t)
    if name == "mul":
        # int64 unscaled products wrap silently; a float64 shadow detects
        # magnitudes past ~19 digits (long-decimal storage limit) when the
        # data is host-resident — under jit tracing or on an accelerator
        # the check is skipped (ingest/cast boundaries still guard)
        if _dec_shadow_checkable(x, y, valid):
            T.check_decimal_overflow(
                np.asarray(x).astype(np.float64)
                * np.asarray(y).astype(np.float64),
                None if valid is None or not hasattr(valid, "shape")
                else np.asarray(valid),
                "unscaled product")
        r = _rescale_dec(x * y, sa + sb, so)  # true product scale is sa+sb
        return ColVal(r, valid, out_t)
    raise AssertionError(name)


for _n in ("add", "sub", "mul", "div", "mod"):
    register(_n)((_resolve_arith(_n), _emit_arith(_n)))

def _emit_neg(args):
    v = args[0]
    if v.type.is_decimal and v.type.is_long_decimal:
        from presto_tpu.exec import dec128 as D128

        if v.is_scalar and not hasattr(v.data, "shape"):
            return ColVal(-int(v.data), v.valid, v.type)
        return ColVal(D128.neg(jnp.asarray(v.data)), v.valid, v.type)
    return ColVal(-jnp.asarray(v.data) if hasattr(v.data, "shape")
                  else -v.data, v.valid, v.type)


register("neg")((
    lambda args: args[0] if len(args) == 1 and args[0].is_numeric else None,
    _emit_neg,
))


# ---- comparisons ----------------------------------------------------------


def _resolve_cmp(args):
    if len(args) != 2:
        return None
    a, b = args
    if T.common_super_type(a, b) is not None or a == b:
        return T.BOOLEAN
    return None


def _emit_cmp(name):
    def emit(args):
        a, b = args
        if a.type.is_string or b.type.is_string:
            return _string_compare(name, a, b)
        valid = all_valid(a, b)
        a_long = a.type.is_decimal and a.type.is_long_decimal
        b_long = b.type.is_decimal and b.type.is_long_decimal
        if a_long or b_long:
            from presto_tpu.exec import dec128 as D128

            if a.type.is_floating or b.type.is_floating:
                def flat(v, lng):
                    if not lng:
                        return _decimal_to_double(v).data
                    s = v.type.decimal_scale
                    return D128.to_float64(_lift128(v)) / (10 ** s)
                return ColVal(_PYOPS[name](flat(a, a_long), flat(b, b_long)),
                              valid, T.BOOLEAN)
            x, y = _lift128(a), _lift128(b)
            sx, sy = _dec_scale(a.type), _dec_scale(b.type)
            less, equal = D128.cmp_scaled(x, sx, y, sy)
            r = {"eq": lambda: equal,
                 "ne": lambda: ~equal,
                 "lt": lambda: less,
                 "le": lambda: less | equal,
                 "gt": lambda: ~(less | equal),
                 "ge": lambda: ~less}[name]()
            return ColVal(r, valid, T.BOOLEAN)
        return ColVal(_PYOPS[name](jnp.asarray(a.data) if not a.is_scalar else a.data,
                                   b.data), valid, T.BOOLEAN)

    return emit


for _n in ("eq", "ne", "lt", "le", "gt", "ge"):
    register(_n)((_resolve_cmp, _emit_cmp(_n)))


# ---- boolean 3VL ----------------------------------------------------------


def _bool_data(v: ColVal):
    return v.data


register("and")((
    lambda args: T.BOOLEAN if all(a.name in ("BOOLEAN", "UNKNOWN") for a in args) else None,
    lambda args: _emit_and(args),
))
register("or")((
    lambda args: T.BOOLEAN if all(a.name in ("BOOLEAN", "UNKNOWN") for a in args) else None,
    lambda args: _emit_or(args),
))
register("not")((
    lambda args: T.BOOLEAN if len(args) == 1 and args[0].name in ("BOOLEAN", "UNKNOWN") else None,
    lambda args: ColVal(~jnp.asarray(args[0].data) if hasattr(args[0].data, "shape")
                        else not args[0].data, args[0].valid, T.BOOLEAN),
))


def _emit_and(args):
    a, b = args
    da, db = jnp.asarray(a.data), jnp.asarray(b.data)
    va = a.valid if a.valid is not None else True
    vb = b.valid if b.valid is not None else True
    false_a = (va if va is not True else True) & ~da if va is not True else ~da
    false_b = (vb if vb is not True else True) & ~db if vb is not True else ~db
    data = da & db
    if a.valid is None and b.valid is None:
        return ColVal(data, None, T.BOOLEAN)
    # null unless result determined: false wins over null
    valid = jnp.asarray(va) & jnp.asarray(vb) | false_a | false_b
    return ColVal(data, valid, T.BOOLEAN)


def _emit_or(args):
    a, b = args
    da, db = jnp.asarray(a.data), jnp.asarray(b.data)
    va = a.valid if a.valid is not None else True
    vb = b.valid if b.valid is not None else True
    true_a = jnp.asarray(va) & da
    true_b = jnp.asarray(vb) & db
    data = true_a | true_b
    if a.valid is None and b.valid is None:
        return ColVal(da | db, None, T.BOOLEAN)
    valid = jnp.asarray(va) & jnp.asarray(vb) | true_a | true_b
    return ColVal(data, valid, T.BOOLEAN)


# ---- null handling --------------------------------------------------------


register("is_null")((
    lambda args: T.BOOLEAN if len(args) == 1 else None,
    lambda args: ColVal(
        ~args[0].valid if args[0].valid is not None and hasattr(args[0].valid, "shape")
        else (jnp.zeros(jnp.asarray(args[0].data).shape, bool) if args[0].valid is None
              else not args[0].valid),
        None, T.BOOLEAN),
))


def _resolve_coalesce(args):
    t = args[0]
    for a in args[1:]:
        t = T.common_super_type(t, a) or t
    return t


def _emit_coalesce(args):
    out = args[-1]
    for v in reversed(args[:-1]):
        if v.valid is None:
            out = v
        else:
            cond = jnp.asarray(v.valid)
            data = jnp.where(cond, jnp.asarray(v.data), jnp.asarray(out.data))
            valid = cond | (jnp.asarray(out.valid) if out.valid is not None else True)
            out = ColVal(data, valid if out.valid is not None else None, v.type, v.dictionary)
    return out


register("coalesce")((_resolve_coalesce, _emit_coalesce))

register("nullif")((
    lambda args: args[0] if len(args) == 2 else None,
    lambda args: _emit_nullif(args),
))


def _emit_nullif(args):
    a, b = args
    eq = lookup("eq").emit([a, b])
    eq_true = jnp.asarray(eq.data) & (jnp.asarray(eq.valid) if eq.valid is not None else True)
    valid = (jnp.asarray(a.valid) if a.valid is not None else
             jnp.ones(jnp.asarray(a.data).shape, bool)) & ~eq_true
    return ColVal(a.data, valid, a.type, a.dictionary)


# ---- conditional ----------------------------------------------------------


def _resolve_if(args):
    if len(args) == 3 and args[0].name == "BOOLEAN":
        return T.common_super_type(args[1], args[2])
    return None


def _emit_if(args):
    c, a, b = args
    cond = jnp.asarray(c.data)
    if c.valid is not None:
        cond = cond & jnp.asarray(c.valid)
    if a.type.is_string:
        a2, b2 = _unify_dictionaries(a, b)
        data = jnp.where(cond, jnp.asarray(a2.data), jnp.asarray(b2.data))
        valid = _merge_valid(cond, a2, b2)
        return ColVal(data, valid, a2.type, a2.dictionary)
    if a.type.name in ("ARRAY", "MAP", "ROW") \
            or b.type.name in ("ARRAY", "MAP", "ROW"):
        a2, b2 = _unify_tuple_dictionaries(a, b)
        data = jnp.where(cond, jnp.asarray(a2.data), jnp.asarray(b2.data))
        return ColVal(data, _merge_valid(cond, a2, b2),
                      a2.type if a2.type.name != "UNKNOWN" else b2.type,
                      a2.dictionary)
    data = jnp.where(cond, jnp.asarray(a.data), jnp.asarray(b.data))
    return ColVal(data, _merge_valid(cond, a, b), a.type if a.type != T.UNKNOWN else b.type)


def _merge_valid(cond, a, b):
    if a.valid is None and b.valid is None:
        return None
    va = jnp.asarray(a.valid) if a.valid is not None else True
    vb = jnp.asarray(b.valid) if b.valid is not None else True
    return jnp.where(cond, va, vb)


def _unify_dictionaries(a: ColVal, b: ColVal):
    if a.dictionary is None and isinstance(a.data, str):
        a = _lit_to_dict_colval(a)
    if b.dictionary is None and isinstance(b.data, str):
        b = _lit_to_dict_colval(b)
    if a.dictionary is b.dictionary:
        return a, b
    merged = Dictionary(np.unique(np.concatenate([a.dictionary.values, b.dictionary.values])))
    la = jnp.asarray(translate_codes(a.dictionary, merged))
    lb = jnp.asarray(translate_codes(b.dictionary, merged))
    ca = la[jnp.clip(a.data, 0, len(a.dictionary) - 1)]
    cb = lb[jnp.clip(b.data, 0, len(b.dictionary) - 1)]
    return (ColVal(ca, a.valid, a.type, merged), ColVal(cb, b.valid, b.type, merged))


def _unify_tuple_dictionaries(a: ColVal, b: ColVal):
    """Branch merge for container (tuple-dictionary) values: a NULL arm
    adopts the other arm's dictionary; two dictionaries merge by entry
    union with code translation (same role as _unify_dictionaries)."""
    if a.dictionary is None and b.dictionary is None:
        return a, b
    if a.dictionary is None:
        a = ColVal(jnp.asarray(0, jnp.int32), a.valid, b.type, b.dictionary)
        return a, b
    if b.dictionary is None:
        b = ColVal(jnp.asarray(0, jnp.int32), b.valid, a.type, a.dictionary)
        return a, b
    if a.dictionary is b.dictionary:
        return a, b
    av, bv = a.dictionary.values.tolist(), b.dictionary.values.tolist()
    uniq = sorted(set(av) | set(bv), key=repr)
    cmap = {v: i for i, v in enumerate(uniq)}
    u = np.empty(len(uniq), dtype=object)
    u[:] = uniq
    merged = Dictionary(u)
    la = jnp.asarray(np.fromiter((cmap[v] for v in av), np.int32, len(av)))
    lb = jnp.asarray(np.fromiter((cmap[v] for v in bv), np.int32, len(bv)))
    ca = la[jnp.clip(a.data, 0, len(av) - 1)]
    cb = lb[jnp.clip(b.data, 0, len(bv) - 1)]
    return (ColVal(ca, a.valid, a.type, merged),
            ColVal(cb, b.valid, b.type, merged))


register("if")((_resolve_if, _emit_if))


def _resolve_case(args):
    # args: c1, v1, c2, v2, ..., [else]
    vals = [args[i] for i in range(1, len(args) - (len(args) % 2), 2)]
    if len(args) % 2 == 1:
        vals.append(args[-1])
    t = vals[0]
    for v in vals[1:]:
        t = T.common_super_type(t, v) or t
    return t


def _emit_case(args):
    has_else = len(args) % 2 == 1
    pairs = [(args[i], args[i + 1]) for i in range(0, len(args) - (1 if has_else else 0), 2)]
    if has_else:
        out = args[-1]
    else:
        v0 = pairs[0][1]
        shape = jnp.asarray(v0.data).shape
        out = ColVal(jnp.zeros(shape, _np_dtype(v0.type)), jnp.zeros(shape, bool) if shape else False,
                     v0.type, v0.dictionary)
    for c, v in reversed(pairs):
        out = _emit_if([c, v, out])
    return out


register("case")((_resolve_case, _emit_case))


# ---- LIKE / string predicates --------------------------------------------


def _resolve_like(args):
    return T.BOOLEAN if args[0].is_string else None


def _emit_like(args):
    col, pat = args[0], args[1]
    pattern = _as_string_literal(pat)
    if pattern is None:
        raise NotImplementedError("LIKE requires a literal pattern")
    esc = _as_string_literal(args[2]) if len(args) > 2 else None
    rx = re.compile(like_to_regex(pattern, esc), re.DOTALL)
    value = _as_string_literal(col)
    if value is not None:
        return ColVal(bool(rx.match(value)), col.valid, T.BOOLEAN)
    return ColVal(_host_string_pred(col, lambda v: rx.match(v) is not None).data,
                  col.valid, T.BOOLEAN)


register("like")((_resolve_like, _emit_like))


# ---- string functions (host dictionary transforms) ------------------------


def _str_transform(name, fn, resolve_type=T.VARCHAR):
    def resolve(args):
        return resolve_type if args[0].is_string else None

    def emit(args):
        col = args[0]
        lit = _as_string_literal(col)
        extra = [a.data for a in args[1:]]
        for e in extra:
            if hasattr(e, "shape") and getattr(e, "ndim", 0) > 0:
                raise NotImplementedError(f"{name} with non-constant arguments")
        if lit is not None:
            v = fn(lit, *extra)
            if resolve_type == T.VARCHAR:
                return ColVal(v, col.valid, T.VARCHAR)  # still a literal
            return ColVal(v, col.valid, resolve_type)
        if resolve_type.is_string:  # VARCHAR / JSON output
            r = _host_string_transform(col, lambda v: fn(v, *extra),
                                       resolve_type)
            return ColVal(r.data, col.valid, resolve_type, r.dictionary)
        r = _host_string_pred(col, lambda v: fn(v, *extra))
        data = r.data
        if resolve_type != T.BOOLEAN:
            lut = jnp.asarray(
                np.asarray([fn(v, *extra) for v in col.dictionary.values],
                           dtype=_np_dtype(resolve_type)))
            data = lut[jnp.clip(col.data, 0, len(col.dictionary) - 1)]
        return ColVal(data, col.valid, resolve_type)

    return resolve, emit


def _substr(v, start, length=None):
    start = int(start)
    s = start - 1 if start > 0 else len(v) + start
    if length is None:
        return v[s:]
    return v[s:s + int(length)]


register("substring")((_str_transform("substring", _substr)))
register("substr")((_str_transform("substr", _substr)))
register("lower")((_str_transform("lower", lambda v: v.lower())))
register("upper")((_str_transform("upper", lambda v: v.upper())))
register("trim")((_str_transform("trim", lambda v: v.strip())))
register("ltrim")((_str_transform("ltrim", lambda v: v.lstrip())))
register("rtrim")((_str_transform("rtrim", lambda v: v.rstrip())))
register("reverse")((_str_transform("reverse", lambda v: v[::-1])))
register("replace")((_str_transform(
    "replace", lambda v, old, new="": v.replace(str(old), str(new)))))
register("length")((_str_transform("length", lambda v: len(v), T.BIGINT)))
register("strpos")((_str_transform(
    "strpos", lambda v, sub: v.find(str(sub)) + 1, T.BIGINT)))
register("starts_with")((_str_transform(
    "starts_with", lambda v, p: v.startswith(str(p)), T.BOOLEAN)))


def _resolve_concat(args):
    if all(a.is_string for a in args):
        return T.VARCHAR
    if args and all(a.name == "ARRAY" for a in args):
        ct = args[0].params[0]
        for a in args[1:]:
            ct2 = T.common_super_type(ct, a.params[0])
            ct = ct2 if ct2 is not None else ct
        return T.array_of(ct)
    return None


def _emit_concat_arrays(args):
    """ARRAY || ARRAY / concat(arrays...) — dedups code tuples host-side
    so the work is per distinct combination, not per row (concrete codes
    only; compiled mode falls back)."""
    codes_list = [np.asarray(a.data) for a in args]
    scalar = all(c.ndim == 0 for c in codes_list)
    n = max((len(c) for c in codes_list if c.ndim > 0), default=1)
    cols = [np.broadcast_to(np.atleast_1d(c), (n,)) for c in codes_list]
    stacked = np.stack(cols, axis=1)
    uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
    outs = np.empty(len(uniq), dtype=object)
    for k, combo in enumerate(uniq):
        t = ()
        for a, code in zip(args, combo):
            dv = a.dictionary.values if a.dictionary is not None \
                else np.empty(0, dtype=object)
            t = t + (tuple(dv[int(code)]) if 0 <= int(code) < len(dv) else ())
        outs[k] = t
    rt = _resolve_concat([a.type for a in args])
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(
        outs, ColVal(codes, all_valid(*args), rt), rt)


def _emit_concat(args):
    if args and args[0].type.name == "ARRAY":
        return _emit_concat_arrays(args)
    out = args[0]
    for nxt in args[1:]:
        lo, ln = _as_string_literal(out), _as_string_literal(nxt)
        if lo is not None and ln is not None:
            out = ColVal(lo + ln, all_valid(out, nxt), T.VARCHAR)
        elif ln is not None:
            r = _host_string_transform(out, lambda v: v + ln)
            out = ColVal(r.data, all_valid(out, nxt), T.VARCHAR, r.dictionary)
        elif lo is not None:
            r = _host_string_transform(nxt, lambda v: lo + v)
            out = ColVal(r.data, all_valid(out, nxt), T.VARCHAR, r.dictionary)
        elif out.dictionary is not None and nxt.dictionary is not None \
                and len(out.dictionary) * len(nxt.dictionary) <= (1 << 20):
            # dictionary x dictionary concat: the result dictionary is
            # the value cross product (|A| x |B| host strings — q84's
            # last_name || ', ' || first_name is ~60x64), codes combine
            # row-major, then re-sort to keep the code-order ==
            # lexicographic-order invariant
            av = out.dictionary.values.astype(str)
            bv = nxt.dictionary.values.astype(str)
            prod = np.char.add(av[:, None], bv[None, :]).astype(
                object).ravel()
            nb = len(bv)
            codes = ColVal(
                jnp.clip(out.data, 0, len(av) - 1) * nb
                + jnp.clip(nxt.data, 0, nb - 1),
                all_valid(out, nxt), T.VARCHAR)
            out = normalize_dictionary(prod, codes)
        elif out.dictionary is not None and nxt.dictionary is not None:
            raise NotImplementedError(
                "concat of string columns whose dictionary product "
                f"({len(out.dictionary)} x {len(nxt.dictionary)}) "
                "exceeds the materialization cap")
        else:
            raise NotImplementedError(
                "concat of non-dictionary string columns")
    return out


register("concat")((_resolve_concat, _emit_concat))


# ---- date/time ------------------------------------------------------------


def _extract_emit(field):
    def emit(args):
        v = args[0]
        days = jnp.asarray(v.data)
        if v.type.name == "TIMESTAMP":
            days = jnp.floor_divide(days, 86_400_000_000).astype(jnp.int64)
        y, m, d = civil_from_days(days)
        if field == "YEAR":
            r = y
        elif field == "MONTH":
            r = m
        elif field == "DAY":
            r = d
        elif field == "QUARTER":
            r = (m - 1) // 3 + 1
        elif field == "DOW":
            r = (days + 4) % 7  # 1970-01-01 = Thursday
        elif field == "DOY":
            r = days - days_from_civil(y, jnp.asarray(1), jnp.asarray(1)) + 1
        elif field == "WEEK":
            r = (days - days_from_civil(y, jnp.asarray(1), jnp.asarray(1))) // 7 + 1
        else:
            raise NotImplementedError(f"EXTRACT({field})")
        return ColVal(r.astype(jnp.int64), v.valid, T.BIGINT)

    return emit


for _f in ("YEAR", "MONTH", "DAY", "QUARTER", "DOW", "DOY", "WEEK"):
    register(f"extract_{_f.lower()}")((
        lambda args: T.BIGINT if args[0].is_temporal else None,
        _extract_emit(_f),
    ))
register("year")(( lambda args: T.BIGINT if args[0].is_temporal else None, _extract_emit("YEAR")))
register("month")((lambda args: T.BIGINT if args[0].is_temporal else None, _extract_emit("MONTH")))
register("day")((lambda args: T.BIGINT if args[0].is_temporal else None, _extract_emit("DAY")))
register("quarter")((lambda args: T.BIGINT if args[0].is_temporal else None, _extract_emit("QUARTER")))


def _resolve_date_cast(args):
    return T.DATE if args[0].is_string else None


def _emit_date_from_str(args):
    v = args[0]
    lit = _as_string_literal(v)
    to_days = lambda s: int(
        (np.datetime64(str(s).strip(), "D") - np.datetime64("1970-01-01", "D"))
        / np.timedelta64(1, "D"))
    if lit is not None:
        return ColVal(to_days(lit), v.valid, T.DATE)
    lut = jnp.asarray(np.asarray([to_days(x) for x in v.dictionary.values], dtype=np.int32))
    return ColVal(lut[jnp.clip(v.data, 0, len(v.dictionary) - 1)], v.valid, T.DATE)


register("date")((_resolve_date_cast, _emit_date_from_str))


def _resolve_date_add(args):
    # date_add(unit, value, date)
    return T.DATE if len(args) == 3 and args[2].name == "DATE" else None


def _emit_date_add(args):
    unit = _as_string_literal(args[0])
    n = args[1].data
    d = args[2]
    if unit in ("day", "DAY"):
        return ColVal((jnp.asarray(d.data) + n).astype(jnp.int32), d.valid, T.DATE)
    if unit in ("week", "WEEK"):
        return ColVal((jnp.asarray(d.data) + 7 * n).astype(jnp.int32), d.valid, T.DATE)
    if unit in ("month", "MONTH"):
        return ColVal(add_months(d.data, n), d.valid, T.DATE)
    if unit in ("year", "YEAR"):
        return ColVal(add_months(d.data, 12 * n), d.valid, T.DATE)
    raise NotImplementedError(f"date_add unit {unit}")


register("date_add")((_resolve_date_add, _emit_date_add))


# ---- math -----------------------------------------------------------------


def _math1(name, fn, out=None):
    def resolve(args):
        if len(args) == 1 and args[0].is_numeric:
            return out or (args[0] if not out else out)
        return None

    def emit(args):
        a = args[0]
        t = out or a.type
        return ColVal(fn(jnp.asarray(a.data) if not a.is_scalar else a.data),
                      a.valid, t)

    return resolve, emit


register("abs")((_math1("abs", jnp.abs)))
register("sqrt")((lambda args: T.DOUBLE if args[0].is_numeric else None,
                  lambda args: ColVal(jnp.sqrt(jnp.asarray(args[0].data).astype(jnp.float64)),
                                      args[0].valid, T.DOUBLE)))
register("exp")((lambda args: T.DOUBLE if args[0].is_numeric else None,
                 lambda args: ColVal(jnp.exp(jnp.asarray(args[0].data).astype(jnp.float64)),
                                     args[0].valid, T.DOUBLE)))
register("ln")((lambda args: T.DOUBLE if args[0].is_numeric else None,
                lambda args: ColVal(jnp.log(jnp.asarray(args[0].data).astype(jnp.float64)),
                                    args[0].valid, T.DOUBLE)))
register("log10")((lambda args: T.DOUBLE if args[0].is_numeric else None,
                   lambda args: ColVal(jnp.log10(jnp.asarray(args[0].data).astype(jnp.float64)),
                                       args[0].valid, T.DOUBLE)))
register("floor")((_math1("floor", lambda x: jnp.floor(x))))
register("ceil")((_math1("ceil", lambda x: jnp.ceil(x))))
register("ceiling")((_math1("ceiling", lambda x: jnp.ceil(x))))
register("sign")((_math1("sign", jnp.sign)))


def _dmath1(name, fn):
    """1-arg numeric -> DOUBLE (reference: MathFunctions.java)."""
    return (lambda args: T.DOUBLE if len(args) == 1 and args[0].is_numeric
            else None,
            lambda args: ColVal(
                fn(jnp.asarray(args[0].data).astype(jnp.float64)),
                args[0].valid, T.DOUBLE))


for _nm, _f in [("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
                ("asin", jnp.arcsin), ("acos", jnp.arccos),
                ("atan", jnp.arctan), ("sinh", jnp.sinh),
                ("cosh", jnp.cosh), ("tanh", jnp.tanh),
                ("degrees", jnp.degrees), ("radians", jnp.radians),
                ("cbrt", jnp.cbrt), ("log2", jnp.log2),
                ("exp2", jnp.exp2)]:
    register(_nm)(_dmath1(_nm, _f))


def _resolve_round(args):
    if args[0].is_numeric:
        return args[0]
    return None


def _emit_round(args):
    a = args[0]
    d = int(args[1].data) if len(args) > 1 else 0
    x = jnp.asarray(a.data)
    if a.type.is_integer:
        return a
    scale = 10.0 ** d
    # SQL rounds half away from zero; jnp.round rounds half to even
    r = jnp.sign(x) * jnp.floor(jnp.abs(x) * scale + 0.5) / scale
    return ColVal(r, a.valid, a.type)


register("round")((_resolve_round, _emit_round))

register("power")((
    lambda args: T.DOUBLE if len(args) == 2 else None,
    lambda args: ColVal(jnp.power(jnp.asarray(args[0].data).astype(jnp.float64),
                                  args[1].data), all_valid(*args), T.DOUBLE),
))
register("pow")(( REGISTRY["power"].resolve, REGISTRY["power"].emit))
def _emit_fold(op):
    def emit(args):
        acc = jnp.asarray(args[0].data) if not args[0].is_scalar else args[0].data
        for a in args[1:]:
            acc = op(acc, a.data)
        return ColVal(acc, all_valid(*args), args[0].type)

    return emit


register("greatest")((_resolve_coalesce, _emit_fold(jnp.maximum)))
register("least")((_resolve_coalesce, _emit_fold(jnp.minimum)))


# ---- cast -----------------------------------------------------------------


def _overflow_checked_valid(fits, v: ColVal, safe: bool, guards, msg: str):
    """Shared CAST-overflow plumbing: under TRY_CAST the failing rows go
    NULL; otherwise raise eagerly, or (at trace time) append a guard that
    aborts the compiled program to the dynamic path, which re-evaluates
    eagerly and raises properly.  Returns the result validity mask."""
    if safe:
        return fits if v.valid is None else (jnp.asarray(v.valid) & fits)
    live = fits if v.valid is None else fits | ~jnp.asarray(v.valid)
    if isinstance(fits, jax.core.Tracer):
        if guards is not None:
            guards.append(~jnp.all(live))
    elif not bool(jnp.all(live)):
        raise ValueError(msg)
    return v.valid


def _emit_cast_decimal(v: ColVal, to: T.Type, safe: bool,
                       guards=None) -> ColVal:
    from presto_tpu.exec import dec128 as D128

    frm = v.type
    if frm.is_decimal and frm.is_long_decimal:
        s = frm.decimal_scale
        if v.is_scalar and not hasattr(v.data, "shape"):
            # python-int long scalar: fold host-side, exactly
            import decimal as _d
            from decimal import ROUND_HALF_UP, Decimal

            _hp = _d.Context(prec=80)
            d = _hp.create_decimal(int(v.data)).scaleb(-s, context=_hp)
            if to.is_decimal:
                with _d.localcontext() as ctx:
                    ctx.prec = 80
                    unscaled = int(d.scaleb(to.decimal_scale).quantize(
                        Decimal(1), rounding=ROUND_HALF_UP))
                limit = (1 << 63) if not to.is_long_decimal else 10 ** 38
                if abs(unscaled) >= limit:
                    if safe:
                        return ColVal(0, False, to)
                    raise ValueError(
                        f"DECIMAL overflow: CAST to {to} (reference "
                        "raises on rescale overflow, "
                        "UnscaledDecimal128Arithmetic.rescale)")
                return ColVal(unscaled, v.valid, to)
            if to.is_floating:
                return ColVal(float(d), v.valid, to)
            if to.is_integer:
                iv = int(d.quantize(Decimal(1), rounding=ROUND_HALF_UP,
                                    context=_hp))
                _tmin, _tmax = to.integer_bounds()
                if not _tmin <= iv <= _tmax:
                    if safe:
                        return ColVal(0, False, to)
                    raise ValueError(
                        f"DECIMAL overflow: CAST {frm} -> {to} value "
                        "does not fit an integer")
                return ColVal(iv, v.valid, to)
            if to.is_string:
                return ColVal(str(d), v.valid, to)
            raise NotImplementedError(f"CAST {frm} -> {to}")
        a = _lift128(v)
        if to.is_decimal and to.is_long_decimal:
            r = D128.scale_up(a, to.decimal_scale - s) \
                if to.decimal_scale >= s \
                else D128.scale_down_round(a, s - to.decimal_scale)
            if not safe:
                _check_dec38(r, f"CAST {frm} -> {to}")
            return ColVal(r, v.valid, to)
        if to.is_decimal:  # long -> short: rescale, must fit int64
            r = D128.scale_down_round(a, s - to.decimal_scale) \
                if s >= to.decimal_scale \
                else D128.scale_up(a, to.decimal_scale - s)
            fits = r[..., D128.HI] == (r[..., D128.LO] >> 63)
            short = r[..., D128.LO]
            valid = _overflow_checked_valid(
                fits, v, safe, guards,
                f"DECIMAL overflow: CAST {frm} -> {to} value "
                "does not fit a short decimal")
            return ColVal(short, valid, to)
        if to.is_floating:
            r = D128.to_float64(a) / (10 ** s)
            return ColVal(r.astype(to.numpy_dtype()), v.valid, to)
        if to.is_integer:
            r = D128.scale_down_round(a, s)
            # rounded magnitude may exceed the TARGET integer type:
            # taking the low limb alone (or astype to a narrower int)
            # would silently wrap (reference raises on overflow)
            lo_limb = r[..., D128.LO]
            fits = r[..., D128.HI] == (lo_limb >> 63)
            tmin, tmax = to.integer_bounds()
            if to.name != "BIGINT":
                fits = fits & (lo_limb >= tmin) & (lo_limb <= tmax)
            valid = _overflow_checked_valid(
                fits, v, safe, guards,
                f"DECIMAL overflow: CAST {frm} -> {to} value "
                "does not fit an integer")
            return ColVal(lo_limb.astype(to.numpy_dtype()),
                          valid, to)
        if to.is_string:
            if isinstance(a, jax.core.Tracer):
                raise NotImplementedError(
                    "CAST(long decimal AS VARCHAR) inside a compiled "
                    "fragment")
            from decimal import Decimal

            ints = D128.to_host_ints(np.asarray(a))  # signed
            vals = np.empty(len(ints), dtype=object)
            import decimal as _d

            with _d.localcontext() as ctx:
                ctx.prec = 80  # scaleb rounds to context precision
                for i, u in enumerate(ints):
                    vals[i] = str(Decimal(u).scaleb(-s))
            codes = ColVal(jnp.arange(len(ints), dtype=jnp.int32),
                           v.valid, to)
            return normalize_dictionary(vals, codes)
        raise NotImplementedError(f"CAST {frm} -> {to}")
    x = jnp.asarray(v.data)
    if to.is_decimal and to.is_long_decimal:
        s = to.decimal_scale
        if (frm.is_decimal or frm.is_integer) and v.is_scalar \
                and not isinstance(v.data, jax.core.Tracer):
            import decimal as _d

            s0 = frm.decimal_scale if frm.is_decimal else 0
            with _d.localcontext() as ctx:
                ctx.prec = 80
                unscaled = int(_d.Decimal(int(v.data)).scaleb(s - s0)
                               .quantize(_d.Decimal(1),
                                         rounding=_d.ROUND_HALF_UP))
            return ColVal(unscaled, v.valid, to)
        if frm.is_decimal:
            a = D128.from_int64(x.astype(jnp.int64))
            r = D128.scale_up(a, s - frm.decimal_scale) \
                if s >= frm.decimal_scale \
                else D128.scale_down_round(a, frm.decimal_scale - s)
            return ColVal(r, v.valid, to)
        if frm.is_integer:
            return ColVal(D128.scale_up(D128.from_int64(
                x.astype(jnp.int64)), s), v.valid, to)
        if frm.is_floating:
            if v.is_scalar and not isinstance(v.data, jax.core.Tracer):
                # concrete scalar: exact host fold keeps it a python int
                # (so downstream literal arithmetic stays exact)
                from decimal import ROUND_HALF_UP, Decimal

                unscaled = int(Decimal(float(v.data)).scaleb(s).quantize(
                    Decimal(1), rounding=ROUND_HALF_UP))
                return ColVal(unscaled, v.valid, to)
            scaled = x.astype(jnp.float64) * (10 ** s)
            r = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            hi = jnp.floor(r / (2.0 ** 64))
            lo_f = r - hi * (2.0 ** 64)
            lo = _f64_to_u64_bits(lo_f)
            return ColVal(jnp.stack(
                [hi.astype(jnp.int64), lo], axis=-1), v.valid, to)
        raise NotImplementedError(f"CAST {frm} -> {to}")
    if to.is_decimal:
        s = to.decimal_scale
        if frm.is_decimal:
            return ColVal(_rescale_dec(x.astype(jnp.int64), frm.decimal_scale, s,
                                       v.valid),
                          v.valid, to)
        if frm.is_integer:
            return ColVal(x.astype(jnp.int64) * (10 ** s), v.valid, to)
        if frm.is_floating:
            scaled = x.astype(jnp.float64) * (10 ** s)
            r = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            nan = jnp.isnan(scaled)  # e.g. TRY_CAST parse failures
            r = jnp.where(nan, 0.0, r)
            valid = v.valid
            if hasattr(nan, "shape") and (getattr(nan, "ndim", 0) > 0
                                          or bool(jnp.any(nan))):
                valid = (~nan) if valid is None else (jnp.asarray(valid)
                                                      & ~nan)
            return ColVal(r.astype(jnp.int64), valid, to)
        raise NotImplementedError(f"CAST {frm} -> {to}")
    # from decimal
    s = frm.decimal_scale
    if to.is_floating:
        r = x.astype(jnp.float64) / (10 ** s)
        return ColVal(r.astype(to.numpy_dtype()), v.valid, to)
    if to.is_integer:
        # HALF_UP rounding (reference DecimalCasts.shortDecimalToBigint
        # rounds, it does not truncate) + target-dtype overflow check —
        # astype alone would silently wrap e.g. 3000000000.5 -> INTEGER
        half = (10 ** s) // 2
        r = jnp.sign(x.astype(jnp.int64)) * (
            (jnp.abs(x.astype(jnp.int64)) + half) // (10 ** s))
        tmin, tmax = to.integer_bounds()
        fits = (r >= tmin) & (r <= tmax)
        valid = _overflow_checked_valid(
            fits, v, safe, guards,
            f"DECIMAL overflow: CAST {frm} -> {to} value "
            "does not fit the target integer type")
        return ColVal(r.astype(to.numpy_dtype()), valid, to)
    raise NotImplementedError(f"CAST {frm} -> {to}")


def _container_same_elements(a: T.Type, b: T.Type) -> bool:
    def same(x, y):
        return x == y or y.name == "UNKNOWN" or x.name == "UNKNOWN"

    if a.name == "ROW":
        return len(a.params) == len(b.params) and all(
            same(x[1], y[1]) for x, y in zip(a.params, b.params))
    return all(same(x, y) for x, y in zip(a.params, b.params))


def _py_cast_scalar(x, ft: T.Type, tt: T.Type):
    if x is None:
        return None
    if ft == tt or tt.name == "UNKNOWN":
        return x
    if tt.name in ("ARRAY", "MAP", "ROW"):
        return _py_cast_value(x, ft, tt)
    if tt.is_string:
        return x if ft.is_string else _render_varchar(x, ft)
    if tt.is_integer:
        return int(x)
    if tt.is_floating:
        return float(x)
    if tt.name == "BOOLEAN":
        return bool(x)
    raise NotImplementedError(f"CAST {ft} -> {tt} inside a container")


def _py_cast_value(t, frm: T.Type, to: T.Type):
    """Convert one container dictionary entry between element types."""
    if t is None:
        return None
    if frm.name == "ARRAY":
        return tuple(_py_cast_scalar(e, frm.params[0], to.params[0])
                     for e in t)
    if frm.name == "MAP":
        return _map_sort(
            (_py_cast_scalar(k, frm.params[0], to.params[0]),
             _py_cast_scalar(w, frm.params[1], to.params[1])) for k, w in t)
    return tuple(_py_cast_scalar(e, ft[1], tt[1])
                 for e, ft, tt in zip(t, frm.params, to.params))


def _cast_to_varchar(v: ColVal) -> ColVal:
    """Host-side render (reference: the type's cast-to-varchar operators,
    e.g. operator/scalar/...CastToVarchar).  Needs concrete data — under
    jit tracing np.asarray raises and the query falls back to dynamic."""
    frm = v.type

    def fmt(x):
        return _render_varchar(x, frm)

    if v.is_scalar:
        x = v.data.item() if hasattr(v.data, "item") else v.data
        out = _lit_to_dict_colval(ColVal(fmt(x), None, T.VARCHAR))
        return ColVal(out.data, v.valid, T.VARCHAR, out.dictionary)
    arr = np.asarray(v.data)
    vals = [fmt(x) for x in arr.tolist()]
    uniq, inv = np.unique(np.asarray(vals, dtype=str), return_inverse=True)
    return ColVal(jnp.asarray(inv.astype(np.int32)), v.valid, T.VARCHAR,
                  Dictionary(uniq.astype(object)))


def _render_varchar(x, frm: T.Type) -> str:
    import datetime as _dt

    if frm.name == "BOOLEAN":
        return "true" if x else "false"
    if frm.is_integer:
        return str(int(x))
    if frm.is_floating:
        f = float(x)
        if f != f:
            return "NaN"
        if f == float("inf"):
            return "Infinity"
        if f == float("-inf"):
            return "-Infinity"
        # Java Double.toString: plain decimal in [1e-3, 1e7), else
        # scientific with a [1,10) mantissa and no exponent sign
        if 1e-3 <= abs(f) < 1e7 or f == 0.0:
            if f == int(f):
                return f"{f:.1f}"
            return repr(f)
        mant, exp = f"{f:E}".split("E")
        mant = mant.rstrip("0").rstrip(".")
        if "." not in mant:
            mant += ".0"
        return f"{mant}E{int(exp)}"
    if frm.is_decimal:
        s = frm.decimal_scale
        n = int(x)
        sign = "-" if n < 0 else ""
        n = abs(n)
        if s == 0:
            return sign + str(n)
        return f"{sign}{n // 10 ** s}.{n % 10 ** s:0{s}d}"
    if frm.name == "DATE":
        return (_dt.date(1970, 1, 1)
                + _dt.timedelta(days=int(x))).isoformat()
    if frm.name == "TIMESTAMP":  # int64 microseconds since epoch
        t = _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(x))
        return t.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]
    if frm.name == "TIMESTAMP_TZ":  # UTC micros; zone in the type
        from presto_tpu import session_ctx
        from presto_tpu.functions import tzdb

        zone = frm.tz or session_ctx.current_zone()
        local = tzdb.rules(zone).utc_to_local_scalar(int(x))
        t = _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=local)
        return t.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3] + " " + zone
    if frm.name == "TIME":  # micros since midnight
        us = int(x)
        return (_dt.datetime(1970, 1, 1)
                + _dt.timedelta(microseconds=us)).strftime("%H:%M:%S.%f")[:-3]
    if frm.name == "TIME_TZ":
        us = int(x)
        off = int(frm.tz or 0)
        body = (_dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=us)
                ).strftime("%H:%M:%S.%f")[:-3]
        sign = "-" if off < 0 else "+"
        return f"{body}{sign}{abs(off) // 60:02d}:{abs(off) % 60:02d}"
    raise NotImplementedError(f"CAST {frm} -> VARCHAR")


def emit_cast(v: ColVal, to: T.Type, safe: bool = False,
              guards=None) -> ColVal:
    frm = v.type
    if frm == to:
        return v
    if frm.name in ("TIMESTAMP_TZ", "TIME", "TIME_TZ") \
            or to.name in ("TIMESTAMP_TZ", "TIME", "TIME_TZ"):
        from presto_tpu.functions import datetime_tz as _dtz

        r = _dtz.emit_cast_tz(v, to, safe)
        if r is not None:
            return r  # None: fall through (e.g. ->VARCHAR render below)
    if frm.is_string and to.is_string:
        if to.name == "JSON" and frm.name != "JSON":
            # reference JsonType cast: the varchar becomes a JSON *string
            # value* (quoted/escaped), not a parsed document — parsing is
            # json_parse's job
            return _host_string_transform(
                v if not isinstance(v.data, str) else _lit_to_dict_colval(v),
                lambda s: _json_mod.dumps(str(s)), T.JSON)
        if frm.name == "JSON" and to.name != "JSON":
            # JSON string values unquote; other documents render compact
            def unwrap(s):
                try:
                    doc = _json_mod.loads(str(s))
                except ValueError:
                    return str(s)
                return doc if isinstance(doc, str) else \
                    _json_mod.dumps(doc, separators=(",", ":"))

            src = v if not isinstance(v.data, str) else _lit_to_dict_colval(v)
            return _host_string_transform(src, unwrap, T.VARCHAR)
        # VARCHAR <-> CHAR: same physical form, re-tag only
        return ColVal(v.data, v.valid, to, v.dictionary)
    _SKETCHES = ("HLL", "P4HLL", "QDIGEST", "TDIGEST")
    if frm.name in ("HLL", "P4HLL") and to.name in ("HLL", "P4HLL"):
        # dense-format re-tag (reference: HyperLogLog <-> P4HyperLogLog
        # casts; this engine's HLL blobs are always dense)
        return ColVal(v.data, v.valid, to, v.dictionary)
    if frm.name in _SKETCHES and to.name == "VARBINARY":
        # RAW serialized sketch bytes (reference: CAST(hll AS varbinary)
        # returns the airlift-serialized form verbatim)
        return ColVal(v.data, v.valid, T.VARBINARY, v.dictionary)
    if frm.name == "VARBINARY" and to.name in _SKETCHES:
        return ColVal(v.data, v.valid, to, v.dictionary)
    if frm.name in _SKETCHES and to.is_string:
        # export: serialized sketch -> base64 text
        import base64 as _b64

        vals = v.dictionary.values if v.dictionary is not None \
            else np.empty(0, dtype=object)
        obj = np.asarray([_b64.b64encode(b).decode("ascii") for b in vals]
                         or [""], dtype=object)
        codes = jnp.clip(v.data, 0, max(len(obj) - 1, 0))
        return normalize_dictionary(obj, ColVal(codes, v.valid, T.VARCHAR))
    if frm.is_string and to.name in _SKETCHES:
        import base64 as _b64
        import binascii

        if isinstance(v.data, str):
            v = _lit_to_dict_colval(v)
        vals = v.dictionary.values
        out = np.empty(max(len(vals), 1), dtype=object)
        out[:] = [b""] * len(out)
        bad = np.zeros(len(out), dtype=bool)
        for i, s in enumerate(vals):
            # per-entry: a malformed value NULLs that row, never the
            # query (unreferenced dictionary entries must not poison it)
            try:
                out[i] = _b64.b64decode(str(s), validate=True)
            except (binascii.Error, ValueError):
                bad[i] = True
        codes = jnp.clip(v.data, 0, len(out) - 1)
        valid = v.valid
        if bad.any():
            ok = ~jnp.asarray(bad)[codes]
            valid = ok if valid is None else (jnp.asarray(valid) & ok)
        return _tuple_dict_normalize(out, ColVal(codes, valid, to), to)
    if frm.name in ("ARRAY", "MAP", "ROW") and to.name == frm.name:
        if frm.name == "ROW" and len(frm.params) != len(to.params):
            raise ValueError(
                f"cannot cast {frm} to {to}: field count mismatch")
        if _container_same_elements(frm, to):
            # pure re-tag (field renaming); shared dictionary unchanged
            return ColVal(v.data, v.valid, to, v.dictionary)
        # element types differ: convert every dictionary entry host-side
        entries = v.dictionary.values if v.dictionary is not None \
            else np.empty(0, dtype=object)
        outs = np.empty(max(len(entries), 1), dtype=object)
        outs[:] = [()] * len(outs)
        for i, t in enumerate(entries):
            outs[i] = _py_cast_value(t, frm, to)
        codes = jnp.clip(v.data, 0, len(outs) - 1)
        return _tuple_dict_normalize(outs, ColVal(codes, v.valid, to), to)
    if frm.name == "UNKNOWN":  # CAST(NULL AS anything) == typed NULL
        if to.is_string:
            return ColVal("", False, to)
        if to.name == "ARRAY":
            d = np.empty(1, dtype=object)
            d[0] = ()
            return ColVal(jnp.asarray(0, jnp.int32), False, to, Dictionary(d))
        return ColVal(to.numpy_dtype().type(0), False, to)
    if to.is_string and not frm.is_string:
        return _cast_to_varchar(v)
    if frm.is_string and not to.is_string:
        if to.name == "DATE":
            return _emit_date_from_str([v])
        if to.name == "TIMESTAMP":
            def _ts_parse(s):
                t = str(s).strip()
                if " " in t and "T" not in t:
                    t = t.replace(" ", "T", 1)
                return int((np.datetime64(t)
                            - np.datetime64("1970-01-01T00:00:00"))
                           / np.timedelta64(1, "us"))

            from presto_tpu.functions.datetime_tz import _host_parse_lut

            return _host_parse_lut(v, _ts_parse, T.TIMESTAMP, safe)
        # parse numerics via dictionary LUT; None == parse failure (kept
        # distinct from a genuine float('NaN') parse)
        def parse_dec128(x):
            """Exact unscaled Int128 from a decimal string (reference:
            Decimals.parse for long decimals)."""
            import decimal as _d

            try:
                with _d.localcontext() as ctx:
                    ctx.prec = 80  # default 28 can't quantize 38 digits
                    d = _d.Decimal(x)
                    unscaled = int(d.scaleb(to.decimal_scale).quantize(
                        _d.Decimal(1), rounding=_d.ROUND_HALF_UP))
            except _d.InvalidOperation:
                if safe:
                    return None
                raise ValueError(f"cannot CAST '{x}' to {to}")
            if abs(unscaled) >= 10 ** to.decimal_precision:
                if safe:
                    return None
                raise ValueError(
                    f"DECIMAL overflow: '{x}' exceeds {to}")
            return unscaled

        def parse(x):
            try:
                f = float(x)
            except ValueError:
                if safe:
                    return None
                raise
            if to.is_decimal and \
                    abs(f) * (10 ** to.decimal_scale) \
                    >= T.DECIMAL_UNSCALED_LIMIT:
                # int64 unscaled storage limit (~19 digits): short
                # decimals reject; DECIMAL(p>18) takes the exact
                # two-limb path (parse_dec128)
                if safe:
                    return None
                raise ValueError(
                    f"DECIMAL overflow: '{x}' exceeds 19 significant digits")
            return f
        lit = _as_string_literal(v)
        if lit is not None:
            if to.is_decimal and to.is_long_decimal:
                unscaled = parse_dec128(lit)
                if unscaled is None:
                    return emit_cast(ColVal(False, False, T.UNKNOWN),
                                     to, safe)
                return ColVal(unscaled, v.valid, to)  # long scalar: py int
            val = parse(lit)
            if val is None:  # safe-parse failure -> typed NULL
                return emit_cast(ColVal(False, False, T.UNKNOWN), to, safe)
            if to.is_integer:
                if val != val:  # CAST('NaN' AS INTEGER) has no value
                    return emit_cast(ColVal(False, False, T.UNKNOWN),
                                     to, safe)
                return ColVal(int(val), v.valid, to)
            if to.is_decimal:  # scale to the unscaled int64 representation
                return _emit_cast_decimal(
                    ColVal(val, v.valid, T.DOUBLE), to, safe)
            return ColVal(val, v.valid, to)  # 'NaN' parses to a real NaN
        if to.is_decimal and to.is_long_decimal:
            from presto_tpu.exec import dec128 as D128

            bad_np = np.zeros(len(v.dictionary), dtype=bool)
            ints = []
            for i, x in enumerate(v.dictionary.values):
                r = parse_dec128(x)
                if r is None:
                    bad_np[i] = True
                    r = 0
                ints.append(r)
            lut = jnp.asarray(D128.from_host_ints(ints))
            data = lut[jnp.clip(v.data, 0, len(v.dictionary) - 1)]
            valid = v.valid
            if bad_np.any():
                bad = jnp.asarray(bad_np)[
                    jnp.clip(v.data, 0, len(v.dictionary) - 1)]
                valid = (~bad) if valid is None \
                    else (jnp.asarray(valid) & ~bad)
            return ColVal(data, valid, to)
        bad_np = np.zeros(len(v.dictionary), dtype=bool)
        lut_vals = []
        for i, x in enumerate(v.dictionary.values):
            r = parse(x)
            if r is None:  # failure marker, distinct from a genuine NaN
                bad_np[i] = True
                r = 0.0
            lut_vals.append(r)
        lut = jnp.asarray(np.asarray(lut_vals, dtype=np.float64))
        data = lut[jnp.clip(v.data, 0, len(v.dictionary) - 1)]
        valid = v.valid
        if bad_np.any():
            # rows referencing unparseable entries become NULL, not 0
            bad = jnp.asarray(bad_np)[
                jnp.clip(v.data, 0, len(v.dictionary) - 1)]
            valid = (~bad) if valid is None else (jnp.asarray(valid) & ~bad)
        return emit_cast(ColVal(data, valid, T.DOUBLE), to, safe)
    if frm.name == "DATE" and to.name == "TIMESTAMP":
        d = (jnp.asarray(v.data).astype(jnp.int64) if not v.is_scalar
             or hasattr(v.data, "shape") else int(v.data))
        return ColVal(d * 86_400_000_000, v.valid, T.TIMESTAMP)
    if frm.name == "TIMESTAMP" and to.name == "DATE":
        d = jnp.floor_divide(jnp.asarray(v.data).astype(jnp.int64),
                             86_400_000_000)
        return ColVal(d.astype(jnp.int32), v.valid, T.DATE)
    if to.is_decimal or frm.is_decimal:
        return _emit_cast_decimal(v, to, safe, guards=guards)
    if frm == T.UNKNOWN:
        # typed NULL
        return ColVal(jnp.zeros(jnp.asarray(v.data).shape, _np_dtype(to))
                      if hasattr(v.data, "shape") else _np_dtype(to).type(0).item(),
                      v.valid if v.valid is not None else False, to)
    data = v.data
    if not v.is_scalar or hasattr(data, "dtype"):
        # arrays AND device 0-d scalars (ir.Param bindings, distributed
        # ScalarSub values) stay on device: int()/float() would force a
        # host sync — and abort the trace under jit
        if to.is_integer and (frm.is_floating or frm.is_decimal):
            data = jnp.trunc(jnp.asarray(data)).astype(_np_dtype(to))
        else:
            data = jnp.asarray(data).astype(_np_dtype(to))
    else:
        if to.is_integer:
            data = int(data)
        elif to.is_floating:
            data = float(data)
        elif to.name == "BOOLEAN":
            data = bool(data)
    return ColVal(data, v.valid, to)


# ---- extended math (reference: presto-main operator/scalar/MathFunctions) --


def _math_double1(name, fn):
    return (lambda args: T.DOUBLE if args[0].is_numeric else None,
            lambda args: ColVal(fn(jnp.asarray(args[0].data).astype(jnp.float64)),
                                args[0].valid, T.DOUBLE))


for _n, _f in [("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
               ("asin", jnp.arcsin), ("acos", jnp.arccos),
               ("atan", jnp.arctan), ("sinh", jnp.sinh), ("cosh", jnp.cosh),
               ("tanh", jnp.tanh), ("cbrt", jnp.cbrt),
               ("degrees", jnp.degrees), ("radians", jnp.radians),
               ("log2", jnp.log2)]:
    register(_n)(_math_double1(_n, _f))

register("atan2")((
    lambda args: T.DOUBLE if len(args) == 2 else None,
    lambda args: ColVal(jnp.arctan2(jnp.asarray(args[0].data).astype(jnp.float64),
                                    jnp.asarray(args[1].data).astype(jnp.float64)),
                        all_valid(*args), T.DOUBLE)))
register("log")((
    lambda args: T.DOUBLE if len(args) == 2 else None,
    # Presto: log(base, value)
    lambda args: ColVal(jnp.log(jnp.asarray(args[1].data).astype(jnp.float64))
                        / jnp.log(jnp.asarray(args[0].data).astype(jnp.float64)),
                        all_valid(*args), T.DOUBLE)))
register("pi")((lambda args: T.DOUBLE if not args else None,
                lambda args: ColVal(float(np.pi), None, T.DOUBLE)))
register("e")((lambda args: T.DOUBLE if not args else None,
               lambda args: ColVal(float(np.e), None, T.DOUBLE)))


def _emit_truncate(args):
    a = args[0]
    d = int(args[1].data) if len(args) > 1 else 0
    x = jnp.asarray(a.data)
    if a.type.is_integer:
        return a
    if a.type.is_decimal:
        keep = max(a.type.decimal_scale - d, 0)
        s = 10 ** keep
        return ColVal(jnp.sign(x) * (jnp.abs(x) // s) * s, a.valid, a.type)
    scale = 10.0 ** d
    return ColVal(jnp.trunc(x * scale) / scale, a.valid, a.type)


register("truncate")((_resolve_round, _emit_truncate))


def _emit_width_bucket(args):
    x = jnp.asarray(args[0].data).astype(jnp.float64)
    lo, hi = args[1].data, args[2].data
    n = args[3].data
    raw = jnp.floor((x - lo) / (hi - lo) * n) + 1
    r = jnp.clip(raw, 0, jnp.asarray(n, jnp.float64) + 1)
    return ColVal(r.astype(jnp.int64), all_valid(*args), T.BIGINT)


register("width_bucket")((
    lambda args: T.BIGINT if len(args) == 4 else None, _emit_width_bucket))


# bitwise (reference: operator/scalar/BitwiseFunctions)
def _bitwise2(fn):
    return (lambda args: T.BIGINT if len(args) == 2
            and all(a.is_integer for a in args) else None,
            lambda args: ColVal(fn(jnp.asarray(args[0].data).astype(jnp.int64),
                                   jnp.asarray(args[1].data).astype(jnp.int64)),
                                all_valid(*args), T.BIGINT))


# HLL building blocks for distributed approx_distinct: per-row register
# index and rank (rho) from the shared value hash (kernels.hll_hash64) —
# the partial/final split rewrites approx_distinct into standard
# max/sum/count aggregates over these (plan/distribute.py; reference:
# ApproximateCountDistinctAggregation's partial HLL state merge).
HLL_M = 1024
HLL_LOG2M = 10


def _hll_col(cv):
    from presto_tpu.batch import Column as _Col
    from presto_tpu.exec import kernels as _K

    col = _Col(jnp.asarray(cv.data), cv.valid if cv.valid is not None
               and hasattr(cv.valid, "shape") else None, cv.type,
               cv.dictionary)
    return _K.hll_hash64(col)


register("$hll_reg")((
    lambda args: T.BIGINT if len(args) == 1 else None,
    lambda args: ColVal((_hll_col(args[0])
                         & jnp.uint64(HLL_M - 1)).astype(jnp.int64),
                        args[0].valid, T.BIGINT)))


def _hll_rho_emit(args):
    h = _hll_col(args[0])
    w = ((h >> jnp.uint64(HLL_LOG2M))
         & jnp.uint64(0xFFFFFFFF)).astype(jnp.float64)
    rho = jnp.where(w > 0,
                    32.0 - jnp.floor(jnp.log2(jnp.maximum(w, 1.0))), 33.0)
    return ColVal(rho, args[0].valid, T.DOUBLE)


register("$hll_rho")((
    lambda args: T.DOUBLE if len(args) == 1 else None, _hll_rho_emit))


register("bitwise_and")(_bitwise2(jnp.bitwise_and))
register("bitwise_or")(_bitwise2(jnp.bitwise_or))
register("bitwise_xor")(_bitwise2(jnp.bitwise_xor))
register("bitwise_left_shift")(_bitwise2(lambda x, y: x << y))
register("bitwise_right_shift")(_bitwise2(
    lambda x, y: (x.astype(jnp.uint64) >> y.astype(jnp.uint64)).astype(jnp.int64)))
register("bitwise_not")((
    lambda args: T.BIGINT if len(args) == 1 and args[0].is_integer else None,
    lambda args: ColVal(~jnp.asarray(args[0].data).astype(jnp.int64),
                        args[0].valid, T.BIGINT)))


# ---- extended strings (reference: operator/scalar/StringFunctions) ---------

def _pad(v, n, p, left):
    n = int(n)
    if n < 0:
        raise ValueError(f"pad target length must be >= 0 (got {n})")
    p = str(p) or " "
    if len(v) >= n:
        return v[:n]
    fill = (p * ((n - len(v)) // len(p) + 1))[:n - len(v)]
    return fill + v if left else v + fill


register("lpad")((_str_transform(
    "lpad", lambda v, n, p=" ": _pad(v, n, p, True))))
register("rpad")((_str_transform(
    "rpad", lambda v, n, p=" ": _pad(v, n, p, False))))
register("repeat")((_str_transform("repeat", lambda v, n: v * int(n))))


def _split_part(v, delim, idx):
    parts = v.split(str(delim))
    i = int(idx)
    return parts[i - 1] if 1 <= i <= len(parts) else ""


register("split_part")((_str_transform("split_part", _split_part)))
register("position")((_str_transform(
    "position", lambda v, sub: v.find(str(sub)) + 1, T.BIGINT)))
register("codepoint")((_str_transform(
    "codepoint", lambda v: ord(v[0]) if v else 0, T.BIGINT)))
register("contains_str")((_str_transform(
    "contains_str", lambda v, sub: str(sub) in v, T.BOOLEAN)))
register("ends_with")((_str_transform(
    "ends_with", lambda v, p: v.endswith(str(p)), T.BOOLEAN)))
register("chr")((
    lambda args: T.VARCHAR if args[0].is_integer else None,
    lambda args: ColVal(chr(int(args[0].data)), args[0].valid, T.VARCHAR)
    if args[0].is_scalar else (_ for _ in ()).throw(
        NotImplementedError("chr of non-constant")),
))


# regexes evaluate over the (small) dictionary on host — the mandatory
# dictionary-aware projection (reference: operator/scalar/JoniRegexp* via
# DictionaryAwarePageProjection)
import re as _re_mod


def _regexp_like(v, pattern):
    return _re_mod.search(str(pattern), v) is not None


def _regexp_extract(v, pattern, group=0):
    m = _re_mod.search(str(pattern), v)
    if m is None:
        return ""
    return m.group(int(group))


def _regexp_replace(v, pattern, repl=""):
    # Presto group references are $0..$9; everything else is literal —
    # escape backslashes first so they can't form Python re escapes
    py_repl = str(repl).replace("\\", "\\\\")
    py_repl = _re_mod.sub(r"\$(\d+)", r"\\g<\1>", py_repl)
    return _re_mod.sub(str(pattern), py_repl, v)


register("regexp_like")((_str_transform("regexp_like", _regexp_like, T.BOOLEAN)))
register("regexp_extract")((_str_transform("regexp_extract", _regexp_extract)))
register("regexp_replace")((_str_transform("regexp_replace", _regexp_replace)))


# ---- extended date/time (reference: operator/scalar/DateTimeFunctions) -----


def _emit_day_name_style(field):
    def emit(args):
        v = args[0]
        days = jnp.asarray(v.data)
        if v.type.name == "TIMESTAMP":
            days = jnp.floor_divide(days, 86_400_000_000).astype(jnp.int64)
        y, m, d = civil_from_days(days)
        if field == "day_of_week":   # ISO: Monday=1..Sunday=7
            r = (days + 3) % 7 + 1
        elif field == "day_of_year":
            r = days - days_from_civil(y, jnp.asarray(1), jnp.asarray(1)) + 1
        elif field == "week_of_year":
            # ISO-8601: the week containing this date's Thursday, numbered
            # within the Thursday's year (Presto week() semantics)
            thursday = days - (days + 3) % 7 + 3
            ty, _, _ = civil_from_days(thursday)
            r = (thursday
                 - days_from_civil(ty, jnp.asarray(1), jnp.asarray(1))) // 7 + 1
        elif field == "last_day_of_month":
            nm_y = jnp.where(m == 12, y + 1, y)
            nm_m = jnp.where(m == 12, 1, m + 1)
            r = days_from_civil(nm_y, nm_m, jnp.asarray(1)) - 1
            return ColVal(r.astype(jnp.int32), v.valid, T.DATE)
        else:
            raise AssertionError(field)
        return ColVal(r.astype(jnp.int64), v.valid, T.BIGINT)

    return emit


for _fld in ("day_of_week", "day_of_year", "week_of_year"):
    register(_fld)((lambda args: T.BIGINT if args[0].is_temporal else None,
                    _emit_day_name_style(_fld)))
register("dow")((REGISTRY["day_of_week"].resolve, REGISTRY["day_of_week"].emit))
register("doy")((REGISTRY["day_of_year"].resolve, REGISTRY["day_of_year"].emit))
register("week")((REGISTRY["week_of_year"].resolve, REGISTRY["week_of_year"].emit))
register("last_day_of_month")((
    lambda args: T.DATE if args[0].is_temporal else None,
    _emit_day_name_style("last_day_of_month")))


def _emit_date_trunc(args):
    unit = _as_string_literal(args[0])
    v = args[1]
    if unit is None:
        raise NotImplementedError("date_trunc with non-constant unit")
    unit = unit.lower()
    days = jnp.asarray(v.data)
    is_ts = v.type.name == "TIMESTAMP"
    us = days if is_ts else None
    if is_ts:
        days = jnp.floor_divide(days, 86_400_000_000).astype(jnp.int64)
    y, m, d = civil_from_days(days)
    if unit == "day":
        r = days
    elif unit == "week":  # ISO week starts Monday; 1970-01-01 is Thursday
        r = days - (days + 3) % 7
    elif unit == "month":
        r = days_from_civil(y, m, jnp.asarray(1))
    elif unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        r = days_from_civil(y, qm, jnp.asarray(1))
    elif unit == "year":
        r = days_from_civil(y, jnp.asarray(1), jnp.asarray(1))
    elif unit in ("hour", "minute", "second") and is_ts:
        step = {"hour": 3_600_000_000, "minute": 60_000_000,
                "second": 1_000_000}[unit]
        return ColVal(jnp.floor_divide(us, step) * step, v.valid, v.type)
    else:
        raise NotImplementedError(f"date_trunc({unit}, {v.type})")
    if is_ts:
        return ColVal(r.astype(jnp.int64) * 86_400_000_000, v.valid, v.type)
    return ColVal(r.astype(jnp.int32), v.valid, T.DATE)


register("date_trunc")((
    lambda args: args[1] if len(args) == 2 and args[1].is_temporal else None,
    _emit_date_trunc))


def _emit_date_diff(args):
    unit = _as_string_literal(args[0])
    if unit is None:
        raise NotImplementedError("date_diff with non-constant unit")
    unit = unit.lower()
    a, b = args[1], args[2]

    def to_days(v):
        x = jnp.asarray(v.data)
        if v.type.name == "TIMESTAMP":
            return jnp.floor_divide(x, 86_400_000_000).astype(jnp.int64)
        return x.astype(jnp.int64)

    da, db = to_days(a), to_days(b)
    if unit == "day":
        r = db - da
    elif unit == "week":
        r = (db - da) // 7
    elif unit in ("month", "quarter", "year"):
        ya, ma, dda = civil_from_days(da)
        yb, mb, ddb = civil_from_days(db)
        # COMPLETE periods elapsed (Presto/Joda): a partial trailing
        # month does not count, in either direction; the start day is
        # clamped to the end month's length (Jan 31 + 1 month = Feb 29)

        def days_in_month(y, m):
            ny = jnp.where(m == 12, y + 1, y)
            nm = jnp.where(m == 12, 1, m + 1)
            return (days_from_civil(ny, nm, jnp.asarray(1))
                    - days_from_civil(y, m, jnp.asarray(1)))

        months = (yb - ya) * 12 + (mb - ma)
        fwd_incomplete = ddb < jnp.minimum(dda, days_in_month(yb, mb))
        bwd_incomplete = dda < jnp.minimum(ddb, days_in_month(ya, ma))
        months = months - ((months > 0) & fwd_incomplete) \
                        + ((months < 0) & bwd_incomplete)
        trunc_div = lambda x, k: jnp.sign(x) * (jnp.abs(x) // k)
        r = {"month": months, "quarter": trunc_div(months, 3),
             "year": trunc_div(months, 12)}[unit]
    elif unit in ("hour", "minute", "second", "millisecond") and \
            a.type.name == "TIMESTAMP" and b.type.name == "TIMESTAMP":
        step = {"hour": 3_600_000_000, "minute": 60_000_000,
                "second": 1_000_000, "millisecond": 1_000}[unit]
        r = (jnp.asarray(b.data) - jnp.asarray(a.data)) // step
    else:
        raise NotImplementedError(f"date_diff({unit})")
    return ColVal(r.astype(jnp.int64), all_valid(a, b), T.BIGINT)


register("date_diff")((
    lambda args: T.BIGINT if len(args) == 3 else None, _emit_date_diff))

register("from_unixtime")((
    lambda args: T.TIMESTAMP if args[0].is_numeric else None,
    lambda args: ColVal((jnp.asarray(args[0].data).astype(jnp.float64)
                         * 1e6).astype(jnp.int64), args[0].valid, T.TIMESTAMP)))
register("to_unixtime")((
    lambda args: T.DOUBLE if args[0].name == "TIMESTAMP" else None,
    lambda args: ColVal(jnp.asarray(args[0].data).astype(jnp.float64) / 1e6,
                        args[0].valid, T.DOUBLE)))


# ---- JSON functions (reference: operator/scalar/JsonFunctions +
# JsonExtract; JSON values ride VARCHAR columns, path evaluation is a
# host dictionary transform like the other string functions) -----------

import json as _json_mod


def _json_path_get(v, path):
    """Evaluate the JsonPath subset $.a.b[0] (reference:
    JsonExtract.generateExtractor's supported grammar)."""
    try:
        doc = _json_mod.loads(v)
    except (ValueError, TypeError):
        return None
    p = str(path)
    if not p.startswith("$"):
        return None
    i = 1
    cur = doc
    while i < len(p) and cur is not None:
        if p[i] == ".":
            j = i + 1
            while j < len(p) and p[j] not in ".[":
                j += 1
            key = p[i + 1:j]
            cur = cur.get(key) if isinstance(cur, dict) else None
            i = j
        elif p[i] == "[":
            j = p.find("]", i)
            if j < 0:
                return None  # unclosed bracket: invalid path, not a crash
            token = p[i + 1:j].strip("\"'")
            if isinstance(cur, list):
                try:
                    cur = cur[int(token)]
                except (ValueError, IndexError):
                    cur = None
            elif isinstance(cur, dict):
                cur = cur.get(token)
            else:
                cur = None
            i = j + 1
        else:
            return None
    return cur


def _json_extract(v, path):
    r = _json_path_get(v, path)
    return "" if r is None else _json_mod.dumps(r, separators=(",", ":"))


def _json_extract_scalar(v, path):
    import math as _math

    r = _json_path_get(v, path)
    if r is None or isinstance(r, (dict, list)):
        return ""
    if isinstance(r, bool):
        return "true" if r else "false"
    if isinstance(r, float) and _math.isfinite(r) and r == int(r):
        return str(int(r))
    return str(r)


def _json_array_length(v):
    try:
        doc = _json_mod.loads(v)
    except (ValueError, TypeError):
        return 0
    return len(doc) if isinstance(doc, list) else 0


def _json_size(v, path):
    r = _json_path_get(v, path)
    if isinstance(r, (dict, list)):
        return len(r)
    return 0


register("json_extract")((_str_transform("json_extract", _json_extract)))
register("json_extract_scalar")((_str_transform("json_extract_scalar",
                                                _json_extract_scalar)))
register("json_format")((_str_transform(
    "json_format", lambda v: _json_mod.dumps(_json_mod.loads(v),
                                             separators=(",", ":")))))
# json_parse returns the distinct JSON type in canonical form; invalid
# input raises (reference: JsonFunctions.jsonParse over JsonType)
register("json_parse")((_str_transform(
    "json_parse",
    lambda v: _json_mod.dumps(_json_mod.loads(v), separators=(",", ":")),
    T.JSON)))
register("json_array_length")((_str_transform(
    "json_array_length", _json_array_length, T.BIGINT)))
register("json_size")((_str_transform("json_size", _json_size, T.BIGINT)))
def _is_json_scalar(v):
    try:
        doc = _json_mod.loads(v)
    except (ValueError, TypeError):
        return False
    return not isinstance(doc, (dict, list))  # JSON null IS a scalar


register("is_json_scalar")((_str_transform(
    "is_json_scalar", _is_json_scalar, T.BOOLEAN)))


# ---- ARRAY functions (reference: operator/scalar/ArrayFunctions etc.) -----
#
# Arrays extend the dictionary-always policy to nested values: a column
# of arrays is int32 codes into a sorted dictionary of element TUPLES
# (the reference's ArrayBlock offsets would be ragged — hostile to the
# static-shape model).  Array functions are host dictionary transforms,
# exactly like the string functions above.


def _is_array(t: T.Type) -> bool:
    return t.name == "ARRAY"


def _elem_type(t: T.Type) -> T.Type:
    return t.params[0] if t.params else T.UNKNOWN


def _tuple_cmp(a, b) -> int:
    """Total order over dictionary tuples: elementwise-lexicographic
    with prefix ordering (python tuple semantics), NULL elements last,
    nested tuples recursive, incomparable types by repr.  Code order ==
    semantic order makes ORDER BY / min / max / </<= over ARRAY and ROW
    columns correct straight from the codes (reference:
    ArrayLessThanOperator ordering)."""
    for x, y in zip(a, b):
        if x is None and y is None:
            continue
        if x is None:
            return 1
        if y is None:
            return -1
        if isinstance(x, tuple) and isinstance(y, tuple):
            c = _tuple_cmp(x, y)
            if c:
                return c
            continue
        try:
            if x < y:
                return -1
            if y < x:
                return 1
        except TypeError:  # heterogenous slots: deterministic fallback
            rx, ry = repr(x), repr(y)
            if rx != ry:
                return -1 if rx < ry else 1
    return (len(a) > len(b)) - (len(a) < len(b))


def _tuple_dict_normalize(values: np.ndarray, codes: ColVal,
                          out_type: T.Type) -> ColVal:
    """normalize_dictionary for tuple dictionaries, canonical order =
    SEMANTIC order (see _tuple_cmp)."""
    import functools as _ft

    # repr pre-sort makes cmp-equal-but-distinct entries (1 vs 1.0)
    # deterministic across processes (string hashes are randomized)
    uniq = sorted(sorted(set(values.tolist()), key=repr),
                  key=_ft.cmp_to_key(_tuple_cmp))
    code_map = {v: i for i, v in enumerate(uniq)}
    inverse = np.fromiter((code_map[v] for v in values.tolist()),
                          np.int32, len(values))
    lut = jnp.asarray(inverse)
    new_codes = lut[jnp.clip(codes.data, 0, len(values) - 1)]
    u = np.empty(len(uniq), dtype=object)
    u[:] = uniq
    return ColVal(new_codes, codes.valid, out_type, Dictionary(u))


def _array_transform(name, fn, out_type=None):
    """out_type: None -> same ARRAY type (fn returns tuples);
    a T.Type -> fixed scalar type; 'elem' -> the element type."""

    def resolve(args):
        if not _is_array(args[0]):
            return None
        if out_type is None:
            return args[0]
        if out_type == "elem":
            return _elem_type(args[0])
        return out_type

    def emit(args):
        col = args[0]
        extra = []
        for a in args[1:]:
            if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
                raise NotImplementedError(f"{name} with non-constant arguments")
            v = a.data
            if a.dictionary is not None:  # constant string / array argument:
                v = a.dictionary.values[int(v)]  # pass the value, not the code
            elif hasattr(v, "item"):
                v = v.item()
            extra.append(v)
        rt = resolve([a.type for a in args])
        vals = col.dictionary.values if col.dictionary is not None \
            else np.empty(0, object)
        # per-entry errors become NULL for that entry (Presto returns
        # NULL for e.g. out-of-range element_at) instead of poisoning
        # the whole column because one dictionary value is unusual
        outs = np.empty(len(vals), dtype=object)
        null = np.zeros(len(vals), dtype=bool)
        for i, v in enumerate(vals):
            try:
                r = fn(tuple(v), *extra)
            except (ValueError, IndexError, TypeError):
                r = None
            if r is None:
                null[i] = True
                r = _NULL_PLACEHOLDER.get(
                    rt.name if rt is not None else "", 0)
            outs[i] = r
        def and_null(base):
            if not null.any():
                return base
            bad = jnp.asarray(null)[jnp.clip(col.data, 0,
                                             max(len(vals) - 1, 0))]
            return (~bad) if base is None else (base & ~bad)
        if rt is not None and rt.name == "ARRAY":
            r = _tuple_dict_normalize(outs, ColVal(col.data, col.valid,
                                                   rt), rt)
            return ColVal(r.data, and_null(r.valid), rt, r.dictionary)
        if rt is not None and rt.is_string:
            r = normalize_dictionary(
                outs, ColVal(col.data, col.valid, T.VARCHAR))
            return ColVal(r.data, and_null(r.valid), T.VARCHAR, r.dictionary)
        lut = jnp.asarray(np.asarray(outs.tolist(),
                                     dtype=rt.numpy_dtype()))
        data = lut[jnp.clip(col.data, 0, max(len(vals) - 1, 0))]
        return ColVal(data, and_null(col.valid), rt)

    return resolve, emit


_NULL_PLACEHOLDER = {"ARRAY": (), "VARCHAR": "", "BOOLEAN": False,
                     "BIGINT": 0, "INTEGER": 0, "DOUBLE": 0.0}


def _resolve_array_ctor(args):
    if not args:
        return T.array_of(T.UNKNOWN)
    ct = args[0]
    for a in args[1:]:
        nxt = T.common_super_type(ct, a)
        if nxt is None:
            return None
        ct = nxt
    return T.array_of(ct)


def _scalar_is_null(a: ColVal) -> bool:
    """NULL-ness of a scalar ColVal: covers python bools AND 0-dim
    device/numpy bools (computed NULLs like element_at misses)."""
    v = a.valid
    if v is None:
        return False
    if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0:
        return False  # vector validity — not a scalar context
    return not bool(v)


def _emit_array_ctor(args):
    vals = []
    for a in args:
        if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
            raise NotImplementedError(
                "ARRAY[...] over column values is not supported yet")
        if _scalar_is_null(a):
            vals.append(None)  # NULL element, not its physical placeholder
            continue
        v = a.data
        if isinstance(v, (jnp.ndarray, np.generic)):
            v = v.item() if hasattr(v, "item") else v
        if a.dictionary is not None:  # string / nested-array element:
            v = a.dictionary.values[int(v)]  # decode the dictionary code
        vals.append(v)
    t = _resolve_array_ctor([a.type for a in args])
    d = np.empty(1, dtype=object)
    d[0] = tuple(vals)
    return ColVal(jnp.asarray(0, jnp.int32), None, t, Dictionary(d))


register("array_constructor")((_resolve_array_ctor, _emit_array_ctor))
# cardinality / element_at registered below with MAP-aware dispatch


def _element_at(v, i):
    i = int(i)
    if i == 0:
        raise ValueError("SQL array indices are 1-based")
    if abs(i) > len(v):
        return None  # Presto: NULL beyond the array bounds
    return v[i - 1] if i > 0 else v[i]
register("contains")((_array_transform(
    "contains", lambda v, x: any(e == x for e in v), T.BOOLEAN)))
register("array_min")((_array_transform(
    "array_min", lambda v: min((e for e in v if e is not None),
                               default=None), "elem")))
register("array_max")((_array_transform(
    "array_max", lambda v: max((e for e in v if e is not None),
                               default=None), "elem")))
register("array_position")((_array_transform(
    "array_position",
    lambda v, x: next((i + 1 for i, e in enumerate(v) if e == x), 0),
    T.BIGINT)))
register("array_distinct")((_array_transform(
    "array_distinct", lambda v: tuple(dict.fromkeys(v)))))
register("array_sort")((_array_transform(
    "array_sort",  # NULLs last (reference: ArraySortFunction)
    lambda v: tuple(sorted(e for e in v if e is not None))
    + tuple(e for e in v if e is None))))
register("array_join")((
    lambda args: T.VARCHAR if _is_array(args[0]) else None,
    _array_transform("array_join",
                     lambda v, d: str(d).join(str(e) for e in v),
                     T.VARCHAR)[1]))
register("slice")((_array_transform(
    "slice", lambda v, start, length: v[int(start) - 1:
                                        int(start) - 1 + int(length)])))
register("flatten")((_array_transform(
    "flatten", lambda v: tuple(e for sub in v
                               for e in (sub if sub is not None else ())),
    "elem")))
register("array_remove")((_array_transform(
    "array_remove", lambda v, x: tuple(e for e in v
                                       if e is None or e != x))))
register("array_union")((_array_transform(
    "array_union", lambda v, w: tuple(dict.fromkeys(tuple(v) + tuple(w))))))
register("array_intersect")((_array_transform(
    "array_intersect",
    lambda v, w: tuple(dict.fromkeys(e for e in v if e in set(w))))))
register("array_except")((_array_transform(
    "array_except",
    lambda v, w: tuple(dict.fromkeys(e for e in v if e not in set(w))))))
register("arrays_overlap")((_array_transform(
    "arrays_overlap",
    lambda v, w: any(e in set(w) for e in v if e is not None),
    T.BOOLEAN)))


def _resolve_sequence(args):
    if len(args) in (2, 3) and all(a.is_integer for a in args):
        return T.array_of(T.BIGINT)
    return None


def _emit_sequence(args):
    vals = []
    for a in args:
        if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
            raise NotImplementedError("sequence over column bounds")
        vals.append(int(a.data))
    start, stop = vals[0], vals[1]
    step = vals[2] if len(vals) > 2 else (1 if stop >= start else -1)
    if step == 0:
        raise ValueError("sequence step cannot be zero")
    if (stop - start) * step < 0:
        raise ValueError(
            "sequence stop value should be " +
            ("greater than or equal to" if step > 0 else "less than or equal to")
            + " start value" + (" if step is greater than zero"
                                if step > 0 else " if step is less than zero"))
    n = max(0, (stop - start) // step + 1)
    if n > 10_000_000:
        raise ValueError("sequence result is too large")
    d = np.empty(1, dtype=object)
    d[0] = tuple(range(start, start + n * step, step))
    return ColVal(jnp.asarray(0, jnp.int32), all_valid(*args),
                  T.array_of(T.BIGINT), Dictionary(d))


register("sequence")((_resolve_sequence, _emit_sequence))


def _resolve_split(args):
    if len(args) in (2, 3) and args[0].is_string and args[1].is_string:
        return T.array_of(T.VARCHAR)
    return None


def _emit_split(args):
    col = args[0]
    delim = _as_string_literal(args[1])
    if delim is None:
        raise NotImplementedError("split with a non-constant delimiter")
    limit = None
    if len(args) > 2:
        limit = int(args[2].data)
    if isinstance(col.data, str):
        col = _lit_to_dict_colval(col)
    rt = T.array_of(T.VARCHAR)
    vals = col.dictionary.values
    outs = np.empty(max(len(vals), 1), dtype=object)
    outs[:] = [()] * len(outs)
    for i, v in enumerate(vals):
        outs[i] = tuple(str(v).split(delim) if limit is None
                        else str(v).split(delim, limit - 1))
    return _tuple_dict_normalize(
        outs, ColVal(jnp.clip(col.data, 0, len(outs) - 1), col.valid, rt), rt)


register("split")((_resolve_split, _emit_split))


# ---- higher-order (lambda) functions --------------------------------
# Reference: operator/scalar/ArrayTransformFunction.java, ArrayFilterFunction,
# ArrayAnyMatchFunction / AllMatch / NoneMatch, ArrayReduceFunction,
# ZipWithFunction.  The lambda body is traced over the *flattened dictionary
# elements* (colval.LambdaVal.apply), so the work is per distinct array
# value, vectorized on device — not per row.  Captures of enclosing row
# columns would break that factoring and are rejected.


def _is_function(t) -> bool:
    return t is not None and getattr(t, "name", None) == "FUNCTION"


def _fn_ret(t: T.Type) -> T.Type:
    return t.params[0]


def _check_lambda(lam, name):
    from presto_tpu.exec.colval import LambdaVal

    if not isinstance(lam, LambdaVal):
        raise NotImplementedError(f"{name} expects a lambda argument")
    if lam.free_refs():
        raise NotImplementedError(
            f"{name}: lambda captures of enclosing columns are not supported")


def _colval_from_pylist(vals, t: T.Type) -> ColVal:
    """Vector ColVal from host scalars (None == NULL)."""
    n = len(vals)
    valid = np.asarray([v is not None for v in vals], dtype=bool)
    v_arg = None if valid.all() else jnp.asarray(valid)
    if t.name in ("ARRAY", "MAP", "ROW"):
        obj = np.empty(n, dtype=object)
        for i, v in enumerate(vals):
            obj[i] = tuple(v) if v is not None else ()
        return _tuple_dict_normalize(
            obj, ColVal(jnp.arange(n, dtype=jnp.int32), v_arg, t), t)
    if t.is_string:
        obj = np.asarray(["" if v is None else str(v) for v in vals],
                         dtype=object)
        return normalize_dictionary(
            obj, ColVal(jnp.arange(n, dtype=jnp.int32), v_arg, T.VARCHAR))
    if t.name == "UNKNOWN":
        return ColVal(jnp.zeros((n,), jnp.int32), jnp.zeros((n,), bool), t)
    data = np.asarray([(0 if v is None else v) for v in vals],
                      dtype=t.numpy_dtype())
    return ColVal(jnp.asarray(data), v_arg, t)


def _pylist_from_colval(cv: ColVal, n: int) -> list:
    """Host decode of a (concrete) ColVal to python scalars, None == NULL."""
    data = cv.data
    if not hasattr(data, "shape") or getattr(data, "ndim", 0) == 0:
        codes = np.full(n, np.asarray(data))
    else:
        codes = np.asarray(data)
    if cv.dictionary is not None:
        dvals = cv.dictionary.values
        if len(dvals) == 0:
            out = [None] * n
        else:
            out = [dvals[int(c)] for c in np.clip(codes, 0, len(dvals) - 1)]
        # numpy string scalars must not leak into dictionary tuples: their
        # repr differs from python str, breaking canonical entry ordering
        out = [str(v) if isinstance(v, np.str_) else v for v in out]
    else:
        out = codes.tolist()
    if cv.valid is None:
        return out
    valid = cv.valid
    if not hasattr(valid, "shape") or getattr(valid, "ndim", 0) == 0:
        valid = np.full(n, bool(valid))
    else:
        valid = np.asarray(valid)
    return [v if ok else None for v, ok in zip(out, valid)]


def _arr_entries(col: ColVal) -> np.ndarray:
    return col.dictionary.values if col.dictionary is not None \
        else np.empty(0, dtype=object)


def _flat_apply(lam, entries):
    """Evaluate a 1-param lambda over every element of every entry; returns
    (per-entry lengths, flat result list)."""
    lens = [len(t) for t in entries]
    flat = [e for t in entries for e in t]
    if not flat:
        return lens, []
    elem = _colval_from_pylist(flat, lam.param_types[0])
    res = lam.apply({lam.params[0]: elem})
    return lens, _pylist_from_colval(res, len(flat))


def _dict_lut_result(vals: list, col: ColVal, rt: T.Type) -> ColVal:
    """Per-dictionary-entry host results -> ColVal via device LUT gather."""
    if len(vals) == 0:
        vals = [None]
    ne = len(vals)
    null = np.asarray([v is None for v in vals], dtype=bool)
    codes = jnp.clip(col.data, 0, ne - 1)
    bad = jnp.asarray(null)[codes]
    if col.valid is None:
        valid = ~bad
    else:
        valid = jnp.asarray(col.valid) & ~bad
    if rt.name in ("ARRAY", "MAP", "ROW"):
        obj = np.empty(ne, dtype=object)
        for i, v in enumerate(vals):
            obj[i] = tuple(v) if v is not None else ()
        return _tuple_dict_normalize(obj, ColVal(codes, valid, rt), rt)
    if rt.name in ("HLL", "P4HLL", "QDIGEST", "TDIGEST"):
        # serialized-sketch results: dictionary over the byte blobs
        obj = np.empty(ne, dtype=object)
        for i, v in enumerate(vals):
            obj[i] = v if v is not None else b""
        return ColVal(codes, valid, rt, Dictionary(obj))
    if rt.is_string:
        obj = np.asarray(["" if v is None else str(v) for v in vals],
                         dtype=object)
        return normalize_dictionary(obj, ColVal(codes, valid, T.VARCHAR))
    lut = jnp.asarray(np.asarray([0 if v is None else v for v in vals],
                                 dtype=rt.numpy_dtype()))
    return ColVal(lut[codes], valid, rt)


def _emit_transform(args):
    col, lam = args
    _check_lambda(lam, "transform")
    entries = _arr_entries(col)
    rt = T.array_of(lam.ret_type)
    lens, res_vals = _flat_apply(lam, entries)
    outs = np.empty(max(len(entries), 1), dtype=object)
    outs[:] = [()] * len(outs)
    off = 0
    for i, L in enumerate(lens):
        outs[i] = tuple(res_vals[off:off + L])
        off += L
    return _tuple_dict_normalize(
        outs, ColVal(jnp.clip(col.data, 0, len(outs) - 1), col.valid, rt), rt)


def _emit_filter(args):
    col, lam = args
    _check_lambda(lam, "filter")
    entries = _arr_entries(col)
    lens, res_vals = _flat_apply(lam, entries)
    outs = np.empty(max(len(entries), 1), dtype=object)
    outs[:] = [()] * len(outs)
    off = 0
    for i, L in enumerate(lens):
        outs[i] = tuple(e for e, k in zip(entries[i], res_vals[off:off + L])
                        if k is not None and bool(k))
        off += L
    return _tuple_dict_normalize(
        outs, ColVal(jnp.clip(col.data, 0, len(outs) - 1), col.valid,
                     col.type), col.type)


def _emit_match(name):
    def emit(args):
        col, lam = args
        _check_lambda(lam, name)
        entries = _arr_entries(col)
        lens, res_vals = _flat_apply(lam, entries)
        vals = []
        off = 0
        for L in lens:
            window = res_vals[off:off + L]
            off += L
            any_true = any(v is not None and bool(v) for v in window)
            any_false = any(v is not None and not bool(v) for v in window)
            has_null = any(v is None for v in window)
            if name == "any_match":
                r = True if any_true else (None if has_null else False)
            elif name == "all_match":
                r = False if any_false else (None if has_null else True)
            else:  # none_match
                r = False if any_true else (None if has_null else True)
            vals.append(r)
        return _dict_lut_result(vals, col, T.BOOLEAN)

    return emit


def _emit_reduce(args):
    arr, init, merge, out = args
    _check_lambda(merge, "reduce")
    _check_lambda(out, "reduce")
    if hasattr(init.data, "shape") and getattr(init.data, "ndim", 0) > 0:
        raise NotImplementedError("reduce with a non-constant initial state")
    entries = _arr_entries(arr)
    ne = len(entries)
    init_null = init.valid is not None and not hasattr(init.valid, "shape") \
        and not bool(init.valid)
    iv = None if init_null else (
        init.data.item() if hasattr(init.data, "item") else init.data)
    states = [iv] * ne
    maxlen = max((len(t) for t in entries), default=0)
    # step-synchronous evaluation: one vectorized merge over all entries
    # that still have an element at this position (lax.scan analog, but the
    # per-entry work happens on dictionary values, host-driven)
    for step in range(maxlen):
        idxs = [i for i in range(ne) if len(entries[i]) > step]
        sc = _colval_from_pylist([states[i] for i in idxs],
                                 merge.param_types[0])
        ec = _colval_from_pylist([entries[i][step] for i in idxs],
                                 merge.param_types[1])
        res = _pylist_from_colval(
            merge.apply({merge.params[0]: sc, merge.params[1]: ec}),
            len(idxs))
        for j, i in enumerate(idxs):
            states[i] = res[j]
    if ne:
        fc = _colval_from_pylist(states, out.param_types[0])
        finals = _pylist_from_colval(out.apply({out.params[0]: fc}), ne)
    else:
        finals = []
    return _dict_lut_result(finals, arr, out.ret_type)


def _emit_zip_with(args):
    a, b, lam = args
    _check_lambda(lam, "zip_with")
    # needs concrete codes to pair row-wise (falls back under tracing)
    ca, cb = np.asarray(a.data), np.asarray(b.data)
    av, bv = _arr_entries(a), _arr_entries(b)
    scalar = ca.ndim == 0 and cb.ndim == 0
    ca1, cb1 = np.atleast_1d(ca), np.atleast_1d(cb)
    n = max(len(ca1), len(cb1))
    if len(ca1) == 1:
        ca1 = np.repeat(ca1, n)
    if len(cb1) == 1:
        cb1 = np.repeat(cb1, n)
    pairs = np.stack([np.clip(ca1, 0, max(len(av) - 1, 0)),
                      np.clip(cb1, 0, max(len(bv) - 1, 0))], axis=1)
    uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
    e1t, e2t = lam.param_types
    flat1, flat2, lens = [], [], []
    for i, j in uniq:
        t1 = av[i] if len(av) else ()
        t2 = bv[j] if len(bv) else ()
        L = max(len(t1), len(t2))  # Presto zip_with pads the shorter w/ NULL
        lens.append(L)
        flat1.extend(list(t1) + [None] * (L - len(t1)))
        flat2.extend(list(t2) + [None] * (L - len(t2)))
    if flat1:
        r = lam.apply({lam.params[0]: _colval_from_pylist(flat1, e1t),
                       lam.params[1]: _colval_from_pylist(flat2, e2t)})
        res_vals = _pylist_from_colval(r, len(flat1))
    else:
        res_vals = []
    outs = np.empty(max(len(uniq), 1), dtype=object)
    outs[:] = [()] * len(outs)
    off = 0
    for k, L in enumerate(lens):
        outs[k] = tuple(res_vals[off:off + L])
        off += L
    rt = T.array_of(lam.ret_type)
    codes = jnp.asarray(inv.astype(np.int32))
    if scalar:
        codes = codes[0]
    return _tuple_dict_normalize(outs, ColVal(codes, all_valid(a, b), rt), rt)


register("transform")((
    lambda args: T.array_of(_fn_ret(args[1])) if len(args) == 2
    and _is_array(args[0]) and _is_function(args[1]) else None,
    _emit_transform))
register("filter")((
    lambda args: args[0] if len(args) == 2 and _is_array(args[0])
    and _is_function(args[1]) else None,
    _emit_filter))
for _m in ("any_match", "all_match", "none_match"):
    register(_m)((
        lambda args: T.BOOLEAN if len(args) == 2 and _is_array(args[0])
        and _is_function(args[1]) else None,
        _emit_match(_m)))
register("zip_with")((
    lambda args: T.array_of(_fn_ret(args[2])) if len(args) == 3
    and _is_array(args[0]) and _is_array(args[1])
    and _is_function(args[2]) else None,
    _emit_zip_with))
register("reduce")((
    lambda args: _fn_ret(args[3]) if len(args) == 4 and _is_array(args[0])
    and _is_function(args[2]) and _is_function(args[3]) else None,
    _emit_reduce))


# ---- MAP / ROW types -------------------------------------------------
# Reference: spi/type/MapType + RowType, spi/block/MapBlock + RowBlock,
# operator/scalar/MapFunctions + MapTransformValuesFunction etc.
# Physical form mirrors ARRAY: int32 codes into a dictionary whose entries
# are key-sorted tuples of (key, value) pairs (MAP) or field tuples (ROW).


def _is_map(t: T.Type) -> bool:
    return t.name == "MAP"


def _map_sort(pairs) -> tuple:
    return tuple(sorted(pairs, key=lambda p: repr(p[0])))


def _map_build(keys, values) -> tuple:
    keys = list(keys)
    if any(k is None for k in keys):
        raise ValueError("map key cannot be null")
    if len(set(keys)) != len(keys):
        raise ValueError("duplicate map keys are not allowed")
    return _map_sort(zip(keys, values))


def _pair_codes(args):
    """Row-wise pairing of N dictionary-coded columns; returns
    (uniq combos [k,N], inverse codes, scalar?).  NULL rows get code -1
    so their (meaningless) stale codes never pair — the combined combo is
    recognizably invalid instead of crashing entry construction.
    Concrete codes only (compiled mode falls back)."""
    codes_list = []
    for a in args:
        c = np.asarray(a.data)
        if a.valid is not None and hasattr(a.valid, "shape") \
                and getattr(a.valid, "ndim", 0) > 0:
            c = np.where(np.asarray(a.valid), np.atleast_1d(c), -1)
        codes_list.append(c)
    scalar = all(c.ndim == 0 for c in codes_list)
    n = max((len(c) for c in codes_list if c.ndim > 0), default=1)
    cols = [np.broadcast_to(np.atleast_1d(c), (n,)) for c in codes_list]
    uniq, inv = np.unique(np.stack(cols, axis=1), axis=0, return_inverse=True)
    return uniq, inv, scalar, n


def _resolve_map_ctor(args):
    if len(args) == 0:
        return T.map_of(T.UNKNOWN, T.UNKNOWN)
    if len(args) == 2 and all(a.name == "ARRAY" for a in args):
        return T.map_of(args[0].params[0], args[1].params[0])
    return None


def _emit_map_ctor(args):
    rt = _resolve_map_ctor([a.type for a in args])
    if not args:
        d = np.empty(1, dtype=object)
        d[0] = ()
        return ColVal(jnp.asarray(0, jnp.int32), None, rt, Dictionary(d))
    ka, va = args
    uniq, inv, scalar, _ = _pair_codes(args)
    kd, vd = _arr_entries(ka), _arr_entries(va)
    outs = np.empty(len(uniq), dtype=object)
    for i, (ck, cv) in enumerate(uniq):
        if int(ck) < 0 or int(cv) < 0:  # NULL row — result NULL via valid
            outs[i] = ()
            continue
        keys = kd[int(ck)] if int(ck) < len(kd) else ()
        vals = vd[int(cv)] if int(cv) < len(vd) else ()
        if len(keys) != len(vals):
            raise ValueError("map key and value arrays must match in length")
        outs[i] = _map_build(keys, vals)
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(
        outs, ColVal(codes, all_valid(*args), rt), rt)


register("map")((_resolve_map_ctor, _emit_map_ctor))


def _map_value_fn(name, fn, rt_fn):
    """Per-dictionary-entry map transform; extras decoded like
    _array_transform."""

    def resolve(args):
        return rt_fn(args) if args and _is_map(args[0]) else None

    def emit(args):
        col = args[0]
        extra = []
        for a in args[1:]:
            if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
                raise NotImplementedError(f"{name} with non-constant arguments")
            v = a.data
            if a.dictionary is not None:
                v = a.dictionary.values[int(v)]
            elif hasattr(v, "item"):
                v = v.item()
            extra.append(v)
        rt = rt_fn([a.type for a in args])
        entries = _arr_entries(col)
        vals = []
        for t in entries:
            try:
                vals.append(fn(t, *extra))
            except (ValueError, IndexError, TypeError, KeyError):
                vals.append(None)
        return _dict_lut_result(vals, col, rt)

    return resolve, emit


register("map_keys")((_map_value_fn(
    "map_keys", lambda t: tuple(k for k, _ in t),
    lambda a: T.array_of(a[0].params[0]))))
register("map_values")((_map_value_fn(
    "map_values", lambda t: tuple(v for _, v in t),
    lambda a: T.array_of(a[0].params[1]))))
register("map_entries")((_map_value_fn(
    "map_entries", lambda t: tuple(tuple(p) for p in t),
    lambda a: T.array_of(T.row_of([(None, a[0].params[0]),
                                   (None, a[0].params[1])])))))


def _map_lookup(t, key):
    for k, v in t:
        if k == key:
            return v
    return None


def _resolve_element_at(args):
    if not args:
        return None
    if _is_array(args[0]):
        return _elem_type(args[0])
    if _is_map(args[0]):
        return args[0].params[1]
    return None


def _emit_element_at(args):
    if _is_map(args[0].type):
        return _map_value_fn("element_at", _map_lookup,
                             lambda a: a[0].params[1])[1](args)
    return _array_transform("element_at", _element_at, "elem")[1](args)


register("element_at")((_resolve_element_at, _emit_element_at))


def _emit_subscript(args):
    # a[i] / m[k] — lenient NULL-on-missing semantics (element_at;
    # the reference's subscript operator raises on out-of-bounds)
    return _emit_element_at(args)


register("subscript")((_resolve_element_at, _emit_subscript))

def _emit_cardinality(args):
    col = args[0]
    return _dict_lut_result([len(t) for t in _arr_entries(col)],
                            col, T.BIGINT)


register("cardinality")((
    lambda args: T.BIGINT if args and args[0].name in ("ARRAY", "MAP")
    else None,
    _emit_cardinality))


def _resolve_map_concat(args):
    if args and all(_is_map(a) for a in args):
        kt, vt = args[0].params
        for a in args[1:]:
            kt = T.common_super_type(kt, a.params[0]) or kt
            vt = T.common_super_type(vt, a.params[1]) or vt
        return T.map_of(kt, vt)
    return None


def _emit_map_concat(args):
    rt = _resolve_map_concat([a.type for a in args])
    uniq, inv, scalar, _ = _pair_codes(args)
    dicts = [_arr_entries(a) for a in args]
    outs = np.empty(len(uniq), dtype=object)
    for i, combo in enumerate(uniq):
        merged = {}
        for dv, code in zip(dicts, combo):
            if 0 <= int(code) < len(dv):
                merged.update(dict(dv[int(code)]))  # later maps win
        outs[i] = _map_sort(merged.items())
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(
        outs, ColVal(codes, all_valid(*args), rt), rt)


register("map_concat")((_resolve_map_concat, _emit_map_concat))

def _resolve_map_from_entries(args):
    if args and _is_array(args[0]) and args[0].params[0].name == "ROW" \
            and len(args[0].params[0].params) == 2:
        return T.map_of(args[0].params[0].params[0][1],
                        args[0].params[0].params[1][1])
    return None


def _emit_map_from_entries(args):
    col = args[0]
    rt = _resolve_map_from_entries([a.type for a in args])
    vals = []
    for t in _arr_entries(col):
        try:
            vals.append(_map_build([p[0] for p in t], [p[1] for p in t]))
        except (ValueError, IndexError, TypeError):
            vals.append(None)
    return _dict_lut_result(vals, col, rt)


register("map_from_entries")((_resolve_map_from_entries,
                              _emit_map_from_entries))


def _emit_map_hof(name):
    def emit(args):
        col, lam = args
        _check_lambda(lam, name)
        entries = _arr_entries(col)
        lens = [len(t) for t in entries]
        ks = [k for t in entries for k, _ in t]
        vs = [v for t in entries for _, v in t]
        if ks:
            kc = _colval_from_pylist(ks, lam.param_types[0])
            vc = _colval_from_pylist(vs, lam.param_types[1])
            res = _pylist_from_colval(
                lam.apply({lam.params[0]: kc, lam.params[1]: vc}), len(ks))
        else:
            res = []
        if name == "map_filter":
            rt = col.type
        elif name == "transform_values":
            rt = T.map_of(col.type.params[0], lam.ret_type)
        else:
            rt = T.map_of(lam.ret_type, col.type.params[1])
        outs = np.empty(max(len(entries), 1), dtype=object)
        outs[:] = [()] * len(outs)
        off = 0
        for i, L in enumerate(lens):
            window = res[off:off + L]
            off += L
            pairs = entries[i]
            if name == "map_filter":
                outs[i] = tuple(p for p, r in zip(pairs, window)
                                if r is not None and bool(r))
            elif name == "transform_values":
                outs[i] = tuple((k, r) for (k, _), r in zip(pairs, window))
            else:  # transform_keys
                newk = list(window)
                if any(k is None for k in newk):
                    raise ValueError("map key cannot be null")
                if len(set(newk)) != len(newk):
                    raise ValueError("duplicate map keys from transform_keys")
                outs[i] = _map_sort((r, v) for (_, v), r in zip(pairs, window))
        return _tuple_dict_normalize(
            outs, ColVal(jnp.clip(col.data, 0, len(outs) - 1),
                         col.valid, rt), rt)

    return emit


register("map_filter")((
    lambda args: args[0] if len(args) == 2 and _is_map(args[0])
    and _is_function(args[1]) else None,
    _emit_map_hof("map_filter")))
register("transform_values")((
    lambda args: T.map_of(args[0].params[0], _fn_ret(args[1]))
    if len(args) == 2 and _is_map(args[0]) and _is_function(args[1])
    else None,
    _emit_map_hof("transform_values")))
register("transform_keys")((
    lambda args: T.map_of(_fn_ret(args[1]), args[0].params[1])
    if len(args) == 2 and _is_map(args[0]) and _is_function(args[1])
    else None,
    _emit_map_hof("transform_keys")))


# ---- ROW -------------------------------------------------------------


def _resolve_row_ctor(args):
    return T.row_of([(None, a) for a in args])


def _emit_row_ctor(args):
    vals = []
    for a in args:
        if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
            raise NotImplementedError(
                "ROW(...) over column values is not supported yet")
        if _scalar_is_null(a):
            vals.append(None)
            continue
        v = a.data
        if isinstance(v, (jnp.ndarray, np.generic)):
            v = v.item() if hasattr(v, "item") else v
        if a.dictionary is not None:
            v = a.dictionary.values[int(v)]
        vals.append(v)
    t = _resolve_row_ctor([a.type for a in args])
    d = np.empty(1, dtype=object)
    d[0] = tuple(vals)
    return ColVal(jnp.asarray(0, jnp.int32), None, t, Dictionary(d))


register("row")((_resolve_row_ctor, _emit_row_ctor))


def _emit_row_field(args):
    col, idx_v = args
    idx = int(idx_v.data)
    ft = col.type.params[idx][1]
    entries = _arr_entries(col)
    vals = [t[idx] if idx < len(t) else None for t in entries]
    return _dict_lut_result(vals, col, ft)


register("row_field")((lambda args: None, _emit_row_field))


# ---- sketch functions (HLL / QDIGEST) --------------------------------
# Reference: operator/scalar/HyperLogLogFunctions.java (cardinality,
# empty_approx_set) and QuantileDigestFunctions.java; sketches are
# dictionary-encoded serialized byte strings (functions/sketches.py).


def _sketch_dict_fn(name, fn, rt_fn, type_names):
    def resolve(args):
        if args and args[0].name in type_names:
            return rt_fn(args)
        return None

    def emit(args):
        col = args[0]
        if col.dictionary is None and isinstance(col.data,
                                                 (bytes, bytearray)):
            # scalar blob (e.g. from_base64 result cast to a sketch):
            # lift into the 1-entry dictionary form the LUT path expects
            d = np.empty(1, dtype=object)
            d[0] = bytes(col.data)
            col = ColVal(jnp.asarray(0, jnp.int32), col.valid, col.type,
                         Dictionary(d))
            args = [col] + list(args[1:])
        extra = []
        for a in args[1:]:
            if hasattr(a.data, "shape") and getattr(a.data, "ndim", 0) > 0:
                raise NotImplementedError(f"{name} with non-constant arguments")
            v = a.data
            if a.dictionary is not None:
                v = a.dictionary.values[int(v)]
            elif hasattr(v, "item"):
                v = v.item()
            extra.append(v)
        rt = rt_fn([a.type for a in args])
        import struct as _struct

        vals = []
        for t in _arr_entries(col):
            try:
                vals.append(fn(t, *extra))
            except (ValueError, IndexError, TypeError, _struct.error):
                vals.append(None)  # malformed sketch -> NULL for that row
        return _dict_lut_result(vals, col, rt)

    return resolve, emit


def _register_sketch_fns():
    from presto_tpu.functions import sketches as SK

    prev_card = REGISTRY["cardinality"]

    def card_resolve(args):
        if args and args[0].name in ("HLL", "P4HLL", "QDIGEST"):
            return T.BIGINT
        return prev_card.resolve(args)

    def card_emit(args):
        if args[0].type.name in ("HLL", "P4HLL", "QDIGEST"):
            def card(blob):
                if args[0].type.name in ("HLL", "P4HLL"):
                    return SK.hll_cardinality(blob)
                return int(SK._qd_parse(blob)[1])

            return _sketch_dict_fn("cardinality", card, lambda a: T.BIGINT,
                                   ("HLL", "P4HLL", "QDIGEST"))[1](args)
        return prev_card.emit(args)

    register("cardinality")((card_resolve, card_emit))

    register("empty_approx_set")((
        lambda args: T.HLL if not args else None,
        lambda args: ColVal(jnp.asarray(0, jnp.int32), None, T.HLL,
                            Dictionary(np.asarray([SK.hll_empty()],
                                                  dtype=object)))))

    from presto_tpu.functions import tdigest as TD

    def _vaq(tname):
        def one(blob, q):
            if tname == "TDIGEST":
                return TD.tdigest_value_at_quantile(blob, float(q))
            return SK.qdigest_value_at_quantile(blob, float(q))

        return one

    def _vaq_dispatch(args):
        return _sketch_dict_fn(
            "value_at_quantile", _vaq(args[0].type.name),
            lambda a: T.DOUBLE if a[0].params
            and a[0].params[0].is_floating
            else (a[0].params[0] if a[0].params else T.DOUBLE),
            ("QDIGEST", "TDIGEST"))[1](args)

    register("value_at_quantile")((
        lambda args: (T.DOUBLE if args
                      and args[0].name in ("QDIGEST", "TDIGEST")
                      and (not args[0].params
                           or args[0].params[0].is_floating)
                      else args[0].params[0]
                      if args and args[0].name in ("QDIGEST", "TDIGEST")
                      else None),
        _vaq_dispatch))

    register("values_at_quantiles")((
        lambda args: (T.array_of(T.DOUBLE) if args
                      and args[0].name in ("QDIGEST", "TDIGEST")
                      else None),
        lambda args: _sketch_dict_fn(
            "values_at_quantiles",
            lambda blob, qs, _f=_vaq(args[0].type.name): tuple(
                _f(blob, q) for q in qs),
            lambda a: T.array_of(T.DOUBLE),
            ("QDIGEST", "TDIGEST"))[1](args)))

    register("quantile_at_value")((
        lambda args: (T.DOUBLE if args
                      and args[0].name in ("QDIGEST", "TDIGEST")
                      else None),
        lambda args: _sketch_dict_fn(
            "quantile_at_value",
            (lambda blob, v: TD.tdigest_quantile_at_value(blob, float(v)))
            if args[0].type.name == "TDIGEST"
            else (lambda blob, v: SK.qdigest_quantile_at_value(
                blob, float(v))),
            lambda a: T.DOUBLE,
            ("QDIGEST", "TDIGEST"))[1](args)))

    register("scale_tdigest")((_sketch_dict_fn(
        "scale_tdigest",
        lambda blob, f: TD.tdigest_scale(blob, float(f)),
        lambda a: a[0],
        ("TDIGEST",))))

    register("destructure_tdigest")((_sketch_dict_fn(
        "destructure_tdigest",
        lambda blob: tuple(
            (tuple(p) if isinstance(p, list) else p)
            for p in TD.tdigest_destructure(blob)),
        lambda a: T.row_of([("means", T.array_of(T.DOUBLE)),
                            ("weights", T.array_of(T.DOUBLE)),
                            ("compression", T.DOUBLE),
                            ("min", T.DOUBLE), ("max", T.DOUBLE),
                            ("sum", T.DOUBLE)]),
        ("TDIGEST",))))


_register_sketch_fns()

# round-4 breadth: the extended batches register on import (kept in
# their own modules to keep this file navigable)
from presto_tpu.functions import scalar_ext as _scalar_ext  # noqa: E402,F401
from presto_tpu.functions import scalar_ext2 as _scalar_ext2  # noqa: E402,F401
from presto_tpu.functions import datetime_tz as _datetime_tz  # noqa: E402,F401
from presto_tpu.functions import geospatial as _geospatial  # noqa: E402,F401
from presto_tpu.functions import ml as _ml  # noqa: E402,F401
