"""Geospatial functions (planar) — the presto-geospatial core, TPU-first.

Reference parity: presto-geospatial's GeoFunctions (ST_Point,
ST_GeometryFromText, ST_Contains, ST_Distance, ST_Area, ST_X/Y, ...)
over Esri geometry objects.  TPU-native adaptation: the hot analytics
shape is "millions of device-resident points against a handful of
geometries" (geofencing), so POINT columns live ON DEVICE as (n, 2)
float64 arrays and containment/distance lower to vectorized jnp math
(ray casting / segment distance over broadcast polygon edges — no
per-row host calls).  Non-point geometries (POLYGON, LINESTRING,
MULTIPOINT) are WKT-parsed host tuples behind the usual dictionary
encoding, like ARRAY values.

Spatial joins extract into a grid-indexed P.SpatialJoin
(plan/optimizer._extract_spatial_joins; the reference's SpatialJoinNode
+ PagesRTreeIndex role) — see grid_contains_join/grid_distance_join
below.  A residual CROSS+filter remains only for shapes the rule does
not cover.
"""

from __future__ import annotations

import math
import re

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.exec.colval import ColVal, all_valid
from presto_tpu.functions.scalar import (
    _as_string_literal,
    _str_transform,
    _tuple_dict_normalize,
    register,
)

GEOMETRY = T.Type("GEOMETRY")  # dictionary-encoded parsed geometry
POINTS = T.Type("GEOMETRY", ("point",))  # device (n, 2) f64 columns
T._PHYSICAL.setdefault("GEOMETRY", np.int32)


# ---------------------------------------------------------------------------
# WKT parse/format (host; geometries are few and dictionary-encoded)
# ---------------------------------------------------------------------------


def parse_wkt(text: str):
    """WKT -> ('point', (x, y)) | ('linestring', ((x,y),...)) |
    ('polygon', (ring, ...)) | ('multipoint', ((x,y),...))."""
    s = text.strip()
    m = re.match(r"(?i)^(point|linestring|polygon|multipoint)\s*", s)
    if not m:
        raise ValueError(f"unsupported WKT: {text[:40]}")
    kind = m.group(1).lower()
    body = s[m.end():].strip()
    if body.upper() == "EMPTY":
        return (kind, ())

    def coords(seg: str):
        out = []
        for pair in seg.split(","):
            xy = pair.split()
            out.append((float(xy[0]), float(xy[1])))
        return tuple(out)

    inner = body.strip()
    assert inner.startswith("(") and inner.endswith(")")
    inner = inner[1:-1]
    if kind == "point":
        return ("point", coords(inner)[0])
    if kind in ("linestring", "multipoint"):
        inner = inner.replace("(", "").replace(")", "")
        return (kind, coords(inner))
    rings = re.findall(r"\(([^()]*)\)", inner)
    return ("polygon", tuple(coords(r) for r in rings))


def to_wkt(g) -> str:
    kind, data = g
    if not data:
        return f"{kind.upper()} EMPTY"
    if kind == "point":
        return f"POINT ({_num(data[0])} {_num(data[1])})"
    if kind in ("linestring", "multipoint"):
        return (kind.upper() + " ("
                + ", ".join(f"{_num(x)} {_num(y)}" for x, y in data) + ")")
    return ("POLYGON ("
            + ", ".join("(" + ", ".join(f"{_num(x)} {_num(y)}"
                                        for x, y in ring) + ")"
                        for ring in data) + ")")


def _num(v: float) -> str:
    return f"{int(v)}" if float(v).is_integer() else f"{v}"


def _ring_contains(ring, px, py):
    """Vectorized ray casting: ring = host tuple of (x, y); px/py device
    arrays.  Boundary-inclusive within float tolerance."""
    n = len(ring)
    inside = jnp.zeros(px.shape, bool)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        crosses = ((y1 > py) != (y2 > py))
        xint = (x2 - x1) * (py - y1) / ((y2 - y1) or 1e-300) + x1
        inside = inside ^ (crosses & (px < xint))
    return inside


def _seg_distance(ax, ay, bx, by, px, py):
    """Distance from device points to host segment AB (vectorized)."""
    dx, dy = bx - ax, by - ay
    L2 = dx * dx + dy * dy
    t = jnp.clip(((px - ax) * dx + (py - ay) * dy) / (L2 or 1e-300),
                 0.0, 1.0)
    cx, cy = ax + t * dx, ay + t * dy
    return jnp.sqrt((px - cx) ** 2 + (py - cy) ** 2)


def _poly_contains_points(g, px, py):
    kind, data = g
    if kind == "polygon":
        if not data:
            return jnp.zeros(px.shape, bool)
        inside = _ring_contains(data[0], px, py)
        for hole in data[1:]:
            inside = inside & ~_ring_contains(hole, px, py)
        return inside
    if kind == "point":
        return (px == data[0]) & (py == data[1])
    if kind == "multipoint":
        hit = jnp.zeros(px.shape, bool)
        for x, y in data:
            hit = hit | ((px == x) & (py == y))
        return hit
    raise NotImplementedError(f"ST_Contains over {kind}")


def _geom_distance_points(g, px, py):
    kind, data = g
    if kind == "point":
        return jnp.sqrt((px - data[0]) ** 2 + (py - data[1]) ** 2)
    if kind == "multipoint":
        d = None
        for x, y in data:
            dd = jnp.sqrt((px - x) ** 2 + (py - y) ** 2)
            d = dd if d is None else jnp.minimum(d, dd)
        return d
    segs = []
    if kind == "linestring":
        segs = list(zip(data[:-1], data[1:]))
    elif kind == "polygon":
        for ring in data:  # hole boundaries count too (point in a hole
            # is OUTSIDE: its nearest boundary may be the hole ring)
            segs += [(ring[i], ring[(i + 1) % len(ring)])
                     for i in range(len(ring))]
    d = None
    for (ax, ay), (bx, by) in segs:
        dd = _seg_distance(ax, ay, bx, by, px, py)
        d = dd if d is None else jnp.minimum(d, dd)
    if kind == "polygon":  # interior points are at distance 0
        d = jnp.where(_poly_contains_points(g, px, py), 0.0, d)
    return d


def _geom_segments(g):
    """Host segment list of a geometry's boundary (all rings)."""
    kind, data = g
    if kind == "linestring":
        return list(zip(data[:-1], data[1:]))
    if kind == "polygon":
        out = []
        for ring in data:
            out += [(ring[i], ring[(i + 1) % len(ring)])
                    for i in range(len(ring))]
        return out
    return []


def _segments_intersect(s1, s2) -> bool:
    """Proper/improper 2D segment intersection (orientation tests)."""
    (ax, ay), (bx, by) = s1
    (cx, cy), (dx, dy) = s2

    def orient(px, py, qx, qy, rx, ry):
        v = (qx - px) * (ry - py) - (qy - py) * (rx - px)
        return 0 if abs(v) < 1e-12 else (1 if v > 0 else -1)

    o1 = orient(ax, ay, bx, by, cx, cy)
    o2 = orient(ax, ay, bx, by, dx, dy)
    o3 = orient(cx, cy, dx, dy, ax, ay)
    o4 = orient(cx, cy, dx, dy, bx, by)
    if o1 != o2 and o3 != o4:
        return True

    def on(px, py, qx, qy, rx, ry):  # r collinear-on pq
        return (min(px, qx) - 1e-12 <= rx <= max(px, qx) + 1e-12
                and min(py, qy) - 1e-12 <= ry <= max(py, qy) + 1e-12)

    if o1 == 0 and on(ax, ay, bx, by, cx, cy):
        return True
    if o2 == 0 and on(ax, ay, bx, by, dx, dy):
        return True
    if o3 == 0 and on(cx, cy, dx, dy, ax, ay):
        return True
    return o4 == 0 and on(cx, cy, dx, dy, bx, by)


def _boundaries_cross(ga, gb) -> bool:
    return any(_segments_intersect(s1, s2)
               for s1 in _geom_segments(ga) for s2 in _geom_segments(gb))


def _shoelace(ring) -> float:
    s = 0.0
    n = len(ring)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        s += x1 * y2 - x2 * y1
    return abs(s) / 2.0


def _bbox(g):
    kind, data = g
    pts = [data] if kind == "point" else \
        (data[0] if kind == "polygon" else data)
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    return min(xs), min(ys), max(xs), max(ys)


# ---------------------------------------------------------------------------
# value plumbing
# ---------------------------------------------------------------------------


def _geom_of(v: ColVal):
    """Host geometry for a scalar/literal geometry ColVal."""
    if v.type.is_string:
        lit = _as_string_literal(v)
        if lit is not None:
            return parse_wkt(lit)
    if v.type.name == "GEOMETRY" and v.dictionary is not None \
            and getattr(v.data, "ndim", 1) == 0:
        return v.dictionary.values[int(v.data)]
    if v.type.name == "GEOMETRY" and v.is_scalar \
            and isinstance(v.data, tuple):
        return v.data
    if v.type == POINTS and getattr(v.data, "ndim", 0) == 1:
        # scalar ST_Point(x, y): a single device pair routes through
        # the host-geometry paths
        return ("point", (float(v.data[0]), float(v.data[1])))
    return None


def _points_of(v: ColVal):
    """(px, py) device arrays for a POINTS ColVal, else None."""
    if v.type == POINTS and getattr(v.data, "ndim", 0) == 2:
        return v.data[:, 0], v.data[:, 1]
    return None


def _geoms_apply(col: ColVal, fn, out_type):
    """Host map over a dictionary-encoded GEOMETRY column."""
    vals = [fn(g) for g in col.dictionary.values]
    if out_type.name == "GEOMETRY" or out_type.is_string:
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return _tuple_dict_normalize(out, ColVal(col.data, col.valid,
                                                 out_type), out_type)
    lut = jnp.asarray(np.asarray(vals, dtype=out_type.numpy_dtype()))
    data = lut[jnp.clip(col.data, 0, len(col.dictionary) - 1)]
    return ColVal(data, col.valid, out_type)


# ---------------------------------------------------------------------------
# registrations
# ---------------------------------------------------------------------------

register("st_point")((
    lambda args: POINTS if len(args) == 2
    and all(a.is_numeric for a in args) else None,
    lambda args: ColVal(
        jnp.stack(jnp.broadcast_arrays(
            jnp.asarray(args[0].data).astype(jnp.float64),
            jnp.asarray(args[1].data).astype(jnp.float64)), axis=-1),
        all_valid(*args), POINTS)))

register("st_geometryfromtext")((_str_transform(
    "st_geometryfromtext", parse_wkt, GEOMETRY)))


def _emit_astext(args):
    v = args[0]
    g0 = _geom_of(v)
    if g0 is not None:  # scalar point / literal geometry
        return ColVal(to_wkt(g0), v.valid, T.VARCHAR)
    if v.type == POINTS:
        # device points render host-side; dynamic mode only
        pts = np.asarray(v.data)
        vals = np.empty(len(pts), dtype=object)
        vals[:] = [to_wkt(("point", (float(x), float(y))))
                   for x, y in pts]
        from presto_tpu.exec.colval import normalize_dictionary

        return normalize_dictionary(
            vals, ColVal(jnp.arange(len(pts), dtype=jnp.int32), v.valid,
                         T.VARCHAR))
    return _geoms_apply(v, to_wkt, T.VARCHAR)


register("st_astext")((
    lambda args: T.VARCHAR if len(args) == 1
    and args[0].name == "GEOMETRY" else None, _emit_astext))


def _xy_emit(idx):
    def emit(args):
        v = args[0]
        p = _points_of(v)
        if p is not None:
            return ColVal(p[idx], v.valid, T.DOUBLE)
        g = _geom_of(v)
        if g is not None and g[0] == "point":
            return ColVal(float(g[1][idx]), v.valid, T.DOUBLE)
        return _geoms_apply(
            args[0], lambda g2: float(g2[1][idx])
            if g2[0] == "point" else float("nan"), T.DOUBLE)

    return emit


register("st_x")((lambda args: T.DOUBLE if args
                  and args[0].name == "GEOMETRY" else None, _xy_emit(0)))
register("st_y")((lambda args: T.DOUBLE if args
                  and args[0].name == "GEOMETRY" else None, _xy_emit(1)))


def _resolve_geom_pair(out):
    def resolve(args):
        if len(args) == 2 and all(
                a.name == "GEOMETRY" or a.is_string for a in args):
            return out
        return None

    return resolve


def _emit_contains(args):
    g = _geom_of(args[0])
    p = _points_of(args[1])
    if g is not None and p is not None:
        # the TPU-shaped path: constant geometry, device point column
        return ColVal(_poly_contains_points(g, *p),
                      all_valid(*args), T.BOOLEAN)
    g2 = _geom_of(args[1])
    if g is not None and g2 is not None:
        if g2[0] == "point":
            px = jnp.asarray([g2[1][0]])
            py = jnp.asarray([g2[1][1]])
            return ColVal(bool(_poly_contains_points(g, px, py)[0]),
                          all_valid(*args), T.BOOLEAN)
        if g2[0] in ("multipoint", "linestring", "polygon"):
            pts = g2[1] if g2[0] != "polygon" else g2[1][0]
            px = jnp.asarray([q[0] for q in pts])
            py = jnp.asarray([q[1] for q in pts])
            inside = bool(jnp.all(_poly_contains_points(g, px, py)))
            # vertex containment alone is wrong for non-convex
            # containers: the contained shape must also never cross
            # the container's boundary
            ok = inside and not _boundaries_cross(g, g2)
            return ColVal(ok, all_valid(*args), T.BOOLEAN)
    raise NotImplementedError(
        "ST_Contains needs a constant geometry on the left")


register("st_contains")((_resolve_geom_pair(T.BOOLEAN), _emit_contains))
register("st_within")((
    _resolve_geom_pair(T.BOOLEAN),
    lambda args: _emit_contains([args[1], args[0]])))


def _emit_distance(args):
    a, b = args
    pa_, pb = _points_of(a), _points_of(b)
    if pa_ is not None and pb is not None:
        d = jnp.sqrt((pa_[0] - pb[0]) ** 2 + (pa_[1] - pb[1]) ** 2)
        return ColVal(d, all_valid(a, b), T.DOUBLE)
    for pts, other in ((pa_, b), (pb, a)):
        if pts is not None:
            g = _geom_of(other)
            if g is None:
                break
            return ColVal(_geom_distance_points(g, *pts),
                          all_valid(a, b), T.DOUBLE)
    ga, gb = _geom_of(a), _geom_of(b)
    if ga is not None and gb is not None and gb[0] == "point":
        px = jnp.asarray([gb[1][0]])
        py = jnp.asarray([gb[1][1]])
        return ColVal(float(_geom_distance_points(ga, px, py)[0]),
                      all_valid(a, b), T.DOUBLE)
    if ga is not None and gb is not None and ga[0] == "point":
        return _emit_distance([b, a])
    raise NotImplementedError("ST_Distance geometry pair")


register("st_distance")((_resolve_geom_pair(T.DOUBLE), _emit_distance))


def _emit_intersects(args):
    # bbox prefilter + containment/distance exact checks for the
    # supported kinds (reference: ST_Intersects via Esri relate)
    g = _geom_of(args[0])
    p = _points_of(args[1])
    if g is not None and p is not None:
        return _emit_contains(args)
    ga, gb = _geom_of(args[0]), _geom_of(args[1])
    if ga is not None and gb is not None:
        ax0, ay0, ax1, ay1 = _bbox(ga)
        bx0, by0, bx1, by1 = _bbox(gb)
        if ax1 < bx0 or bx1 < ax0 or ay1 < by0 or by1 < ay0:
            return ColVal(False, all_valid(*args), T.BOOLEAN)
        if gb[0] == "point":
            return _emit_contains(args)
        if ga[0] == "point":
            return _emit_contains([args[1], args[0]])
        # polygon/linestring pair: boundaries crossing, or one shape's
        # vertex inside the other (covers containment without crossing)
        hit = _boundaries_cross(ga, gb)
        if not hit and ga[0] == "polygon":
            pts = gb[1] if gb[0] != "polygon" else gb[1][0]
            px = jnp.asarray([q[0] for q in pts])
            py = jnp.asarray([q[1] for q in pts])
            hit = bool(jnp.any(_poly_contains_points(ga, px, py)))
        if not hit and gb[0] == "polygon":
            qts = ga[1] if ga[0] != "polygon" else ga[1][0]
            qx = jnp.asarray([q[0] for q in qts])
            qy = jnp.asarray([q[1] for q in qts])
            hit = bool(jnp.any(_poly_contains_points(gb, qx, qy)))
        return ColVal(hit, all_valid(*args), T.BOOLEAN)
    raise NotImplementedError("ST_Intersects geometry pair")


register("st_intersects")((_resolve_geom_pair(T.BOOLEAN),
                           _emit_intersects))


def _area(g) -> float:
    if g[0] != "polygon" or not g[1]:
        return 0.0
    a = _shoelace(g[1][0])
    for hole in g[1][1:]:
        a -= _shoelace(hole)
    return a


def _envelope(g):
    x0, y0, x1, y1 = _bbox(g)
    return ("polygon", (((x0, y0), (x1, y0), (x1, y1), (x0, y1),
                         (x0, y0)),))


def _geom1(name, fn, out_type):
    def emit(args):
        g = _geom_of(args[0])
        if g is not None:
            r = fn(g)
            if out_type.name == "GEOMETRY":
                return ColVal(r, args[0].valid, GEOMETRY)
            return ColVal(r, args[0].valid, out_type)
        return _geoms_apply(args[0], fn, out_type)

    return (lambda args: out_type if len(args) == 1
            and args[0].name == "GEOMETRY" else None, emit)


register("st_area")(_geom1("st_area", _area, T.DOUBLE))
register("st_envelope")(_geom1("st_envelope", _envelope, GEOMETRY))
def _centroid(g):
    kind, data = g
    if kind == "point":
        return ("point", data)
    if kind == "multipoint":
        return ("point", (float(np.mean([p[0] for p in data])),
                          float(np.mean([p[1] for p in data]))))
    if kind == "linestring":
        # length-weighted segment midpoints (GeoFunctions semantics)
        tx = ty = tl = 0.0
        for (x1, y1), (x2, y2) in zip(data[:-1], data[1:]):
            ln = math.dist((x1, y1), (x2, y2))
            tx += (x1 + x2) / 2 * ln
            ty += (y1 + y2) / 2 * ln
            tl += ln
        if tl == 0:
            return ("point", data[0])
        return ("point", (tx / tl, ty / tl))
    # polygon: signed-area-weighted centroid over rings (holes
    # subtract via opposite winding of the shoelace terms)
    ax = ay = asum = 0.0
    for ri, ring in enumerate(data):
        sx = sy = s = 0.0
        for i in range(len(ring)):
            x1, y1 = ring[i]
            x2, y2 = ring[(i + 1) % len(ring)]
            cross = x1 * y2 - x2 * y1
            sx += (x1 + x2) * cross
            sy += (y1 + y2) * cross
            s += cross
        sign = 1.0 if ri == 0 else -1.0
        ax += sign * abs(s) * (sx / (3.0 * s) if s else 0.0)
        ay += sign * abs(s) * (sy / (3.0 * s) if s else 0.0)
        asum += sign * abs(s)
    if asum == 0:
        return ("point", data[0][0])
    return ("point", (ax / asum, ay / asum))


register("st_centroid")(_geom1("st_centroid", _centroid, GEOMETRY))
register("st_npoints")(_geom1(
    "st_npoints",
    lambda g: sum(len(r) for r in g[1]) if g[0] == "polygon"
    else (1 if g[0] == "point" else len(g[1])), T.BIGINT))
register("st_length")(_geom1(
    "st_length",
    lambda g: float(sum(
        math.dist(a, b) for a, b in zip(g[1][:-1], g[1][1:])))
    if g[0] == "linestring" else 0.0, T.DOUBLE))


# ---------------------------------------------------------------------------
# grid-indexed spatial join runtime (reference: SpatialJoinOperator +
# PagesRTreeIndex).  TPU-native: a uniform grid replaces the R-tree —
# candidate generation is vectorized numpy over (cell, build) pairs and
# the exact predicate runs on device over PADDED edge arrays, so the hot
# math is fixed-shape elementwise work instead of per-node tree descent.
# ---------------------------------------------------------------------------


def _geom_rings(g):
    """All rings/segment chains of a geometry as coordinate tuples
    (even-odd ray parity over every ring handles holes for free)."""
    kind, data = g
    if kind == "polygon":
        return [tuple(r) for r in data]
    raise NotImplementedError(f"spatial join build over {kind}")


def grid_contains_join(px, py, geoms):
    """point-in-polygon join.  px/py: host float64 arrays (n probes);
    geoms: list of parsed geometries (build side).  Returns (lidx, ridx)
    numpy index arrays of matching pairs."""
    n = len(px)
    m = len(geoms)
    if n == 0 or m == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    boxes = np.empty((m, 4), np.float64)
    edge_lists = []
    for j, g in enumerate(geoms):
        rings = _geom_rings(g)
        pts = [p for r in rings for p in r]
        if not pts:  # POLYGON EMPTY: contains nothing
            boxes[j] = (np.inf, np.inf, -np.inf, -np.inf)
            edge_lists.append([])
            continue
        xs = np.asarray([p[0] for p in pts])
        ys = np.asarray([p[1] for p in pts])
        boxes[j] = (xs.min(), ys.min(), xs.max(), ys.max())
        segs = []
        for r in rings:
            k = len(r)
            for i in range(k):
                x1, y1 = r[i]
                x2, y2 = r[(i + 1) % k]
                segs.append((x1, y1, x2, y2))
        edge_lists.append(segs)

    lidx, ridx = _grid_candidates(px, py, boxes)
    if len(lidx) == 0:
        return lidx, ridx
    # exact even-odd ray cast on device, BUCKETED by edge count so one
    # high-vertex polygon does not inflate the padding for everyone
    # (pow2 classes keep the compiled-shape count logarithmic)
    nedges = np.asarray([len(s) for s in edge_lists], np.int64)
    pair_edges = nedges[ridx]
    classes = np.maximum(
        1 << np.ceil(np.log2(np.maximum(pair_edges, 1))).astype(np.int64),
        4)
    out_l, out_r = [], []
    for cls in np.unique(classes):
        sel = np.flatnonzero(classes == cls)
        sl, sr = lidx[sel], ridx[sel]
        uniq_g, inv_g = np.unique(sr, return_inverse=True)
        E = np.full((len(uniq_g), int(cls), 4), np.nan)  # NaN never crosses
        for gi, j in enumerate(uniq_g):
            segs = edge_lists[j]
            if segs:
                E[gi, :len(segs)] = segs
        hit = _raycast_pairs(px[sl], py[sl], E, inv_g)
        out_l.append(sl[hit])
        out_r.append(sr[hit])
    return np.concatenate(out_l), np.concatenate(out_r)


def _raycast_pairs(cx, cy, E, gsel):
    """Even-odd ray parity for candidate pairs: cx/cy host points (C,),
    E (G, emax, 4) padded edges, gsel (C,) geometry index per pair."""
    import jax.numpy as jnp

    ex1 = jnp.asarray(E[:, :, 0])[gsel]
    ey1 = jnp.asarray(E[:, :, 1])[gsel]
    ex2 = jnp.asarray(E[:, :, 2])[gsel]
    ey2 = jnp.asarray(E[:, :, 3])[gsel]
    pcx = jnp.asarray(cx)[:, None]
    pcy = jnp.asarray(cy)[:, None]
    crosses = (ey1 > pcy) != (ey2 > pcy)
    denom = jnp.where(ey2 == ey1, 1e-300, ey2 - ey1)
    xint = (ex2 - ex1) * (pcy - ey1) / denom + ex1
    parity = jnp.sum(crosses & (pcx < xint), axis=1) % 2 == 1
    return np.asarray(parity)


def grid_distance_join(px, py, bx, by, radius, strict=False):
    """point-to-point distance join: |p - b| </<= radius.  Host numpy
    candidate generation over radius-sized cells (3x3 neighborhoods),
    exact distances on device."""
    n, m = len(px), len(bx)
    if n == 0 or m == 0 or radius < 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    boxes = np.stack([bx - radius, by - radius,
                      bx + radius, by + radius], axis=1)
    lidx, ridx = _grid_candidates(px, py, boxes)
    if len(lidx) == 0:
        return lidx, ridx
    import jax.numpy as jnp

    d2 = (jnp.asarray(px)[lidx] - jnp.asarray(bx)[ridx]) ** 2 \
        + (jnp.asarray(py)[lidx] - jnp.asarray(by)[ridx]) ** 2
    r2 = float(radius) * float(radius)
    hit = np.asarray(d2 < r2 if strict else d2 <= r2)
    return lidx[hit], ridx[hit]


def _grid_candidates(px, py, boxes):
    """(probe, build) candidate pairs whose probe point falls in the
    build bbox, via a uniform grid sized to the p95 bbox dimension.
    Vectorized throughout; the (cell, build) relation is sorted once and
    probed with searchsorted, the numpy analog of a hash-grid lookup.
    Returns indices into the ORIGINAL boxes array."""
    # drop degenerate/empty bboxes up front (they match nothing and inf
    # coordinates would poison the cell arithmetic)
    ok = np.isfinite(boxes).all(axis=1) & (boxes[:, 0] <= boxes[:, 2])
    build_map = np.flatnonzero(ok)
    boxes = boxes[ok]
    m = len(boxes)
    empty = (np.empty(0, np.int64), np.empty(0, np.int64))
    if m == 0 or len(px) == 0:
        return empty
    w = boxes[:, 2] - boxes[:, 0]
    h = boxes[:, 3] - boxes[:, 1]
    # p95 sizing bounds skew from a few outsized bboxes; anything still
    # spanning too many cells joins brute-force below (the grid analog
    # of an R-tree root-level entry)
    cs = max(float(np.percentile(w, 95)), float(np.percentile(h, 95)),
             1e-9)
    x0 = float(min(boxes[:, 0].min(), px.min()))
    y0 = float(min(boxes[:, 1].min(), py.min()))
    jx0 = np.floor((boxes[:, 0] - x0) / cs).astype(np.int64)
    jy0 = np.floor((boxes[:, 1] - y0) / cs).astype(np.int64)
    jx1 = np.floor((boxes[:, 2] - x0) / cs).astype(np.int64)
    jy1 = np.floor((boxes[:, 3] - y0) / cs).astype(np.int64)
    ncx = int(jx1.max()) + 2
    spans = (jx1 - jx0 + 1) * (jy1 - jy0 + 1)
    small = np.flatnonzero(spans <= 256)
    big = np.flatnonzero(spans > 256)

    parts_l, parts_r = [], []
    if len(small):
        sx = jx1[small] - jx0[small] + 1
        sp = spans[small]
        total_cells = int(sp.sum())
        builds = np.repeat(small, sp)
        off0 = np.concatenate([[0], np.cumsum(sp)[:-1]])
        k = np.arange(total_cells, dtype=np.int64) - np.repeat(off0, sp)
        rsx = np.repeat(sx, sp)
        cells = ((np.repeat(jy0[small], sp) + k // rsx) * ncx
                 + np.repeat(jx0[small], sp) + k % rsx)
        order = np.argsort(cells, kind="stable")
        cells, builds = cells[order], builds[order]
        pgx = np.floor((px - x0) / cs).astype(np.int64)
        pgy = np.floor((py - y0) / cs).astype(np.int64)
        pcell = pgy * ncx + pgx
        lo = np.searchsorted(cells, pcell, side="left")
        hi = np.searchsorted(cells, pcell, side="right")
        counts = hi - lo
        total = int(counts.sum())
        if total:
            lidx = np.repeat(np.arange(len(px), dtype=np.int64), counts)
            offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
            flat = np.arange(total, dtype=np.int64) \
                - np.repeat(offsets, counts) + np.repeat(lo, counts)
            parts_l.append(lidx)
            parts_r.append(builds[flat])
    for j in big:  # rare skew outliers: bbox test against every probe
        inbox = np.flatnonzero(
            (px >= boxes[j, 0]) & (px <= boxes[j, 2])
            & (py >= boxes[j, 1]) & (py <= boxes[j, 3]))
        parts_l.append(inbox)
        parts_r.append(np.full(len(inbox), j, np.int64))
    if not parts_l:
        return empty
    lidx = np.concatenate(parts_l)
    ridx = np.concatenate(parts_r)
    # bbox refinement before the exact predicate
    keep = ((px[lidx] >= boxes[ridx, 0]) & (px[lidx] <= boxes[ridx, 2])
            & (py[lidx] >= boxes[ridx, 1]) & (py[lidx] <= boxes[ridx, 3]))
    return lidx[keep], build_map[ridx[keep]]
