"""Aggregate function catalog: signature resolution + reduction specs.

Reference parity: the 98 aggregation files under presto-main/.../operator/
aggregation/ and AccumulatorCompiler.  Here every aggregate is described as
a (init, map, segment-combine, finalize) spec over fixed-shape arrays so
group-by lowers to jax.ops.segment_* reductions — the TPU replacement for
per-group accumulator objects.  PARTIAL/FINAL splitting (reference:
AggregationNode.Step) works on the intermediate columns declared here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List


from presto_tpu import types as T


@dataclasses.dataclass
class AggSpec:
    name: str
    resolve: Callable  # (arg_types: List[Type]) -> Optional[Type]
    # intermediate state columns: list of (suffix, init_value, combine)
    # combine in {'sum', 'min', 'max', 'bitor'} — all segment-reducible
    states: Callable  # (arg_types) -> List[Tuple[str, str]]  (suffix, combine-op)
    # map inputs -> state columns (row-wise, pre-reduction)
    # finalize state columns -> result


RESOLVERS: Dict[str, Callable] = {}


def _numeric_sum_type(t: T.Type) -> T.Type:
    if t.is_integer:
        return T.BIGINT
    if t.is_decimal:
        # Presto: sum(DECIMAL(p,s)) -> DECIMAL(38,s) — the accumulator
        # is Int128 (two-limb), so whole-column sums cannot wrap
        # (reference: DecimalSumAggregation)
        return T.decimal(38, t.decimal_scale)
    return T.DOUBLE


def resolve(name: str, arg_types: List[T.Type], distinct: bool = False) -> T.Type:
    name = name.lower()
    if name in ("count", "count_if"):
        return T.BIGINT
    if name == "approx_distinct":
        return T.BIGINT
    if name == "approx_count":
        # COUNT(x) WITH ERROR (seeded-sample estimate; sql/parser.py)
        return T.BIGINT
    if name == "approx_sum":
        # SUM(x) WITH ERROR: same result type as the exact sum
        if not arg_types or not arg_types[0].is_numeric:
            raise TypeError(f"sum over {arg_types or 'no args'}")
        return _numeric_sum_type(arg_types[0])
    if name == "sum":
        if arg_types[0].name in ("INTERVAL_DAY_TIME",
                                 "INTERVAL_YEAR_MONTH"):
            # reference: IntervalDayToSecondSumAggregation
            return arg_types[0]
        if not arg_types[0].is_numeric:
            raise TypeError(f"sum over {arg_types[0]}")
        return _numeric_sum_type(arg_types[0])
    if name == "avg":
        if arg_types[0].name in ("INTERVAL_DAY_TIME",
                                 "INTERVAL_YEAR_MONTH"):
            # reference: IntervalDayToSecondAverageAggregation
            return arg_types[0]
        if not arg_types[0].is_numeric:
            raise TypeError(f"avg over {arg_types[0]}")
        return T.DOUBLE
    if name in ("min", "max", "arbitrary", "any_value"):
        return arg_types[0]
    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        return T.DOUBLE
    if name in ("bool_and", "bool_or", "every"):
        return T.BOOLEAN
    if name in ("corr", "covar_samp", "covar_pop", "regr_slope",
                "regr_intercept"):
        return T.DOUBLE
    if name in ("skewness", "kurtosis"):
        if not arg_types[0].is_numeric:
            raise TypeError(f"{name} over {arg_types[0]}")
        return T.DOUBLE
    if name == "entropy":
        if not arg_types[0].is_numeric:
            raise TypeError(f"entropy over {arg_types[0]}")
        return T.DOUBLE
    if name in ("bitwise_and_agg", "bitwise_or_agg"):
        if not arg_types[0].is_integer:
            raise TypeError(f"{name} over {arg_types[0]}")
        return T.BIGINT
    if name == "histogram":
        return T.map_of(arg_types[0], T.BIGINT)
    if name == "numeric_histogram":
        if len(arg_types) != 2:
            raise TypeError("numeric_histogram takes (buckets, value)")
        return T.map_of(T.DOUBLE, T.DOUBLE)
    if name == "map_union":
        if arg_types[0].name != "MAP":
            raise TypeError("map_union takes a MAP argument")
        return arg_types[0]
    if name in ("classification_miss_rate", "classification_fall_out",
                "classification_precision", "classification_recall",
                "classification_thresholds"):
        # (buckets, truth_bool, prediction_prob[, weight]) ->
        # ARRAY(DOUBLE) at thresholds i/buckets (reference:
        # Classification*Aggregation / PrecisionRecallAggregation)
        if len(arg_types) not in (3, 4) \
                or not arg_types[0].is_integer \
                or arg_types[1].name != "BOOLEAN" \
                or not arg_types[2].is_numeric:
            raise TypeError(
                f"{name} takes (buckets, truth boolean, prediction"
                "[, weight])")
        return T.array_of(T.DOUBLE)
    if name == "evaluate_classifier_predictions":
        # (truth, prediction) -> summary text (reference: presto-ml
        # EvaluateClassifierPredictionsAggregation)
        if len(arg_types) != 2:
            raise TypeError(
                "evaluate_classifier_predictions takes (truth, prediction)")
        return T.VARCHAR
    if name in ("learn_classifier", "learn_regressor"):
        if len(arg_types) != 2 or arg_types[1].name != "FEATURES":
            raise TypeError(f"{name} takes (label, features(...))")
        lt = arg_types[0]
        if name == "learn_regressor" and not lt.is_numeric:
            raise TypeError(f"learn_regressor label must be numeric, "
                            f"got {lt}")
        if name == "learn_classifier" and not (
                lt.is_numeric or lt.is_string
                or lt.name in ("BOOLEAN", "DATE")):
            raise TypeError(f"learn_classifier label type {lt} "
                            "is not supported")
        return T.VARBINARY  # serialized model (presto-ml Model role)
    if name == "approx_percentile":
        # (value, p) / (value, ARRAY[p..]) / (value, weight, p[, acc])
        # — reference: Approximate*PercentileAggregations (+Array forms)
        if not arg_types or not arg_types[0].is_numeric:
            raise TypeError(
                f"approx_percentile over {arg_types or 'no args'}")
        if len(arg_types) == 2:
            if arg_types[1].name == "ARRAY":
                return T.array_of(arg_types[0])
            return arg_types[0]
        if len(arg_types) in (3, 4):
            if arg_types[2].name == "ARRAY":
                return T.array_of(arg_types[0])
            return arg_types[0]
        raise TypeError("approx_percentile takes (value[, weight], "
                        "percentile[, accuracy])")
    if name == "checksum":
        return T.BIGINT
    if name in ("min_by", "max_by"):
        if len(arg_types) == 3:
            # n-variant: the n smallest/largest keys' values as an array
            # (reference: MinMaxByNAggregationFunction)
            if not arg_types[2].is_integer:
                raise TypeError(f"{name}(value, key, n): n must be integer")
            return T.array_of(arg_types[0])
        if len(arg_types) != 2:
            raise TypeError(f"{name} takes (value, key[, n])")
        return arg_types[0]
    if name == "geometric_mean":
        return T.DOUBLE
    if name == "array_agg":
        return T.array_of(arg_types[0])
    if name == "approx_set":
        return T.HLL
    if name == "merge":
        if arg_types[0].name not in ("HLL", "P4HLL", "QDIGEST", "TDIGEST"):
            raise TypeError(
                "merge() takes an HLL, P4HLL, QDIGEST or TDIGEST argument")
        return arg_types[0]
    if name == "qdigest_agg":
        if not arg_types[0].is_numeric:
            raise TypeError(f"qdigest_agg over {arg_types[0]}")
        return T.qdigest_of(arg_types[0])
    if name == "tdigest_agg":
        # (value[, weight[, compression]]) — reference:
        # TDigestAggregationFunction
        if not arg_types or not arg_types[0].is_numeric:
            raise TypeError(f"tdigest_agg over {arg_types or 'no args'}")
        if len(arg_types) > 3 or any(not t.is_numeric
                                     for t in arg_types[1:]):
            raise TypeError("tdigest_agg takes (value[, weight"
                            "[, compression]])")
        return T.tdigest_of(T.DOUBLE)
    if name == "map_agg":
        if len(arg_types) != 2:
            raise TypeError("map_agg takes (key, value)")
        return T.map_of(arg_types[0], arg_types[1])
    if name == "set_agg":
        # distinct values as an array (reference: SetAggregationFunction)
        return T.array_of(arg_types[0])
    if name == "set_union":
        if arg_types[0].name != "ARRAY":
            raise TypeError("set_union takes an ARRAY argument")
        return arg_types[0]
    if name == "map_union_sum":
        if arg_types[0].name != "MAP" \
                or not arg_types[0].params[1].is_numeric:
            raise TypeError("map_union_sum takes a MAP(K, numeric)")
        return arg_types[0]
    if name == "approx_most_frequent":
        if len(arg_types) != 3:
            raise TypeError(
                "approx_most_frequent takes (buckets, value, capacity)")
        return T.map_of(arg_types[1], T.BIGINT)
    if name == "reduce_agg":
        # (value, init_state, input_lambda, combine_lambda) -> state
        if len(arg_types) < 2:
            raise TypeError(
                "reduce_agg takes (value, state, input_fn, combine_fn)")
        return arg_types[1]
    if name == "multimap_agg":
        if len(arg_types) != 2:
            raise TypeError("multimap_agg takes (key, value)")
        return T.map_of(arg_types[0], T.array_of(arg_types[1]))
    raise KeyError(f"unknown aggregate function: {name}")


AGG_NAMES = {
    "count", "count_if", "sum", "avg", "min", "max", "arbitrary", "any_value",
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "bool_and", "bool_or", "every", "approx_distinct", "corr", "covar_samp",
    "covar_pop", "approx_percentile", "checksum", "min_by", "max_by",
    "approx_count", "approx_sum",
    "geometric_mean", "array_agg", "map_agg", "multimap_agg",
    "approx_set", "merge", "qdigest_agg", "tdigest_agg",
    "regr_slope", "regr_intercept", "skewness", "kurtosis", "entropy",
    "bitwise_and_agg", "bitwise_or_agg", "histogram", "numeric_histogram",
    "map_union", "learn_classifier", "learn_regressor",
    "set_agg", "set_union", "map_union_sum", "approx_most_frequent",
    "reduce_agg", "evaluate_classifier_predictions",
    "classification_miss_rate", "classification_fall_out",
    "classification_precision", "classification_recall",
    "classification_thresholds",
}


def is_aggregate(name: str) -> bool:
    return name.lower() in AGG_NAMES


WINDOW_ONLY = {"row_number", "rank", "dense_rank", "ntile", "lag", "lead",
               "first_value", "last_value", "nth_value", "cume_dist", "percent_rank"}


def is_window(name: str) -> bool:
    n = name.lower()
    return n in WINDOW_ONLY or is_aggregate(n)


def resolve_window(name: str, arg_types: List[T.Type]) -> T.Type:
    """Return type of a window function call (reference: the
    WindowFunctionSupplier signatures in operator/window/)."""
    n = name.lower()
    if n in ("row_number", "rank", "dense_rank", "ntile"):
        return T.BIGINT
    if n in ("percent_rank", "cume_dist"):
        return T.DOUBLE
    if n in ("lag", "lead", "first_value", "last_value", "nth_value"):
        if not arg_types:
            raise KeyError(f"{name} requires an argument")
        return arg_types[0]
    if is_aggregate(n):
        return resolve(n, arg_types)
    raise KeyError(f"unknown window function: {name}")
