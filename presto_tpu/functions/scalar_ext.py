"""Extended scalar function batch (round-4 breadth push).

Reference parity: the long tail of presto-main/.../operator/scalar/ —
MathFunctions' trig/probability surface, StringFunctions' distance
family, re2j RegexpFunctions, VarbinaryFunctions + HmacFunctions,
UrlFunctions, DateTimeFunctions' Joda field/format surface and the
Teradata compatibility shims (to_char/to_date).  Same conventions as
scalar.py: dictionary-encoded strings transform on host over UNIQUE
dictionary values (never per row), numeric kernels are jnp elementwise,
strict null propagation unless noted.
"""

from __future__ import annotations

import base64
import binascii
import datetime as _dt
import hashlib
import hmac as _hmac
import math
import re
import struct
import unicodedata
import urllib.parse as _url
import zlib

import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Dictionary
from presto_tpu.exec.colval import ColVal, all_valid, normalize_dictionary
from presto_tpu.functions.scalar import (
    _as_string_literal,
    _host_string_pred,
    _str_transform,
    _tuple_dict_normalize,
    civil_from_days,
    days_from_civil,
    register,
)

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _f64(v):
    return jnp.asarray(v.data).astype(jnp.float64)


def _math1d(name, fn):
    """1-arg numeric -> DOUBLE."""
    return (lambda args: T.DOUBLE if args[0].is_numeric else None,
            lambda args: ColVal(fn(_f64(args[0])), args[0].valid, T.DOUBLE))


def _mathNd(name, n, fn, valid_fn=None):
    """n-arg numeric -> DOUBLE elementwise."""

    def resolve(args):
        if len(args) == n and all(a.is_numeric for a in args):
            return T.DOUBLE
        return None

    def emit(args):
        xs = [_f64(a) for a in args]
        out = fn(*xs)
        valid = all_valid(*args)
        if valid_fn is not None:
            ok = valid_fn(*xs)
            valid = ok if valid is None else (valid & ok)
        return ColVal(out, valid, T.DOUBLE)

    return resolve, emit


def _pred1d(name, fn):
    """1-arg floating -> BOOLEAN."""
    return (lambda args: T.BOOLEAN if args[0].is_numeric else None,
            lambda args: ColVal(fn(_f64(args[0])), args[0].valid, T.BOOLEAN))


def _const(name, value, typ=T.DOUBLE):
    return (lambda args: typ if not args else None,
            lambda args: ColVal(value, None, typ))


def _obj_dict_normalize(values: np.ndarray, codes: ColVal,
                        out_type: T.Type) -> ColVal:
    """normalize_dictionary for non-str dictionary values (bytes,
    tuples): sorted-unique by natural order, codes remapped."""
    uniq = sorted(set(values.tolist()))
    code_map = {v: i for i, v in enumerate(uniq)}
    inverse = np.fromiter((code_map[v] for v in values.tolist()),
                          np.int32, len(values))
    lut = jnp.asarray(inverse)
    new_codes = lut[jnp.clip(codes.data, 0, max(len(values) - 1, 0))]
    u = np.empty(len(uniq), dtype=object)
    u[:] = uniq
    return ColVal(new_codes, codes.valid, out_type, Dictionary(u))


def _host_transform_typed(col: ColVal, fn, out_type: T.Type) -> ColVal:
    """Dictionary transform whose outputs are bytes/objects (VARBINARY)
    or strings, normalized appropriately."""
    vals = np.empty(len(col.dictionary), dtype=object)
    vals[:] = [fn(v) for v in col.dictionary.values]
    cv = ColVal(col.data, col.valid, out_type)
    if out_type.name == "VARBINARY":
        return _obj_dict_normalize(vals, cv, out_type)
    return normalize_dictionary(vals, cv)


def _str_fn(name, fn, out_type=T.VARCHAR, in_name=None):
    """1-string-arg function over dictionary values; scalars fold."""

    def resolve(args):
        if len(args) != 1 or not args[0].is_string:
            return None
        if in_name is not None and args[0].name != in_name:
            return None
        return out_type

    def emit(args):
        col = args[0]
        lit = col.data if col.is_scalar and isinstance(
            col.data, (str, bytes)) else None
        if lit is not None:
            return ColVal(fn(lit), col.valid, out_type)
        if out_type.is_string:
            return _host_transform_typed(col, fn, out_type)
        if out_type == T.BOOLEAN:
            return _host_string_pred(col, fn)
        lut = jnp.asarray(np.asarray(
            [fn(v) for v in col.dictionary.values],
            dtype=out_type.numpy_dtype()))
        data = lut[jnp.clip(col.data, 0, len(col.dictionary) - 1)]
        return ColVal(data, col.valid, out_type)

    return resolve, emit


def _str2_fn(name, fn, out_type):
    """2-string-arg function: literal x literal, column x literal,
    literal x column, and dictionary x dictionary via the value cross
    product (bounded)."""

    def resolve(args):
        if len(args) == 2 and all(a.is_string for a in args):
            return out_type
        return None

    def emit(args):
        a, b = args
        la = a.data if a.is_scalar and isinstance(a.data, (str, bytes)) \
            else None
        lb = b.data if b.is_scalar and isinstance(b.data, (str, bytes)) \
            else None
        valid = all_valid(a, b)
        if la is not None and lb is not None:
            return ColVal(fn(la, lb), valid, out_type)

        def via_lut(col, f1):
            vals = [f1(v) for v in col.dictionary.values]
            if out_type.is_string:
                o = np.empty(len(vals), dtype=object)
                o[:] = vals
                r = _obj_dict_normalize(o, ColVal(col.data, valid,
                                                  out_type), out_type) \
                    if out_type.name == "VARBINARY" else \
                    normalize_dictionary(o, ColVal(col.data, valid,
                                                   out_type))
                return r
            lut = jnp.asarray(np.asarray(vals,
                                         dtype=out_type.numpy_dtype()))
            d = lut[jnp.clip(col.data, 0, len(col.dictionary) - 1)]
            return ColVal(d, valid, out_type)

        if lb is not None:
            return via_lut(a, lambda v: fn(v, lb))
        if la is not None:
            return via_lut(b, lambda v: fn(la, v))
        if a.dictionary is not None and b.dictionary is not None \
                and len(a.dictionary) * len(b.dictionary) <= (1 << 18):
            av = a.dictionary.values
            bv = b.dictionary.values
            nb = len(bv)
            vals = [fn(x, y) for x in av for y in bv]
            codes = jnp.clip(a.data, 0, len(av) - 1) * nb \
                + jnp.clip(b.data, 0, nb - 1)
            cv = ColVal(codes, valid, out_type)
            if out_type.is_string:
                o = np.empty(len(vals), dtype=object)
                o[:] = vals
                return _obj_dict_normalize(o, cv, out_type) \
                    if out_type.name == "VARBINARY" else \
                    normalize_dictionary(o, cv)
            lut = jnp.asarray(np.asarray(vals,
                                         dtype=out_type.numpy_dtype()))
            return ColVal(lut[codes], valid, out_type)
        raise NotImplementedError(
            f"{name} over non-dictionary string columns")

    return resolve, emit


# ---------------------------------------------------------------------------
# math: trig/hyperbolic/conversions
# ---------------------------------------------------------------------------

register("sin")(_math1d("sin", jnp.sin))
register("cos")(_math1d("cos", jnp.cos))
register("tan")(_math1d("tan", jnp.tan))
register("asin")(_math1d("asin", jnp.arcsin))
register("acos")(_math1d("acos", jnp.arccos))
register("atan")(_math1d("atan", jnp.arctan))
register("sinh")(_math1d("sinh", jnp.sinh))
register("cosh")(_math1d("cosh", jnp.cosh))
register("tanh")(_math1d("tanh", jnp.tanh))
register("cbrt")(_math1d("cbrt", jnp.cbrt))
register("degrees")(_math1d("degrees", jnp.degrees))
register("radians")(_math1d("radians", jnp.radians))
register("log2")(_math1d("log2", jnp.log2))
register("is_nan")(_pred1d("is_nan", jnp.isnan))
register("is_finite")(_pred1d("is_finite", jnp.isfinite))
register("is_infinite")(_pred1d("is_infinite", jnp.isinf))
register("infinity")(_const("infinity", float("inf")))
register("nan")(_const("nan", float("nan")))


def _resolve_mod(args):
    if len(args) == 2 and all(a.is_numeric for a in args):
        if all(a.is_integer for a in args):
            return T.common_super_type(*args)
        return T.DOUBLE
    return None


def _emit_mod(args):
    a, b = args
    t = _resolve_mod([a.type, b.type])
    x = jnp.asarray(a.data)
    y = jnp.asarray(b.data)
    if t.is_integer:
        r = (x - jnp.trunc(
            x.astype(jnp.float64) / jnp.where(y == 0, 1, y)
        ).astype(x.dtype) * y).astype(t.numpy_dtype())
        # fmod sign semantics on ints without float rounding at scale:
        r = x % jnp.where(y == 0, 1, y)
        r = jnp.where((r != 0) & ((r < 0) != (x < 0)), r - y, r)
        valid = all_valid(a, b)
        ok = y != 0
        valid = ok if valid is None else (valid & ok)
        return ColVal(r.astype(t.numpy_dtype()), valid, t)
    r = jnp.fmod(x.astype(jnp.float64), y.astype(jnp.float64))
    return ColVal(r, all_valid(a, b), T.DOUBLE)


register("mod")((_resolve_mod, _emit_mod))


def _bit_count_emit(args):
    x = jnp.asarray(args[0].data).astype(jnp.int64)
    bits = 64 if len(args) < 2 else int(np.asarray(args[1].data))
    if bits < 64:
        x = x & ((1 << bits) - 1)
        # sign bit of the narrowed width counts as set for negatives
    cnt = jnp.sum(((x[..., None] >> jnp.arange(64, dtype=jnp.int64)) & 1),
                  axis=-1)
    return ColVal(cnt.astype(jnp.int64), args[0].valid, T.BIGINT)


register("bit_count")((
    lambda args: T.BIGINT if args and args[0].is_integer else None,
    _bit_count_emit))
register("bitwise_logical_shift_right")((
    lambda args: T.BIGINT if len(args) == 2 else None,
    lambda args: ColVal(
        jnp.asarray(
            (np.uint64 if False else jnp.asarray(args[0].data)
             .astype(jnp.uint64)) >> jnp.asarray(args[1].data)
            .astype(jnp.uint64)).astype(jnp.int64),
        all_valid(*args), T.BIGINT)))
register("bitwise_arithmetic_shift_right")((
    lambda args: T.BIGINT if len(args) == 2 else None,
    lambda args: ColVal(
        jnp.asarray(args[0].data).astype(jnp.int64)
        >> jnp.asarray(args[1].data).astype(jnp.int64),
        all_valid(*args), T.BIGINT)))


# probability CDFs (reference: operator/scalar/MathFunctions.java's
# *_cdf / inverse_*_cdf family) — closed forms + jax.scipy specials
from jax.scipy import special as _sp  # noqa: E402

register("normal_cdf")(_mathNd(
    "normal_cdf", 3,
    lambda mean, sd, v: 0.5 * (1.0 + _sp.erf((v - mean)
                                             / (sd * math.sqrt(2.0)))),
    valid_fn=lambda mean, sd, v: sd > 0))
register("inverse_normal_cdf")(_mathNd(
    "inverse_normal_cdf", 3,
    lambda mean, sd, p: mean + sd * math.sqrt(2.0) * _sp.erfinv(2 * p - 1),
    valid_fn=lambda mean, sd, p: (sd > 0) & (p > 0) & (p < 1)))
register("cauchy_cdf")(_mathNd(
    "cauchy_cdf", 3,
    lambda med, sc, v: jnp.arctan((v - med) / sc) / jnp.pi + 0.5,
    valid_fn=lambda med, sc, v: sc > 0))
register("inverse_cauchy_cdf")(_mathNd(
    "inverse_cauchy_cdf", 3,
    lambda med, sc, p: med + sc * jnp.tan(jnp.pi * (p - 0.5)),
    valid_fn=lambda med, sc, p: (sc > 0) & (p > 0) & (p < 1)))
register("laplace_cdf")(_mathNd(
    "laplace_cdf", 3,
    lambda mean, sc, v: jnp.where(
        v < mean, 0.5 * jnp.exp((v - mean) / sc),
        1.0 - 0.5 * jnp.exp(-(v - mean) / sc)),
    valid_fn=lambda mean, sc, v: sc > 0))
register("logistic_cdf")(_mathNd(
    "logistic_cdf", 3,
    lambda mean, sc, v: 1.0 / (1.0 + jnp.exp(-(v - mean) / sc)),
    valid_fn=lambda mean, sc, v: sc > 0))
register("weibull_cdf")(_mathNd(
    "weibull_cdf", 3,
    lambda a, b, v: 1.0 - jnp.exp(-jnp.power(jnp.maximum(v, 0.0) / b, a)),
    valid_fn=lambda a, b, v: (a > 0) & (b > 0)))
register("poisson_cdf")(_mathNd(
    "poisson_cdf", 2,
    lambda lam, k: _sp.gammaincc(jnp.floor(k) + 1.0, lam),
    valid_fn=lambda lam, k: (lam > 0) & (k >= 0)))
register("chi_squared_cdf")(_mathNd(
    "chi_squared_cdf", 2,
    lambda df, v: _sp.gammainc(df / 2.0, v / 2.0),
    valid_fn=lambda df, v: (df > 0) & (v >= 0)))
register("gamma_cdf")(_mathNd(
    "gamma_cdf", 3,
    lambda shape, scale, v: _sp.gammainc(shape, v / scale),
    valid_fn=lambda shape, scale, v: (shape > 0) & (scale > 0) & (v >= 0)))
register("beta_cdf")(_mathNd(
    "beta_cdf", 3,
    lambda a, b, v: _sp.betainc(a, b, jnp.clip(v, 0.0, 1.0)),
    valid_fn=lambda a, b, v: (a > 0) & (b > 0) & (v >= 0) & (v <= 1)))
register("binomial_cdf")(_mathNd(
    "binomial_cdf", 3,
    lambda n, p, s: jnp.where(
        s >= n, 1.0, jnp.where(
            s < 0, 0.0,
            _sp.betainc(jnp.maximum(n - jnp.floor(s), 1.0),
                        jnp.floor(s) + 1.0, 1.0 - p))),
    valid_fn=lambda n, p, s: (n > 0) & (p >= 0) & (p <= 1)))
register("f_cdf")(_mathNd(
    "f_cdf", 3,
    lambda d1, d2, v: _sp.betainc(d1 / 2, d2 / 2,
                                  d1 * v / (d1 * v + d2)),
    valid_fn=lambda d1, d2, v: (d1 > 0) & (d2 > 0) & (v >= 0)))
register("wilson_interval_lower")(_mathNd(
    "wilson_interval_lower", 3,
    lambda s, n, z: (s + z * z / 2 - z * jnp.sqrt(
        jnp.maximum(s * (n - s) / n + z * z / 4, 0.0))) / (n + z * z),
    valid_fn=lambda s, n, z: (n > 0) & (s >= 0) & (s <= n) & (z > 0)))
register("wilson_interval_upper")(_mathNd(
    "wilson_interval_upper", 3,
    lambda s, n, z: (s + z * z / 2 + z * jnp.sqrt(
        jnp.maximum(s * (n - s) / n + z * z / 4, 0.0))) / (n + z * z),
    valid_fn=lambda s, n, z: (n > 0) & (s >= 0) & (s <= n) & (z > 0)))


def _from_base(v, radix):
    return int(str(v).strip(), int(radix))


def _to_base(x, radix):
    x = int(x)
    radix = int(radix)
    if x == 0:
        return "0"
    digs = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg = x < 0
    x = abs(x)
    out = []
    while x:
        out.append(digs[x % radix])
        x //= radix
    return ("-" if neg else "") + "".join(reversed(out))


register("from_base")((_str_transform("from_base", _from_base, T.BIGINT)))


def _emit_to_base(args):
    x = args[0]
    radix = int(np.asarray(args[1].data))
    data = np.asarray(x.data)
    if data.ndim == 0:
        return ColVal(_to_base(int(data), radix), x.valid, T.VARCHAR)
    uniq, inv = np.unique(data, return_inverse=True)
    vals = np.asarray([_to_base(int(u), radix) for u in uniq],
                      dtype=object)
    return normalize_dictionary(
        vals, ColVal(jnp.asarray(inv.astype(np.int32)), x.valid,
                     T.VARCHAR))


register("to_base")((
    lambda args: T.VARCHAR if len(args) == 2 and args[0].is_integer
    else None, _emit_to_base))


# ---------------------------------------------------------------------------
# string distance / shaping
# ---------------------------------------------------------------------------


def _levenshtein(a, b):
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _hamming(a, b):
    if len(a) != len(b):
        raise ValueError("hamming_distance: equal lengths required")
    return sum(x != y for x, y in zip(a, b))


register("levenshtein_distance")(
    _str2_fn("levenshtein_distance", _levenshtein, T.BIGINT))
register("hamming_distance")(
    _str2_fn("hamming_distance", _hamming, T.BIGINT))
register("jaccard_index")(_str2_fn(
    "jaccard_index",
    lambda a, b: (len(set(a) & set(b)) / len(set(a) | set(b)))
    if (a or b) else 1.0, T.DOUBLE))


def _translate(v, frm, to):
    table = {}
    for i, c in enumerate(str(frm)):
        table[ord(c)] = str(to)[i] if i < len(str(to)) else None
    return v.translate(table)


register("translate")((_str_transform("translate", _translate)))
register("normalize")((_str_transform(
    "normalize", lambda v, form="NFC": unicodedata.normalize(
        str(form), v))))
register("soundex")((_str_transform("soundex", lambda v: _soundex(v))))


def _soundex(v):
    if not v:
        return ""
    v = v.upper()
    codes = {"B": "1", "F": "1", "P": "1", "V": "1",
             "C": "2", "G": "2", "J": "2", "K": "2", "Q": "2", "S": "2",
             "X": "2", "Z": "2", "D": "3", "T": "3", "L": "4",
             "M": "5", "N": "5", "R": "6"}
    out = [v[0]]
    last = codes.get(v[0], "")
    for c in v[1:]:
        code = codes.get(c, "")
        if code and code != last:
            out.append(code)
        if c not in "HW":
            last = code
    return ("".join(out) + "000")[:4]


register("from_utf8")((_str_fn(
    "from_utf8", lambda v: (v if isinstance(v, bytes) else
                            str(v).encode()).decode("utf-8", "replace"),
    T.VARCHAR)))
register("to_utf8")((_str_fn(
    "to_utf8", lambda v: v.encode() if isinstance(v, str) else bytes(v),
    T.VARBINARY)))


# ---------------------------------------------------------------------------
# regexp long tail (re2j RegexpFunctions)
# ---------------------------------------------------------------------------


def _rx(pattern):
    return re.compile(str(pattern))


def _regexp_count(v, pat):
    return len(_rx(pat).findall(v))


def _regexp_position(v, pat, start=1):
    m = _rx(pat).search(v, int(start) - 1)
    return -1 if m is None else m.start() + 1


register("regexp_count")((_str_transform(
    "regexp_count", _regexp_count, T.BIGINT)))
register("regexp_position")((_str_transform(
    "regexp_position", _regexp_position, T.BIGINT)))


def _emit_regexp_array(fn_name, per_value):
    def resolve(args):
        if args and args[0].is_string:
            return T.array_of(T.VARCHAR)
        return None

    def emit(args):
        col = args[0]
        extra = [np.asarray(a.data).item() if hasattr(a.data, "shape")
                 and getattr(a.data, "ndim", 0) == 0 else a.data
                 for a in args[1:]]
        out_t = T.array_of(T.VARCHAR)
        lit = _as_string_literal(col)
        if lit is not None:
            vals = np.empty(1, dtype=object)
            vals[0] = tuple(per_value(lit, *extra))
            return _tuple_dict_normalize(
                vals, ColVal(jnp.asarray(0, jnp.int32), col.valid, out_t),
                out_t)
        vals = np.empty(len(col.dictionary), dtype=object)
        vals[:] = [tuple(per_value(v, *extra))
                   for v in col.dictionary.values]
        return _tuple_dict_normalize(
            vals, ColVal(col.data, col.valid, out_t), out_t)

    return resolve, emit


register("regexp_extract_all")(_emit_regexp_array(
    "regexp_extract_all",
    lambda v, pat, group=0: [m.group(int(group))
                             for m in _rx(pat).finditer(v)]))
register("regexp_split")(_emit_regexp_array(
    "regexp_split", lambda v, pat: _rx(pat).split(v)))


# ---------------------------------------------------------------------------
# binary / codec / hashing (VarbinaryFunctions + HmacFunctions)
# ---------------------------------------------------------------------------


def _as_bytes(v):
    return v if isinstance(v, bytes) else str(v).encode()


def _bin_fn(name, fn, out_type=T.VARBINARY):
    return _str_fn(name, lambda v: fn(_as_bytes(v)), out_type)


register("to_hex")(_bin_fn("to_hex",
                           lambda b: b.hex().upper(), T.VARCHAR))
register("from_hex")(_str_fn(
    "from_hex", lambda v: binascii.unhexlify(
        v if isinstance(v, str) else v.decode()), T.VARBINARY))
register("to_base64")(_bin_fn(
    "to_base64", lambda b: base64.b64encode(b).decode(), T.VARCHAR))
register("from_base64")(_str_fn(
    "from_base64", lambda v: base64.b64decode(_as_bytes(v) + b"=="),
    T.VARBINARY))
register("to_base64url")(_bin_fn(
    "to_base64url", lambda b: base64.urlsafe_b64encode(b).decode(),
    T.VARCHAR))
register("from_base64url")(_str_fn(
    "from_base64url",
    lambda v: base64.urlsafe_b64decode(_as_bytes(v) + b"=="),
    T.VARBINARY))
register("md5")(_bin_fn("md5", lambda b: hashlib.md5(b).digest()))
register("sha1")(_bin_fn("sha1", lambda b: hashlib.sha1(b).digest()))
register("sha256")(_bin_fn("sha256", lambda b: hashlib.sha256(b).digest()))
register("sha512")(_bin_fn("sha512", lambda b: hashlib.sha512(b).digest()))
register("crc32")(_bin_fn("crc32", lambda b: zlib.crc32(b) & 0xFFFFFFFF,
                          T.BIGINT))


def _xxh64(data: bytes, seed: int = 0) -> int:
    """Pure-python xxHash64 (public domain algorithm)."""
    P1, P2, P3 = 0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9
    P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    n = len(data)
    if n >= 32:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        i = 0
        while i <= n - 32:
            for k, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 8 * k:i + 8 * k + 8],
                                      "little")
                v = (v + lane * P2) & M
                v = rotl(v, 31)
                v = (v * P1) & M
                if k == 0:
                    v1 = v
                elif k == 1:
                    v2 = v
                elif k == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            v = (v * P2) & M
            v = rotl(v, 31)
            v = (v * P1) & M
            h = ((h ^ v) * P1 + P4) & M
    else:
        h = (seed + P5) & M
        i = 0
    h = (h + n) & M
    while i <= n - 8:
        lane = int.from_bytes(data[i:i + 8], "little")
        h ^= rotl((lane * P2) & M, 31) * P1 & M
        h = (rotl(h, 27) * P1 + P4) & M
        i += 8
    if i <= n - 4:
        h ^= (int.from_bytes(data[i:i + 4], "little") * P1) & M
        h = (rotl(h, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h ^= (data[i] * P5) & M
        h = (rotl(h, 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h


def _signed64(u):
    return u - (1 << 64) if u >= (1 << 63) else u


register("xxhash64")(_bin_fn(
    "xxhash64", lambda b: struct.pack(">q", _signed64(_xxh64(b)))))
for _alg in ("md5", "sha1", "sha256", "sha512"):
    register(f"hmac_{_alg}")(_str2_fn(
        f"hmac_{_alg}",
        (lambda alg: lambda v, key: _hmac.new(
            _as_bytes(key), _as_bytes(v), alg).digest())(_alg),
        T.VARBINARY))


def _emit_int_to_bin(fmt, in_float=False):
    def emit(args):
        x = args[0]
        data = np.asarray(x.data)
        conv = (lambda u: struct.pack(fmt, u))
        if data.ndim == 0:
            return ColVal(conv(data.item()), x.valid, T.VARBINARY)
        uniq, inv = np.unique(data, return_inverse=True)
        vals = np.empty(len(uniq), dtype=object)
        vals[:] = [conv(u.item()) for u in uniq]
        return _obj_dict_normalize(
            vals, ColVal(jnp.asarray(inv.astype(np.int32)), x.valid,
                         T.VARBINARY), T.VARBINARY)

    return emit


register("to_big_endian_64")((
    lambda args: T.VARBINARY if args and args[0].is_integer else None,
    _emit_int_to_bin(">q")))
register("to_big_endian_32")((
    lambda args: T.VARBINARY if args and args[0].is_integer else None,
    _emit_int_to_bin(">i")))
register("to_ieee754_64")((
    lambda args: T.VARBINARY if args and args[0].is_numeric else None,
    _emit_int_to_bin(">d")))
register("to_ieee754_32")((
    lambda args: T.VARBINARY if args and args[0].is_numeric else None,
    _emit_int_to_bin(">f")))
register("from_big_endian_64")(_str_fn(
    "from_big_endian_64",
    lambda v: struct.unpack(">q", _as_bytes(v))[0], T.BIGINT,
    in_name="VARBINARY"))
register("from_big_endian_32")(_str_fn(
    "from_big_endian_32",
    lambda v: struct.unpack(">i", _as_bytes(v))[0], T.INTEGER,
    in_name="VARBINARY"))
register("from_ieee754_64")(_str_fn(
    "from_ieee754_64",
    lambda v: struct.unpack(">d", _as_bytes(v))[0], T.DOUBLE,
    in_name="VARBINARY"))
register("from_ieee754_32")(_str_fn(
    "from_ieee754_32",
    lambda v: struct.unpack(">f", _as_bytes(v))[0], T.REAL,
    in_name="VARBINARY"))


# ---------------------------------------------------------------------------
# URL functions (operator/scalar/UrlFunctions.java)
# ---------------------------------------------------------------------------


def _url_part(part):
    def fn(v):
        u = _url.urlparse(v)
        if part == "protocol":
            return u.scheme
        if part == "host":
            return u.hostname or ""
        if part == "path":
            return u.path
        if part == "query":
            return u.query
        if part == "fragment":
            return u.fragment
        raise KeyError(part)

    return fn


for _p in ("protocol", "host", "path", "query", "fragment"):
    register(f"url_extract_{_p}")((_str_transform(
        f"url_extract_{_p}", _url_part(_p))))
register("url_extract_port")((_str_transform(
    "url_extract_port",
    lambda v: _url.urlparse(v).port or -1, T.BIGINT)))
register("url_extract_parameter")((_str_transform(
    "url_extract_parameter",
    lambda v, name: (_url.parse_qs(_url.urlparse(v).query)
                     .get(str(name), [""])[0]))))
register("url_encode")((_str_transform(
    "url_encode", lambda v: _url.quote_plus(v))))
register("url_decode")((_str_transform(
    "url_decode", lambda v: _url.unquote_plus(v))))


# ---------------------------------------------------------------------------
# datetime Joda surface (DateTimeFunctions.java)
# ---------------------------------------------------------------------------


def _ts_micros(v):
    """TIMESTAMP int64 micros; DATE widens to midnight micros."""
    d = jnp.asarray(v.data)
    if v.type.name == "DATE":
        return d.astype(jnp.int64) * 86_400_000_000
    return d.astype(jnp.int64)


def _time_field(name, fn):
    return (lambda args: T.BIGINT if args and args[0].is_temporal
            else None,
            lambda args: ColVal(fn(_ts_micros(args[0])).astype(jnp.int64),
                                args[0].valid, T.BIGINT))


register("hour")(_time_field(
    "hour", lambda us: (us // 3_600_000_000) % 24))
register("minute")(_time_field(
    "minute", lambda us: (us // 60_000_000) % 60))
register("second")(_time_field(
    "second", lambda us: (us // 1_000_000) % 60))
register("millisecond")(_time_field(
    "millisecond", lambda us: (us // 1_000) % 1000))
register("timezone_hour")(_time_field(
    "timezone_hour", lambda us: jnp.zeros_like(us)))  # engine is UTC
register("timezone_minute")(_time_field(
    "timezone_minute", lambda us: jnp.zeros_like(us)))


def _days_of(v):
    d = jnp.asarray(v.data)
    if v.type.name == "TIMESTAMP":
        return jnp.floor_divide(d, 86_400_000_000).astype(jnp.int64)
    return d.astype(jnp.int64)


def _date_field(name, fn):
    return (lambda args: T.BIGINT if args and args[0].is_temporal
            else None,
            lambda args: ColVal(fn(_days_of(args[0])).astype(jnp.int64),
                                args[0].valid, T.BIGINT))


register("day_of_week")(_date_field(
    "day_of_week", lambda days: ((days + 3) % 7) + 1))  # ISO Mon=1
register("day_of_month")(_date_field(
    "day_of_month", lambda days: civil_from_days(days)[2]))
register("day_of_year")(_date_field(
    "day_of_year",
    lambda days: days - days_from_civil(civil_from_days(days)[0],
                                        jnp.asarray(1),
                                        jnp.asarray(1)) + 1))


def _iso_week_year(days):
    """ISO-8601 week number and week-year (Joda weekOfWeekyear /
    weekyear)."""
    dow = (days + 3) % 7  # 0 = Monday
    thursday = days - dow + 3
    y, _m, _d = civil_from_days(thursday)
    jan1 = days_from_civil(y, jnp.asarray(1), jnp.asarray(1))
    week = (thursday - jan1) // 7 + 1
    return week, y


register("week_of_year")(_date_field(
    "week_of_year", lambda days: _iso_week_year(days)[0]))
register("year_of_week")(_date_field(
    "year_of_week", lambda days: _iso_week_year(days)[1]))
register("yow")(_date_field(
    "yow", lambda days: _iso_week_year(days)[1]))


_MYSQL_FMT = {
    "%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%-m", "%d": "%d",
    "%e": "%-d", "%H": "%H", "%k": "%-H", "%i": "%M", "%s": "%S",
    "%f": "%f", "%p": "%p", "%h": "%I", "%I": "%I", "%j": "%j",
    "%a": "%a", "%W": "%A", "%M": "%B", "%b": "%b", "%T": "%H:%M:%S",
    "%%": "%%",
}


def _mysql_to_strftime(fmt):
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "%" and i + 1 < len(fmt):
            tok = fmt[i:i + 2]
            out.append(_MYSQL_FMT.get(tok, tok[1]))
            i += 2
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


_JODA_FMT = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MMMM", "%B"), ("MMM", "%b"),
    ("MM", "%m"), ("M", "%-m"), ("dd", "%d"), ("d", "%-d"),
    ("HH", "%H"), ("H", "%-H"), ("hh", "%I"), ("h", "%-I"),
    ("mm", "%M"), ("m", "%-M"), ("ss", "%S"), ("s", "%-S"),
    ("SSS", "%f"), ("a", "%p"), ("EEEE", "%A"), ("EEE", "%a"),
    ("DDD", "%j"), ("ZZ", "+00:00"), ("Z", "+0000"),
]


def _joda_to_strftime(fmt):
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "'":
            j = fmt.find("'", i + 1)
            if j == i + 1:
                out.append("'")
                i += 2
                continue
            out.append(fmt[i + 1:j if j > 0 else len(fmt)])
            i = (j if j > 0 else len(fmt)) + 1
            continue
        for tok, rep in _JODA_FMT:
            if fmt.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


def _dt_of_micros(us):
    return _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(us))


def _strftime_portable(dtv, fmt):
    # %-m style (no zero pad) is glibc-specific; expand manually
    def sub(m):
        c = m.group(1)
        v = {"m": dtv.month, "d": dtv.day, "H": dtv.hour,
             "I": (dtv.hour % 12) or 12, "M": dtv.minute,
             "S": dtv.second}[c]
        return str(v)

    fmt = re.sub(r"%-([mdHIMS])", sub, fmt)
    return dtv.strftime(fmt)


def _emit_temporal_format(to_strftime):
    def emit(args):
        v = args[0]
        fmt = to_strftime(str(np.asarray(args[1].data)
                              if not isinstance(args[1].data, str)
                              else args[1].data))
        data = np.asarray(v.data)
        us = data.astype(np.int64) * (86_400_000_000
                                      if v.type.name == "DATE" else 1)
        if us.ndim == 0:
            return ColVal(_strftime_portable(_dt_of_micros(us), fmt),
                          v.valid, T.VARCHAR)
        uniq, inv = np.unique(us, return_inverse=True)
        vals = np.asarray([_strftime_portable(_dt_of_micros(u), fmt)
                           for u in uniq], dtype=object)
        return normalize_dictionary(
            vals, ColVal(jnp.asarray(inv.astype(np.int32)), v.valid,
                         T.VARCHAR))

    return emit


register("date_format")((
    lambda args: T.VARCHAR if len(args) == 2 and args[0].is_temporal
    else None, _emit_temporal_format(_mysql_to_strftime)))
register("format_datetime")((
    lambda args: T.VARCHAR if len(args) == 2 and args[0].is_temporal
    else None, _emit_temporal_format(_joda_to_strftime)))


def _parse_to_micros(v, fmt):
    d = _dt.datetime.strptime(str(v).strip(), fmt)
    return int((d - _dt.datetime(1970, 1, 1)).total_seconds() * 1e6)


register("date_parse")((_str_transform(
    "date_parse",
    lambda v, fmt: _parse_to_micros(v, _mysql_to_strftime(str(fmt))),
    T.TIMESTAMP)))
register("parse_datetime")((_str_transform(
    "parse_datetime",
    lambda v, fmt: _parse_to_micros(
        v, _joda_to_strftime(str(fmt)).replace("+00:00", "%z")
        .replace("+0000", "%z")),
    T.TIMESTAMP)))
register("from_iso8601_date")((_str_transform(
    "from_iso8601_date",
    lambda v: (_dt.date.fromisoformat(str(v))
               - _dt.date(1970, 1, 1)).days, T.DATE)))
register("from_iso8601_timestamp")((_str_transform(
    "from_iso8601_timestamp",
    lambda v: int((_dt.datetime.fromisoformat(
        str(v).replace("Z", "+00:00")).replace(tzinfo=None)
        - _dt.datetime(1970, 1, 1)).total_seconds() * 1e6),
    T.TIMESTAMP)))


def _emit_to_iso8601(args):
    v = args[0]
    data = np.asarray(v.data)
    if v.type.name == "DATE":
        conv = lambda x: (_dt.date(1970, 1, 1)
                          + _dt.timedelta(days=int(x))).isoformat()
    else:
        conv = lambda x: _dt_of_micros(x).isoformat() + "Z"
    if data.ndim == 0:
        return ColVal(conv(data.item()), v.valid, T.VARCHAR)
    uniq, inv = np.unique(data, return_inverse=True)
    vals = np.asarray([conv(u) for u in uniq], dtype=object)
    return normalize_dictionary(
        vals, ColVal(jnp.asarray(inv.astype(np.int32)), v.valid,
                     T.VARCHAR))


register("to_iso8601")((
    lambda args: T.VARCHAR if args and args[0].is_temporal else None,
    _emit_to_iso8601))
register("to_char")((
    lambda args: T.VARCHAR if len(args) == 2 and args[0].is_temporal
    else None, _emit_temporal_format(_joda_to_strftime)))
register("to_date")((_str_transform(
    "to_date",
    lambda v, fmt: _parse_to_micros(v, _joda_to_strftime(str(fmt)))
    // 86_400_000_000, T.DATE)))
register("to_timestamp")((_str_transform(
    "to_timestamp",
    lambda v, fmt: _parse_to_micros(v, _joda_to_strftime(str(fmt))),
    T.TIMESTAMP)))


def _now_emit(args):
    import time as _time

    return ColVal(int(_time.time() * 1e6), None, T.TIMESTAMP)


register("now")((lambda args: T.TIMESTAMP if not args else None,
                 _now_emit))
register("current_timestamp")((
    lambda args: T.TIMESTAMP if not args else None, _now_emit))
register("localtimestamp")((
    lambda args: T.TIMESTAMP if not args else None, _now_emit))
register("current_date")((
    lambda args: T.DATE if not args else None,
    lambda args: ColVal(
        (_dt.date.today() - _dt.date(1970, 1, 1)).days, None, T.DATE)))
register("current_timezone")((
    lambda args: T.VARCHAR if not args else None,
    lambda args: ColVal("UTC", None, T.VARCHAR)))


def _parse_duration(v):
    m = re.fullmatch(r"\s*([\d.]+)\s*(ns|us|ms|s|m|h|d)\s*", str(v))
    if not m:
        raise ValueError(f"invalid duration: {v}")
    mult = {"ns": 1e-3, "us": 1.0, "ms": 1e3, "s": 1e6, "m": 6e7,
            "h": 3.6e9, "d": 8.64e10}[m.group(2)]
    return int(float(m.group(1)) * mult)


register("parse_duration")((_str_transform(
    "parse_duration", _parse_duration, T.INTERVAL_DAY_TIME)))
register("to_milliseconds")((
    lambda args: T.BIGINT if args
    and args[0].name == "INTERVAL_DAY_TIME" else None,
    lambda args: ColVal(jnp.asarray(args[0].data).astype(jnp.int64)
                        // 1000, args[0].valid, T.BIGINT)))


# ---------------------------------------------------------------------------
# JSON long tail
# ---------------------------------------------------------------------------


def _json_array_get(v, idx):
    import json as _json

    try:
        arr = _json.loads(v)
        if not isinstance(arr, list):
            return None
        i = int(idx)
        if i < 0:
            i += len(arr)
        if not 0 <= i < len(arr):
            return None
        e = arr[i]
        return _json.dumps(e) if isinstance(e, (dict, list)) \
            else (_json.dumps(e) if not isinstance(e, str) else e)
    except ValueError:
        return None


def _json_array_contains(v, needle):
    import json as _json

    try:
        arr = _json.loads(v)
        return isinstance(arr, list) and needle in arr
    except ValueError:
        return False


register("json_array_get")((_str_transform(
    "json_array_get", _json_array_get, T.JSON)))


def _emit_json_array_contains(args):
    col, needle = args
    nv = needle.data
    if hasattr(nv, "item") and getattr(nv, "ndim", 0) == 0:
        nv = nv.item()
    if needle.type == T.BOOLEAN:
        nv = bool(nv)
    elif needle.type.is_integer:
        nv = int(nv)
    elif needle.type.is_floating:
        nv = float(nv)
    lit = _as_string_literal(col)
    if lit is not None:
        return ColVal(_json_array_contains(lit, nv), col.valid, T.BOOLEAN)
    return _host_string_pred(col, lambda v: _json_array_contains(v, nv))


register("json_array_contains")((
    lambda args: T.BOOLEAN if len(args) == 2 and args[0].is_string
    else None, _emit_json_array_contains))


# ---------------------------------------------------------------------------
# arrays long tail
# ---------------------------------------------------------------------------


def _array_transform(name, fn, resolve_out):
    """Host transform over array-dictionary tuples."""

    def resolve(args):
        if args and args[0].name == "ARRAY":
            return resolve_out(args[0])
        return None

    def emit(args):
        col = args[0]
        out_t = resolve_out(col.type)
        vals = np.empty(len(col.dictionary), dtype=object)
        vals[:] = [fn(t) for t in col.dictionary.values]
        cv = ColVal(col.data, col.valid, out_t)
        if out_t.name == "ARRAY":
            return _tuple_dict_normalize(vals, cv, out_t)
        if out_t == T.BOOLEAN:
            lut = jnp.asarray(np.asarray([bool(x) for x in vals]))
            return ColVal(lut[jnp.clip(col.data, 0,
                                       len(col.dictionary) - 1)],
                          col.valid, T.BOOLEAN)
        lut_np = np.asarray([0 if x is None else x for x in vals],
                            dtype=out_t.numpy_dtype())
        miss = np.asarray([x is None for x in vals])
        idx = jnp.clip(col.data, 0, len(col.dictionary) - 1)
        data = jnp.asarray(lut_np)[idx]
        mvalid = ~jnp.asarray(miss)[idx]
        valid = mvalid if col.valid is None else (col.valid & mvalid)
        return ColVal(data, valid, out_t)

    return resolve, emit


register("array_sum")(_array_transform(
    "array_sum",
    lambda t: sum(x for x in t if x is not None and
                  isinstance(x, (int, float))),
    lambda at: T.DOUBLE if at.params[0].is_floating else T.BIGINT))
register("array_average")(_array_transform(
    "array_average",
    lambda t: (float(np.mean([x for x in t if x is not None]))
               if any(x is not None for x in t) else None),
    lambda at: T.DOUBLE))
register("array_duplicates")(_array_transform(
    "array_duplicates",
    lambda t: tuple(sorted({x for x in t if t.count(x) > 1},
                           key=repr)),
    lambda at: at))
register("array_has_duplicates")(_array_transform(
    "array_has_duplicates",
    lambda t: len(set(t)) != len(t),
    lambda at: T.BOOLEAN))


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------


def _emit_typeof(args):
    return ColVal(str(args[0].type), None, T.VARCHAR)


register("typeof")((lambda args: T.VARCHAR if len(args) == 1 else None,
                    _emit_typeof))


def _emit_concat_ws(args):
    from presto_tpu.functions.scalar import _emit_concat

    sep = args[0]
    s = _as_string_literal(sep)
    if s is None:
        raise NotImplementedError("concat_ws with non-constant separator")
    parts = []
    for i, a in enumerate(args[1:]):
        if i:
            parts.append(ColVal(s, None, T.VARCHAR))
        parts.append(a)
    return _emit_concat(parts)


register("concat_ws")((
    lambda args: T.VARCHAR if len(args) >= 2
    and all(a.is_string for a in args) else None, _emit_concat_ws))
