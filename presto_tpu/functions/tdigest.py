"""t-digest: mergeable quantile sketch (Dunning's merging variant).

Reference parity: presto-main/.../operator/aggregation/tdigest/TDigest.java
(tdigest_agg, merge(tdigest), value_at_quantile, values_at_quantiles,
quantile_at_value, scale_tdigest, destructure_tdigest over
TDigestType).  The reference implements the same merging t-digest with
the k1 scale function; this module reimplements the algorithm on numpy
from the published description — not a translation.

Format (little-endian):
  'PTD1' | compression f64 | total_weight f64 | min f64 | max f64 |
  k u32 | means f64[k] | weights f64[k]

Centroids are kept sorted by mean.  Accuracy follows the k1 scale
function: fine near q=0/1, coarse in the middle — the property that
makes t-digest preferred over q-digest for tail quantiles.
"""

from __future__ import annotations

import math
import struct
from typing import Iterable, List, Optional

import numpy as np

_MAGIC = b"PTD1"
DEFAULT_COMPRESSION = 100.0


def _k1(q: float, compression: float) -> float:
    # scale function k_1(q) = (δ / 2π) asin(2q - 1)
    return compression / (2 * math.pi) * math.asin(2 * q - 1)


def _serialize(compression: float, total: float, mn: float, mx: float,
               means: np.ndarray, weights: np.ndarray) -> bytes:
    k = len(means)
    return (_MAGIC + struct.pack("<ddddI", compression, total, mn, mx, k)
            + np.asarray(means, "<f8").tobytes()
            + np.asarray(weights, "<f8").tobytes())


def _parse(blob: bytes):
    if not blob or blob[:4] != _MAGIC:
        raise ValueError("not a t-digest")
    compression, total, mn, mx, k = struct.unpack_from("<ddddI", blob, 4)
    off = 4 + 8 * 4 + 4
    means = np.frombuffer(blob, "<f8", k, off)
    weights = np.frombuffer(blob, "<f8", k, off + 8 * k)
    return compression, total, mn, mx, means, weights


def _compress(means: np.ndarray, weights: np.ndarray,
              compression: float):
    """One merging pass over mean-sorted centroids, bounding centroid
    weight by the k1 scale function."""
    if len(means) == 0:
        return means, weights
    order = np.argsort(means, kind="stable")
    means = np.asarray(means, np.float64)[order]
    weights = np.asarray(weights, np.float64)[order]
    total = float(weights.sum())
    out_m: List[float] = [float(means[0])]
    out_w: List[float] = [float(weights[0])]
    w_so_far = 0.0
    for m, w in zip(means[1:], weights[1:]):
        q0 = w_so_far / total
        q2 = min((w_so_far + out_w[-1] + w) / total, 1.0)
        if _k1(q2, compression) - _k1(q0, compression) <= 1.0:
            # merge into the current centroid (weighted mean)
            nw = out_w[-1] + w
            out_m[-1] += (m - out_m[-1]) * w / nw
            out_w[-1] = nw
        else:
            w_so_far += out_w[-1]
            out_m.append(float(m))
            out_w.append(float(w))
    return np.asarray(out_m), np.asarray(out_w)


def tdigest_from_values(values: Iterable, weights: Optional[Iterable] = None,
                        compression: float = DEFAULT_COMPRESSION) -> bytes:
    vals = np.asarray([float(v) for v in values], np.float64)
    if weights is not None:
        ws = np.asarray([float(w) for w in weights], np.float64)
        if len(ws) != len(vals):
            raise ValueError("weights/values length mismatch")
    else:
        ws = np.ones(len(vals), np.float64)
    keep = ~np.isnan(vals)  # the same mask MUST filter both arrays
    vals, ws = vals[keep], ws[keep]
    if len(vals) == 0:
        return _serialize(compression, 0.0, math.inf, -math.inf,
                          np.empty(0), np.empty(0))
    # two-level build: a 2x-resolution pass first, then the final
    # compression — the buffered-merge trick the reference's
    # MergingDigest uses to keep tail centroids tight
    m, w = _compress(vals, ws, 2 * compression)
    m, w = _compress(m, w, compression)
    return _serialize(compression, float(w.sum()), float(vals.min()),
                      float(vals.max()), m, w)


def tdigest_merge(blobs: Iterable[bytes]) -> bytes:
    parts = [_parse(b) for b in blobs if b]
    if not parts:
        return tdigest_from_values([])
    compression = max(p[0] for p in parts)
    means = np.concatenate([p[4] for p in parts]) if parts else np.empty(0)
    weights = np.concatenate([p[5] for p in parts]) if parts else np.empty(0)
    if len(means) == 0:
        return tdigest_from_values([], compression=compression)
    mn = min(p[2] for p in parts)
    mx = max(p[3] for p in parts)
    m, w = _compress(means, weights, compression)
    return _serialize(compression, float(w.sum()), mn, mx, m, w)


def tdigest_value_at_quantile(blob: bytes, q: float) -> Optional[float]:
    """Quantile estimate with linear interpolation between centroid
    midpoints (the reference TDigest.valueAt approach)."""
    _c, total, mn, mx, means, weights = _parse(blob)
    if total <= 0 or len(means) == 0:
        return None
    q = min(max(q, 0.0), 1.0)
    target = q * total
    # cumulative weight up to each centroid's MIDPOINT
    cum = np.cumsum(weights) - weights / 2.0
    if target <= cum[0]:
        # below the first midpoint: interpolate from the true min
        if weights[0] >= 2 and target >= 1:
            frac = (target - 0.5) / max(cum[0] - 0.5, 1e-12)
            return mn + frac * (float(means[0]) - mn)
        return mn
    if target >= cum[-1]:
        if weights[-1] >= 2 and total - target >= 1:
            frac = (target - cum[-1]) / max(
                total - 0.5 - cum[-1], 1e-12)
            return float(means[-1]) + frac * (mx - float(means[-1]))
        return mx
    i = int(np.searchsorted(cum, target, side="right")) - 1
    span = cum[i + 1] - cum[i]
    frac = (target - cum[i]) / max(span, 1e-12)
    return float(means[i] + frac * (means[i + 1] - means[i]))


def tdigest_quantile_at_value(blob: bytes, value: float) -> Optional[float]:
    _c, total, mn, mx, means, weights = _parse(blob)
    if total <= 0 or len(means) == 0:
        return None
    if value <= mn:
        return 0.0
    if value >= mx:
        return 1.0
    cum = np.cumsum(weights) - weights / 2.0
    i = int(np.searchsorted(means, value, side="right"))
    if i == 0:
        frac = (value - mn) / max(float(means[0]) - mn, 1e-12)
        return float(frac * cum[0] / total)
    if i >= len(means):
        frac = (value - float(means[-1])) / max(mx - float(means[-1]),
                                                1e-12)
        return float((cum[-1] + frac * (total - cum[-1])) / total)
    span = float(means[i] - means[i - 1])
    frac = (value - float(means[i - 1])) / max(span, 1e-12)
    return float((cum[i - 1] + frac * (cum[i] - cum[i - 1])) / total)


def tdigest_scale(blob: bytes, factor: float) -> bytes:
    """Multiply every weight (reference: scale_tdigest)."""
    if factor <= 0:
        raise ValueError("scale factor must be positive")
    compression, total, mn, mx, means, weights = _parse(blob)
    return _serialize(compression, total * factor, mn, mx, means,
                      np.asarray(weights) * factor)


def tdigest_destructure(blob: bytes):
    """(means, weights, compression, min, max, total) — the reference's
    destructure_tdigest row."""
    compression, total, mn, mx, means, weights = _parse(blob)
    return (list(map(float, means)), list(map(float, weights)),
            compression, mn, mx, total)
