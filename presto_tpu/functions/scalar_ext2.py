"""Scalar function batch 3 (round-5 breadth push).

Reference parity: the remaining presto-main/.../operator/scalar/ surface
that rounds 2-4 skipped — MathFunctions' inverse-CDF family and
cosine_similarity, the volatile functions (MathFunctions.random,
UuidFunction, ArrayShuffleFunction) whose non-determinism the engine
models with a per-query cache nonce (exec/executor._volatile_nonce),
StringFunctions.splitToMap/splitToMultimap/strrpos, WordStemFunction,
KeySamplingPercentFunction, ColorFunctions (color/rgb/render/bar — the
COLOR type trims to BIGINT codes here), the array long tail
(ArrayFrequency/CumSum/Normalize/SortDesc, CombinationsFunction,
NgramsFunction, ZipFunction), and the map long tail (MapZipWith,
MultimapFromEntries, MapSubset, RemoveNullValues, MapNormalize, the
keys/values-match family).

Conventions follow scalar.py: dictionary values transform on host per
UNIQUE entry, numeric kernels are jnp elementwise, strict NULL
propagation unless the reference says otherwise.
"""

from __future__ import annotations

import math
import os as _os
import uuid as _uuid

import jax.numpy as jnp
import numpy as np
from jax.scipy import special as _sp

from presto_tpu import session_ctx, types as T
from presto_tpu.batch import Dictionary
from presto_tpu.exec.colval import ColVal, all_valid
from presto_tpu.functions.scalar import (
    _arr_entries,
    _array_transform,
    _check_lambda,
    _colval_from_pylist,
    _dict_lut_result,
    _fn_ret,
    _is_array,
    _is_function,
    _is_map,
    _map_sort,
    _map_value_fn,
    _pair_codes,
    _pylist_from_colval,
    _tuple_dict_normalize,
    register,
)
from presto_tpu.functions.scalar_ext import _mathNd

# ---------------------------------------------------------------------------
# volatile functions (reference: FunctionMetadata deterministic=false;
# the compiled-program caches key volatile queries per execution)
# ---------------------------------------------------------------------------


def _fresh_rng() -> np.random.Generator:
    return np.random.default_rng(int.from_bytes(_os.urandom(8), "little"))


def _rows() -> int:
    cap = session_ctx.batch_capacity()
    return int(cap) if cap else 1


def _resolve_random(args):
    if not args:
        return T.DOUBLE
    if len(args) == 1 and args[0].is_integer:
        return args[0]
    return None


def _emit_random(args):
    """random() -> [0,1) DOUBLE per row; random(n) -> [0,n) integer
    (reference: MathFunctions.random).  Values are drawn on host at
    trace time — per-query freshness comes from the volatile cache
    nonce, per-row freshness from drawing batch_capacity values."""
    n = _rows()
    rng = _fresh_rng()
    if not args:
        vals = rng.random(n)
        data = jnp.asarray(vals) if n > 1 else jnp.asarray(vals[0])
        return ColVal(data, None, T.DOUBLE)
    bound = args[0]
    b = bound.data
    if hasattr(b, "shape") and getattr(b, "ndim", 0) > 0:
        raise NotImplementedError("random(n) needs a constant bound")
    b = int(b.item() if hasattr(b, "item") else b)
    if b <= 0:
        raise ValueError("bound must be positive")
    vals = rng.integers(0, b, size=n)
    data = jnp.asarray(vals.astype(bound.type.numpy_dtype()))
    if n == 1:
        data = data[0]
    return ColVal(data, bound.valid, bound.type)


register("random")((_resolve_random, _emit_random))
register("rand")((_resolve_random, _emit_random))


def _emit_uuid(args):
    n = _rows()
    if n > 200_000:
        raise NotImplementedError(
            "uuid() over very large batches is not supported")
    vals = np.empty(n, dtype=object)
    vals[:] = [str(_uuid.uuid4()) for _ in range(n)]
    codes = jnp.arange(n, dtype=jnp.int32) if n > 1 \
        else jnp.asarray(0, jnp.int32)
    return ColVal(codes, None, T.VARCHAR, Dictionary(vals))


register("uuid")((lambda args: T.VARCHAR if not args else None, _emit_uuid))


def _emit_shuffle(args):
    rng = _fresh_rng()

    def fn(v):
        out = list(v)
        rng.shuffle(out)
        return tuple(out)

    return _array_transform("shuffle", fn)[1](args)


register("shuffle")((
    lambda args: args[0] if len(args) == 1 and _is_array(args[0]) else None,
    _emit_shuffle))


# ---------------------------------------------------------------------------
# inverse CDFs (reference: MathFunctions.inverse*Cdf).  Closed forms
# where they exist; elsewhere vectorized bracket-doubling + bisection on
# the same jax.scipy.special CDFs the forward functions use — fixed
# iteration counts keep the whole solve one fused XLA region.
# ---------------------------------------------------------------------------


def _bisect(cdf, p, lo, hi, iters=56):
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.float64), p.shape)
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.float64), p.shape)
    for _ in range(iters):
        mid = 0.5 * (lo + hi)
        below = cdf(mid) < p
        lo = jnp.where(below, mid, lo)
        hi = jnp.where(below, hi, mid)
    return 0.5 * (lo + hi)


def _grow_hi(cdf, p, start=1.0, doublings=36):
    hi = jnp.full(p.shape, start, jnp.float64)
    for _ in range(doublings):
        hi = jnp.where(cdf(hi) < p, hi * 2.0, hi)
    return hi


def _guard_p(p, v):
    return jnp.where((p >= 0.0) & (p <= 1.0), v, jnp.nan)


def _inv_beta(a, b, p):
    return _guard_p(p, _bisect(lambda v: _sp.betainc(a, b, v), p, 0.0, 1.0))


def _inv_chi2(df, p):
    hi = _grow_hi(lambda v: _sp.gammainc(df / 2.0, v / 2.0), p)
    return _guard_p(p, _bisect(
        lambda v: _sp.gammainc(df / 2.0, v / 2.0), p, 0.0, hi))


def _inv_gamma(shape, scale, p):
    hi = _grow_hi(lambda v: _sp.gammainc(shape, v / scale), p)
    return _guard_p(p, _bisect(
        lambda v: _sp.gammainc(shape, v / scale), p, 0.0, hi))


def _inv_f(d1, d2, p):
    def cdf(v):
        return _sp.betainc(d1 / 2, d2 / 2,
                           jnp.clip(d1 * v / (d1 * v + d2), 0.0, 1.0))

    hi = _grow_hi(cdf, p)
    return _guard_p(p, _bisect(cdf, p, 0.0, hi))


register("inverse_beta_cdf")(_mathNd("inverse_beta_cdf", 3, _inv_beta))
register("inverse_chi_squared_cdf")(_mathNd(
    "inverse_chi_squared_cdf", 2, _inv_chi2))
register("inverse_gamma_cdf")(_mathNd("inverse_gamma_cdf", 3, _inv_gamma))
register("inverse_f_cdf")(_mathNd("inverse_f_cdf", 3, _inv_f))
register("inverse_laplace_cdf")(_mathNd(
    "inverse_laplace_cdf", 3,
    lambda mean, scale, p: _guard_p(p, jnp.where(
        p < 0.5, mean + scale * jnp.log(2.0 * p),
        mean - scale * jnp.log(2.0 - 2.0 * p)))))
register("inverse_logistic_cdf")(_mathNd(
    "inverse_logistic_cdf", 3,
    lambda mean, scale, p: _guard_p(
        p, mean + scale * jnp.log(p / (1.0 - p)))))
register("inverse_weibull_cdf")(_mathNd(
    "inverse_weibull_cdf", 3,
    lambda a, b, p: _guard_p(
        p, b * jnp.power(-jnp.log1p(-p), 1.0 / a))))


def _disc_inverse(cdf_at, p, hi0):
    """Smallest integer k with CDF(k) >= p (discrete inverses)."""
    lo = jnp.zeros(p.shape, jnp.float64)
    hi = jnp.broadcast_to(jnp.asarray(hi0, jnp.float64), p.shape)
    for _ in range(40):
        mid = jnp.floor(0.5 * (lo + hi))
        below = cdf_at(mid) < p
        lo = jnp.where(below, mid + 1.0, lo)
        hi = jnp.where(below, hi, mid)
    return lo


def _inv_poisson(lam, p):
    hi = lam + 12.0 * jnp.sqrt(lam) + 64.0
    k = _disc_inverse(lambda m: _sp.gammaincc(m + 1.0, lam), p, hi)
    return _guard_p(p, k)


def _inv_binomial(n, sp_, p):
    def cdf(m):
        return jnp.where(
            m >= n, 1.0,
            1.0 - _sp.betainc(jnp.maximum(m + 1.0, 1e-30),
                              jnp.maximum(n - m, 1e-30), sp_))

    return _guard_p(p, _disc_inverse(cdf, p, n))


register("inverse_poisson_cdf")(_mathNd(
    "inverse_poisson_cdf", 2, _inv_poisson))
register("inverse_binomial_cdf")(_mathNd(
    "inverse_binomial_cdf", 3, _inv_binomial))


# ---------------------------------------------------------------------------
# cosine_similarity over sparse MAP(VARCHAR, DOUBLE) vectors
# (reference: MathFunctions.cosineSimilarity)
# ---------------------------------------------------------------------------


def _pairwise_dict_fn(name, fn, rt):
    """2-dictionary-column function evaluated per unique value pair."""

    def emit(args):
        a, b = args
        uniq, inv, scalar, _n = _pair_codes(args)
        av, bv = _arr_entries(a), _arr_entries(b)
        outs = []
        for ca, cb in uniq:
            if int(ca) < 0 or int(cb) < 0:
                outs.append(None)
                continue
            try:
                outs.append(fn(av[int(ca)] if int(ca) < len(av) else (),
                               bv[int(cb)] if int(cb) < len(bv) else ()))
            except (ValueError, TypeError, ZeroDivisionError):
                outs.append(None)
        codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
            else jnp.asarray(inv.astype(np.int32))
        return _dict_lut_result(outs, ColVal(codes, all_valid(a, b), rt), rt)

    return emit


def _cosine(m1, m2):
    d1, d2 = dict(m1), dict(m2)
    n1 = math.sqrt(sum(v * v for v in d1.values()))
    n2 = math.sqrt(sum(v * v for v in d2.values()))
    if n1 == 0.0 or n2 == 0.0:
        return None
    dot = sum(v * d2.get(k, 0.0) for k, v in d1.items())
    return dot / (n1 * n2)


register("cosine_similarity")((
    lambda args: T.DOUBLE if len(args) == 2 and all(_is_map(a) for a in args)
    else None,
    _pairwise_dict_fn("cosine_similarity", _cosine, T.DOUBLE)))


# ---------------------------------------------------------------------------
# string long tail
# ---------------------------------------------------------------------------


def _strrpos(s, sub, instance=1):
    """1-based position of the instance'th occurrence from the END
    (reference: StringFunctions.stringReversePosition)."""
    inst = int(instance)
    if inst <= 0:
        raise ValueError("strrpos instance must be positive")
    if not sub:
        return 0
    pos, found = len(s), 0
    while found < inst:
        pos = s.rfind(sub, 0, pos)
        if pos < 0:
            return 0
        found += 1
    return pos + 1


def _str_fn(name, fn, rt, nargs=(1, 2, 3)):
    """String-first function with constant extra args over dictionary
    values (the _array_transform convention, string flavor)."""

    def resolve(args):
        return rt if args and args[0].is_string \
            and len(args) in (nargs if isinstance(nargs, tuple) else (nargs,)) \
            else None

    def emit(args):
        col = args[0]
        extra = []
        for a in args[1:]:
            v = a.data
            if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0:
                raise NotImplementedError(
                    f"{name} with non-constant arguments")
            if a.dictionary is not None:
                v = a.dictionary.values[int(v)]
            elif hasattr(v, "item"):
                v = v.item()
            extra.append(v)
        if col.dictionary is None and isinstance(col.data, (str, bytes)):
            # string literal: fold through a single-entry dictionary
            vals = np.empty(1, dtype=object)
            vals[0] = col.data
            col = ColVal(jnp.asarray(0, jnp.int32), col.valid, col.type,
                         Dictionary(vals))
        vals = col.dictionary.values if col.dictionary is not None \
            else np.empty(0, object)
        outs = []
        for v in vals:
            try:
                outs.append(fn(str(v), *extra))
            except (ValueError, TypeError, IndexError):
                outs.append(None)
        return _dict_lut_result(outs, ColVal(col.data, col.valid, rt), rt)

    return resolve, emit


register("strrpos")(_str_fn("strrpos", _strrpos, T.BIGINT, (2, 3)))


def _split_to_map(s, entry_d, kv_d):
    out = {}
    if s:
        for part in s.split(entry_d):
            k, sep, v = part.partition(kv_d)
            if not sep:
                raise ValueError(f"key-value delimiter missing in {part!r}")
            if k in out:
                raise ValueError(f"duplicate key {k!r} in split_to_map")
            out[k] = v
    return _map_sort(out.items())


def _split_to_multimap(s, entry_d, kv_d):
    out: dict = {}
    if s:
        for part in s.split(entry_d):
            k, sep, v = part.partition(kv_d)
            if not sep:
                raise ValueError(f"key-value delimiter missing in {part!r}")
            out.setdefault(k, []).append(v)
    return _map_sort((k, tuple(v)) for k, v in out.items())


register("split_to_map")(_str_fn(
    "split_to_map", _split_to_map, T.map_of(T.VARCHAR, T.VARCHAR), 3))
register("split_to_multimap")(_str_fn(
    "split_to_multimap", _split_to_multimap,
    T.map_of(T.VARCHAR, T.array_of(T.VARCHAR)), 3))


def _fnv64(b: bytes) -> int:
    h = 0xCBF29CE484222325
    for c in b:
        h ^= c
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _ksp(s):
    # the engine's xxhash64 scalar lives as a jnp kernel; host-side here
    # a 64-bit FNV-1a stands in (same bucketing contract: deterministic,
    # uniform; documented deviation from the reference's xxHash64)
    return (_fnv64(str(s).encode("utf-8")) % 100) / 100.0


register("key_sampling_percent")(_str_fn(
    "key_sampling_percent", _ksp, T.DOUBLE, 1))


# ---- word_stem: Porter stemmer (reference: WordStemFunction over
# lucene's snowball English stemmer; the classic Porter algorithm) ----

_VOWELS = "aeiou"


def _is_cons(w, i):
    c = w[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(w, i - 1)
    return True


def _measure(w):
    m, i, n = 0, 0, len(w)
    while i < n and _is_cons(w, i):
        i += 1
    while i < n:
        while i < n and not _is_cons(w, i):
            i += 1
        if i >= n:
            break
        m += 1
        while i < n and _is_cons(w, i):
            i += 1
    return m


def _has_vowel(w):
    return any(not _is_cons(w, i) for i in range(len(w)))


def _ends_cvc(w):
    if len(w) < 3:
        return False
    if not (_is_cons(w, -3 + len(w)) and not _is_cons(w, len(w) - 2)
            and _is_cons(w, len(w) - 1)):
        return False
    return w[-1] not in "wxy"


def _porter(word: str) -> str:
    w = word.lower()
    if len(w) <= 2:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]
    # step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and _has_vowel(w[:-2]):
        w, flag = w[:-2], True
    elif w.endswith("ing") and _has_vowel(w[:-3]):
        w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif len(w) >= 2 and w[-1] == w[-2] and _is_cons(w, len(w) - 1) \
                and w[-1] not in "lsz":
            w = w[:-1]
        elif _measure(w) == 1 and _ends_cvc(w):
            w += "e"
    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in (("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
                     ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
                     ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                     ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
                     ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
                     ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
                     ("iviti", "ive"), ("biliti", "ble")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 3
    for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                     ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                     ("ness", "")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                "ement", "ment", "ent", "ou", "ism", "ate", "iti", "ous",
                "ive", "ize"):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 1:
                w = w[:-len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" \
                and _measure(w[:-3]) > 1:
            w = w[:-3]
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = _measure(stem)
        if m > 1 or (m == 1 and not _ends_cvc(stem)):
            w = stem
    # step 5b
    if len(w) >= 2 and w.endswith("ll") and _measure(w) > 1:
        w = w[:-1]
    return w


def _word_stem(s, lang="en"):
    if lang != "en":
        raise ValueError(f"unsupported stemmer language: {lang}")
    return _porter(s)


register("word_stem")(_str_fn("word_stem", _word_stem, T.VARCHAR, (1, 2)))


# ---------------------------------------------------------------------------
# color functions (reference: operator/scalar/ColorFunctions.java; the
# COLOR type trims to a BIGINT code — negative = ANSI system color,
# else packed 24-bit rgb)
# ---------------------------------------------------------------------------

_ANSI_COLORS = {"black": 1, "red": 2, "green": 3, "yellow": 4, "blue": 5,
                "magenta": 6, "cyan": 7, "white": 8}


def _parse_color(s):
    s = str(s).strip().lower()
    if s.startswith("#") and len(s) == 4:
        r, g, b = (int(c, 16) * 17 for c in s[1:])
        return (r << 16) | (g << 8) | b
    if s in _ANSI_COLORS:
        return -_ANSI_COLORS[s]
    raise ValueError(f"invalid color: {s!r}")


register("color")(_str_fn("color", _parse_color, T.BIGINT, 1))


def _rgb(r, g, b):
    for v in (r, g, b):
        if not 0 <= v <= 255:
            raise ValueError("rgb component out of [0,255]")
    return (int(r) << 16) | (int(g) << 8) | int(b)


register("rgb")((
    lambda args: T.BIGINT if len(args) == 3
    and all(a.is_integer for a in args) else None,
    lambda args: _int3_host("rgb", _rgb, args)))


def _int3_host(name, fn, args):
    datas = []
    for a in args:
        v = a.data
        if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0:
            raise NotImplementedError(f"{name} over column values")
        datas.append(int(v.item() if hasattr(v, "item") else v))
    return ColVal(jnp.asarray(fn(*datas), jnp.int64), all_valid(*args),
                  T.BIGINT)


def _ansi_for(code: int) -> str:
    if code < 0:
        return f"\x1b[3{-code - 1}m"
    r, g, b = (code >> 16) & 255, (code >> 8) & 255, code & 255
    n = 16 + 36 * (r * 6 // 256) + 6 * (g * 6 // 256) + (b * 6 // 256)
    return f"\x1b[38;5;{n}m"


def _resolve_render(args):
    if len(args) == 1 and args[0].name == "BOOLEAN":
        return T.VARCHAR
    if len(args) == 2 and args[1].is_integer:
        return T.VARCHAR
    return None


def _emit_render(args):
    if len(args) == 1:  # render(boolean) -> colored check mark / cross
        b = args[0]
        vals = np.asarray(["\x1b[31m✘\x1b[0m", "\x1b[32m✔\x1b[0m"],
                          dtype=object)
        codes = jnp.asarray(b.data, jnp.int32)
        return ColVal(codes, b.valid, T.VARCHAR, Dictionary(vals))
    v, c = args
    code = c.data
    if hasattr(code, "shape") and getattr(code, "ndim", 0) > 0:
        raise NotImplementedError("render with a non-constant color")
    prefix = _ansi_for(int(code.item() if hasattr(code, "item") else code))
    if v.type.is_string:
        if v.dictionary is None and isinstance(v.data, (str, bytes)):
            d = np.empty(1, dtype=object)
            d[0] = v.data
            v = ColVal(jnp.asarray(0, jnp.int32), v.valid, v.type,
                       Dictionary(d))
        vals = v.dictionary.values if v.dictionary is not None \
            else np.empty(0, object)
        outs = [f"{prefix}{s}\x1b[0m" for s in vals]
        return _dict_lut_result(outs, ColVal(v.data, all_valid(v, c),
                                             T.VARCHAR), T.VARCHAR)
    raise NotImplementedError("render over non-string values")


register("render")((_resolve_render, _emit_render))


def _bar(x, width, low=-(_ANSI_COLORS["red"]), high=-(_ANSI_COLORS["green"])):
    x = min(max(float(x), 0.0), 1.0)
    width = int(width)
    if width < 0:
        raise ValueError("bar width must be >= 0")
    n = int(round(x * width))
    out = []
    for i in range(n):
        frac = i / max(n - 1, 1)
        if int(low) < 0 and int(high) < 0:
            code = int(low) if frac < 0.5 else int(high)
        else:
            lr, lg, lb = (int(low) >> 16) & 255, (int(low) >> 8) & 255, \
                int(low) & 255
            hr, hg, hb = (int(high) >> 16) & 255, (int(high) >> 8) & 255, \
                int(high) & 255
            code = _rgb(int(lr + (hr - lr) * frac),
                        int(lg + (hg - lg) * frac),
                        int(lb + (hb - lb) * frac))
        out.append(_ansi_for(code) + "█")
    return "".join(out) + "\x1b[0m" + " " * (width - n)


def _resolve_bar(args):
    return T.VARCHAR if len(args) in (2, 4) and args[0].is_numeric else None


def _emit_bar(args):
    datas = []
    for a in args:
        v = a.data
        if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0:
            raise NotImplementedError("bar over column values")
        datas.append(v.item() if hasattr(v, "item") else v)
    s = _bar(*datas)
    vals = np.empty(1, dtype=object)
    vals[0] = s
    return ColVal(jnp.asarray(0, jnp.int32), all_valid(*args), T.VARCHAR,
                  Dictionary(vals))


register("bar")((_resolve_bar, _emit_bar))


# ---------------------------------------------------------------------------
# array long tail
# ---------------------------------------------------------------------------


def _freq(v):
    out: dict = {}
    for e in v:
        if e is not None:
            out[e] = out.get(e, 0) + 1
    return _map_sort(out.items())


def _emit_array_frequency(args):
    rt = T.map_of(args[0].type.params[0], T.BIGINT)
    vals = [_freq(tuple(v)) for v in _arr_entries(args[0])]
    return _dict_lut_result(vals, ColVal(args[0].data, args[0].valid, rt),
                            rt)


register("array_frequency")((
    lambda args: T.map_of(args[0].params[0], T.BIGINT)
    if len(args) == 1 and _is_array(args[0]) else None,
    _emit_array_frequency))


def _cum_sum(v):
    out, acc, dead = [], 0, False
    for e in v:
        if e is None or dead:
            out.append(None)
            dead = True  # reference: elements after a NULL are NULL
        else:
            acc += e
            out.append(acc)
    return tuple(out)


register("array_cum_sum")((
    lambda args: args[0] if len(args) == 1 and _is_array(args[0])
    and args[0].params[0].is_numeric else None,
    _array_transform("array_cum_sum", _cum_sum)[1]))


def _normalize_arr(v, p):
    p = float(p)
    if p < 0:
        raise ValueError("array_normalize requires p >= 0")
    if any(e is None for e in v):
        return None
    if p == 0:
        return tuple(v)
    norm = sum(abs(e) ** p for e in v) ** (1.0 / p)
    if norm == 0:
        return tuple(v)
    return tuple(e / norm for e in v)


register("array_normalize")((
    lambda args: args[0] if len(args) == 2 and _is_array(args[0])
    and args[0].params[0].is_floating else None,
    _array_transform("array_normalize", _normalize_arr)[1]))

register("array_sort_desc")((_array_transform(
    "array_sort_desc",
    lambda v: tuple(sorted((e for e in v if e is not None), reverse=True))
    + tuple(None for e in v if e is None))))


def _combinations(v, n):
    import itertools as _it

    n = int(n)
    if n < 0 or n > 5:
        raise ValueError("combinations n must be in [0, 5]")
    return tuple(tuple(c) for c in _it.combinations(v, n))


register("combinations")((
    lambda args: T.array_of(args[0]) if len(args) == 2
    and _is_array(args[0]) else None,
    _array_transform("combinations", _combinations)[1]))


def _ngrams(v, n):
    n = int(n)
    if n <= 0:
        raise ValueError("ngrams n must be positive")
    if n >= len(v):
        return (tuple(v),)
    return tuple(tuple(v[i:i + n]) for i in range(len(v) - n + 1))


register("ngrams")((
    lambda args: T.array_of(args[0]) if len(args) == 2
    and _is_array(args[0]) else None,
    _array_transform("ngrams", _ngrams)[1]))


def _resolve_zip(args):
    if len(args) < 2 or not all(_is_array(a) for a in args):
        return None
    return T.array_of(T.row_of([(None, a.params[0]) for a in args]))


def _emit_zip(args):
    rt = _resolve_zip([a.type for a in args])
    uniq, inv, scalar, _n = _pair_codes(args)
    entr = [_arr_entries(a) for a in args]
    outs = np.empty(max(len(uniq), 1), dtype=object)
    outs[:] = [()] * len(outs)
    for i, combo in enumerate(uniq):
        if any(int(c) < 0 for c in combo):
            continue
        tups = [entr[j][int(c)] if int(c) < len(entr[j]) else ()
                for j, c in enumerate(combo)]
        L = max((len(t) for t in tups), default=0)
        outs[i] = tuple(
            tuple(t[k] if k < len(t) else None for t in tups)
            for k in range(L))  # reference: zip pads shorter arrays w/ NULL
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(outs, ColVal(codes, all_valid(*args), rt),
                                 rt)


register("zip")((_resolve_zip, _emit_zip))


# ---------------------------------------------------------------------------
# map long tail
# ---------------------------------------------------------------------------

register("map_remove_null_values")((_map_value_fn(
    "map_remove_null_values",
    lambda t: tuple((k, v) for k, v in t if v is not None),
    lambda a: a[0])))

register("map_normalize")((_map_value_fn(
    "map_normalize",
    lambda t: (lambda s: tuple(
        (k, (v / s if v is not None else None)) for k, v in t))
    (sum(v for _, v in t if v is not None)),
    lambda a: a[0] if a[0].params[1].is_floating else None)))


def _map_subset_fn(t, keys):
    want = set(keys)
    return tuple((k, v) for k, v in t if k in want)


def _emit_map_subset(args):
    m, ks = args
    rt = m.type
    uniq, inv, scalar, _n = _pair_codes(args)
    mv, kv = _arr_entries(m), _arr_entries(ks)
    outs = np.empty(max(len(uniq), 1), dtype=object)
    outs[:] = [()] * len(outs)
    for i, (cm, ck) in enumerate(uniq):
        if int(cm) < 0 or int(ck) < 0:
            continue
        outs[i] = _map_subset_fn(
            mv[int(cm)] if int(cm) < len(mv) else (),
            kv[int(ck)] if int(ck) < len(kv) else ())
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(outs, ColVal(codes, all_valid(m, ks), rt),
                                 rt)


register("map_subset")((
    lambda args: args[0] if len(args) == 2 and _is_map(args[0])
    and _is_array(args[1]) else None,
    _emit_map_subset))


def _resolve_multimap_from_entries(args):
    a = args[0] if args else None
    if a is None or not _is_array(a) or a.params[0].name != "ROW":
        return None
    fields = a.params[0].params
    return T.map_of(fields[0][1], T.array_of(fields[1][1]))


def _mm_from_entries(v):
    out: dict = {}
    for pair in v:
        if pair is None:
            raise ValueError("map entry cannot be null")
        k, val = pair
        if k is None:
            raise ValueError("map key cannot be null")
        out.setdefault(k, []).append(val)
    return _map_sort((k, tuple(vs)) for k, vs in out.items())


def _safe_mm(v):
    try:
        return _mm_from_entries(v)
    except (ValueError, TypeError):
        return None


def _emit_multimap_from_entries(args):
    rt = _resolve_multimap_from_entries([args[0].type])
    vals = [_safe_mm(tuple(v)) for v in _arr_entries(args[0])]
    return _dict_lut_result(vals, ColVal(args[0].data, args[0].valid, rt),
                            rt)


register("multimap_from_entries")((
    _resolve_multimap_from_entries, _emit_multimap_from_entries))


def _emit_map_zip_with(args):
    m1, m2, lam = args
    _check_lambda(lam, "map_zip_with")
    rt = T.map_of(m1.type.params[0], lam.ret_type)
    uniq, inv, scalar, _n = _pair_codes([m1, m2])
    e1, e2 = _arr_entries(m1), _arr_entries(m2)
    # flatten the unioned key space of every combo for ONE lambda apply
    combo_keys, flat_k, flat_v1, flat_v2 = [], [], [], []
    for ca, cb in uniq:
        if int(ca) < 0 or int(cb) < 0:
            combo_keys.append(None)
            continue
        d1 = dict(e1[int(ca)]) if int(ca) < len(e1) else {}
        d2 = dict(e2[int(cb)]) if int(cb) < len(e2) else {}
        keys = sorted(set(d1) | set(d2), key=repr)
        combo_keys.append(keys)
        for k in keys:
            flat_k.append(k)
            flat_v1.append(d1.get(k))
            flat_v2.append(d2.get(k))
    if flat_k:
        kc = _colval_from_pylist(flat_k, lam.param_types[0])
        v1c = _colval_from_pylist(flat_v1, lam.param_types[1])
        v2c = _colval_from_pylist(flat_v2, lam.param_types[2])
        res = _pylist_from_colval(
            lam.apply({lam.params[0]: kc, lam.params[1]: v1c,
                       lam.params[2]: v2c}), len(flat_k))
    else:
        res = []
    outs = np.empty(max(len(uniq), 1), dtype=object)
    outs[:] = [()] * len(outs)
    off = 0
    for i, keys in enumerate(combo_keys):
        if keys is None:
            continue
        window = res[off:off + len(keys)]
        off += len(keys)
        outs[i] = _map_sort(zip(keys, window))
    codes = jnp.asarray(int(inv[0]), jnp.int32) if scalar \
        else jnp.asarray(inv.astype(np.int32))
    return _tuple_dict_normalize(
        outs, ColVal(codes, all_valid(m1, m2), rt), rt)


register("map_zip_with")((
    lambda args: T.map_of(args[0].params[0], _fn_ret(args[2]))
    if len(args) == 3 and _is_map(args[0]) and _is_map(args[1])
    and _is_function(args[2]) else None,
    _emit_map_zip_with))


def _emit_keys_values_match(name, which, quantifier):
    def emit(args):
        col, lam = args
        _check_lambda(lam, name)
        entries = _arr_entries(col)
        lens = [len(t) for t in entries]
        flat = [(k if which == "keys" else v)
                for t in entries for k, v in t]
        if flat:
            ptype = lam.param_types[0]
            res = _pylist_from_colval(
                lam.apply({lam.params[0]:
                           _colval_from_pylist(flat, ptype)}), len(flat))
        else:
            res = []
        outs = []
        off = 0
        for L in lens:
            window = [bool(r) if r is not None else None
                      for r in res[off:off + L]]
            off += L
            if quantifier == "all":
                v = (False if any(r is False for r in window)
                     else (None if any(r is None for r in window) else True))
            elif quantifier == "any":
                v = (True if any(r is True for r in window)
                     else (None if any(r is None for r in window)
                           else False))
            else:  # none
                v = (False if any(r is True for r in window)
                     else (None if any(r is None for r in window) else True))
            outs.append(v)
        return _dict_lut_result(outs, ColVal(col.data, col.valid,
                                             T.BOOLEAN), T.BOOLEAN)

    return emit


# ---------------------------------------------------------------------------
# ARRAY/ROW ordering comparisons (reference: ArrayLessThanOperator +
# RowComparisonOperator family).  The dictionary CODES are canonical-
# repr-ordered, not semantically ordered, so </<=/>/>= over collection
# columns must compare the VALUES pairwise (python tuple comparison is
# exactly elementwise-lexicographic with prefix ordering); a NULL
# element makes the comparison NULL (the reference throws).
# ---------------------------------------------------------------------------


def _is_orderable_collection(t) -> bool:
    return t is not None and t.name in ("ARRAY", "ROW")


def _wrap_collection_cmp(name, pyop):
    from presto_tpu.functions.scalar import REGISTRY as _R

    old = _R[name]

    def resolve(args):
        if len(args) == 2 and all(_is_orderable_collection(a)
                                  for a in args):
            return T.BOOLEAN
        return old.resolve(args)

    def fn(x, y):
        return pyop(tuple(x), tuple(y))

    pair_emit = _pairwise_dict_fn(name, fn, T.BOOLEAN)

    def emit(args):
        if len(args) == 2 and all(
                _is_orderable_collection(a.type) for a in args):
            return pair_emit(args)
        return old.emit(args)

    register(name)((resolve, emit))


for _cmp_name, _op in (("lt", lambda x, y: x < y),
                       ("le", lambda x, y: x <= y),
                       ("gt", lambda x, y: x > y),
                       ("ge", lambda x, y: x >= y)):
    _wrap_collection_cmp(_cmp_name, _op)


# ---------------------------------------------------------------------------
# IS [NOT] DISTINCT FROM (reference: the distinct_from operator family —
# null-safe comparison that never returns NULL)
# ---------------------------------------------------------------------------


def _resolve_distinct_from(args):
    if len(args) != 2:
        return None
    from presto_tpu.functions.scalar import REGISTRY as _R

    return T.BOOLEAN if _R["eq"].resolve(args) is not None \
        or T.UNKNOWN in (args[0], args[1]) else None


def _emit_distinct_from(args):
    from presto_tpu.functions.scalar import REGISTRY as _R

    a, b = args

    def validity(c):
        if c.valid is None:
            return jnp.asarray(True)
        return jnp.asarray(c.valid)

    av, bv = validity(a), validity(b)
    if a.type == T.UNKNOWN or b.type == T.UNKNOWN:
        # a literal NULL operand: distinct iff the other side is
        # non-null (both-null is NOT distinct)
        return ColVal(av | bv, None, T.BOOLEAN)
    eqv = _R["eq"].emit([a, b])
    eq_data = jnp.asarray(eqv.data)
    one_null = av ^ bv
    both_valid = av & bv
    out = one_null | (both_valid & ~eq_data)
    return ColVal(out, None, T.BOOLEAN)


register("is_distinct_from")((_resolve_distinct_from,
                              _emit_distinct_from))


# ---------------------------------------------------------------------------
# comparator / lambda overloads of existing functions, and the data-size
# parser (reference: ArraySortComparatorFunction,
# JoniRegexpReplaceLambdaFunction, DataSizeFunctions)
# ---------------------------------------------------------------------------

from presto_tpu.functions.scalar import REGISTRY as _REG  # noqa: E402


def _wrap_array_sort():
    old = _REG["array_sort"]

    def resolve(args):
        if len(args) == 2 and _is_array(args[0]) \
                and _is_function(args[1]):
            return args[0]
        return old.resolve(args)

    def emit(args):
        from presto_tpu.exec.colval import LambdaVal

        if len(args) == 2 and isinstance(args[1], LambdaVal):
            return _emit_array_sort_cmp(args)
        return old.emit(args)

    register("array_sort")((resolve, emit))


def _emit_array_sort_cmp(args):
    """array_sort(a, (x, y) -> cmp): all intra-array pairs evaluate in
    ONE vectorized lambda apply, then a host sort consults the
    precomputed comparisons (reference: ArraySortComparatorFunction)."""
    import functools

    col, lam = args
    _check_lambda(lam, "array_sort")
    entries = _arr_entries(col)
    xs, ys, owners = [], [], []
    for ei, t in enumerate(entries):
        for i in range(len(t)):
            for j in range(i + 1, len(t)):
                xs.append(t[i])
                ys.append(t[j])
                owners.append((ei, i, j))
    if xs:
        et = lam.param_types[0]
        res = _pylist_from_colval(
            lam.apply({lam.params[0]: _colval_from_pylist(xs, et),
                       lam.params[1]: _colval_from_pylist(ys, et)}),
            len(xs))
    else:
        res = []
    cmps: dict = {}
    for (ei, i, j), r in zip(owners, res):
        cmps[(ei, i, j)] = 0 if r is None else int(r)
    outs = []
    for ei, t in enumerate(entries):
        def cmp(i, j, _ei=ei):
            if i == j:
                return 0
            if i < j:
                return cmps.get((_ei, i, j), 0)
            return -cmps.get((_ei, j, i), 0)

        order = sorted(range(len(t)), key=functools.cmp_to_key(cmp))
        outs.append(tuple(t[i] for i in order))
    return _dict_lut_result(outs, ColVal(col.data, col.valid, col.type),
                            col.type)


_wrap_array_sort()


def _wrap_regexp_replace():
    import re as _re

    old = _REG["regexp_replace"]

    def resolve(args):
        if len(args) == 3 and args[0].is_string \
                and _is_function(args[2]):
            return T.VARCHAR
        return old.resolve(args)

    def emit(args):
        from presto_tpu.exec.colval import LambdaVal

        if len(args) == 3 and isinstance(args[2], LambdaVal):
            return _emit_regexp_replace_lambda(args, _re)
        return old.emit(args)

    register("regexp_replace")((resolve, emit))


def _emit_regexp_replace_lambda(args, _re):
    """regexp_replace(s, p, groups -> r): every match's capturing-group
    array across every distinct string feeds ONE vectorized lambda
    apply; NULL lambda results drop the match (reference:
    JoniRegexpReplaceLambdaFunction)."""
    col, pat, lam = args
    _check_lambda(lam, "regexp_replace")
    p = pat.data
    if pat.dictionary is not None:
        p = pat.dictionary.values[int(p)]
    rx = _re.compile(str(p))
    if col.dictionary is None and isinstance(col.data, (str, bytes)):
        vals_in = [str(col.data)]
        codes = jnp.asarray(0, jnp.int32)
    else:
        vals_in = [str(v) for v in _arr_entries_str(col)]
        codes = col.data
    per_string = []  # list of (spans, n_matches)
    flat_groups = []
    for s in vals_in:
        ms = list(rx.finditer(s))
        per_string.append(ms)
        for m in ms:
            flat_groups.append(tuple(m.groups()))
    if flat_groups:
        res = _pylist_from_colval(
            lam.apply({lam.params[0]: _colval_from_pylist(
                flat_groups, T.array_of(T.VARCHAR))}), len(flat_groups))
    else:
        res = []
    outs = []
    off = 0
    for s, ms in zip(vals_in, per_string):
        parts, last = [], 0
        for m in ms:
            parts.append(s[last:m.start()])
            r = res[off]
            off += 1
            if r is not None:
                parts.append(str(r))
            last = m.end()
        parts.append(s[last:])
        outs.append("".join(parts))
    return _dict_lut_result(outs, ColVal(codes, col.valid, T.VARCHAR),
                            T.VARCHAR)


def _arr_entries_str(col):
    return col.dictionary.values if col.dictionary is not None else []


_wrap_regexp_replace()


_DATA_SIZE_UNITS = {"B": 1, "kB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30,
                    "TB": 1 << 40, "PB": 1 << 50, "EB": 1 << 60}


def _parse_data_size(s):
    import re as _re

    m = _re.fullmatch(r"\s*([\d.]+)\s*([A-Za-z]+)\s*", str(s))
    if not m or m.group(2) not in _DATA_SIZE_UNITS:
        raise ValueError(f"invalid data size: {s!r}")
    v = int(float(m.group(1)) * _DATA_SIZE_UNITS[m.group(2)])
    # reference returns DECIMAL(38,0); BIGINT covers sizes to 8EB —
    # documented trim
    return v


register("parse_presto_data_size")(_str_fn(
    "parse_presto_data_size", _parse_data_size, T.BIGINT, 1))


def _fix_array_sort_nulls_and_join():
    """array_sort puts NULLs LAST (reference: ArraySortFunction);
    array_join gains the 3-arg null-replacement form."""
    old_join = _REG["array_join"]

    def _join(v, d, nr=None):
        parts = []
        for e in v:
            if e is None:
                if nr is not None:
                    parts.append(str(nr))
            else:
                parts.append(_fmt_join(e))
        return str(d).join(parts)

    def resolve(args):
        return T.VARCHAR if args and _is_array(args[0]) \
            and len(args) in (2, 3) else None

    register("array_join")((
        resolve, _array_transform("array_join", _join, T.VARCHAR)[1]))
    _ = old_join  # superseded registration


def _fmt_join(e):
    if isinstance(e, bool):
        return "true" if e else "false"
    return str(e)


_fix_array_sort_nulls_and_join()


for _nm, _which, _q in (("all_keys_match", "keys", "all"),
                        ("any_keys_match", "keys", "any"),
                        ("no_keys_match", "keys", "none"),
                        ("any_values_match", "values", "any"),
                        ("no_values_match", "values", "none")):
    register(_nm)((
        (lambda args: T.BOOLEAN if len(args) == 2 and _is_map(args[0])
         and _is_function(args[1]) else None),
        _emit_keys_values_match(_nm, _which, _q)))


