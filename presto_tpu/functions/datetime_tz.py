"""TIME / TIMESTAMP WITH TIME ZONE function surface.

Reference: presto-main/.../operator/scalar/DateTimeFunctions.java
(at_timezone, with_timezone, zone-aware extract/date_trunc/date_add/
date_format, timezone_hour/minute), spi/type/TimestampWithTimeZoneType,
TimeWithTimeZoneType.

Design (see types.Type.tz): the zone rides the column TYPE, the device
lane is pure UTC int64 micros.  Zone-dependent functions LOCALIZE the
lane (one searchsorted over the zone's transition table, tzdb.ZoneRules)
into a plain-TIMESTAMP wall clock, reuse the existing zone-less
emitters, and — when the result is temporal — convert back.  That keeps
every civil-field algorithm (civil_from_days etc.) in exactly one place
and makes the TZ surface a thin adapter instead of a parallel
implementation.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from presto_tpu import session_ctx
from presto_tpu import types as T
from presto_tpu.exec.colval import ColVal
from presto_tpu.functions import tzdb
from presto_tpu.functions.scalar import (
    REGISTRY,
    _as_string_literal,
    register,
)

US_PER_DAY = 86_400_000_000


def _zone_of(v: ColVal) -> tzdb.ZoneRules:
    return tzdb.rules(v.type.tz or "UTC")


def _localize(v: ColVal) -> ColVal:
    """TIMESTAMP_TZ -> zone-less TIMESTAMP carrying the zone's wall
    clock (device conversion); anything else passes through."""
    if v.type.name != "TIMESTAMP_TZ":
        return v
    r = _zone_of(v)
    data = v.data if not hasattr(v.data, "shape") and v.is_scalar \
        else jnp.asarray(v.data)
    if v.is_scalar and not hasattr(v.data, "shape"):
        return ColVal(r.utc_to_local_scalar(int(v.data)), v.valid,
                      T.TIMESTAMP)
    return ColVal(r.utc_to_local(data.astype(jnp.int64)), v.valid,
                  T.TIMESTAMP)


def _delocalize(v: ColVal, zone: str) -> ColVal:
    """Zone-less wall-clock TIMESTAMP -> TIMESTAMP_TZ in `zone`."""
    r = tzdb.rules(zone)
    if v.is_scalar and not hasattr(v.data, "shape"):
        return ColVal(r.local_to_utc_scalar(int(v.data)), v.valid,
                      T.timestamp_tz(zone))
    return ColVal(r.local_to_utc(jnp.asarray(v.data).astype(jnp.int64)),
                  v.valid, T.timestamp_tz(zone))


def _zone_arg(v: ColVal) -> str:
    z = _as_string_literal(v)
    if z is None:
        raise NotImplementedError("time zone argument must be a literal")
    if not tzdb.is_valid_zone(z):
        raise ValueError(f"unknown time zone: {z!r}")
    return z


# ---- at_timezone / with_timezone -----------------------------------------


def _emit_at_timezone(args):
    v, zone = args[0], _zone_arg(args[1])
    if v.type.name == "TIMESTAMP":  # coerce via the session zone first
        v = _delocalize(v, session_ctx.current_zone())
    if v.type.name != "TIMESTAMP_TZ":
        raise NotImplementedError(f"at_timezone({v.type})")
    # same instant, new display zone: the lane is already UTC
    return ColVal(v.data, v.valid, T.timestamp_tz(zone), v.dictionary)


register("at_timezone")((
    lambda args: (T.timestamp_tz() if len(args) == 2
                  and args[0].name in ("TIMESTAMP", "TIMESTAMP_TZ")
                  and args[1].is_string else None),
    _emit_at_timezone))


def _emit_with_timezone(args):
    v, zone = args[0], _zone_arg(args[1])
    if v.type.name != "TIMESTAMP":
        raise NotImplementedError(f"with_timezone({v.type})")
    return _delocalize(v, zone)


register("with_timezone")((
    lambda args: (T.timestamp_tz() if len(args) == 2
                  and args[0].name == "TIMESTAMP"
                  and args[1].is_string else None),
    _emit_with_timezone))


# ---- session-dependent constants ------------------------------------------
# (reference: now()/current_timestamp return TIMESTAMP WITH TIME ZONE at
# the session zone and are stable across the query —
# session.getStartTime())


def _now_tz_emit(args):
    return ColVal(session_ctx.query_start_us(), None,
                  T.timestamp_tz(session_ctx.current_zone()))


register("now")((lambda args: T.timestamp_tz() if not args else None,
                 _now_tz_emit))
register("current_timestamp")((
    lambda args: T.timestamp_tz() if not args else None, _now_tz_emit))
register("localtimestamp")((
    lambda args: T.TIMESTAMP if not args else None,
    lambda args: _localize(_now_tz_emit(args))))
register("current_date")((
    lambda args: T.DATE if not args else None,
    lambda args: ColVal(
        int(_localize(_now_tz_emit(args)).data) // US_PER_DAY, None,
        T.DATE)))
register("current_timezone")((
    lambda args: T.VARCHAR if not args else None,
    lambda args: ColVal(session_ctx.current_zone(), None, T.VARCHAR)))
register("current_user")((
    lambda args: T.VARCHAR if not args else None,
    lambda args: ColVal(session_ctx.current_user(), None, T.VARCHAR)))
register("localtime")((
    lambda args: T.TIME if not args else None,
    lambda args: ColVal(
        int(_localize(_now_tz_emit(args)).data) % US_PER_DAY, None,
        T.TIME)))


def _current_time_emit(args):
    zone = session_ctx.current_zone()
    utc = session_ctx.query_start_us()
    off_us = tzdb.rules(zone).offset_at_utc_scalar(utc)
    return ColVal((utc + off_us) % US_PER_DAY, None,
                  T.time_tz(off_us // 60_000_000))


register("current_time")((
    lambda args: T.time_tz() if not args else None, _current_time_emit))


# ---- unix time ------------------------------------------------------------

register("to_unixtime")((
    lambda args: (T.DOUBLE if args
                  and args[0].name in ("TIMESTAMP", "TIMESTAMP_TZ")
                  else None),
    lambda args: ColVal(jnp.asarray(args[0].data).astype(jnp.float64) / 1e6,
                        args[0].valid, T.DOUBLE)))

_prev_from_unixtime = REGISTRY["from_unixtime"]


def _emit_from_unixtime(args):
    if len(args) == 1:
        return _prev_from_unixtime.emit(args)
    us = (jnp.asarray(args[0].data).astype(jnp.float64)
          * 1e6).astype(jnp.int64)
    if len(args) == 2:  # (unixtime, zone-string)
        return ColVal(us, args[0].valid,
                      T.timestamp_tz(_zone_arg(args[1])))
    # (unixtime, hours, minutes) fixed offset: total = hours*60+minutes
    # (reference DateTimeFunctions.fromUnixTime(double, long, long))
    total = int(np.asarray(args[1].data)) * 60 + int(np.asarray(args[2].data))
    sign = "-" if total < 0 else "+"
    return ColVal(us, args[0].valid,
                  T.timestamp_tz(
                      f"{sign}{abs(total) // 60:02d}:{abs(total) % 60:02d}"))


register("from_unixtime")((
    lambda args: (T.TIMESTAMP if len(args) == 1 and args[0].is_numeric
                  else T.timestamp_tz()
                  if (len(args) == 2 and args[0].is_numeric
                      and args[1].is_string)
                  or (len(args) == 3 and all(a.is_numeric for a in args))
                  else None),
    _emit_from_unixtime))


# ---- timezone_hour / timezone_minute --------------------------------------


def _tz_offset_us(v: ColVal):
    r = _zone_of(v)
    if v.is_scalar and not hasattr(v.data, "shape"):
        return jnp.asarray(r.offset_at_utc_scalar(int(v.data)), jnp.int64)
    data = jnp.asarray(v.data).astype(jnp.int64)
    return r.utc_to_local(data) - data


def _tz_field(divisor, mod):
    def emit(args):
        v = args[0]
        if v.type.name == "TIME_TZ":
            off_min = int(v.type.tz or 0)
            off = jnp.full(jnp.asarray(v.data).shape, off_min * 60_000_000,
                           jnp.int64) if hasattr(v.data, "shape") \
                else jnp.asarray(off_min * 60_000_000, jnp.int64)
        elif v.type.name == "TIMESTAMP_TZ":
            off = _tz_offset_us(v)
        else:
            off = jnp.zeros_like(jnp.asarray(v.data), jnp.int64)
        sign = jnp.sign(off)
        r = sign * ((jnp.abs(off) // divisor) % mod)
        return ColVal(r.astype(jnp.int64), v.valid, T.BIGINT)

    return emit


register("timezone_hour")((
    lambda args: T.BIGINT if args and args[0].name in
    ("TIMESTAMP", "TIMESTAMP_TZ", "TIME_TZ") else None,
    _tz_field(3_600_000_000, 24)))
register("timezone_minute")((
    lambda args: T.BIGINT if args and args[0].name in
    ("TIMESTAMP", "TIMESTAMP_TZ", "TIME_TZ") else None,
    _tz_field(60_000_000, 60)))


# ---- localizing adapters over the zone-less emitters ----------------------
# Every civil-field function keeps its single zone-less implementation;
# the adapter converts a TIMESTAMP_TZ argument to its wall clock first
# (and TIME/TIME_TZ to micros where the original expects TIMESTAMP).


def _wrap_localize_arg(name, arg_idx=0, relocalize_result=False):
    prev = REGISTRY.get(name)
    if prev is None:
        return
    prev_resolve, prev_emit = prev.resolve, prev.emit

    def resolve(args):
        mapped = [T.TIMESTAMP if a.name == "TIMESTAMP_TZ"
                  and i == arg_idx else a for i, a in enumerate(args)]
        r = prev_resolve(mapped)
        if r is None:
            return None
        if relocalize_result and len(args) > arg_idx \
                and args[arg_idx].name == "TIMESTAMP_TZ" \
                and r.name == "TIMESTAMP":
            return args[arg_idx]
        return r

    def emit(args):
        src = args[arg_idx] if arg_idx < len(args) else None
        if src is not None and src.type.name == "TIMESTAMP_TZ":
            largs = list(args)
            largs[arg_idx] = _localize(src)
            out = prev_emit(largs)
            if relocalize_result and out.type.name == "TIMESTAMP":
                return _delocalize(out, src.type.tz or "UTC")
            return out
        return prev_emit(args)

    REGISTRY[name].resolve = resolve
    REGISTRY[name].emit = emit


for _n in ("extract_year", "extract_month", "extract_day",
           "extract_quarter", "extract_dow", "extract_doy",
           "extract_week", "year", "month", "day", "quarter",
           "day_of_week", "day_of_month", "day_of_year", "week_of_year",
           "year_of_week", "yow", "date_format", "format_datetime",
           "to_iso8601", "to_char", "date"):
    _wrap_localize_arg(_n, 0)
for _n in ("hour", "minute", "second", "millisecond"):
    _wrap_localize_arg(_n, 0)
_wrap_localize_arg("date_trunc", 1, relocalize_result=True)
_wrap_localize_arg("date_add", 2, relocalize_result=True)
for _i in (1, 2):
    _wrap_localize_arg("date_diff", _i)


# ---- TIME field access ----------------------------------------------------
# hour/minute/second/millisecond over TIME / TIME_TZ: the lane is
# already local micros-since-midnight, so the field math is direct.


def _extend_time_fields():
    for name, div, mod in (("hour", 3_600_000_000, 24),
                           ("minute", 60_000_000, 60),
                           ("second", 1_000_000, 60),
                           ("millisecond", 1_000, 1000)):
        prev = REGISTRY[name]
        prev_resolve, prev_emit = prev.resolve, prev.emit

        def resolve(args, _pr=prev_resolve):
            if args and args[0].name in ("TIME", "TIME_TZ"):
                return T.BIGINT
            return _pr(args)

        def emit(args, _pe=prev_emit, _div=div, _mod=mod):
            v = args[0]
            if v.type.name in ("TIME", "TIME_TZ"):
                us = jnp.asarray(v.data).astype(jnp.int64)
                return ColVal(((us // _div) % _mod).astype(jnp.int64),
                              v.valid, T.BIGINT)
            return _pe(args)

        prev.resolve = resolve
        prev.emit = emit


_extend_time_fields()


# ---- casts ---------------------------------------------------------------
# (reference: DateTimeOperators / the *CastTo* operators on
# TimestampWithTimeZoneType, TimeType, TimeWithTimeZoneType)


def _session_zone_of(t: T.Type) -> str:
    return t.tz or session_ctx.current_zone()


def emit_cast_tz(v: ColVal, to: T.Type, safe: bool):
    """Cast arms for the TZ family.  Returns None for combinations the
    generic emit_cast path already handles (rendering to VARCHAR)."""
    frm = v.type
    if to.is_string:
        return None  # _cast_to_varchar renders via _render_varchar
    if frm.name == "TIMESTAMP_TZ":
        if to.name == "TIMESTAMP_TZ":
            # zone-less target (bare CAST .. AS TIMESTAMP WITH TIME
            # ZONE) is the identity — keep the VALUE's zone; only an
            # explicit target zone retags (same instant either way)
            return ColVal(v.data, v.valid,
                          frm if to.tz is None else to, v.dictionary)
        if to.name == "TIMESTAMP":
            return _localize(v)
        if to.name == "DATE":
            loc = _localize(v)
            return ColVal(
                jnp.floor_divide(jnp.asarray(loc.data), US_PER_DAY)
                .astype(jnp.int32), v.valid, T.DATE)
        if to.name == "TIME":
            loc = _localize(v)
            return ColVal(jnp.mod(jnp.asarray(loc.data), US_PER_DAY)
                          .astype(jnp.int64), v.valid, T.TIME)
        return None
    if to.name == "TIMESTAMP_TZ":
        zone = _session_zone_of(to)
        if frm.name == "TIMESTAMP":
            return _delocalize(v, zone)
        if frm.name == "DATE":
            wall = ColVal(jnp.asarray(v.data).astype(jnp.int64)
                          * US_PER_DAY if hasattr(v.data, "shape")
                          or not v.is_scalar
                          else int(v.data) * US_PER_DAY, v.valid,
                          T.TIMESTAMP)
            return _delocalize(wall, zone)
        if frm.is_string:
            return _parse_tstz_strings(v, zone, safe)
        return None
    if frm.name == "TIME":
        if to.name == "TIME_TZ":
            off = int(to.tz) if to.tz is not None else \
                tzdb.rules(session_ctx.current_zone()).offset_at_utc_scalar(
                    session_ctx.query_start_us()) // 60_000_000
            return ColVal(v.data, v.valid, T.time_tz(off), v.dictionary)
        return None
    if frm.name == "TIME_TZ" and to.name == "TIME":
        return ColVal(v.data, v.valid, T.TIME, v.dictionary)
    if to.name == "TIME" and frm.is_string:
        return _parse_time_strings(v, safe)
    return None


def _host_parse_lut(v: ColVal, parse_one, out_type: T.Type, safe: bool,
                    dtype=np.int64):
    """Parse every dictionary entry host-side into an int lane LUT."""
    from presto_tpu.functions.scalar import _lit_to_dict_colval

    if isinstance(v.data, str):
        v = _lit_to_dict_colval(v)
    vals = v.dictionary.values
    lut = np.zeros(max(len(vals), 1), dtype=dtype)
    bad = np.zeros(max(len(vals), 1), dtype=bool)
    for i, s in enumerate(vals):
        try:
            lut[i] = parse_one(str(s))
        except (ValueError, KeyError):
            if not safe:
                raise ValueError(f"cannot CAST {s!r} to {out_type}")
            bad[i] = True
    codes = jnp.clip(v.data, 0, len(lut) - 1)
    data = jnp.asarray(lut)[codes]
    valid = v.valid
    if bad.any():
        ok = ~jnp.asarray(bad)[codes]
        valid = ok if valid is None else (jnp.asarray(valid) & ok)
    return ColVal(data, valid, out_type)


def _parse_tstz_strings(v: ColVal, default_zone: str, safe: bool):
    """VARCHAR -> TIMESTAMP WITH TIME ZONE.  A zone suffix in the text
    wins; otherwise the cast-target/session zone interprets the wall
    clock.  Mixed-zone inputs collapse to the FIRST zone seen (single
    zone per column — same instant, display zone approximated)."""
    import re as _re

    zone_seen = [None]

    def parse_one(s):
        m = _re.match(
            r"^(\d{4}-\d{2}-\d{2})"
            r"(?:[ T](\d{2}:\d{2}(?::\d{2}(?:\.\d{1,6})?)?))?"
            r"(?:\s+(\S.*))?$", s.strip())
        if m is None:
            raise ValueError(s)
        civil = m.group(1) + ("T" + m.group(2) if m.group(2) else "")
        local_us = int((np.datetime64(civil)
                        - np.datetime64("1970-01-01T00:00:00"))
                       / np.timedelta64(1, "us"))
        zone = m.group(3) or default_zone
        if zone_seen[0] is None:
            zone_seen[0] = zone
        return tzdb.rules(zone).local_to_utc_scalar(local_us)

    out = _host_parse_lut(v, parse_one, T.timestamp_tz(default_zone), safe)
    return ColVal(out.data, out.valid,
                  T.timestamp_tz(zone_seen[0] or default_zone))


def _parse_time_strings(v: ColVal, safe: bool):
    import re as _re

    def parse_one(s):
        m = _re.match(r"^(\d{2}):(\d{2})(?::(\d{2})(?:\.(\d{1,6}))?)?$",
                      s.strip())
        if m is None:
            raise ValueError(s)
        frac = (m.group(4) or "").ljust(6, "0")
        return ((int(m.group(1)) * 3600 + int(m.group(2)) * 60
                 + int(m.group(3) or 0)) * 1_000_000 + int(frac or 0))

    return _host_parse_lut(v, parse_one, T.TIME, safe)
