"""Serializable sketches: HyperLogLog and quantile digest.

Reference parity: presto's HyperLogLog / P4HyperLogLog types over airlift
sketches (`spi/type/HyperLogLogType`, `operator/aggregation/
ApproximateSetAggregation` + `MergeHyperLogLogAggregation` +
`HyperLogLogFunctions.cardinality`) and QDigest
(`operator/aggregation/QuantileDigestAggregationFunction`,
`operator/scalar/QuantileDigestFunctions.value_at_quantile`).

These are the EXPORTABLE forms: byte strings that round-trip through
query results, CAST to/from VARCHAR (base64), and merge across
queries/nodes — the role airlift's serialized sketches play on the wire.
The in-query vectorized approx_distinct/approx_percentile paths
(exec/kernels.py) stay separate: they never materialize per-row sketch
objects, which is the TPU-friendly formulation; these host-side sketches
exist for the persist/merge-later workflow.

Formats (little-endian):
  HLL:     'PTH1' | m u16 | registers u8[m]
  QDIGEST: 'PTQ1' | k u16 | n u64 | centroids (value f64, weight f64)[k]
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional, Tuple

import numpy as np


HLL_M = 1024  # ~3.25% standard error (1.04/sqrt(m))
QDIGEST_K = 200  # centroid budget (t-digest-like accuracy in the tails)

_HLL_MAGIC = b"PTH1"
_QD_MAGIC = b"PTQ1"


# ---------------------------------------------------------------------------
# value hashing (must be stable across processes: xxh64 of a canonical
# byte encoding per type family)
# ---------------------------------------------------------------------------


def hash_value(v) -> int:
    import hashlib

    if isinstance(v, bool) or isinstance(v, np.bool_):
        enc = b"\x01" if v else b"\x00"
    elif isinstance(v, (int, np.integer)):
        enc = struct.pack("<q", int(v))
    elif isinstance(v, (float, np.floating)):
        enc = struct.pack("<d", float(v))
    elif isinstance(v, bytes):
        enc = v
    else:
        enc = str(v).encode("utf-8")
    # blake2b, NOT native.xxh64: the native lib's fallback is 32-bit
    # (crc32), which would starve the rho computation of bits and make
    # sketches built on different hosts silently incompatible
    return struct.unpack(
        "<Q", hashlib.blake2b(enc, digest_size=8).digest())[0]


# ---------------------------------------------------------------------------
# HyperLogLog
# ---------------------------------------------------------------------------


def hll_empty(m: int = HLL_M) -> bytes:
    return _HLL_MAGIC + struct.pack("<H", m) + b"\x00" * m


def hll_from_values(values: Iterable) -> bytes:
    m = HLL_M
    log2m = m.bit_length() - 1
    reg = np.zeros(m, dtype=np.uint8)
    for v in values:
        if v is None:
            continue
        h = hash_value(v)
        j = h & (m - 1)
        w = h >> log2m  # remaining 54 bits
        rho = (64 - log2m) - w.bit_length() + 1
        if rho > reg[j]:
            reg[j] = rho
    return _HLL_MAGIC + struct.pack("<H", m) + reg.tobytes()


def _hll_registers(blob: bytes) -> np.ndarray:
    if len(blob) < 6 or blob[:4] != _HLL_MAGIC:
        raise ValueError("not a serialized HyperLogLog")
    (m,) = struct.unpack("<H", blob[4:6])
    reg = np.frombuffer(blob[6:6 + m], dtype=np.uint8)
    if len(reg) != m:
        raise ValueError("truncated HyperLogLog")
    return reg


def hll_merge(blobs: Iterable[bytes]) -> bytes:
    out: Optional[np.ndarray] = None
    m = HLL_M
    for b in blobs:
        if b is None:
            continue
        reg = _hll_registers(b)
        if out is None:
            out = reg.copy()
            m = len(reg)
        else:
            if len(reg) != m:
                raise ValueError("cannot merge HLLs of different precisions")
            out = np.maximum(out, reg)
    if out is None:
        return hll_empty()
    return _HLL_MAGIC + struct.pack("<H", m) + out.tobytes()


def hll_cardinality(blob: bytes) -> int:
    reg = _hll_registers(blob).astype(np.float64)
    m = len(reg)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    E = alpha * m * m / np.sum(2.0 ** (-reg))
    zeros = int(np.sum(reg == 0))
    if E <= 2.5 * m and zeros > 0:
        E = m * np.log(m / zeros)
    return int(round(E))


# ---------------------------------------------------------------------------
# quantile digest (t-digest-flavored: merge-by-size compression keeps the
# tails accurate; reference behavior of QuantileDigest within its error
# bound)
# ---------------------------------------------------------------------------


def _qd_compress(cent: List[Tuple[float, float]],
                 k: int = QDIGEST_K) -> List[Tuple[float, float]]:
    cent = sorted(cent)
    while len(cent) > k:
        # merge the adjacent pair with the smallest combined weight,
        # preferring the middle (keeps tail centroids sharp)
        n = len(cent)
        best, best_cost = 1, float("inf")
        for i in range(1, n):
            qmid = (i / n - 0.5)
            cost = (cent[i - 1][1] + cent[i][1]) * (1.0 + 8.0 * qmid * qmid)
            if cost < best_cost:
                best, best_cost = i, cost
        (v1, w1), (v2, w2) = cent[best - 1], cent[best]
        cent[best - 1:best + 1] = [((v1 * w1 + v2 * w2) / (w1 + w2),
                                    w1 + w2)]
    return cent


def qdigest_from_values(values: Iterable) -> bytes:
    vals = np.asarray([float(v) for v in values if v is not None],
                      dtype=np.float64)
    if len(vals) == 0:
        return _QD_MAGIC + struct.pack("<HQ", 0, 0)
    vals.sort()
    # bucket into ~4k evenly-populated runs first (bounds the python loop)
    chunks = np.array_split(vals, min(len(vals), 20 * QDIGEST_K))
    cent = [(float(c.mean()), float(len(c))) for c in chunks if len(c)]
    cent = _qd_compress(cent)
    return _qd_serialize(cent, len(vals))


def _qd_serialize(cent: List[Tuple[float, float]], n: int) -> bytes:
    out = [_QD_MAGIC, struct.pack("<HQ", len(cent), n)]
    for v, w in cent:
        out.append(struct.pack("<dd", v, w))
    return b"".join(out)


def _qd_parse(blob: bytes) -> Tuple[List[Tuple[float, float]], int]:
    if len(blob) < 14 or blob[:4] != _QD_MAGIC:
        raise ValueError("not a serialized qdigest")
    k, n = struct.unpack("<HQ", blob[4:14])
    if len(blob) < 14 + 16 * k:
        raise ValueError("truncated qdigest")
    cent = []
    off = 14
    for _ in range(k):
        v, w = struct.unpack("<dd", blob[off:off + 16])
        cent.append((v, w))
        off += 16
    return cent, n


def qdigest_merge(blobs: Iterable[bytes]) -> bytes:
    cent: List[Tuple[float, float]] = []
    n = 0
    for b in blobs:
        if b is None:
            continue
        c, cn = _qd_parse(b)
        cent.extend(c)
        n += cn
    return _qd_serialize(_qd_compress(cent), n)


def qdigest_value_at_quantile(blob: bytes, q: float) -> Optional[float]:
    cent, n = _qd_parse(blob)
    if not cent:
        return None
    q = min(max(float(q), 0.0), 1.0)
    total = sum(w for _, w in cent)
    target = q * total
    cum = 0.0
    for v, w in cent:
        cum += w
        if cum >= target:
            return v
    return cent[-1][0]


def qdigest_quantile_at_value(blob: bytes, value: float) -> Optional[float]:
    cent, n = _qd_parse(blob)
    if not cent:
        return None
    total = sum(w for _, w in cent)
    cum = 0.0
    for v, w in cent:
        if v > value:
            break
        cum += w
    return min(cum / total, 1.0)
