"""Verifier: replay a query corpus against two targets and compare
result checksums.

Reference parity: presto-verifier (PrestoVerifier + checksum/
ChecksumValidator + resolver/) — control vs test cluster A/B runs with
order-insensitive checksums and float tolerance.  Targets here are any
`sql -> rows` callables: two engine sessions (e.g. different session
properties, or engine-vs-engine across versions) or the sqlite oracle.

CLI:  python -m presto_tpu.verifier --sf 0.01 [--corpus tpch|tpcds]
runs the bundled corpus engine-vs-sqlite and prints a report.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Dict, List, Optional

Runner = Callable[[str], list]


@dataclasses.dataclass
class VerifyResult:
    name: str
    state: str  # MATCH | MISMATCH | CONTROL_FAIL | TEST_FAIL | SKIP
    detail: str = ""
    control_ms: float = 0.0
    test_ms: float = 0.0


def row_checksum(rows, float_digits: int = 4) -> int:
    """Order-insensitive checksum with float canonicalization
    (reference: checksum/FloatingPointColumnValidator's tolerance idea,
    collapsed into rounding before hashing)."""
    from presto_tpu import native

    total = 0
    for row in rows:
        parts = []
        for v in row:
            if v is None:
                parts.append("\\N")
            elif isinstance(v, float):
                if math.isnan(v):
                    parts.append("nan")
                elif v == 0:
                    parts.append("0")
                else:
                    parts.append(f"{v:.{float_digits}e}")
            else:
                parts.append(str(v))
        h = native.xxh64("|".join(parts).encode("utf-8"))
        total = (total + h) & 0xFFFFFFFFFFFFFFFF  # commutative merge
    return total


class Verifier:
    def __init__(self, control: Runner, test: Runner,
                 float_digits: int = 4):
        self.control = control
        self.test = test
        self.float_digits = float_digits

    def verify_one(self, name: str, sql: str) -> VerifyResult:
        t0 = time.perf_counter()
        try:
            control_rows = self.control(sql)
        except Exception as e:  # noqa: BLE001 — report, don't crash the run
            return VerifyResult(name, "CONTROL_FAIL", f"{type(e).__name__}: {e}")
        t1 = time.perf_counter()
        try:
            test_rows = self.test(sql)
        except Exception as e:  # noqa: BLE001
            return VerifyResult(name, "TEST_FAIL", f"{type(e).__name__}: {e}",
                                control_ms=(t1 - t0) * 1e3)
        t2 = time.perf_counter()
        r = VerifyResult(name, "MATCH", control_ms=(t1 - t0) * 1e3,
                         test_ms=(t2 - t1) * 1e3)
        if len(control_rows) != len(test_rows):
            r.state = "MISMATCH"
            r.detail = f"row count {len(control_rows)} != {len(test_rows)}"
            return r
        c1 = row_checksum(control_rows, self.float_digits)
        c2 = row_checksum(test_rows, self.float_digits)
        if c1 != c2:
            r.state = "MISMATCH"
            r.detail = f"checksum {c1:#x} != {c2:#x}"
        return r

    def run(self, corpus: Dict[str, str]) -> List[VerifyResult]:
        return [self.verify_one(name, sql) for name, sql in corpus.items()]


def session_runner(session) -> Runner:
    return lambda sql: session.sql(sql).rows


def sqlite_runner(conn) -> Runner:
    from tests.sqlite_oracle import to_sqlite

    return lambda sql: conn.execute(to_sqlite(sql)).fetchall()


def report(results: List[VerifyResult]) -> str:
    lines = []
    counts: Dict[str, int] = {}
    for r in results:
        counts[r.state] = counts.get(r.state, 0) + 1
        mark = {"MATCH": "ok", "MISMATCH": "DIFF"}.get(r.state, "FAIL")
        lines.append(f"  [{mark:>4}] {r.name:<12} "
                     f"control={r.control_ms:8.1f}ms test={r.test_ms:8.1f}ms"
                     + (f"  {r.detail}" if r.detail else ""))
    summary = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    return "\n".join([f"verifier: {summary}"] + lines)


def main(argv: Optional[list] = None) -> int:
    import argparse
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser()
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--corpus", choices=("tpch", "tpcds"), default="tpch")
    p.add_argument("--device", default="cpu",
                   help="jax platform (default cpu: a 22-query corpus "
                        "pays per-query compiles; use 'tpu' deliberately)")
    args = p.parse_args(argv)

    import jax

    if args.device:
        jax.config.update("jax_platforms", args.device)
    import presto_tpu
    from tests.sqlite_oracle import build_sqlite

    if args.corpus == "tpch":
        from presto_tpu.catalog import tpch_catalog
        from tests.tpch_queries import QUERIES

        session = presto_tpu.connect(
            tpch_catalog(args.sf, cache_dir="/tmp/presto_tpu_cache"))
        oracle = build_sqlite(args.sf)
    else:
        from presto_tpu.catalog import tpcds_catalog
        from presto_tpu.connectors import tpcds as tpcds_gen
        from tests.tpcds_queries import QUERIES

        session = presto_tpu.connect(
            tpcds_catalog(args.sf, cache_dir="/tmp/presto_tpu_cache"))
        oracle = build_sqlite(args.sf, generator=tpcds_gen)

    v = Verifier(sqlite_runner(oracle), session_runner(session))
    results = v.run({f"q{k}": sql for k, sql in sorted(QUERIES.items())})
    print(report(results))
    return 0 if all(r.state == "MATCH" for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
