"""Disk spill for over-budget operator state.

Reference parity: spiller/ (FileSingleStreamSpiller writing serialized
pages to temp files, GenericPartitioningSpiller fanning rows out to
per-partition spill files, SpillSpaceTracker accounting; docs
admin/spill.rst).  Here a spill unit is a host-materialized column set
(one partition of a Grace hash build), written as a compressed,
checksummed PTPG frame via the native C++ codec (presto_tpu/native,
the PagesSerde/LZ4 analog of execution/buffer/PagesSerde.java:49-60);
device arrays are pulled to host exactly once on spill and re-uploaded
on unspill.
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.native import serde


class SpillError(Exception):
    pass


class SpillSpaceExhausted(SpillError):
    """Typed ENOSPC: the node-wide spill-disk bound (`max_spill_bytes`)
    cannot take another frame.  The query FAILS with this error — after
    releasing every reservation it holds (the spiller's close() frees
    its files' bytes; a refused frame is deleted before the raise) — so
    concurrent queries sharing the tracker keep their full budget."""


class SpillSpaceTracker:
    """Bounds total spill bytes on disk (reference:
    spiller/SpillSpaceTracker.java, max-spill-per-node).  Thread-safe:
    concurrent server queries share one tracker per session, and a
    reserve racing a release must never lose bytes in either
    direction."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self.used = 0
        self._lock = threading.Lock()

    def reserve(self, bytes_: int) -> None:
        with self._lock:
            if self.used + bytes_ > self.max_bytes:
                raise SpillSpaceExhausted(
                    f"spill space exhausted: "
                    f"{(self.used + bytes_) / 1e6:.1f}MB "
                    f"> {self.max_bytes / 1e6:.1f}MB")
            self.used += bytes_

    def free(self, bytes_: int) -> None:
        with self._lock:
            self.used = max(0, self.used - bytes_)


class SpillCipher:
    """AES-256-CTR over whole spill files with an ephemeral per-query key
    (reference: spiller/AesSpillCipher.java — the key lives only in
    memory, so spilled data is unreadable after the process exits)."""

    def __init__(self):
        self.key = os.urandom(32)

    def _cipher(self, nonce: bytes):
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)

        return Cipher(algorithms.AES(self.key), modes.CTR(nonce))

    def encrypt(self, data: bytes) -> bytes:
        nonce = os.urandom(16)
        enc = self._cipher(nonce).encryptor()
        return nonce + enc.update(data) + enc.finalize()

    def decrypt(self, data: bytes) -> bytes:
        dec = self._cipher(data[:16]).decryptor()
        return dec.update(data[16:]) + dec.finalize()


class FileSpiller:
    """Spills Batches to PTPG files and reads them back (reference:
    FileSingleStreamSpiller); pass a SpillCipher to encrypt files at rest
    (spill_encryption session property).

    Integrity contract: every spill frame is written CHECKSUMMED, and
    every unspill verifies the checksum with the declared-encoding check
    (`require_checksum` — a frame whose flags byte lost the CHECKSUMMED
    bit is itself corrupt, not exempt).  Any damage surfaces as a typed
    `SpillError`, never as silently-wrong rows.  `verify_writes=True`
    additionally reads each frame back right after writing and RE-SPILLS
    once on mismatch (`rewrites` counts them) — turning a write-path
    corruption into a transparent recovery instead of a failed query."""

    def __init__(self, directory: str,
                 tracker: Optional[SpillSpaceTracker] = None,
                 cipher: Optional[SpillCipher] = None,
                 verify_writes: bool = False):
        self.dir = directory
        self.tracker = tracker
        self.cipher = cipher
        self.verify_writes = verify_writes
        self.rewrites = 0
        self.files: List[Tuple[str, int]] = []
        self._meta: Dict[str, dict] = {}
        os.makedirs(directory, exist_ok=True)

    def spill(self, batch: Batch) -> str:
        """Write a compacted host copy of the batch; returns a handle."""
        arrays: Dict[str, np.ndarray] = {}
        meta: Dict[str, tuple] = {}
        sel = np.asarray(batch.sel)
        for name, c in batch.columns.items():
            d = np.asarray(c.data)[sel]
            arrays[f"d_{name}"] = d
            if c.valid is not None:
                arrays[f"v_{name}"] = np.asarray(c.valid)[sel]
            meta[name] = (c.type, c.dictionary)
        path = os.path.join(self.dir, f"spill_{uuid.uuid4().hex}.ptpg")
        self._write_file(path, arrays)
        if self.verify_writes:
            try:
                self._read_file(path)
            except SpillError:
                # transparent re-spill: the data is still in memory, so a
                # damaged write heals here instead of failing the query
                # at unspill time (chaos: faults `corrupt`/`truncate`)
                self.rewrites += 1
                self._write_file(path, arrays)
                self._read_file(path)  # second damage = real disk trouble
        size = os.path.getsize(path)
        if self.tracker is not None:
            try:
                self.tracker.reserve(size)
            except SpillError:
                os.remove(path)  # enforce the bound; no orphan on disk
                raise
        self.files.append((path, size))
        self._meta[path] = meta
        return path

    def _write_file(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        import io

        from presto_tpu.parallel import faults as F

        rule = F.apply_spill("WRITE", path)
        if rule is not None and rule.action == "enospc":
            raise SpillSpaceExhausted(
                "injected fault: spill device out of space")
        if self.cipher is not None:
            buf = io.BytesIO()
            serde.write_stream(buf, arrays)
            with open(path, "wb") as f:
                f.write(self.cipher.encrypt(buf.getvalue()))
        else:
            with open(path, "wb") as f:
                serde.write_stream(f, arrays)
        if rule is not None and rule.action in ("truncate", "corrupt"):
            F.damage_spill_file(path, rule.action)

    def _read_file(self, handle: str) -> Dict[str, np.ndarray]:
        """Read + verify one spill file; every failure mode (truncation,
        checksum mismatch, a stripped CHECKSUMMED flag, a cipher left
        half-decrypted) maps to the one typed SpillError the executor's
        chaos contract is built on."""
        import io

        try:
            if self.cipher is not None:
                with open(handle, "rb") as f:
                    return serde.read_stream(
                        io.BytesIO(self.cipher.decrypt(f.read())),
                        require_checksum=True)
            with open(handle, "rb") as f:
                return serde.read_stream(f, require_checksum=True)
        except SpillError:
            raise
        except (ValueError, OSError) as e:
            raise SpillError(f"corrupt spill frame {handle}: {e}") from e

    def unspill(self, handle: str) -> Batch:
        meta = self._meta[handle]
        z = self._read_file(handle)
        cols = {}
        n = 0
        for name, (typ, dictionary) in meta.items():
            d = z[f"d_{name}"]
            n = len(d)
            v = z.get(f"v_{name}")
            cols[name] = Column(d, v, typ, dictionary)
        if n == 0:
            # kernels require capacity >= 1; an empty partition becomes one
            # dead (sel=False) row, the shape every operator already handles
            cols = {name: Column(np.zeros(1, dtype=c.data.dtype), None,
                                 c.type, c.dictionary)
                    for name, c in cols.items()}
            return Batch(cols, np.zeros(1, dtype=bool))
        return Batch(cols, np.ones(n, dtype=bool))

    def close(self) -> None:
        for path, size in self.files:
            try:
                os.remove(path)
            except OSError:
                pass
            if self.tracker is not None:
                self.tracker.free(size)
        self.files.clear()
        self._meta.clear()


def default_spill_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "presto_tpu_spill")


# ---------------------------------------------------------------------------
# Durable batch checkpoints (recoverable grouped execution, P8 analog of
# RECOVERABLE_GROUPED_EXECUTION + REMOTE_MATERIALIZED exchanges:
# per-bucket results persist across executor instances, so a re-run after
# a failure resumes from completed buckets instead of recomputing).
# Unlike FileSpiller (whose column metadata lives in memory), these
# frames carry their metadata on disk.
# ---------------------------------------------------------------------------

import pickle


def save_batch(path: str, batch: Batch) -> None:
    sel = np.asarray(batch.sel)
    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, tuple] = {}
    for name, c in batch.columns.items():
        arrays[f"d_{name}"] = np.asarray(c.data)[sel]
        if c.valid is not None:
            arrays[f"v_{name}"] = np.asarray(c.valid)[sel]
        meta[name] = (str(c.type),
                      None if c.dictionary is None else c.dictionary.values)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        blob = pickle.dumps(meta, protocol=4)
        f.write(len(blob).to_bytes(8, "little"))
        f.write(blob)
        serde.write_stream(f, arrays)
    os.replace(tmp, path)  # atomic: a crash mid-write leaves no ckpt


def load_batch(path: str) -> Batch:
    from presto_tpu import types as T
    from presto_tpu.batch import Dictionary

    with open(path, "rb") as f:
        mlen = int.from_bytes(f.read(8), "little")
        meta = pickle.loads(f.read(mlen))
        z = serde.read_stream(f)
    cols = {}
    n = 0
    for name, (type_str, dict_values) in meta.items():
        d = z[f"d_{name}"]
        n = len(d)
        v = z.get(f"v_{name}")
        dictionary = None if dict_values is None else Dictionary(dict_values)
        cols[name] = Column(d, v, T.parse_type(type_str), dictionary)
    if n == 0:
        cols = {name: Column(np.zeros(1, dtype=c.data.dtype), None, c.type,
                             c.dictionary) for name, c in cols.items()}
        return Batch(cols, np.zeros(1, dtype=bool))
    return Batch(cols, np.ones(n, dtype=bool))
