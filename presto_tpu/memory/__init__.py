"""Memory management: hierarchical accounting, pools, spill.

Reference parity: the 3-level scheme of SURVEY.md §5 — per-allocation
LocalMemoryContext -> AggregatedMemoryContext trees
(presto-memory-context/), per-node MemoryPool (memory/MemoryPool.java),
and spilling under pressure (MemoryRevokingScheduler + spiller/).  On
TPU the budgeted resource is HBM: operators account device-batch bytes
against a query budget, and over-budget hash builds switch to grouped
(bucket-at-a-time, P8 Lifespan analog) execution with host/disk spill.
"""

from presto_tpu.memory.context import (ExceededMemoryLimitError,
                                       MemoryPool, QueryMemoryContext)
from presto_tpu.memory.spill import FileSpiller, SpillSpaceTracker

__all__ = ["ExceededMemoryLimitError", "MemoryPool", "QueryMemoryContext",
           "FileSpiller", "SpillSpaceTracker"]
