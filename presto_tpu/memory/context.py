"""Hierarchical memory accounting.

Reference parity: presto-memory-context (LocalMemoryContext /
AggregatedMemoryContext user+revocable trees) + memory/MemoryPool.java.
Simplified to the engine's execution model: one QueryMemoryContext per
query tracking reserved/revocable bytes per plan node against a pool;
exceeding the query limit raises (the reference blocks the driver or
revokes; here revocable reservations signal the spillable operators to
switch to grouped execution before the limit trips).
"""

from __future__ import annotations

import threading
from typing import Dict


class ExceededMemoryLimitError(Exception):
    """Reference: ExceededMemoryLimitException (presto-spi
    StandardErrorCode EXCEEDED_LOCAL_MEMORY_LIMIT)."""


class MemoryPool:
    """Per-process pool shared by concurrent queries (reference:
    memory/MemoryPool.java general pool; the reserved pool's
    biggest-query promotion is a no-op with one process)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.reserved = 0
        self.query_reservations: Dict[str, int] = {}
        self._lock = threading.Lock()  # concurrent server queries share the pool

    def reserve(self, query_id: str, bytes_: int) -> None:
        with self._lock:
            if self.reserved + bytes_ > self.capacity:
                raise ExceededMemoryLimitError(
                    f"memory pool exhausted: "
                    f"{(self.reserved + bytes_) / 1e6:.1f}"
                    f"MB > {self.capacity / 1e6:.1f}MB "
                    f"({len(self.query_reservations)} queries resident)")
            self.reserved += bytes_
            self.query_reservations[query_id] = (
                self.query_reservations.get(query_id, 0) + bytes_)

    def free(self, query_id: str, bytes_: int) -> None:
        with self._lock:
            self.reserved = max(0, self.reserved - bytes_)
            cur = self.query_reservations.get(query_id, 0) - bytes_
            if cur <= 0:
                self.query_reservations.pop(query_id, None)
            else:
                self.query_reservations[query_id] = cur

    @property
    def free_bytes(self) -> int:
        return self.capacity - self.reserved


class QueryMemoryContext:
    """Per-query accounting tree, flattened to {node id: bytes}
    (reference: AggregatedMemoryContext per operator/driver/task/query)."""

    def __init__(self, query_id: str, pool: MemoryPool, limit_bytes: int):
        self.query_id = query_id
        self.pool = pool
        self.limit = limit_bytes
        self.by_node: Dict[int, int] = {}
        self.current = 0
        self.peak = 0
        # revocable ledger (reference: the revocable half of
        # AggregatedMemoryContext + MemoryRevokingScheduler): bytes an
        # operator holds but can give back by spilling.  Reserved in the
        # POOL (they are real HBM) but not against the query limit until
        # converted — exactly the reference's accounting split.
        self.revocable_by_node: Dict[int, int] = {}
        self.revocable = 0
        self.revocations = 0

    def set_bytes(self, node_id: int, bytes_: int) -> None:
        """Absolute reservation for one node (operators re-declare as
        their state grows, like LocalMemoryContext.setBytes)."""
        delta = bytes_ - self.by_node.get(node_id, 0)
        if delta == 0:
            return
        if delta > 0:
            if self.current + delta > self.limit:
                raise ExceededMemoryLimitError(
                    f"query {self.query_id} exceeded memory limit: "
                    f"{(self.current + delta) / 1e6:.1f}MB > "
                    f"{self.limit / 1e6:.1f}MB")
            self.pool.reserve(self.query_id, delta)  # may raise; state intact
        else:
            self.pool.free(self.query_id, -delta)
        self.by_node[node_id] = bytes_
        self.current += delta
        self.peak = max(self.peak, self.current)

    def would_exceed(self, extra_bytes: int) -> bool:
        """Probe used by spillable operators to decide grouped execution
        BEFORE allocating (the MemoryRevokingScheduler threshold role)."""
        return self.current + extra_bytes > self.limit

    def headroom(self) -> int:
        """Bytes this query may still allocate before tripping its limit
        — the resident budget the degradation planner hands to
        exec/spill_exec (partitions whose working set fits stay
        on-chip; the rest spill)."""
        return max(self.limit - self.current, 0)

    # ---- revocable reservations (spill-tiered operators) -------------
    def set_revocable(self, node_id: int, bytes_: int) -> bool:
        """Declare revocable operator state (a hash-join build, GROUP BY
        accumulators).  Reserved in the pool, NOT counted against the
        query limit — the operator promises it can revoke (spill) on
        demand.  Returns False when the POOL cannot fit it: that is the
        memory-pressure signal telling the caller to degrade instead of
        building resident state."""
        delta = bytes_ - self.revocable_by_node.get(node_id, 0)
        if delta > 0:
            try:
                self.pool.reserve(self.query_id, delta)
            except ExceededMemoryLimitError:
                return False
        elif delta < 0:
            self.pool.free(self.query_id, -delta)
        if bytes_ <= 0:
            self.revocable_by_node.pop(node_id, None)
        else:
            self.revocable_by_node[node_id] = bytes_
        self.revocable += delta
        return True

    def revoke(self, node_id: int) -> int:
        """Release one node's revocable reservation (the operator is
        spilling its state).  Returns the bytes revoked."""
        amt = self.revocable_by_node.pop(node_id, 0)
        if amt:
            self.pool.free(self.query_id, amt)
            self.revocable -= amt
            self.revocations += 1
        return amt

    def convert_revocable(self, node_id: int) -> None:
        """Promote a revocable reservation to a regular one — the
        operator decided to stay resident, so its state now counts
        against the query limit (reference: the revoke-or-convert choice
        at HashBuilderOperator.finishMemoryRevoke).  Raises
        ExceededMemoryLimitError when the limit cannot take it; the
        revocable reservation is left intact so the caller can revoke()
        and degrade."""
        amt = self.revocable_by_node.get(node_id, 0)
        if not amt:
            return
        if self.current + amt > self.limit:
            raise ExceededMemoryLimitError(
                f"query {self.query_id} cannot convert {amt / 1e6:.1f}MB "
                f"revocable: {(self.current + amt) / 1e6:.1f}MB > "
                f"{self.limit / 1e6:.1f}MB")
        # pool reservation carries over unchanged; only the ledger moves
        self.revocable_by_node.pop(node_id)
        self.revocable -= amt
        self.by_node[node_id] = self.by_node.get(node_id, 0) + amt
        self.current += amt
        self.peak = max(self.peak, self.current)

    def release_all(self) -> None:
        self.pool.free(self.query_id, self.current + self.revocable)
        self.by_node.clear()
        self.revocable_by_node.clear()
        self.current = 0
        self.revocable = 0


def batch_bytes(batch) -> int:
    """Device bytes of a Batch: column data + validity + selection.
    Uses .nbytes metadata only — never materializes device arrays."""
    total = getattr(batch.sel, "nbytes", 0)
    for c in batch.columns.values():
        total += getattr(c.data, "nbytes", 0)
        if c.valid is not None:
            total += getattr(c.valid, "nbytes", 0)
    return int(total)
