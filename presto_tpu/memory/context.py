"""Hierarchical memory accounting.

Reference parity: presto-memory-context (LocalMemoryContext /
AggregatedMemoryContext user+revocable trees) + memory/MemoryPool.java.
Simplified to the engine's execution model: one QueryMemoryContext per
query tracking reserved/revocable bytes per plan node against a pool;
exceeding the query limit raises (the reference blocks the driver or
revokes; here revocable reservations signal the spillable operators to
switch to grouped execution before the limit trips).
"""

from __future__ import annotations

import threading
from typing import Dict


class ExceededMemoryLimitError(Exception):
    """Reference: ExceededMemoryLimitException (presto-spi
    StandardErrorCode EXCEEDED_LOCAL_MEMORY_LIMIT)."""


class MemoryPool:
    """Per-process pool shared by concurrent queries (reference:
    memory/MemoryPool.java general pool; the reserved pool's
    biggest-query promotion is a no-op with one process)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.reserved = 0
        self.query_reservations: Dict[str, int] = {}
        self._lock = threading.Lock()  # concurrent server queries share the pool

    def reserve(self, query_id: str, bytes_: int) -> None:
        with self._lock:
            if self.reserved + bytes_ > self.capacity:
                raise ExceededMemoryLimitError(
                    f"memory pool exhausted: "
                    f"{(self.reserved + bytes_) / 1e6:.1f}"
                    f"MB > {self.capacity / 1e6:.1f}MB "
                    f"({len(self.query_reservations)} queries resident)")
            self.reserved += bytes_
            self.query_reservations[query_id] = (
                self.query_reservations.get(query_id, 0) + bytes_)

    def free(self, query_id: str, bytes_: int) -> None:
        with self._lock:
            self.reserved = max(0, self.reserved - bytes_)
            cur = self.query_reservations.get(query_id, 0) - bytes_
            if cur <= 0:
                self.query_reservations.pop(query_id, None)
            else:
                self.query_reservations[query_id] = cur

    @property
    def free_bytes(self) -> int:
        return self.capacity - self.reserved


class QueryMemoryContext:
    """Per-query accounting tree, flattened to {node id: bytes}
    (reference: AggregatedMemoryContext per operator/driver/task/query)."""

    def __init__(self, query_id: str, pool: MemoryPool, limit_bytes: int):
        self.query_id = query_id
        self.pool = pool
        self.limit = limit_bytes
        self.by_node: Dict[int, int] = {}
        self.current = 0
        self.peak = 0

    def set_bytes(self, node_id: int, bytes_: int) -> None:
        """Absolute reservation for one node (operators re-declare as
        their state grows, like LocalMemoryContext.setBytes)."""
        delta = bytes_ - self.by_node.get(node_id, 0)
        if delta == 0:
            return
        if delta > 0:
            if self.current + delta > self.limit:
                raise ExceededMemoryLimitError(
                    f"query {self.query_id} exceeded memory limit: "
                    f"{(self.current + delta) / 1e6:.1f}MB > "
                    f"{self.limit / 1e6:.1f}MB")
            self.pool.reserve(self.query_id, delta)  # may raise; state intact
        else:
            self.pool.free(self.query_id, -delta)
        self.by_node[node_id] = bytes_
        self.current += delta
        self.peak = max(self.peak, self.current)

    def would_exceed(self, extra_bytes: int) -> bool:
        """Probe used by spillable operators to decide grouped execution
        BEFORE allocating (the MemoryRevokingScheduler threshold role)."""
        return self.current + extra_bytes > self.limit

    def release_all(self) -> None:
        self.pool.free(self.query_id, self.current)
        self.by_node.clear()
        self.current = 0


def batch_bytes(batch) -> int:
    """Device bytes of a Batch: column data + validity + selection.
    Uses .nbytes metadata only — never materializes device arrays."""
    total = getattr(batch.sel, "nbytes", 0)
    for c in batch.columns.values():
        total += getattr(c.data, "nbytes", 0)
        if c.valid is not None:
            total += getattr(c.valid, "nbytes", 0)
    return int(total)
