"""Interactive SQL CLI.

Reference parity: presto-cli (Console.java, StatusPrinter.java,
OutputFormat) — interactive prompt, multiple output formats, \\timing,
server or embedded operation.  Usage:

    python -m presto_tpu.cli --catalog tpch --sf 0.01       # embedded
    python -m presto_tpu.cli --server http://host:port      # remote
    python -m presto_tpu.cli --execute "SELECT 1" --format CSV
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional, Tuple


# ---------------------------------------------------------------------------
# output formatting (reference: presto-cli OutputFormat + AlignedTablePrinter)
# ---------------------------------------------------------------------------

def format_aligned(columns: List[str], rows: List[tuple]) -> str:
    cells = [[_render(v) for v in row] for row in rows]
    widths = [len(c) for c in columns]
    for row in cells:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(c.ljust(w) for c, w in zip(columns, widths)), sep]
    for row in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(row, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


def format_csv(columns: List[str], rows: List[tuple]) -> str:
    import csv
    import io

    buf = io.StringIO()
    w = csv.writer(buf)
    w.writerow(columns)
    for row in rows:
        w.writerow(["" if v is None else v for v in row])
    return buf.getvalue().rstrip("\n")


def format_tsv(columns: List[str], rows: List[tuple]) -> str:
    lines = ["\t".join(columns)]
    for row in rows:
        lines.append("\t".join("" if v is None else str(v) for v in row))
    return "\n".join(lines)


def format_json(columns: List[str], rows: List[tuple]) -> str:
    import json

    return json.dumps([dict(zip(columns, row)) for row in rows],
                      default=str, indent=2)


FORMATTERS = {"ALIGNED": format_aligned, "CSV": format_csv,
              "TSV": format_tsv, "JSON": format_json}


def _render(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


# ---------------------------------------------------------------------------
# execution backends
# ---------------------------------------------------------------------------

class EmbeddedBackend:
    def __init__(self, sf: float, cache_dir: Optional[str]):
        import presto_tpu
        from presto_tpu.catalog import tpch_catalog

        self.session = presto_tpu.connect(
            tpch_catalog(sf, cache_dir=cache_dir))

    def run(self, sql: str) -> Tuple[List[str], List[tuple]]:
        r = self.session.sql(sql)
        return [n for n, _ in r.columns], r.rows


class RemoteBackend:
    def __init__(self, server_uri: str):
        from presto_tpu.client import StatementClient

        self.server_uri = server_uri
        self._client_cls = StatementClient

    def run(self, sql: str) -> Tuple[List[str], List[tuple]]:
        client = self._client_cls(self.server_uri, sql)
        rows = list(client.rows())
        cols = ([c["name"] for c in client.columns] if client.columns
                else [])
        return cols, rows


# ---------------------------------------------------------------------------

BANNER = "presto-tpu CLI — \\q quits, \\timing toggles timing, \\f FORMAT"


def repl(backend, fmt: str, show_timing: bool = False,
         stdin=None, stdout=None) -> None:
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    print(BANNER, file=stdout)
    buf: List[str] = []
    while True:
        try:
            prompt = "presto-tpu> " if not buf else "        ...> "
            if stdin is sys.stdin and sys.stdin.isatty():
                line = input(prompt)
            else:
                line = stdin.readline()
                if not line:
                    break
                line = line.rstrip("\n")
        except (EOFError, KeyboardInterrupt):
            break
        stripped = line.strip()
        if not buf and stripped.startswith("\\"):
            cmd = stripped.split()
            if cmd[0] in ("\\q", "\\quit"):
                break
            if cmd[0] == "\\timing":
                show_timing = not show_timing
                print(f"timing {'on' if show_timing else 'off'}", file=stdout)
                continue
            if cmd[0] == "\\f" and len(cmd) > 1 and cmd[1].upper() in FORMATTERS:
                fmt = cmd[1].upper()
                print(f"format {fmt}", file=stdout)
                continue
            print(f"unknown command {cmd[0]}", file=stdout)
            continue
        buf.append(line)
        if not stripped.endswith(";"):
            continue
        sql = "\n".join(buf).rstrip().rstrip(";")
        buf = []
        if not sql.strip():
            continue
        try:
            t0 = time.perf_counter()
            cols, rows = backend.run(sql)
            elapsed = time.perf_counter() - t0
            print(FORMATTERS[fmt](cols, rows), file=stdout)
            if show_timing:
                print(f"Time: {elapsed:.3f}s", file=stdout)
        except Exception as e:  # noqa: BLE001 — REPL reports and continues
            print(f"ERROR: {e}", file=stdout)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=BANNER)
    p.add_argument("--server", help="remote server URI (default: embedded)")
    p.add_argument("--sf", type=float, default=0.01,
                   help="embedded TPC-H scale factor")
    p.add_argument("--cache-dir", default="/tmp/presto_tpu_cache")
    p.add_argument("--execute", "-e", help="run one statement and exit")
    p.add_argument("--format", "-f", default="ALIGNED",
                   choices=sorted(FORMATTERS))
    p.add_argument("--timing", action="store_true")
    args = p.parse_args(argv)

    backend = (RemoteBackend(args.server) if args.server
               else EmbeddedBackend(args.sf, args.cache_dir))
    if args.execute:
        try:
            t0 = time.perf_counter()
            cols, rows = backend.run(args.execute.rstrip(";"))
            print(FORMATTERS[args.format](cols, rows))
            if args.timing:
                print(f"Time: {time.perf_counter() - t0:.3f}s")
            return 0
        except Exception as e:  # noqa: BLE001
            print(f"ERROR: {e}", file=sys.stderr)
            return 1
    repl(backend, args.format, args.timing)
    return 0


if __name__ == "__main__":
    sys.exit(main())
