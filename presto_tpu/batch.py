"""Columnar batch representation — the TPU-native Page/Block.

Reference parity: presto-spi/.../spi/Page.java:34 (Page = positionCount +
Block[]) and the Block hierarchy in presto-spi/.../spi/block/.  Redesigned
for XLA's static-shape world:

- A `Batch` is a pytree of fixed-shape device arrays: one data array per
  column, an optional per-column validity mask (None == no nulls, like the
  reference's mayHaveNull fast path), and a row-selection mask `sel`.
- Filters AND into `sel` instead of compacting (no data-dependent shapes
  inside jit).  `row_count` is a traced scalar = popcount(sel).
- Strings are ALWAYS dictionary-encoded (the reference's DictionaryBlock,
  presto-spi/.../spi/block/DictionaryBlock.java, promoted from an
  optimization to the only representation): int32 codes on device, the
  dictionary itself is a host-side numpy array of strings shared by
  reference (`Dictionary`).  String functions evaluate over the (small)
  dictionary on host and the result is gathered through the codes on
  device — this is the dictionary-aware projection of
  operator/project/DictionaryAwarePageProjection.java, made mandatory.
- LazyBlock (late materialization) has no analog: XLA dead-code eliminates
  unused columns after tracing, which is strictly stronger.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.types import Type

_dict_ids = itertools.count()


class Dictionary:
    """Host-side string dictionary, identity-hashed so batches stay
    jit-cache-friendly (a new Dictionary object => new compilation key only
    when used as a static argument; codes arrays are ordinary operands)."""

    __slots__ = ("values", "_id", "_value_hashes")

    def __init__(self, values: np.ndarray):
        # values: 1-D object/str array; code i means values[i]. values[-1]
        # position is NOT reserved; null is carried by the validity mask.
        self.values = np.asarray(values)
        self._id = next(_dict_ids)

    def __len__(self) -> int:
        return len(self.values)

    def __hash__(self) -> int:
        return self._id

    def __eq__(self, other) -> bool:
        return self is other

    def __repr__(self) -> str:
        return f"Dictionary(#{self._id}, {len(self.values)} values)"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One column: data array + optional validity mask (True == non-null)."""

    data: jax.Array
    valid: Optional[jax.Array]  # None => all valid
    type: Type
    dictionary: Optional[Dictionary] = None

    def tree_flatten(self):
        return (self.data, self.valid), (self.type, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid = children
        return cls(data, valid, aux[0], aux[1])

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Batch:
    """A set of equal-capacity columns + a row-selection mask."""

    columns: Dict[str, Column]
    sel: jax.Array  # bool[capacity]; True == row is live

    def tree_flatten(self):
        names = tuple(self.columns)
        return (tuple(self.columns[n] for n in names), self.sel), names

    @classmethod
    def tree_unflatten(cls, names, children):
        cols, sel = children
        return cls(dict(zip(names, cols)), sel)

    @property
    def capacity(self) -> int:
        return self.sel.shape[0]

    def row_count(self) -> jax.Array:
        return jnp.sum(self.sel)

    def column(self, name: str) -> Column:
        return self.columns[name]

    def with_columns(self, columns: Dict[str, Column]) -> "Batch":
        return Batch(columns, self.sel)

    def with_sel(self, sel: jax.Array) -> "Batch":
        return Batch(self.columns, sel)

    def select(self, names: Sequence[str]) -> "Batch":
        return Batch({n: self.columns[n] for n in names}, self.sel)


# ---------------------------------------------------------------------------
# Host-side ingestion
# ---------------------------------------------------------------------------


def encode_strings(values: np.ndarray) -> tuple[np.ndarray, Dictionary]:
    """Dictionary-encode a host string column -> (int32 codes, Dictionary).
    The dictionary is SORTED so that code order == lexicographic order,
    making ORDER BY / comparisons on strings pure integer ops on device.
    The O(n) hashing pass runs in the native C++ library when available
    (presto_tpu/native pt_dict_encode); numpy np.unique otherwise."""
    from presto_tpu import native

    arr = np.asarray(values, dtype=object).astype(str)
    if len(arr) >= 4096:
        encoded = native.dict_encode(arr)
        if encoded is not None:
            codes, uniq = encoded
            return codes, Dictionary(uniq)
    uniq, codes = np.unique(arr, return_inverse=True)
    return codes.astype(np.int32), Dictionary(uniq)


def column_from_numpy(data: np.ndarray, typ: Type, valid: Optional[np.ndarray] = None) -> Column:
    if isinstance(data, np.ma.MaskedArray):
        # connectors return masked arrays for nullable columns (the SPI's
        # null channel; reference: Block.isNull)
        mask = np.ma.getmaskarray(data)
        nv = ~mask
        valid = nv if valid is None else (np.asarray(valid) & nv)
        data = data.filled("" if typ.is_string else 0)
    dictionary = None
    if typ.is_string and data.dtype.kind in ("U", "S", "O"):
        data, dictionary = encode_strings(data)
    if typ.is_decimal and typ.is_long_decimal:
        from presto_tpu.exec import dec128 as D128

        if data.ndim == 2 and data.dtype.kind == "i":
            pass  # already limbs
        else:
            import decimal as _d

            s = typ.decimal_scale
            with _d.localcontext() as ctx:
                ctx.prec = 80  # default 28 can't hold 38-digit values
                ints = [int(_d.Decimal(str(v)).scaleb(s).quantize(
                    _d.Decimal(1), rounding=_d.ROUND_HALF_UP))
                    for v in data]
            data = D128.from_host_ints(ints)
        v = None if valid is None else jnp.asarray(valid, dtype=bool)
        return Column(jnp.asarray(data), v, typ, None)
    if typ.is_decimal and data.dtype.kind == "f":
        # host floats (e.g. a decoded decimal column re-ingested via
        # CTAS/INSERT) carry the unscaled value; rescale, don't truncate
        scaled = data * (10 ** typ.decimal_scale)
        from presto_tpu.types import check_decimal_overflow

        check_decimal_overflow(scaled, valid, "ingested value")
        data = np.round(scaled)
    data = np.ascontiguousarray(data, dtype=typ.numpy_dtype())
    v = None if valid is None else jnp.asarray(valid, dtype=bool)
    return Column(jnp.asarray(data), v, typ, dictionary)


def batch_from_numpy(
    arrays: Dict[str, np.ndarray],
    types: Dict[str, Type],
    valids: Optional[Dict[str, np.ndarray]] = None,
) -> Batch:
    cols = {}
    n = None
    for name, arr in arrays.items():
        v = (valids or {}).get(name)
        cols[name] = column_from_numpy(arr, types[name], v)
        n = len(arr) if n is None else n
        assert len(arr) == n, f"ragged column {name}"
    sel = jnp.ones((n or 0,), dtype=bool)
    return Batch(cols, sel)


_COMPACT_THRESHOLD = 262_144  # capacity above which selective fetch wins


def to_numpy(batch: Batch, extra=None):
    """Materialize to host: (column arrays with strings decoded, live-row
    mask[, extra pulled value]).  ONE device_get for the whole batch —
    per-column transfers pay a full RPC round-trip each on tunneled TPU
    backends.  Large mostly-dead batches (a TopN mask over a scan-sized
    capacity) are compacted on device first: pull the 1-byte/row sel,
    gather the survivors, pull only those — the difference between 7s and
    0.2s for a 10-row result over a 6M-row capacity on a tunneled chip."""
    if batch.capacity > _COMPACT_THRESHOLD:
        sel_h, extra_h = jax.device_get((batch.sel, extra))
        sel_h = np.asarray(sel_h)
        live = np.flatnonzero(sel_h)
        if len(live) < batch.capacity // 4:
            idx = jnp.asarray(live)
            pulled = jax.device_get(
                {n: (c.data[idx],
                     None if c.valid is None else c.valid[idx])
                 for n, c in batch.columns.items()})
            out = _decode_pulled(batch, pulled)
            ones = np.ones(len(live), dtype=bool)
            return (out, ones) if extra is None else (out, ones, extra_h)
        # dense batch: fall through to the single full fetch below (sel
        # already pulled; extra too)
        pulled = jax.device_get(
            {n: (c.data, c.valid) for n, c in batch.columns.items()})
        out = _decode_pulled(batch, pulled)
        return (out, sel_h) if extra is None else (out, sel_h, extra_h)
    pulled = jax.device_get(
        (batch.sel,
         {n: (c.data, c.valid) for n, c in batch.columns.items()},
         extra))
    sel, datas, extra_h = pulled
    sel = np.asarray(sel)
    out = _decode_pulled(batch, datas)
    return (out, sel) if extra is None else (out, sel, extra_h)


def decode_host_column(data, valid, typ, dictionary) -> np.ndarray:
    """Decode one pulled column on host: dictionary lookup, decimal
    rescale, NULL masking.  Shared by every result-materialization path
    (to_numpy and the compiled packed fetch)."""
    data = np.asarray(data)
    if dictionary is not None:
        codes = np.clip(data, 0, len(dictionary) - 1)
        data = dictionary.values[codes]
    elif typ.is_decimal and typ.is_long_decimal and data.ndim == 2:
        # two-limb Int128: decode to exact python Decimals (reference:
        # Int128ArrayBlock -> SqlDecimal)
        from decimal import Decimal

        from presto_tpu.exec import dec128 as D128

        ints = D128.to_host_ints(data)  # signed (hi limb is signed)
        s = typ.decimal_scale
        out = np.empty(len(ints), dtype=object)
        import decimal as _d

        with _d.localcontext() as ctx:
            ctx.prec = 80  # scaleb ROUNDS to context precision (28!)
            for i, v in enumerate(ints):
                out[i] = Decimal(v).scaleb(-s)
        data = out
    elif typ.is_decimal:
        data = data.astype(np.float64) / (10 ** typ.decimal_scale)
    if valid is not None:
        data = np.ma.masked_array(data, mask=~np.asarray(valid))
    return data


def _decode_pulled(batch: Batch, datas) -> Dict[str, np.ndarray]:
    return {name: decode_host_column(datas[name][0], datas[name][1],
                                     col.type, col.dictionary)
            for name, col in batch.columns.items()}
