"""Transaction manager: autocommit + explicit transactions with rollback.

Reference parity: transaction/TransactionManager + the access-mode
checks in transaction/TransactionAccessControl — START TRANSACTION
[READ ONLY] / COMMIT / ROLLBACK, single-statement autocommit otherwise.
Isolation is snapshot-by-undo: the first write to a table inside a
transaction records an undo entry (memory-connector pre-image, or the
inverse DDL action); ROLLBACK replays undos in reverse.  Connectors
without pre-image support (localfile shards) reject transactional
writes, like reference connectors that lack transaction support.
"""

from __future__ import annotations

from typing import List, Optional


class TransactionError(Exception):
    pass


class Transaction:
    def __init__(self, read_only: bool = False):
        self.read_only = read_only
        self.undo: List[tuple] = []  # (kind, payload) in apply order
        self._snapshotted: set = set()


class TransactionManager:
    """One manager per session (the engine's session IS the reference's
    transaction-bound client session)."""

    def __init__(self, session):
        self.session = session
        self.current: Optional[Transaction] = None

    # ---- statement surface ------------------------------------------
    def begin(self, read_only: bool = False) -> None:
        if self.current is not None:
            raise TransactionError("transaction already in progress")
        self.current = Transaction(read_only)

    def commit(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        self.current = None  # writes already applied; drop undo log

    def rollback(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        txn, self.current = self.current, None
        cat = self.session.catalog
        for kind, payload in reversed(txn.undo):
            if kind == "table_preimage":
                table, data, rows = payload
                table.data = data
                table._rows = rows
                table._invalidate()
            elif kind == "uncreate":
                cat.drop(payload, if_exists=True)
            elif kind == "reregister":
                cat.register(payload)

    # ---- write hooks (called by the executor's write paths) ----------
    def check_write_allowed(self) -> None:
        if self.current is not None and self.current.read_only:
            raise TransactionError("read-only transaction")

    def record_table_write(self, table) -> None:
        """Before mutating `table`, snapshot its pre-image once."""
        self.check_write_allowed()
        if self.current is None:
            return  # autocommit
        if id(table) in self.current._snapshotted:
            return
        if not hasattr(table, "data"):
            raise TransactionError(
                f"table '{table.name}' does not support transactional "
                "writes (memory connector only)")
        self.current._snapshotted.add(id(table))
        self.current.undo.append(
            ("table_preimage",
             (table, {k: v.copy() for k, v in table.data.items()},
              table._rows)))

    def record_create(self, name: str) -> None:
        self.check_write_allowed()
        if self.current is not None:
            self.current.undo.append(("uncreate", name))

    def record_drop(self, table) -> None:
        self.check_write_allowed()
        if self.current is not None:
            if not hasattr(table, "data"):
                raise TransactionError(
                    f"DROP of '{table.name}' is not transactional "
                    "(storage would be deleted); COMMIT first")
            self.current.undo.append(("reregister", table))
