"""Transaction manager: autocommit + explicit transactions with rollback.

Reference parity: transaction/TransactionManager + the access-mode
checks in transaction/TransactionAccessControl — START TRANSACTION
[READ ONLY] / COMMIT / ROLLBACK, single-statement autocommit otherwise.
Isolation is snapshot-by-undo: the first write to a table inside a
transaction records an undo entry, and ROLLBACK replays undos in
reverse.  Two snapshot kinds:

- memory-connector pre-image (copy the arrays);
- SINK SNAPSHOT: staged-sink connectors (localfile manifest, the
  parquet/orc sidecar manifests) expose snapshot_state()/restore_state()
  — the undo restores the pre-write manifest generation, and because
  committed writes only ADD files (previous generations are retired
  lazily, never deleted while a transaction is open), the restored
  manifest's files are all still on disk.  This is also what gives the
  refresh-and-serve scenario its isolation: a reader holding generation
  N's file list is untouched by the commit that publishes N+1
  (exec/writer.py, docs/WRITES.md).

Connectors with neither snapshot form reject transactional writes, like
reference connectors that lack transaction support.
"""

from __future__ import annotations

from typing import List, Optional


class TransactionError(Exception):
    pass


class Transaction:
    def __init__(self, read_only: bool = False):
        self.read_only = read_only
        self.undo: List[tuple] = []  # (kind, payload) in apply order
        self._snapshotted: set = set()


class TransactionManager:
    """One manager per session (the engine's session IS the reference's
    transaction-bound client session)."""

    def __init__(self, session):
        self.session = session
        self.current: Optional[Transaction] = None

    # ---- statement surface ------------------------------------------
    def begin(self, read_only: bool = False) -> None:
        if self.current is not None:
            raise TransactionError("transaction already in progress")
        self.current = Transaction(read_only)

    def commit(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        self.current = None  # writes already applied; drop undo log

    def rollback(self) -> None:
        if self.current is None:
            raise TransactionError("no transaction in progress")
        txn, self.current = self.current, None
        cat = self.session.catalog
        for kind, payload in reversed(txn.undo):
            if kind == "table_preimage":
                table, data, rows = payload
                table.data = data
                table._rows = rows
                table._invalidate()
            elif kind == "sink_state":
                table, state = payload
                table.restore_state(state)
            elif kind == "uncreate":
                try:
                    t = cat.get(payload)
                except KeyError:
                    t = None
                if t is not None and hasattr(t, "drop_data"):
                    t.drop_data()  # staged CTAS files go with the undo
                cat.drop(payload, if_exists=True)
            elif kind == "reregister":
                cat.register(payload)

    # ---- write hooks (called by the executor's write paths) ----------
    def check_write_allowed(self) -> None:
        if self.current is not None and self.current.read_only:
            raise TransactionError("read-only transaction")

    def record_table_write(self, table) -> None:
        """Before mutating `table`, snapshot its pre-image once: a data
        copy for memory tables, the manifest for staged-sink tables."""
        self.check_write_allowed()
        if self.current is None:
            return  # autocommit
        if id(table) in self.current._snapshotted:
            return
        if hasattr(table, "snapshot_state"):
            self.current._snapshotted.add(id(table))
            self.current.undo.append(
                ("sink_state", (table, table.snapshot_state())))
            return
        if not hasattr(table, "data"):
            raise TransactionError(
                f"table '{table.name}' does not support transactional "
                "writes (no pre-image or manifest snapshot)")
        self.current._snapshotted.add(id(table))
        self.current.undo.append(
            ("table_preimage",
             (table, {k: v.copy() for k, v in table.data.items()},
              table._rows)))

    def record_create(self, name: str) -> None:
        self.check_write_allowed()
        if self.current is not None:
            self.current.undo.append(("uncreate", name))

    def record_replace(self, name: str, old_table,
                       in_place: bool = False) -> None:
        """CREATE OR REPLACE undo: a cross-storage replace re-registers
        the old table object over the new one; an in-place
        (same-manifest) replace is covered by the manifest snapshot the
        writer records via record_presnapshot BEFORE the sink commit."""
        self.check_write_allowed()
        if self.current is None or in_place:
            return
        self.current.undo.append(("reregister", old_table))

    def record_presnapshot(self, table) -> None:
        """Snapshot a staged-sink table's manifest BEFORE a replace
        commit (exec/writer.py calls this ahead of sink.finish)."""
        self.check_write_allowed()
        if self.current is None or not hasattr(table, "snapshot_state"):
            return
        if id(table) in self.current._snapshotted:
            return
        self.current._snapshotted.add(id(table))
        self.current.undo.append(
            ("sink_state", (table, table.snapshot_state())))

    def record_drop(self, table) -> None:
        self.check_write_allowed()
        if self.current is not None:
            if not hasattr(table, "data") \
                    and not hasattr(table, "snapshot_state"):
                raise TransactionError(
                    f"DROP of '{table.name}' is not transactional "
                    "(storage would be deleted); COMMIT first")
            self.current.undo.append(("reregister", table))

    @property
    def active(self) -> bool:
        """True while an explicit transaction is open — staged-sink
        commits defer retired-file garbage collection so a later
        ROLLBACK can still restore the pre-write manifest's files."""
        return self.current is not None
