"""Benchmark suite: named operator- and SQL-level microbenchmarks.

Reference parity: presto-benchmark (BenchmarkSuite.java:32 over a
LocalQueryRunner — HandTpchQuery1/6 hand-built pipelines, hash build
+join, aggregations) and presto-benchmark-driver's wall-time stats.
Hand-built benchmarks call the kernel layer directly (the compiled
fragment a query would lower to); SQL benchmarks run through the full
engine.

CLI:  python -m presto_tpu.benchmarks [--sf 0.1] [--runs 3] [--filter x]
prints one line per benchmark: name, wall ms (median of runs), rows/sec.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable, Dict, List, Optional


@dataclasses.dataclass
class BenchResult:
    name: str
    median_ms: float
    rows_per_sec: float
    runs_ms: List[float]


class BenchmarkSuite:
    def __init__(self, session, runs: int = 3):
        self.session = session
        self.runs = runs
        self.benchmarks: Dict[str, tuple] = {}  # name -> (fn, row_count)

    def add(self, name: str, fn: Callable[[], object], rows: int) -> None:
        self.benchmarks[name] = (fn, rows)

    def add_sql(self, name: str, sql: str, rows: int) -> None:
        self.add(name, lambda: self.session.sql(sql), rows)

    def run(self, pattern: Optional[str] = None) -> List[BenchResult]:
        out = []
        for name, (fn, rows) in self.benchmarks.items():
            if pattern and pattern not in name:
                continue
            fn()  # prewarm (compile caches, device upload)
            times = []
            for _ in range(self.runs):
                t0 = time.perf_counter()
                fn()
                times.append((time.perf_counter() - t0) * 1e3)
            med = statistics.median(times)
            out.append(BenchResult(name, med, rows / (med / 1e3), times))
        return out


def _hand_q1(session):
    """Hand-built TPC-H Q1 fragment at the kernel layer (reference:
    HandTpchQuery1.java building the operator pipeline by hand)."""
    import jax
    import jax.numpy as jnp

    from presto_tpu.exec import compile_cache as CC
    from presto_tpu.exec import kernels as K
    from presto_tpu.exec.executor import scan_batch
    from presto_tpu.plan import nodes as P

    t = session.catalog.get("lineitem")
    node = P.TableScan("lineitem", {c: c for c in (
        "l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
        "l_extendedprice", "l_discount", "l_tax")},
        {c: t.schema[c] for c in (
            "l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
            "l_extendedprice", "l_discount", "l_tax")})
    b = scan_batch(t, node)

    @CC.build_jit
    def frag(b):
        sel = b.sel & (b.columns["l_shipdate"].data <= 10471)
        key = (b.columns["l_returnflag"].data * 8
               + b.columns["l_linestatus"].data).astype(jnp.int32)
        qty = b.columns["l_quantity"].data
        price = b.columns["l_extendedprice"].data
        disc = b.columns["l_discount"].data
        tax = b.columns["l_tax"].data
        disc_price = price * (1.0 - disc)
        charge = disc_price * (1.0 + tax)
        vals = jnp.stack([
            jnp.where(sel, qty, 0.0), jnp.where(sel, price, 0.0),
            jnp.where(sel, disc_price, 0.0), jnp.where(sel, charge, 0.0),
            jnp.where(sel, disc, 0.0), sel.astype(qty.dtype)])
        return K.fused_group_sums(vals, key, 64)

    return lambda: jax.block_until_ready(frag(b))


def build_default_suite(session, sf: float) -> BenchmarkSuite:
    from presto_tpu.connectors import tpch as tpch_gen
    from tests.tpch_queries import QUERIES

    suite = BenchmarkSuite(session)
    li = tpch_gen.row_count("lineitem", sf)
    orders = tpch_gen.row_count("orders", sf)
    suite.add("hand_tpch_q1", _hand_q1(session), li)
    suite.add_sql("sql_tpch_q1", QUERIES[1], li)
    suite.add_sql("sql_tpch_q3", QUERIES[3], li + orders)
    suite.add_sql("sql_tpch_q6", QUERIES[6], li)
    suite.add_sql("hash_join",
                  "SELECT count(*) FROM lineitem, orders "
                  "WHERE l_orderkey = o_orderkey", li + orders)
    suite.add_sql("group_by_bigkey",
                  "SELECT l_orderkey, count(*) FROM lineitem "
                  "GROUP BY l_orderkey", li)
    suite.add_sql("order_by",
                  "SELECT l_extendedprice FROM lineitem "
                  "ORDER BY l_extendedprice DESC LIMIT 100", li)
    suite.add_sql("window_rank",
                  "SELECT l_orderkey, rank() OVER "
                  "(PARTITION BY l_returnflag ORDER BY l_extendedprice) "
                  "FROM lineitem LIMIT 10", li)
    return suite


def main(argv: Optional[list] = None) -> int:
    import argparse
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser()
    p.add_argument("--sf", type=float, default=0.1)
    p.add_argument("--runs", type=int, default=3)
    p.add_argument("--filter", default=None)
    p.add_argument("--device", default=None,
                   help="jax platform override (e.g. cpu); default = "
                        "the real backend, as benchmarks should be")
    args = p.parse_args(argv)

    import jax

    if args.device:
        jax.config.update("jax_platforms", args.device)
    import presto_tpu
    from presto_tpu.catalog import tpch_catalog

    session = presto_tpu.connect(
        tpch_catalog(args.sf, cache_dir="/tmp/presto_tpu_cache"))
    suite = build_default_suite(session, args.sf)
    suite.runs = args.runs
    for r in suite.run(args.filter):
        print(f"{r.name:<20} {r.median_ms:10.1f} ms   "
              f"{r.rows_per_sec:14,.0f} rows/s   runs={['%.0f' % t for t in r.runs_ms]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
