"""Parquet read path — an in-engine decoder, no external parquet library.

Reference parity: presto-parquet/ (ParquetReader, PageReader, the
column readers under reader/) + presto-hive's ParquetPageSourceFactory.
TPU-native adaptation: the engine's columns are whole numpy arrays, so
each column chunk decodes straight into one contiguous array (strings
into object arrays that the Batch layer dictionary-encodes) — there is
no per-1024-row block streaming because the consumer is a fused XLA
program, not a per-page operator pipeline.

Scope (the flat-schema core the reference's readers spend most of their
code on): PLAIN / PLAIN_DICTIONARY / RLE_DICTIONARY encodings, the
RLE+bit-packed hybrid for definition levels and dictionary indices,
data pages v1 + v2, dictionary pages, UNCOMPRESSED/SNAPPY/GZIP/ZSTD
codecs (snappy block format decompressed in-repo), BOOLEAN/INT32/INT64/
FLOAT/DOUBLE/BYTE_ARRAY/FIXED_LEN_BYTE_ARRAY physical types with the
UTF8/DATE/TIMESTAMP/DECIMAL converted types, optional fields
(max definition level 1).  Nested schemas (repeated groups) are out of
scope, like the early reference reader.
"""

from __future__ import annotations

import gzip
import io
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T

MAGIC = b"PAR1"


# ---------------------------------------------------------------------------
# thrift compact protocol (the only wire format parquet metadata uses)
# ---------------------------------------------------------------------------


class _Thrift:
    """Minimal thrift compact-protocol reader returning dicts keyed by
    field id (parquet.thrift assigns stable ids; names live in the spec)."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.b = buf
        self.i = pos

    def _u8(self) -> int:
        v = self.b[self.i]
        self.i += 1
        return v

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            v = self._u8()
            out |= (v & 0x7F) << shift
            if not v & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_binary(self) -> bytes:
        n = self.varint()
        out = self.b[self.i:self.i + n]
        self.i += n
        return out

    def skip(self, ftype: int) -> None:
        self.read_value(ftype)

    def read_value(self, ftype: int):
        if ftype in (1, 2):  # BOOLEAN_TRUE / BOOLEAN_FALSE
            return ftype == 1
        if ftype == 3:  # BYTE
            v = struct.unpack_from("b", self.b, self.i)[0]
            self.i += 1
            return v
        if ftype in (4, 5, 6):  # I16 / I32 / I64
            return self.zigzag()
        if ftype == 7:  # DOUBLE
            v = struct.unpack_from("<d", self.b, self.i)[0]
            self.i += 8
            return v
        if ftype == 8:  # BINARY / STRING
            return self.read_binary()
        if ftype in (9, 10):  # LIST / SET
            return self.read_list()
        if ftype == 12:  # STRUCT
            return self.read_struct()
        if ftype == 11:  # MAP
            hdr = self._u8()
            if hdr == 0:
                return {}
            n = hdr  # size as varint already? compact: size varint then kv byte
            raise NotImplementedError("thrift map in parquet metadata")
        raise NotImplementedError(f"thrift compact type {ftype}")

    def read_list(self):
        hdr = self._u8()
        size = hdr >> 4
        etype = hdr & 0x0F
        if size == 15:
            size = self.varint()
        return [self.read_value(etype) for _ in range(size)]

    def read_struct(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        fid = 0
        while True:
            hdr = self._u8()
            if hdr == 0:  # STOP
                return out
            delta = hdr >> 4
            ftype = hdr & 0x0F
            if delta == 0:
                fid = self.zigzag()
            else:
                fid += delta
            out[fid] = self.read_value(ftype)


# ---------------------------------------------------------------------------
# snappy block-format decompression (no python-snappy in the image)
# ---------------------------------------------------------------------------


def snappy_decompress(data: bytes) -> bytes:
    """Raw snappy block format (the framing parquet uses none of):
    varint uncompressed length, then literal/copy tagged elements."""
    i = 0
    n = 0
    shift = 0
    while True:
        v = data[i]
        i += 1
        n |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    out = bytearray(n)
    o = 0
    while i < len(data):
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:
                nbytes = ln - 60
                ln = int.from_bytes(data[i:i + nbytes], "little") + 1
                i += nbytes
            out[o:o + ln] = data[i:i + ln]
            i += ln
            o += ln
            continue
        if kind == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[i]
            i += 1
        elif kind == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 2], "little")
            i += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 4], "little")
            i += 4
        # overlapping copies are the RLE mechanism: byte-at-a-time when
        # the window is shorter than the run
        if off >= ln:
            out[o:o + ln] = out[o - off:o - off + ln]
            o += ln
        else:
            for _ in range(ln):
                out[o] = out[o - off]
                o += 1
    return bytes(out[:o])


def _decompress(codec: int, data: bytes, uncompressed_size: int) -> bytes:
    if codec == 0:  # UNCOMPRESSED
        return data
    if codec == 1:  # SNAPPY
        return snappy_decompress(data)
    if codec == 2:  # GZIP
        return gzip.decompress(data)
    if codec == 6:  # ZSTD
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            data, max_output_size=uncompressed_size)
    raise NotImplementedError(f"parquet codec {codec}")


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (levels + dictionary indices)
# ---------------------------------------------------------------------------


def _rle_bp_decode(data: bytes, bit_width: int, count: int) -> np.ndarray:
    """Parquet's RLE/bit-packing hybrid (format/Encodings.md; reference:
    parquet-column's RunLengthBitPackingHybridDecoder)."""
    out = np.empty(count, np.int64)
    o = 0
    i = 0
    if bit_width == 0:
        out[:] = 0
        return out
    byte_w = (bit_width + 7) // 8
    while o < count and i < len(data):
        # varint header
        hdr = 0
        shift = 0
        while True:
            v = data[i]
            i += 1
            hdr |= (v & 0x7F) << shift
            if not v & 0x80:
                break
            shift += 7
        if hdr & 1:  # bit-packed run: (hdr >> 1) groups of 8 values
            n_groups = hdr >> 1
            n_vals = n_groups * 8
            n_bytes = n_groups * bit_width
            chunk = np.frombuffer(data[i:i + n_bytes], np.uint8)
            i += n_bytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            decoded = vals @ weights
            take = min(n_vals, count - o)
            out[o:o + take] = decoded[:take]
            o += take
        else:  # RLE run
            run = hdr >> 1
            v = int.from_bytes(data[i:i + byte_w], "little")
            i += byte_w
            take = min(run, count - o)
            out[o:o + take] = v
            o += take
    return out


def _delta_binary_decode(data: bytes, count: int
                         ) -> Tuple[np.ndarray, int]:
    """DELTA_BINARY_PACKED (format/Encodings.md; v2 integer pages):
    header = block_size, miniblocks/block, total_count, first_value;
    blocks = min_delta + per-miniblock bit widths + bit-packed deltas.
    Returns (values, bytes_consumed)."""
    t = _Thrift(data)
    block_size = t.varint()
    n_mini = t.varint()
    total = t.varint()
    first = t.zigzag()
    out = np.empty(max(total, 1), np.int64)
    out[0] = first
    filled = 1
    per_mini = block_size // max(n_mini, 1)
    while filled < total:
        min_delta = t.zigzag()
        widths = [t._u8() for _ in range(n_mini)]
        for w in widths:
            if filled >= total:
                # trailing miniblock bytes are still present in the
                # stream and must be consumed
                t.i += (w * per_mini + 7) // 8
                continue
            nbytes = (w * per_mini + 7) // 8
            chunk = np.frombuffer(t.b[t.i:t.i + nbytes], np.uint8)
            t.i += nbytes
            if w == 0:
                deltas = np.zeros(per_mini, np.int64)
            else:
                bits = np.unpackbits(chunk, bitorder="little")
                usable = (len(bits) // w) * w
                vals = bits[:usable].reshape(-1, w)
                weights = (1 << np.arange(w, dtype=np.int64))
                deltas = (vals @ weights)[:per_mini]
            take = min(per_mini, total - filled)
            d = deltas[:take] + min_delta
            out[filled:filled + take] = out[filled - 1] + np.cumsum(d)
            filled += take
    return out[:total], t.i


# ---------------------------------------------------------------------------
# value decoding
# ---------------------------------------------------------------------------

_PLAIN_NP = {1: np.int32, 2: np.int64, 4: np.float32, 5: np.float64}


def _plain_decode(ptype: int, data: bytes, count: int, type_length: int):
    if ptype == 0:  # BOOLEAN: bit-packed LSB-first
        bits = np.unpackbits(np.frombuffer(data, np.uint8),
                             bitorder="little")
        return bits[:count].astype(bool), len(data)
    if ptype in _PLAIN_NP:
        dt = np.dtype(_PLAIN_NP[ptype]).newbyteorder("<")
        nb = dt.itemsize * count
        return np.frombuffer(data[:nb], dt).copy(), nb
    if ptype == 6:  # BYTE_ARRAY: u32 length prefix per value
        out = np.empty(count, object)
        i = 0
        for k in range(count):
            n = int.from_bytes(data[i:i + 4], "little")
            i += 4
            out[k] = data[i:i + n]
            i += n
        return out, i
    if ptype == 7:  # FIXED_LEN_BYTE_ARRAY
        out = np.empty(count, object)
        i = 0
        for k in range(count):
            out[k] = data[i:i + type_length]
            i += type_length
        return out, i
    if ptype == 3:  # INT96 (legacy impala timestamps)
        raw = np.frombuffer(data[:12 * count], np.uint8).reshape(-1, 12)
        nanos = raw[:, :8].copy().view("<u8").reshape(-1).astype(np.int64)
        jdays = raw[:, 8:].copy().view("<u4").reshape(-1).astype(np.int64)
        micros = (jdays - 2440588) * 86_400_000_000 + nanos // 1000
        return micros, 12 * count
    raise NotImplementedError(f"parquet physical type {ptype}")


# ---------------------------------------------------------------------------
# file reader
# ---------------------------------------------------------------------------


class ParquetColumn:
    def __init__(self, name, ptype, type_length, optional, converted,
                 scale, precision, logical):
        self.name = name
        self.ptype = ptype
        self.type_length = type_length
        self.optional = optional
        self.converted = converted
        self.scale = scale
        self.precision = precision
        self.logical = logical  # LogicalType struct (field-id dict)

    def sql_type(self) -> T.Type:
        """Parquet (physical, converted/logical) -> engine type
        (reference: ParquetTypeUtils.getPrestoType)."""
        c = self.converted
        lt = self.logical or {}
        if self.ptype == 0:
            return T.BOOLEAN
        if self.ptype == 1:  # INT32
            if c == 6:  # DATE
                return T.DATE
            if c == 5 and self.precision:  # DECIMAL
                return T.decimal(self.precision, self.scale)
            return T.INTEGER
        if self.ptype == 2:  # INT64
            if c in (9, 10) or 8 in lt:  # TIMESTAMP_MILLIS/MICROS
                return T.TIMESTAMP
            if c == 5 and self.precision:
                return T.decimal(self.precision, self.scale)
            return T.BIGINT
        if self.ptype == 3:
            return T.TIMESTAMP
        if self.ptype == 4:
            return T.REAL
        if self.ptype == 5:
            return T.DOUBLE
        if self.ptype in (6, 7):
            if c == 0 or 1 in lt:  # UTF8 / StringType
                return T.VARCHAR
            if c == 5 and self.precision:
                return T.decimal(self.precision, self.scale)
            return T.VARBINARY
        raise NotImplementedError(f"parquet type {self.ptype}")


class ParquetFile:
    """One .parquet file: schema + row groups, column-chunk decoding."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            f.seek(0, io.SEEK_END)
            size = f.tell()
            f.seek(size - 8)
            meta_len = int.from_bytes(f.read(4), "little")
            assert f.read(4) == MAGIC, "not a parquet file"
            f.seek(size - 8 - meta_len)
            meta_buf = f.read(meta_len)
        md = _Thrift(meta_buf).read_struct()
        # FileMetaData: 2=schema, 3=num_rows, 4=row_groups
        self.num_rows = md.get(3, 0)
        self.columns = self._parse_schema(md[2])
        self.row_groups = md.get(4, [])

    def _parse_schema(self, elements) -> List[ParquetColumn]:
        # SchemaElement: 1=type, 2=type_length, 3=repetition_type,
        # 4=name, 5=num_children, 6=converted_type, 7=scale,
        # 8=precision, 10=logicalType
        root = elements[0]
        if root.get(5, 0) != len(elements) - 1:
            # nested groups present: accept only the flat prefix
            flat = []
            i = 1
            while i < len(elements):
                el = elements[i]
                if el.get(5):  # group node: skip its subtree
                    raise NotImplementedError(
                        "nested parquet schemas are not supported")
                flat.append(el)
                i += 1
            elements = [root] + flat
        out = []
        for el in elements[1:]:
            rep = el.get(3, 0)  # 0=required 1=optional 2=repeated
            if rep == 2:
                raise NotImplementedError("repeated parquet fields")
            out.append(ParquetColumn(
                name=el[4].decode(), ptype=el.get(1, 0),
                type_length=el.get(2, 0), optional=rep == 1,
                converted=el.get(6, -1), scale=el.get(7, 0),
                precision=el.get(8, 0), logical=el.get(10)))
        return out

    def rg_stats(self, rg_index: int, col: ParquetColumn):
        """(min, max, null_count) for one column chunk from the footer
        Statistics, or None when absent/undecodable (reference:
        TupleDomainParquetPredicate reading ColumnChunkMetaData stats).
        Written by both this module's writer and any conformant one."""
        rg = self.row_groups[rg_index]
        for cc in rg[1]:
            meta = cc[3]
            if [p.decode() for p in meta[3]] == [col.name]:
                st = meta.get(12)
                if not isinstance(st, dict):
                    return None
                mn_raw = st.get(6, st.get(2))  # min_value, else legacy
                mx_raw = st.get(5, st.get(1))
                nulls = st.get(3, 0)
                if mn_raw is None or mx_raw is None:
                    return None
                mn = _stat_decode(mn_raw, col.ptype, col)
                mx = _stat_decode(mx_raw, col.ptype, col)
                if mn is None or mx is None:
                    return None
                return mn, mx, nulls
        return None

    def rg_byte_size(self, rg_index: int) -> int:
        rg = self.row_groups[rg_index]
        if 2 in rg:  # total_byte_size (avoid the O(ncols) fallback sum)
            return rg[2]
        return sum(cc[3].get(7, 0) for cc in rg[1])

    # -- column chunk decode ------------------------------------------
    def read_column(self, rg_index: int, col: ParquetColumn
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """(values, validity) for one column chunk (reference:
        reader/PageReader + the Plain/Dictionary column readers)."""
        rg = self.row_groups[rg_index]
        # RowGroup: 1=columns, 2=total_byte_size, 3=num_rows
        chunk = None
        for cc in rg[1]:
            meta = cc[3]  # ColumnMetaData
            path = [p.decode() for p in meta[3]]
            if path == [col.name]:
                chunk = meta
                break
        if chunk is None:
            raise KeyError(f"column {col.name} not in row group")
        codec = chunk.get(4, 0)
        num_values = chunk[5]
        data_off = chunk[9]
        dict_off = chunk.get(11)
        start = min(data_off, dict_off) if dict_off else data_off
        total = chunk[7]  # total_compressed_size
        with open(self.path, "rb") as f:
            f.seek(start)
            buf = f.read(total)

        values = np.empty(num_values, object) \
            if col.ptype in (6, 7) else np.empty(num_values, np.float64)
        defined = np.ones(num_values, bool)
        dictionary = None
        filled = 0
        typed_parts: List[np.ndarray] = []
        i = 0
        while filled < num_values:
            th = _Thrift(buf, i)
            ph = th.read_struct()
            i = th.i
            # PageHeader: 1=type, 2=uncompressed, 3=compressed,
            # 5=data_page_header, 7=dictionary_page_header, 8=v2
            ptype_pg = ph[1]
            comp = ph[3]
            raw = buf[i:i + comp]
            i += comp
            if ptype_pg == 2:  # DICTIONARY_PAGE
                page = _decompress(codec, raw, ph[2])
                dph = ph[7]  # 1=num_values, 2=encoding
                dictionary, _ = _plain_decode(col.ptype, page, dph[1],
                                              col.type_length)
                continue
            if ptype_pg == 0:  # DATA_PAGE v1
                page = _decompress(codec, raw, ph[2])
                dp = ph[5]  # 1=num_values, 2=encoding, 3=def_enc, 4=rep_enc
                n = dp[1]
                enc = dp[2]
                pos = 0
                if col.optional:
                    ln = int.from_bytes(page[pos:pos + 4], "little")
                    pos += 4
                    levels = _rle_bp_decode(page[pos:pos + ln], 1, n)
                    pos += ln
                    present = levels.astype(bool)
                else:
                    present = np.ones(n, bool)
            elif ptype_pg == 3:  # DATA_PAGE_V2
                dp = ph[8]
                # 1=num_values, 2=num_nulls, 3=num_rows, 4=encoding,
                # 5=def_len, 6=rep_len, 7=is_compressed
                n = dp[1]
                enc = dp[4]
                dlen = dp.get(5, 0)
                rlen = dp.get(6, 0)
                lev = raw[:dlen + rlen]
                body = raw[dlen + rlen:]
                if dp.get(7, True):
                    body = _decompress(codec, body,
                                       ph[2] - dlen - rlen)
                if col.optional and dlen:
                    levels = _rle_bp_decode(lev[rlen:rlen + dlen], 1, n)
                    present = levels.astype(bool)
                else:
                    present = np.ones(n, bool)
                page = body
                pos = 0
            else:
                continue  # index pages etc.

            n_present = int(present.sum())
            if enc == 0:  # PLAIN
                vals, _used = _plain_decode(col.ptype, page[pos:],
                                            n_present, col.type_length)
            elif enc in (2, 8):  # PLAIN_DICTIONARY / RLE_DICTIONARY
                bw = page[pos]
                pos += 1
                idx = _rle_bp_decode(page[pos:], bw, n_present)
                if dictionary is None:
                    raise ValueError("dictionary page missing")
                vals = dictionary[np.clip(idx, 0,
                                          max(len(dictionary) - 1, 0))]
            elif enc == 3:  # RLE (v2 boolean values; u32 length prefix)
                ln = int.from_bytes(page[pos:pos + 4], "little")
                pos += 4
                vals = _rle_bp_decode(page[pos:pos + ln], 1,
                                      n_present).astype(bool)
            elif enc == 5:  # DELTA_BINARY_PACKED (v2 ints)
                vals, _used = _delta_binary_decode(page[pos:], n_present)
                if col.ptype == 1:
                    vals = vals.astype(np.int32)
            elif enc == 6:  # DELTA_LENGTH_BYTE_ARRAY (v2 strings)
                lens, used = _delta_binary_decode(page[pos:], n_present)
                body = page[pos + used:]
                vals = np.empty(n_present, object)
                o = 0
                for k in range(n_present):
                    ln = int(lens[k])
                    vals[k] = bytes(body[o:o + ln])
                    o += ln
            elif enc == 7:  # DELTA_BYTE_ARRAY (prefix + suffix deltas)
                pref, used1 = _delta_binary_decode(page[pos:], n_present)
                sufl, used2 = _delta_binary_decode(
                    page[pos + used1:], n_present)
                body = page[pos + used1 + used2:]
                vals = np.empty(n_present, object)
                o = 0
                prev = b""
                for k in range(n_present):
                    ln = int(sufl[k])
                    prev = prev[:int(pref[k])] + bytes(body[o:o + ln])
                    o += ln
                    vals[k] = prev
            else:
                raise NotImplementedError(f"parquet encoding {enc}")
            page_vals = np.empty(
                n, object if col.ptype in (6, 7) else vals.dtype)
            page_vals[present] = vals
            typed_parts.append(page_vals)
            defined[filled:filled + n] = present
            filled += n

        allv = np.concatenate(typed_parts) if typed_parts else \
            np.empty(0, object)
        valid = defined if col.optional and not defined.all() else None
        return self._convert(col, allv, valid)

    def _convert(self, col: ParquetColumn, vals: np.ndarray,
                 valid: Optional[np.ndarray]):
        """Physical values -> the engine's physical representation."""
        t = col.sql_type()
        fill0 = valid is not None
        if t.name == "VARCHAR":
            out = np.empty(len(vals), object)
            for k, v in enumerate(vals):
                out[k] = v.decode("utf-8", "replace") \
                    if isinstance(v, bytes) else ("" if v is None else v)
            return out, valid, t
        if t.name == "VARBINARY":
            out = np.empty(len(vals), object)
            for k, v in enumerate(vals):
                out[k] = v if isinstance(v, bytes) else b""
            return out, valid, t
        if t.is_decimal and col.ptype in (6, 7):
            out = np.empty(len(vals), np.int64)
            for k, v in enumerate(vals):
                out[k] = int.from_bytes(v, "big", signed=True) \
                    if isinstance(v, bytes) and len(v) else 0
            return out, valid, t
        if t.name == "TIMESTAMP" and col.ptype == 2:
            arr = np.where(valid, vals, 0) if fill0 else vals
            arr = arr.astype(np.int64)
            if col.converted == 9 or _ts_unit_is_millis(col.logical):
                arr = arr * 1000  # millis -> engine micros
            return arr, valid, t
        dt = t.numpy_dtype()
        arr = np.where(valid, vals, 0) if fill0 else vals
        return np.asarray(arr).astype(dt), valid, t


def _ts_unit_is_millis(logical) -> bool:
    # LogicalType: 8=TIMESTAMP{1=isAdjustedToUTC, 2=unit{1=MILLIS,...}}
    try:
        unit = logical[8][2]
        return 1 in unit
    except (KeyError, TypeError):
        return False


# ---------------------------------------------------------------------------
# writer (reference: presto-parquet writer/ — ParquetWriter,
# PrimitiveColumnWriter; PLAIN encoding, v1 data pages, one row group)
# ---------------------------------------------------------------------------


class _TWrite:
    """Minimal thrift compact-protocol writer."""

    def __init__(self):
        self.out = bytearray()
        self._fid = [0]

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, fid: int, ftype: int) -> None:
        delta = fid - self._fid[-1]
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ftype)
        else:
            self.out.append(ftype)
            self.zigzag(fid)
        self._fid[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self.field(fid, 5)
        self.zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self.field(fid, 6)
        self.zigzag(v)

    def binary(self, fid: int, v: bytes) -> None:
        self.field(fid, 8)
        self.varint(len(v))
        self.out += v

    def begin_struct(self, fid: int) -> None:
        self.field(fid, 12)
        self._fid.append(0)

    def end_struct(self) -> None:
        self.out.append(0)
        self._fid.pop()

    def begin_list(self, fid: int, etype: int, size: int) -> None:
        self.field(fid, 9)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self.varint(size)


def _rle_encode_levels(levels: np.ndarray) -> bytes:
    """Definition levels (bit width 1) as one RLE-run-per-change —
    tiny and always valid."""
    out = bytearray()
    i = 0
    n = len(levels)
    while i < n:
        v = int(levels[i])
        j = i
        while j < n and levels[j] == v:
            j += 1
        run = j - i
        hdr = run << 1  # RLE run
        while True:
            b = hdr & 0x7F
            hdr >>= 7
            if hdr:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        out.append(v)
        i = j
    return bytes(out)


def _parquet_physical(t: T.Type):
    """engine type -> (physical type id, converted type id or -1)."""
    if t.name == "BOOLEAN":
        return 0, -1
    if t.name in ("TINYINT", "SMALLINT", "INTEGER"):
        return 1, -1
    if t.name == "DATE":
        return 1, 6
    if t.name == "BIGINT" or (t.is_decimal and not t.is_long_decimal):
        return 2, 5 if t.is_decimal else -1
    if t.name == "TIMESTAMP":
        return 2, 10  # TIMESTAMP_MICROS
    if t.name == "REAL":
        return 4, -1
    if t.name == "DOUBLE":
        return 5, -1
    if t.name == "VARBINARY":
        return 6, -1
    if t.is_string:
        return 6, 0  # BYTE_ARRAY + UTF8
    raise NotImplementedError(f"parquet write of {t}")


def _plain_encode(ptype: int, vals, t: T.Type) -> bytes:
    if ptype == 0:
        return np.packbits(np.asarray(vals, bool),
                           bitorder="little").tobytes()
    if ptype == 1:
        return np.asarray(vals).astype("<i4").tobytes()
    if ptype == 2:
        return np.asarray(vals).astype("<i8").tobytes()
    if ptype == 4:
        return np.asarray(vals).astype("<f4").tobytes()
    if ptype == 5:
        return np.asarray(vals).astype("<f8").tobytes()
    out = bytearray()
    for v in vals:
        b = v.encode() if isinstance(v, str) else \
            (bytes(v) if v is not None else b"")
        out += len(b).to_bytes(4, "little")
        out += b
    return bytes(out)


def _stat_bytes(ptype: int, vals, t: T.Type):
    """(min_value, max_value) plain-encoded for the Statistics struct,
    or None when the column has no non-null values / an unordered
    physical type."""
    if len(vals) == 0 or ptype == 0:
        return None
    if ptype in (1, 2, 4, 5):
        a = np.asarray(vals)
        if a.dtype.kind == "f":
            a = a[~np.isnan(a)]  # NaN must not poison the zone map
            if len(a) == 0:
                return None
        lo, hi = a.min(), a.max()
        fmt = {1: "<i4", 2: "<i8", 4: "<f4", 5: "<f8"}[ptype]
        return (np.asarray(lo).astype(fmt).tobytes(),
                np.asarray(hi).astype(fmt).tobytes())
    if ptype == 6 and t.is_string and t.name != "VARBINARY":
        enc = [v.encode() if isinstance(v, str) else bytes(v)
               for v in vals]
        return (min(enc), max(enc))
    return None


def _stat_decode(raw: bytes, ptype: int, col: "ParquetColumn"):
    """Plain-encoded Statistics value -> SQL-space python scalar (days
    for DATE, micros for TIMESTAMP — the same space the planner's
    literals live in)."""
    try:
        if ptype == 1:
            return int(np.frombuffer(raw[:4], "<i4")[0])
        if ptype == 2:
            return int(np.frombuffer(raw[:8], "<i8")[0])
        if ptype == 4:
            return float(np.frombuffer(raw[:4], "<f4")[0])
        if ptype == 5:
            return float(np.frombuffer(raw[:8], "<f8")[0])
        if ptype == 6 and col.converted == 0:  # UTF8
            return raw.decode("utf-8")
    except (ValueError, UnicodeDecodeError):
        return None
    return None


def write_parquet(path: str, arrays: Dict[str, np.ndarray],
                  schema: Dict[str, T.Type],
                  row_group_rows: int = 0) -> int:
    """Write PLAIN-encoded v1 pages (uncompressed) with footer
    Statistics per column chunk.  row_group_rows > 0 splits the rows
    into multiple row groups — the pruning grain of the selective read
    path.  Readable by this module AND by any conformant reader — the
    tests cross-check with an independent implementation."""
    cols = list(schema)
    n = len(next(iter(arrays.values()))) if arrays else 0
    grp = row_group_rows if row_group_rows > 0 else max(n, 1)
    bounds = [(s, min(s + grp, n)) for s in range(0, max(n, 1), grp)]
    body = io.BytesIO()
    body.write(MAGIC)
    groups = []  # [(rows, [(c, ptype, conv, off, tot, optional, t, stat, nulls)])]
    for g0, g1 in bounds:
        chunk_meta = []
        for c in cols:
            t = schema[c]
            a = arrays[c][g0:g1]
            if isinstance(a, np.ma.MaskedArray):
                valid = ~np.ma.getmaskarray(a)
                a = a.filled("" if t.is_string else 0)
            else:
                valid = None
            ptype, conv = _parquet_physical(t)
            optional = valid is not None
            if optional:
                levels = valid.astype(np.int64)
                lev = _rle_encode_levels(levels)
                lev_block = len(lev).to_bytes(4, "little") + lev
                vals = np.asarray(a)[valid]
            else:
                lev_block = b""
                vals = np.asarray(a)
            payload = lev_block + _plain_encode(ptype, vals, t)
            nulls = 0 if valid is None else int((~valid).sum())
            stat = _stat_bytes(ptype, vals, t)
            ph = _TWrite()
            ph.i32(1, 0)  # type = DATA_PAGE
            ph.i32(2, len(payload))  # uncompressed
            ph.i32(3, len(payload))  # compressed (none)
            ph.begin_struct(5)  # data_page_header
            ph.i32(1, g1 - g0)
            ph.i32(2, 0)  # PLAIN
            ph.i32(3, 3)  # def levels: RLE
            ph.i32(4, 3)  # rep levels: RLE
            ph.end_struct()
            ph.out.append(0)  # end PageHeader struct
            off = body.tell()
            body.write(bytes(ph.out))
            body.write(payload)
            total = body.tell() - off
            chunk_meta.append((c, ptype, conv, off, total, optional, t,
                               stat, nulls))
        groups.append((g1 - g0, chunk_meta))

    # FileMetaData
    md = _TWrite()
    md.i32(1, 1)  # version
    # schema list: root + columns
    md.begin_list(2, 12, len(cols) + 1)
    root = _TWrite()
    root.binary(4, b"schema")
    root.i32(5, len(cols))
    root.out.append(0)
    md.out += root.out
    for c, ptype, conv, _off, _tot, optional, t, _st, _nu in groups[0][1]:
        el = _TWrite()
        el.i32(1, ptype)
        el.i32(3, 1 if optional else 0)  # repetition
        el.binary(4, c.encode())
        if conv >= 0:
            el.i32(6, conv)
        if t.is_decimal:
            el.i32(7, t.decimal_scale)
            el.i32(8, t.decimal_precision)
        el.out.append(0)
        md.out += el.out
    md.i64(3, n)  # num_rows
    md.begin_list(4, 12, len(groups))
    for g_rows, chunk_meta in groups:
        rg = _TWrite()
        rg.begin_list(1, 12, len(cols))
        total_bytes = 0
        for c, ptype, conv, off, tot, optional, t, stat, nulls in chunk_meta:
            cc = _TWrite()
            cc.i64(2, off)  # file_offset
            cc.begin_struct(3)  # ColumnMetaData
            cc.i32(1, ptype)
            cc.begin_list(2, 5, 1)
            cc.zigzag(0)  # encodings: [PLAIN]
            cc.begin_list(3, 8, 1)
            cc.varint(len(c.encode()))
            cc.out += c.encode()
            cc.i32(4, 0)  # codec: UNCOMPRESSED
            cc.i64(5, g_rows)  # num_values
            cc.i64(6, tot)  # total_uncompressed_size
            cc.i64(7, tot)  # total_compressed_size
            cc.i64(9, off)  # data_page_offset
            if stat is not None or nulls:
                # Statistics (field 12): 3=null_count, 5=max_value,
                # 6=min_value — the zone map the selective read path
                # prunes on (reference: OrcSelectiveRecordReader /
                # parquet TupleDomainParquetPredicate)
                cc.begin_struct(12)
                cc.i64(3, nulls)
                if stat is not None:
                    cc.binary(5, stat[1])
                    cc.binary(6, stat[0])
                cc.end_struct()
            cc.end_struct()
            cc.out.append(0)  # end ColumnChunk
            rg.out += cc.out
            total_bytes += tot
        rg.i64(2, total_bytes)
        rg.i64(3, g_rows)
        rg.out.append(0)  # end RowGroup
        md.out += rg.out
    # column_orders (field 7): TYPE_ORDER for every column — readers
    # ignore min_value/max_value statistics unless this is present
    md.begin_list(7, 12, len(cols))
    for _ in cols:
        co = _TWrite()
        co.begin_struct(1)  # ColumnOrder.TYPE_ORDER
        co.end_struct()
        co.out.append(0)  # end ColumnOrder union
        md.out += co.out
    md.out.append(0)  # end FileMetaData
    meta = bytes(md.out)
    body.write(meta)
    body.write(len(meta).to_bytes(4, "little"))
    body.write(MAGIC)
    with open(path, "wb") as f:
        f.write(body.getvalue())
    return n
