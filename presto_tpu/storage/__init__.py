from presto_tpu.storage.shard import (  # noqa: F401
    Domain, ShardReader, write_shard)
