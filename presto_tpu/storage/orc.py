"""ORC read path — an in-engine decoder, no external ORC library.

Reference parity: presto-orc/ (OrcReader, StripeReader, the stream
readers under stream/ and reader/ — the reference's single biggest
connector-side codebase at ~54k LoC).  TPU-native adaptation mirrors
storage/parquet.py: column chunks decode straight into whole numpy
arrays for one fused XLA consumer, so the reader keeps ORC's layout
smarts (stripes, RLE families, dictionary encodings) and drops the
per-batch streaming scaffolding.

Scope: the ORC v1 (0.12) core — protobuf-decoded postscript/footer/
stripe footers, ZLIB/SNAPPY/ZSTD/LZ4/NONE block compression, byte RLE,
boolean RLE, integer RLE v1 + all four RLE v2 sub-encodings (short
repeat / direct / delta / patched base), PRESENT streams, and the
BOOLEAN/BYTE/SHORT/INT/LONG/FLOAT/DOUBLE/STRING (direct + dictionary)/
BINARY/DATE/TIMESTAMP/DECIMAL column types over flat schemas.
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.storage.parquet import snappy_decompress

MAGIC = b"ORC"


# ---------------------------------------------------------------------------
# protobuf wire-format reader (ORC metadata is proto, not thrift)
# ---------------------------------------------------------------------------


class _Proto:
    def __init__(self, buf: bytes):
        self.b = buf
        self.i = 0

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            v = self.b[self.i]
            self.i += 1
            out |= (v & 0x7F) << shift
            if not v & 0x80:
                return out
            shift += 7

    def read_message(self) -> Dict[int, list]:
        """Message -> {field_number: [values...]} (repeated fields keep
        every occurrence; submessages stay as raw bytes for the caller
        to parse with the right shape)."""
        out: Dict[int, list] = {}
        n = len(self.b)
        while self.i < n:
            key = self.varint()
            fnum = key >> 3
            wt = key & 7
            if wt == 0:
                v = self.varint()
            elif wt == 1:
                v = struct.unpack_from("<q", self.b, self.i)[0]
                self.i += 8
            elif wt == 2:
                ln = self.varint()
                v = self.b[self.i:self.i + ln]
                self.i += ln
            elif wt == 5:
                v = struct.unpack_from("<i", self.b, self.i)[0]
                self.i += 4
            else:
                raise NotImplementedError(f"proto wire type {wt}")
            out.setdefault(fnum, []).append(v)
        return out


def _msg(buf: bytes) -> Dict[int, list]:
    return _Proto(buf).read_message()


def _packed_varints(buf: bytes) -> List[int]:
    p = _Proto(buf)
    out = []
    while p.i < len(buf):
        out.append(p.varint())
    return out


# ---------------------------------------------------------------------------
# compression framing + codecs
# ---------------------------------------------------------------------------


def _lz4_block_decompress(data: bytes, max_out: int) -> bytes:
    """LZ4 block format (no frame), pure python."""
    out = bytearray()
    i = 0
    n = len(data)
    while i < n:
        token = data[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                v = data[i]
                i += 1
                lit += v
                if v != 255:
                    break
        out += data[i:i + lit]
        i += lit
        if i >= n:
            break
        off = int.from_bytes(data[i:i + 2], "little")
        i += 2
        ml = token & 0xF
        if ml == 15:
            while True:
                v = data[i]
                i += 1
                ml += v
                if v != 255:
                    break
        ml += 4
        if off >= ml:
            start = len(out) - off
            out += out[start:start + ml]
        else:
            for _ in range(ml):
                out.append(out[-off])
    return bytes(out)


def _decompress_stream(codec: int, data: bytes, block_size: int) -> bytes:
    """ORC chunked compression: 3-byte little-endian header per chunk,
    LSB = isOriginal (uncompressed)."""
    if codec == 0:  # NONE
        return data
    out = bytearray()
    i = 0
    while i + 3 <= len(data):
        hdr = int.from_bytes(data[i:i + 3], "little")
        i += 3
        orig = hdr & 1
        ln = hdr >> 1
        chunk = data[i:i + ln]
        i += ln
        if orig:
            out += chunk
        elif codec == 1:  # ZLIB (raw deflate)
            out += zlib.decompress(chunk, wbits=-15)
        elif codec == 2:  # SNAPPY
            out += snappy_decompress(chunk)
        elif codec == 4:  # LZ4
            out += _lz4_block_decompress(chunk, block_size)
        elif codec == 5:  # ZSTD
            import zstandard

            out += zstandard.ZstdDecompressor().decompress(
                chunk, max_output_size=block_size)
        else:
            raise NotImplementedError(f"orc compression kind {codec}")
    return bytes(out)


# ---------------------------------------------------------------------------
# RLE decoders (reference: stream/LongInputStreamV1/V2, ByteInputStream,
# BooleanInputStream)
# ---------------------------------------------------------------------------


def _byte_rle(data: bytes, count: int) -> np.ndarray:
    out = np.empty(count, np.uint8)
    o = 0
    i = 0
    while o < count and i < len(data):
        h = data[i]
        i += 1
        if h < 128:  # run of h+3 copies
            run = h + 3
            out[o:o + run] = data[i]
            i += 1
            o += run
        else:  # 256-h literals
            lit = 256 - h
            out[o:o + lit] = np.frombuffer(data[i:i + lit], np.uint8)
            i += lit
            o += lit
    return out[:count]


def _bool_rle(data: bytes, count: int) -> np.ndarray:
    nbytes = (count + 7) // 8
    by = _byte_rle(data, nbytes)
    bits = np.unpackbits(by, bitorder="big")
    return bits[:count].astype(bool)


def _zigzag_np(v: np.ndarray) -> np.ndarray:
    u = v.astype(np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)
            ^ -(v & 1).astype(np.int64))


class _IntRle:
    """Integer RLE, both versions (reference: LongInputStreamV1/V2)."""

    def __init__(self, data: bytes, signed: bool, v2: bool):
        self.b = data
        self.i = 0
        self.signed = signed
        self.v2 = v2

    def _varint(self) -> int:
        out = 0
        shift = 0
        while True:
            v = self.b[self.i]
            self.i += 1
            out |= (v & 0x7F) << shift
            if not v & 0x80:
                return out
            shift += 7

    def _svarint(self) -> int:
        v = self._varint()
        return (v >> 1) ^ -(v & 1)

    def read(self, count: int) -> np.ndarray:
        out = np.empty(count, np.int64)
        o = 0
        while o < count:
            if self.v2:
                o = self._read_v2(out, o, count)
            else:
                o = self._read_v1(out, o, count)
        return out

    # -- v1 -----------------------------------------------------------
    def _read_v1(self, out, o, count) -> int:
        h = self.b[self.i]
        self.i += 1
        if h < 128:  # run: h+3 values, delta byte, base varint
            run = h + 3
            delta = struct.unpack_from("b", self.b, self.i)[0]
            self.i += 1
            base = self._svarint() if self.signed else self._varint()
            take = min(run, count - o)
            out[o:o + take] = base + delta * np.arange(take)
            return o + take
        lit = 256 - h
        for k in range(min(lit, count - o)):
            out[o + k] = self._svarint() if self.signed else self._varint()
        return o + min(lit, count - o)

    # -- v2 -----------------------------------------------------------
    _W = [1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64]  # 5-bit width table
    _WIDTH = [0, 0, 1, 2, 4, 8, 16, 24, 32, 40, 48, 52, 56, 60, 62, 64]

    @classmethod
    def _decode_width(cls, enc: int) -> int:
        """The 5-bit encoded bit width (Table in the ORC spec)."""
        if enc <= 23:
            return enc + 1
        return {24: 26, 25: 28, 26: 30, 27: 32, 28: 40,
                29: 48, 30: 56, 31: 64}[enc]

    def _bits(self, n_vals: int, width: int) -> np.ndarray:
        nbytes = (n_vals * width + 7) // 8
        chunk = np.frombuffer(self.b[self.i:self.i + nbytes], np.uint8)
        self.i += nbytes
        if width == 0:
            return np.zeros(n_vals, np.int64)
        bits = np.unpackbits(chunk, bitorder="big")
        need = n_vals * width
        bits = bits[:need].reshape(n_vals, width)
        weights = (1 << np.arange(width - 1, -1, -1, dtype=np.uint64))
        return (bits.astype(np.uint64) @ weights).astype(np.int64)

    def _read_v2(self, out, o, count) -> int:
        h = self.b[self.i]
        kind = h >> 6
        if kind == 0:  # SHORT_REPEAT
            width = ((h >> 3) & 0x7) + 1
            run = (h & 0x7) + 3
            self.i += 1
            v = int.from_bytes(self.b[self.i:self.i + width], "big")
            self.i += width
            if self.signed:
                v = (v >> 1) ^ -(v & 1)
            take = min(run, count - o)
            out[o:o + take] = v
            return o + take
        if kind == 1:  # DIRECT
            width = self._decode_width((h >> 1) & 0x1F)
            n = (((h & 1) << 8) | self.b[self.i + 1]) + 1
            self.i += 2
            vals = self._bits(n, width)
            if self.signed:
                vals = _zigzag_np(vals)
            take = min(n, count - o)
            out[o:o + take] = vals[:take]
            return o + take
        if kind == 3:  # DELTA
            width_enc = (h >> 1) & 0x1F
            width = 0 if width_enc == 0 else self._decode_width(width_enc)
            n = (((h & 1) << 8) | self.b[self.i + 1]) + 1
            self.i += 2
            base = self._svarint() if self.signed else self._varint()
            delta0 = self._svarint()
            vals = np.empty(n, np.int64)
            vals[0] = base
            if n > 1:
                vals[1] = base + delta0
            if n > 2:
                if width:
                    deltas = self._bits(n - 2, width)
                else:
                    deltas = np.full(n - 2, abs(delta0), np.int64)
                sign = 1 if delta0 >= 0 else -1
                if width:
                    deltas = deltas * sign
                    vals[2:] = vals[1] + np.cumsum(deltas)
                else:
                    vals[2:] = vals[1] + sign * np.cumsum(deltas)
            take = min(n, count - o)
            out[o:o + take] = vals[:take]
            return o + take
        # kind == 2: PATCHED_BASE
        width = self._decode_width((h >> 1) & 0x1F)
        n = (((h & 1) << 8) | self.b[self.i + 1]) + 1
        h3 = self.b[self.i + 2]
        h4 = self.b[self.i + 3]
        self.i += 4
        bw = (h3 >> 5) + 1  # base value width, BYTES
        pw_enc = h3 & 0x1F
        pw = self._decode_width(pw_enc)  # patch width, bits
        pgw = (h4 >> 5) + 1  # patch GAP width, BITS (1..8)
        pll = h4 & 0x1F  # patch list length
        base_raw = int.from_bytes(self.b[self.i:self.i + bw], "big")
        self.i += bw
        msb = 1 << (bw * 8 - 1)
        base = -(base_raw & (msb - 1)) if base_raw & msb else base_raw
        vals = self._bits(n, width)
        # patch entries pack at the closest "fixed bits" width covering
        # gap width + patch width (getClosestFixedBits); gap-filler
        # entries (value 0) extend gaps past 255
        # getClosestFixedBits: 1..24, then 26/28/30/32/40/48/56/64
        need = pgw + pw
        if need <= 24:
            cw = need
        else:
            cw = next(w for w in (26, 28, 30, 32, 40, 48, 56, 64)
                      if w >= need)
        patches = self._bits(pll, cw)
        gaps = (patches >> pw) & ((1 << pgw) - 1)
        pvals = patches & ((1 << pw) - 1)
        pos = 0
        for k in range(pll):
            pos += int(gaps[k])
            v = int(pvals[k])
            if v != 0 and pos < n:
                vals[pos] |= v << width
        vals = vals + base
        take = min(n, count - o)
        out[o:o + take] = vals[:take]
        return o + take


# ---------------------------------------------------------------------------
# file reader
# ---------------------------------------------------------------------------

# proto field ids (orc_proto.proto)
_PS_FOOTER_LEN, _PS_COMPRESSION, _PS_BLOCK, _PS_META_LEN = 1, 2, 3, 5
_FTR_STRIPES, _FTR_TYPES, _FTR_NROWS = 3, 4, 6
_STR_OFFSET, _STR_INDEX_LEN, _STR_DATA_LEN, _STR_FOOTER_LEN, _STR_NROWS = \
    1, 2, 3, 4, 5

_KIND = {0: "boolean", 1: "byte", 2: "short", 3: "int", 4: "long",
         5: "float", 6: "double", 7: "string", 8: "binary",
         9: "timestamp", 10: "list", 11: "map", 12: "struct",
         13: "union", 14: "decimal", 15: "date", 16: "varchar",
         17: "char"}


class OrcColumn:
    def __init__(self, cid: int, kind: str, name: str,
                 precision: int = 0, scale: int = 0):
        self.cid = cid
        self.kind = kind
        self.name = name
        self.precision = precision
        self.scale = scale

    def sql_type(self) -> T.Type:
        k = self.kind
        if k == "boolean":
            return T.BOOLEAN
        if k in ("byte", "short"):
            return T.SMALLINT
        if k == "int":
            return T.INTEGER
        if k == "long":
            return T.BIGINT
        if k == "float":
            return T.REAL
        if k == "double":
            return T.DOUBLE
        if k in ("string", "varchar", "char"):
            return T.VARCHAR
        if k == "binary":
            return T.VARBINARY
        if k == "date":
            return T.DATE
        if k == "timestamp":
            return T.TIMESTAMP
        if k == "decimal":
            return T.decimal(self.precision or 38, self.scale)
        raise NotImplementedError(f"orc type {k}")


class OrcFile:
    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            tail_len = min(size, 16 * 1024)
            f.seek(size - tail_len)
            tail = f.read(tail_len)
        ps_len = tail[-1]
        ps = _msg(tail[-1 - ps_len:-1])
        self.codec = ps.get(_PS_COMPRESSION, [0])[0]
        self.block_size = ps.get(_PS_BLOCK, [262144])[0]
        footer_len = ps[_PS_FOOTER_LEN][0]
        meta_len0 = ps.get(_PS_META_LEN, [0])[0]
        need = min(1 + ps_len + footer_len + meta_len0, size)
        if need > len(tail):
            # many-stripe file: the Metadata section outgrew the probe
            # read — fetch the real tail (the 16 KB guess covers the
            # common case, like the reference's expectedFooterSize)
            with open(path, "rb") as f:
                f.seek(size - need)
                tail = f.read(need)
        footer_raw = tail[-1 - ps_len - footer_len:-1 - ps_len]
        footer = _msg(_decompress_stream(self.codec, footer_raw,
                                         self.block_size))
        self.num_rows = footer.get(_FTR_NROWS, [0])[0]
        types = [_msg(t) for t in footer.get(_FTR_TYPES, [])]
        root = types[0]
        if _KIND[root.get(1, [12])[0]] != "struct":
            raise NotImplementedError("non-struct ORC root")
        subtypes = root.get(2, [])
        if isinstance(subtypes and subtypes[0], bytes):
            # packed repeated uint32
            subtypes = [v for b in subtypes for v in _packed_varints(b)]
        names = [n.decode() for n in root.get(3, [])]
        self.columns: List[OrcColumn] = []
        for cid, name in zip(subtypes, names):
            tmsg = types[cid]
            kind = _KIND[tmsg.get(1, [0])[0]]
            if kind in ("list", "map", "struct", "union"):
                raise NotImplementedError("nested ORC schemas")
            self.columns.append(OrcColumn(
                cid, kind, name,
                precision=tmsg.get(5, [0])[0], scale=tmsg.get(6, [0])[0]))
        self.stripes = [_msg(s) for s in footer.get(_FTR_STRIPES, [])]
        # Metadata section (per-stripe ColumnStatistics; reference:
        # metadata/Metadata.java feeding OrcPredicate stripe pruning)
        meta_len = ps.get(_PS_META_LEN, [0])[0]
        self.stripe_stats: List[Optional[list]] = []
        if meta_len:
            meta_raw = tail[-1 - ps_len - footer_len - meta_len:
                            -1 - ps_len - footer_len]
            try:
                metadata = _msg(_decompress_stream(
                    self.codec, meta_raw, self.block_size))
                self.stripe_stats = [
                    _msg(ss).get(1, []) for ss in metadata.get(1, [])]
            except Exception:
                self.stripe_stats = []  # stats are advisory only

    def stripe_col_stats(self, stripe_index: int, col: "OrcColumn"):
        """(min, max) in SQL space for one column of one stripe, or
        None.  Column ids index the flat type list; entry 0 is the root
        struct."""
        if stripe_index >= len(self.stripe_stats):
            return None
        entries = self.stripe_stats[stripe_index]
        if col.cid >= len(entries):
            return None
        cs = _msg(entries[col.cid])

        def zz(v):
            return (v >> 1) ^ -(v & 1)

        if 2 in cs:  # IntegerStatistics
            sub = _msg(cs[2][0])
            if 1 in sub and 2 in sub:
                return zz(sub[1][0]), zz(sub[2][0])
        if 3 in cs:  # DoubleStatistics (fixed64 doubles)
            sub = _Proto(cs[3][0]).read_message()
            if 1 in sub and 2 in sub:
                mn = struct.unpack("<d", struct.pack("<q", sub[1][0]))[0]
                mx = struct.unpack("<d", struct.pack("<q", sub[2][0]))[0]
                return mn, mx
        if 4 in cs:  # StringStatistics
            sub = _msg(cs[4][0])
            if 1 in sub and 2 in sub:
                try:
                    return sub[1][0].decode(), sub[2][0].decode()
                except UnicodeDecodeError:
                    return None
        if 7 in cs:  # DateStatistics (sint32 days)
            sub = _msg(cs[7][0])
            if 1 in sub and 2 in sub:
                return zz(sub[1][0]), zz(sub[2][0])
        if 9 in cs:  # TimestampStatistics (sint64 MILLIS -> micros)
            sub = _msg(cs[9][0])
            if 1 in sub and 2 in sub:
                return zz(sub[1][0]) * 1000, zz(sub[2][0]) * 1000 + 999
        return None

    # -- stripe decode -------------------------------------------------
    def _stripe_streams(self, st) -> Tuple[dict, dict]:
        """({(column, kind): bytes}, {column: (encoding, dict_size)})."""
        offset = st[_STR_OFFSET][0]
        index_len = st.get(_STR_INDEX_LEN, [0])[0]
        data_len = st.get(_STR_DATA_LEN, [0])[0]
        footer_len = st.get(_STR_FOOTER_LEN, [0])[0]
        with open(self.path, "rb") as f:
            f.seek(offset)
            blob = f.read(index_len + data_len + footer_len)
        sf = _msg(_decompress_stream(
            self.codec, blob[index_len + data_len:], self.block_size))
        streams = [_msg(s) for s in sf.get(1, [])]
        encodings = [_msg(e) for e in sf.get(2, [])]
        out = {}
        pos = 0
        for s in streams:
            kind = s.get(1, [0])[0]
            col = s.get(2, [0])[0]
            ln = s.get(3, [0])[0]
            # indexes precede data; both counted from stripe start
            out[(col, kind)] = (pos, ln)
            pos += ln
        enc = {cid: (e.get(1, [0])[0], e.get(2, [0])[0])
               for cid, e in enumerate(encodings)}
        raw = {k: blob[p:p + ln] for k, (p, ln) in out.items()}
        return raw, enc

    def _stream(self, raw, col, kind) -> bytes:
        data = raw.get((col, kind))
        if data is None:
            return b""
        return _decompress_stream(self.codec, data, self.block_size)

    def read_column(self, stripe_index: int, col: OrcColumn
                    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        st = self.stripes[stripe_index]
        n = st[_STR_NROWS][0]
        raw, encs = self._stripe_streams(st)
        enc_kind, dict_size = encs.get(col.cid, (0, 0))
        # ColumnEncoding.Kind: DIRECT=0 DICTIONARY=1 DIRECT_V2=2
        # DICTIONARY_V2=3
        v2 = enc_kind in (2, 3)
        present_b = self._stream(raw, col.cid, 0)
        present = _bool_rle(present_b, n) if present_b else None
        n_vals = int(present.sum()) if present is not None else n
        data = self._stream(raw, col.cid, 1)
        k = col.kind

        if k == "boolean":
            vals = _bool_rle(data, n_vals)
        elif k == "byte":
            vals = _byte_rle(data, n_vals).astype(np.int8).astype(np.int64)
        elif k in ("short", "int", "long", "date"):
            vals = _IntRle(data, signed=True, v2=v2).read(n_vals)
        elif k == "float":
            vals = np.frombuffer(data[:4 * n_vals], "<f4").copy()
        elif k == "double":
            vals = np.frombuffer(data[:8 * n_vals], "<f8").copy()
        elif k in ("string", "varchar", "char", "binary"):
            length_b = self._stream(raw, col.cid, 2)
            if enc_kind in (1, 3):  # DICTIONARY / DICTIONARY_V2
                dict_b = self._stream(raw, col.cid, 3)
                lens = _IntRle(length_b, False, v2).read(dict_size)
                dvals = np.empty(dict_size, object)
                o = 0
                for i2 in range(dict_size):
                    ln = int(lens[i2])
                    dvals[i2] = dict_b[o:o + ln]
                    o += ln
                codes = _IntRle(data, False, v2).read(n_vals)
                vals = dvals[np.clip(codes, 0,
                                     max(dict_size - 1, 0))]
            else:
                lens = _IntRle(length_b, False, v2).read(n_vals)
                vals = np.empty(n_vals, object)
                o = 0
                for i2 in range(n_vals):
                    ln = int(lens[i2])
                    vals[i2] = data[o:o + ln]
                    o += ln
        elif k == "timestamp":
            secs = _IntRle(data, True, v2).read(n_vals)
            nanos_b = self._stream(raw, col.cid, 5)  # SECONDARY
            nraw = _IntRle(nanos_b, False, v2).read(n_vals)
            zeros = nraw & 0x7
            nanos = nraw >> 3
            mult = np.where(zeros > 0, 10 ** (zeros + 1), 1)
            nanos = nanos * mult
            base = 1420070400  # 2015-01-01 00:00:00 UTC, the ORC epoch
            vals = (secs + base) * 1_000_000 + nanos // 1000
        elif k == "decimal":
            # unbounded zigzag varint mantissa + scale RLE (SECONDARY)
            p = _Proto(data)
            ints = []
            for _ in range(n_vals):
                v = p.varint()
                ints.append((v >> 1) ^ -(v & 1))
            vals = np.asarray(ints, np.int64)
        else:
            raise NotImplementedError(f"orc column kind {k}")

        # scatter through the present mask
        if present is not None:
            full = np.empty(n, object) if isinstance(
                vals.dtype, object.__class__) or vals.dtype == object \
                else np.zeros(n, vals.dtype)
            full[present] = vals
            return self._convert(col, full, present)
        return self._convert(col, vals, None)

    def _convert(self, col, vals, valid):
        t = col.sql_type()
        if t.name in ("VARCHAR",):
            out = np.empty(len(vals), object)
            for i, v in enumerate(vals):
                out[i] = v.decode("utf-8", "replace") \
                    if isinstance(v, bytes) else ("" if v is None else v)
            if col.kind == "char":
                pass  # ORC stores padded values already
            return out, valid, t
        if t.name == "VARBINARY":
            out = np.empty(len(vals), object)
            for i, v in enumerate(vals):
                out[i] = v if isinstance(v, bytes) else b""
            return out, valid, t
        if t.is_decimal:
            return np.asarray(vals).astype(np.int64), valid, t
        arr = np.asarray(vals)
        if arr.dtype == object:
            arr = np.asarray([0 if v is None else v for v in vals])
        return arr.astype(t.numpy_dtype()), valid, t


# ---------------------------------------------------------------------------
# writer (reference: presto-orc OrcWriter/StripeWriter + the column
# writers under writer/ — here: one stripe, DIRECT (RLE v1) encodings,
# NONE compression; readable by any conformant implementation)
# ---------------------------------------------------------------------------


class _PWrite:
    """Minimal protobuf wire-format writer."""

    def __init__(self):
        self.out = bytearray()

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def field_varint(self, fnum: int, v: int) -> None:
        self.varint((fnum << 3) | 0)
        self.varint(v)

    def field_bytes(self, fnum: int, data: bytes) -> None:
        self.varint((fnum << 3) | 2)
        self.varint(len(data))
        self.out += data

    def field_msg(self, fnum: int, msg: "_PWrite") -> None:
        self.field_bytes(fnum, bytes(msg.out))

    def field_zigzag(self, fnum: int, v: int) -> None:
        """sint32/sint64 field (zigzag varint)."""
        self.varint(fnum << 3)
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field_double(self, fnum: int, v: float) -> None:
        self.varint((fnum << 3) | 1)
        self.out += struct.pack("<d", v)


def _rle_v1_write(vals, signed: bool) -> bytes:
    """Integer RLE v1: runs of >=3 equal values, else literal groups."""
    out = bytearray()

    def varint(v: int):
        if signed:
            v = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return

    i = 0
    n = len(vals)
    while i < n:
        j = i
        while j + 1 < n and vals[j + 1] == vals[i] and j - i < 129:
            j += 1
        run = j - i + 1
        if run >= 3:
            out.append(run - 3)
            out.append(0)  # delta
            varint(int(vals[i]))
            i = j + 1
            continue
        k = i
        while k < n and k - i < 128:
            if k + 2 < n and vals[k] == vals[k + 1] == vals[k + 2]:
                break
            k += 1
        lit = k - i
        out.append(256 - lit)
        for m in range(i, k):
            varint(int(vals[m]))
        i = k
    return bytes(out)


def _byte_rle_write(data: bytes) -> bytes:
    """Byte RLE (PRESENT/boolean byte stream): literal groups only —
    always valid, simple."""
    out = bytearray()
    i = 0
    while i < len(data):
        chunk = data[i:i + 128]
        out.append(256 - len(chunk))
        out += chunk
        i += len(chunk)
    return bytes(out)


def _bool_rle_write(bits: np.ndarray) -> bytes:
    by = np.packbits(bits.astype(bool), bitorder="big").tobytes()
    return _byte_rle_write(by)


_ORC_KIND = {"BOOLEAN": 0, "SMALLINT": 2, "INTEGER": 3, "BIGINT": 4,
             "REAL": 5, "DOUBLE": 6, "VARCHAR": 7, "CHAR": 7,
             "JSON": 7, "VARBINARY": 8, "TIMESTAMP": 9, "DATE": 15,
             "TINYINT": 1}


def _column_stats_msg(t, live, n_nulls) -> "_PWrite":
    """ColumnStatistics proto for one column of one stripe (reference:
    presto-orc .../metadata/statistics/*Statistics + OrcWriter's
    StripeStatistics) — the zone map select_stripes-style pruning reads."""
    cs = _PWrite()
    cs.field_varint(1, int(len(live)))  # numberOfValues (non-null)
    kind = _ORC_KIND.get(t.name)
    if len(live):
        if kind in (1, 2, 3, 4):  # IntegerStatistics (sint64 zigzag)
            sub = _PWrite()
            sub.field_zigzag(1, int(np.min(live)))
            sub.field_zigzag(2, int(np.max(live)))
            cs.field_msg(2, sub)
        elif kind in (5, 6):  # DoubleStatistics
            a = np.asarray(live, np.float64)
            a = a[~np.isnan(a)]
            if len(a):
                sub = _PWrite()
                sub.field_double(1, float(a.min()))
                sub.field_double(2, float(a.max()))
                cs.field_msg(3, sub)
        elif kind == 7:  # StringStatistics
            vals = [v if isinstance(v, str) else str(v) for v in live]
            sub = _PWrite()
            sub.field_bytes(1, min(vals).encode())
            sub.field_bytes(2, max(vals).encode())
            cs.field_msg(4, sub)
        elif kind == 15:  # DateStatistics (sint32 days)
            sub = _PWrite()
            sub.field_zigzag(1, int(np.min(live)))
            sub.field_zigzag(2, int(np.max(live)))
            cs.field_msg(7, sub)
        elif kind == 9:  # TimestampStatistics (sint64 MILLIS)
            us = np.asarray(live, np.int64)
            sub = _PWrite()
            sub.field_zigzag(1, int(us.min() // 1000))
            sub.field_zigzag(2, int(us.max() // 1000))
            cs.field_msg(9, sub)
    if n_nulls:
        cs.field_varint(10, 1)  # hasNull
    return cs


def write_orc(path: str, arrays: Dict[str, np.ndarray],
              schema: Dict[str, T.Type], stripe_rows: int = 0) -> int:
    """ORC v0.12 file, DIRECT encodings, no compression; stripe_rows > 0
    splits rows into multiple stripes, each with ColumnStatistics in the
    Metadata section (the stats-pruning grain)."""
    cols = list(schema)
    n = len(next(iter(arrays.values()))) if arrays else 0
    grp = stripe_rows if stripe_rows > 0 else max(n, 1)
    bounds = [(s, min(s + grp, n)) for s in range(0, max(n, 1), grp)]

    body = io.BytesIO()
    body.write(MAGIC)
    stripe_infos = []  # (offset, data_len, footer_len, rows)
    stripe_stats = []  # per stripe: [ColumnStatistics _PWrite] col order
    for g0, g1 in bounds:
        streams = []  # (column id, kind, bytes)
        col_stats = []
        for ci, c in enumerate(cols, start=1):
            t = schema[c]
            a = arrays[c][g0:g1]
            if isinstance(a, np.ma.MaskedArray):
                valid = ~np.ma.getmaskarray(a)
                a = a.filled("" if t.is_string else 0)
                streams.append((ci, 0, _bool_rle_write(valid)))
                live = np.asarray(a)[valid]
                nulls = int((~valid).sum())
            else:
                valid = None
                live = np.asarray(a)
                nulls = 0
            col_stats.append(_column_stats_msg(t, live, nulls))
            kind = _ORC_KIND.get(t.name)
            if kind is None:
                raise NotImplementedError(f"orc write of {t}")
            if kind == 0:  # boolean bits
                streams.append((ci, 1, _bool_rle_write(live.astype(bool))))
            elif kind in (1,):  # tinyint: byte rle
                streams.append((ci, 1, _byte_rle_write(
                    live.astype(np.int8).tobytes())))
            elif kind in (2, 3, 4, 15):  # ints / date: signed RLE v1
                streams.append((ci, 1, _rle_v1_write(
                    live.astype(np.int64), signed=True)))
            elif kind == 5:
                streams.append((ci, 1, live.astype("<f4").tobytes()))
            elif kind == 6:
                streams.append((ci, 1, live.astype("<f8").tobytes()))
            elif kind in (7, 8):  # string/binary: DATA + LENGTH
                bs = [v.encode() if isinstance(v, str) else
                      (bytes(v) if v is not None else b"") for v in live]
                streams.append((ci, 1, b"".join(bs)))
                streams.append((ci, 2, _rle_v1_write(
                    np.asarray([len(b) for b in bs], np.int64),
                    signed=False)))
            elif kind == 9:  # timestamp: seconds from 2015 + nanos
                micros = live.astype(np.int64)
                secs = micros // 1_000_000 - 1420070400
                nanos = (micros % 1_000_000) * 1000
                streams.append((ci, 1, _rle_v1_write(secs, signed=True)))
                # SECONDARY (kind 5): nanos << 3, no trailing-zero packing
                streams.append((ci, 5, _rle_v1_write(
                    nanos.astype(np.int64) << 3, signed=False)))
        stripe_stats.append(col_stats)

        data_start = body.tell()
        for _ci, _k, blob in streams:
            body.write(blob)
        data_len = body.tell() - data_start

        sf = _PWrite()
        for ci, k, blob in streams:
            st = _PWrite()
            st.field_varint(1, k)
            st.field_varint(2, ci)
            st.field_varint(3, len(blob))
            sf.field_msg(1, st)
        for _ in range(len(cols) + 1):  # root + columns: DIRECT encoding
            enc = _PWrite()
            enc.field_varint(1, 0)
            sf.field_msg(2, enc)
        sf_bytes = bytes(sf.out)
        body.write(sf_bytes)
        stripe_infos.append((data_start, data_len, len(sf_bytes), g1 - g0))

    # Metadata section: one StripeStatistics per stripe (root column
    # first, then data columns — reference metadata/Metadata.java)
    meta = _PWrite()
    for (_o, _d, _f, rows), col_stats in zip(stripe_infos, stripe_stats):
        ss = _PWrite()
        root_cs = _PWrite()
        root_cs.field_varint(1, rows)  # root struct: every row counts
        ss.field_msg(1, root_cs)
        for cs in col_stats:
            ss.field_msg(1, cs)
        meta.field_msg(1, ss)
    meta_bytes = bytes(meta.out)
    body.write(meta_bytes)

    # footer
    ftr = _PWrite()
    ftr.field_varint(1, 3)  # headerLength (magic)
    ftr.field_varint(2, body.tell())  # contentLength
    for off, dlen, sflen, rows in stripe_infos:
        stripe = _PWrite()
        stripe.field_varint(1, off)  # offset
        stripe.field_varint(2, 0)  # indexLength
        stripe.field_varint(3, dlen)
        stripe.field_varint(4, sflen)
        stripe.field_varint(5, rows)
        ftr.field_msg(3, stripe)
    root = _PWrite()
    root.field_varint(1, 12)  # STRUCT
    for ci in range(1, len(cols) + 1):
        root.field_varint(2, ci)  # subtypes (non-packed repeated)
    for c in cols:
        root.field_bytes(3, c.encode())
    ftr.field_msg(4, root)
    for c in cols:
        el = _PWrite()
        el.field_varint(1, _ORC_KIND[schema[c].name])
        ftr.field_msg(4, el)
    ftr.field_varint(6, n)  # numberOfRows
    ftr.field_varint(8, 10000)  # rowIndexStride
    ftr_bytes = bytes(ftr.out)
    body.write(ftr_bytes)

    ps = _PWrite()
    ps.field_varint(1, len(ftr_bytes))
    ps.field_varint(2, 0)  # compression NONE
    ps.field_varint(3, 262144)
    # version: repeated uint32 [0, 12] (non-packed)
    ps.field_varint(4, 0)
    ps.field_varint(4, 12)
    ps.field_varint(5, len(meta_bytes))  # metadataLength
    ps.field_varint(6, 6)  # writerVersion
    ps.field_bytes(8, b"ORC")  # magic
    ps_bytes = bytes(ps.out)
    body.write(ps_bytes)
    body.write(bytes([len(ps_bytes)]))
    with open(path, "wb") as f:
        f.write(body.getvalue())
    return n
