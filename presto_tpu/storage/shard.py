"""Columnar shard file format: stripes of per-column PTPG frames with
zone maps (min/max per stripe-column) and a JSON footer.

Reference parity: the role of presto-orc (OrcWriter/OrcReader +
StripeReader with row-group min/max pruning via OrcPredicate) and
presto-raptor's ORC shard storage, redesigned around the engine's own
native serde: every payload is a compressed + checksummed PTPG frame
(presto_tpu/native/serde.py), strings are file-level sorted dictionaries
with int32 codes per stripe (so zone maps on codes are order-exact),
and predicate pruning happens before any frame is decoded.

File layout (little-endian):
  magic 'PTSH'
  [stripe-column frames ... ]         any order; footer holds offsets
  [string dictionary frames ... ]
  footer json (utf-8)
  footer_len u64 | magic 'PTSH'
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.native import serde

MAGIC = b"PTSH"
DEFAULT_STRIPE_ROWS = 1 << 16


class Domain:
    """A per-column value constraint for scan pruning — the engine's
    TupleDomain analog (presto-spi/.../spi/predicate/TupleDomain.java),
    trimmed to ranges + point sets over orderable types."""

    def __init__(self, lo=None, hi=None, values: Optional[list] = None):
        self.lo = lo
        self.hi = hi
        self.values = values  # discrete IN-list; None = range-only

    def overlaps(self, zmin, zmax) -> bool:
        if zmin is None or zmax is None:
            return True  # no stats -> cannot prune
        if self.values is not None:
            return any(zmin <= v <= zmax for v in self.values)
        if self.lo is not None and zmax < self.lo:
            return False
        if self.hi is not None and zmin > self.hi:
            return False
        return True

    def __repr__(self):
        if self.values is not None:
            return f"Domain(in={self.values!r})"
        return f"Domain([{self.lo!r}, {self.hi!r}])"


def write_shard(path: str, arrays: Dict[str, np.ndarray],
                schema: Dict[str, T.Type],
                stripe_rows: int = DEFAULT_STRIPE_ROWS) -> None:
    """Write columns to a shard file. String columns (object/str dtype)
    are dictionary-encoded file-wide with a sorted dictionary."""
    from presto_tpu.batch import encode_strings

    n = len(next(iter(arrays.values()))) if arrays else 0
    for name, a in arrays.items():
        assert len(a) == n, f"ragged column {name}"

    encoded: Dict[str, np.ndarray] = {}
    dictionaries: Dict[str, np.ndarray] = {}
    for name, a in arrays.items():
        a = np.asarray(a)
        if schema[name].is_string and a.dtype.kind in ("U", "S", "O"):
            codes, d = encode_strings(a)
            encoded[name] = codes
            dictionaries[name] = d.values
        else:
            if schema[name].is_decimal and a.dtype.kind == "f":
                # unscaled floats (decoded decimals) -> scaled ints
                a = np.round(a * (10 ** schema[name].decimal_scale))
            encoded[name] = np.ascontiguousarray(a, dtype=schema[name].numpy_dtype())

    footer: dict = {
        "version": 1,
        "nrows": n,
        "columns": [{"name": c, "type": str(schema[c])} for c in arrays],
        "stripes": [],
        "dicts": {},
    }
    with open(path, "wb") as f:
        f.write(MAGIC)
        off = 4
        starts = list(range(0, max(n, 1), stripe_rows)) if n else []
        for s in starts:
            e = min(s + stripe_rows, n)
            stripe = {"nrows": e - s, "cols": {}}
            for name, a in encoded.items():
                part = a[s:e]
                frame = serde.serialize_columns({name: part})
                zmin, zmax = _zone(part)
                stripe["cols"][name] = {
                    "off": off, "len": len(frame), "min": zmin, "max": zmax}
                f.write(frame)
                off += len(frame)
            footer["stripes"].append(stripe)
        for name, values in dictionaries.items():
            # offset-encoded (not delimiter-joined): round-trips empty
            # strings and values containing any byte
            blobs = [v.encode("utf-8") for v in values.tolist()]
            lens = np.fromiter(map(len, blobs), count=len(blobs),
                               dtype=np.int64)
            frame = serde.serialize_columns({
                name: np.frombuffer(b"".join(blobs), dtype=np.uint8),
                name + "\x00lens": lens,
            })
            footer["dicts"][name] = {"off": off, "len": len(frame),
                                     "count": len(values)}
            f.write(frame)
            off += len(frame)
        fj = json.dumps(footer).encode("utf-8")
        f.write(fj)
        f.write(struct.pack("<Q", len(fj)))
        f.write(MAGIC)


def _zone(a: np.ndarray):
    from presto_tpu import native

    if a.dtype == np.bool_ or a.size == 0 or a.ndim > 1:
        return None, None
    lo, hi = native.minmax(a.astype(np.int64) if a.dtype == np.int32 else a)
    if isinstance(lo, float) and (np.isnan(lo) or np.isnan(hi)):
        return None, None
    return lo, hi


class ShardReader:
    """Reads a shard file with projection + zone-map predicate pruning."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(size - 12)
            tail = f.read(12)
            if tail[8:] != MAGIC:
                raise ValueError(f"{path}: not a PTSH shard")
            (flen,) = struct.unpack("<Q", tail[:8])
            f.seek(size - 12 - flen)
            self.footer = json.loads(f.read(flen).decode("utf-8"))
        self.schema: Dict[str, T.Type] = {
            c["name"]: T.parse_type(c["type"]) for c in self.footer["columns"]}
        self._dict_cache: Dict[str, np.ndarray] = {}

    @property
    def nrows(self) -> int:
        return self.footer["nrows"]

    @property
    def n_stripes(self) -> int:
        return len(self.footer["stripes"])

    def dictionary(self, column: str) -> Optional[np.ndarray]:
        info = self.footer["dicts"].get(column)
        if info is None:
            return None
        if column not in self._dict_cache:
            frame = self._read_at(info["off"], info["len"])
            cols = serde.deserialize_columns(frame)
            blob = bytes(cols[column])
            lens = cols[column + "\x00lens"]
            offs = np.zeros(len(lens) + 1, dtype=np.int64)
            np.cumsum(lens, out=offs[1:])
            values = [blob[offs[i]:offs[i + 1]].decode("utf-8")
                      for i in range(len(lens))]
            assert len(values) == info["count"]
            self._dict_cache[column] = np.array(values, dtype=object)
        return self._dict_cache[column]

    def _read_at(self, off: int, length: int) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(off)
            return f.read(length)

    def select_stripes(self, domains: Optional[Dict[str, Domain]]) -> List[int]:
        """Stripe indices whose zone maps intersect every domain.  String
        domains are translated to dictionary-code ranges first (dictionary
        is sorted, so order is preserved)."""
        if not domains:
            return list(range(self.n_stripes))
        coded: Dict[str, Domain] = {}
        for col, dom in domains.items():
            if col not in self.schema:
                continue
            if self.schema[col].is_string:
                d = self.dictionary(col)
                if d is None:
                    continue
                coded[col] = _string_domain_to_codes(dom, d)
            else:
                coded[col] = dom
        keep = []
        for i, stripe in enumerate(self.footer["stripes"]):
            ok = True
            for col, dom in coded.items():
                info = stripe["cols"].get(col)
                if info is None:
                    continue
                if not dom.overlaps(info["min"], info["max"]):
                    ok = False
                    break
            if ok:
                keep.append(i)
        return keep

    def _empty_column(self, c: str) -> np.ndarray:
        typ = self.schema[c]
        dtype = typ.numpy_dtype()
        # sketch-state columns are 2-D (n_rows, width) matrices; an empty
        # read must keep the width so downstream concat/merge stays valid
        if typ.name in ("HLL_STATE", "KLL_STATE") and typ.params:
            return np.zeros((0, int(typ.params[0])), dtype=dtype)
        return np.empty(0, dtype)

    def read(self, columns: Optional[List[str]] = None,
             stripes: Optional[List[int]] = None,
             decode_strings: bool = True) -> Dict[str, np.ndarray]:
        cols = columns if columns is not None else list(self.schema)
        which = stripes if stripes is not None else range(self.n_stripes)
        parts: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        for i in which:
            stripe = self.footer["stripes"][i]
            for c in cols:
                info = stripe["cols"][c]
                frame = self._read_at(info["off"], info["len"])
                parts[c].append(serde.deserialize_columns(frame)[c])
        out: Dict[str, np.ndarray] = {}
        for c in cols:
            a = (np.concatenate(parts[c]) if parts[c]
                 else self._empty_column(c))
            if decode_strings and self.schema[c].is_string:
                d = self.dictionary(c)
                if d is not None:
                    a = d[np.clip(a, 0, max(len(d) - 1, 0))] if len(d) else \
                        np.empty(0, dtype=object)
            out[c] = a
        return out

    def stripe_row_ranges(self) -> List[Tuple[int, int]]:
        out = []
        start = 0
        for s in self.footer["stripes"]:
            out.append((start, start + s["nrows"]))
            start += s["nrows"]
        return out


def _string_domain_to_codes(dom: Domain, dictionary: np.ndarray) -> Domain:
    strs = dictionary.astype(str)
    if dom.values is not None:
        codes = []
        for v in dom.values:
            i = int(np.searchsorted(strs, str(v)))
            if i < len(strs) and strs[i] == str(v):
                codes.append(i)
        # no matching codes => impossible domain (prunes every stripe)
        return Domain(values=codes if codes else [-1])
    lo = int(np.searchsorted(strs, str(dom.lo))) if dom.lo is not None else None
    # upper bound: first dictionary entry > hi, minus one
    hi = (int(np.searchsorted(strs, str(dom.hi), side="right")) - 1
          if dom.hi is not None else None)
    return Domain(lo=lo, hi=hi)
