"""Observability: query/operator stats, events, EXPLAIN ANALYZE.

Reference parity: the metrics pipeline of SURVEY.md §5 — OperatorStats/
QueryStats recorded around every operator call (operator/Driver.java:380),
QueryMonitor events to pluggable EventListeners (event/QueryMonitor.java),
and EXPLAIN ANALYZE rendering (operator/ExplainAnalyzeOperator.java).
"""

from presto_tpu.observe.stats import NodeStats, QueryMonitor, QueryStats
from presto_tpu.observe.events import (EventListener, QueryCompletedEvent,
                                       QueryCreatedEvent)

__all__ = ["NodeStats", "QueryMonitor", "QueryStats", "EventListener",
           "QueryCreatedEvent", "QueryCompletedEvent"]
