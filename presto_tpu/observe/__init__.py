"""Observability: query/operator stats, events, tracing, metrics,
EXPLAIN ANALYZE.

Reference parity: the metrics pipeline of SURVEY.md §5 — OperatorStats/
QueryStats recorded around every operator call (operator/Driver.java:380),
QueryMonitor events to pluggable EventListeners (event/QueryMonitor.java),
and EXPLAIN ANALYZE rendering (operator/ExplainAnalyzeOperator.java) —
plus the TPU-native additions: span-based query tracing stitched across
coordinator→worker HTTP hops (observe/trace.py), a process-wide metrics
registry served as Prometheus text from /v1/metrics (observe/metrics.py),
and XLA cost-analysis / jax.profiler attribution for fused programs
(observe/profile.py).  See docs/OBSERVABILITY.md.
"""

from presto_tpu.observe.stats import NodeStats, QueryMonitor, QueryStats
from presto_tpu.observe.events import (EventListener, QueryCompletedEvent,
                                       QueryCreatedEvent)

__all__ = ["NodeStats", "QueryMonitor", "QueryStats", "EventListener",
           "QueryCreatedEvent", "QueryCompletedEvent"]
