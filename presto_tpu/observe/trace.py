"""Query tracing: a zero-dependency span recorder.

Reference parity: the reference engine's query event pipeline + live
web-UI timeline (execution/QueryStats.java stage/task/operator
timelines, webapp timeline.jsx) — reimagined for an engine whose
compiled fragments are opaque fused XLA programs: what the reference
gets from per-operator OperationTimers, we get from spans around the
phases the host CAN see (parse/plan/execute, fragment schedule, task
execution, page pulls, XLA compiles, hedged attempts) plus XLA
cost-analysis / profiler attribution INSIDE programs
(observe/profile.py).

Model: one `Tracer` per query records `Span`s — query -> phase ->
fragment -> task -> attempt — identified by DETERMINISTIC ids (a
process counter, never a random source or the clock, so seeded chaos
runs replay identical id sequences).  Trace context propagates to
cluster workers in the `X-Presto-Trace` header (`trace_id;span_id`);
workers record task spans locally and ship them back on the task
status payload, where the coordinator merges every span carrying this
query's trace id into ONE trace.  A dropped header degrades to a
worker-LOCAL trace (fresh trace id; the coordinator counts the
foreign spans it refused) — never an error.

Export is Chrome trace-event JSON (`chrome_trace`): load the payload
from `/v1/query/{id}/trace` (server/protocol.py) or
`QueryStats.trace_spans` in Perfetto / chrome://tracing.  Lanes: each
process is a `pid` row (coordinator / worker:PORT), each thread a
`tid` row — so hedge monitors, compile-ahead workers, and retried
attempts appear as their own lanes instead of being inferred from
counters.

This module also owns the engine's span CLOCKS (`clock_ns`, `wall_s`):
the test_lint AST rule confines `time.time` / `time.perf_counter*`
to observe/, so every wall measurement that can end up in a span or a
metric routes through here.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

#: the trace-context propagation header (coordinator -> worker):
#: "trace_id;parent_span_id"
TRACE_HEADER = "X-Presto-Trace"

#: span kinds, outermost to innermost (docs/OBSERVABILITY.md)
KINDS = ("query", "phase", "fragment", "task", "attempt", "compile",
         "span")


# ---------------------------------------------------------------------------
# clocks (the only module allowed to read them — test_lint rule)
# ---------------------------------------------------------------------------


def clock_ns() -> int:
    """Monotonic high-resolution clock for durations."""
    return time.perf_counter_ns()


def wall_s() -> float:
    """Unix wall clock (seconds) for timestamps that leave the process
    (HMAC signing, trace alignment across coordinator/worker)."""
    return time.time()


def epoch_us() -> float:
    """Unix wall clock in microseconds — the chrome trace `ts` unit.
    Coordinator and worker spans align on it (same-host resolution is
    more than enough for HTTP-hop-sized spans)."""
    return time.time_ns() / 1_000.0


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str  # "" = root
    name: str
    kind: str = "span"
    start_us: float = 0.0
    end_us: float = 0.0  # 0 = still open
    lane: str = "coordinator"  # process lane (chrome pid)
    tid: str = ""  # thread lane within the process (chrome tid)
    args: Dict[str, object] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "kind": self.kind, "start_us": self.start_us,
             "end_us": self.end_us, "lane": self.lane, "tid": self.tid}
        if self.args:
            d["args"] = {k: v for k, v in self.args.items()
                         if isinstance(v, (str, int, float, bool))
                         or v is None}
        return d

    @property
    def dur_us(self) -> float:
        return max(self.end_us - self.start_us, 0.0)


# deterministic id sources: process-scoped counters, never a clock or a
# random source (seeded chaos runs must replay identical id sequences)
_trace_seq = itertools.count(1)


def _fresh_trace_id() -> str:
    return f"tr-{os.getpid():x}-{next(_trace_seq)}"


class Tracer:
    """Per-query span recorder.  Thread-safe: the span list takes a
    lock; the *nesting stack* is per-thread (each thread that calls
    `span()` nests under its own enclosing span, falling back to the
    tracer's root)."""

    def __init__(self, trace_id: Optional[str] = None,
                 lane: str = "coordinator",
                 root_parent: str = ""):
        self.trace_id = trace_id or _fresh_trace_id()
        self.lane = lane
        #: parent id for this tracer's root spans (the coordinator span
        #: a worker-side tracer hangs its task span under)
        self.root_parent = root_parent
        self.root: Optional[Span] = None
        self.spans: List[Span] = []
        self.dropped = 0  # foreign-trace spans refused by add_spans
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._stacks: Dict[int, List[Span]] = {}  # thread ident -> stack

    # -- ids -----------------------------------------------------------
    def new_id(self) -> str:
        return f"{self.trace_id}.{next(self._seq)}"

    # -- manual begin/end (cross-thread spans) -------------------------
    def begin(self, name: str, kind: str = "span",
              parent: Optional[object] = None, **args) -> Span:
        if parent is None:
            parent_id = self._thread_parent_id()
        elif isinstance(parent, Span):
            parent_id = parent.span_id
        else:
            parent_id = str(parent)
        sp = Span(trace_id=self.trace_id, span_id=self.new_id(),
                  parent_id=parent_id, name=name, kind=kind,
                  start_us=epoch_us(), lane=self.lane,
                  tid=threading.current_thread().name, args=dict(args))
        with self._lock:
            self.spans.append(sp)
        return sp

    def end(self, sp: Optional[Span], **args) -> None:
        if sp is None:
            return
        sp.end_us = epoch_us()
        if args:
            sp.args.update(args)

    def _thread_parent_id(self) -> str:
        stack = self._stacks.get(threading.get_ident())
        if stack:
            return stack[-1].span_id
        if self.root is not None:
            return self.root.span_id
        return self.root_parent

    # -- structured nesting --------------------------------------------
    def begin_root(self, name: str, kind: str = "query", **args) -> Span:
        self.root = self.begin(name, kind=kind, parent=self.root_parent,
                               **args)
        return self.root

    @contextmanager
    def span(self, name: str, kind: str = "span", **args):
        sp = self.begin(name, kind=kind, **args)
        stack = self._stacks.setdefault(threading.get_ident(), [])
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            self.end(sp)

    # -- merge / export ------------------------------------------------
    def add_spans(self, span_dicts, require_trace: bool = True) -> int:
        """Merge externally recorded spans (a worker's task spans riding
        its status payload).  Spans carrying a DIFFERENT trace id are
        refused and counted (`dropped`) — a worker that never saw the
        X-Presto-Trace header produced a worker-local trace, which must
        not be grafted into this query's tree under made-up parents."""
        merged = 0
        for d in span_dicts or []:
            try:
                tid = str(d.get("trace_id", ""))
                if require_trace and tid != self.trace_id:
                    self.dropped += 1
                    continue
                sp = Span(trace_id=tid or self.trace_id,
                          span_id=str(d["span_id"]),
                          parent_id=str(d.get("parent_id", "")),
                          name=str(d.get("name", "span")),
                          kind=str(d.get("kind", "span")),
                          start_us=float(d.get("start_us", 0.0)),
                          end_us=float(d.get("end_us", 0.0)),
                          lane=str(d.get("lane", "remote")),
                          tid=str(d.get("tid", "")),
                          args=dict(d.get("args") or {}))
            except (KeyError, TypeError, ValueError):
                self.dropped += 1
                continue
            with self._lock:
                self.spans.append(sp)
            merged += 1
        return merged

    def snapshot(self) -> List[dict]:
        """Spans as JSON-safe dicts (open spans are closed at 'now' so a
        crash mid-span still exports a valid trace)."""
        now = epoch_us()
        with self._lock:
            spans = list(self.spans)
        out = []
        for sp in spans:
            d = sp.to_dict()
            if not d["end_us"]:
                d["end_us"] = now
                d.setdefault("args", {})["unclosed"] = True
            out.append(d)
        return out

    def to_chrome(self) -> dict:
        return chrome_trace(self.snapshot(), self.trace_id)


def chrome_trace(span_dicts: List[dict], trace_id: str = "") -> dict:
    """Span dicts -> Chrome trace-event JSON (loads in Perfetto /
    chrome://tracing).  Each distinct `lane` becomes a pid row, each
    (lane, tid) a named thread row; spans are complete ('X') events."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    events: List[dict] = []
    for d in span_dicts:
        lane = d.get("lane") or "coordinator"
        if lane not in pids:
            pids[lane] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[lane], "tid": 0,
                           "args": {"name": lane}})
        tkey = (lane, d.get("tid") or "main")
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pids[lane], "tid": tids[tkey],
                           "args": {"name": tkey[1]}})
        args = dict(d.get("args") or {})
        args["kind"] = d.get("kind", "span")
        args["span_id"] = d.get("span_id", "")
        if d.get("parent_id"):
            args["parent_id"] = d["parent_id"]
        start = float(d.get("start_us", 0.0))
        events.append({
            "ph": "X", "name": d.get("name", "span"),
            "cat": d.get("kind", "span"),
            "ts": start,
            "dur": max(float(d.get("end_us", start)) - start, 0.0),
            "pid": pids[lane], "tid": tids[tkey], "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"traceId": trace_id}}


# ---------------------------------------------------------------------------
# thread-local activation + wire context
# ---------------------------------------------------------------------------

_tls = threading.local()


@contextmanager
def activate(tracer: Optional[Tracer]):
    """Route this thread's span recording to `tracer` (None = no-op).
    Nested activations shadow; the previous tracer is restored."""
    prev = getattr(_tls, "tracer", None)
    _tls.tracer = tracer
    try:
        yield tracer
    finally:
        _tls.tracer = prev


def current() -> Optional[Tracer]:
    return getattr(_tls, "tracer", None)


@contextmanager
def maybe_span(name: str, kind: str = "span", **args):
    """Record a span on the thread's active tracer, or do nothing —
    instrumentation sites stay one-liners either way."""
    tr = current()
    if tr is None:
        yield None
        return
    with tr.span(name, kind=kind, **args) as sp:
        yield sp


def propagation_enabled() -> bool:
    """Header-propagation kill switch (chaos-tested degradation hook):
    PRESTO_TPU_TRACE_PROPAGATION=off strips the X-Presto-Trace header
    from every outbound request, so workers fall back to worker-local
    traces."""
    return os.environ.get("PRESTO_TPU_TRACE_PROPAGATION", "").lower() \
        not in ("off", "0", "false")


def wire_context() -> Optional[str]:
    """The X-Presto-Trace header value for an outbound request:
    `trace_id;current_span_id` (None = no active tracer / propagation
    off)."""
    if not propagation_enabled():
        return None
    tr = current()
    if tr is None:
        return None
    return f"{tr.trace_id};{tr._thread_parent_id()}"


def from_wire(header: Optional[str]):
    """Header value -> (trace_id, parent_span_id) or (None, "")."""
    if not header or ";" not in header:
        return None, ""
    trace_id, _, parent = header.partition(";")
    trace_id = trace_id.strip()
    return (trace_id or None), parent.strip()


# ---------------------------------------------------------------------------
# session policy
# ---------------------------------------------------------------------------


def detail(session) -> str:
    """`trace_detail` session property: off | basic | full.  `basic`
    (default) records query/phase/fragment/task/attempt/compile spans;
    `full` adds page-pull and per-exchange spans in cluster mode; `off`
    disables the recorder (the observability_overhead A/B lever)."""
    try:
        d = str(session.properties.get("trace_detail", "basic")).lower()
    except Exception:
        return "basic"
    return d if d in ("off", "basic", "full") else "basic"


def enabled(session) -> bool:
    return detail(session) != "off"
