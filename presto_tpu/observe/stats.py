"""Query + per-operator statistics.

Reference parity: execution/QueryStats.java + operator/OperatorStats.java
(recorded by OperationTimer around every getOutput/addInput,
operator/Driver.java:380) and the query lifecycle states of
QueryStateMachine (execution/QueryStateMachine.java: QUEUED → PLANNING →
RUNNING → FINISHED/FAILED).  Per-node stats are collected in dynamic
execution; compiled/distributed execution reports fragment-level timings
(the whole plan is one fused XLA program — there is no per-operator
boundary at runtime, which is the point of the design; attribution
INSIDE those programs comes from XLA cost analysis + the profiler via
observe/profile.py, and the host-visible lifecycle from the span
recorder in observe/trace.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from contextlib import contextmanager
from typing import Dict, Optional

_query_ids = itertools.count(1)


@dataclasses.dataclass
class NodeStats:
    """Per-plan-node runtime stats (reference: OperatorStats)."""

    node_kind: str = ""
    rows_out: int = 0
    wall_ns: int = 0
    invocations: int = 0


@dataclasses.dataclass
class QueryStats:
    """Reference: execution/QueryStats.java, trimmed to the engine's
    phases; phase_ns keys: parse, plan, execute (plan includes analysis
    + optimization; execute includes any XLA compile)."""

    query_id: str = ""
    sql: str = ""
    state: str = "QUEUED"
    create_time: float = 0.0
    end_time: float = 0.0
    phase_ns: Dict[str, int] = dataclasses.field(default_factory=dict)
    execution_mode: str = ""  # dynamic | compiled | distributed
    output_rows: int = 0
    error: Optional[str] = None
    peak_memory_bytes: int = 0
    spilled_bytes: int = 0
    spilled_partitions: int = 0
    recovered_buckets: int = 0  # grouped-execution buckets loaded from ckpt
    # spill-tiered degradation (exec/spill_exec.py, docs/SPILL.md):
    # partitions spilled as checksummed PTPG frames, bytes written,
    # partitions restored (unspilled), recursive re-partition rounds,
    # and the query's deepest tier engaged (0 resident / 1 partial
    # spill / 2 recursive partitioning — a high-water mark, not a sum).
    # spilled_bytes/spilled_partitions above stay as legacy aliases.
    # Spill-I/O recovery events (spill_enospc, spill_rewrites,
    # spill_df_resident) ride the `recovery` dict below.
    spill_partitions: int = 0
    spill_bytes: int = 0
    spill_restores: int = 0
    spill_recursions: int = 0
    degradation_tier: int = 0
    # sort economics (ordering-aware execution, plan/properties.py):
    # sorts the executor routed (taken) vs avoided (elided: presorted
    # kernel variants, memo replays, satisfied ORDER BYs), memo replays
    # specifically, and ordering-claim guard trips (each one a
    # fell-back-to-the-sort-path event — correctness kept, sort paid).
    # Compiled/chunked modes count TRACE-TIME routing decisions (the
    # program runs the same ops every call); dynamic mode counts per
    # execution.
    sorts_taken: int = 0
    sorts_elided: int = 0
    sort_memo_hits: int = 0
    ordering_guard_trips: int = 0
    # materialized views (exec/matview.py): refreshes that delta-folded
    # vs degraded to full recompute (degrade is LOUD — it shows here and
    # in the REFRESH result row), splits the delta actually scanned vs
    # the source total (delta cost ∝ delta, not history), and SELECTs
    # the containment matcher served from an MV snapshot.
    mv_refresh_delta: int = 0
    mv_refresh_full: int = 0
    mv_delta_splits: int = 0
    mv_source_splits: int = 0
    mv_routed: int = 0
    # compile economics (exec/compile_cache.py): XLA programs this query
    # BUILT (compiles; compile_ms is the AOT lower+compile wall),
    # executables it reused from the shared memo / persistent disk cache
    # (compile_cache_hits — disk hits observed via jax.monitoring), and
    # shared-memo entries a compile-ahead thread had ready before the
    # query thread asked (compile_ahead_hits).  A warm same-process
    # re-run of a cached query reports compiles == 0 (asserted in
    # tier-1); a cold process over a warmed cache dir reports
    # compile_cache_hits > 0.
    compiles: int = 0
    compile_ms: float = 0.0
    compile_cache_hits: int = 0
    compile_ahead_hits: int = 0
    # dynamic filtering (plan/runtime_filters.py): build-side runtime
    # filters produced / applied at probe scans, rows pruned before the
    # join (dynamic + cluster modes count rows; compiled/chunked modes
    # count TRACE-TIME routing decisions, like the sort economics),
    # whole chunks skipped by the chunked runner, shard stripes pruned
    # by runtime domains, and cluster-side wall spent waiting on the
    # filter side channel (bounded by dynamic_filtering_wait_ms).
    df_filters_produced: int = 0
    df_filters_applied: int = 0
    df_rows_pruned: int = 0
    df_chunks_pruned: int = 0
    df_splits_pruned: int = 0
    df_wait_ms: float = 0.0
    # fragment fusion (plan/distribute.fuse_fragments): fragments this
    # cluster query spliced into fused shard_map super-fragments (0 =
    # the per-fragment HTTP path ran, incl. after a fused-attempt
    # fallback), exchange page bytes that crossed the host HTTP path
    # (pulled for non-result exchange edges: coordinator-observed +
    # fused-task counters; per-worker aggregates live on /v1/info), and
    # the trace-time estimate of bytes the fused program moved through
    # ICI collectives instead (all_to_all / all_gather payloads x ndev).
    fragments_fused: int = 0
    exchange_bytes_host: int = 0
    exchange_bytes_collective: int = 0
    # multi-host lane: the slice of the collective estimate that rode
    # the cross-process (DCN) fabric — a gang-fused query moves bytes
    # here instead of exchange_bytes_host
    exchange_bytes_dcn: int = 0
    # sketch lane (ROADMAP 6, docs/PERF.md): bytes of fixed-width
    # mergeable sketch state (HLL registers / KLL summaries) that moved
    # over merge edges INSTEAD of a hash repartition of input rows — a
    # sketch-only aggregate reports 0 repartition exchange bytes and
    # puts its (tiny) partial-state gather here.  On the fused mesh the
    # global-HLL edge lowers to one lax.pmax; those payload bytes count
    # here, not in exchange_bytes_collective.
    exchange_bytes_sketch: int = 0
    # opt-in approximation rewrites (plan/optimizer.py behind session
    # prefer_approx_distinct): count(DISTINCT x) calls replaced with
    # approx_distinct(x) in this query's plan
    approx_rewrites: int = 0
    # fusion economics (plan/fusion_cost.py): per-edge fuse-vs-cut
    # verdicts of the cost model — exchange edges spliced into a fused
    # program (== fragments_fused), edges kept on the HTTP path, edges
    # where the runtime decision memo overrode the model (a recorded
    # misprediction of THIS shape flipped them), the wall spent pricing
    # edges, and the per-reason skip counts: cost (model priced CUT
    # cheaper), kind (fragment_fusion_kinds excluded), memo (decision-
    # memo override), cross_host (no declared mesh) — exported like
    # agg_strategy as presto_tpu_query_fusion_skips_total{reason}.
    fusion_edges_fused: int = 0
    fusion_edges_cut: int = 0
    fusion_edges_mispredicted: int = 0
    fusion_cost_ms: float = 0.0
    fusion_skips: Dict[str, int] = dataclasses.field(default_factory=dict)
    # serving tier (server/serving.py): prepared-statement economics —
    # binds through the typed aval path (plan + executable shared across
    # parameter VALUES), warm binds that skipped parse/plan/compile
    # entirely (a registry dict hit + device transfer), and EXECUTEs
    # that fell back to text substitution (string/NULL params, static
    # parameter positions like LIMIT ?, subquery params) where the plan
    # is value-keyed.  result_cache_hit flags a query served straight
    # from the serving result cache with no execution at all;
    # resource_group / admission_wait_ms record the admission decision
    # (reference: query JSON resourceGroupId + queuedTime).
    prepared_binds: int = 0
    prepared_plan_hits: int = 0
    prepared_fallbacks: int = 0
    # query coalescing (server/serving.QueryCoalescer): concurrent
    # EXECUTEs of the SAME prepared signature stacked into a leading
    # batch axis and served by ONE vmap-batched XLA launch.
    # coalesced_batch_size: how many queries shared this query's launch
    # (0 = ran solo; every batch member records the same size).
    # coalesce_ms: micro-batch window wait the LEADER paid collecting
    # riders (riders record 0 — their wait overlaps the leader's).
    # coalesce_batches: batches this query led (leader-only, 0 or 1).
    # coalesce_fallbacks: batch memberships abandoned for a solo re-run
    # (batched build/launch failed or the leader faulted — correctness
    # kept, amortization lost).
    coalesced_batch_size: int = 0
    coalesce_ms: float = 0.0
    coalesce_batches: int = 0
    coalesce_fallbacks: int = 0
    # adaptive aggregation economics (plan/agg_strategy.py, ROADMAP 2):
    # partial_agg_ratio — the LAST reduction ratio a partial stage
    # observed (live rows in / groups out; ~1.0 means the partial stage
    # reduced nothing).  partial_aggs_bypassed — bypass events: chunked
    # flips to the pass-through lane plus pass-through executions served
    # in dynamic/cluster mode.  partial_aggs_reenabled — hysteresis
    # recoveries (a probe saw the ratio come back and re-armed the
    # partial stage).  agg_strategy — how each executed grouped
    # aggregate was planned: strategy name -> count (one_pass /
    # final_only / two_phase; exported like `recovery` as
    # presto_tpu_query_agg_strategy_total{strategy}).
    partial_agg_ratio: float = 0.0
    partial_aggs_bypassed: int = 0
    partial_aggs_reenabled: int = 0
    agg_strategy: Dict[str, int] = dataclasses.field(default_factory=dict)
    result_cache_hit: int = 0
    resource_group: str = ""
    admission_wait_ms: float = 0.0
    # write subsystem (exec/writer.py, PageSink SPI): rows/bytes a
    # CTAS/INSERT streamed into connector sinks, files the commit
    # published (0 for append-SPI connectors like memory), and the wall
    # spent in page coercion/layout/sink appends + the finish/commit
    # step.  Exported like every numeric counter through the metrics
    # registry (observe/metrics.py).
    rows_written: int = 0
    bytes_written: int = 0
    write_files: int = 0
    write_ms: float = 0.0
    # tracing (observe/trace.py): this query's trace id, the recorded
    # span dicts (coordinator + merged worker spans; chrome-exportable
    # via trace.chrome_trace / GET /v1/query/{id}/trace), and the count
    # of foreign-trace spans the coordinator refused to merge (a worker
    # that never saw the X-Presto-Trace header recorded a worker-LOCAL
    # trace — the degradation is counted, never an error)
    trace_id: str = ""
    trace_spans: Optional[list] = None
    trace_spans_dropped: int = 0
    # cluster-mode recovery counters (parallel/retry.RunContext.count):
    # http_retries, pages_retried, workers_quarantined, workers_readmitted,
    # hedges_launched, hedges_won, task_cancels, query_retries,
    # deadline_expired, tasks_rerun (task-granular restart),
    # journal_writes, queries_adopted, adoption_ms (journaled
    # failover, parallel/journal.py) — see docs/ROBUSTNESS.md for the
    # schema; every key auto-exports through
    # presto_tpu_query_recovery_total{kind} (observe/metrics.py)
    recovery: Dict[str, int] = dataclasses.field(default_factory=dict)
    # id(plan node) -> NodeStats; populated in dynamic mode
    node_stats: Dict[int, NodeStats] = dataclasses.field(default_factory=dict)
    # rendered plan (annotated with per-node stats when collected) for
    # the web UI's plan pane (reference: webapp plan.jsx consuming
    # /v1/query/{id}?pretty)
    plan_text: str = ""

    @property
    def total_ns(self) -> int:
        return sum(self.phase_ns.values())

    def summary(self) -> str:
        ph = ", ".join(f"{k}={v / 1e6:.1f}ms" for k, v in self.phase_ns.items())
        return (f"[{self.query_id}] {self.state} mode={self.execution_mode} "
                f"rows={self.output_rows} {ph}")


class QueryMonitor:
    """Tracks one query execution: phase timings, node stats, events
    (reference: QueryStateMachine + event/QueryMonitor.java)."""

    def __init__(self, session, sql: str):
        from presto_tpu.observe import trace as TR

        self.session = session
        self.stats = QueryStats(
            query_id=f"q_{next(_query_ids)}",
            sql=sql,
            create_time=time.time(),
        )
        self.collect_node_stats = bool(
            session.properties.get("collect_node_stats", False))
        self.rows_preset = False  # EXPLAIN ANALYZE pins the analyzed count
        # tracing (observe/trace.py): one tracer per query when enabled;
        # the query root span opens here and closes in finish()/fail().
        # execute_query / ClusterSession.sql ACTIVATE the tracer on the
        # query thread so nested instrumentation (compile_cache, the
        # cluster client, chunked fragments) finds it.
        self.tracer = None
        if TR.enabled(session):
            # fleet deployments tag each coordinator's spans with its
            # own lane (chrome pid row) so one merged trace separates
            # per-coordinator activity; solo sessions keep the classic
            # "coordinator" lane
            self.tracer = TR.Tracer(lane=getattr(
                session, "_trace_lane", None) or "coordinator")
            self.stats.trace_id = self.tracer.trace_id
            self.tracer.begin_root(
                "query", kind="query", query_id=self.stats.query_id,
                sql=sql[:200])

    @classmethod
    def begin(cls, session, sql: str):
        from presto_tpu.observe.events import QueryCreatedEvent, dispatch

        mon = cls(session, sql)
        with session.history_lock:
            session.history.append(mon.stats)
        dispatch(session.event_listeners, "query_created",
                 QueryCreatedEvent(mon.stats.query_id, sql,
                                   mon.stats.create_time))
        return mon

    @contextmanager
    def phase(self, name: str):
        self.stats.state = {"parse": "PLANNING", "plan": "PLANNING",
                            "execute": "RUNNING"}.get(name, "RUNNING")
        t0 = time.perf_counter_ns()
        # entered manually (not `with`) so spans recorded INSIDE the
        # phase nest under it on this thread's stack
        cm = self.tracer.span(name, kind="phase") \
            if self.tracer is not None else None
        if cm is not None:
            cm.__enter__()
        try:
            yield
        finally:
            if cm is not None:
                cm.__exit__(None, None, None)
            self.stats.phase_ns[name] = (
                self.stats.phase_ns.get(name, 0) + time.perf_counter_ns() - t0)

    def record_node(self, node, rows_out: int, wall_ns: int) -> None:
        ns = self.stats.node_stats.setdefault(
            id(node), NodeStats(node_kind=type(node).__name__))
        ns.rows_out = rows_out
        ns.wall_ns += wall_ns
        ns.invocations += 1

    def _close_trace(self) -> None:
        """End the root span, export the span dicts onto the stats, and
        fold the finished query into the metrics registry — the one
        funnel every execution mode's completion passes through."""
        from presto_tpu.observe import metrics as M

        if self.tracer is not None:
            self.tracer.end(self.tracer.root, state=self.stats.state)
            self.stats.trace_spans = self.tracer.snapshot()
            self.stats.trace_spans_dropped = self.tracer.dropped
        try:
            M.observe_query(self.stats)
        except Exception:
            pass  # metrics export must never fail a query

    def finish(self, result) -> None:
        from presto_tpu.observe.events import QueryCompletedEvent, dispatch

        self.stats.state = "FINISHED"
        self.stats.end_time = time.time()
        plan = getattr(self, "plan", None)
        if plan is not None and not self.stats.plan_text:
            try:
                if self.stats.node_stats:
                    self.stats.plan_text = annotated_plan(
                        plan.root, plan.subplans, self.stats)
                else:
                    from presto_tpu.plan.nodes import plan_tree_str

                    self.stats.plan_text = plan_tree_str(plan.root)
            except Exception:
                pass  # the plan pane is best-effort
        if not self.rows_preset:
            try:
                self.stats.output_rows = len(result)
            except TypeError:
                pass
        self._close_trace()
        dispatch(self.session.event_listeners, "query_completed",
                 QueryCompletedEvent(self.stats.query_id, self.stats.sql,
                                     "FINISHED", self.stats))

    def fail(self, error: BaseException) -> None:
        from presto_tpu.observe.events import QueryCompletedEvent, dispatch

        self.stats.state = "FAILED"
        self.stats.end_time = time.time()
        self.stats.error = f"{type(error).__name__}: {error}"
        self._close_trace()
        dispatch(self.session.event_listeners, "query_completed",
                 QueryCompletedEvent(self.stats.query_id, self.stats.sql,
                                     "FAILED", self.stats, self.stats.error))


def annotated_plan(plan_root, subplans, stats: QueryStats) -> str:
    """EXPLAIN ANALYZE rendering: the logical plan with per-node rows and
    wall time (reference: PlanPrinter.textDistributedPlan with stats,
    fed by ExplainAnalyzeOperator)."""
    from presto_tpu.plan.nodes import plan_tree_str

    def annotate(node):
        ns = stats.node_stats.get(id(node))
        if ns is None:
            return ""
        # recorded walls are inclusive of children; report self time
        child = sum(stats.node_stats[id(c)].wall_ns for c in node.sources
                    if id(c) in stats.node_stats)
        excl = max(ns.wall_ns - child, 0)
        return f"   <- rows={ns.rows_out} time={excl / 1e6:.2f}ms"

    lines = [plan_tree_str(plan_root, annotate=annotate)]
    for pid, sub in sorted(subplans.items()):
        lines.append(f"\nSubplan {pid}:")
        lines.append(plan_tree_str(sub, 1, annotate=annotate))
    ph = ", ".join(f"{k}: {v / 1e6:.1f}ms" for k, v in stats.phase_ns.items())
    lines.append(f"\nQuery {stats.query_id}: {ph}; output rows: "
                 f"{stats.output_rows}")
    lines.append(trace_summary_line(stats))
    return "\n".join(lines)


def trace_summary_line(stats: QueryStats) -> str:
    """The EXPLAIN ANALYZE trace attachment: where to fetch the chrome
    trace-event JSON (served by /v1/query/{id}/trace; also on
    QueryResult.stats.trace_spans) and how big it is."""
    if not stats.trace_id:
        return "Trace: disabled (trace_detail=off)"
    n = "recording" if stats.trace_spans is None \
        else f"{len(stats.trace_spans)} spans"
    return (f"Trace: {stats.trace_id} ({n}; chrome-trace JSON at "
            f"/v1/query/{stats.query_id}/trace, loads in Perfetto)")
