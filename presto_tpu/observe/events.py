"""Query event pipeline.

Reference parity: presto-spi/.../spi/eventlistener/ (QueryCreatedEvent,
QueryCompletedEvent, EventListener) dispatched by event/QueryMonitor.java;
manager eventlistener/EventListenerManager.java.  Listeners registered on
the Session receive created/completed events — the hook for query logs,
audit, and external metrics sinks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float  # unix seconds


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    stats: "QueryStats"  # noqa: F821  (observe.stats)
    error: Optional[str] = None


@dataclasses.dataclass
class RecoveryEvent:
    """One failure-recovery action in cluster mode (retry, hedge,
    quarantine, cancellation) — emitted by parallel/retry.RunContext as
    it bumps the matching QueryStats.recovery counter.  `kind` matches
    the counter key (docs/ROBUSTNESS.md lists the schema); `detail`
    carries action-specific context (worker url, task id, delay)."""

    query_id: str
    kind: str
    detail: Optional[dict] = None


class EventListener:
    """Subclass and override; register via Session.add_event_listener."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def recovery(self, event: RecoveryEvent) -> None:
        pass


def dispatch(listeners, method: str, event) -> None:
    for lis in listeners:
        try:
            getattr(lis, method)(event)
        except Exception:
            pass  # listener failures never fail the query (reference behavior)


class FileAuditLogListener(EventListener):
    """JSON-lines audit sink (reference: the event-listener plugins used
    for query audit logs — http-event-listener / custom sinks on
    QueryCompletedEvent).  One line per event, flushed immediately so the
    log survives crashes; attach via session.add_event_listener."""

    def __init__(self, path: str, user: str = ""):
        self.path = path
        self.user = user

    def _write(self, record: dict) -> None:
        import json

        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._write({"event": "query_created", "query_id": event.query_id,
                     "user": self.user, "sql": event.sql,
                     "create_time": event.create_time})

    def query_completed(self, event: QueryCompletedEvent) -> None:
        s = event.stats
        self._write({
            "event": "query_completed", "query_id": event.query_id,
            "user": self.user, "sql": event.sql, "state": event.state,
            "error": event.error,
            "execution_mode": s.execution_mode,
            "output_rows": int(s.output_rows),
            "total_ms": s.total_ns / 1e6,
            "peak_memory_bytes": int(s.peak_memory_bytes),
            "spilled_bytes": int(s.spilled_bytes),
        })
