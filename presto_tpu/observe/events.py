"""Query event pipeline.

Reference parity: presto-spi/.../spi/eventlistener/ (QueryCreatedEvent,
QueryCompletedEvent, EventListener) dispatched by event/QueryMonitor.java;
manager eventlistener/EventListenerManager.java.  Listeners registered on
the Session receive created/completed events — the hook for query logs,
audit, and external metrics sinks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float  # unix seconds


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    stats: "QueryStats"  # noqa: F821  (observe.stats)
    error: Optional[str] = None


class EventListener:
    """Subclass and override; register via Session.add_event_listener."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


def dispatch(listeners, method: str, event) -> None:
    for lis in listeners:
        try:
            getattr(lis, method)(event)
        except Exception:
            pass  # listener failures never fail the query (reference behavior)
