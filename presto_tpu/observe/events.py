"""Query event pipeline.

Reference parity: presto-spi/.../spi/eventlistener/ (QueryCreatedEvent,
QueryCompletedEvent, EventListener) dispatched by event/QueryMonitor.java;
manager eventlistener/EventListenerManager.java.  Listeners registered on
the Session receive created/completed events — the hook for query logs,
audit, and external metrics sinks.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float  # unix seconds


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    stats: "QueryStats"  # noqa: F821  (observe.stats)
    error: Optional[str] = None


@dataclasses.dataclass
class RecoveryEvent:
    """One failure-recovery action in cluster mode (retry, hedge,
    quarantine, cancellation) — emitted by parallel/retry.RunContext as
    it bumps the matching QueryStats.recovery counter.  `kind` matches
    the counter key (docs/ROBUSTNESS.md lists the schema); `detail`
    carries action-specific context (worker url, task id, delay)."""

    query_id: str
    kind: str
    detail: Optional[dict] = None


class EventListener:
    """Subclass and override; register via Session.add_event_listener."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def recovery(self, event: RecoveryEvent) -> None:
        pass


#: listener classes whose failure was already logged once (the debug
#: log is once-per-class so a hot listener bug can't flood stderr)
_logged_listener_classes: set = set()


def dispatch(listeners, method: str, event) -> None:
    for lis in listeners:
        try:
            getattr(lis, method)(event)
        except Exception as e:  # noqa: BLE001 — listener failures never
            # fail the query (reference behavior), but they are no
            # longer SILENT: every drop counts into the
            # presto_tpu_listener_errors_total metric (by listener
            # class), and PRESTO_TPU_DEBUG logs the first failure per
            # listener class with the exception
            cls = type(lis).__name__
            try:
                from presto_tpu.observe import metrics as M

                M.listener_error(cls)
            except Exception:  # noqa: BLE001 — metrics must not raise here
                pass
            if os.environ.get("PRESTO_TPU_DEBUG") \
                    and cls not in _logged_listener_classes:
                _logged_listener_classes.add(cls)
                logging.getLogger("presto_tpu.observe").warning(
                    "event listener %s.%s failed (suppressed; counted in "
                    "listener_errors): %s: %s",
                    cls, method, type(e).__name__, e)


class FileAuditLogListener(EventListener):
    """JSON-lines audit sink (reference: the event-listener plugins used
    for query audit logs — http-event-listener / custom sinks on
    QueryCompletedEvent).  One line per event, flushed immediately so the
    log survives crashes; attach via session.add_event_listener."""

    def __init__(self, path: str, user: str = ""):
        self.path = path
        self.user = user

    def _write(self, record: dict) -> None:
        import json

        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._write({"event": "query_created", "query_id": event.query_id,
                     "user": self.user, "sql": event.sql,
                     "create_time": event.create_time})

    def query_completed(self, event: QueryCompletedEvent) -> None:
        from presto_tpu.observe.metrics import querystats_counter_fields

        s = event.stats
        record = {
            "event": "query_completed", "query_id": event.query_id,
            "user": self.user, "sql": event.sql, "state": event.state,
            "error": event.error,
            "execution_mode": s.execution_mode,
            "total_ms": s.total_ns / 1e6,
            "phase_ms": {k: round(v / 1e6, 3)
                         for k, v in s.phase_ns.items()},
        }
        # EVERY numeric QueryStats counter rides the audit record —
        # enumerated from the dataclass (the same list the metrics
        # exporter and the schema-drift test walk), so a new subsystem's
        # counters (compile/df/fusion/serving/recovery, and whatever
        # comes next) can never silently miss the audit log again
        for name in querystats_counter_fields():
            v = getattr(s, name, 0)
            record[name] = float(v) if isinstance(v, float) else int(v)
        record["recovery"] = dict(s.recovery)
        record["agg_strategy"] = dict(getattr(s, "agg_strategy", None)
                                      or {})
        record["fusion_skips"] = dict(getattr(s, "fusion_skips", None)
                                      or {})
        record["resource_group"] = s.resource_group or None
        record["trace_id"] = s.trace_id or None
        self._write(record)
