"""Profiled execution: XLA cost analysis + jax.profiler capture.

The engine's compiled/chunked/fused programs are single fused XLA
blobs — there is no per-operator boundary at runtime (the
observe/stats.py design note).  Attribution inside them therefore
comes from the COMPILER, not the interpreter:

- `executable_cost` pulls XLA's cost analysis (FLOPs, bytes accessed)
  off a compiled program — the per-fragment numbers EXPLAIN ANALYZE
  attaches next to the measured wall in compiled/chunked/cluster
  modes, with a roofline-model estimated wall
  (`estimate_wall_ms`) so estimated-vs-measured gaps surface
  scheduling/transfer overheads;
- `maybe_profile` wraps a query in `jax.profiler.trace` when
  `PRESTO_TPU_PROFILE=<dir>` (or the `profile_query` session property)
  is set — the captured xplane maps back to plan node names through
  the `jax.named_scope` annotations the executor emits at every
  operator-lowering site (exec/executor.py).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional


#: roofline peaks for the estimated-wall model; env-overridable so the
#: operator can pin them to the real part (defaults: one TPU v4 core's
#: order of magnitude; on CPU the estimate is labeled as such)
DEFAULT_PEAK_FLOPS = 137e12
DEFAULT_HBM_GBPS = 1200.0
CPU_PEAK_FLOPS = 100e9
CPU_MEM_GBPS = 20.0


def _normalize(raw) -> Optional[dict]:
    """XLA cost_analysis payload (dict, or [dict] on older jax) ->
    {"flops": float, "bytes_accessed": float, ...extras}."""
    if raw is None:
        return None
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    for k, v in raw.items():
        if not isinstance(v, (int, float)):
            continue
        key = str(k).replace(" ", "_")
        out[key] = float(v)
    if "flops" not in out and "bytes_accessed" not in out:
        return None
    return out


def executable_cost(executable, args=None) -> Optional[dict]:
    """Cost analysis of a compile_cache.Executable (or a bare jitted
    callable).  AOT-compiled executables answer directly; a live-jit
    wrapper needs `args` to lower against (EXPLAIN ANALYZE only — the
    lower+compile there is a diagnostic cost, never on the hot path).
    Returns None when the backend can't answer; never raises."""
    try:
        compiled = getattr(executable, "_compiled", None)
        if compiled is not None:
            return _normalize(compiled.cost_analysis())
        if args is not None:
            lower = getattr(executable, "lower", None)
            if lower is not None:
                return _normalize(lower(*args).compile().cost_analysis())
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None
    return None


def merge_costs(costs) -> Optional[dict]:
    """Sum cost dicts across a fragment's program family (chunk loop +
    fold + compact programs all bill the same fragment)."""
    total: dict = {}
    seen = False
    for c in costs:
        if not c:
            continue
        seen = True
        for k, v in c.items():
            total[k] = total.get(k, 0.0) + float(v)
    return total if seen else None


def platform() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — no backend: call it cpu
        return "cpu"


def estimate_wall_ms(cost: Optional[dict]) -> Optional[float]:
    """Roofline estimate: max(compute, memory) time for the program's
    FLOPs / bytes at the platform's peak rates (env overrides
    PRESTO_TPU_PEAK_FLOPS / PRESTO_TPU_HBM_GBPS)."""
    if not cost:
        return None
    cpu = platform() == "cpu"
    peak = float(os.environ.get(
        "PRESTO_TPU_PEAK_FLOPS",
        CPU_PEAK_FLOPS if cpu else DEFAULT_PEAK_FLOPS))
    bw = float(os.environ.get(
        "PRESTO_TPU_HBM_GBPS",
        CPU_MEM_GBPS if cpu else DEFAULT_HBM_GBPS)) * 1e9
    t_flops = cost.get("flops", 0.0) / max(peak, 1.0)
    t_bytes = cost.get("bytes_accessed", 0.0) / max(bw, 1.0)
    return max(t_flops, t_bytes) * 1e3


def cost_line(cost: Optional[dict], wall_ms: Optional[float] = None,
              note: str = "") -> str:
    """One EXPLAIN ANALYZE attribution line: measured wall + XLA cost
    analysis + roofline estimate."""
    parts = []
    if wall_ms is not None:
        parts.append(f"wall={wall_ms:.2f}ms")
    if cost:
        if "flops" in cost:
            parts.append(f"xla_flops={cost['flops']:,.0f}")
        if "bytes_accessed" in cost:
            parts.append(f"hbm_bytes={cost['bytes_accessed']:,.0f}")
        est = estimate_wall_ms(cost)
        if est is not None:
            parts.append(f"est_wall={est:.2f}ms")
    else:
        parts.append("xla_cost=unavailable"
                     + (f" ({note})" if note else ""))
    return " ".join(parts)


# ---------------------------------------------------------------------------
# jax.profiler capture
# ---------------------------------------------------------------------------


def profile_dir(session=None) -> Optional[str]:
    """Where to write a jax.profiler capture: the `profile_query`
    session property (a directory path; "" / falsy = off) or the
    PRESTO_TPU_PROFILE env var."""
    d = None
    if session is not None:
        try:
            d = session.properties.get("profile_query") or None
        except Exception:
            d = None
    if d is None:
        d = os.environ.get("PRESTO_TPU_PROFILE") or None
    if d in ("0", "off", "false", None):
        return None
    return str(d)


@contextmanager
def maybe_profile(session=None):
    """Wrap a query in jax.profiler.trace when profiling is requested;
    capture failures (unsupported backend, busy profiler) never fail
    the query."""
    d = profile_dir(session)
    if d is None:
        yield None
        return
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        ctx = jax.profiler.trace(d)
        ctx.__enter__()
    except Exception:  # noqa: BLE001 — profiling is best-effort
        yield None
        return
    try:
        yield d
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception:  # noqa: BLE001
            pass
