"""Cluster-wide metrics: a process-level registry + Prometheus text.

Reference parity: the reference engine exports every QueryStats /
operator counter through JMX (presto-main jmx beans, scraped by the
jmx connector and the ops dashboards).  Our answer is a dependency-free
registry — counters, gauges, and histograms with bounded reservoirs —
served as Prometheus text exposition from `/v1/metrics` on BOTH the
coordinator (server/protocol.py) and every cluster worker
(parallel/cluster.py), replacing the ad-hoc JSON-only aggregation that
previously lived on `/v1/info` as the sole ops surface.

The registry is the process-wide sink every subsystem rolls into:

- every numeric `QueryStats` counter field folds in at query
  completion (`observe_query`, called by QueryMonitor.finish/fail) as
  `presto_tpu_query_<field>_total` — the field list is ENUMERATED from
  the dataclass (`querystats_counter_fields`), and the schema-drift
  test asserts each one appears in a live `/v1/metrics` scrape, so a
  new QueryStats counter can never silently miss the ops surface;
- cluster recovery counters (`presto_tpu_query_recovery_total{kind}`)
  and per-phase wall (`presto_tpu_query_phase_seconds_total{phase}`);
- worker task counters (`presto_tpu_worker_*`, parallel/cluster.py);
- event-listener failures (`presto_tpu_listener_errors_total`,
  observe/events.py — previously swallowed silently).

Naming scheme (docs/OBSERVABILITY.md): `presto_tpu_<subsystem>_<what>
_<unit-or-total>`; labels are bounded-cardinality enums only (state,
mode, phase, kind, listener class) — never query ids or SQL text.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _labels_key(label_names: Sequence[str], labels: Dict[str, object]):
    if set(labels) != set(label_names):
        raise ValueError(f"expected labels {label_names}, got "
                         f"{sorted(labels)}")
    return tuple(str(labels[n]) for n in label_names)


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def header(self) -> List[str]:
        return [f"# HELP {self.name} {_escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}"]

    def _series(self, suffix: str, key: tuple, value: float,
                extra: Sequence[Tuple[str, str]] = ()) -> str:
        pairs = [(n, v) for n, v in zip(self.label_names, key)]
        pairs += list(extra)
        lbl = ",".join(f'{n}="{_escape_label(str(v))}"' for n, v in pairs)
        return f"{self.name}{suffix}{{{lbl}}} {_fmt(value)}" if lbl \
            else f"{self.name}{suffix} {_fmt(value)}"


class Counter(Metric):
    kind = "counter"

    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[tuple, float] = {}
        if not self.label_names:
            self._values[()] = 0.0  # appear in scrapes before first inc

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labels_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        key = _labels_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return self.header() + [self._series("", k, v) for k, v in items]


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[tuple, float] = {}
        self._fn: Optional[Callable[[], float]] = None
        if not self.label_names:
            self._values[()] = 0.0

    def set(self, value: float, **labels) -> None:
        key = _labels_key(self.label_names, labels)
        with self._lock:
            self._values[key] = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Collect-time callback (unlabeled gauges only) — e.g. uptime,
        queue depth read at scrape time."""
        self._fn = fn

    def render(self) -> List[str]:
        if self._fn is not None:
            try:
                v = float(self._fn())
            except Exception:  # noqa: BLE001 — a broken probe reads 0
                v = 0.0
            return self.header() + [self._series("", (), v)]
        with self._lock:
            items = sorted(self._values.items())
        return self.header() + [self._series("", k, v) for k, v in items]


#: default histogram buckets: wall-clock style, milliseconds-friendly
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
                   float("inf"))

#: bounded reservoir size (per histogram) for host-side quantiles
RESERVOIR_SIZE = 512


class Histogram(Metric):
    """Cumulative-bucket histogram + a BOUNDED reservoir for host-side
    quantiles.  The reservoir is deterministic (a NumPy-free LCG seeded
    at construction, never the wall clock or `random`), so tests replay
    identical sampling decisions."""

    kind = "histogram"

    def __init__(self, name, help_="", buckets: Sequence[float] = None):
        super().__init__(name, help_, ())
        bs = tuple(buckets) if buckets else DEFAULT_BUCKETS
        if bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self.buckets = bs
        self._counts = [0] * len(bs)
        self._sum = 0.0
        self._count = 0
        self._reservoir: List[float] = []
        self._lcg = 0x9E3779B9  # fixed seed: deterministic sampling

    def _next_u32(self) -> int:
        self._lcg = (self._lcg * 1664525 + 1013904223) & 0xFFFFFFFF
        return self._lcg

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            if len(self._reservoir) < RESERVOIR_SIZE:
                self._reservoir.append(v)
            else:  # algorithm-R replacement with the deterministic LCG
                j = self._next_u32() % self._count
                if j < RESERVOIR_SIZE:
                    self._reservoir[j] = v

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            vals = sorted(self._reservoir)
        if not vals:
            return None
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    def render(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        out = self.header()
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(self._series("_bucket", (), cum, [("le", _fmt(b))]))
        out.append(self._series("_sum", (), s))
        out.append(self._series("_count", (), total))
        return out


class Registry:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, help_, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_, **kw)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} registered as "
                                f"{type(m).__name__}")
            return m

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self._get_or_make(Counter, name, help_,
                                 label_names=label_names)

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self._get_or_make(Gauge, name, help_,
                                 label_names=label_names)

    def histogram(self, name, help_="", buckets=None) -> Histogram:
        return self._get_or_make(Histogram, name, help_, buckets=buckets)

    def get(self, name) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for _name, m in metrics:
            lines += m.render()
        return "\n".join(lines) + "\n"


#: THE process-wide registry (coordinator and worker scrapes read it)
REGISTRY = Registry()


# ---------------------------------------------------------------------------
# QueryStats -> registry (the schema-drift contract)
# ---------------------------------------------------------------------------

#: numeric QueryStats fields that are NOT monotone counters (timestamps)
NON_COUNTER_FIELDS = frozenset({"create_time", "end_time"})


def querystats_counter_fields() -> List[str]:
    """Every numeric counter field of the QueryStats dataclass, detected
    from the field DEFAULTS (int/float, bool excluded) minus the
    timestamp fields — the single source of truth the exporter, the
    audit log, and the schema-drift test all enumerate."""
    from presto_tpu.observe.stats import QueryStats

    out = []
    for f in dataclasses.fields(QueryStats):
        if f.name in NON_COUNTER_FIELDS:
            continue
        if isinstance(f.default, bool):
            continue
        if isinstance(f.default, (int, float)):
            out.append(f.name)
    return out


def query_metric_name(field: str) -> str:
    return f"presto_tpu_query_{field}_total"


_FIELD_HELP = "Sum of QueryStats.{f} across completed queries"


def ensure_query_metrics() -> None:
    """Pre-register every QueryStats counter metric (plus the lifecycle
    families) so a scrape covers the full schema from process start —
    on workers too, which never run whole queries themselves."""
    for f in querystats_counter_fields():
        REGISTRY.counter(query_metric_name(f), _FIELD_HELP.format(f=f))
    REGISTRY.counter("presto_tpu_queries_total",
                     "Completed queries by terminal state and mode",
                     ("state", "mode"))
    REGISTRY.counter("presto_tpu_query_phase_seconds_total",
                     "Wall seconds per query phase", ("phase",))
    REGISTRY.counter("presto_tpu_query_recovery_total",
                     "Cluster recovery actions by kind "
                     "(docs/ROBUSTNESS.md schema)", ("kind",))
    REGISTRY.counter("presto_tpu_query_agg_strategy_total",
                     "Grouped aggregates executed per planned strategy "
                     "(plan/agg_strategy.py: one_pass/final_only/"
                     "two_phase)", ("strategy",))
    REGISTRY.counter("presto_tpu_query_fusion_skips_total",
                     "Exchange edges kept on the HTTP path per skip "
                     "reason (plan/fusion_cost.py: cost/kind/memo/"
                     "cross_host)", ("reason",))
    REGISTRY.histogram("presto_tpu_query_wall_ms",
                       "End-to-end query wall time (ms)")
    REGISTRY.counter("presto_tpu_listener_errors_total",
                     "Event-listener exceptions swallowed by dispatch",
                     ("listener",))


def observe_query(stats) -> None:
    """Fold one finished QueryStats into the registry (called by
    QueryMonitor.finish/fail — every execution path ends there)."""
    ensure_query_metrics()
    mode = getattr(stats, "execution_mode", "") or "none"
    REGISTRY.counter("presto_tpu_queries_total", "", ("state", "mode")) \
        .inc(state=getattr(stats, "state", "UNKNOWN") or "UNKNOWN",
             mode=mode)
    for f in querystats_counter_fields():
        v = getattr(stats, f, 0) or 0
        if v:
            REGISTRY.counter(query_metric_name(f)).inc(float(v))
    for phase, ns in (getattr(stats, "phase_ns", None) or {}).items():
        REGISTRY.counter("presto_tpu_query_phase_seconds_total", "",
                         ("phase",)).inc(ns / 1e9, phase=phase)
    for kind, n in (getattr(stats, "recovery", None) or {}).items():
        REGISTRY.counter("presto_tpu_query_recovery_total", "",
                         ("kind",)).inc(float(n), kind=kind)
    for strat, n in (getattr(stats, "agg_strategy", None) or {}).items():
        REGISTRY.counter("presto_tpu_query_agg_strategy_total", "",
                         ("strategy",)).inc(float(n), strategy=strat)
    for reason, n in (getattr(stats, "fusion_skips", None) or {}).items():
        REGISTRY.counter("presto_tpu_query_fusion_skips_total", "",
                         ("reason",)).inc(float(n), reason=reason)
    REGISTRY.histogram("presto_tpu_query_wall_ms").observe(
        getattr(stats, "total_ns", 0) / 1e6)


def record_recovery(kind: str, n: int = 1) -> None:
    """Count a recovery action that happens OUTSIDE a query's own
    RunContext — e.g. protocol-level adoption of a dead peer's
    journaled queries (server/protocol._adopt_from), which runs before
    any QueryStats exists to fold the counter through observe_query.
    Same family as the per-query recovery keys, so dashboards see one
    `presto_tpu_query_recovery_total{kind}` surface either way."""
    ensure_query_metrics()
    REGISTRY.counter("presto_tpu_query_recovery_total", "",
                     ("kind",)).inc(float(n), kind=kind)


def listener_error(listener_class: str) -> None:
    """Count one swallowed event-listener failure (observe/events.py)."""
    REGISTRY.counter("presto_tpu_listener_errors_total",
                     "Event-listener exceptions swallowed by dispatch",
                     ("listener",)).inc(listener=listener_class)


def set_fleet_gauges(fleet_stats: Dict[str, object]) -> None:
    """Fleet-coordination gauges (server/fleet.py): ring size, slot
    leases in flight, gossip/invalidation traffic, front-door routing.
    Scrape-time refresh like the serving-tier gauges — the fleet stats
    dict is the source of truth, the registry is the exposition."""
    ring = fleet_stats.get("ring")
    if isinstance(ring, (list, tuple)):
        REGISTRY.gauge("presto_tpu_fleet_coordinators",
                       "Coordinators on the ownership ring"
                       ).set(len(ring))
    slots = fleet_stats.get("slots")
    if isinstance(slots, dict):
        for k, v in slots.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                name = "".join(c if c.isalnum() or c == "_" else "_"
                               for c in str(k)).lower()
                REGISTRY.gauge(f"presto_tpu_fleet_slot_{name}",
                               f"Worker slot-lease {k}").set(v)
    for k, v in fleet_stats.items():
        if k in ("ring", "slots") or not isinstance(v, (int, float)) \
                or isinstance(v, bool):
            continue
        name = "".join(c if c.isalnum() or c == "_" else "_"
                       for c in str(k)).lower()
        REGISTRY.gauge(f"presto_tpu_fleet_{name}",
                       f"Fleet {k}").set(v)


def render_scrape(extra_counters: Optional[Dict[str, float]] = None,
                  prefix: str = "presto_tpu_worker_") -> str:
    """The /v1/metrics payload: the registry, plus (on workers) the
    task-accounting counters dict folded in as gauges under `prefix` —
    the same numbers /v1/info has always served as JSON."""
    ensure_query_metrics()
    text = REGISTRY.render()
    if extra_counters:
        lines = []
        for k, v in sorted(extra_counters.items()):
            name = prefix + "".join(
                c if c.isalnum() or c == "_" else "_" for c in str(k))
            lines.append(f"# HELP {name} Worker counter {k}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(float(v))}")
        text += "\n".join(lines) + "\n"
    return text
